"""Backup/restore engine: orchestration of pack ∥ send ∥ progress.

Re-designs ``client/src/backup/mod.rs`` + ``backup_orchestrator.rs`` +
``send.rs`` on asyncio:

* ``run_backup`` runs the packer (thread executor — chunking may drive the
  device) **concurrently** with the send loop, coupled by pause/resume
  backpressure on the local packfile buffer: packing pauses when unsent
  packfiles exceed 100 MiB, resumes below 50 MiB free
  (``defaults.rs:38,59``, ``backup_orchestrator.rs:81-113``).
* The send loop acquires peers: reuse the active transport, else dial known
  peers most-free-storage-first, else issue a storage request and wait for
  a match (``send.rs:209-262``); request sizing is
  ``estimate − fulfilled`` clamped to [50 MB step, 150 MB cap]
  (``send.rs:359-369``).
* Packfiles are deleted locally only after the peer's signed ack
  (``send.rs:277-289``); encrypted index files follow once packing
  completes, watermarked by ``highest_sent_index`` so re-runs resume
  (``send.rs:135-176``, ``config/backup.rs:80-98``).
* ``run_restore`` asks the server for the latest snapshot + negotiated
  peers, pulls everything back over RESTORE_ALL transports, rebuilds the
  blob index from the restored index files, and unpacks byte-identically
  (``backup/mod.rs:130-192``).
"""

from __future__ import annotations

import asyncio
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Dict, Optional

from . import defaults, wire
from .audit import (
    AuditResult,
    build_challenge_table,
    check_proofs,
    record_fail,
    record_miss,
    record_pass,
    select_challenges,
)
from .crypto import KeyManager
# imported at module scope so the cold tier's crash sites register with
# the live faults registry the moment the engine is importable (the
# BKW003 static/live registry parity check depends on it)
from .dedupstore import TieredDedupIndex
from .erasure import gf_cpu
from .erasure import stripe as rs_stripe
from .net.client import NoBackups, ServerClient, ServerError
from .net.p2p import (
    P2PError,
    P2PNode,
    PartialStore,
    Receiver,
    RestoreFilesWriter,
    SendProgress,
    Transport,
    adaptive_deadline,
)
from .net.peer_stats import PeerStats
from .net.transfer import BYTES_RESENT, RESTORE_SOURCES, TransferScheduler
from .obs import invariants as obs_invariants
from .obs import journal as obs_journal
from .obs import metrics as obs_metrics
from .obs import profile as obs_profile
from .obs import trace as obs_trace
from .ops.backend import ChunkerBackend, select_backend
from .snapshot.blob_index import BlobIndex, ChallengeTable
from .snapshot.packer import DirPacker
from .snapshot.packfile import PackfileReader, PackfileWriter, packfile_path
from .store import (EVENT_BACKUP, EVENT_GC, EVENT_REPAIR,
                    EVENT_RESTORE_REQUEST, Store)
from .utils import faults, retry, tracing


class EngineError(Exception):
    pass


_BACKUP_RUNS = obs_metrics.counter(
    "bkw_backup_runs_total", "Backup runs by outcome", ("outcome",))
_RESTORE_RUNS = obs_metrics.counter(
    "bkw_restore_runs_total", "Restore runs by outcome", ("outcome",))
_AUDIT_ROUNDS = obs_metrics.counter(
    "bkw_audit_rounds_total", "Audit rounds run")
_REPAIR_ROUNDS = obs_metrics.counter(
    "bkw_repair_rounds_total", "Peer-loss repair rounds run")
_SHARDS_REBUILT = obs_metrics.counter(
    "bkw_repair_shards_rebuilt_total",
    "Erasure shards rebuilt sourcelessly and re-homed")
_BUSY_REJECTS = obs_metrics.counter(
    "bkw_engine_busy_rejections_total",
    "Backup/restore/repair attempts rejected while the engine was busy",
    ("op",))
_RECOVERY_RUNS = obs_metrics.counter(
    "bkw_recovery_runs_total", "Startup recovery sweeps run")
_RECOVERY_ITEMS = obs_metrics.counter(
    "bkw_recovery_items_total",
    "Items reconciled by the startup recovery sweep", ("category",))
_RECOVERY_SECONDS = obs_metrics.histogram(
    "bkw_recovery_seconds", "Startup recovery sweep wall time")

_GC_RUNS = obs_metrics.counter(
    "bkw_gc_runs_total", "GC runs by outcome", ("outcome",))
_GC_BYTES_RECLAIMED = obs_metrics.counter(
    "bkw_gc_bytes_reclaimed_total",
    "Bytes GC retired, by where they lived (remote placements vs local"
    " packfiles)", ("kind",))
_GC_PACKFILES_DROPPED = obs_metrics.counter(
    "bkw_gc_packfiles_dropped_total",
    "Packfiles GC retired with zero live bytes")
_GC_PACKFILES_COMPACTED = obs_metrics.counter(
    "bkw_gc_packfiles_compacted_total",
    "Sparse packfiles GC pulled back and re-packed")
_GC_SNAPSHOTS_PRUNED = obs_metrics.counter(
    "bkw_gc_snapshots_pruned_total",
    "Snapshots retention marked dead")

# Crash-matrix seams around the engine's multi-step placement commits
_CP_PLACE_PRE = faults.register_crash_site("placement.insert.pre")
_CP_PLACE_POST = faults.register_crash_site("placement.insert.post")
_CP_STRIPE_PRE = faults.register_crash_site("stripe.finish.pre")
_CP_STRIPE_POST = faults.register_crash_site("stripe.finish.post")
_CP_REHOME_PRE = faults.register_crash_site("repair.rehome.pre")
_CP_REHOME_POST = faults.register_crash_site("repair.rehome.post")
# GC's multi-step seams (docs/lifecycle.md): prune commit, sweep-plan
# manifest, compaction seal, make-before-break placement swap, reclaim
# retire — each bracketed pre/post like the placement seams above
_CP_GC_PRUNE_PRE = faults.register_crash_site("gc.prune.pre")
_CP_GC_PRUNE_POST = faults.register_crash_site("gc.prune.post")
_CP_GC_SWEEP_PRE = faults.register_crash_site("gc.sweep.pre")
_CP_GC_SWEEP_POST = faults.register_crash_site("gc.sweep.post")
_CP_GC_SEAL_PRE = faults.register_crash_site("gc.compact.seal.pre")
_CP_GC_SEAL_POST = faults.register_crash_site("gc.compact.seal.post")
_CP_GC_SWAP_PRE = faults.register_crash_site("gc.swap.pre")
_CP_GC_SWAP_POST = faults.register_crash_site("gc.swap.post")
_CP_GC_RECLAIM_PRE = faults.register_crash_site("gc.reclaim.pre")
_CP_GC_RECLAIM_POST = faults.register_crash_site("gc.reclaim.post")


def _registry_stage_sums() -> Dict[str, float]:
    """Cumulative per-stage seconds from the registry — the source the
    end-of-run summary frame is derived from (deltas against a baseline
    captured at run start, since the registry is process-global)."""
    reg = obs_metrics.registry()
    out: Dict[str, float] = {}
    pack = reg.get("bkw_pack_stage_seconds")
    if pack is not None:
        for stage in ("seal", "write", "stall", "chunk_hash"):
            out[stage] = pack.sum_value(stage=stage)
    for metric, label in (("bkw_transfer_send_seconds", "send"),
                          ("bkw_transfer_wait_seconds", "send_wait")):
        fam = reg.get(metric)
        if fam is not None:
            out[label] = fam.sum_value()
    return out


class Orchestrator:
    """Cross-task shared state (backup_orchestrator.rs:20-45)."""

    def __init__(self):
        self.bytes_written = 0
        self.bytes_sent = 0
        # incremental local-buffer accounting: seeded with leftovers from
        # a previous interrupted run, bumped by on_packfile, drained by
        # sends — so backpressure never re-stats the whole pack dir on
        # every loop tick (VERDICT r2 weak 5)
        self.buffer_bytes = 0
        # buffer_bytes is bumped from the packer executor thread and
        # drained on the event loop; the lock keeps the read-modify-write
        # from losing updates (directory rescans would eventually
        # reconcile, but backpressure would act on a stale counter)
        self._buffer_lock = threading.Lock()
        self.packing_completed = False
        self.failed = False
        self._resume = threading.Event()
        self._resume.set()
        # seal->send wakeup (docs/dataflow.md): the packfile writer
        # thread signals through call_soon_threadsafe(notify_packfile),
        # so the send loop wakes the moment a packfile commits instead
        # of polling on a backoff timer
        self._packfile_event = asyncio.Event()
        self.active_transports: Dict[bytes, Transport] = {}

    def notify_packfile(self) -> None:
        """Event-loop side of the seal wakeup: a packfile committed (or
        packing finished — the producer must fire this after flipping
        ``packing_completed`` so a parked send loop sees the flag)."""
        self._packfile_event.set()

    async def wait_packfile(self, timeout: float) -> None:
        """Park the send loop until the next seal commit.  ``timeout``
        is only a missed-wakeup backstop, not pacing: the caller's loop
        re-reads the buffer counter after every return either way."""
        if self._packfile_event.is_set():
            self._packfile_event.clear()
            return
        try:
            await asyncio.wait_for(self._packfile_event.wait(), timeout)
        except asyncio.TimeoutError:
            return
        self._packfile_event.clear()

    def adjust_buffer(self, delta: int) -> None:
        with self._buffer_lock:
            self.buffer_bytes += delta

    def set_buffer(self, value: int) -> None:
        with self._buffer_lock:
            self.buffer_bytes = value

    # pause/resume (backup_orchestrator.rs:81-113)
    def pause(self) -> None:
        self._resume.clear()

    def resume(self) -> None:
        self._resume.set()

    @property
    def paused(self) -> bool:
        return not self._resume.is_set()

    def block_if_paused(self) -> None:
        """Called from the packer thread between blobs
        (block_if_paused! macro, backup/mod.rs:241-250)."""
        self._resume.wait()


class Engine:
    def __init__(self, keys: KeyManager, store: Store, server: ServerClient,
                 node: P2PNode, backend: Optional[ChunkerBackend] = None,
                 messenger=None, dedup_mesh=None):
        self.keys = keys
        self.store = store
        self.server = server
        self.node = node
        self.backend = backend or select_backend()
        self.messenger = messenger
        self.index = BlobIndex(keys, self._index_dir())
        self.index.load()
        self.challenge_tables = ChallengeTable(keys, store.challenge_dir())
        # with a mesh attached, dedup decisions run batched on the sharded
        # HBM table; BlobIndex stays the persisted authority + parity
        # oracle.  On an accelerator backend the mesh is attached by
        # DEFAULT (single axis over every local device) so real runs
        # exercise the HBM table without caller plumbing (SURVEY §7 3e);
        # BKW_DEVICE_DEDUP=0 opts out.
        if dedup_mesh is None and getattr(self.backend, "name", "") == "tpu" \
                and os.environ.get("BKW_DEVICE_DEDUP", "1") != "0":
            dedup_mesh = self._default_mesh()
        self.device_dedup = None
        if dedup_mesh is not None:
            self.device_dedup = self._make_device_dedup(dedup_mesh)
            # the manifest pipeline shards batches over the same mesh so
            # digests can hand off to the dedup table on device
            if hasattr(self.backend, "attach_mesh"):
                self.backend.attach_mesh(dedup_mesh,
                                         self.device_dedup.axis)
        self.orchestrator = Orchestrator()
        self.last_pack_stats = None
        # backup and restore are mutually exclusive and non-reentrant
        # (restore_orchestrator.rs:45-56); a second start must fail loudly,
        # not corrupt the pack dir with a concurrent packer
        self._exclusive = asyncio.Lock()
        # peer-loss repair: the demotion hook spawns repair rounds unless a
        # test drives them explicitly; _avoid_peers excludes the peers
        # under repair from placement while a round runs
        self.auto_repair = True
        self._repair_task: Optional[asyncio.Task] = None
        self._avoid_peers: set = set()
        # transfer plane of the most recent send loop (telemetry seam)
        self._transfers: Optional[TransferScheduler] = None
        # per-peer throughput/latency/success estimators, persisted in the
        # client config DB (net/peer_stats.py; the WAN-aware scheduling
        # measurement seam)
        self.peer_stats = PeerStats(store)
        # per-backup dispatch/bytes/padding roll-up (obs/profile.py)
        self.last_pipeline_report = None
        # per-backup overlap verdict (wall vs max stage, docs/dataflow.md)
        self.last_overlap = None
        # most recent startup recovery sweep report (engine.recover)
        self.last_recovery: Optional[Dict] = None

    @staticmethod
    def _default_mesh():
        """Single-axis mesh over every local device; None off-accelerator."""
        try:
            import jax
            import numpy as _np
            from jax.sharding import Mesh
            devices = jax.devices()
            if not devices:
                return None
            return Mesh(_np.array(devices), ("data",))
        except Exception:
            return None

    # --- paths -------------------------------------------------------------

    def _make_device_dedup(self, mesh):
        """Device dedup front for ``mesh``: tiered by default.

        The tiered front keeps the HBM table under
        ``DEDUP_HBM_BUDGET_BYTES`` with the LSM cold tier under the
        store's data dir absorbing demoted fingerprints
        (docs/dedup_tiering.md); ``BKW_DEDUP_TIERED=0`` falls back to
        the grow-only :class:`MeshDedupIndex`.
        """
        if os.environ.get("BKW_DEDUP_TIERED", "1") != "0":
            return TieredDedupIndex(
                mesh, self.index, cold_dir=self.store.dedup_cold_dir())
        from .snapshot.device_dedup import MeshDedupIndex
        return MeshDedupIndex(mesh, self.index)

    def _pack_dir(self) -> Path:
        return self.store.packfile_dir()

    def _index_dir(self) -> Path:
        return self.store.index_dir()

    def _log(self, msg: str) -> None:
        if self.messenger is not None:
            self.messenger.log(msg)

    def _progress(self, **kw) -> None:
        if self.messenger is not None:
            self.messenger.progress(**kw)

    # --- size estimate (backup/mod.rs:207-238) -----------------------------

    def estimate_size(self, root: Path) -> int:
        last = self.store.last_backup_size()
        total = 0
        for dirpath, _dirnames, filenames in os.walk(root):
            for f in filenames:
                try:
                    total += (Path(dirpath) / f).stat().st_size
                except OSError:
                    pass
        if last is not None:
            # incremental estimate: only the size delta needs new storage
            return max(total - last, min(total, 50 * 1000 * 1000))
        return total

    # --- buffer accounting --------------------------------------------------

    def _unsent_packfiles(self) -> list:
        """(packfile_id, path, size) of every local packfile not yet sent."""
        out = []
        base = self._pack_dir()
        if not base.is_dir():
            return out
        for shard in sorted(base.iterdir()):
            if not shard.is_dir():
                continue
            for f in sorted(shard.iterdir()):
                if f.suffix:  # .tmp
                    continue
                try:
                    out.append((bytes.fromhex(f.name), f, f.stat().st_size))
                except (ValueError, OSError):
                    continue
        return out

    def _buffer_bytes(self) -> int:
        return sum(s for _, _, s in self._unsent_packfiles())

    @staticmethod
    async def _blocking(fn, *args):
        """Run blocking disk I/O on the executor: the send/stripe/repair
        paths must never stall the event loop on a read/unlink/scan."""
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    # --- startup recovery sweep (docs/crash_consistency.md) -----------------

    async def recover(self) -> Dict:
        """Reconcile disk against the config DB after a (possible) crash.

        Called by ``ClientApp.start`` before any scheduler runs, and
        idempotent: a second call on a consistent store reconciles zero
        items.  The sweep

        * deletes orphaned ``.tmp`` files a crashed tmp+replace commit
          left in the pack / index / challenge directories;
        * AEAD-verifies every leftover local packfile's header (the
          GCM tag is the recorded digest) — a torn file is dropped and
          its blobs forgotten so the next backup re-packs them;
        * re-adopts verified packfiles the blob index cannot name (the
          crash beat the index flush): their headers are authoritative,
          so the blobs roll forward into the index instead of being
          re-packed from source;
        * retires placement rows whose packfile neither the index nor
          the local disk can resurrect — unreachable peer bytes must not
          masquerade as durability;
        * finishes packfiles whose placements already completed (the
          crash hit between the last ack and the local unlink);
        * counts the rest as the drain backlog, and probes for
          under-placed stripes with the same
          :meth:`_queue_underplaced_stripes` walk the repair round uses;
        * clears stale ``repair_staging/`` and restore staging trees;
        * expires abandoned partial transfers past
          ``defaults.PARTIAL_STORE_TTL_S``.

        Emits a ``recovery_report`` journal event and ``bkw_recovery_*``
        metrics, then (when ``auto_repair`` is on and there is a backlog)
        schedules the normal background repair round to drain it.
        """
        if self._exclusive.locked():
            _BUSY_REJECTS.inc(op="recover")
            raise EngineError("a backup or restore is already running")
        async with self._exclusive:
            with obs_trace.span("engine.recover"):
                report = await self._blocking(self._recover_sync)
        if self.auto_repair and (report["packfiles_pending"]
                                 or report["stripes_underplaced"]):
            if self._repair_task is None or self._repair_task.done():
                self._repair_task = asyncio.create_task(self._auto_repair())
        return report

    def _recover_sync(self) -> Dict:
        t0 = time.monotonic()
        rep: Dict[str, int] = {
            "tmp_cleaned": 0,
            "packfiles_corrupt": 0,
            "packfiles_adopted": 0,
            "packfiles_completed": 0,
            "packfiles_pending": 0,
            "placements_retired": 0,
            "stripes_underplaced": 0,
            "staging_cleared": 0,
            "partials_expired": 0,
            "gc_rolled_back": 0,
            "gc_rolled_forward": 0,
        }

        # interrupted GC first: roll the swap forward or back BEFORE the
        # leftover-packfile walk below, so a rolled-back compacted
        # packfile is gone before adoption could mistake it for a normal
        # pending backup packfile (docs/lifecycle.md GC state machine)
        self._recover_gc_state(rep)

        # orphaned .tmp files from crashed tmp+replace commits
        pack_base = self._pack_dir()
        tmp_dirs = [self._index_dir(), self.store.challenge_dir()]
        if pack_base.is_dir():
            tmp_dirs.extend(d for d in pack_base.iterdir() if d.is_dir())
        for d in tmp_dirs:
            if not d.is_dir():
                continue
            for f in d.glob("*.tmp"):
                try:
                    f.unlink()
                    rep["tmp_cleaned"] += 1
                except OSError:
                    pass

        # leftover local packfiles: verify, adopt, finish, or keep for the
        # drain
        reader = PackfileReader(self.keys, pack_base)
        geom = self._stripe_geometry()
        for pid, path, _size in self._unsent_packfiles():
            try:
                entries = reader.read_header(pid)
            except Exception:
                # torn seal: drop the file and forget its blobs so the
                # next backup re-packs them from source (the repair
                # path's forget-then-repack contract)
                try:
                    path.unlink()
                except OSError:
                    pass
                self.index.forget_packfiles([pid])
                # its audit tables go with it: challenge state for a
                # dead packfile must not resurrect it as auditable
                self.challenge_tables.forget([pid])
                rep["packfiles_corrupt"] += 1
                continue
            if bytes(pid) not in self.index.packfile_ids():
                owned_elsewhere = entries and all(
                    self.index.lookup(e.hash) not in (None, bytes(pid))
                    for e in entries)
                if owned_elsewhere:
                    # a GC replacement whose plan was lost (crash before
                    # the seal was recorded in gc_state): every blob is
                    # still owned by the packfile it was compacted from,
                    # so adopting this copy would double-place the data
                    # and leave orphaned placements once it drained.
                    # Drop it; the next GC re-compacts from the owners.
                    try:
                        path.unlink()
                    except OSError:
                        pass
                    self.challenge_tables.forget([pid])
                    rep["gc_rolled_back"] += 1
                    continue
                # the crash beat the index flush: the sealed file is the
                # authoritative record (its header just AEAD-verified),
                # so roll FORWARD — re-adopt its blobs into the index
                # instead of re-packing them from source
                self.index.finalize_packfile(pid, [e.hash for e in entries])
                rep["packfiles_adopted"] += 1
            holders = set()
            whole_placed = False
            for _peer, idx in self.store.shards_for_packfile(pid):
                if idx < 0:
                    whole_placed = True
                else:
                    holders.add(int(idx))
            full_stripe = False
            if geom is not None and holders:
                expected = max(geom[0] + geom[1], max(holders) + 1)
                full_stripe = holders >= set(range(expected))
            if whole_placed or full_stripe:
                # every byte is acked on peers; only the local unlink
                # was lost to the crash
                try:
                    path.unlink()
                except OSError:
                    pass
                rep["packfiles_completed"] += 1
            else:
                rep["packfiles_pending"] += 1

        if rep["packfiles_adopted"]:
            self.index.flush()  # adoption must survive the next crash

        # placement rows for packfiles the index cannot name and no local
        # file can resurrect: unreachable forever (the mapping died with
        # the crashed process), so retire the rows — leaked peer bytes
        # stop masquerading as durability
        unsent_pids = {bytes(pid)
                       for pid, _p, _s in self._unsent_packfiles()}
        live_pids = self.index.packfile_ids()
        stale = sorted({(pid, peer) for pid, peer, _s, _i, _t
                        in self.store.all_placements()
                        if pid not in live_pids and pid not in unsent_pids})
        for pid, peer in stale:
            rep["placements_retired"] += \
                self.store.retire_placement(pid, peer)

        # under-placed stripes: the scar the repair round would revisit
        stripe_lost: Dict = {}
        self._queue_underplaced_stripes(stripe_lost, {}, set(), unsent_pids)
        rep["stripes_underplaced"] = len(stripe_lost)

        # stale staging trees: a crashed repair or restore re-pulls from
        # scratch, so half-staged bytes are only a disk leak
        for staging in (self.store.data_base / "repair_staging",
                        self.store.data_base / "gc_staging",
                        self.store.restore_dir()):
            if staging.is_dir() and any(staging.iterdir()):
                shutil.rmtree(staging, ignore_errors=True)
                rep["staging_cleared"] += 1

        # abandoned inbound partials (the receiver-side TTL janitor —
        # also run periodically on the durability sweep, app.py)
        rep["partials_expired"] = self.expire_partials()

        # "reconciled" counts state this sweep actually changed; pending
        # backlog is observed, not reconciled (the drain owns it)
        backlog = ("packfiles_pending", "stripes_underplaced")
        reconciled = sum(v for k, v in rep.items() if k not in backlog)
        for category, n in rep.items():
            if n and category not in backlog:
                _RECOVERY_ITEMS.inc(n, category=category)
        _RECOVERY_RUNS.inc()
        dt = time.monotonic() - t0
        _RECOVERY_SECONDS.observe(dt)
        rep["reconciled"] = reconciled
        rep["elapsed_s"] = round(dt, 6)
        obs_journal.emit("recovery_report", **rep)
        self.last_recovery = rep
        return rep

    def expire_partials(self) -> int:
        """Receiver-side TTL janitor over every peer's partial-transfer
        spill dir — shared by startup recovery and the periodic
        durability sweep (app.py), so abandoned partials age out even on
        long-lived processes that never restart."""
        expired = 0
        recv = self.store.data_base / "received_packfiles"
        if recv.is_dir():
            for peer_dir in recv.iterdir():
                part = peer_dir / "partial"
                if part.is_dir():
                    expired += PartialStore(part).expire()
        return expired

    def _recover_gc_state(self, rep: Dict) -> None:
        """Resolve a GC interrupted mid-flight (docs/lifecycle.md).

        The swap's durable index flush is the commit point.  After a
        crash the freshly-loaded index tells us which side we are on:
        an old packfile id still mapped means the flush never landed —
        roll BACK (the compacted replacements are re-derivable, the old
        placements are still authoritative); an old id gone (its hashes
        re-homed or tombstoned) means it did — roll FORWARD by re-running
        the idempotent swap body so retire/reclaim bookkeeping finishes.
        Runs before the leftover-packfile walk so a rolled-back
        replacement is deleted before adoption could mistake it for a
        pending backup packfile.
        """
        state = self.store.get_gc_state()
        if not state:
            return
        if state.get("phase") == "reclaim":
            # everything durable already committed; the reclaim_backlog
            # table carries the best-effort tail — the next GC drains it
            self.store.set_gc_state(None)
            rep["gc_rolled_forward"] += 1
            return
        new_map = {bytes.fromhex(h): [bytes.fromhex(x)
                                      for x in info["hashes"]]
                   for h, info in state.get("new", {}).items()}
        old_pids = [bytes.fromhex(h)
                    for h in list(state.get("drop", []))
                    + list(state.get("compact", []))]
        ids = self.index.packfile_ids()
        committed = (bool(set(new_map) & ids)
                     or any(pid not in ids for pid in old_pids))
        if committed and old_pids:
            self._gc_apply_swap(old_pids, new_map)
            # the interrupted run died before its accounting: attribute
            # the retired packfiles here (bytes are counted inside the
            # idempotent swap body itself)
            if state.get("drop"):
                _GC_PACKFILES_DROPPED.inc(len(state["drop"]))
            if state.get("compact"):
                _GC_PACKFILES_COMPACTED.inc(len(state["compact"]))
            self.store.set_gc_state(None)
            rep["gc_rolled_forward"] += 1
            return
        # pre-commit crash: the replacements never entered the index, so
        # delete their local files and audit tables, and hand any shards
        # already placed (make-before-break places FIRST) to the reclaim
        # backlog — the holders' bytes must not leak
        for npid in new_map:
            try:
                packfile_path(self._pack_dir(), npid).unlink()
            except OSError:
                pass
            self.challenge_tables.forget([npid])
            for peer, size, idx in self.store.placements_for_packfile(npid):
                fid = rs_stripe.shard_id(npid, idx) if idx >= 0 \
                    else bytes(npid)
                kind = wire.FileInfoKind.SHARD if idx >= 0 \
                    else wire.FileInfoKind.PACKFILE
                self.store.queue_reclaim(fid, peer, int(kind), size)
                self.store.retire_placement(npid, peer)
        self.store.set_gc_state(None)
        rep["gc_rolled_back"] += 1

    # --- snapshot lifecycle: retention, GC, compaction, reclaim -------------
    # (docs/lifecycle.md)

    async def run_gc(self, policy: Optional[str] = None) -> Dict:
        """Retention prune + mark-and-sweep GC + make-before-break
        compaction + remote reclaim, one serialized pass.

        Phases: *prune* (retention marks snapshots dead — lineage rows
        stay, data is untouched); *mark* (live set = blobs reachable
        from any retained snapshot's manifest); *sweep* (classify
        packfiles: zero live bytes drop, occupancy below
        ``GC_COMPACT_OCCUPANCY`` compacts; persist the plan); *compact*
        (pull sparse packfiles back k-of-n, re-pack only the live blobs,
        fresh challenge tables); *place* (new packfiles ride the normal
        RS send pipeline and must be acked BEFORE anything retires);
        *swap* (one durable index flush forgets old packfiles, finalizes
        replacements, tombstones dead blobs); *reclaim* (signed RECLAIM
        requests tell holders to drop superseded bytes, best-effort —
        the backlog table persists what did not drain).

        Holds the backup/restore exclusivity lock; at no instant may the
        invariant monitor see a retained snapshot's bytes unprotected.
        """
        if self._exclusive.locked():
            _BUSY_REJECTS.inc(op="gc")
            raise EngineError("a backup or restore is already running")
        async with self._exclusive:
            with obs_trace.span("engine.gc"):
                try:
                    report = await self._run_gc_locked(policy)
                except BaseException:
                    _GC_RUNS.inc(outcome="failed")
                    raise
        _GC_RUNS.inc(outcome="ok")
        return report

    async def _run_gc_locked(self, policy: Optional[str]) -> Dict:
        t0 = time.monotonic()
        report: Dict = {
            "snapshots_pruned": 0, "packfiles_dropped": 0,
            "packfiles_compacted": 0, "blobs_dropped": 0,
            "bytes_reclaimed_remote": 0, "bytes_reclaimed_local": 0,
            "placements_retired": 0, "reclaims_sent": 0,
            "reclaim_bytes_freed": 0, "refused": "",
        }

        # prune: one sqlite commit flips pruned_at on the victims
        faults.crashpoint(_CP_GC_PRUNE_PRE)
        pruned = await self._blocking(self.store.apply_retention, policy)
        faults.crashpoint(_CP_GC_PRUNE_POST)
        report["snapshots_pruned"] = len(pruned)
        if pruned:
            _GC_SNAPSHOTS_PRUNED.inc(len(pruned))
            self._log(f"gc: retention pruned {len(pruned)} snapshot(s)")

        # refuse to collect what we cannot reason about: no retained
        # snapshot at all, or retained snapshots predating the manifest
        # plane (their reachable set is unknowable — dropping anything
        # could tear them)
        retained = await self._blocking(self.store.retained_snapshots)
        unmanifested = await self._blocking(
            self.store.snapshots_without_manifest)
        if not retained or unmanifested:
            report["refused"] = (
                "no retained snapshots recorded"
                if not retained else
                f"{len(unmanifested)} retained snapshot(s) have no"
                " manifest (pre-lifecycle backups)")
            self._log(f"gc: refused: {report['refused']}")
            # a previous run's committed reclaims still deserve a drain
            report.update(await self._drain_reclaims())
            return self._gc_finish(report, t0)

        # mark + sweep classification (pure compute over two DB scans)
        live = await self._blocking(self.store.live_blobs)
        known = await self._blocking(self.store.manifest_blobs)
        drop, compact = self._gc_classify(live, known)

        # sweep-plan manifest: the roll-forward/roll-back record
        faults.crashpoint(_CP_GC_SWEEP_PRE)
        await self._blocking(self.store.set_gc_state, {
            "phase": "sweep",
            "drop": [p.hex() for p in drop],
            "compact": [p.hex() for p in compact],
            "new": {}})
        faults.crashpoint(_CP_GC_SWEEP_POST)

        # compact: pull the sparse packfiles' bytes back and re-pack
        # only the live blobs into fresh packfiles (fresh ids, fresh
        # challenge tables).  A packfile whose bytes cannot be staged is
        # left exactly as it was — never break what we could not rebuild.
        new_map: Dict[bytes, dict] = {}
        staging = self.store.data_base / "gc_staging"
        try:
            if compact:
                staged = await self._gc_stage_packfiles(compact, staging)
                short = [p for p in compact if p not in staged]
                if short:
                    self._log(f"gc: {len(short)} packfile(s) not stageable"
                              " this run; left in place")
                    compact = [p for p in compact if p in staged]
                if compact:
                    new_map = await self._blocking(
                        self._gc_repack, compact, staged, live)
            # compaction seal commit: the plan now names the replacements
            faults.crashpoint(_CP_GC_SEAL_PRE)
            await self._blocking(self.store.set_gc_state, {
                "phase": "place",
                "drop": [p.hex() for p in drop],
                "compact": [p.hex() for p in compact],
                "new": {pid.hex(): {"hashes": [h.hex() for h in info["hashes"]],
                                    "size": info["size"]}
                        for pid, info in new_map.items()}})
            faults.crashpoint(_CP_GC_SEAL_POST)
        finally:
            await self._blocking(
                lambda: shutil.rmtree(staging, ignore_errors=True))

        # place (make BEFORE break): the replacements travel the normal
        # RS send pipeline — striped, per-shard challenge tables, local
        # copies unlinked only on the holders' signed acks
        if new_map:
            orch = self.orchestrator = Orchestrator()
            orch.set_buffer(self._buffer_bytes())
            orch.packing_completed = True
            estimate = max(sum(i["size"] for i in new_map.values()), 1)
            await self._send_loop(orch, estimate)

        # swap: ONE durable commit breaks the old placements' authority
        faults.crashpoint(_CP_GC_SWAP_PRE)
        swap = await self._blocking(
            self._gc_apply_swap, drop + compact,
            {pid: info["hashes"] for pid, info in new_map.items()})
        # accounting rides the commit: the swap body counted the bytes,
        # the packfile counts land here, both BEFORE the post-swap seam
        # so a crash there does not lose the run's evidence
        if drop:
            _GC_PACKFILES_DROPPED.inc(len(drop))
        if compact:
            _GC_PACKFILES_COMPACTED.inc(len(compact))
        await self._blocking(self.store.set_gc_state, {"phase": "reclaim"})
        faults.crashpoint(_CP_GC_SWAP_POST)
        report["packfiles_dropped"] = len(drop)
        report["packfiles_compacted"] = len(compact)
        report["blobs_dropped"] = swap["blobs_dropped"]
        report["placements_retired"] = swap["placements_retired"]
        report["bytes_reclaimed_remote"] = swap["remote_bytes"]
        report["bytes_reclaimed_local"] = swap["local_bytes"]
        # manifest rows of pruned snapshots are only needed as the
        # occupancy denominator until their blobs are collected
        await self._blocking(self.store.drop_pruned_manifests)

        # the swap's flush minted new index file(s); ship them before the
        # old bytes retire, so a restore rebuilt purely from peers sees
        # the post-GC map (tombstones included) rather than a stale map
        # naming packfiles the holders are about to delete
        await self._gc_ship_index()

        # reclaim retire: best-effort; whatever does not drain stays in
        # the backlog table for the next run (or recovery)
        faults.crashpoint(_CP_GC_RECLAIM_PRE)
        report.update(await self._drain_reclaims())
        await self._blocking(self.store.set_gc_state, None)
        faults.crashpoint(_CP_GC_RECLAIM_POST)
        return self._gc_finish(report, t0)

    async def _gc_ship_index(self) -> None:
        """Send index files past the watermark to a holder (the same
        sequential protocol as a backup's tail).  Best-effort: with no
        storage peers on record (offline runs, drop-only unit tests) the
        next backup's send loop resumes from the watermark instead."""
        if self.node is None or not self.store.find_peers_with_storage():
            return
        orch = self.orchestrator
        orch.packing_completed = True
        await self._send_index_files(orch, 1, 0)

    def _gc_finish(self, report: Dict, t0: float) -> Dict:
        report["elapsed_s"] = round(time.monotonic() - t0, 6)
        self.store.add_event(EVENT_GC, {
            k: report[k] for k in (
                "snapshots_pruned", "packfiles_dropped",
                "packfiles_compacted", "blobs_dropped",
                "bytes_reclaimed_remote", "bytes_reclaimed_local",
                "refused")})
        obs_journal.emit("gc_report", **report)
        self._log(
            f"gc done: {report['packfiles_dropped']} dropped,"
            f" {report['packfiles_compacted']} compacted,"
            f" {report['bytes_reclaimed_remote']} remote byte(s) retired")
        return report

    def _gc_classify(self, live: Dict[bytes, int],
                     known: Dict[bytes, int]) -> tuple:
        """Split the index's packfiles into (drop, compact) lists.

        Occupancy is judged on manifest-known payload bytes only: a blob
        no manifest (retained OR pruned) names is invisible to GC — it
        is never counted and never collected (the refuse-guard upstream
        keeps pre-lifecycle retained data out of here entirely).
        """
        totals: Dict[bytes, int] = {}
        alive: Dict[bytes, int] = {}
        for h, pid in self.index.blob_map().items():
            size = known.get(h)
            if size is None:
                continue
            totals[pid] = totals.get(pid, 0) + size
            if h in live:
                alive[pid] = alive.get(pid, 0) + size
        drop, compact = [], []
        for pid, total in sorted(totals.items()):
            live_bytes = alive.get(pid, 0)
            if live_bytes == 0:
                drop.append(pid)
            elif total and live_bytes / total < defaults.GC_COMPACT_OCCUPANCY:
                compact.append(pid)
        return drop, compact

    async def _gc_stage_packfiles(self, pids: list,
                                  staging: Path) -> Dict[bytes, Path]:
        """Obtain readable plaintext-decryptable bytes for each packfile
        to compact: a copy still sitting in the local pack dir is used
        directly (no pull); otherwise the k survivor shards come back
        over the restore data plane (hedged, fastest-first) with a
        whole-copy fetch as fallback, and stripes assemble in a private
        staging tree.  Returns {packfile_id: base_dir for PackfileReader}.
        """
        staged: Dict[bytes, Path] = {}
        need_pull = []
        for pid in pids:
            pid = bytes(pid)
            if packfile_path(self._pack_dir(), pid).is_file():
                staged[pid] = self._pack_dir()
            else:
                need_pull.append(pid)
        if not need_pull:
            return staged
        await self._blocking(
            lambda: shutil.rmtree(staging, ignore_errors=True))
        staging.mkdir(parents=True, exist_ok=True)
        writer = RestoreFilesWriter(self.store, base=staging)
        sched = TransferScheduler(messenger=self.messenger,
                                  peer_stats=self.peer_stats)
        geom = self._stripe_geometry()
        for pid in need_pull:
            shard_map: Dict[int, tuple] = {}
            whole = []
            for peer, size, idx in self.store.placements_for_packfile(pid):
                if idx < 0:
                    whole.append((peer, size))
                else:
                    shard_map[idx] = (peer, size)
            got = 0
            k = geom[0] if geom is not None else defaults.RS_K
            if shard_map:
                got = await self._pull_stripe(pid, shard_map, writer, sched)
            if got < min(k, len(shard_map)) or (not shard_map and whole):
                for peer, size in whole:
                    wants = [(wire.FileInfoKind.PACKFILE, pid)]
                    res = await sched.submit_pull(
                        peer, size,
                        self._fetch_job(peer, wants, writer, size),
                        label=f"gc:whole:{pid.hex()[:8]}")
                    if res.ok:
                        break
        shard_root = staging / "shard"
        if shard_root.is_dir():
            await self._blocking(lambda: rs_stripe.assemble_tree(
                shard_root, staging / "pack", self.backend))
        for pid in need_pull:
            if packfile_path(staging / "pack", pid).is_file():
                staged[pid] = staging / "pack"
        return staged

    def _gc_repack(self, compact: list, staged: Dict[bytes, Path],
                   live: Dict[bytes, int]) -> Dict[bytes, dict]:
        """Re-pack the live blobs of the sparse packfiles into fresh
        packfiles (executor thread).  The replacements get challenge
        tables built from their local ciphertext at seal time — the same
        audit seam a backup seal uses — but are NOT finalized into the
        blob index yet: that happens atomically in the swap, after the
        new placements are acked.  Returns
        {new_packfile_id: {"hashes": [...], "size": int}}.
        """
        new_map: Dict[bytes, dict] = {}

        def on_sealed(pid, path, hashes, size):
            try:
                if not self.challenge_tables.has(pid):
                    self.challenge_tables.save(
                        pid, build_challenge_table(
                            self.backend, path.read_bytes(),
                            count=defaults.AUDIT_CHALLENGES_PER_PACKFILE))
            except Exception as e:
                self._log(f"gc: challenge table for "
                          f"{bytes(pid).hex()[:8]} failed: {e}")
            new_map[bytes(pid)] = {
                "hashes": [bytes(h) for h in hashes], "size": int(size)}

        owner = self.index.blob_map()
        writer = PackfileWriter(self.keys, self._pack_dir(),
                                on_packfile=on_sealed)
        try:
            for old_pid in compact:
                old_pid = bytes(old_pid)
                reader = PackfileReader(self.keys, staged[old_pid])
                for blob in reader.iter_blobs(old_pid):
                    h = bytes(blob.hash)
                    # keep a blob only if it is live AND this packfile is
                    # its one committed home — a hash owned elsewhere
                    # would otherwise be duplicated
                    if h in live and owner.get(h) == old_pid:
                        writer.add_blob(blob)
            writer.flush()
        finally:
            writer.shutdown()
        return new_map

    def _gc_apply_swap(self, old_pids: list,
                       new_map: Dict[bytes, list]) -> Dict[str, int]:
        """The break half of make-before-break, idempotent (the recovery
        roll-forward re-runs it verbatim): forget the old packfiles,
        finalize the replacements, tombstone the blobs nothing names any
        more, and flush — ONE durable index commit.  Only then do the
        old audit tables, local copies, and placement rows retire, each
        superseded remote file going onto the reclaim backlog.
        """
        lost = self.index.forget_packfiles(old_pids)
        for npid, hashes in new_map.items():
            self.index.finalize_packfile(npid, hashes)
        dead = sorted(h for h in lost if self.index.lookup(h) is None)
        self.index.record_tombstones(dead)
        self.index.flush()  # <- the commit point
        self.challenge_tables.forget(old_pids)
        local_bytes = 0
        remote_bytes = 0
        retired = 0
        for pid in old_pids:
            pid = bytes(pid)
            path = packfile_path(self._pack_dir(), pid)
            try:
                local_bytes += path.stat().st_size
                path.unlink()
            except OSError:
                pass
            for peer, size, idx in self.store.placements_for_packfile(pid):
                fid = rs_stripe.shard_id(pid, idx) if idx >= 0 else pid
                kind = wire.FileInfoKind.SHARD if idx >= 0 \
                    else wire.FileInfoKind.PACKFILE
                # queue-then-retire: a crash between the two re-queues on
                # the next pass (INSERT OR IGNORE), never leaks the row
                self.store.queue_reclaim(fid, peer, int(kind), size)
                retired += self.store.retire_placement(pid, peer)
                remote_bytes += size
        # counted here, not in the caller, so a recovery roll-forward's
        # re-run attributes whatever it finishes retiring; a re-run over
        # already-retired state finds zero bytes, so no double count
        if remote_bytes:
            _GC_BYTES_RECLAIMED.inc(remote_bytes, kind="remote")
        if local_bytes:
            _GC_BYTES_RECLAIMED.inc(local_bytes, kind="local")
        return {"blobs_dropped": len(dead),
                "placements_retired": retired,
                "remote_bytes": remote_bytes,
                "local_bytes": local_bytes}

    async def _drain_reclaims(self) -> Dict[str, int]:
        """Drain the reclaim backlog: one signed RECLAIM request per
        holder (batched to ``RECLAIM_MAX_ITEMS``), crediting our local
        view of the peer's quota and clearing rows only on its ack.
        Failures are isolated per peer; unreachable holders keep their
        rows for the next drain."""
        backlog = await self._blocking(self.store.reclaim_backlog)
        sent = 0
        freed = 0
        by_peer: Dict[bytes, list] = {}
        for fid, peer, kind, size in backlog:
            by_peer.setdefault(peer, []).append((fid, kind, size))
        for peer, items in sorted(by_peer.items()):
            if self.node is None:
                break
            for start in range(0, len(items), defaults.RECLAIM_MAX_ITEMS):
                batch = items[start:start + defaults.RECLAIM_MAX_ITEMS]
                try:
                    t = await self.node.connect(
                        peer, wire.RequestType.RECLAIM,
                        timeout=self._dial_budget(peer))
                except (P2PError, ServerError, OSError,
                        asyncio.TimeoutError) as e:
                    self._log(f"gc: reclaim dial {peer.hex()[:8]}"
                              f" failed: {e}")
                    break
                try:
                    freed_now = await self.node.request_reclaim(
                        t, [(wire.FileInfoKind(kind), fid)
                            for fid, kind, _s in batch])
                except (P2PError, OSError, asyncio.TimeoutError) as e:
                    self._log(f"gc: reclaim to {peer.hex()[:8]}"
                              f" failed: {e}")
                    break
                finally:
                    await t.close()
                total = sum(s for _f, _k, s in batch)
                await self._blocking(
                    self.store.credit_peer_transmitted, peer, total)
                for fid, _kind, _s in batch:
                    await self._blocking(
                        self.store.clear_reclaim, fid, peer)
                sent += len(batch)
                freed += freed_now
        return {"reclaims_sent": sent, "reclaim_bytes_freed": freed}

    # --- backup ------------------------------------------------------------

    async def run_backup(self, root: Optional[Path] = None) -> bytes:
        if self._exclusive.locked():
            _BUSY_REJECTS.inc(op="backup")
            raise EngineError("a backup or restore is already running")
        async with self._exclusive:
            with obs_trace.span("engine.backup"):
                try:
                    snapshot = await self._run_backup_locked(root)
                except BaseException:
                    _BACKUP_RUNS.inc(outcome="failed")
                    raise
            _BACKUP_RUNS.inc(outcome="ok")
            return snapshot

    async def _run_backup_locked(self, root: Optional[Path]) -> bytes:
        root = Path(root or (self.store.get_backup_path() or ""))
        if not root.is_dir():
            raise EngineError(f"backup path {root} is not a directory")
        stage_base = _registry_stage_sums()
        profile_base = obs_profile.baseline()
        orch = self.orchestrator = Orchestrator()
        loop = asyncio.get_running_loop()
        # the size estimate walks the whole tree: keep it off the event
        # loop (backup/mod.rs:207-238 runs it blocking; we cannot)
        estimate = await loop.run_in_executor(None, self.estimate_size, root)
        orch.set_buffer(self._buffer_bytes())  # leftovers from past runs
        self._log(f"backup started, estimated {estimate} bytes")
        self._progress(size_estimate=estimate, running=True)
        snapshot_holder: dict = {}
        # the snapshot's reachable-blob manifest, collected as the packer
        # visits every blob (duplicates included) — GC's mark phase is a
        # join against this, persisted atomically with the lineage row.
        # Written only from the single pack thread, read after it joins.
        manifest: Dict[bytes, int] = {}
        # contextvars do not cross run_in_executor: hand the backup's
        # trace id to the pack thread so its spans journal under it
        backup_tid = obs_trace.current_trace_id()

        def pack_thread() -> None:
            writer = PackfileWriter(
                self.keys, self._pack_dir(),
                on_packfile=self._on_packfile_threadsafe(loop),
                seal_workers=defaults.PACK_SEAL_WORKERS)
            packer = DirPacker(self.backend, writer, self.index,
                               progress=self._pack_progress,
                               should_pause=orch.block_if_paused,
                               dedup_index=self.device_dedup,
                               on_blob=lambda h, s: manifest.setdefault(h, s))
            try:
                with obs_trace.bind(backup_tid), \
                        tracing.span("engine.pack"), \
                        tracing.jax_profiler("backup_pack"):
                    snapshot_holder["hash"] = packer.pack(root)
                snapshot_holder["stats"] = packer.stats
            finally:
                writer.shutdown()

        # BKW_BACKUP_PHASED=1 is the sum(stage) baseline leg the bench
        # speedup ratio is measured against: the send stage starts only
        # after the full pack finished, so nothing overlaps the wire.
        # Default is the streaming dataflow — pack, seal, and send all
        # concurrently busy, linked by bounded queues (docs/dataflow.md).
        phased = os.environ.get("BKW_BACKUP_PHASED", "0") == "1"
        wall_t0 = time.monotonic()
        pack_fut = loop.run_in_executor(None, pack_thread)
        send_task = None
        if not phased:
            send_task = asyncio.create_task(self._send_loop(orch, estimate))
        try:
            await pack_fut
            orch.packing_completed = True
            # wake a send loop parked on the seal event: no more seal
            # commits are coming, the drain check must run now
            orch.notify_packfile()
            await self._blocking(self.index.flush)
        except BaseException:
            # BaseException on purpose: an injected CrashInjected (and a
            # cancel of this coroutine) must still tear down the send
            # loop instead of leaving it spinning against a dead backup
            orch.failed = True
            if send_task is not None:
                send_task.cancel()
            raise
        if send_task is None:
            send_task = asyncio.create_task(self._send_loop(orch, estimate))
        try:
            await send_task
        except asyncio.CancelledError:
            raise EngineError("send pipeline cancelled")
        wall_s = time.monotonic() - wall_t0
        snapshot = snapshot_holder["hash"]
        self.last_pack_stats = snapshot_holder["stats"]
        # per-stage roll-up, derived from the metrics registry (delta vs.
        # the baseline captured at run start) — one source of truth
        # shared with GET /metrics and the messenger summary below
        now_sums = _registry_stage_sums()
        stages = {k: now_sums.get(k, 0.0) - stage_base.get(k, 0.0)
                  for k in now_sums}
        # overlap verdict for the dataflow gate: busy stages only (stall
        # and send_wait are idle time by definition — counting them
        # would reward a stalled pipeline)
        self.last_overlap = obs_profile.overlap_report(
            {k: stages.get(k, 0.0)
             for k in ("chunk_hash", "seal", "write", "send")},
            wall_s, mode="phased" if phased else "stream")
        # lineage + manifest commit (one store transaction): parent is
        # the previous retained head, so prune/GC can reason about the
        # chain (docs/lifecycle.md)
        parent = self.store.latest_snapshot()
        await self._blocking(
            self.store.record_snapshot, snapshot,
            None if parent is None else parent.hash,
            snapshot_holder["stats"].bytes_read, list(manifest.items()))
        await self.server.backup_done(snapshot)
        self.store.add_event(EVENT_BACKUP, {
            "size": snapshot_holder["stats"].bytes_read,
            "snapshot": snapshot.hex()})
        # per-backup pipeline report: dispatch counts, bytes, padding
        # efficiency, stage seconds — the number the round-5 digest-merge
        # gate watches (PERF.md)
        self.last_pipeline_report = obs_profile.report(profile_base)
        obs_profile.emit_report(
            self.last_pipeline_report, snapshot=snapshot.hex(),
            backend=getattr(self.backend, "name", "?"),
            bytes_read=snapshot_holder["stats"].bytes_read)
        self._log(f"backup finished: {snapshot.hex()}")
        if self.messenger is not None:
            self.messenger.transfer("engine", "summary",
                                    size=orch.bytes_sent, stages=stages,
                                    overlap=self.last_overlap)
        if tracing.enabled():
            self._log("trace spans:\n" + tracing.format_report())
        return snapshot

    def _pack_progress(self, **kw) -> None:
        self._progress(**kw)

    def _on_packfile_threadsafe(self, loop):
        def cb(pid, path, hashes, size):
            self.index.finalize_packfile(pid, hashes)
            # Precompute the audit challenge table while the plaintext
            # packfile is still local (it is unlinked after the peer's
            # ack) — hashed in one device batch alongside packing.  A
            # failure here degrades auditing, never the backup itself.
            try:
                if not self.challenge_tables.has(pid):
                    self.challenge_tables.save(
                        pid, build_challenge_table(
                            self.backend, path.read_bytes(),
                            count=defaults.AUDIT_CHALLENGES_PER_PACKFILE))
            except Exception as e:
                self._log(f"challenge table for {bytes(pid).hex()[:8]}"
                          f" failed: {e}")
            self.orchestrator.bytes_written += size
            self.orchestrator.adjust_buffer(size)
            self._progress(bytes_on_disk=self.orchestrator.bytes_written)
            # continuous admission: wake the send loop NOW — the buffer
            # counter above is already visible, so the packfile can be
            # on the wire before the next seal finishes
            loop.call_soon_threadsafe(self.orchestrator.notify_packfile)
        return cb

    # --- send pipeline (send.rs) -------------------------------------------

    async def _send_loop(self, orch: Orchestrator, estimate: int) -> None:
        fulfilled = 0
        # the concurrent transfer plane: bounded in-flight bytes, per-peer
        # ordering, per-transfer failure isolation (net/transfer.py).  One
        # scheduler per send loop so serial/concurrent knobs re-read
        # defaults each run.
        sched = self._transfers = TransferScheduler(
            messenger=self.messenger, peer_stats=self.peer_stats)
        # unified retry shapes (utils/retry.py): the storage re-request
        # backs off across consecutive dry spells, the peer wait grows
        # toward its cap while idle and resets on progress.  Waiting on
        # the PACKER is not a retry anymore: the seal callback's event
        # wakes this loop directly (Orchestrator.wait_packfile).
        request_timer = retry.RetryTimer(retry.STORAGE_REQUEST)
        peer_wait = retry.Backoff(retry.PEER_WAIT)
        # continuous admission (docs/dataflow.md): every packfile handed
        # to the transfer plane is tracked here (pid -> its admission
        # tick's task) until its tick resolves.  The scan below skips
        # tracked pids — a slow transfer never blocks admission of the
        # next sealed packfile, and a file still on disk (it is unlinked
        # only post-ack) is never double-submitted.
        inflight: Dict[bytes, "asyncio.Task[int]"] = {}

        async def reap(wait: bool) -> None:
            """Fold finished admission ticks into the loop's accounting;
            with ``wait`` parks until at least one tick resolves.  An
            injected crash inside a tick re-raises here."""
            nonlocal fulfilled
            if not inflight:
                return
            done, _pending = await asyncio.wait(
                set(inflight.values()), timeout=None if wait else 0,
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                return
            for pid in [p for p, t in inflight.items() if t in done]:
                del inflight[pid]
            for t in done:
                placed = t.result()
                if placed:
                    fulfilled += placed
                    peer_wait.reset()
                    self._progress(bytes_transmitted=orch.bytes_sent)

        async def reap_or_seal() -> None:
            """Park until an in-flight tick resolves OR the next seal
            commit — whichever lets the loop make progress first."""
            waiter = asyncio.ensure_future(
                orch.wait_packfile(defaults.SEND_WAKEUP_BACKSTOP_S))
            try:
                await asyncio.wait({waiter, *inflight.values()},
                                   return_when=asyncio.FIRST_COMPLETED)
            finally:
                if not waiter.done():
                    waiter.cancel()
                    try:
                        await waiter
                    except asyncio.CancelledError:
                        pass
            await reap(wait=False)

        try:
            while True:
                buffer = orch.buffer_bytes
                # backpressure (send.rs:52-54, 95-100)
                if buffer > defaults.PACKFILE_LOCAL_BUFFER_LIMIT \
                        and not orch.paused:
                    orch.pause()
                    self._log("packing paused: local buffer full")
                elif orch.paused and (
                        defaults.PACKFILE_LOCAL_BUFFER_LIMIT - buffer
                        > defaults.PACKFILE_RESUME_THRESHOLD):
                    orch.resume()
                    self._log("packing resumed")
                await reap(wait=False)
                if buffer <= 0:
                    if not orch.packing_completed:
                        # event-driven: the seal callback wakes this loop
                        # the moment a packfile commits (no dir scan, no
                        # backoff poll); the timeout is only a
                        # missed-wakeup backstop
                        await orch.wait_packfile(
                            defaults.SEND_WAKEUP_BACKSTOP_S)
                        continue
                    if inflight:
                        await reap(wait=True)
                        continue
                    # counter says drained: confirm with one real scan
                    # before finishing (the counter is advisory, the dir
                    # is truth)
                    unsent = await self._blocking(self._unsent_packfiles)
                    if not unsent:
                        break
                    orch.set_buffer(sum(s for _, _, s in unsent))
                else:
                    unsent = await self._blocking(self._unsent_packfiles)
                    unsent = [u for u in unsent
                              if bytes(u[0]) not in inflight]
                    if not unsent:
                        if inflight:
                            # everything on disk is already admitted:
                            # wait for a completion or the next seal
                            await reap_or_seal()
                        elif not orch.packing_completed:
                            await orch.wait_packfile(
                                defaults.SEND_WAKEUP_BACKSTOP_S)
                        else:
                            orch.set_buffer(0)
                        continue
                # admit the fresh batch WITHOUT awaiting it: the tick
                # task owns these pids until its transfers resolve, and
                # the loop goes straight back to watching the seal queue
                tick = asyncio.create_task(self._send_tick(
                    orch, sched, unsent, estimate, fulfilled,
                    request_timer, peer_wait))
                for pid, _path, _size in unsent:
                    inflight[bytes(pid)] = tick
        except BaseException:
            # teardown (cancel or injected crash): the admission ticks
            # must not outlive the loop and spin against a dead backup
            for t in set(inflight.values()):
                t.cancel()
            if inflight:
                await asyncio.gather(*set(inflight.values()),
                                     return_exceptions=True)
            raise
        # index files last, watermarked (send.rs:135-176)
        await self._send_index_files(orch, estimate, fulfilled)

    async def _send_tick(self, orch: Orchestrator, sched: TransferScheduler,
                         unsent: list, estimate: int, fulfilled: int,
                         request_timer, peer_wait) -> int:
        """One admission batch: stripe what can reach k+m distinct peers,
        fan the rest out whole-file.  Returns bytes fully placed; files
        that could not go out stay on disk and leave the in-flight set
        when this task resolves, so the next scan retries them.  The
        peer-wait backoff on a dry tick happens HERE (while the pids are
        still tracked), so a peerless swarm cannot spin the scan loop."""
        placed = 0
        # erasure-first: any packfile that can reach RS_K+RS_M distinct
        # peers right now goes out as a shard stripe; the rest fall
        # through to the whole-file path below, so small swarms behave
        # exactly as before sharding existed
        unsent, striped = await self._send_stripes(orch, sched, unsent)
        if striped:
            placed += striped
            request_timer.reset()
            self._progress(bytes_transmitted=orch.bytes_sent)
        if not unsent:
            return placed
        # a peer only qualifies if it can take the next packfile —
        # otherwise an almost-full peer would be reacquired forever
        # and the storage-request branch would starve
        transport, peer_id, peer_free = await self._get_peer_connection(
            orch, estimate, fulfilled, request_timer,
            min_free=min(s for _, _, s in unsent))
        if transport is None:
            await peer_wait.sleep()
            return placed
        peer_wait.reset()
        request_timer.reset()
        sent = await self._send_whole_files(
            orch, sched, unsent, (transport, bytes(peer_id), peer_free))
        if sent:
            placed += sent
        else:
            if not sched.peer_busy(peer_id):
                # dry tick on an idle socket: recycle it so the next scan
                # re-evaluates peers fresh.  A busy socket stays — sibling
                # ticks still have acks pending on it.
                await self._drop_transport(orch, peer_id)
            await peer_wait.sleep()
        return placed

    async def _send_whole_files(self, orch: Orchestrator,
                                sched: TransferScheduler, unsent: list,
                                first_conn) -> int:
        """Whole-packfile fan-out: distribute ``unsent`` over up to
        TRANSFER_MAX_PEERS connected peers and put every assigned file in
        flight concurrently (per-peer ordering preserved by the plane).
        Returns bytes acked; failed peers are dropped, their files stay
        on disk for the next tick.
        """
        # allowance-tracked connections, the qualifying peer first
        conns = [[first_conn[0], bytes(first_conn[1]), first_conn[2]]]
        if len(unsent) > 1 and defaults.TRANSFER_MAX_PEERS > 1:
            extra = await self._get_stripe_connections(
                orch, min(defaults.TRANSFER_MAX_PEERS, len(unsent)) - 1,
                {conns[0][1]} | self._avoid_peers,
                min(s for _, _, s in unsent))
            conns += [[t, bytes(p), free] for t, p, free in extra
                      if bytes(p) != conns[0][1]]
        tasks = []
        for pid, path, size in unsent:
            # Most-free connection that can take it; skip, don't stop:
            # unsent is in directory order, so a large packfile sorting
            # first must not starve smaller ones that still fit some peer
            # (the first peer qualified on min_free, the smallest file).
            best = None
            for c in conns:
                if size <= c[2] + defaults.PEER_OVERUSE_GRACE // 2 and (
                        best is None or c[2] > best[2]):
                    best = c
            if best is None:
                continue
            best[2] -= size
            tasks.append(sched.submit(
                best[1], size,
                self._whole_file_job(orch, best[0], best[1], pid, path, size),
                label=f"pack:{bytes(pid).hex()[:8]}"))
        sent = 0
        dropped = set()
        # completion-order reap: a failed peer is dropped (its transport
        # closed, its queued siblings failing fast) while the healthy
        # peers' transfers are still in flight
        async for r in sched.as_completed(tasks):
            if r.ok:
                sent += r.size
            elif isinstance(r.error, P2PError) and r.peer_id not in dropped:
                dropped.add(r.peer_id)
                await self._drop_transport(orch, r.peer_id)
        return sent

    def _peer_throughput(self, peer_id: bytes) -> float:
        """Measured EWMA throughput hint for adaptive deadlines; 0.0
        until the peer has enough samples to trust."""
        est = self.peer_stats.get(peer_id) if self.peer_stats else None
        if est is None or est.samples < defaults.PLACEMENT_MIN_SAMPLES:
            return 0.0
        return est.throughput_bps

    def _pull_rate(self, peer_id: bytes) -> float:
        """Source-selection score for download lanes: EWMA throughput
        derated by success ratio, with the neutral placement prior for
        never-measured peers so fresh holders stay schedulable between
        measured-fast and measured-slow ones."""
        est = self.peer_stats.get(peer_id) if self.peer_stats else None
        if est is None or est.samples < defaults.PLACEMENT_MIN_SAMPLES:
            return float(defaults.PLACEMENT_NEUTRAL_SCORE_BPS)
        return max(est.throughput_bps * max(est.success, 0.0), 1.0)

    def _dial_budget(self, peer_id: bytes) -> float:
        """Adaptive dial budget (the PR 8 deadline policy applied to the
        rendezvous confirm): the base ack window plus the peer's measured
        EWMA latency derated by the transfer safety fraction, under the
        transfer deadline cap.  Replaces the old fixed 10 s guess so a
        slow-but-alive peer is not misclassified as dark while a truly
        dark one still fails within seconds."""
        est = self.peer_stats.get(peer_id) if self.peer_stats else None
        lat = 0.0
        if est is not None and est.samples > 0:
            lat = float(est.latency_s) / max(
                defaults.TRANSFER_DEADLINE_SAFETY, 1e-6)
        return min(defaults.TRANSFER_DEADLINE_CAP_S,
                   defaults.ACK_TIMEOUT_S + lat)

    async def _send_resumable(self, orch: Orchestrator, transport,
                              peer_id: bytes, data: bytes,
                              file_info: wire.FileInfoKind,
                              file_id: bytes) -> None:
        """The shared abort-and-resume loop
        (``TransferScheduler.run_resumable``) with this engine's
        connection bookkeeping plugged in: a failed attempt drops the
        poisoned transport from the orchestrator and a retry redials,
        registering the fresh transport so sibling jobs reuse it."""
        peer_id = bytes(peer_id)

        async def on_drop() -> None:
            await self._drop_transport(orch, peer_id)

        async def redial():
            if self.node is None:
                raise P2PError("reconnect for resume failed: engine closed")
            try:
                t = await self.node.connect(
                    peer_id, wire.RequestType.TRANSPORT, timeout=3.0)
            except (P2PError, ServerError, OSError,
                    asyncio.TimeoutError) as e:
                raise P2PError(f"reconnect for resume failed: {e}") from e
            orch.active_transports[peer_id] = t
            return t

        await TransferScheduler.run_resumable(
            transport, peer_id, data, file_info, file_id,
            throughput_bps=self._peer_throughput(peer_id),
            redial=redial, on_drop=on_drop)

    def _whole_file_job(self, orch: Orchestrator, transport, peer_id: bytes,
                        pid: bytes, path: Path, size: int):
        """One scheduled transfer: read off-loop, send (resumably), then
        post-ack bookkeeping.  An OSError on the read is isolated to this
        transfer (the file is retried next tick), not a peer failure."""
        async def job() -> None:
            data = await self._blocking(path.read_bytes)
            await self._send_resumable(orch, transport, peer_id, data,
                                       wire.FileInfoKind.PACKFILE, pid)
            self.store.add_peer_transmitted(peer_id, size)
            faults.crashpoint(_CP_PLACE_PRE)
            self.store.record_placement(pid, peer_id, size)
            faults.crashpoint(_CP_PLACE_POST)
            # delete only after ack (send.rs:277-289) AND after the
            # placement row commits: a crash between the two leaves the
            # local copy, which recover() finishes against the recorded
            # placement — the reverse order would strand acked bytes the
            # DB knows nothing about
            await self._blocking(path.unlink)
            orch.bytes_sent += size
            orch.adjust_buffer(-size)
            self._progress(bytes_transmitted=orch.bytes_sent)
        return job

    # --- erasure-coded stripe placement (erasure/) --------------------------

    @staticmethod
    def _stripe_geometry():
        """(k, m) when erasure placement is enabled, else None.

        Read per call so tests (and operators) can flip RS_K/RS_M without
        rebuilding the engine; RS_M = 0 disables striping entirely.
        """
        k, m = int(defaults.RS_K), int(defaults.RS_M)
        if k < 1 or m < 1 or k + m > 256:
            return None
        return k, m

    async def _send_stripes(self, orch: Orchestrator,
                            sched: TransferScheduler, unsent: list):
        """Place unsent packfiles as k+m shard stripes on distinct peers.

        Per packfile: skip shard indices already placed (deterministic
        encode makes re-sends byte-identical, so a retry after a crash or
        a dead peer resumes the same stripe), acquire one fresh transport
        per missing shard, put **every missing shard in flight
        concurrently** — each to its own peer, so the stripe's wall clock
        is bounded by the slowest single shard, not the sum — and delete
        the local file only once all k+m shards are acked.  Returns
        (files for the legacy whole-file path, bytes fully placed).  A
        packfile that already has a whole-file placement, or that cannot
        reach enough distinct peers this tick, is handed back for the
        legacy path — never stranded.
        """
        geom = self._stripe_geometry()
        if geom is None:
            return unsent, 0
        k, m = geom
        n = k + m
        loop = asyncio.get_running_loop()
        leftover = []
        placed_bytes = 0
        for pid, path, size in unsent:
            holders: Dict[int, bytes] = {}
            whole_placed = False
            for peer, idx in self.store.shards_for_packfile(pid):
                if idx < 0:
                    whole_placed = True
                else:
                    holders[idx] = bytes(peer)
            if whole_placed:
                leftover.append((pid, path, size))
                continue
            missing = [i for i in range(n) if i not in holders]
            if not missing:
                # fully placed by an earlier interrupted run
                await self._finish_stripe(orch, pid, path, size)
                placed_bytes += size
                continue
            shard_size = rs_stripe.HEADER_LEN + gf_cpu.shard_len(size, k)
            exclude = set(holders.values()) | self._avoid_peers
            conns = await self._get_stripe_connections(
                orch, len(missing), exclude, shard_size)
            if len(conns) < len(missing):
                leftover.append((pid, path, size))
                continue
            try:
                data = await self._blocking(path.read_bytes)
            except OSError as e:
                # never swallow the read failure: report it and hand the
                # packfile back so the next tick retries instead of the
                # stripe silently vanishing from this run
                self._log(f"packfile {bytes(pid).hex()[:8]} read failed:"
                          f" {e}; queued for retry")
                leftover.append((pid, path, size))
                continue
            # GF(2^8) matmul (device or numpy oracle): off the event loop
            containers = await loop.run_in_executor(
                None, rs_stripe.split_packfile, data, k, m, self.backend)
            for i in missing:
                await self._blocking(
                    self._save_shard_challenge_table, pid, i, containers[i])
            pairs = list(zip(missing, conns))
            tasks = [
                sched.submit(peer_id, len(containers[i]),
                             self._shard_job(orch, transport, peer_id, pid,
                                             i, containers[i]),
                             label=f"shard:{bytes(pid).hex()[:8]}:{i}")
                for i, (transport, peer_id, _free) in pairs]
            all_acked = True
            for ((i, (_t, peer_id, _f)), r) in zip(
                    pairs, await sched.gather(tasks)):
                if r.ok:
                    holders[i] = bytes(peer_id)
                else:
                    # this shard's failure stays its own: the siblings
                    # already completed to THEIR peers
                    all_acked = False
                    if isinstance(r.error, P2PError):
                        await self._drop_transport(orch, peer_id)
            if all_acked and len(holders) == n:
                await self._finish_stripe(orch, pid, path, size)
                placed_bytes += size
                if self.messenger is not None:
                    self.messenger.erasure(bytes(pid).hex(), "placed",
                                           shards=n, rebuilt=0)
            else:
                # partial stripe: retried next tick (placed shards skip)
                leftover.append((pid, path, size))
        return leftover, placed_bytes

    def _shard_job(self, orch: Orchestrator, transport, peer_id: bytes,
                   pid: bytes, index: int, container: bytes):
        """One scheduled shard transfer + its post-ack bookkeeping."""
        async def job() -> None:
            await self._send_resumable(orch, transport, peer_id, container,
                                       wire.FileInfoKind.SHARD,
                                       rs_stripe.shard_id(pid, index))
            self.store.add_peer_transmitted(peer_id, len(container))
            faults.crashpoint(_CP_PLACE_PRE)
            self.store.record_placement(pid, peer_id, len(container),
                                        shard_index=index)
            faults.crashpoint(_CP_PLACE_POST)
        return job

    async def _finish_stripe(self, orch: Orchestrator, pid: bytes,
                             path: Path, size: int) -> None:
        """Local-delete + accounting once every shard of ``pid`` is acked
        (the striped analogue of the post-ack unlink in the legacy path)."""
        faults.crashpoint(_CP_STRIPE_PRE)
        try:
            await self._blocking(path.unlink)
        except OSError:
            pass
        faults.crashpoint(_CP_STRIPE_POST)
        orch.bytes_sent += size
        orch.adjust_buffer(-size)
        self._log(f"packfile {bytes(pid).hex()[:8]} placed as "
                  f"{defaults.RS_K}+{defaults.RS_M} stripe")

    def _save_shard_challenge_table(self, pid: bytes, index: int,
                                    container: bytes) -> None:
        """Audit table keyed by the 13-byte shard id, built while the
        shard bytes are local.  Failure degrades auditing, not backup."""
        sid = rs_stripe.shard_id(pid, index)
        try:
            if not self.challenge_tables.has(sid):
                self.challenge_tables.save(sid, build_challenge_table(
                    self.backend, container,
                    count=defaults.AUDIT_CHALLENGES_PER_PACKFILE))
        except Exception as e:
            self._log(f"challenge table for shard {sid.hex()[:8]}"
                      f" failed: {e}")

    async def _get_stripe_connections(self, orch: Orchestrator, need: int,
                                      exclude: set, min_free: int) -> list:
        """Up to ``need`` transports to DISTINCT peers outside ``exclude``,
        each with ``min_free`` bytes of allowance: reuse actives first,
        then dial known peers in measured-capacity order (the same
        ordering ``find_peers_with_storage`` gives the legacy path)."""
        conns = []
        chosen = set()
        # capacity demotion applies to active transports too: an open
        # socket to a measured-flaky peer is not a reason to keep
        # placing shards on it
        demoted = self.store.placement_demoted_peers()
        for peer_id, t in list(orch.active_transports.items()):
            if len(conns) >= need:
                break
            key = bytes(peer_id)
            if key in exclude or key in chosen or key in demoted:
                continue
            peer = self.store.get_peer(key)
            if peer is not None and peer.free_storage >= min_free:
                conns.append((t, key, peer.free_storage))
                chosen.add(key)
        if len(conns) < need:
            for peer in self.store.find_peers_with_storage(
                    exclude=exclude | chosen):
                if len(conns) >= need:
                    break
                key = bytes(peer.pubkey)
                if peer.free_storage < min_free:
                    continue  # capacity-ordered now, so keep scanning:
                    # a later (slower) peer may still have the space
                if key in orch.active_transports:
                    continue  # already weighed in the reuse pass
                try:
                    t = await self.node.connect(
                        key, wire.RequestType.TRANSPORT, timeout=3.0)
                except (P2PError, ServerError, OSError,
                        asyncio.TimeoutError) as e:
                    self._log(f"dial {key.hex()[:8]} failed: {e}")
                    continue
                orch.active_transports[key] = t
                conns.append((t, key, peer.free_storage))
                chosen.add(key)
        return conns

    async def _send_index_files(self, orch, estimate, fulfilled) -> None:
        request_timer = retry.RetryTimer(retry.STORAGE_REQUEST)
        peer_wait = retry.Backoff(retry.PEER_WAIT)
        while True:
            # Re-filter by the persisted watermark every attempt so a retry
            # after a mid-batch failure never re-sends files already acked
            # (the peer's writer refuses overwrites, which would livelock).
            # Mirrors send.rs re-checking highest_sent_index per file.
            watermark = self.store.get_highest_sent_index()

            def scan(wm=watermark):
                return sorted(
                    (p for p in self._index_dir().iterdir()
                     if p.name.isdigit() and int(p.name) > wm),
                    key=lambda p: int(p.name))

            files = await self._blocking(scan)
            if not files:
                return
            transport, peer_id, _free = await self._get_peer_connection(
                orch, estimate, fulfilled, request_timer)
            if transport is None:
                await peer_wait.sleep()
                continue
            peer_wait.reset()
            request_timer.reset()
            try:
                # index files stay strictly sequential on one peer: the
                # watermark is a prefix property, so out-of-order acks
                # would let a crash skip files on resume
                for f in files:
                    num = int(f.name)
                    data = await self._blocking(f.read_bytes)
                    await transport.send_data(
                        data, wire.FileInfoKind.INDEX,
                        num.to_bytes(8, "little"))
                    self.store.set_highest_sent_index(num)
                    self.store.add_peer_transmitted(peer_id, len(data))
                return
            except P2PError:
                await self._drop_transport(orch, peer_id)

    async def _get_peer_connection(self, orch, estimate, fulfilled,
                                   request_timer, min_free: int = 1):
        """(transport, peer_id, free) — reuse, dial known, or request
        storage (send.rs:209-262).  ``min_free`` is the size of the next
        file to send: peers whose remaining allowance (plus overuse grace)
        cannot take it are skipped so the storage-request path still runs.
        ``request_timer`` throttles the storage-request branch with
        jittered backoff across consecutive dry calls (utils/retry.py).
        """
        usable = min_free - defaults.PEER_OVERUSE_GRACE // 2

        demoted = self.store.placement_demoted_peers()
        sched = getattr(self, "_transfers", None)
        for peer_id, t in list(orch.active_transports.items()):
            if bytes(peer_id) in self._avoid_peers \
                    or bytes(peer_id) in demoted:
                await self._drop_transport(orch, peer_id)
                continue
            peer = self.store.get_peer(peer_id)
            free = peer.free_storage if peer else 0
            if free > 0 and free >= usable:
                return t, peer_id, free
            if sched is not None and sched.peer_busy(peer_id):
                # too full for the NEXT file but a concurrent tick still
                # has transfers in flight on this socket: keep it open.
                # Closing here would strand the sibling's ack wait and
                # force an abort-and-resume for a send that was fine.
                continue
            await self._drop_transport(orch, peer_id)
        for peer in self.store.find_peers_with_storage(
                exclude=self._avoid_peers):
            if peer.free_storage < usable:
                continue  # capacity-ordered now, so keep scanning:
                # a later (slower) peer may still have the space
            if bytes(peer.pubkey) in orch.active_transports:
                continue  # kept-busy transport above; dialing again would
                # replace the registered socket and orphan its acks
            try:
                t = await self.node.connect(peer.pubkey,
                                            wire.RequestType.TRANSPORT,
                                            timeout=3.0)
                orch.active_transports[peer.pubkey] = t
                return t, peer.pubkey, peer.free_storage
            except (P2PError, ServerError, OSError,
                    asyncio.TimeoutError) as e:
                self._log(
                    f"dial {bytes(peer.pubkey).hex()[:8]} failed: {e}")
                continue
        # no peer available: storage request, throttled (send.rs:296-309)
        now = time.time()
        if request_timer.due(now):
            request_timer.fire(now)
            missing = max(estimate - fulfilled, 0)
            amount = min(max(missing, defaults.STORAGE_REQUEST_STEP),
                         defaults.STORAGE_REQUEST_CAP)
            # with erasure enabled, ask the matchmaker for a full stripe's
            # worth of DISTINCT peers so grants spread instead of landing
            # on one giant candidate (server caps per-candidate share)
            geom = self._stripe_geometry()
            min_peers = (geom[0] + geom[1]) if geom else 1
            try:
                await self.server.backup_storage_request(
                    amount, min_peers=min_peers)
            except Exception:
                pass
        return None, None, 0

    async def _drop_transport(self, orch, peer_id) -> None:
        t = orch.active_transports.pop(bytes(peer_id), None)
        if t is not None:
            await t.close()

    # --- storage audits (verifier side, audit/) ----------------------------

    def note_audit_due(self, peer_id: bytes) -> None:
        """Pull a peer's next audit forward (server AuditDue push)."""
        self.store.mark_audit_due(peer_id)

    async def audit_peer(self, peer_id: bytes,
                         now: Optional[float] = None) -> Optional[AuditResult]:
        """One challenge–response audit round against one peer.

        Selection burns the challenge cursor before anything is sent, the
        proof must echo our sequence number under this session's nonce
        (replays from older sessions/rounds are rejected), and the outcome
        lands in the ledger + the coordination server.  Returns None when
        the peer has nothing auditable left (tables consumed).
        """
        peer_id = bytes(peer_id)
        now = time.time() if now is None else now
        challenges, expected = select_challenges(
            self.store, self.challenge_tables, peer_id)
        if not challenges:
            from dataclasses import replace
            st = self.store.get_audit_state(peer_id)
            self.store.put_audit_state(replace(
                st, next_due=now + defaults.AUDIT_INTERVAL_S))
            return None
        try:
            t = await self.node.connect(peer_id, wire.RequestType.AUDIT,
                                        timeout=10.0)
        except (P2PError, ServerError, OSError, asyncio.TimeoutError) as e:
            st = record_miss(self.store, peer_id, now=now)
            self._audit_event(peer_id, "miss", str(e), st)
            return AuditResult(passed=False, checked=0,
                              detail=f"unreachable: {e}")
        try:
            seq = t.seq
            t.seq += 1
            await t.send_body(wire.P2PBody(
                kind=wire.P2PBodyKind.CHALLENGE,
                header=wire.P2PHeader(sequence_number=seq,
                                      session_nonce=t.session_nonce),
                challenges=tuple(challenges)))
            reply = await t.recv_body(defaults.AUDIT_PROOF_TIMEOUT_S)
        except P2PError as e:
            st = record_miss(self.store, peer_id, now=now)
            self._audit_event(peer_id, "miss", str(e), st)
            return AuditResult(passed=False, checked=0,
                              detail=f"no proof: {e}")
        finally:
            await t.close()
        if reply.kind != wire.P2PBodyKind.PROOF \
                or reply.header.sequence_number != seq:
            result = AuditResult(passed=False, checked=len(challenges),
                                 detail="bad or replayed proof body")
        else:
            result = check_proofs(challenges, expected, reply.proofs)
        if result.passed:
            st = record_pass(self.store, peer_id, now=now)
        else:
            st = record_fail(self.store, peer_id, result.detail, now=now)
        self._audit_event(peer_id, "pass" if result.passed else "fail",
                          result.detail, st)
        try:
            await self.server.audit_report(peer_id, result.passed,
                                           result.detail)
        except Exception as e:
            self._log(f"audit report upload failed: {e}")
        return result

    async def run_audit_round(self, now: Optional[float] = None) -> Dict:
        """Audit every peer whose ledger says it is due."""
        now = time.time() if now is None else now
        _AUDIT_ROUNDS.inc()
        results: Dict[bytes, AuditResult] = {}
        with obs_trace.span("engine.audit_round"):
            for peer in self.store.audit_due_peers(now):
                res = await self.audit_peer(peer, now=now)
                if res is not None:
                    results[bytes(peer)] = res
        return results

    async def audit_scheduler(self, poll_s: float = 30.0) -> None:
        """Background verifier loop; skips polls while a backup/restore
        holds the engine so audits never contend for the transports."""
        while True:
            await asyncio.sleep(poll_s)
            if self._exclusive.locked():
                continue
            try:
                await self.run_audit_round()
            except Exception as e:  # keep the loop alive across bad rounds
                self._log(f"audit round failed: {e}")

    def _audit_event(self, peer_id: bytes, outcome: str, detail: str,
                     state) -> None:
        hexid = bytes(peer_id).hex()
        msg = f"audit {outcome} for peer {hexid[:8]}"
        if detail:
            msg += f": {detail}"
        if state.demoted:
            msg += " (peer demoted)"
        self._log(msg)
        if self.messenger is not None:
            self.messenger.audit(hexid, outcome, detail=detail,
                                 demoted=state.demoted)
        if state.demoted:
            # journal the demotion so the breach explainer can rank it
            # against armed fault sites in the breach window
            obs_journal.emit("placement_demotion", peer=hexid[:8],
                            outcome=outcome, misses=state.misses)
            self._on_peer_demoted(peer_id)

    # --- peer-loss repair ----------------------------------------------------

    def _on_peer_demoted(self, peer_id: bytes) -> None:
        """Audit-ledger demotion hook: schedule a repair round.

        Fires at most one background round at a time; tests set
        ``auto_repair = False`` and drive :meth:`repair_round` explicitly.
        """
        if not self.auto_repair:
            return
        if self._repair_task is not None and not self._repair_task.done():
            return
        self._repair_task = asyncio.create_task(self._auto_repair())

    async def _auto_repair(self) -> None:
        try:
            await self.repair_round()
        except Exception as e:  # background task: log, never crash the app
            self._log(f"repair round failed: {e}")

    async def aclose(self) -> None:
        """Cancel any in-flight background repair (app shutdown)."""
        if self._repair_task is not None:
            self._repair_task.cancel()
            try:
                await self._repair_task
            except (asyncio.CancelledError, Exception):
                pass
            self._repair_task = None

    async def repair_round(self, now: Optional[float] = None) -> Dict:
        """Re-replicate packfiles orphaned by demoted or long-dark peers.

        Walks the placement rows for every peer that is audit-demoted or
        unseen past ``PEER_DARK_DEADLINE_S``, finds the packfiles whose
        every replica lived on lost peers, forgets those blobs in the
        index, and re-packs them from the local source tree — CDC + blake3
        are deterministic, so the unchanged source reproduces exactly the
        forgotten blobs while everything else dedups away.  The fresh
        packfiles go to surviving peers through the normal send loop; only
        then are the dead placements retired and the reclaimed allocation
        reported to the coordination server.
        """
        if self._exclusive.locked():
            _BUSY_REJECTS.inc(op="repair")
            raise EngineError("a backup or restore is already running")
        async with self._exclusive:
            _REPAIR_ROUNDS.inc()
            with obs_trace.span("engine.repair_round"):
                return await self._repair_round_locked(now)

    def _lost_peers(self, now: float) -> set:
        """Peers holding placements that are demoted or dark past
        deadline — the shared definition in obs/invariants.py, so the
        repair plane and the durability monitor can never disagree."""
        return obs_invariants.lost_peers(self.store, now)

    async def _repair_round_locked(self, now: Optional[float]) -> Dict:
        now = time.time() if now is None else now
        lost = self._lost_peers(now)
        report: Dict = {"peers": {}, "packfiles": 0, "bytes_lost": 0,
                        "bytes_replaced": 0, "blobs": 0,
                        "shards_rebuilt": 0}
        # a packfile is orphaned only if EVERY replica is on a lost peer;
        # a lost erasure shard whose stripe keeps live holders goes to the
        # sourceless rebuild path instead (no local source tree needed)
        per_peer: Dict[bytes, list] = {}
        orphaned: Dict[bytes, int] = {}
        stripe_lost: Dict[bytes, Dict[int, tuple]] = {}
        for peer in lost:
            rows = self.store.shard_placements_for_peer(peer)
            per_peer[peer] = rows
            for pid, size, idx in rows:
                pidb = bytes(pid)
                holders = {bytes(p)
                           for p in self.store.peers_for_packfile(pid)}
                if holders <= lost:
                    if idx >= 0:
                        orphaned[pidb] = orphaned.get(pidb, 0) + size
                    else:
                        orphaned[pidb] = size
                elif idx >= 0:
                    stripe_lost.setdefault(pidb, {})[idx] = (peer, size)
                # idx < 0 with live holders: another whole replica
                # survives — nothing to rebuild, the row just retires
        unsent_pids = {bytes(pid)
                       for pid, _path, _size in self._unsent_packfiles()}
        self._queue_underplaced_stripes(stripe_lost, orphaned, lost,
                                        unsent_pids)
        if not lost and not stripe_lost and not unsent_pids:
            return report
        shards_rebuilt = 0
        shard_bytes_replaced = 0
        if stripe_lost:
            shards_rebuilt, shard_bytes_replaced, unrebuildable = \
                await self._rebuild_lost_shards(stripe_lost, lost)
            for pidb in unrebuildable:
                # fewer than k shards survive and no whole copy: only the
                # local source can bring the data back — re-pack fallback
                orphaned[pidb] = orphaned.get(pidb, 0) + sum(
                    s for _, s in stripe_lost[pidb].values())
        lost_hashes = self.index.forget_packfiles(orphaned)
        # the dead packfiles' audit tables go with them (whole-file AND
        # per-shard): challenge state must not outlive the data it names
        self.challenge_tables.forget(orphaned)
        bytes_lost = sum(orphaned.values()) + sum(
            s for pidb, lm in stripe_lost.items() if pidb not in orphaned
            for _, s in lm.values())
        self._log(f"repair: {len(lost)} lost peer(s), "
                  f"{len(orphaned)} orphaned packfile(s), "
                  f"{shards_rebuilt} shard(s) rebuilt sourcelessly, "
                  f"{len(lost_hashes)} blob(s) to re-replicate")
        bytes_replaced = 0
        # also run the pipeline when a previous failed round left forgotten
        # blobs re-packed but unsent on disk: everything dedups, the
        # leftovers drain, and only then do the placements retire
        if lost_hashes or self._unsent_packfiles():
            # the device dedup mesh mirrors the index: rebuild its table
            # from the pruned map so re-packed blobs are not misclassified
            # as duplicates
            if self.device_dedup is not None:
                self.device_dedup = self._make_device_dedup(
                    self.device_dedup.mesh)
            self._avoid_peers = set(lost)
            try:
                bytes_replaced = await self._repack_and_send(bytes_lost)
            finally:
                self._avoid_peers = set()
        # placements retire only after the replacement copies are acked;
        # a failed round leaves the rows so the next round retries (the
        # forget is idempotent and the re-pack dedups what already went)
        from dataclasses import replace
        for peer in lost:
            retired = self.store.retire_placements(peer)
            st = self.store.get_audit_state(peer)
            if not st.demoted:
                # dark-but-never-audited peers: persist the demotion so
                # they stay out of placement after this round
                self.store.put_audit_state(replace(
                    st, demoted=True,
                    last_result="dark: placements repaired away"))
            peer_lost = sum(s for pid, s, _idx in per_peer[peer]
                            if bytes(pid) in orphaned)
            report["peers"][bytes(peer).hex()] = {
                "placements_retired": retired, "bytes_lost": peer_lost}
            try:
                await self.server.repair_report(
                    peer, packfiles_lost=len(orphaned),
                    bytes_lost=peer_lost, bytes_replaced=bytes_replaced)
            except Exception as e:
                self._log(f"repair report for {bytes(peer).hex()[:8]} "
                          f"failed: {e}")
        report.update(packfiles=len(orphaned), bytes_lost=bytes_lost,
                      bytes_replaced=bytes_replaced + shard_bytes_replaced,
                      blobs=len(lost_hashes), shards_rebuilt=shards_rebuilt)
        self.store.add_event(EVENT_REPAIR, {
            "peers": [bytes(p).hex() for p in lost],
            "packfiles": len(orphaned), "bytes_lost": bytes_lost,
            "bytes_replaced": bytes_replaced + shard_bytes_replaced,
            "shards_rebuilt": shards_rebuilt})
        self._log(f"repair complete: {bytes_replaced} bytes re-replicated")
        return report

    def _queue_underplaced_stripes(self, stripe_lost: Dict, orphaned: Dict,
                                   lost: set, unsent_pids: set) -> None:
        """Queue stripes that are short a shard with NO lost row to blame
        — the scar a partially re-homed repair round leaves ("stripe
        stays degraded until peers join").  Without this, no later round
        would ever look at them: the dead rows are already retired, so
        the lost-peer walk comes up empty while the stripe sits one
        failure closer to unrestorable.  The missing indexes take the
        same sourceless rebuild path; the synthetic rows carry no dead
        peer to retire (``b""``) and a sibling shard's size as the
        estimate.  Stripes whose packfile still sits locally unsent
        (``unsent_pids``) are skipped — the leftover drain finishes them
        from the local bytes, which is cheaper than pulling k shards.
        """
        n = defaults.RS_K + defaults.RS_M
        by_pid: Dict[bytes, list] = {}
        for pid, peer, size, idx, _sent in self.store.all_placements():
            if idx >= 0:
                by_pid.setdefault(bytes(pid), []).append(
                    (bytes(peer), int(size), int(idx)))
        for pidb, rows in by_pid.items():
            if pidb in orphaned or pidb in unsent_pids:
                continue
            live = {idx for peer, _s, idx in rows if peer not in lost}
            if not live:
                continue  # every row lost: the orphan/repack walk owns it
            expected = max(n, max(idx for _p, _s, idx in rows) + 1)
            queued = stripe_lost.get(pidb, {})
            missing = set(range(expected)) - live - set(queued)
            if not missing:
                continue
            est = max(s for _p, s, _i in rows)
            entry = stripe_lost.setdefault(pidb, {})
            for idx in sorted(missing):
                entry[idx] = (b"", est)

    async def _rebuild_lost_shards(self, stripe_lost: Dict, lost: set):
        """Sourceless shard repair on the restore data plane: pull the k
        survivor shards each damaged stripe needs, shard-granular
        (RESTORE_FETCH through the same download lanes a restore uses —
        fastest holders first, hedged stalls, re-queue on failure),
        staged privately; decode + re-encode the lost rows —
        byte-identical, so the pre-computed challenge tables stay valid —
        and place them on fresh peers.  Stripes are processed in the
        durability monitor's at-risk order (fewest clean survivors
        first), so the data closest to unrestorable re-homes first.  The
        local source tree is never touched.  Returns ``(shards rebuilt,
        bytes placed, pids needing the re-pack-from-source fallback)``.
        """
        staging = self.store.data_base / "repair_staging"
        shutil.rmtree(staging, ignore_errors=True)
        staging.mkdir(parents=True, exist_ok=True)
        writer = RestoreFilesWriter(self.store, base=staging)
        survivors: Dict[bytes, list] = {}
        for pidb in stripe_lost:
            survivors[pidb] = [
                (bytes(p), i)
                for p, i in self.store.shards_for_packfile(pidb)
                if i >= 0 and bytes(p) not in lost]
        at_risk = sorted(stripe_lost,
                         key=lambda pidb: len(survivors[pidb]))
        pull_sched = TransferScheduler(messenger=self.messenger,
                                       peer_stats=self.peer_stats)
        streamed: set = set()
        for pidb in at_risk:
            est = max((s for _p, s in stripe_lost[pidb].values()),
                      default=0)
            shard_map = {i: (p, est) for p, i in survivors[pidb]}
            got = 0
            if shard_map:
                got = await self._pull_stripe(pidb, shard_map, writer,
                                              pull_sched)
            if got < min(defaults.RS_K, len(shard_map)):
                # shard pulls came up short: fall back to full
                # RESTORE_ALL streams from this stripe's untapped holders
                # (also the interop path for peers predating the fetch
                # protocol)
                for peer_id in sorted({p for p, _i in survivors[pidb]}
                                      - streamed):
                    try:
                        t = await self.node.connect(
                            peer_id, wire.RequestType.RESTORE_ALL,
                            timeout=self._dial_budget(peer_id))
                        try:
                            await Receiver(
                                t, writer.sink,
                                part_sink=writer.sink_part,
                                resume_query=writer.resume_offer).run()
                        finally:
                            await t.close()
                        streamed.add(peer_id)
                    except (P2PError, ServerError, OSError,
                            asyncio.TimeoutError) as e:
                        self._log(f"repair fetch from {peer_id.hex()[:8]}"
                                  f" failed: {e}")
        rebuilt = 0
        placed_bytes = 0
        unrebuildable = []
        loop = asyncio.get_running_loop()
        orch = Orchestrator()  # transport bookkeeping for fresh placements
        sched = TransferScheduler(messenger=self.messenger,
                                  peer_stats=self.peer_stats)

        def read_staged(d: Path) -> list:
            if not d.is_dir():
                return []
            return [f.read_bytes() for f in sorted(d.iterdir())
                    if f.is_file()]

        try:
            for pidb in at_risk:
                lost_map = stripe_lost[pidb]
                shard_dir = staging / "shard" / pidb.hex()
                blobs = await self._blocking(read_staged, shard_dir)
                missing = sorted(lost_map)
                try:
                    new_shards = await loop.run_in_executor(
                        None, rs_stripe.rebuild_shards, blobs, missing,
                        self.backend)
                except rs_stripe.StripeError as e:
                    self._log(f"stripe {pidb.hex()[:8]} not rebuildable:"
                              f" {e}")
                    live_whole = any(
                        i < 0 and bytes(p) not in lost
                        for p, i in self.store.shards_for_packfile(pidb))
                    if not live_whole:
                        unrebuildable.append(pidb)
                    continue
                holders = {bytes(p) for p, _i
                           in self.store.shards_for_packfile(pidb)}
                conns = await self._get_stripe_connections(
                    orch, len(missing), holders | lost | self._avoid_peers,
                    max(len(c) for c in new_shards.values()))
                pairs = list(zip(missing, conns))
                tasks = []
                for idx, (transport, peer_id, _free) in pairs:
                    container = new_shards[idx]
                    await self._blocking(self._save_shard_challenge_table,
                                         pidb, idx, container)
                    tasks.append(sched.submit(
                        peer_id, len(container),
                        self._repair_shard_job(orch, transport, peer_id,
                                               pidb, idx, container,
                                               lost_map[idx][0]),
                        label=f"repair:{pidb.hex()[:8]}:{idx}"))
                placed_here = 0
                for ((idx, (_t, peer_id, _f)), r) in zip(
                        pairs, await sched.gather(tasks)):
                    if r.ok:
                        rebuilt += 1
                        _SHARDS_REBUILT.inc()
                        placed_here += 1
                        placed_bytes += len(new_shards[idx])
                    elif isinstance(r.error, P2PError):
                        await self._drop_transport(orch, peer_id)
                if placed_here < len(missing):
                    self._log(f"stripe {pidb.hex()[:8]}: re-homed only "
                              f"{placed_here}/{len(missing)} shard(s); "
                              "stripe stays degraded until peers join")
                if placed_here and self.messenger is not None:
                    self.messenger.erasure(pidb.hex(), "rebuilt",
                                           shards=len(missing),
                                           rebuilt=placed_here)
        finally:
            for peer_id in list(orch.active_transports):
                await self._drop_transport(orch, peer_id)
            await self._blocking(
                lambda: shutil.rmtree(staging, ignore_errors=True))
        return rebuilt, placed_bytes, unrebuildable

    def _repair_shard_job(self, orch, transport, peer_id: bytes,
                          pidb: bytes, idx: int, container: bytes,
                          dead_peer: bytes):
        """One scheduled replacement-shard transfer; on ack the dead row
        retires immediately instead of waiting for the end-of-round
        retirement."""
        async def job() -> None:
            await self._send_resumable(orch, transport, peer_id, container,
                                       wire.FileInfoKind.SHARD,
                                       rs_stripe.shard_id(pidb, idx))
            self.store.add_peer_transmitted(peer_id, len(container))
            faults.crashpoint(_CP_REHOME_PRE)
            self.store.record_placement(pidb, peer_id, len(container),
                                        shard_index=idx)
            # record-then-retire: a crash between the two leaves BOTH rows
            # (over-placed, cleaned by the next repair round's retirement),
            # never neither (data on a dead peer with no replacement row)
            faults.crashpoint(_CP_REHOME_POST)
            self.store.retire_placement(pidb, dead_peer)
        return job

    async def _repack_and_send(self, bytes_lost: int) -> int:
        """Re-pack forgotten blobs from source and send to fresh peers.

        Same pack ∥ send machinery as a backup, minus the snapshot upload:
        the snapshot hash is unchanged (the data is), only placement moves.
        """
        root = Path(self.store.get_backup_path() or "")
        if not root.is_dir():
            raise EngineError(
                f"cannot repair: backup path {root} is not a directory")
        orch = self.orchestrator = Orchestrator()
        loop = asyncio.get_running_loop()
        orch.set_buffer(self._buffer_bytes())
        estimate = max(bytes_lost, 1)
        repair_tid = obs_trace.current_trace_id()

        def pack_thread() -> None:
            writer = PackfileWriter(
                self.keys, self._pack_dir(),
                on_packfile=self._on_packfile_threadsafe(loop),
                seal_workers=defaults.PACK_SEAL_WORKERS)
            packer = DirPacker(self.backend, writer, self.index,
                               progress=self._pack_progress,
                               should_pause=orch.block_if_paused,
                               dedup_index=self.device_dedup)
            try:
                with obs_trace.bind(repair_tid), \
                        tracing.span("engine.repair_pack"):
                    packer.pack(root)
            finally:
                writer.shutdown()

        pack_fut = loop.run_in_executor(None, pack_thread)
        send_task = asyncio.create_task(self._send_loop(orch, estimate))
        try:
            await pack_fut
            orch.packing_completed = True
            orch.notify_packfile()
            await self._blocking(self.index.flush)
        except BaseException:
            # BaseException on purpose: an injected CrashInjected (and a
            # cancel of this coroutine) must still tear down the send
            # loop instead of leaving it spinning against a dead backup
            orch.failed = True
            send_task.cancel()
            raise
        try:
            await send_task
        except asyncio.CancelledError:
            raise EngineError("repair send pipeline cancelled")
        return orch.bytes_sent

    # --- restore (backup/mod.rs:117-192) -----------------------------------

    async def run_restore(self, dest: Optional[Path] = None) -> Path:
        if self._exclusive.locked():
            _BUSY_REJECTS.inc(op="restore")
            raise EngineError("a backup or restore is already running")
        async with self._exclusive:
            with obs_trace.span("engine.restore"):
                try:
                    out = await self._run_restore_locked(dest)
                except BaseException:
                    _RESTORE_RUNS.inc(outcome="failed")
                    raise
            _RESTORE_RUNS.inc(outcome="ok")
            return out

    async def _run_restore_locked(self, dest: Optional[Path]) -> Path:
        last = self.store.last_event_time(EVENT_RESTORE_REQUEST)
        if last is not None and \
                time.time() - last < defaults.RESTORE_REQUEST_THROTTLE_S:
            raise EngineError("restore requested too recently")
        try:
            info = await self.server.backup_restore()
        except NoBackups:
            raise EngineError("no snapshot recorded on server")
        if info.snapshot_hash is None:
            raise EngineError("no snapshot recorded on server")
        # throttle only once a snapshot is actually negotiated: a
        # NoBackups or network error must not burn the user's one
        # restore-request slot per window
        self.store.add_event(EVENT_RESTORE_REQUEST, {})
        peers = [bytes.fromhex(p) for p in info.peers]
        if not peers:
            raise EngineError("no peers hold our data")
        writer = RestoreFilesWriter(self.store)
        plan = self._restore_plan()
        streamed: set = set()
        if plan is not None:
            # shard-granular pull plan over the local placement map:
            # each stripe from its k fastest holders with hedged spares,
            # whole-copy peers as single batched pulls
            stripes, whole, known = plan
            await self._pull_striped_restore(stripes, whole, writer)
            legacy_peers = [p for p in peers if p not in known]
        else:
            # no placement map (disaster recovery onto a fresh identity):
            # only the negotiated peer list exists, so every peer pushes
            # its whole stream (and old peers only speak this path)
            legacy_peers = list(peers)
        if legacy_peers:
            streamed = await self._pull_restore_all(legacy_peers, writer)
        # erasure assembly BEFORE coverage is judged: any k valid shards
        # of a stripe reconstruct its packfile into the pack tree, so up
        # to m dark peers per stripe cost nothing
        await self._assemble_restored_stripes()
        # Coverage decides success, not per-peer completion: shard pulls
        # deliberately skip n-k holders per stripe, and a negotiated peer
        # that stores nothing for us (the matcher's save/notify crash
        # window in net/server.py) refuses the dial while the data the
        # others returned still covers the snapshot.
        need_check = plan is not None or len(streamed) < len(legacy_peers)
        if need_check:
            ctx = self._restored_ctx()
            gap = self._restored_coverage_gap(info.snapshot_hash, ctx)
            if gap is not None and plan is not None:
                # fetch-plane shortfall: fall back to full RESTORE_ALL
                # streams from every peer that has not streamed yet
                fallback = [p for p in peers if p not in streamed]
                if fallback:
                    self._log("restore coverage gap after shard pulls;"
                              " falling back to full streams")
                    streamed |= await self._pull_restore_all(fallback,
                                                             writer)
                    await self._assemble_restored_stripes()
                    ctx = self._restored_ctx()
                    gap = self._restored_coverage_gap(info.snapshot_hash,
                                                      ctx)
            if gap is not None:
                missing = [p for p in peers if p not in streamed]
                raise EngineError(
                    "restore incomplete; no stream from: "
                    + ", ".join(p.hex()[:8] for p in missing)
                    + f"; first missing blob {gap.hex()}")
        else:
            ctx = None
        path = self._unpack_restored(info.snapshot_hash, dest, ctx)
        # the staging buffer is deleted only after a successful unpack
        # (backup/mod.rs:180); a failed unpack keeps it for retry/forensics
        shutil.rmtree(self.store.restore_dir(), ignore_errors=True)
        return path

    # --- restore data plane: the pull planner (docs/transfer.md) -----------

    def _restore_plan(self):
        """``(stripes, whole, known_peers)`` from the local placement map,
        or None when the map is empty and only the legacy full-stream
        path can run.  ``stripes`` maps pid -> shard index -> (holder,
        size); ``whole`` maps peer -> pid -> size for packfiles with no
        stripe rows (when both exist the striped pull is preferred — the
        whole copy stays a coverage-gap fallback source)."""
        stripes: Dict[bytes, Dict[int, tuple]] = {}
        whole_rows: Dict[bytes, Dict[bytes, int]] = {}
        known: set = set()
        for pid, peer, size, idx, _sent in self.store.all_placements():
            pidb, peerb = bytes(pid), bytes(peer)
            known.add(peerb)
            if idx >= 0:
                stripes.setdefault(pidb, {})[int(idx)] = (peerb, int(size))
            else:
                whole_rows.setdefault(peerb, {})[pidb] = int(size)
        if not stripes and not whole_rows:
            return None
        whole = {
            peer: {pid: s for pid, s in pids.items() if pid not in stripes}
            for peer, pids in whole_rows.items()}
        whole = {peer: pids for peer, pids in whole.items() if pids}
        return stripes, whole, known

    @staticmethod
    def _restore_dest(writer: RestoreFilesWriter,
                      file_info: wire.FileInfoKind, file_id: bytes) -> Path:
        """Where ``writer.sink`` lands one file — the puller's existence
        check for 'did the named want actually come back'."""
        if file_info == wire.FileInfoKind.INDEX:
            num = int.from_bytes(bytes(file_id)[:8], "little")
            return writer.dir / "index" / f"{num:06d}"
        if file_info == wire.FileInfoKind.SHARD:
            pid, idx = bytes(file_id)[:-1], bytes(file_id)[-1]
            return writer.dir / "shard" / pid.hex() / f"{idx:03d}"
        h = bytes(file_id).hex()
        return writer.dir / "pack" / h[:2] / h

    def _fetch_job(self, peer_id: bytes, wants: list,
                   writer: RestoreFilesWriter, size_hint: int):
        """One RESTORE_FETCH pull as a schedulable download: connect
        under the adaptive dial budget, name the wants, receive under the
        adaptive transfer deadline, then verify every named want landed
        (a gap raises, so the scheduler re-queues it elsewhere).  Returns
        the bytes received for the estimators."""
        peer_id = bytes(peer_id)
        paths = [self._restore_dest(writer, k, f)
                 for k, f in wants if f]

        async def job() -> int:
            if self.node is None:
                raise P2PError("engine closed")
            deadline = adaptive_deadline(size_hint,
                                         self._peer_throughput(peer_id))
            t = await self.node.connect(
                peer_id, wire.RequestType.RESTORE_FETCH,
                timeout=self._dial_budget(peer_id))
            try:
                await self.node.request_fetch(t, wants)
                await asyncio.wait_for(
                    Receiver(t, writer.sink, part_sink=writer.sink_part,
                             resume_query=writer.resume_offer).run(),
                    deadline)
            finally:
                await t.close()

            def landed() -> int:
                got = 0
                for p in paths:
                    if not p.exists():
                        raise P2PError(
                            f"peer {peer_id.hex()[:8]} did not return"
                            f" {p.name}")
                    got += p.stat().st_size
                return got

            return await self._blocking(landed)
        return job

    async def _pull_stripe(self, pidb: bytes, shard_map: Dict,
                           writer: RestoreFilesWriter,
                           sched: TransferScheduler) -> int:
        """Pull one stripe's shards k-of-n: the k fastest holders are the
        primaries, the rest are spares — a primary that stalls past the
        hedge fraction of its adaptive deadline races a redundant spare
        shard, and an outright failure re-queues behind the remaining
        spares.  Returns the number of shards landed (≥ k restores the
        stripe; fewer surfaces later as a coverage gap)."""
        k = min(defaults.RS_K, len(shard_map))
        ranked = sorted(shard_map.items(),
                        key=lambda kv: self._pull_rate(kv[1][0]),
                        reverse=True)
        primaries, spares = ranked[:k], ranked[k:]
        spare_iter = iter(spares)
        delivered: list = []

        def submit_one(idx: int, holder: bytes, size: int):
            sid = rs_stripe.shard_id(pidb, idx)
            wants = [(wire.FileInfoKind.SHARD, sid)]
            return sched.submit_pull(
                holder, size, self._fetch_job(holder, wants, writer, size),
                label=f"restore:shard:{pidb.hex()[:8]}:{idx}")

        async def one_primary(idx: int, holder: bytes, size: int):
            primary = submit_one(idx, holder, size)
            hedge_after = max(
                0.05, float(defaults.RESTORE_HEDGE_DEADLINE_FRACTION)
                * adaptive_deadline(size, self._peer_throughput(holder)))

            def spawn_hedge():
                nxt = next(spare_iter, None)
                if nxt is None:
                    return None
                s_idx, (s_holder, s_size) = nxt
                return submit_one(s_idx, s_holder, s_size)

            return await sched.pull_hedged(primary, spawn_hedge,
                                           hedge_after)

        results = await asyncio.gather(
            *(one_primary(idx, holder, size)
              for idx, (holder, size) in primaries),
            return_exceptions=True)
        for res in results:
            if isinstance(res, BaseException):
                self._log(f"stripe {pidb.hex()[:8]} pull error: {res}")
            elif res is not None and res.ok:
                delivered.append(res.peer_id)
        # re-queue the shortfall behind the remaining (healthier-ranked)
        # spares, one at a time — failures here are cheap and bounded
        while len(delivered) < k:
            nxt = next(spare_iter, None)
            if nxt is None:
                break
            s_idx, (s_holder, s_size) = nxt
            res = await submit_one(s_idx, s_holder, s_size)
            if res.ok:
                delivered.append(res.peer_id)
        if delivered:
            RESTORE_SOURCES.observe(len(set(delivered)))
        if len(delivered) < k:
            self._log(f"stripe {pidb.hex()[:8]}: only {len(delivered)}/{k}"
                      " shard(s) pulled; relying on fallback coverage")
        return len(delivered)

    async def _pull_striped_restore(self, stripes: Dict, whole: Dict,
                                    writer: RestoreFilesWriter) -> None:
        """Execute the pull plan through one unified scheduler: stripe
        pulls, whole-copy batch pulls, and an index sweep (index files
        have no placement rows, so every distinct holder is asked once
        for everything it has)."""
        sched = TransferScheduler(messenger=self.messenger,
                                  peer_stats=self.peer_stats)
        tasks = []
        for peer, pids in sorted(whole.items()):
            wants = [(wire.FileInfoKind.PACKFILE, pid)
                     for pid in sorted(pids)]
            size = sum(pids.values())
            tasks.append(sched.submit_pull(
                peer, size, self._fetch_job(peer, wants, writer, size),
                label=f"restore:whole:{peer.hex()[:8]}"))
        holders = {h for m in stripes.values() for h, _s in m.values()}
        for peer in sorted(set(whole) | holders):
            tasks.append(sched.submit_pull(
                peer, 0,
                self._fetch_job(peer, [(wire.FileInfoKind.INDEX, b"")],
                                writer, 0),
                label=f"restore:index:{peer.hex()[:8]}"))
        stripe_tasks = [
            asyncio.ensure_future(
                self._pull_stripe(pidb, shard_map, writer, sched))
            for pidb, shard_map in sorted(stripes.items())]
        await asyncio.gather(*tasks, *stripe_tasks, return_exceptions=True)
        self._log(
            f"restore pull plan done: {len(stripes)} stripe(s),"
            f" {len(whole)} whole-copy peer(s),"
            f" {sched.bytes_pulled} byte(s) pulled")

    async def _pull_restore_all(self, peers: list,
                                writer: RestoreFilesWriter) -> set:
        """Legacy full-stream fan-out (RESTORE_ALL): every peer pushes
        everything it holds for us.  Returns the peers whose stream
        completed."""
        streamed: set = set()

        async def pull(peer_id: bytes) -> None:
            t = await self.node.connect(peer_id,
                                        wire.RequestType.RESTORE_ALL,
                                        timeout=self._dial_budget(peer_id))
            try:
                await Receiver(t, writer.sink,
                               part_sink=writer.sink_part,
                               resume_query=writer.resume_offer).run()
            finally:
                await t.close()
            streamed.add(peer_id)
            self._log(f"peer {peer_id.hex()[:8]} restore stream complete")

        results = await asyncio.gather(*(pull(p) for p in peers),
                                       return_exceptions=True)
        for peer_id, res in zip(peers, results):
            if isinstance(res, BaseException):
                self._log(f"restore from {peer_id.hex()[:8]} failed: {res}")
        return streamed

    async def _assemble_restored_stripes(self) -> None:
        """Rebuild packfiles from erasure shards in the restore staging
        buffer (restore_dir/shard -> restore_dir/pack); best-effort — a
        stripe with fewer than k valid shards is logged and surfaces later
        as a coverage gap, exactly like a missing packfile."""
        restore_dir = self.store.restore_dir()
        shard_root = restore_dir / "shard"
        if not shard_root.is_dir():
            return
        done, failed = await asyncio.get_running_loop().run_in_executor(
            None, rs_stripe.assemble_tree, shard_root,
            restore_dir / "pack", self.backend)
        if done:
            self._log(f"assembled {len(done)} packfile(s) from erasure"
                      " shards")
            if self.messenger is not None:
                self.messenger.erasure("restore", "assembled",
                                       shards=len(done), rebuilt=len(done))
        for pid, reason in failed:
            self._log(f"stripe {bytes(pid).hex()[:8]} not assembled:"
                      f" {reason}")

    def _restored_ctx(self):
        """(index, reader, resolve) over the restore staging buffer."""
        restore_dir = self.store.restore_dir()
        index = BlobIndex(self.keys, restore_dir / "index")
        index.load()
        reader = PackfileReader(self.keys, restore_dir / "pack")
        if len(index) == 0:  # no/partial index: rebuild from headers
            index.rebuild_from_packfiles(reader, restore_dir / "pack")
        # lazily built from packfile headers when the loaded index points
        # at a packfile that didn't come back (e.g. it was retired by a
        # repair round but an old index file still names it)
        fallback: dict = {}

        def resolve(h):
            pid = index.lookup(h)
            if pid is not None:
                try:
                    return reader.get_blob(pid, h)
                except Exception:
                    pass
            if "index" not in fallback:
                fb = BlobIndex(self.keys, restore_dir / "index")
                fb.rebuild_from_packfiles(reader, restore_dir / "pack")
                fallback["index"] = fb
            pid2 = fallback["index"].lookup(h)
            if pid2 is None or pid2 == pid:
                raise EngineError(f"blob {bytes(h).hex()} not restored")
            return reader.get_blob(pid2, h)

        return index, reader, resolve

    def _restored_coverage_gap(self, snapshot_hash: bytes, ctx=None):
        from .snapshot.unpacker import snapshot_coverage_gap
        _index, _reader, resolve = ctx or self._restored_ctx()

        def retrievable(h):
            # An index entry alone is NOT coverage: all index files may have
            # landed on a surviving peer while the packfile holding the blob
            # was on the failed one.  Actually read + decrypt the blob.
            try:
                resolve(h)
                return True
            except Exception:
                return False

        return snapshot_coverage_gap(resolve, retrievable, snapshot_hash)

    def _unpack_restored(self, snapshot_hash: bytes,
                         dest: Optional[Path], ctx=None) -> Path:
        from .snapshot.unpacker import DirUnpacker
        _index, _reader, resolve = ctx or self._restored_ctx()
        dest = Path(dest or (self.store.get_backup_path() or ""))
        DirUnpacker(resolve, progress=self._pack_progress).unpack(
            snapshot_hash, dest)
        self._log(f"restore complete into {dest}")
        return dest
