"""Scenario builtins + deterministic scorecards for the sim plane.

Five population-scale situations the real-time swarm harness
(scenario/swarm.py) cannot reach at its hundreds-of-clients ceiling:

* ``flashcrowd`` — the population arrives inside one hour and all wants
  storage at once; gates on match-rate and p99 time-to-placement.
* ``regionfail`` — a quarter of the regions die at one instant two days
  in (correlated failure); gates on repair-debt drain time and
  population durability-violation client-seconds.  The 10⁵-client
  simulated week of this is the tier-1 acceptance builtin.
* ``auditstorm`` — a freeloader cohort takes placements and drops the
  bytes; the resulting audit-report storm must block the freeloaders
  from further matches (>= 2 distinct failing reporters, the real
  store-side defense) without ever blocking an honest live client.
* ``drought`` — arrivals too sparse to pair inside the request expiry;
  gates that the deadline-heap expiry fires (no immortal queue entries)
  and that persistent retries still converge on matches.
* ``repaircascade`` — an uncorrelated 10% of clients vanish at once;
  the repair thundering herd must drain without starving the economy.

A scorecard is a plain sorted-JSON-able dict computed purely from
virtual time and the seeded model — never from the wall clock — so the
same seed replays **byte-identically** (the determinism acceptance
gate).  Wall-derived numbers (events/s, sim-seconds per wall-second)
ride in a separate stats dict and in the ``bkw_sim_*`` gauges.
"""

from __future__ import annotations

import asyncio
import gc
import json
import time
from typing import Dict, Optional, Tuple

from .. import defaults
from ..obs import diagnose as obs_diagnose
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs.series import SeriesRecorder
from .clock import SimClock
from .driver import SimDriver
from .model_client import SimParams, SimWorld

WEEK_S = 7 * 86_400.0

#: Virtual-time cadence of the SLO sampler — 2016 ticks per simulated
#: week, far below the per-event budget.
SLO_SAMPLE_S = 300.0

_EVENTS = obs_metrics.counter(
    "bkw_sim_events_total", "Virtual-clock events fired per scenario run",
    ("scenario",))
_SIM_SECONDS = obs_metrics.counter(
    "bkw_sim_seconds_total", "Simulated seconds advanced per scenario",
    ("scenario",))
_COMPRESSION = obs_metrics.gauge(
    "bkw_sim_time_compression",
    "Sim-seconds per wall-second of the last run", ("scenario",))
_EVENTS_PER_S = obs_metrics.gauge(
    "bkw_sim_events_per_wall_second",
    "Event throughput of the last run", ("scenario",))
_CLIENTS = obs_metrics.gauge(
    "bkw_sim_clients", "Population by model-client state at run end",
    ("scenario", "state"))
_DEBT = obs_metrics.gauge(
    "bkw_sim_repair_debt_bytes",
    "World-truth bytes with no live copy at run end", ("scenario",))
_VIOL = obs_metrics.counter(
    "bkw_sim_violation_client_seconds_total",
    "Client-seconds spent with any unrestorable byte (world truth)",
    ("scenario",))
_WAITS = obs_metrics.histogram(
    "bkw_sim_match_wait_seconds",
    "Sim seconds from first ask to fully placed", ("scenario",),
    buckets=obs_metrics.log_buckets(1.0, 2.0, 22))


def _wall() -> float:
    # The one wall-clock read in the sim plane: measuring its OWN time
    # compression requires real elapsed seconds (BKW006-baselined).
    return time.monotonic()


#: name -> (description, param overrides on top of SimParams defaults)
BUILTINS: Dict[str, Tuple[str, dict]] = {
    "flashcrowd": (
        "whole population arrives within one hour and requests at once",
        dict(clients=20_000, sim_seconds=WEEK_S, arrival_span_s=3600.0)),
    "regionfail": (
        "25% of regions die at one instant on day 2 (tier-1: 1e5 clients)",
        dict(clients=100_000, sim_seconds=WEEK_S,
             fail_at_s=2 * 86_400.0, fail_fraction=0.25,
             fail_kind="region",
             # thinner per-client cadence than default: 10^5 clients is
             # the tier-1 acceptance run, and the failure/repair
             # dynamics (detect -> repair-report -> re-place) do not
             # need a 3-day backup rhythm to be exercised
             backup_interval_s=6 * 86_400.0,
             audit_interval_s=3 * 86_400.0)),
    "auditstorm": (
        "2% freeloaders drop every byte; audit reports must block them",
        dict(clients=20_000, sim_seconds=WEEK_S, freeloader_rate=0.02,
             pass_report_rate=0.05)),
    "drought": (
        "arrivals sparser than the request expiry; retries must converge",
        dict(clients=500, sim_seconds=WEEK_S,
             arrival_span_s=5 * 86_400.0,
             backup_interval_s=10 * 86_400.0)),
    "repaircascade": (
        "10% of clients vanish uncorrelated at once on day 3",
        dict(clients=50_000, sim_seconds=WEEK_S,
             fail_at_s=3 * 86_400.0, fail_fraction=0.10,
             fail_kind="random")),
}


def builtin_sims() -> Dict[str, str]:
    """name -> one-line description (the scripts/scenario.py catalog)."""
    return {name: desc for name, (desc, _p) in BUILTINS.items()}


def make_scenario(name: str, clients: Optional[int] = None,
                  seed: Optional[int] = None,
                  sim_seconds: Optional[float] = None) -> SimParams:
    if name not in BUILTINS:
        raise KeyError(f"unknown sim scenario {name!r};"
                       f" builtins: {sorted(BUILTINS)}")
    _desc, over = BUILTINS[name]
    params = dict(over)
    if clients is not None:
        params["clients"] = int(clients)
    if sim_seconds is not None:
        params["sim_seconds"] = float(sim_seconds)
    params["seed"] = 0 if seed is None else int(seed)
    return SimParams(**params)


# --- gates -------------------------------------------------------------------


def _gate(gates: list, name: str, passed: bool, detail: str) -> None:
    gates.append({"name": name, "passed": bool(passed), "detail": detail})


def _blocked(world: SimWorld, cid: bytes) -> bool:
    return world.store.audit_failing_reporters(
        cid, defaults.AUDIT_REPORT_WINDOW_S) \
        >= defaults.AUDIT_SERVER_BLOCK_FAILURES


def _evaluate_gates(name: str, world: SimWorld, card: dict) -> list:
    gates: list = []
    rate = card["match_rate"]
    viol = card["violation_client_seconds"]
    if name == "flashcrowd":
        _gate(gates, "match_rate>=0.95", rate >= 0.95,
              f"placed/demand = {rate}")
        p99 = card["match_wait_p99_s"]
        _gate(gates, "p99_match_wait<=24h", p99 <= 86_400.0,
              f"p99 first-ask-to-placed = {p99}s")
        _gate(gates, "no_data_at_risk", viol == 0.0,
              f"violation_client_seconds = {viol}")
    elif name in ("regionfail", "repaircascade"):
        _gate(gates, "match_rate>=0.90", rate >= 0.90,
              f"placed/demand = {rate}")
        drain = card["repair_drain_s"]
        _gate(gates, "repair_debt_drained<=3d",
              drain is not None and drain <= 3 * 86_400.0,
              f"debt peak {card['repair_debt_peak_bytes']}b drained to <=5%"
              f" in {drain}s")
        # every affected owner carries ~detect_span/2 of undetected loss
        # plus one repair round-trip; 2 sim-days per lost-data client is
        # a generous population envelope for both builtins
        budget = 2 * 86_400.0 * max(
            1, int(world.params.clients * world.params.fail_fraction))
        _gate(gates, "violation_seconds_bounded", viol <= budget,
              f"{viol} client-seconds <= budget {budget}")
        # the live SLO plane must notice the injected failure (never
        # before it — pre-fault the world is provably quiet) and the
        # explainer must pin the injection site in its top-3 causes
        slo = card.get("slo") or {}
        fail_at = world.params.fail_at_s or 0.0
        first = slo.get("first_breach_t")
        _gate(gates, "slo_breach_after_fault",
              first is not None and first >= fail_at,
              f"first breach at {first}s (fault at {fail_at:g}s)")
        causes = [c["id"] for c in
                  (slo.get("diagnosis") or {}).get("causes", [])[:3]]
        _gate(gates, "slo_diagnosis_names_fault",
              any(c.startswith("fault:sim.") for c in causes),
              f"top causes: {causes}")
    elif name == "auditstorm":
        _gate(gates, "match_rate>=0.90", rate >= 0.90,
              f"placed/demand = {rate}")
        frees = [c for c in world.clients if c.freeloader]
        reported = [c for c in frees
                    if any(p[2] for p in c_pieces(world, c))]
        blocked = sum(1 for c in frees[:500] if _blocked(world, c.cid))
        checked = len(frees[:500])
        _gate(gates, "freeloaders_blocked>=0.8",
              checked > 0 and blocked >= 0.8 * checked,
              f"{blocked}/{checked} freeloaders match-blocked"
              f" ({len(reported)} held dropped pieces)")
        honest = [c for c in world.clients
                  if not c.freeloader and c.state != "dead"][:200]
        honest_blocked = sum(1 for c in honest if _blocked(world, c.cid))
        _gate(gates, "honest_not_blocked", honest_blocked == 0,
              f"{honest_blocked}/{len(honest)} live honest clients blocked")
    elif name == "drought":
        _gate(gates, "requests_expired", card["expired"] > 0,
              f"{card['expired']} queue entries reaped by the deadline heap")
        _gate(gates, "retries_converge>=0.5", rate >= 0.5,
              f"placed/demand = {rate} despite sparse arrivals")
        _gate(gates, "no_data_at_risk", viol == 0.0,
              f"violation_client_seconds = {viol}")
    return gates


def c_pieces(world: SimWorld, client) -> list:
    """A freeloader's *held* pieces are scattered on its victims; walk
    the reverse index (audit evidence for the auditstorm gate detail)."""
    out = []
    for owner_idx, pid in world.held.get(client.cid, ()):
        piece = world.clients[owner_idx].pieces.get(pid)
        if piece is not None:
            out.append(piece)
    return out


# --- the run -----------------------------------------------------------------


async def run_scenario_async(name: str, spec: SimParams
                             ) -> Tuple[dict, dict]:
    """Run one scenario on a fresh SimClock; returns (scorecard, stats).
    The scorecard is wall-clock-free and byte-stable per seed; stats
    carry the wall-derived compression numbers."""
    reg = obs_metrics.registry()

    def _ctr(metric: str) -> float:
        fam = reg.get(metric)
        return fam.value() if fam is not None else 0.0

    matched0 = _ctr("bkw_matchmakings_total")
    expired0 = _ctr("bkw_matchmaking_expired_total")
    clock = SimClock()
    driver = SimDriver(clock)
    world = SimWorld(clock, spec)
    # A 10^6-event run allocates faster than the cyclic collector's
    # default thresholds assume; with collection on, gen-2 sweeps over
    # the (acyclic) piece/heap population cost ~20% of the wall budget.
    # Batch work, single-threaded, bounded lifetime: collect once at
    # the end instead.
    gc_was_enabled = gc.isenabled()
    gc.disable()

    # --- live SLO plane on virtual time (obs/slo.py) ---------------------
    # World-truth numbers are recorded as synthetic series (the registry
    # is only flushed post-run), the burn-rate monitor runs the REAL
    # multi-window spans against the virtual clock, and the first breach
    # is diagnosed against the injected failure — all virtual-time
    # derived, so card["slo"] replays byte-identically per seed.
    recorder = SeriesRecorder((), clock=clock)
    slo_catalog = [obs_slo.Objective(
        id="sim_durability", kind="counter_rate",
        family="sim:violation_fraction_seconds", budget=1e-4,
        description="population fraction-seconds with unrestorable data")]
    slo_state: dict = {"breaches": [], "diagnosis": None, "ticks": 0}

    def _slo_breach(breach) -> None:
        slo_state["breaches"].append(breach.to_dict())
        if slo_state["diagnosis"] is None:
            events = []
            if spec.fail_at_s is not None and spec.fail_fraction > 0:
                events.append({"ts": spec.fail_at_s, "kind": "fault",
                               "site": f"sim.{spec.fail_kind}_fail"})
            # window wide enough to reach back past the detection lag
            # to the injection instant
            slo_state["diagnosis"] = obs_diagnose.explain(
                breach, recorder=recorder, events=events,
                now=breach.t, window_s=4 * 3600.0)

    slo = obs_slo.SLOMonitor(recorder, catalog=slo_catalog, clock=clock,
                             on_breach=_slo_breach, client="sim")

    def _slo_tick() -> None:
        t = clock.monotonic()
        world._accrue()  # bring the lazy ledger up to the tick instant
        recorder.record("sim:violation_fraction_seconds",
                        world.violation_client_seconds
                        / max(spec.clients, 1), t=t, kind="counter")
        recorder.record("sim:repair_debt_bytes",
                        float(world.repair_debt_bytes), t=t)
        recorder.record("sim:deaths", float(world.deaths), t=t,
                        kind="counter")
        slo_state["ticks"] += 1
        slo.evaluate(now=t)
        clock.call_later(SLO_SAMPLE_S, _slo_tick)

    clock.call_later(SLO_SAMPLE_S, _slo_tick)

    t0 = _wall()
    try:
        world.populate()
        await driver.run(spec.sim_seconds)
        world.finish()
        queue_end = world.matchmaker.pending()
        wall_s = max(_wall() - t0, 1e-9)
        waits = sorted(world.match_waits)
        card = {
            "scenario": name,
            "seed": spec.seed,
            "clients": spec.clients,
            "sim_seconds": spec.sim_seconds,
            "events": driver.events,
            "requests": world.requests,
            "retries": world.retries,
            "matchmakings": int(_ctr("bkw_matchmakings_total") - matched0),
            "expired": int(_ctr("bkw_matchmaking_expired_total") - expired0),
            "queue_depth_end": queue_end,
            "transfers": world.transfers,
            "failed_transfers": world.failed_transfers,
            "demand_bytes": world.demand_bytes,
            "granted_bytes": world.granted_bytes,
            "placed_bytes": world.placed_bytes,
            "match_rate": round(world.match_rate(), 6),
            "match_wait_p50_s": round(world.wait_quantile(0.50), 3),
            "match_wait_p99_s": round(world.wait_quantile(0.99), 3),
            "audit_failures": world.audit_failures,
            "audit_passes": world.audit_passes,
            "repairs_started": world.repairs_started,
            "deaths": world.deaths,
            "repair_debt_peak_bytes": world.debt_peak_bytes,
            "repair_debt_bytes_end": world.repair_debt_bytes,
            "repair_drain_s": (None if world.drain_s is None
                               else round(world.drain_s, 3)),
            "violation_client_seconds":
                round(world.violation_client_seconds, 3),
            "population": world.state_counts(),
        }
        card["slo"] = {
            "ticks": slo_state["ticks"],
            "status": slo.summary()["status"],
            "breaches": slo_state["breaches"],
            "first_breach_t": (slo_state["breaches"][0]["t"]
                               if slo_state["breaches"] else None),
            "diagnosis": slo_state["diagnosis"],
        }
        card["gates"] = _evaluate_gates(name, world, card)
        card["passed"] = all(g["passed"] for g in card["gates"])
        stats = {
            "wall_s": round(wall_s, 3),
            "events_per_s": round(driver.events / wall_s, 1),
            "time_compression": round(spec.sim_seconds / wall_s, 1),
        }
        _flush_metrics(name, world, driver, waits, stats)
        return card, stats
    finally:
        await driver.shutdown()
        world.close()
        if gc_was_enabled:
            gc.enable()
            gc.collect()


def _flush_metrics(name: str, world: SimWorld, driver: SimDriver,
                   waits, stats: dict) -> None:
    """One registry write per family AFTER the run — metric plumbing
    stays out of the per-event budget and out of the scorecard."""
    _EVENTS.inc(driver.events, scenario=name)
    _SIM_SECONDS.inc(world.params.sim_seconds, scenario=name)
    _COMPRESSION.set(stats["time_compression"], scenario=name)
    _EVENTS_PER_S.set(stats["events_per_s"], scenario=name)
    for state, count in world.state_counts().items():
        _CLIENTS.set(count, scenario=name, state=state)
    _DEBT.set(world.repair_debt_bytes, scenario=name)
    _VIOL.inc(world.violation_client_seconds, scenario=name)
    for w in waits:
        _WAITS.observe(w, scenario=name)


def run_sim(name: str, clients: Optional[int] = None,
            seed: Optional[int] = None,
            sim_seconds: Optional[float] = None) -> Tuple[dict, dict]:
    """Sync entry point (scripts, bench, tests outside a loop)."""
    spec = make_scenario(name, clients=clients, seed=seed,
                         sim_seconds=sim_seconds)
    return asyncio.run(run_scenario_async(name, spec))


def card_json(card: dict) -> str:
    """The canonical byte-stable rendering (determinism gate compares
    these strings across runs)."""
    return json.dumps(card, sort_keys=True, separators=(",", ":"))
