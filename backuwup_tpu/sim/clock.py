"""SimClock: the virtual-time implementation of the clock seam.

Implements the same three-method contract as
:class:`backuwup_tpu.utils.clock.SystemClock` — ``now()``,
``monotonic()``, ``await sleep()`` — plus the deadline heap the driver
schedules against.  Time never advances on its own: it jumps to the
next scheduled deadline when :class:`~backuwup_tpu.sim.driver.SimDriver`
pops it, so a simulated week costs exactly as much wall time as the
event handlers themselves.

Two scheduling surfaces share one heap:

* ``call_at`` / ``call_later`` — the driver's event API: a plain (or
  async) callable fired when virtual time reaches the deadline.  Ties
  break by submission order (a monotonic seq), so runs are replayable.
* ``sleep(delay)`` — the seam API: parks the *calling task* on the heap
  via a future the wakeup event resolves.  ``blocked`` counts tasks
  parked here, which is how the driver knows the loop has quiesced and
  it is safe to jump time forward.

``now == monotonic`` by construction: virtual time only moves forward,
so the interval clock and the timestamp clock are the same axis (the
real-time split exists to survive NTP steps, which the sim does not
model).
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Callable, List, Optional, Tuple


class SimClock:
    """Heap-backed virtual clock satisfying the ``utils.clock`` seam."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._heap: List[Tuple[float, int, Callable, tuple]] = []
        self._seq = itertools.count()
        #: tasks currently parked inside :meth:`sleep` — the driver's
        #: quiescence signal
        self.blocked = 0

    # --- the seam contract --------------------------------------------------

    def now(self) -> float:
        return self._now

    def monotonic(self) -> float:
        return self._now

    async def sleep(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.call_later(delay, self._wake, fut)
        # ``blocked`` must drop the moment the wake event FIRES (inside
        # :meth:`_wake`), not when this task resumes: the driver checks
        # ``active <= blocked`` between firing an event and yielding,
        # and a woken-but-not-yet-resumed sleeper still counted as
        # parked would let it advance time right past the resumption.
        self.blocked += 1
        try:
            await fut
        except BaseException:
            if not (fut.done() and not fut.cancelled()):
                # cancelled while parked: _wake never ran (and when its
                # stale heap event eventually fires it will skip the
                # done future), so retire the slot here
                self.blocked -= 1
            raise

    def _wake(self, fut) -> None:
        if not fut.done():  # the sleeper may have been cancelled
            fut.set_result(None)
            self.blocked -= 1

    # --- the deadline heap --------------------------------------------------

    def call_at(self, when: float, fn: Callable, *args) -> None:
        """Schedule ``fn(*args)`` for virtual time ``when`` (clamped to
        now — the past is not addressable).  ``fn`` may be sync or a
        coroutine function; the driver awaits coroutines inline."""
        when = self._now if when < self._now else float(when)
        heapq.heappush(self._heap, (when, next(self._seq), fn, args))

    def call_later(self, delay: float, fn: Callable, *args) -> None:
        self.call_at(self._now + max(0.0, float(delay)), fn, *args)

    def next_deadline(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def pop_event(self) -> Tuple[Callable, tuple]:
        """Advance to — and return — the earliest event.  Driver-only."""
        when, _seq, fn, args = heapq.heappop(self._heap)
        if when > self._now:
            self._now = when
        return fn, args

    def advance_to(self, when: float) -> None:
        """Jump to ``when`` without firing anything (the driver's final
        hop to the horizon after the heap runs dry)."""
        if when > self._now:
            self._now = float(when)

    def pending(self) -> int:
        return len(self._heap)
