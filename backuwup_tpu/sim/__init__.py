"""Virtual-clock simulation plane (docs/simulation.md).

A deterministic discrete-event harness that runs the REAL coordination
code — ShardedMatchmaker, SqliteServerStore, PeerStats, retry policies,
the durability sweep — on simulated time, with lightweight model
clients standing in for the engine/crypto/bytes.  A simulated week of
10⁵–10⁶ client churn executes in tier-1 minutes (``bkw_sim_*`` metrics
record the compression ratio).
"""

from .clock import SimClock
from .driver import SimDriver
from .model_client import ModelClient, SimParams, SimWorld, client_id
from .scenarios import (BUILTINS, builtin_sims, card_json, make_scenario,
                        run_scenario_async, run_sim)

__all__ = [
    "SimClock", "SimDriver", "ModelClient", "SimParams", "SimWorld",
    "client_id", "BUILTINS", "builtin_sims", "card_json",
    "make_scenario", "run_scenario_async", "run_sim",
]
