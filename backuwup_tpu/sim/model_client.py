"""Model clients: seeded state machines over the real wire types.

A :class:`ModelClient` is what is left of a backup client when the
engine, the crypto, and the bytes are deleted: the *protocol* state
machine — request storage, receive :class:`~backuwup_tpu.wire.\
BackupMatched` grants, complete transfers after ``size / bandwidth``
virtual seconds, audit holders, report failures, repair lost bytes.
Everything between a request and a grant is the REAL coordination
plane: :class:`~backuwup_tpu.net.matchmaking.ShardedMatchmaker` over a
direct-commit :class:`~backuwup_tpu.net.serverstore.SqliteServerStore`,
both running on the :class:`~backuwup_tpu.sim.clock.SimClock` — the sim
contributes populations and physics, never a matchmaking
reimplementation.

Durability accounting is world-truth, not client-belief: a piece
becomes *lost* the virtual instant its holder dies or drops it (the
owner only finds out at its next detection window), and
``violation_client_seconds`` integrates the number of clients holding
any lost byte — the population-scale analogue of
``bkw_durability_violation_seconds_total``.  ``repair_debt_bytes`` is
the same ledger summed in bytes; scenario gates measure how fast a
failure's debt spike drains back to ~zero.

Determinism: one ``random.Random(seed)`` drawn in event order, ids from
``blake2b``, no wall-clock reads (BKW006 covers this package) — the
same seed replays byte-identically.
"""

from __future__ import annotations

import hashlib
import math
import random
from collections import namedtuple
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .. import defaults, wire
from ..net.matchmaking import ShardedMatchmaker
from ..net.peer_stats import PeerStats
from ..net.serverstore import SqliteServerStore
from ..utils import retry

#: states a ModelClient moves through (population gauge labels)
S_OFFLINE = "offline"   # not yet arrived, or temporarily dark
S_IDLE = "idle"         # online, nothing pending
S_REQUESTING = "requesting"  # bytes awaiting grant or transfer
S_STEADY = "steady"     # all pieces placed
S_DEAD = "dead"         # permanent departure

_ONLINE = (S_IDLE, S_REQUESTING, S_STEADY)

#: TransferResult-shaped record for PeerStats.observe
_Transfer = namedtuple("_Transfer", "peer_id size ok wait_s send_s")


def client_id(index: int) -> bytes:
    """Deterministic 32-byte client id (wire.CLIENT_ID_LEN)."""
    return hashlib.blake2b(b"bkw-sim-client:%d" % index,
                           digest_size=32).digest()


@dataclass(frozen=True)
class SimParams:
    """Knobs for one simulated population; scenarios freeze these."""

    clients: int
    sim_seconds: float
    seed: int = 0
    regions: int = 8
    arrival_span_s: float = 86_400.0   # arrivals spread over this window
    backup_interval_s: float = 3 * 86_400.0
    last_cycle_before_s: float = 86_400.0  # no new cycles this close to end
    audit_interval_s: float = 2 * 86_400.0
    detect_span_s: float = 12 * 3600.0  # loss noticed within this window
    expiry_s: float = defaults.BACKUP_REQUEST_EXPIRY_S
    shards: int = 2
    size_min_b: int = 32 << 20
    size_max_b: int = 1 << 30
    bw_min_bps: float = 2e6
    bw_max_bps: float = 32e6
    notify_latency_s: float = 0.05
    freeloader_rate: float = 0.0
    flapper_rate: float = 0.01
    flap_span_s: float = 3600.0
    background_death_rate: float = 0.0  # fraction dying over the horizon
    fail_at_s: Optional[float] = None
    fail_fraction: float = 0.0
    fail_kind: str = "region"  # or "random"
    pass_report_rate: float = 0.02  # audit passes recorded to the store
    peer_stats_stride: int = 997  # clients feeding the shared PeerStats


class ModelClient:
    """One simulated client; all behavior runs as clock events."""

    __slots__ = ("world", "idx", "cid", "region", "state", "bw_bps",
                 "pieces", "pending", "lost_bytes", "freeloader",
                 "request_started", "demand_bytes", "placed_bytes",
                 "timer", "next_pid", "cycles")

    def __init__(self, world: "SimWorld", idx: int):
        self.world = world
        self.idx = idx
        self.cid = client_id(idx)
        self.region = idx % world.params.regions
        self.state = S_OFFLINE
        rng = world.rng
        p = world.params
        self.bw_bps = _log_uniform(rng, p.bw_min_bps, p.bw_max_bps)
        #: pid -> [size, holder_cid, dropped] (dropped: holder kept the
        #: negotiation but not the data — a freeloader placement)
        self.pieces: Dict[int, list] = {}
        self.pending = 0          # bytes granted-nor-placed yet
        self.lost_bytes = 0       # bytes with no live copy (world truth)
        self.freeloader = rng.random() < p.freeloader_rate
        self.request_started: Optional[float] = None
        self.demand_bytes = 0
        self.placed_bytes = 0
        self.timer = retry.RetryTimer(retry.STORAGE_REQUEST,
                                      rand=rng.random, clock=world.clock)
        self.next_pid = 0
        self.cycles = 0

    # --- lifecycle events ---------------------------------------------------

    def arrive(self) -> None:
        if self.state != S_OFFLINE:
            return
        self.state = S_IDLE
        w = self.world
        w.store.register_client(self.cid)
        w.clock.call_later(w.rng.random() * 60.0, self.start_backup)
        w.clock.call_later(
            w.params.audit_interval_s * (0.5 + w.rng.random()),
            self.audit_tick)

    def die(self) -> None:
        """Permanent departure: held data becomes lost for its owners."""
        if self.state == S_DEAD:
            return
        self.state = S_DEAD
        self.world.on_death(self)

    def go_dark(self, span_s: float) -> None:
        """Temporary offline window (exercises the offline-drop and
        failed-push paths of the real matchmaker)."""
        if self.state in (S_DEAD, S_OFFLINE):
            return
        prev = self.state
        self.state = S_OFFLINE
        self.world.clock.call_later(span_s, self._return_online, prev)

    def _return_online(self, prev: str) -> None:
        if self.state != S_OFFLINE:
            return
        if self.pending > 0:
            # demand accumulated while dark (e.g. a loss noticed just
            # before the flap): pick the request loop back up
            self.state = S_REQUESTING
            self.world.clock.call_later(1.0, self._retry_check)
        else:
            self.state = prev

    # --- the backup cycle ---------------------------------------------------

    def start_backup(self) -> None:
        if self.state not in (S_IDLE, S_STEADY, S_REQUESTING):
            return
        w = self.world
        p = w.params
        size = int(_log_uniform(w.rng, p.size_min_b, p.size_max_b))
        self.cycles += 1
        self._add_demand(size)
        nxt = w.clock.now() + p.backup_interval_s * (0.9 + 0.2 * w.rng.random())
        if nxt < p.sim_seconds - p.last_cycle_before_s:
            w.clock.call_at(nxt, self.start_backup)

    def _add_demand(self, size: int) -> None:
        """New bytes to place (growth or repair); triggers a request."""
        w = self.world
        if self.pending == 0 and self.request_started is None:
            self.request_started = w.clock.now()
        self.pending += size
        self.demand_bytes += size
        w.demand_bytes += size
        if self.state in _ONLINE:
            self.state = S_REQUESTING
            w.clock.call_at(w.clock.now(), self._request, size)

    async def _request(self, amount: int) -> None:
        """Ask the REAL matchmaker; the unmatched remainder queues on its
        deadline heap and the retry check below re-asks after expiry."""
        if self.state != S_REQUESTING or self.pending <= 0:
            return
        w = self.world
        amount = min(amount, self.pending)
        w.requests += 1
        await w.matchmaker.fulfill(self.cid, amount, min_peers=1)
        self.timer.fire()
        w.clock.call_later(w.params.expiry_s * (1.05 + 0.1 * w.rng.random()),
                           self._retry_check)

    def _retry_check(self) -> None:
        if self.state != S_REQUESTING or self.pending <= 0:
            return
        w = self.world
        w.retries += 1
        w.clock.call_at(w.clock.now(), self._request, self.pending)

    # --- grants and transfers ----------------------------------------------

    def on_push(self, msg) -> None:
        """A server push delivered over the (simulated) WebSocket."""
        if isinstance(msg, wire.BackupMatched) and self.state in _ONLINE:
            self._on_grant(bytes(msg.destination_id),
                           int(msg.storage_available))

    def _on_grant(self, dest: bytes, available: int) -> None:
        amt = min(self.pending, available)
        if amt <= 0:
            return  # stale grant for an already-satisfied request
        self.pending -= amt
        w = self.world
        w.granted_bytes += amt
        send_s = amt / self.bw_bps
        w.clock.call_later(send_s + w.params.notify_latency_s,
                           self._transfer_done, dest, amt, send_s)

    def _transfer_done(self, dest: bytes, amt: int, send_s: float) -> None:
        if self.state == S_DEAD:
            return
        w = self.world
        holder = w.by_cid.get(dest)
        ok = holder is not None and holder.state != S_DEAD
        if self.idx % w.params.peer_stats_stride == 0:
            w.peer_stats.observe(_Transfer(
                peer_id=dest, size=amt, ok=ok, wait_s=0.0, send_s=send_s))
        if not ok:
            # the peer vanished mid-transfer: the bytes still need a home
            self.pending += amt
            if self.state in _ONLINE:
                self.state = S_REQUESTING
                if self.request_started is None:
                    self.request_started = w.clock.now()
            w.failed_transfers += 1
            w.clock.call_later(w.params.expiry_s * w.rng.random(),
                               self._retry_check)
            return
        w.transfers += 1
        self.placed_bytes += amt
        w.placed_bytes += amt
        healed = min(amt, self.lost_bytes)
        if healed:
            w.on_healed(self, healed)
        pid = self.next_pid
        self.next_pid += 1
        dropped = holder.freeloader
        self.pieces[pid] = [amt, dest, dropped]
        w.held.setdefault(dest, set()).add((self.idx, pid))
        if dropped:
            # the holder ack'd and will pass negotiation checks, but the
            # data is gone the moment it lands — world-truth loss now,
            # owner discovery at the next audit over this piece
            w.on_lost(self, amt)
        if self.pending <= 0:
            self.pending = 0
            self.state = S_STEADY
            self.timer.reset()
            if self.request_started is not None:
                w.match_waits.append(w.clock.now() - self.request_started)
                self.request_started = None

    # --- audits and repair --------------------------------------------------

    def audit_tick(self) -> None:
        if self.state == S_DEAD:
            return
        w = self.world
        if self.state in _ONLINE and self.pieces:
            pid, piece = self._audit_target()
            size, holder_cid, dropped = piece
            holder = w.by_cid.get(holder_cid)
            failed = dropped or holder is None or holder.state == S_DEAD
            if failed:
                w.audit_failures += 1
                w.store.save_audit_report(self.cid, holder_cid, False,
                                          "sim: holder lost data")
                self._start_repair(pid)
            else:
                w.audit_passes += 1
                if w.rng.random() < w.params.pass_report_rate:
                    w.store.save_audit_report(self.cid, holder_cid, True,
                                              "sim: ok")
        w.clock.call_later(
            w.params.audit_interval_s * (0.8 + 0.4 * w.rng.random()),
            self.audit_tick)

    def _audit_target(self) -> Tuple[int, list]:
        """Dropped/dead-holder pieces first (deterministic scan), else a
        seeded pick — models an auditor that cycles all its holders."""
        w = self.world
        for pid in self.pieces:
            piece = self.pieces[pid]
            holder = w.by_cid.get(piece[1])
            if piece[2] or holder is None or holder.state == S_DEAD:
                return pid, piece
        pids = list(self.pieces)
        pid = pids[w.rng.randrange(len(pids))]
        return pid, self.pieces[pid]

    def notice_loss(self, pid: int) -> None:
        """The owner's delayed discovery of a dead holder (the audit /
        dark-deadline path, collapsed to a seeded detection window)."""
        if self.state == S_DEAD or pid not in self.pieces:
            return
        piece = self.pieces[pid]
        self.world.store.save_audit_report(
            self.cid, piece[1], False, "sim: holder dead")
        self._start_repair(pid)

    def _start_repair(self, pid: int) -> None:
        piece = self.pieces.pop(pid, None)
        if piece is None:
            return
        w = self.world
        size, holder_cid, _dropped = piece
        w.held.get(holder_cid, set()).discard((self.idx, pid))
        w.repairs_started += 1
        w.store.save_repair_report(self.cid, holder_cid, 1, size, 0)
        self._add_demand(size)


class SimConnections:
    """The matchmaker's ``Connections`` interface over the population:
    pushes become clock events delivered after a small latency."""

    def __init__(self, world: "SimWorld"):
        self.world = world

    def is_online(self, client_id: bytes) -> bool:
        c = self.world.by_cid.get(bytes(client_id))
        return c is not None and c.state in _ONLINE

    async def notify(self, client_id: bytes, msg) -> bool:
        c = self.world.by_cid.get(bytes(client_id))
        if c is None or c.state not in _ONLINE:
            return False
        self.world.clock.call_later(
            self.world.params.notify_latency_s, c.on_push, msg)
        return True


class SimWorld:
    """Population + real coordination plane + durability ledger."""

    def __init__(self, clock, params: SimParams):
        self.clock = clock
        self.params = params
        self.rng = random.Random(params.seed)
        self.store = SqliteServerStore(":memory:", write_behind=False)
        self.connections = SimConnections(self)
        self.matchmaker = ShardedMatchmaker(
            self.store, self.connections, expiry_s=params.expiry_s,
            shards=params.shards, clock=clock)
        self.peer_stats = PeerStats(clock=clock)
        self.clients: List[ModelClient] = []
        self.by_cid: Dict[bytes, ModelClient] = {}
        #: holder cid -> {(owner idx, pid)} — the reverse placement index
        #: that makes holder-death fan-out O(pieces held)
        self.held: Dict[bytes, Set[Tuple[int, int]]] = {}
        # demand/supply ledger
        self.demand_bytes = 0
        self.granted_bytes = 0
        self.placed_bytes = 0
        self.requests = 0
        self.retries = 0
        self.transfers = 0
        self.failed_transfers = 0
        self.audit_failures = 0
        self.audit_passes = 0
        self.repairs_started = 0
        self.deaths = 0
        self.match_waits: List[float] = []
        # durability ledger (world truth, accrued incrementally)
        self.repair_debt_bytes = 0
        self.debt_peak_bytes = 0
        self.violated_clients = 0
        self.violation_client_seconds = 0.0
        self._viol_last_t = 0.0
        # failure-drain tracking (armed by inject_failure)
        self.fail_time: Optional[float] = None
        self.drain_s: Optional[float] = None
        self._drain_floor = 0

    # --- population construction -------------------------------------------

    def populate(self) -> None:
        """Create the population and schedule arrivals, flaps, and
        background deaths — all draws in index order for replay."""
        p = self.params
        for i in range(p.clients):
            c = ModelClient(self, i)
            self.clients.append(c)
            self.by_cid[c.cid] = c
            self.clock.call_at(self.rng.random() * p.arrival_span_s,
                               c.arrive)
            if self.rng.random() < p.flapper_rate:
                at = p.arrival_span_s + self.rng.random() * max(
                    1.0, p.sim_seconds - 2 * p.arrival_span_s)
                self.clock.call_at(at, c.go_dark, p.flap_span_s)
            if p.background_death_rate > 0 \
                    and self.rng.random() < p.background_death_rate:
                at = p.arrival_span_s + self.rng.random() * max(
                    1.0, p.sim_seconds - p.arrival_span_s)
                self.clock.call_at(at, self._kill, c)
        if p.fail_at_s is not None and p.fail_fraction > 0:
            self.clock.call_at(p.fail_at_s, self.inject_failure)

    def _kill(self, c: ModelClient) -> None:
        if c.state != S_DEAD:
            self.deaths += 1
            c.die()

    def inject_failure(self) -> None:
        """The scenario's mass-failure event: a region (correlated) or a
        seeded random fraction (uncorrelated) departs at one instant."""
        p = self.params
        self.fail_time = self.clock.now()
        if p.fail_kind == "region":
            doomed_regions = max(1, round(p.regions * p.fail_fraction))
            doomed = [c for c in self.clients
                      if c.region < doomed_regions and c.state != S_DEAD]
        else:
            doomed = [c for c in self.clients
                      if c.state != S_DEAD
                      and self.rng.random() < p.fail_fraction]
        for c in doomed:
            self._kill(c)
        self.debt_peak_bytes = max(self.debt_peak_bytes,
                                   self.repair_debt_bytes)
        self._drain_floor = max(1, self.repair_debt_bytes // 20)

    # --- the durability ledger ---------------------------------------------

    def _accrue(self) -> None:
        now = self.clock.now()
        if now > self._viol_last_t:
            self.violation_client_seconds += \
                self.violated_clients * (now - self._viol_last_t)
        self._viol_last_t = now

    def on_lost(self, owner: ModelClient, size: int) -> None:
        self._accrue()
        if owner.lost_bytes == 0:
            self.violated_clients += 1
        owner.lost_bytes += size
        self.repair_debt_bytes += size
        self.debt_peak_bytes = max(self.debt_peak_bytes,
                                   self.repair_debt_bytes)

    def on_healed(self, owner: ModelClient, size: int) -> None:
        self._accrue()
        owner.lost_bytes -= size
        self.repair_debt_bytes -= size
        if owner.lost_bytes == 0:
            self.violated_clients -= 1
        self._check_drained()

    def _check_drained(self) -> None:
        if self.fail_time is not None and self.drain_s is None \
                and self.repair_debt_bytes <= self._drain_floor:
            self.drain_s = self.clock.now() - self.fail_time

    def on_death(self, holder: ModelClient) -> None:
        """Holder death: every piece it held is lost NOW; each owner
        notices within its detection window and starts repair.  The
        dead client's own pending/pieces stop mattering — its queued
        matchmaking entries are dropped at pop (offline check) and its
        placements are reclaimed by its (former) peers."""
        p = self.params
        if holder.lost_bytes > 0:
            # a dead owner has no one to restore to: retire its ledger
            # (mutual-death pairs in a region kill would otherwise pin
            # repair debt forever)
            self._accrue()
            self.violated_clients -= 1
            self.repair_debt_bytes -= holder.lost_bytes
            holder.lost_bytes = 0
            self._check_drained()
        for owner_idx, pid in sorted(self.held.pop(holder.cid, ())):
            owner = self.clients[owner_idx]
            if owner.state == S_DEAD:
                continue
            piece = owner.pieces.get(pid)
            if piece is None or piece[2]:
                continue  # already counted lost (freeloader drop)
            piece[2] = True
            self.on_lost(owner, piece[0])
            self.clock.call_later(p.detect_span_s * self.rng.random(),
                                  owner.notice_loss, pid)
        # peers reclaim the dead client's own placements (the real
        # reclaim path; sampled — one peer per dead client keeps the
        # sqlite cost proportional to deaths, not placements)
        for pid, piece in list(holder.pieces.items())[:1]:
            self.store.reclaim_negotiation(holder.cid, piece[1])

    def finish(self) -> None:
        """Final ledger accrual at the horizon."""
        self._accrue()

    # --- derived facts ------------------------------------------------------

    def match_rate(self) -> float:
        if self.demand_bytes <= 0:
            return 1.0
        return self.placed_bytes / self.demand_bytes

    def state_counts(self) -> Dict[str, int]:
        counts = {s: 0 for s in
                  (S_OFFLINE, S_IDLE, S_REQUESTING, S_STEADY, S_DEAD)}
        for c in self.clients:
            counts[c.state] += 1
        return counts

    def wait_quantile(self, q: float) -> float:
        if not self.match_waits:
            return 0.0
        waits = sorted(self.match_waits)
        i = min(len(waits) - 1, int(q * len(waits)))
        return waits[i]

    def close(self) -> None:
        self.store.close()


def _log_uniform(rng: random.Random, lo: float, hi: float) -> float:
    if hi <= lo:
        return lo
    return math.exp(rng.uniform(math.log(lo), math.log(hi)))
