"""SimDriver: single-threaded discrete-event scheduler over a SimClock.

The driver owns the run loop that makes time compression possible: pop
the earliest deadline off the clock's heap, jump virtual time to it,
run the handler to completion, repeat.  Wall time is spent only inside
handlers — the simulated week between two events costs nothing.

Determinism contract:

* every event fires in (deadline, submission-seq) order — no wall
  clock, no thread scheduling, no hash randomization in the loop;
* an async handler is awaited to completion *inline*, so its store
  writes and notify fan-out land before the next event fires.  Handlers
  must therefore never ``await clock.sleep(...)`` themselves — anything
  that sleeps belongs in a :meth:`spawn`-ed task;
* spawned tasks (retry loops, sweep cadences — the real production
  coroutines) run between events: the driver yields to the asyncio
  loop until every live task is parked in ``SimClock.sleep`` before it
  advances time.  A task blocked on anything *else* (a real socket, a
  real sleep) would stall the run, so quiescence is bounded and the
  driver raises instead of spinning — keeping the determinism promise
  honest rather than silently racing.

``bkw_sim_*`` metrics are flushed by the scenario layer after the run
(one registry write per family, not one per event) so metric plumbing
never shows up in the events/s budget.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from .clock import SimClock

#: cooperative-yield budget per quiescence check; a well-formed model
#: settles in a handful of passes, so hitting this means a spawned task
#: is blocked outside the clock seam
_QUIESCE_LIMIT = 10_000


class SimDriver:
    """Event loop for one simulation run."""

    def __init__(self, clock: Optional[SimClock] = None):
        self.clock = clock if clock is not None else SimClock()
        self.events = 0
        self._active = 0  # spawned tasks not yet completed
        self._tasks: List[asyncio.Task] = []
        self._failures: List[BaseException] = []

    # --- spawned production coroutines --------------------------------------

    def spawn(self, coro) -> asyncio.Task:
        """Run a coroutine (a real retry loop, a sweep cadence) alongside
        the event stream; it advances whenever the tasks it sleeps on the
        virtual clock come due."""
        task = asyncio.ensure_future(coro)
        self._active += 1
        task.add_done_callback(self._on_done)
        self._tasks.append(task)
        return task

    def _on_done(self, task: asyncio.Task) -> None:
        self._active -= 1
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self._failures.append(exc)

    async def _quiesce(self) -> None:
        for _ in range(_QUIESCE_LIMIT):
            if self._failures:
                raise self._failures[0]
            if self._active <= self.clock.blocked:
                return
            await asyncio.sleep(0)
        raise RuntimeError(
            "sim did not quiesce: a spawned task is blocked on something"
            " other than SimClock.sleep — the driver cannot advance"
            " virtual time past it")

    # --- the run loop -------------------------------------------------------

    async def run(self, until: float) -> int:
        """Fire events in deadline order until virtual time would pass
        ``until`` (then jump to it); returns events fired this call."""
        fired = 0
        clock = self.clock
        while True:
            await self._quiesce()
            deadline = clock.next_deadline()
            if deadline is None or deadline > until:
                clock.advance_to(until)
                await self._quiesce()
                break
            fn, args = clock.pop_event()
            self.events += 1
            fired += 1
            res = fn(*args)
            if res is not None and asyncio.iscoroutine(res):
                await res
        return fired

    async def shutdown(self) -> None:
        """Cancel still-running spawned tasks (infinite cadences like
        ``InvariantMonitor.run``) so the surrounding loop can close."""
        for task in self._tasks:
            if not task.done():
                task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
