"""Status messenger: the progress/telemetry hub.

Re-designs ``client/src/ui/ws_status_message.rs``: a process-wide pub/sub
of log lines, lifecycle events, and debounced progress snapshots that UI
front-ends (CLI, web dashboard, tests) subscribe to.  Progress updates are
coalesced to at most one per 100 ms (``:134-141``); subscribers are
lag-tolerant bounded queues (``ui/ws.rs:31-56``).

Every event also flows into the observability plane: the journal (when
installed) records each StatusEvent as one JSONL line, and per-kind /
per-outcome counters land in the metrics registry so ``GET /metrics``
carries the audit/erasure/transfer story without a UI attached.
"""

from __future__ import annotations

import json
import logging
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

from .. import defaults
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

logger = logging.getLogger(__name__)

_EVENTS = obs_metrics.counter(
    "bkw_messenger_events_total", "StatusEvents emitted by kind", ("kind",))
_SUB_ERRORS = obs_metrics.counter(
    "bkw_messenger_subscriber_errors_total",
    "Events dropped by a raising subscriber callback", ("subscriber",))
_AUDITS = obs_metrics.counter(
    "bkw_audit_total", "Audit verdicts by outcome", ("outcome",))
_ERASURE = obs_metrics.counter(
    "bkw_erasure_events_total", "Erasure-coding events by outcome",
    ("outcome",))


def _sub_label(cb: Callable) -> str:
    return getattr(cb, "__qualname__", None) or repr(cb)


@dataclass
class Progress:
    """ws_status_message.rs:48-61."""

    current_file: str = ""
    files_done: int = 0
    files_failed: int = 0
    size_estimate: int = 0
    bytes_on_disk: int = 0
    bytes_transmitted: int = 0
    running: bool = False


@dataclass
class StatusEvent:
    kind: str  # message | progress | backup_started | backup_finished | ...
    payload: dict = field(default_factory=dict)
    ts: float = field(default_factory=time.time)

    def to_json(self) -> str:
        return json.dumps({"kind": self.kind, "payload": self.payload,
                           "ts": self.ts}, sort_keys=True)


class Messenger:
    def __init__(self, debounce_s: float = defaults.PROGRESS_DEBOUNCE_S,
                 history: int = 1000):
        self._subs: List[Callable] = []
        self._debounce_s = debounce_s
        self._last_progress = 0.0
        self.progress_state = Progress()
        self.history: deque = deque(maxlen=history)
        self._sub_logged: set = set()  # subscribers whose first failure logged

    def subscribe(self, cb: Callable[[StatusEvent], None]) -> Callable:
        self._subs.append(cb)
        return lambda: self._subs.remove(cb)

    def _emit(self, event: StatusEvent) -> None:
        self.history.append(event)
        _EVENTS.inc(kind=event.kind)
        obs_journal.emit("status", event=event.kind, payload=event.payload,
                         trace_id=obs_trace.current_trace_id())
        for cb in list(self._subs):
            try:
                cb(event)
            except Exception:
                # lag-tolerant: a broken subscriber never blocks others —
                # but the drops are counted, and the first failure per
                # subscriber is logged so it cannot stay invisible forever
                label = _sub_label(cb)
                _SUB_ERRORS.inc(subscriber=label)
                if label not in self._sub_logged:
                    self._sub_logged.add(label)
                    logger.exception(
                        "messenger subscriber %s raised on %s event"
                        " (first failure; further drops only counted)",
                        label, event.kind)

    # --- producers ---------------------------------------------------------

    def log(self, message: str) -> None:
        self._emit(StatusEvent("message", {"text": message}))

    def progress(self, **fields) -> None:
        """Debounced snapshot merge (at most one event per 100 ms)."""
        p = self.progress_state
        if "file" in fields:
            p.current_file = fields.pop("file")
            p.files_done += 1
        for k, v in fields.items():
            if hasattr(p, k):
                setattr(p, k, v)
        now = time.time()
        if now - self._last_progress >= self._debounce_s:
            self._last_progress = now
            self._emit(StatusEvent("progress", asdict(p)))

    def tick(self) -> None:
        """Undebounced snapshot push (the 400 ms ticker and late-joining
        UI clients; backup/mod.rs:109-114)."""
        self._emit(StatusEvent("progress", asdict(self.progress_state)))

    def peers(self, peers: list) -> None:
        """Peer-ledger telemetry frame (ws_status_message.rs:128-163)."""
        self._emit(StatusEvent("peers", {"peers": peers}))

    def config(self, cfg: dict) -> None:
        self._emit(StatusEvent("config", cfg))

    def audit(self, peer: str, outcome: str, detail: str = "",
              demoted: bool = False) -> None:
        """Storage-audit verdict frame (outcome: pass | fail | miss)."""
        _AUDITS.inc(outcome=outcome)
        self._emit(StatusEvent("audit", {"peer": peer, "outcome": outcome,
                                         "detail": detail,
                                         "demoted": demoted}))

    def erasure(self, subject: str, outcome: str, shards: int = 0,
                rebuilt: int = 0) -> None:
        """Erasure-coding telemetry frame (outcome: placed | assembled |
        rebuilt); ``subject`` is a packfile id hex or a phase label."""
        _ERASURE.inc(outcome=outcome)
        self._emit(StatusEvent("erasure", {"subject": subject,
                                           "outcome": outcome,
                                           "shards": shards,
                                           "rebuilt": rebuilt}))

    def transfer(self, peer: str, outcome: str, size: int = 0,
                 inflight: int = 0, inflight_bytes: int = 0,
                 wait_ms: float = 0.0, send_ms: float = 0.0,
                 label: str = "", stages: Optional[Dict] = None,
                 overlap: Optional[Dict] = None) -> None:
        """Transfer-plane telemetry frame (net/transfer.py).

        ``outcome``: ``sent`` | ``failed`` per completed transfer, or
        ``summary`` for the end-of-run per-stage roll-up (``stages`` maps
        stage name -> seconds: seal/write/wait/send, ``overlap`` is the
        engine's wall-vs-max-stage verdict, docs/dataflow.md).
        ``inflight`` / ``inflight_bytes`` are the plane's gauges at
        emission time.
        """
        payload = {"peer": peer, "outcome": outcome, "size": size,
                   "inflight": inflight, "inflight_bytes": inflight_bytes,
                   "wait_ms": round(wait_ms, 3), "send_ms": round(send_ms, 3),
                   "label": label}
        if stages:
            payload["stages"] = {k: round(float(v), 4)
                                 for k, v in stages.items()}
        if overlap:
            payload["overlap"] = overlap
        self._emit(StatusEvent("transfer", payload))

    def error(self, text: str) -> None:
        self._emit(StatusEvent("error", {"text": text}))

    def _flush_progress(self) -> None:
        """Undebounced final snapshot: a run's last progress must never be
        eaten by the debounce window (UIs would end on a stale percent)."""
        self._last_progress = time.time()
        self._emit(StatusEvent("progress", asdict(self.progress_state)))

    def backup_started(self) -> None:
        self.progress_state = Progress(running=True)
        self._emit(StatusEvent("backup_started"))

    def backup_finished(self, snapshot: bytes) -> None:
        self.progress_state.running = False
        self._flush_progress()
        self._emit(StatusEvent("backup_finished",
                               {"snapshot": bytes(snapshot).hex()}))

    def restore_started(self) -> None:
        self.progress_state = Progress(running=True)
        self._emit(StatusEvent("restore_started"))

    def restore_finished(self) -> None:
        self.progress_state.running = False
        self._flush_progress()
        self._emit(StatusEvent("restore_finished"))

    def panic(self, message: str) -> None:
        """Fatal-error report hook (client main.rs:53-61 panic hook):
        besides the UI frame, trip the journal's flight-recorder dump."""
        self._emit(StatusEvent("panic", {"text": message}))
        obs_journal.panic(message)
