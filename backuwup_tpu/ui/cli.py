"""First-run CLI: recovery-phrase UX (``client/src/ui/cli.rs``).

Fresh setup prints the recovery phrase derived from the root secret
(``cli.rs:55-77``, which prints a BIP39 mnemonic — here both a 24-word
mnemonic from the embedded wordlist and the compact base32 form); the
restore path prompts for an existing phrase in either form and rebuilds
the identity deterministically (``cli.rs:26-51`` + ``identity.rs:46-69``).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

from ..crypto import parse_recovery, secret_to_phrase, secret_to_words

BANNER = """\
Welcome to backuwup!

Your backups are encrypted with keys derived from a single root secret.
The RECOVERY PHRASE below is the only way to get your data back after a
disaster — write it down and keep it somewhere safe and offline.
"""


def print_recovery_phrase(root_secret: bytes, out=None) -> None:
    out = out or sys.stdout
    print(BANNER, file=out)
    words = secret_to_words(root_secret).split()
    for i in range(0, len(words), 6):
        print("    " + " ".join(f"{w:<8}" for w in words[i:i + 6]).rstrip(),
              file=out)
    print("\nor, equivalently, the compact form:\n", file=out)
    print("    " + secret_to_phrase(root_secret), file=out)
    print("\nEither form restores your identity. Anyone with this phrase "
          "can read your backups; never share it.", file=out)


def prompt_restore_phrase(input_fn: Optional[Callable[[str], str]] = None,
                          out=None) -> bytes:
    """Interactive phrase entry with validation loop (cli.rs:26-51);
    accepts the 24-word or the base32 form, returns the root secret."""
    input_fn = input_fn or input
    out = out or sys.stdout
    while True:
        phrase = input_fn("Enter your recovery phrase (words or code): ")
        try:
            return parse_recovery(phrase)
        except ValueError as e:
            print(f"That phrase is not valid ({e}); try again.", file=out)


def first_run_guide(input_fn: Optional[Callable[[str], str]] = None,
                    out=None) -> Optional[bytes]:
    """Fresh-start vs restore choice (cli.rs:10-23).

    Returns None to create a new identity, or the decoded root secret to
    restore an existing one.
    """
    input_fn = input_fn or input
    out = out or sys.stdout
    print("No existing identity found.", file=out)
    while True:
        ans = input_fn(
            "Start fresh (n) or restore from a recovery phrase (r)? [n/r] ")
        ans = ans.strip().lower()
        if ans in ("", "n", "new"):
            return None
        if ans in ("r", "restore"):
            return prompt_restore_phrase(input_fn, out)
        print("Please answer 'n' or 'r'.", file=out)
