"""First-run CLI: recovery-phrase UX (``client/src/ui/cli.rs``).

Fresh setup prints the recovery phrase derived from the root secret
(``cli.rs:55-77``, the BIP39-mnemonic analog); the restore path prompts for
an existing phrase and rebuilds the identity deterministically
(``cli.rs:26-51`` + ``identity.rs:46-69``).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

from ..crypto import phrase_to_secret, secret_to_phrase

BANNER = """\
Welcome to backuwup!

Your backups are encrypted with keys derived from a single root secret.
The RECOVERY PHRASE below is the only way to get your data back after a
disaster — write it down and keep it somewhere safe and offline.
"""


def print_recovery_phrase(root_secret: bytes, out=None) -> None:
    out = out or sys.stdout
    print(BANNER, file=out)
    print("    " + secret_to_phrase(root_secret), file=out)
    print("\nAnyone with this phrase can read your backups; never share it.",
          file=out)


def prompt_restore_phrase(input_fn: Optional[Callable[[str], str]] = None,
                          out=None) -> bytes:
    """Interactive phrase entry with validation loop (cli.rs:26-51);
    returns the decoded root secret."""
    input_fn = input_fn or input
    out = out or sys.stdout
    while True:
        phrase = input_fn("Enter your recovery phrase: ")
        try:
            return phrase_to_secret(phrase)
        except ValueError as e:
            print(f"That phrase is not valid ({e}); try again.", file=out)


def first_run_guide(input_fn: Optional[Callable[[str], str]] = None,
                    out=None) -> Optional[bytes]:
    """Fresh-start vs restore choice (cli.rs:10-23).

    Returns None to create a new identity, or the decoded root secret to
    restore an existing one.
    """
    input_fn = input_fn or input
    out = out or sys.stdout
    print("No existing identity found.", file=out)
    while True:
        ans = input_fn(
            "Start fresh (n) or restore from a recovery phrase (r)? [n/r] ")
        ans = ans.strip().lower()
        if ans in ("", "n", "new"):
            return None
        if ans in ("r", "restore"):
            return prompt_restore_phrase(input_fn, out)
        print("Please answer 'n' or 'r'.", file=out)
