"""Embedded web dashboard (the reference embeds its SPA with rust-embed,
``client/src/ui/mod.rs:12-26``; the assets live in ``client/static/``).

One self-contained page: WebSocket auto-reconnect (1 s), progress %,
rolling 25-sample transfer speed, peer list, logs pane, backup/restore
buttons, and backup-path config — the same surface as
``client/static/app.js:131-244`` / ``index.html:142-170``, in plain JS.
"""

INDEX_HTML = r"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>backuwup</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root { --bg:#10141a; --panel:#1a2129; --text:#e6eaf0; --dim:#8b97a5;
        --accent:#4da3ff; --ok:#43c478; --warn:#e4b343; --err:#e05252; }
* { box-sizing:border-box; }
body { margin:0; background:var(--bg); color:var(--text);
       font:14px/1.5 system-ui, sans-serif; }
.wrap { max-width:880px; margin:0 auto; padding:24px 16px; }
h1 { font-size:20px; margin:0 0 16px; }
h1 small { color:var(--dim); font-weight:normal; margin-left:8px; }
.card { background:var(--panel); border-radius:10px; padding:16px;
        margin-bottom:16px; }
.row { display:flex; gap:12px; align-items:center; flex-wrap:wrap; }
button { background:var(--accent); color:#07111d; font-weight:600;
         border:0; border-radius:8px; padding:8px 18px; cursor:pointer; }
button.secondary { background:#2a3644; color:var(--text); }
button:disabled { opacity:.45; cursor:default; }
input[type=text] { background:#0d1117; color:var(--text); border:1px solid
         #2a3644; border-radius:6px; padding:7px 10px; flex:1; min-width:220px; }
.bar { height:10px; background:#0d1117; border-radius:5px; overflow:hidden;
       margin:10px 0 4px; }
.bar > div { height:100%; width:0; background:var(--ok); transition:width .2s; }
.stats { display:grid; grid-template-columns:repeat(auto-fit,minmax(130px,1fr));
         gap:8px; margin-top:8px; }
.stat { background:#0d1117; border-radius:8px; padding:8px 10px; }
.stat b { display:block; font-size:16px; }
.stat span { color:var(--dim); font-size:12px; }
#logs { background:#0d1117; border-radius:8px; padding:10px; height:180px;
        overflow-y:auto; font:12px/1.5 ui-monospace, monospace;
        white-space:pre-wrap; }
#peers td { padding:3px 10px 3px 0; font:12px ui-monospace, monospace; }
#conn { width:9px; height:9px; border-radius:50%; display:inline-block;
        background:var(--err); margin-right:6px; }
#conn.on { background:var(--ok); }
.err { color:var(--err); }
</style>
</head>
<body>
<div class="wrap">
  <h1><span id="conn"></span>backuwup <small>peer-to-peer encrypted backup</small></h1>

  <div class="card">
    <div class="row">
      <input type="text" id="path" placeholder="backup path">
      <button class="secondary" id="save">Save path</button>
      <button id="backup">Back up</button>
      <button class="secondary" id="restore">Restore</button>
      <button class="secondary" id="audit">Audit peers</button>
    </div>
    <div class="bar"><div id="pbar"></div></div>
    <div class="row" style="justify-content:space-between">
      <span id="pfile" style="color:var(--dim)"></span>
      <span id="ppct"></span>
    </div>
    <div class="stats">
      <div class="stat"><b id="sdone">0</b><span>files done</span></div>
      <div class="stat"><b id="sfail">0</b><span>files failed</span></div>
      <div class="stat"><b id="swritten">0 B</b><span>packed on disk</span></div>
      <div class="stat"><b id="ssent">0 B</b><span>transmitted</span></div>
      <div class="stat"><b id="sspeed">-</b><span>transfer speed</span></div>
    </div>
  </div>

  <div class="card">
    <b>Peers</b>
    <table id="peers"></table>
  </div>

  <div class="card">
    <b>Log</b>
    <div id="logs"></div>
  </div>
</div>
<script>
"use strict";
const $ = id => document.getElementById(id);
let ws = null;
// rolling transfer-speed window (25 samples; static/app.js:44-58)
const speedSamples = [];
function fmtBytes(n) {
  if (!n) return "0 B";
  const u = ["B","KiB","MiB","GiB","TiB"];
  let i = 0; while (n >= 1024 && i < u.length-1) { n /= 1024; i++; }
  return n.toFixed(n >= 100 || i === 0 ? 0 : 1) + " " + u[i];
}
function logLine(text, cls) {
  const el = $("logs");
  const d = document.createElement("div");
  if (cls) d.className = cls;
  d.textContent = new Date().toLocaleTimeString() + "  " + text;
  el.appendChild(d);
  while (el.childElementCount > 500) el.removeChild(el.firstChild);
  el.scrollTop = el.scrollHeight;
}
function send(cmd, extra) {
  if (ws && ws.readyState === 1)
    ws.send(JSON.stringify(Object.assign({command: cmd}, extra || {})));
}
function onProgress(p) {
  $("pfile").textContent = p.current_file || "";
  $("sdone").textContent = p.files_done;
  $("sfail").textContent = p.files_failed;
  $("swritten").textContent = fmtBytes(p.bytes_on_disk);
  $("ssent").textContent = fmtBytes(p.bytes_transmitted);
  const pct = p.size_estimate > 0
    ? Math.min(100, 100 * p.bytes_on_disk / p.size_estimate) : 0;
  $("pbar").style.width = pct + "%";
  $("ppct").textContent = p.running ? pct.toFixed(0) + "%" : "";
  const now = Date.now() / 1000;
  speedSamples.push([now, p.bytes_transmitted]);
  while (speedSamples.length > 25) speedSamples.shift();
  if (speedSamples.length > 1) {
    const [t0, b0] = speedSamples[0], [t1, b1] = speedSamples.at(-1);
    $("sspeed").textContent =
      t1 > t0 ? fmtBytes((b1 - b0) / (t1 - t0)) + "/s" : "-";
  }
  $("backup").disabled = $("restore").disabled = !!p.running;
}
function auditLabel(a) {
  if (!a) return "-";
  const tally = a.passes + "/" + a.failures + "/" + a.misses;
  return a.health + " (" + tally + ")";
}
function onPeers(peers) {
  const t = $("peers");
  t.innerHTML = "<tr><td>peer</td><td>negotiated</td><td>sent</td>" +
                "<td>stored for them</td><td>audit p/f/m</td></tr>";
  for (const p of peers) {
    const r = t.insertRow();
    for (const v of [p.id.slice(0, 12), fmtBytes(p.negotiated),
                     fmtBytes(p.transmitted), fmtBytes(p.received)])
      r.insertCell().textContent = v;
    const c = r.insertCell();
    c.textContent = auditLabel(p.audit);
    if (p.audit && (p.audit.health === "demoted" ||
                    p.audit.health.startsWith("fail")))
      c.className = "err";
  }
}
function onEvent(ev) {
  if (ev.kind === "progress") onProgress(ev.payload);
  else if (ev.kind === "peers") onPeers(ev.payload.peers);
  else if (ev.kind === "config") $("path").value = ev.payload.backup_path || "";
  else if (ev.kind === "message") logLine(ev.payload.text);
  else if (ev.kind === "panic") logLine("PANIC: " + ev.payload.text, "err");
  else if (ev.kind === "backup_started") logLine("backup started");
  else if (ev.kind === "backup_finished")
    logLine("backup finished: " + ev.payload.snapshot);
  else if (ev.kind === "restore_started") logLine("restore started");
  else if (ev.kind === "restore_finished") logLine("restore finished");
  else if (ev.kind === "audit") {
    const a = ev.payload;
    logLine("audit " + a.outcome + " for " + a.peer.slice(0, 12) +
            (a.detail ? ": " + a.detail : "") +
            (a.demoted ? " [demoted]" : ""),
            a.outcome === "pass" ? undefined : "err");
  }
  else if (ev.kind === "error") logLine(ev.payload.text, "err");
}
function connect() {
  ws = new WebSocket((location.protocol === "https:" ? "wss://" : "ws://") +
                     location.host + "/ws");
  ws.onopen = () => { $("conn").classList.add("on"); send("get_config"); };
  ws.onmessage = m => onEvent(JSON.parse(m.data));
  ws.onclose = () => {          // auto-reconnect (static/app.js:131-140)
    $("conn").classList.remove("on");
    setTimeout(connect, 1000);
  };
}
$("save").onclick = () => send("config", {backup_path: $("path").value});
$("backup").onclick = () => send("start_backup");
$("restore").onclick = () => send("start_restore");
$("audit").onclick = () => send("start_audit");
connect();
</script>
</body>
</html>
"""
