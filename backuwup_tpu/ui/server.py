"""Web dashboard server: embedded SPA + WebSocket push/command channel.

Re-designs the reference UI stack on aiohttp:

* ``ui/mod.rs:12-26`` — embedded static assets served at ``/``, push
  channel at ``/ws``;
* ``ui/ws.rs:31-56`` — per-client lag-tolerant forwarding of
  :class:`~backuwup_tpu.ui.messenger.Messenger` events (bounded queues:
  a slow browser tab drops old frames, never blocks the engine);
* ``ui/ws_dispatcher.rs:16-23`` — the four UI commands (``config``,
  ``get_config``, ``start_backup``, ``start_restore``) dispatched onto the
  client app;
* ``ws_status_message.rs:128-163`` + ``backup/mod.rs:109-114`` — progress
  ticker (400 ms) and peer-list telemetry (250 ms) pushed at the cadences
  ``defaults.PROGRESS_TICKER_S`` / ``defaults.PEERS_DEBOUNCE_S`` while any
  client is connected.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Optional, Set

from aiohttp import WSMsgType, web

from .. import defaults
from .static import INDEX_HTML

_QUEUE_CAP = 1000  # per-client event buffer (client/src/main.rs:72)


def ui_bind_addr() -> str:
    return os.environ.get("UI_BIND_ADDR", "127.0.0.1:8102")


class UIServer:
    """Serves the dashboard for one :class:`~backuwup_tpu.app.ClientApp`."""

    def __init__(self, client_app, bind: Optional[str] = None):
        self.app = client_app
        self.messenger = client_app.messenger
        host, _, port = (bind or ui_bind_addr()).rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._web = web.Application()
        self._web.add_routes([web.get("/", self._index),
                              web.get("/ws", self._ws)])
        self._runner: Optional[web.AppRunner] = None
        self._clients: Set[asyncio.Queue] = set()
        self._unsubscribe = None
        self._ticker_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # --- lifecycle ---------------------------------------------------------

    async def start(self) -> str:
        self._loop = asyncio.get_running_loop()
        self._unsubscribe = self.messenger.subscribe(self._fanout)
        self._runner = web.AppRunner(self._web)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve the real port for ephemeral binds
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
            break
        self._ticker_task = asyncio.create_task(self._ticker())
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
        if self._ticker_task is not None:
            self._ticker_task.cancel()
            try:
                await self._ticker_task
            except asyncio.CancelledError:
                pass
        if self._runner is not None:
            await self._runner.cleanup()

    # --- event fan-out (ui/ws.rs:31-56) ------------------------------------

    def _fanout(self, event) -> None:
        """Messenger callback; may fire from the packer thread."""
        if self._loop is None or not self._clients:
            return
        self._loop.call_soon_threadsafe(self._fanout_on_loop, event.to_json())

    def _fanout_on_loop(self, payload: str) -> None:
        for q in list(self._clients):
            if q.full():  # lag-tolerant: drop the oldest frame
                try:
                    q.get_nowait()
                except asyncio.QueueEmpty:
                    pass
            q.put_nowait(payload)

    async def _ticker(self) -> None:
        """Progress ticker + peer telemetry at the configured cadences."""
        last_peers = 0.0
        while True:
            await asyncio.sleep(defaults.PROGRESS_TICKER_S)
            if not self._clients:
                continue
            if self.messenger.progress_state.running:
                self.messenger.tick()
            now = asyncio.get_running_loop().time()
            if now - last_peers >= defaults.PEERS_DEBOUNCE_S:
                last_peers = now
                self.messenger.peers([
                    {"id": p.pubkey.hex(), "negotiated": p.bytes_negotiated,
                     "transmitted": p.bytes_transmitted,
                     "received": p.bytes_received,
                     "audit": self._peer_audit_health(p.pubkey)}
                    for p in self.app.store.list_peers()])

    def _peer_audit_health(self, pubkey: bytes) -> dict:
        st = self.app.store.get_audit_state(pubkey)
        if st.last_audit == 0.0 and not (st.passes or st.failures
                                         or st.misses):
            health = "unaudited"
        elif st.demoted:
            health = "demoted"
        else:
            health = st.last_result or "unaudited"
        return {"health": health, "passes": st.passes,
                "failures": st.failures, "misses": st.misses}

    # --- routes ------------------------------------------------------------

    async def _index(self, _request) -> web.Response:
        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _ws(self, request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        queue: asyncio.Queue = asyncio.Queue(maxsize=_QUEUE_CAP)
        self._clients.add(queue)
        # late joiners see current state immediately
        self.messenger.tick()
        writer = asyncio.create_task(self._write_loop(ws, queue))
        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    await self._dispatch(msg.data)
                elif msg.type == WSMsgType.ERROR:
                    break
        finally:
            self._clients.discard(queue)
            writer.cancel()
            try:
                await writer
            except asyncio.CancelledError:
                pass
        return ws

    async def _write_loop(self, ws, queue: asyncio.Queue) -> None:
        while True:
            payload = await queue.get()
            try:
                await ws.send_str(payload)
            except (ConnectionError, RuntimeError):
                return

    # --- command dispatcher (ui/ws_dispatcher.rs:16-66) --------------------

    async def _dispatch(self, raw: str) -> None:
        try:
            msg = json.loads(raw)
            command = msg.get("command")
        except (json.JSONDecodeError, AttributeError):
            self.messenger.error("malformed UI command")
            return
        if command == "get_config":
            self.messenger.config(
                {"backup_path": self.app.store.get_backup_path() or ""})
        elif command == "config":
            path = str(msg.get("backup_path", ""))
            self.app.store.set_backup_path(path)
            self.messenger.log(f"backup path set to {path}")
            self.messenger.config({"backup_path": path})
        elif command == "start_backup":
            asyncio.create_task(self._run_guarded(self.app.backup()))
        elif command == "start_restore":
            asyncio.create_task(self._run_guarded(self.app.restore()))
        elif command == "start_audit":
            asyncio.create_task(self._run_guarded(self.app.audit()))
        else:
            self.messenger.error(f"unknown UI command: {command!r}")

    async def _run_guarded(self, coro) -> None:
        try:
            await coro
        except Exception as e:  # surfaced to the dashboard, never raised
            self.messenger.error(str(e))
