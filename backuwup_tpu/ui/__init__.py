"""User interfaces: status messenger, CLI, web dashboard."""
