"""L0 wire types: IDs, nonces, hashes, blob/tree model, protocol messages.

Re-designs the reference ``shared/`` crate (``shared/src/types.rs:4-37``,
``shared/src/client_message.rs``, ``shared/src/server_message.rs``,
``shared/src/server_message_ws.rs``, ``shared/src/p2p_message.rs``) and the
client blob model (``client/src/backup/filesystem/mod.rs:14-105``) as plain
dataclasses plus a deterministic binary codec (:mod:`backuwup_tpu.utils.serialization`).

Control-plane messages travel as JSON (``to_json``/``from_json``); data-plane
blobs/trees/p2p bodies travel in the binary codec, mirroring the reference's
serde_json-vs-bincode split (SURVEY.md section 2.5).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Optional

from .utils.serialization import Reader, Writer

# --- fixed-size value types (reference shared/src/types.rs:4-37) ------------
CLIENT_ID_LEN = 32  # Ed25519 public key doubles as the client identity
BLOB_HASH_LEN = 32  # blake3 digest
PACKFILE_ID_LEN = 12  # doubles as the packfile header AES-GCM nonce
SHARD_ID_LEN = PACKFILE_ID_LEN + 1  # packfile id + erasure shard index byte
SESSION_TOKEN_LEN = 16
TRANSPORT_NONCE_LEN = 16
CHALLENGE_NONCE_LEN = 32
AUDIT_NONCE_LEN = 16  # per-window keyed-digest nonce (storage attestation)


def _check(name: str, value: bytes, length: int) -> bytes:
    if not isinstance(value, (bytes, bytearray)) or len(value) != length:
        raise ValueError(f"{name} must be exactly {length} bytes, got {value!r:.60}")
    return bytes(value)


def _check_storage_id(name: str, value: bytes) -> bytes:
    """A storage-plane object id: a whole packfile (12 bytes) or one
    erasure shard (packfile id + index byte, 13 bytes)."""
    if (not isinstance(value, (bytes, bytearray))
            or len(value) not in (PACKFILE_ID_LEN, SHARD_ID_LEN)):
        raise ValueError(
            f"{name} must be {PACKFILE_ID_LEN} or {SHARD_ID_LEN} bytes, "
            f"got {value!r:.60}")
    return bytes(value)


class BlobKind(IntEnum):
    """reference client/src/backup/filesystem/mod.rs:14-18."""

    FILE_CHUNK = 0
    TREE = 1


class CompressionKind(IntEnum):
    """reference client/src/backup/filesystem/mod.rs:20-24 (Zstd added Zlib
    fallback for hosts without libzstd)."""

    NONE = 0
    ZSTD = 1
    ZLIB = 2


class TreeKind(IntEnum):
    FILE = 0
    DIR = 1


@dataclass(frozen=True)
class Blob:
    """An unencrypted unit of backup data (mod.rs:37-43)."""

    hash: bytes
    kind: BlobKind
    data: bytes

    def __post_init__(self) -> None:
        _check("blob hash", self.hash, BLOB_HASH_LEN)


@dataclass(frozen=True)
class PackfileHeaderBlob:
    """Per-blob entry of a packfile header (mod.rs:26-35)."""

    hash: bytes
    kind: BlobKind
    compression: CompressionKind
    length: int  # encrypted (nonce + ciphertext) byte length
    offset: int  # offset into the blob section

    def encode(self, w: Writer) -> None:
        w.fixed(_check("packfile blob hash", self.hash, BLOB_HASH_LEN))
        w.u32(int(self.kind))
        w.u32(int(self.compression))
        w.u64(self.length)
        w.u64(self.offset)

    @classmethod
    def decode(cls, r: Reader) -> "PackfileHeaderBlob":
        return cls(
            hash=r.fixed(BLOB_HASH_LEN),
            kind=BlobKind(r.u32()),
            compression=CompressionKind(r.u32()),
            length=r.u64(),
            offset=r.u64(),
        )


@dataclass(frozen=True)
class TreeMetadata:
    """reference mod.rs:76-81."""

    size: int = 0
    mtime_ns: int = 0
    ctime_ns: int = 0

    def encode(self, w: Writer) -> None:
        w.u64(self.size)
        w.u64(self.mtime_ns)
        w.u64(self.ctime_ns)

    @classmethod
    def decode(cls, r: Reader) -> "TreeMetadata":
        return cls(size=r.u64(), mtime_ns=r.u64(), ctime_ns=r.u64())


@dataclass
class Tree:
    """A directory or file node blob (reference mod.rs:62-74).

    ``children`` of a DIR tree are hashes of child Tree blobs; ``children`` of
    a FILE tree are hashes of its FILE_CHUNK blobs in order.  A node with more
    than TREE_MAX_CHILDREN children is split, the continuation linked through
    ``next_sibling`` (reference dir_packer.rs:313-363).
    """

    kind: TreeKind
    name: str
    metadata: TreeMetadata
    children: list = field(default_factory=list)
    next_sibling: Optional[bytes] = None

    def encode_bytes(self) -> bytes:
        w = Writer()
        w.u32(int(self.kind))
        w.str(self.name)
        self.metadata.encode(w)
        w.u64(len(self.children))
        for c in self.children:
            w.fixed(_check("tree child hash", c, BLOB_HASH_LEN))
        w.opt_fixed(self.next_sibling, BLOB_HASH_LEN)
        return w.take()

    @classmethod
    def decode_bytes(cls, buf: bytes) -> "Tree":
        r = Reader(buf)
        kind = TreeKind(r.u32())
        name = r.str()
        metadata = TreeMetadata.decode(r)
        children = [r.fixed(BLOB_HASH_LEN) for _ in range(r.u64())]
        next_sibling = r.opt_fixed(BLOB_HASH_LEN)
        r.expect_end()
        return cls(kind=kind, name=name, metadata=metadata, children=children,
                   next_sibling=next_sibling)


# --- control-plane JSON messages (reference shared/src/client_message.rs,
#     server_message.rs, server_message_ws.rs) -------------------------------


def _hex(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else bytes(b).hex()


def _unhex(s: Optional[str], length: Optional[int], name: str) -> Optional[bytes]:
    if s is None:
        return None
    b = bytes.fromhex(s)
    return b if length is None else _check(name, b, length)


class JsonMessage:
    """Tagged-JSON base: ``{"t": <class name>, ...fields}``.

    Byte fields are declared via ``_bytes_fields = {name: length}`` (length
    ``None`` = variable) and hex-encoded on the wire.
    """

    _bytes_fields: dict = {}
    _registry: dict = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        JsonMessage._registry[cls.__name__] = cls

    def to_json(self) -> str:
        out = {"t": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name in self._bytes_fields:
                v = _hex(v)
            out[f.name] = v
        return json.dumps(out, separators=(",", ":"), sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "JsonMessage":
        obj = json.loads(s)
        tag = obj.pop("t", None)
        cls = JsonMessage._registry.get(tag)
        if cls is None:
            raise ValueError(f"unknown message tag {tag!r}")
        kw = {}
        for f in dataclasses.fields(cls):
            v = obj.get(f.name)
            if v is None:
                # Fields without a dataclass default are required: reject
                # missing/null so untrusted input can't construct half-built
                # protocol messages.
                required = (f.default is dataclasses.MISSING
                            and f.default_factory is dataclasses.MISSING)
                if required:
                    raise ValueError(f"{tag}: missing required field {f.name!r}")
                continue
            if f.name in cls._bytes_fields:
                if not isinstance(v, str):
                    raise ValueError(f"{tag}: field {f.name!r} must be a hex string")
                v = _unhex(v, cls._bytes_fields[f.name], f.name)
            kw[f.name] = v
        return cls(**kw)


# client -> server (reference shared/src/client_message.rs:9-77)

@dataclass
class ClientRegistrationRequest(JsonMessage):
    pubkey: bytes
    _bytes_fields = {"pubkey": CLIENT_ID_LEN}


@dataclass
class ClientRegistrationAuth(JsonMessage):
    pubkey: bytes
    challenge_response: bytes  # signature over the challenge nonce
    _bytes_fields = {"pubkey": CLIENT_ID_LEN, "challenge_response": None}


@dataclass
class ClientLoginRequest(JsonMessage):
    pubkey: bytes
    _bytes_fields = {"pubkey": CLIENT_ID_LEN}


@dataclass
class ClientLoginAuth(JsonMessage):
    pubkey: bytes
    challenge_response: bytes
    _bytes_fields = {"pubkey": CLIENT_ID_LEN, "challenge_response": None}


@dataclass
class BackupRequest(JsonMessage):
    # min_peers > 1 asks matchmaking to spread the grant over at least
    # that many distinct peers (erasure stripes need k+m distinct
    # holders); 1 keeps the reference's fill-greedily behavior.
    session_token: bytes
    storage_required: int
    min_peers: int = 1
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN}


@dataclass
class BeginP2PConnectionRequest(JsonMessage):
    session_token: bytes
    destination_client_id: bytes
    session_nonce: bytes
    _bytes_fields = {
        "session_token": SESSION_TOKEN_LEN,
        "destination_client_id": CLIENT_ID_LEN,
        "session_nonce": TRANSPORT_NONCE_LEN,
    }


@dataclass
class ConfirmP2PConnectionRequest(JsonMessage):
    session_token: bytes
    source_client_id: bytes
    destination_ip_address: str
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN,
                     "source_client_id": CLIENT_ID_LEN}


@dataclass
class BackupRestoreRequest(JsonMessage):
    session_token: bytes
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN}


@dataclass
class BackupDone(JsonMessage):
    session_token: bytes
    snapshot_hash: bytes
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN,
                     "snapshot_hash": BLOB_HASH_LEN}


@dataclass
class AuditReport(JsonMessage):
    """Client -> server: outcome of one storage-attestation round against
    ``peer_id`` (no reference equivalent; see docs/audit.md).  The server
    aggregates reports across verifiers to adjust matchmaking."""

    session_token: bytes
    peer_id: bytes
    passed: bool
    detail: str = ""
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN,
                     "peer_id": CLIENT_ID_LEN}


@dataclass
class RepairReport(JsonMessage):
    """Client -> server: one repair round re-replicated the placements a
    lost ``peer_id`` held for us (no reference equivalent; see
    docs/failure_model.md).  The server retires the negotiation edges so
    restore peer lists stop naming the dead peer, and records the event
    for allocation accounting."""

    session_token: bytes
    peer_id: bytes
    packfiles_lost: int
    bytes_lost: int
    bytes_replaced: int
    _bytes_fields = {"session_token": SESSION_TOKEN_LEN,
                     "peer_id": CLIENT_ID_LEN}


# server -> client HTTP responses (reference shared/src/server_message.rs:9-54)

@dataclass
class Ok(JsonMessage):
    pass


@dataclass
class ServerChallenge(JsonMessage):
    nonce: bytes
    _bytes_fields = {"nonce": CHALLENGE_NONCE_LEN}


@dataclass
class LoginToken(JsonMessage):
    token: bytes
    _bytes_fields = {"token": SESSION_TOKEN_LEN}


@dataclass
class BackupRestoreInfo(JsonMessage):
    # rs_k/rs_m advertise the erasure geometry the cluster runs (0 = the
    # server predates sharding); shard containers are self-describing, so
    # these are informational for the restoring client's planning only.
    snapshot_hash: Optional[bytes] = None
    peers: list = field(default_factory=list)  # hex client ids
    rs_k: int = 0
    rs_m: int = 0
    _bytes_fields = {"snapshot_hash": BLOB_HASH_LEN}


class ErrorKind:
    """The closed error taxonomy, mirroring the reference's 8 ``ErrorType``
    variants (shared/src/server_message.rs:43-54); payload details ride in
    :class:`Error.detail` (the reference embeds strings in three of them).
    """

    UNAUTHORIZED = "Unauthorized"
    CLIENT_NOT_FOUND = "ClientNotFound"
    DESTINATION_UNREACHABLE = "DestinationUnreachable"
    NO_BACKUPS = "NoBackups"
    RETRY = "Retry"
    BAD_REQUEST = "BadRequest"
    SERVER_ERROR = "ServerError"
    FAILURE = "Failure"

    ALL = (UNAUTHORIZED, CLIENT_NOT_FOUND, DESTINATION_UNREACHABLE,
           NO_BACKUPS, RETRY, BAD_REQUEST, SERVER_ERROR, FAILURE)


# kind -> HTTP status, per the reference's ResponseError mapping
# (server/src/handlers/mod.rs:50-91); ClientExists keeps the reference's
# 409 CONFLICT status with a BAD_REQUEST payload.
ERROR_HTTP_STATUS = {
    ErrorKind.UNAUTHORIZED: 401,
    ErrorKind.CLIENT_NOT_FOUND: 404,
    ErrorKind.DESTINATION_UNREACHABLE: 404,
    ErrorKind.NO_BACKUPS: 404,
    ErrorKind.RETRY: 404,
    ErrorKind.BAD_REQUEST: 400,
    ErrorKind.SERVER_ERROR: 500,
    ErrorKind.FAILURE: 500,
}


@dataclass
class NodeRedirect(JsonMessage):
    """server -> client, HTTP 421: this pubkey's home is another
    coordination node (federation wrong-node arrival; no reference
    equivalent — the reference has exactly one server).  ``url`` is the
    owning node's base URL; clients follow at most one redirect per
    request and only toward a URL already on their configured node
    list."""

    url: str = ""


@dataclass
class Error(JsonMessage):
    # one of ErrorKind.ALL plus a human-readable detail
    kind: str = ErrorKind.FAILURE
    detail: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ErrorKind.ALL:
            self.detail = (f"{self.kind}: {self.detail}"
                           if self.detail else self.kind)
            self.kind = ErrorKind.FAILURE


# server -> client WS push (reference shared/src/server_message_ws.rs:9-35)

@dataclass
class Ping(JsonMessage):
    pass


@dataclass
class BackupMatched(JsonMessage):
    destination_id: bytes
    storage_available: int
    _bytes_fields = {"destination_id": CLIENT_ID_LEN}


@dataclass
class IncomingP2PConnection(JsonMessage):
    source_client_id: bytes
    session_nonce: bytes
    _bytes_fields = {"source_client_id": CLIENT_ID_LEN,
                     "session_nonce": TRANSPORT_NONCE_LEN}


@dataclass
class FinalizeP2PConnection(JsonMessage):
    destination_client_id: bytes
    destination_ip_address: str
    _bytes_fields = {"destination_client_id": CLIENT_ID_LEN}


@dataclass
class AuditDue(JsonMessage):
    """Server -> client WS scheduling nudge: another verifier reported
    ``peer_id`` failing its storage audit — clients holding data there
    should audit it soon rather than waiting out their normal interval."""

    peer_id: bytes
    _bytes_fields = {"peer_id": CLIENT_ID_LEN}


# --- p2p data-plane messages (reference shared/src/p2p_message.rs) ----------

class RequestType(IntEnum):
    """p2p_message.rs:36-39 (AUDIT added for storage attestation,
    RESTORE_FETCH for shard-granular pull restore — docs/transfer.md
    restore data plane)."""

    TRANSPORT = 0
    RESTORE_ALL = 1
    AUDIT = 2
    RESTORE_FETCH = 3
    # GC's make-before-break tail: the owner asks a holder to delete
    # superseded packfiles/shards it placed there (docs/lifecycle.md)
    RECLAIM = 4


class FileInfoKind(IntEnum):
    """p2p_message.rs:51-54 (SHARD added for erasure-coded placement:
    the file_id is a 13-byte shard id and the payload a self-describing
    shard container, erasure/stripe.py)."""

    PACKFILE = 0
    INDEX = 1
    SHARD = 2


@dataclass(frozen=True)
class P2PHeader:
    """Replay-protection header (p2p_message.rs:21-24)."""

    sequence_number: int
    session_nonce: bytes

    def encode(self, w: Writer) -> None:
        w.u64(self.sequence_number)
        w.fixed(_check("session nonce", self.session_nonce, TRANSPORT_NONCE_LEN))

    @classmethod
    def decode(cls, r: Reader) -> "P2PHeader":
        return cls(sequence_number=r.u64(), session_nonce=r.fixed(TRANSPORT_NONCE_LEN))


class P2PBodyKind(IntEnum):
    REQUEST = 0
    FILE = 1
    ACK = 2
    CHALLENGE = 3  # storage-attestation challenge batch
    PROOF = 4  # storage-attestation proof batch
    # resumable chunked transfer (docs/transfer.md resume protocol).
    # FILE frames stay on the wire unchanged, so peers that only speak
    # the whole-file path keep interoperating; the three kinds below are
    # additive.
    FILE_PART = 5  # one byte range of a file, acked like FILE
    RESUME_QUERY = 6  # sender asks: how much of file_id do you hold?
    RESUME_OFFER = 7  # receiver's answer, echoing the query's sequence
    # shard-granular pull restore (docs/transfer.md restore data plane).
    # Additive like the resume trio: only sent on RESTORE_FETCH sessions,
    # which old peers never accept, so RESTORE_ALL interop is untouched.
    FETCH_REQUEST = 8  # puller names the stored items it wants
    # GC reclaim (docs/lifecycle.md).  Additive like FETCH_REQUEST: only
    # sent on RECLAIM sessions, which old peers never accept.  The
    # request reuses the (FileInfoKind, file_id) pair shape of wants;
    # the ack echoes the request's sequence number (the CHALLENGE/PROOF
    # correlation idiom) and reports bytes actually freed in ``offset``.
    RECLAIM_REQUEST = 9
    RECLAIM_ACK = 10


class ProofStatus(IntEnum):
    """Per-window prover outcome inside a PROOF body."""

    OK = 0
    MISSING = 1  # prover no longer holds the packfile at all
    SHORT = 2  # packfile present but shorter than the challenged window


@dataclass(frozen=True)
class StorageChallenge:
    """One random-window audit challenge: prove possession of
    ``packfile_id[offset:offset+length]`` by returning
    blake3(nonce || window-bytes).  The id names a whole packfile
    (12 bytes) or a single erasure shard (13 bytes), so the id is
    length-prefixed on the wire."""

    packfile_id: bytes
    offset: int
    length: int
    nonce: bytes

    def __post_init__(self) -> None:
        _check_storage_id("challenge packfile id", self.packfile_id)
        _check("challenge nonce", self.nonce, AUDIT_NONCE_LEN)

    def encode(self, w: Writer) -> None:
        w.blob(self.packfile_id)
        w.u64(self.offset)
        w.u64(self.length)
        w.fixed(self.nonce)

    @classmethod
    def decode(cls, r: Reader) -> "StorageChallenge":
        return cls(packfile_id=r.blob(), offset=r.u64(),
                   length=r.u64(), nonce=r.fixed(AUDIT_NONCE_LEN))


@dataclass(frozen=True)
class StorageProof:
    """The prover's answer to one :class:`StorageChallenge` (digest is
    all-zero when status != OK)."""

    packfile_id: bytes
    status: ProofStatus
    digest: bytes = b"\x00" * BLOB_HASH_LEN

    def __post_init__(self) -> None:
        _check_storage_id("proof packfile id", self.packfile_id)
        _check("proof digest", self.digest, BLOB_HASH_LEN)

    def encode(self, w: Writer) -> None:
        w.blob(self.packfile_id)
        w.u32(int(self.status))
        w.fixed(self.digest)

    @classmethod
    def decode(cls, r: Reader) -> "StorageProof":
        return cls(packfile_id=r.blob(),
                   status=ProofStatus(r.u32()),
                   digest=r.fixed(BLOB_HASH_LEN))


@dataclass(frozen=True)
class P2PBody:
    """Union of the signed p2p body kinds (p2p_message.rs:27-61 plus the
    attestation pair): connection-init request (seq 0), file payload, ack,
    audit challenge batch, audit proof batch."""

    kind: P2PBodyKind
    header: P2PHeader
    request_type: Optional[RequestType] = None  # REQUEST
    file_info: Optional[FileInfoKind] = None  # FILE / FILE_PART / RESUME_QUERY
    file_id: bytes = b""  # FILE: packfile id or index number (LE bytes)
    data: bytes = b""  # FILE / FILE_PART payload
    acked_sequence: int = 0  # ACK
    challenges: tuple = ()  # CHALLENGE: StorageChallenge...
    proofs: tuple = ()  # PROOF: StorageProof...
    offset: int = 0  # FILE_PART: byte offset / RESUME_OFFER: verified bytes held / RECLAIM_ACK: bytes freed
    total_size: int = 0  # FILE_PART: whole-file length
    file_digest: bytes = b""  # FILE_PART / RESUME_OFFER: whole-file blake3
    prefix_digest: bytes = b""  # RESUME_OFFER: blake3 of the held prefix
    wants: tuple = ()  # FETCH_REQUEST / RECLAIM_REQUEST: (FileInfoKind, file_id) pairs

    def encode_bytes(self) -> bytes:
        w = Writer()
        w.u32(int(self.kind))
        self.header.encode(w)
        if self.kind == P2PBodyKind.REQUEST:
            w.u32(int(self.request_type))
        elif self.kind == P2PBodyKind.FILE:
            w.u32(int(self.file_info))
            w.blob(self.file_id)
            w.blob(self.data)
        elif self.kind == P2PBodyKind.ACK:
            w.u64(self.acked_sequence)
        elif self.kind == P2PBodyKind.CHALLENGE:
            w.u64(len(self.challenges))
            for c in self.challenges:
                c.encode(w)
        elif self.kind == P2PBodyKind.PROOF:
            w.u64(len(self.proofs))
            for p in self.proofs:
                p.encode(w)
        elif self.kind == P2PBodyKind.FILE_PART:
            w.u32(int(self.file_info))
            w.blob(self.file_id)
            w.u64(self.offset)
            w.u64(self.total_size)
            w.fixed(_check("file digest", self.file_digest, BLOB_HASH_LEN))
            w.blob(self.data)
        elif self.kind == P2PBodyKind.RESUME_QUERY:
            w.u32(int(self.file_info))
            w.blob(self.file_id)
        elif self.kind == P2PBodyKind.RESUME_OFFER:
            w.blob(self.file_id)
            w.u64(self.offset)
            # both digests are empty blobs when nothing is held
            w.blob(self.file_digest)
            w.blob(self.prefix_digest)
        elif self.kind in (P2PBodyKind.FETCH_REQUEST,
                           P2PBodyKind.RECLAIM_REQUEST):
            w.u64(len(self.wants))
            for fi, fid in self.wants:
                w.u32(int(fi))
                w.blob(fid)
        elif self.kind == P2PBodyKind.RECLAIM_ACK:
            w.u64(self.acked_sequence)
            w.u64(self.offset)  # bytes freed
        return w.take()

    @classmethod
    def decode_bytes(cls, buf: bytes) -> "P2PBody":
        r = Reader(buf)
        kind = P2PBodyKind(r.u32())
        header = P2PHeader.decode(r)
        kw = {}
        if kind == P2PBodyKind.REQUEST:
            kw["request_type"] = RequestType(r.u32())
        elif kind == P2PBodyKind.FILE:
            kw["file_info"] = FileInfoKind(r.u32())
            kw["file_id"] = r.blob()
            kw["data"] = r.blob()
        elif kind == P2PBodyKind.ACK:
            kw["acked_sequence"] = r.u64()
        elif kind == P2PBodyKind.CHALLENGE:
            kw["challenges"] = tuple(
                StorageChallenge.decode(r) for _ in range(r.u64()))
        elif kind == P2PBodyKind.PROOF:
            kw["proofs"] = tuple(
                StorageProof.decode(r) for _ in range(r.u64()))
        elif kind == P2PBodyKind.FILE_PART:
            kw["file_info"] = FileInfoKind(r.u32())
            kw["file_id"] = r.blob()
            kw["offset"] = r.u64()
            kw["total_size"] = r.u64()
            kw["file_digest"] = r.fixed(BLOB_HASH_LEN)
            kw["data"] = r.blob()
        elif kind == P2PBodyKind.RESUME_QUERY:
            kw["file_info"] = FileInfoKind(r.u32())
            kw["file_id"] = r.blob()
        elif kind == P2PBodyKind.RESUME_OFFER:
            kw["file_id"] = r.blob()
            kw["offset"] = r.u64()
            kw["file_digest"] = r.blob()
            kw["prefix_digest"] = r.blob()
        elif kind in (P2PBodyKind.FETCH_REQUEST,
                      P2PBodyKind.RECLAIM_REQUEST):
            kw["wants"] = tuple(
                (FileInfoKind(r.u32()), r.blob()) for _ in range(r.u64()))
        elif kind == P2PBodyKind.RECLAIM_ACK:
            kw["acked_sequence"] = r.u64()
            kw["offset"] = r.u64()
        r.expect_end()
        return cls(kind=kind, header=header, **kw)


@dataclass(frozen=True)
class EncapsulatedMsg:
    """Signed envelope for every p2p message (p2p_message.rs:12-17).

    ``trace_id`` is an optional trailing frame carrying the sender's
    observability trace id (obs/trace.py).  It sits OUTSIDE the signed
    body on purpose: it is advisory correlation metadata, never input to
    any decision, so it needs no authentication — and old peers that
    stop reading after the signature still interoperate (the field is
    only decoded when bytes remain)."""

    body: bytes  # encoded P2PBody
    signature: bytes  # Ed25519 signature over body
    trace_id: Optional[str] = None  # unauthenticated, advisory

    def encode_bytes(self) -> bytes:
        w = Writer()
        w.blob(self.body)
        w.blob(self.signature)
        if self.trace_id:
            w.str(self.trace_id)
        return w.take()

    @classmethod
    def decode_bytes(cls, buf: bytes) -> "EncapsulatedMsg":
        r = Reader(buf)
        body = r.blob()
        sig = r.blob()
        trace_id = None
        if r.remaining():
            tid = r.str()
            if len(tid) <= 32 and all(c in "0123456789abcdef" for c in tid):
                trace_id = tid or None
        r.expect_end()
        return cls(body=body, signature=sig, trace_id=trace_id)
