"""CPU oracle for windowed Gear CDC (normative semantics in CDC_SPEC.md).

Replaces the reference's FastCDC hot loop (``dir_packer.rs:246-266``) with the
two-stage decomposition: per-position candidate discovery (vectorizable, the
TPU target) + sparse sequential cut selection (host).  The scalar
:func:`gear_hashes_scalar` path is the readability oracle; the numpy path is
bit-identical and fast enough for tests and mid-size corpora.
"""

from __future__ import annotations

import numpy as np

from .gear import GEAR, GEAR_WINDOW, CDCParams


def gear_hashes_scalar(data: bytes) -> np.ndarray:
    """h[i] = (h[i-1] << 1) + GEAR[b[i]] mod 2^32 — definitional loop."""
    out = np.empty(len(data), dtype=np.uint32)
    h = 0
    for i, b in enumerate(data):
        h = ((h << 1) + int(GEAR[b])) & 0xFFFFFFFF
        out[i] = h
    return out


def gear_hashes(data, prev_tail: bytes = b"") -> np.ndarray:
    """Vectorized per-position hashes.

    ``prev_tail`` supplies up to GEAR_WINDOW-1 bytes of left context (the halo
    when a long stream is processed block-wise); hashes are returned only for
    ``data`` positions, identical to hashing the concatenation.
    """
    tail = bytes(prev_tail)[-(GEAR_WINDOW - 1):] if prev_tail else b""
    buf = np.frombuffer(tail + bytes(data), dtype=np.uint8)
    g = GEAR[buf]
    n = len(buf)
    h = np.zeros(n, dtype=np.uint32)
    for k in range(GEAR_WINDOW):
        if k >= n:
            break
        # h[i] += GEAR[b[i-k]] << k
        h[k:] += g[:n - k] << np.uint32(k)
    return h[len(tail):]


def candidate_positions(data, params: CDCParams, prev_tail: bytes = b""):
    """Sorted positions where cand_s / cand_l hold (cand_s ⊆ cand_l)."""
    h = gear_hashes(data, prev_tail)
    cand_l = (h & np.uint32(params.mask_l)) == 0
    pos_l = np.nonzero(cand_l)[0]
    cand_s = (h[pos_l] & np.uint32(params.mask_s)) == 0
    pos_s = pos_l[cand_s]
    return pos_s, pos_l


def select_cuts(pos_s: np.ndarray, pos_l: np.ndarray, n: int,
                params: CDCParams) -> np.ndarray:
    """Resolve chunk end positions from candidate sets (CDC_SPEC.md rules).

    Returns the array of inclusive end positions; chunks are
    ``[0..e0], [e0+1..e1], ...`` and always end with ``n-1`` for n > 0.
    """
    if n == 0:
        return np.empty(0, dtype=np.int64)
    pos_s = np.asarray(pos_s, dtype=np.int64)
    pos_l = np.asarray(pos_l, dtype=np.int64)
    cuts = []
    s = 0
    while True:
        if n - s <= params.min_size:
            cuts.append(n - 1)
            break
        e = None
        # window 1: length in [min, desired) with the strict mask
        lo = s + params.min_size - 1
        hi = min(s + params.desired_size - 2, n - 2)  # e == n-1 is EOF anyway
        i = np.searchsorted(pos_s, lo, side="left")
        if i < len(pos_s) and pos_s[i] <= hi:
            e = int(pos_s[i])
        if e is None:
            # window 2: length in [desired, max) with the loose mask
            lo2 = s + params.desired_size - 1
            hi2 = min(s + params.max_size - 2, n - 2)
            j = np.searchsorted(pos_l, lo2, side="left")
            if j < len(pos_l) and pos_l[j] <= hi2:
                e = int(pos_l[j])
        if e is None:
            # forced cut at max, or EOF
            e = min(s + params.max_size - 1, n - 1)
        cuts.append(e)
        if e == n - 1:
            break
        s = e + 1
    return np.array(cuts, dtype=np.int64)


def cuts_to_chunks(ends) -> list:
    """Inclusive end positions -> [(offset, length), ...]."""
    out, s = [], 0
    for e in ends:
        out.append((s, int(e) - s + 1))
        s = int(e) + 1
    return out


def chunk_stream(data, params: CDCParams = CDCParams()):
    """Chunk one stream; returns list of (offset, length)."""
    n = len(data)
    pos_s, pos_l = candidate_positions(data, params)
    return cuts_to_chunks(select_cuts(pos_s, pos_l, n, params))


def chunk_stream_scalar(data, params: CDCParams = CDCParams()):
    """Definitional single loop over bytes — the ultimate oracle.

    O(n) python; use only on small inputs in tests.
    """
    n = len(data)
    out = []
    s = 0
    h = 0
    for i in range(n):
        h = ((h << 1) + int(GEAR[data[i]])) & 0xFFFFFFFF
        length = i - s + 1
        cut = False
        if i == n - 1:
            cut = True
        elif length >= params.min_size:
            if length < params.desired_size:
                cut = (h & params.mask_s) == 0
            elif length < params.max_size:
                cut = (h & params.mask_l) == 0
            else:
                cut = True
        if cut:
            out.append((s, length))
            s = i + 1
    return out
