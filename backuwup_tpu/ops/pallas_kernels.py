"""Pallas/Mosaic TPU kernels for the dedup pipeline hot ops.

The XLA formulation of the gear-table lookup materializes a (N, 256)
one-hot operand through HBM (~512 bytes of traffic per stream byte); here
the one-hot never leaves VMEM — each grid program stages 32 KiB of bytes,
expands+contracts them against the 256x4 limb table on the MXU in 8 KiB
sub-blocks, and writes only the 4-byte gear value per byte back to HBM.

Kernels gate themselves on the runtime platform: on non-TPU backends the
callers fall back to the pure-XLA paths (bit-identical by construction;
asserted by tests/test_pallas.py on the TPU rig).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .gear import GEAR

# bytes handled per grid program / per MXU sub-block
_TILE_BYTES = 32768
_SUB_BYTES = 8192
_LANES = 128
_TILE_ROWS = _TILE_BYTES // _LANES

_GEAR_LIMBS_F32 = np.stack(
    [(GEAR >> (8 * j)) & 0xFF for j in range(4)], axis=1).astype(np.float32)


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """True when the Pallas TPU lowering is usable on this runtime."""
    if os.environ.get("BKW_PALLAS", "1") == "0":
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        return False
    if platform not in ("tpu", "axon"):
        return False
    try:
        probe = jnp.zeros(_TILE_BYTES, dtype=jnp.uint8)
        out = gear_values_pallas(probe)
        return int(np.asarray(out[0])) == int(GEAR[0])
    except Exception:  # pragma: no cover - lowering failure on exotic rigs
        return False


def _gear_kernel(b_ref, tab_ref, g_ref):
    """One grid program: (TILE_ROWS, 128) u8 -> (TILE_ROWS, 128) u32."""
    sub_rows = _SUB_BYTES // _LANES

    def body(i, carry):
        blk = b_ref[pl.ds(i * sub_rows, sub_rows), :].astype(jnp.int32)
        # rank-3 one-hot stays in VMEM; contraction on the MXU.  No
        # reshapes: Mosaic cannot relayout (rows,128)->(8192,1)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (sub_rows, _LANES, 256), 2)
        oh = (blk[:, :, None] == cols).astype(jnp.bfloat16)
        limbs = jax.lax.dot_general(
            oh, tab_ref[:].astype(jnp.bfloat16),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (sub_rows, 128, 4)
        # Mosaic lacks f32->u32 casts: go through i32 (limbs are 0..255 so
        # the cast is exact; the <<24 wrap is the bit pattern we want) and
        # bitcast to u32 at the store
        l_ = limbs.astype(jnp.int32)
        g = (l_[..., 0] | (l_[..., 1] << 8)
             | (l_[..., 2] << 16) | (l_[..., 3] << 24))
        g_ref[pl.ds(i * sub_rows, sub_rows), :] = pltpu.bitcast(
            g, jnp.uint32)
        return carry

    jax.lax.fori_loop(0, _TILE_ROWS // sub_rows, body, 0)


try:  # pallas imports lazily guarded: CPU-only test runs never need them
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


@jax.jit
def gear_values_pallas(b: jnp.ndarray) -> jnp.ndarray:
    """GEAR[b] for a u8 vector via the VMEM-resident one-hot matmul.

    Accepts any length; internally pads to the tile size and slices back.
    """
    n = b.shape[0]
    padded = -(-max(n, 1) // _TILE_BYTES) * _TILE_BYTES
    if padded != n:
        b = jnp.concatenate([b, jnp.zeros(padded - n, dtype=jnp.uint8)])
    rows = padded // _LANES
    b2 = b.reshape(rows, _LANES)
    tab = jnp.asarray(_GEAR_LIMBS_F32)
    grid = rows // _TILE_ROWS
    g2 = pl.pallas_call(
        _gear_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((256, 4), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(b2, tab)
    return g2.reshape(padded)[:n]
