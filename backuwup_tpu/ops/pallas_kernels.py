"""Pallas/Mosaic TPU kernels for the dedup pipeline hot ops.

The XLA formulation of the gear-table lookup materializes a (N, 256)
one-hot operand through HBM (~512 bytes of traffic per stream byte); here
the one-hot never leaves VMEM — each grid program stages 32 KiB of bytes,
expands+contracts them against the 256x4 limb table on the MXU in 8 KiB
sub-blocks, and writes only the 4-byte gear value per byte back to HBM.

STATUS: EXPERIMENTAL / not wired into the production pipeline.  The
measured round-3 variants here lose to the XLA path (per-limb matvecs
cost ~1M tiny MXU launches, ~315 ms/128 MiB vs ~110 ms for XLA's fused
nibble-bilinear form — PERF.md "dead ends").  They are kept as working,
parity-tested reference points for Mosaic layout experiments
(tests/test_pallas.py runs them on the TPU rig only); the production
scan path lives in cdc_tpu.py.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .gear import GEAR

# bytes handled per grid program / per MXU sub-block
_TILE_BYTES = 32768
_SUB_BYTES = 8192
_LANES = 128

# (256, 128) staging shape: limb j in column j, zeros elsewhere.  The
# kernel only ever contracts one COLUMN at a time as a (1, 256) vector
# rhs — never the full matrix: a multi-column batched-dot rhs silently
# corrupts output columns on this Mosaic version (PERF.md).  The wide
# shape exists purely so the table tiles cleanly into VMEM.
_GEAR_LIMBS_F32 = np.zeros((256, 128), dtype=np.float32)
for _j in range(4):
    _GEAR_LIMBS_F32[:, _j] = (GEAR >> (8 * _j)) & 0xFF


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """True when the Pallas TPU lowering is usable on this runtime."""
    if os.environ.get("BKW_PALLAS", "1") == "0":
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        return False
    if platform not in ("tpu", "axon"):
        return False
    try:
        probe = jnp.zeros(_TILE_BYTES, dtype=jnp.uint8)
        out = gear_values_pallas(probe)
        return int(np.asarray(out[0])) == int(GEAR[0])
    except Exception:  # pragma: no cover - lowering failure on exotic rigs
        return False


def _gear_kernel(b_ref, tab_ref, g_ref):
    """One grid program: (TILE_ROWS, 128) u8 -> (TILE_ROWS, 128) u32.

    Rank-3 one-hot in VMEM contracted per 8-bit limb with a VECTOR rhs —
    the only batched-dot form this Mosaic version lowers correctly (a
    multi-column rhs silently corrupts output columns; see PERF.md).
    Mosaic also lacks f32->u32 casts: limbs go through i32 (values 0..255,
    so the cast is exact and the <<24 wrap is the bit pattern we want) and
    bitcast at the store.
    """
    sub_rows = _SUB_BYTES // _LANES

    def body(i, carry):
        blk = b_ref[pl.ds(i * sub_rows, sub_rows), :].astype(jnp.int32)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (sub_rows, _LANES, 256), 2)
        oh = (blk[:, :, None] == cols).astype(jnp.bfloat16)
        g = None
        for j in range(4):
            lj = jax.lax.dot_general(
                oh, tab_ref[:, j].astype(jnp.bfloat16)[None, :],
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)[..., 0].astype(jnp.int32)
            g = lj if g is None else g | (lj << (8 * j))
        g_ref[pl.ds(i * sub_rows, sub_rows), :] = pltpu.bitcast(
            g, jnp.uint32)
        return carry

    jax.lax.fori_loop(0, _TILE_BYTES // _SUB_BYTES, body, 0)


try:  # pallas imports lazily guarded: CPU-only test runs never need them
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


_LADDER_ROWS = 512  # 64 Ki elements (256 KiB u32) per grid program


def _shift_flat(a, s: int):
    """Row-major shift of a (R,128) u32 tile by ``s`` elements, zero-fill
    from the left edge: y[r,l] = a[r,l-s] (l>=s) else a[r-1,128+l-s].

    Mosaic has no flattened-shift primitive; built from a one-row sublane
    shift plus a lane-dimension concatenate of the wrapped columns.
    """
    am1 = jnp.concatenate([jnp.zeros_like(a[:1]), a[:-1]], axis=0)
    return jnp.concatenate([am1[:, _LANES - s:], a[:, :_LANES - s]], axis=1)


def _make_ladder_cand_kernel(mask_s: int, mask_l: int):
    def kernel(nv_ref, g_ref, gprev_ref, cl_ref, cs_ref):
        """(R,128) gear values (+8-row left halo block) -> candidate bytes.

        The five doubling passes of the 32-tap windowed sum run entirely
        in VMEM over the halo-extended tile: position p needs g back to
        p-31, and the prepended halo row supplies 128 left elements, so
        every tile row is exact; the halo row's own left truncation is
        discarded with it.  Output is one u8 (0/1) per position for each
        mask — 1/4 the write traffic of materializing hashes, in the same
        (R,128) layout as the input (no relayouts, which Mosaic forbids
        for sub-32-bit types).
        """
        i = pl.program_id(0)
        halo = jnp.where(i > 0, gprev_ref[7:8, :],
                         jnp.zeros_like(gprev_ref[7:8, :]))
        a = jnp.concatenate([halo, g_ref[:]], axis=0)  # (R+1, 128)
        for t in range(5):
            s = 1 << t
            a = a + (_shift_flat(a, s) << jnp.uint32(s))
        h = a[1:]
        R = h.shape[0]
        base = i * (R * 128)
        pos = base + (jax.lax.broadcasted_iota(jnp.int32, h.shape, 0) * 128
                      + jax.lax.broadcasted_iota(jnp.int32, h.shape, 1))
        valid = pos < nv_ref[0]
        cand_l = ((h & jnp.uint32(mask_l)) == jnp.uint32(0)) & valid
        cand_s = cand_l & ((h & jnp.uint32(mask_s)) == jnp.uint32(0))
        cl_ref[:] = cand_l.astype(jnp.uint8)
        cs_ref[:] = cand_s.astype(jnp.uint8)

    return kernel


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l"))
def ladder_candidates_pallas(g: jnp.ndarray, n_valid, *,
                             mask_s: int, mask_l: int):
    """Gear values (flat u32, length multiple of LADDER block) ->
    (cand_l, cand_s) u8 arrays of the same length.

    ``n_valid`` bounds the valid positions (padding precedes/follows the
    real stream); callers account for any leading offset themselves.
    """
    n = g.shape[0]
    block = _LADDER_ROWS * _LANES
    assert n % block == 0, "caller pads to the ladder block size"
    rows = n // _LANES
    g2 = g.reshape(rows, _LANES)
    nv = jnp.full((1,), n_valid, dtype=jnp.int32)
    grid = rows // _LADDER_ROWS
    kernel = _make_ladder_cand_kernel(mask_s, mask_l)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_LADDER_ROWS, _LANES), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
            # 8-row halo block ending at the tile's first row; clamped at
            # the left edge (tile 0 zeroes it in-kernel)
            pl.BlockSpec((8, _LANES),
                         lambda i, *_: (jnp.maximum(
                             i * (_LADDER_ROWS // 8) - 1, 0), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_LADDER_ROWS, _LANES), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_LADDER_ROWS, _LANES), lambda i, *_: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    cl, cs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.uint8),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.uint8)],
        grid_spec=grid_spec,
    )(nv, g2, g2)
    return cl.reshape(n), cs.reshape(n)


@jax.jit
def gear_values_pallas(b: jnp.ndarray) -> jnp.ndarray:
    """GEAR[b] for a u8 vector via the VMEM-resident one-hot matmul.

    Accepts any length; internally pads to the tile size and slices back.
    """
    n = b.shape[0]
    padded = -(-max(n, 1) // _TILE_BYTES) * _TILE_BYTES
    if padded != n:
        b = jnp.concatenate([b, jnp.zeros(padded - n, dtype=jnp.uint8)])
    rows = padded // _LANES
    tile_rows = _TILE_BYTES // _LANES
    b2 = b.reshape(rows, _LANES)
    tab = jnp.asarray(_GEAR_LIMBS_F32)
    g2 = pl.pallas_call(
        _gear_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
        grid=(rows // tile_rows,),
        in_specs=[
            pl.BlockSpec((tile_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((256, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_rows, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(b2, tab)
    return g2.reshape(padded)[:n]
