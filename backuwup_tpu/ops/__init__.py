"""Compute kernels: content-defined chunking + BLAKE3 fingerprinting.

CPU oracle implementations (:mod:`.blake3_cpu`, :mod:`.cdc_cpu`) define the
bit-exact semantics; TPU implementations (:mod:`.blake3_tpu`, :mod:`.cdc_tpu`)
must match them exactly — dedup-ratio parity is the judged metric
(BASELINE.md).  The backend seam is :mod:`.backend`.
"""
