"""Device-resident dedup pipeline: scan -> cut -> gather chunks -> digest.

Composes the TPU kernels into the full chunk+hash step that ``bench.py``
times and ``__graft_entry__.py`` exposes to the driver:

1. gear-hash scan of a resident byte segment (:mod:`.cdc_tpu`),
2. host cut selection over the sparse candidate words (tiny transfer),
3. on-device gather of the variable-length chunks into a padded
   ``(B, L*1024)`` batch (``vmap`` of ``dynamic_slice`` — bytes move
   HBM->HBM, never through the host),
4. batched BLAKE3 digests (:mod:`.blake3_tpu`).

The reference executes the same logical pipeline one byte / one chunk at a
time on the CPU (``dir_packer.rs:246-311``).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import defaults
from .blake3_tpu import digest_padded
from .cdc_cpu import cuts_to_chunks, select_cuts
from .cdc_tpu import _HALO, TpuCdcScanner, _decode_words, _scan_segment
from .gear import CDCParams

CHUNK_LEN = 1024


@functools.partial(jax.jit, static_argnames=("l_bucket",))
def gather_chunks(stream: jnp.ndarray, offsets: jnp.ndarray,
                  *, l_bucket: int) -> jnp.ndarray:
    """(B,) chunk offsets -> (B, l_bucket*1024) u8 padded chunk buffers.

    Chunks are sliced from the resident stream; callers mask true lengths
    via the ``lens`` argument of :func:`digest_padded`, so over-read bytes
    beyond each chunk are ignored by the masked BLAKE3 scan.
    """
    span = l_bucket * CHUNK_LEN

    def one(off):
        return jax.lax.dynamic_slice(stream, (off,), (span,))

    return jax.vmap(one)(offsets.astype(jnp.int32))


class DevicePipeline:
    """Chunk + fingerprint segments that already live (or land) in HBM."""

    def __init__(self, params: Optional[CDCParams] = None,
                 l_bucket: int = 3072, b_bucket: int = 128):
        self.params = params or CDCParams()
        self.scanner = TpuCdcScanner(self.params)
        if self.params.max_size > l_bucket * CHUNK_LEN:
            raise ValueError("l_bucket smaller than max chunk size")
        self.l_bucket = l_bucket
        self.b_bucket = b_bucket

    def process_segment(self, stream: jnp.ndarray, n_valid: int,
                        prev_tail: bytes = b"") -> Tuple[List[tuple], np.ndarray]:
        """One resident segment -> (chunks [(offset, length)...], digests).

        ``stream`` must be a device u8 array of length >= n_valid + slack
        for the final gather (padding bytes are masked out of digests).
        ``prev_tail`` is ignored for cut semantics here: segments fed to the
        bench are independent streams.
        """
        p = self.params
        ext = jnp.concatenate(
            [jnp.zeros(_HALO, dtype=jnp.uint8), stream])
        k_cap = self.scanner._k_cap(int(stream.shape[0]))
        widx, wl, ws, nz = _scan_segment(
            ext, jnp.int32(n_valid), jnp.uint32(p.mask_s),
            jnp.uint32(p.mask_l), k_cap=k_cap)
        if int(nz) > k_cap:
            raise RuntimeError("candidate overflow in bench pipeline")
        pos_l, is_s = _decode_words(widx, wl, ws, k_cap, 0)
        chunks = cuts_to_chunks(
            select_cuts(pos_l[is_s], pos_l, n_valid, p))
        digests = self.digest_chunks(stream, chunks)
        return chunks, digests

    def _chunk_bucket(self, n_bytes: int) -> int:
        """Smallest leaf bucket (power of two, >=16 chunks) holding a chunk;
        bounds padding waste to <2x instead of all-chunks-at-max."""
        need = max(1, -(-n_bytes // CHUNK_LEN))
        b = 16
        while b < need:
            b *= 2
        return min(b, self.l_bucket) if need <= self.l_bucket else need

    def digest_chunks(self, stream: jnp.ndarray, chunks: List[tuple]) -> np.ndarray:
        """Gather + digest chunk spans of a resident stream; (N, 32) u8.

        Chunks group into (B, L) size buckets so device work scales with
        actual bytes, not worst-case chunk size.
        """
        if not chunks:
            return np.zeros((0, 32), dtype=np.uint8)
        # slack so the fixed-span gathers never clamp (dynamic_slice clips
        # out-of-range starts, which would shift data)
        stream = jnp.pad(stream, (0, self.l_bucket * CHUNK_LEN))
        out = np.zeros((len(chunks), 32), dtype=np.uint8)
        groups: dict = {}
        for i, (off, ln) in enumerate(chunks):
            groups.setdefault(self._chunk_bucket(ln), []).append(i)
        for L, idxs in sorted(groups.items()):
            for s in range(0, len(idxs), self.b_bucket):
                part = idxs[s:s + self.b_bucket]
                bb = 8
                while bb < len(part):
                    bb *= 2
                bb = min(bb, self.b_bucket)
                offs = np.zeros(bb, dtype=np.int32)
                lens = np.zeros(bb, dtype=np.int32)
                for j, i in enumerate(part):
                    offs[j], lens[j] = chunks[i]
                buf = gather_chunks(stream, jnp.asarray(offs), l_bucket=L)
                root = digest_padded(buf.reshape(bb, L * CHUNK_LEN),
                                     jnp.asarray(lens), L=L)
                got = np.ascontiguousarray(np.asarray(root).astype("<u4"))
                got = got.view(np.uint8).reshape(bb, 32)
                for j, i in enumerate(part):
                    out[i] = got[j]
        return out
