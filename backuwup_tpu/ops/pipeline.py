"""Device-resident dedup pipeline: scan+select -> gather chunks -> digest.

Composes the TPU kernels into the full chunk+hash step that ``bench.py``
times and ``__graft_entry__.py`` exposes to the driver:

1. fused gear-hash scan + on-device FastCDC cut selection of a resident
   byte batch (:func:`..ops.cdc_tpu.scan_select_batch`) — ONE dispatch,
   and the only mid-pipeline download is the tiny packed cut list,
2. on-device gather of the variable-length chunks into a small fixed set
   of padded ``(B, L*1024)`` tiles (``vmap`` of ``dynamic_slice`` — bytes
   move HBM->HBM, never through the host),
3. batched BLAKE3 digests (:mod:`.blake3_tpu`).

Tile shapes are restricted to B in {8, 32, 128} and pow2 leaf buckets so
the whole pipeline compiles a small closed set of programs (first-run cost,
then the persistent cache) — data-dependent shapes were the round-2
throughput killer: every novel (B, L) combo paid a 20-40 s XLA compile.

Dispatch and collect halves are separate methods so
:meth:`DevicePipeline.manifest_segments` can software-pipeline several
segments: segment i+1's scan runs on device while segment i's cuts download
(async) and its digest tiles are assembled on host.

The reference executes the same logical pipeline one byte / one chunk at a
time on the CPU (``dir_packer.rs:246-311``).
"""

from __future__ import annotations

import functools
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import profile as obs_profile
from ..utils import tracing
from .blake3_tpu import blake3_many_tpu, digest_padded
from .cdc_cpu import chunk_stream as chunk_stream_cpu
from .cdc_tpu import (
    _HALO,
    TpuCdcScanner,
    _round_up,
    _segment_bucket,
    scan_select_batch,
)
from .gear import CDCParams

CHUNK_LEN = 1024

# cap on one vmapped-scan dispatch (rows x row bytes)
_SCAN_DISPATCH_BYTES = 128 * 1024 * 1024


def _pad_to(arr: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


def _blake3_host(data: bytes) -> bytes:
    from .blake3_cpu import blake3_hash
    return blake3_hash(data)


def _decode_cut_row(row: np.ndarray):
    """One packed scan+select row -> (overflow, [(offset, length)...]).

    Shared by every collector so the cut decode exists exactly once.
    Vectorized: the python per-chunk loop dominated many-small-file
    batches.
    """
    overflow, n_cuts = int(row[0]), int(row[1])
    if overflow:
        return True, []
    ends = row[2:2 + n_cuts].astype(np.int64)
    offs = np.empty(n_cuts, dtype=np.int64)
    if n_cuts:
        offs[0] = 0
        np.add(ends[:-1], 1, out=offs[1:])
    lens = ends - offs + 1
    return False, list(zip(offs.tolist(), lens.tolist()))


def _async_to_host(arr) -> None:
    """Start a device->host copy in the background when the runtime
    supports it; ``np.asarray`` later completes (or performs) it."""
    try:
        arr.copy_to_host_async()
    except AttributeError:
        pass


def _row_tiles(count: int, cap: int = 128) -> List[int]:
    """Decompose a chunk count into digest tile heights from
    {512, 128, 32, 8} clamped to ``cap`` (the pipeline's ``b_bucket``).

    Big tiles amortize the per-op overhead of the unrolled BLAKE3 program
    (small-lane dispatches are latency-bound); the closed set keeps the
    compiled-program universe finite.  Padding waste is bounded: at most
    one partially-filled tile per size class.  The 512 tier only engages
    when the pipeline raises ``b_bucket`` (small-chunk configs whose
    (B=128, L<=256) tiles are tiny-lane and dispatch-bound).
    """
    out: List[int] = []
    rem = count
    if cap >= 512:
        while rem >= 512:
            out.append(512)
            rem -= 512
        if rem >= 256:
            out.append(512)
            rem = 0
    if cap >= 128:
        while rem >= 128:
            out.append(128)
            rem -= 128
        if rem >= 64:
            out.append(128)
            rem = 0
    if cap >= 32:
        while rem >= 32:
            out.append(32)
            rem -= 32
        if rem >= 16:
            out.append(32)
            rem = 0
    while rem > 0:
        out.append(8)
        rem -= 8
    return out


@functools.partial(jax.jit, static_argnames=("B", "L"),
                   donate_argnames=("acc",))
def _gather_digest(flat: jnp.ndarray, meta: jnp.ndarray, start: jnp.ndarray,
                   acc: jnp.ndarray, *, B: int, L: int) -> jnp.ndarray:
    """Fused HBM gather + batched BLAKE3 for one (B, L) chunk tile.

    ``meta`` is the (3, total) i32 array of [offsets; lengths; starts]
    covering every tile of the batch — uploaded once; each tile call
    slices its ``[start, start+B)`` window on device (``start`` is traced,
    so varying tile layouts never recompile — only (B, L) combinations
    do), gathers the chunk spans out of the resident ``flat`` stream,
    digests, and writes the root chaining values into the donated ``acc``
    at the same window.  One fixed-shape ``acc`` download then returns
    every tile's digests — no variable-shape concatenation, no per-tile
    transfers.
    """
    offs = jax.lax.dynamic_slice(meta[0], (start,), (B,))
    lens = jax.lax.dynamic_slice(meta[1], (start,), (B,))
    span = L * CHUNK_LEN

    def one(off):
        return jax.lax.dynamic_slice(flat, (off,), (span,))

    buf = jax.vmap(one)(offs)
    root = digest_padded(buf, lens, L=L)
    return jax.lax.dynamic_update_slice(acc, root, (start, jnp.int32(0)))


@functools.partial(jax.jit, static_argnames=("l_bucket",))
def gather_chunks(stream: jnp.ndarray, offsets: jnp.ndarray,
                  *, l_bucket: int) -> jnp.ndarray:
    """(B,) chunk offsets -> (B, l_bucket*1024) u8 padded chunk buffers.

    Chunks are sliced from the resident stream; callers mask true lengths
    via the ``lens`` argument of :func:`digest_padded`, so over-read bytes
    beyond each chunk are ignored by the masked BLAKE3 scan.
    """
    span = l_bucket * CHUNK_LEN

    def one(off):
        return jax.lax.dynamic_slice(stream, (off,), (span,))

    return jax.vmap(one)(offsets.astype(jnp.int32))


class DevicePipeline:
    """Chunk + fingerprint segments that already live (or land) in HBM."""

    def __init__(self, params: Optional[CDCParams] = None,
                 l_bucket: int = 3072, b_bucket: int = 128,
                 mesh=None, mesh_axis: str = "data"):
        self.params = params or CDCParams()
        self.scanner = TpuCdcScanner(self.params)
        if self.params.max_size > l_bucket * CHUNK_LEN:
            raise ValueError("l_bucket smaller than max chunk size")
        self.l_bucket = l_bucket
        self.b_bucket = b_bucket
        # mesh for the shard-mapped driver (manifest_segments_mesh);
        # lazily defaults to a single axis over every local device
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        # per-device peak bytes in flight across the mesh dispatch window
        self.mesh_hbm_high_water: dict = {}
        self._nv_cache: OrderedDict = OrderedDict()
        from .blake3_tpu import pallas_digest_available
        from .digest_pool import pool_digest_available
        from .scan_fused import fused_scan_available
        self.fused = fused_scan_available()
        self.pallas_digest = pallas_digest_available()
        # leaf-pool digest stage: one flat leaf scan + tiny tree tiles
        # instead of ~12 per-class pipelines; parity-gated on the live
        # runtime, class tiles remain the fallback
        self.pool_digest = pool_digest_available(self.pallas_digest)

    # --- scan + select (device) -------------------------------------------

    def _caps(self, padded: int) -> Tuple[int, int, int]:
        """(s_cap, l_cap, cut_cap) for a padded row length.

        Candidate capacity is 4x the expectation: every gather/search in
        the parallel cut selection scales with ``l_cap``, and 16x slack
        measured ~3x slower end-to-end.  Density is binomial
        (sigma/mu ~= 1/sqrt(mu)), so 4x overflows only on adversarial
        gear-aligned data — which already needs the oracle fallback.
        """
        p = self.params
        l_cap = max(512, _round_up(4 * max(1, padded >> p.mask_l_bits), 512))
        cut_cap = padded // p.min_size + 1
        return l_cap, l_cap, cut_cap

    def _nv_device(self, nv: np.ndarray) -> jnp.ndarray:
        nv = np.asarray(nv, dtype=np.int32)
        key = nv.tobytes()
        nv_d = self._nv_cache.get(key)
        if nv_d is None:
            # LRU: evict the coldest entry; the old wholesale clear()
            # dropped hot entries (e.g. the full-batch nv that recurs on
            # every steady-state dispatch) on every 65th distinct shape
            while len(self._nv_cache) >= 64:
                self._nv_cache.popitem(last=False)
            nv_d = self._nv_cache[key] = jnp.asarray(nv)
        else:
            self._nv_cache.move_to_end(key)
        return nv_d

    def scan_select_dispatch(self, buf_d: jnp.ndarray,
                             nv: np.ndarray) -> jnp.ndarray:
        """Dispatch the fused scan+select; returns the device packed-cuts
        array and starts its async download."""
        p = self.params
        padded = int(buf_d.shape[1]) - _HALO
        s_cap, l_cap, cut_cap = self._caps(padded)
        with tracing.span("pipeline.scan_select_dispatch"):
            packed_d = scan_select_batch(
                buf_d, self._nv_device(nv),
                min_size=p.min_size, desired_size=p.desired_size,
                max_size=p.max_size, mask_s=p.mask_s, mask_l=p.mask_l,
                s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=self.fused)
        _async_to_host(packed_d)
        actual = int(np.asarray(nv, dtype=np.int64).sum())
        padded_total = int(buf_d.shape[0]) * padded
        obs_profile.dispatch("scan", actual_bytes=actual,
                             padded_bytes=padded_total)
        obs_profile.dispatch("select", actual_bytes=actual,
                             padded_bytes=padded_total)
        return packed_d

    def scan_select_collect(self, packed_d: jnp.ndarray, buf_d: jnp.ndarray,
                            nv: np.ndarray,
                            strict_overflow: bool = False) -> List[List[tuple]]:
        """Packed device cuts -> per-row [(offset, length)...] chunk lists.

        Overflowed rows (sparse capacity exceeded — adversarial data) are
        re-chunked with the CPU oracle to stay bit-identical, unless
        ``strict_overflow`` (benchmarks must never silently time the
        oracle)."""
        with tracing.span("pipeline.cut_collect"):
            packed = np.asarray(packed_d)
        nv = np.asarray(nv, dtype=np.int32)
        per_row: List[List[tuple]] = []
        for r in range(packed.shape[0]):
            overflow, chunks = _decode_cut_row(packed[r])
            if overflow:
                if strict_overflow:
                    raise RuntimeError("candidate overflow in scan+select")
                row_bytes = bytes(np.asarray(
                    buf_d[r, _HALO:_HALO + int(nv[r])]))
                per_row.append(chunk_stream_cpu(row_bytes, self.params))
            else:
                per_row.append(chunks)
        return per_row

    # --- gather + digest (device) -----------------------------------------

    def digest_dispatch(self, buf_d: jnp.ndarray,
                        per_row: List[List[tuple]]):
        """Dispatch gather+digest tiles for one resident batch; returns an
        opaque pending handle for :meth:`digest_collect`."""
        row = int(buf_d.shape[1])
        span_max = self.l_bucket * CHUNK_LEN
        flat = jnp.pad(buf_d.reshape(-1), (0, span_max))
        groups: dict = {}
        for r, chunks in enumerate(per_row):
            base = r * row + _HALO
            for ci, (off, ln) in enumerate(chunks):
                groups.setdefault(self._chunk_bucket(ln), []).append(
                    (base + off, ln, r, ci))
        if not groups:
            return None
        tiles: List[tuple] = []  # (start, Bb, Lb, [(r, ci)...])
        offs_parts: List[np.ndarray] = []
        lens_parts: List[np.ndarray] = []
        start = 0
        for Lb, items in sorted(groups.items()):
            pos = 0
            for Bb in _row_tiles(len(items), self.b_bucket):
                part = items[pos:pos + Bb]
                pos += Bb
                o = np.zeros(Bb, dtype=np.int32)
                ln_arr = np.zeros(Bb, dtype=np.int32)
                for q, (off, ln, _r, _ci) in enumerate(part):
                    o[q] = off
                    ln_arr[q] = ln
                offs_parts.append(o)
                lens_parts.append(ln_arr)
                tiles.append((start, Bb, Lb,
                              [(r, ci) for _o, _l, r, ci in part]))
                start += Bb
        # one meta upload; per-tile starts are sliced from it on device so
        # tile layout never recompiles _gather_digest, and the total is
        # padded to a power of two so neither does meta's shape
        starts = np.array([st for st, _b, _l, _t in tiles], dtype=np.int32)
        total = 256
        while total < max(start, len(starts)):
            total *= 2
        meta = jnp.asarray(np.stack([
            _pad_to(np.concatenate(offs_parts), total),
            _pad_to(np.concatenate(lens_parts), total),
            _pad_to(starts, total)]))
        acc = jnp.zeros((total, 8), dtype=jnp.uint32)
        with tracing.span("pipeline.digest_dispatch"):
            for i, (_st, Bb, Lb, _tags) in enumerate(tiles):
                acc = _gather_digest(flat, meta, meta[2, i], acc,
                                     B=Bb, L=Lb)
                tile_actual = int(lens_parts[i].sum())
                tile_padded = Bb * Lb * CHUNK_LEN
                obs_profile.dispatch("gather", actual_bytes=tile_actual,
                                     padded_bytes=tile_padded)
                obs_profile.dispatch("digest", actual_bytes=tile_actual,
                                     padded_bytes=tile_padded)
        _async_to_host(acc)
        return acc, tiles

    def digest_collect(self, pending,
                       per_row: List[List[tuple]]
                       ) -> List[Tuple[List[tuple], np.ndarray]]:
        """Pending digest handle -> per-row (chunks, digests)."""
        if pending is None:
            return [(chunks, np.zeros((0, 32), dtype=np.uint8))
                    for chunks in per_row]
        acc, tiles = pending
        with tracing.span("pipeline.digest_collect"):
            allcv = np.asarray(acc)
        dig8 = np.ascontiguousarray(allcv.astype("<u4")).view(
            np.uint8).reshape(-1, 32)
        digests_per_row = [np.zeros((len(c), 32), dtype=np.uint8)
                           for c in per_row]
        for st, _Bb, _Lb, tags in tiles:
            for q, (r, ci) in enumerate(tags):
                digests_per_row[r][ci] = dig8[st + q]
        return [(per_row[r], digests_per_row[r])
                for r in range(len(per_row))]

    # --- composed drivers --------------------------------------------------

    def manifest_resident_batch(self, buf_d: jnp.ndarray, nv: np.ndarray,
                                strict_overflow: bool = False,
                                ) -> List[Tuple[List[tuple], np.ndarray]]:
        """One resident ``(B, _HALO + P)`` batch -> per-row
        (chunks, digests).

        ``buf_d`` rows are ``_HALO`` zero bytes then the stream (zero-padded
        to P); ``nv`` holds true lengths.  This is the exact code path the
        engine's backup runs per batch — ``bench.py`` times it (pipelined
        across segments via :meth:`manifest_segments`).
        """
        packed_d = self.scan_select_dispatch(buf_d, nv)
        per_row = self.scan_select_collect(packed_d, buf_d, nv,
                                           strict_overflow)
        pending = self.digest_dispatch(buf_d, per_row)
        return self.digest_collect(pending, per_row)

    def manifest_segments(self, segments,
                          strict_overflow: bool = False):
        """Software-pipelined driver over resident batches (generator).

        ``segments`` is any iterable of ``(buf_d, nv)``; batches are pulled
        (and thus staged to HBM) lazily, at most ~3 in flight, so callers
        can stream arbitrarily many batches without holding them all
        resident.  While batch i's packed cuts cross the (high-latency)
        host link, batch i+1's scan runs on device; digests download
        asynchronously one stage later.  Steady-state wall clock approaches
        pure device compute instead of compute + 2 round trips per batch.
        Yields each batch's per-row results in order.
        """
        it = iter(segments)
        scans: deque = deque()
        digs: deque = deque()

        def pump_scan():
            for buf_d, nv in it:
                scans.append((buf_d, nv,
                              self.scan_select_dispatch(buf_d, nv)))
                return

        pump_scan()
        pump_scan()
        while scans or digs:
            if scans:
                buf_d, nv, packed_d = scans.popleft()
                per_row = self.scan_select_collect(
                    packed_d, buf_d, nv, strict_overflow)
                digs.append((per_row,
                             self.digest_dispatch(buf_d, per_row)))
                del buf_d  # batch bytes may be freed once tiles dispatched
                pump_scan()
            while digs and (len(digs) >= 2 or not scans):
                per_row, pending = digs.popleft()
                yield self.digest_collect(pending, per_row)

    def manifest_segments_stream(self, host_segments,
                                 strict_overflow: bool = False,
                                 depth: Optional[int] = None):
        """:meth:`manifest_segments` fed through a double-buffered
        host->device staging ring (generator).

        ``host_segments`` yields HOST ``(buf, nv)`` batches (numpy).  A
        ring of ``depth`` (default ``defaults.PIPELINE_STAGE_DEPTH``, 2)
        batches is kept staged ahead of consumption with
        ``jax.device_put`` — an async H2D copy on real accelerators — so
        batch N+1's bytes cross the host link while batch N runs
        scan->digest on device.  The synchronous alternative
        (``jnp.asarray`` inside the consuming loop) serializes every
        upload against compute; that staging gap was PERF.md round-5
        item 3.  Results are bit-identical to the non-staged driver.
        """
        from .. import defaults as _defaults
        if depth is None:
            depth = _defaults.PIPELINE_STAGE_DEPTH
        depth = max(1, int(depth))
        it = iter(host_segments)
        ring: deque = deque()

        def stage_one() -> bool:
            for buf, nv in it:
                with tracing.span("pipeline.h2d_stage"):
                    ring.append((jax.device_put(buf), nv))
                return True
            return False

        def staged():
            while True:
                while len(ring) < depth and stage_one():
                    pass
                if not ring:
                    return
                yield ring.popleft()

        yield from self.manifest_segments(staged(), strict_overflow)

    def manifest_segments_device(self, segments, strict_overflow: bool = False,
                                 window: int = 4):
        """Zero-round-trip pipelined driver (generator).

        Unlike :meth:`manifest_segments` (which downloads each batch's cut
        list before staging digest tiles — two host round trips per batch,
        the measured wall-clock floor on high-latency links), every stage
        here runs on device via
        :func:`backuwup_tpu.ops.manifest_device.scan_digest_batch`; the
        only downloads are the packed cuts + digest accumulator, whose
        async copies overlap later batches' compute.  ``window`` bounds
        batches in flight (HBM high-water).

        Overflow handling preserves bit-exactness: a row whose sparse
        candidate capacity overflowed re-chunks on the CPU oracle; a batch
        whose class capacities overflowed re-runs on the host-tiled path.
        """
        from .digest_pool import leaf_capacity
        from .manifest_device import (class_caps, class_leaf_sizes,
                                      scan_digest_batch,
                                      scan_digest_batch_pool, tier_plan)

        p = self.params
        classes = class_leaf_sizes(p)
        it = iter(segments)
        pending: deque = deque()

        def dispatch():
            for buf_d, nv in it:
                B = int(buf_d.shape[0])
                padded = int(buf_d.shape[1]) - _HALO
                s_cap, l_cap, cut_cap = self._caps(padded)
                with tracing.span("pipeline.scan_digest_dispatch"):
                    if self.pool_digest:
                        packed, acc, ovf = scan_digest_batch_pool(
                            buf_d, self._nv_device(nv),
                            min_size=p.min_size, desired_size=p.desired_size,
                            max_size=p.max_size, mask_s=p.mask_s,
                            mask_l=p.mask_l, s_cap=s_cap, l_cap=l_cap,
                            cut_cap=cut_cap, fused=self.fused,
                            leaf_cap=leaf_capacity(B * padded, B * cut_cap),
                            tiers=tier_plan(p, B * padded, B),
                            pallas_digest=self.pallas_digest)
                    else:
                        packed, acc, ovf = scan_digest_batch(
                            buf_d, self._nv_device(nv),
                            min_size=p.min_size, desired_size=p.desired_size,
                            max_size=p.max_size, mask_s=p.mask_s,
                            mask_l=p.mask_l, s_cap=s_cap, l_cap=l_cap,
                            cut_cap=cut_cap, fused=self.fused,
                            classes=classes,
                            caps=class_caps(p, B * padded, B),
                            pallas_digest=self.pallas_digest)
                for a in (packed, acc, ovf):
                    _async_to_host(a)
                actual = int(np.asarray(nv, dtype=np.int64).sum())
                padded_total = B * padded
                for stage in ("scan", "select", "gather", "digest"):
                    obs_profile.dispatch(stage, actual_bytes=actual,
                                         padded_bytes=padded_total)
                pending.append((buf_d, nv, cut_cap, packed, acc, ovf))
                return True
            return False

        for _ in range(window):
            dispatch()
        while pending:
            buf_d, nv, cut_cap, packed_d, acc_d, ovf_d = pending.popleft()
            dispatch()
            with tracing.span("pipeline.scan_digest_collect"):
                packed = np.asarray(packed_d)
                ovf = np.asarray(ovf_d)
            if ovf.any():
                if strict_overflow:
                    raise RuntimeError("class capacity overflow in "
                                       "device manifest")
                # recalibrated path: host-tiled pipeline, still exact
                yield self.manifest_resident_batch(buf_d, nv)
                continue
            acc = np.asarray(acc_d)
            dig8 = np.ascontiguousarray(acc.astype("<u4")).view(
                np.uint8).reshape(-1, cut_cap, 32)
            out = []
            nv = np.asarray(nv, dtype=np.int32)
            for r in range(packed.shape[0]):
                overflow, chunks = _decode_cut_row(packed[r])
                if overflow:
                    if strict_overflow:
                        raise RuntimeError(
                            "candidate overflow in scan+select")
                    row = bytes(np.asarray(
                        buf_d[r, _HALO:_HALO + int(nv[r])]))
                    chunks = chunk_stream_cpu(row, self.params)
                    digs = np.stack([np.frombuffer(
                        _blake3_host(row[o:o + ln]), dtype=np.uint8)
                        for o, ln in chunks]) if chunks else \
                        np.zeros((0, 32), dtype=np.uint8)
                    out.append((chunks, digs))
                    continue
                out.append((chunks, dig8[r, :len(chunks)].copy()))
            yield out

    def _ensure_mesh(self):
        """The mesh for the shard-mapped driver; defaults to one axis
        over every local device (the engine's dedup mesh shape)."""
        if self.mesh is None:
            from jax.sharding import Mesh
            self.mesh = Mesh(np.array(jax.devices()), (self.mesh_axis,))
        return self.mesh

    def manifest_segments_mesh(self, segments, strict_overflow: bool = False,
                               window: int = 4, dedup=None):
        """Multi-device pipelined driver (generator): the zero-round-trip
        manifest of :meth:`manifest_segments_device`, data-parallel over
        the row axis with ``shard_map``.

        Each batch is padded to a row multiple of the mesh size with
        zero rows (``nv=0`` rows produce no cuts), resharded ``P(axis)``,
        and run through
        :func:`backuwup_tpu.ops.manifest_device.scan_digest_batch_pool_mesh`
        — per-shard leaf pools, per-shard tier cascades, and per-shard
        overflow flags, so a pool overflow re-runs ONLY the affected
        shard's rows on the host-tiled path.  ``window`` bounds batches in
        flight; per-device bytes in flight are tracked against
        ``bkw_mesh_hbm_highwater_bytes`` and ``mesh_hbm_high_water``.

        With ``dedup`` (a ``MeshDedupIndex``) each batch's digest
        accumulator is handed to the sharded dedup table ON DEVICE
        (``classify_dispatch``) — zero per-batch host round trips — and
        the generator yields ``(rows, flags)`` where ``flags[r]`` is the
        per-chunk device found-vector (truthy = key resident before that
        batch's insert) or ``None`` when the device could not classify
        the row (shard fallback, candidate overflow, lost lanes);
        ``MeshDedupIndex.resolve_hints`` turns the raw flags into final
        dup hints.  Without ``dedup`` it yields plain rows, bit-identical
        to the single-device driver.
        """
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from .digest_pool import leaf_capacity
        from .manifest_device import scan_digest_batch_pool_mesh, tier_plan

        if not self.pool_digest:
            # parity ladder: no mesh twin for the class-tile digest —
            # fall back to the single-device driver (flags all None, the
            # host authority classifies)
            for rows in self.manifest_segments_device(
                    segments, strict_overflow, window):
                yield (rows, [None] * len(rows)) if dedup is not None \
                    else rows
            return

        mesh = self._ensure_mesh()
        axis = self.mesh_axis
        D = int(mesh.devices.size)
        sharding = NamedSharding(mesh, P(axis))
        p = self.params
        it = iter(segments)
        pending: deque = deque()
        state = {"in_flight": 0}

        def dispatch():
            for buf, nv in it:
                B0 = int(buf.shape[0])
                row = int(buf.shape[1])
                nv = np.asarray(nv, dtype=np.int32)
                B = -(-max(B0, 1) // D) * D
                if B != B0:
                    if isinstance(buf, np.ndarray):
                        buf = np.pad(buf, ((0, B - B0), (0, 0)))
                    else:
                        buf = jnp.pad(buf, ((0, B - B0), (0, 0)))
                    nv = np.pad(nv, (0, B - B0))
                bs = B // D
                padded = row - _HALO
                s_cap, l_cap, cut_cap = self._caps(padded)
                with tracing.span("pipeline.mesh_dispatch"):
                    buf_sh = jax.device_put(buf, sharding)
                    nv_sh = jax.device_put(nv, sharding)
                    rets = scan_digest_batch_pool_mesh(
                        buf_sh, nv_sh, mesh=mesh, axis=axis,
                        min_size=p.min_size, desired_size=p.desired_size,
                        max_size=p.max_size, mask_s=p.mask_s,
                        mask_l=p.mask_l, s_cap=s_cap, l_cap=l_cap,
                        cut_cap=cut_cap, fused=self.fused,
                        leaf_cap=leaf_capacity(bs * padded, bs * cut_cap),
                        tiers=tier_plan(p, bs * padded, bs),
                        pallas_digest=self.pallas_digest,
                        emit_queries=dedup is not None)
                    if dedup is not None:
                        packed, acc, ovf, q = rets
                        found_d, lost_d = dedup.classify_dispatch(q)
                    else:
                        packed, acc, ovf = rets
                        found_d = lost_d = None
                for a in (packed, acc, ovf, found_d, lost_d):
                    if a is not None:
                        _async_to_host(a)
                # accounting: ONE launch per stage (the shard_map program)
                # in the unlabeled families, plus each device's share in
                # the mesh families — per-shard actual bytes come from its
                # contiguous nv slice, padded bytes are its row span
                actual = int(nv.sum(dtype=np.int64))
                for stage in ("scan", "select", "gather", "digest"):
                    obs_profile.dispatch(stage, actual_bytes=actual,
                                         padded_bytes=B * padded)
                per_dev = nv.reshape(D, bs).sum(axis=1, dtype=np.int64)
                for d in range(D):
                    for stage in ("scan", "select", "gather", "digest"):
                        obs_profile.dispatch_device(
                            stage, d, actual_bytes=int(per_dev[d]),
                            padded_bytes=bs * padded)
                # per-device bytes in flight: row buffer + packed cuts +
                # digest accumulator + ovf flag (+ dedup query/value lanes)
                foot = (bs * row + bs * (2 + cut_cap) * 4
                        + bs * cut_cap * 32 + 4)
                if dedup is not None:
                    foot += bs * cut_cap * (16 + 4)
                state["in_flight"] += foot
                for d in range(D):
                    obs_profile.hbm_high_water(d, state["in_flight"])
                    if state["in_flight"] > self.mesh_hbm_high_water.get(d, 0):
                        self.mesh_hbm_high_water[d] = state["in_flight"]
                pending.append((buf, nv, B0, cut_cap, foot,
                                packed, acc, ovf, found_d, lost_d))
                return True
            return False

        for _ in range(window):
            dispatch()
        while pending:
            (buf, nv, B0, cut_cap, foot, packed_d, acc_d, ovf_d,
             found_d, lost_d) = pending.popleft()
            dispatch()
            with tracing.span("pipeline.mesh_collect"):
                packed = np.asarray(packed_d)
                ovf = np.asarray(ovf_d)  # (D,) per-shard flags
            state["in_flight"] -= foot
            B = packed.shape[0]
            bs = B // D
            if ovf.any() and strict_overflow:
                raise RuntimeError("pool capacity overflow in mesh manifest")
            bad = set(np.nonzero(ovf)[0].tolist())
            dig8 = None
            if len(bad) < D:
                acc = np.asarray(acc_d)
                dig8 = np.ascontiguousarray(acc.astype("<u4")).view(
                    np.uint8).reshape(B, cut_cap, 32)
            found = lost = None
            if found_d is not None:
                with tracing.span("pipeline.mesh_collect"):
                    found = np.asarray(found_d).reshape(B, cut_cap)
                    lost = np.asarray(lost_d).reshape(B, cut_cap)
                n_real = int(packed[packed[:, 0] == 0, 1].sum())
                obs_profile.dispatch("index", actual_bytes=32 * n_real,
                                     padded_bytes=32 * B * cut_cap)
                for d in range(D):
                    sl = packed[d * bs:(d + 1) * bs]
                    obs_profile.dispatch_device(
                        "index", d,
                        actual_bytes=32 * int(sl[sl[:, 0] == 0, 1].sum()),
                        padded_bytes=32 * bs * cut_cap)
                # tiered front (dedupstore.TieredDedupIndex): each
                # collected batch is one promotion-clock window
                note = getattr(dedup, "note_window", None)
                if note is not None:
                    note(n_real, int((lost != 0).sum()))
            hb = buf if isinstance(buf, np.ndarray) else None
            out: List = [None] * B
            flags: List = [None] * B
            for s in range(D):
                r0, r1 = s * bs, (s + 1) * bs
                if s in bad:
                    # per-shard fallback: ONLY this shard's rows re-run on
                    # the host-tiled path (the tentpole's whole point —
                    # adversarial data costs one shard, not the batch)
                    if hb is None:
                        hb = np.asarray(buf)
                    sub = self.manifest_resident_batch(
                        jnp.asarray(hb[r0:r1]), nv[r0:r1])
                    for r in range(r0, min(r1, B0)):
                        out[r] = sub[r - r0]
                    continue
                for r in range(r0, min(r1, B0)):
                    overflow, chunks = _decode_cut_row(packed[r])
                    if overflow:
                        if strict_overflow:
                            raise RuntimeError(
                                "candidate overflow in scan+select")
                        if hb is None:
                            hb = np.asarray(buf)
                        rowb = bytes(hb[r, _HALO:_HALO + int(nv[r])])
                        chunks = chunk_stream_cpu(rowb, self.params)
                        digs = np.stack([np.frombuffer(
                            _blake3_host(rowb[o:o + ln]), dtype=np.uint8)
                            for o, ln in chunks]) if chunks else \
                            np.zeros((0, 32), dtype=np.uint8)
                        out[r] = (chunks, digs)
                        continue
                    out[r] = (chunks, dig8[r, :len(chunks)].copy())
                    if found is not None and not lost[r, :len(chunks)].any():
                        flags[r] = found[r, :len(chunks)] != 0
            if dedup is not None:
                yield out[:B0], flags[:B0]
            else:
                yield out[:B0]

    def process_segment(self, stream: jnp.ndarray, n_valid: int,
                        prev_tail: bytes = b"") -> Tuple[List[tuple], np.ndarray]:
        """One resident segment -> (chunks [(offset, length)...], digests).

        ``stream`` must be a device u8 array of length >= n_valid + slack
        for the final gather (padding bytes are masked out of digests).
        ``prev_tail`` is ignored for cut semantics here: segments fed to the
        bench are independent streams.
        """
        ext = jnp.concatenate(
            [jnp.zeros(_HALO, dtype=jnp.uint8), stream]).reshape(1, -1)
        nv = np.full(1, n_valid, dtype=np.int32)
        (chunks, digests), = self.manifest_resident_batch(ext, nv)
        return chunks, digests

    def _manifest_prepass(self, streams, out: List) -> dict:
        """Route a stream batch: fills ``out`` for empty/tiny/long streams
        (the non-batched shapes) and returns the {padded_len: [idx...]}
        groups the resident batch drivers consume."""
        p = self.params
        tiny: List[int] = []
        groups: dict = {}
        for i, s in enumerate(streams):
            n = len(s)
            if n == 0:
                out[i] = ([], np.zeros((0, 32), dtype=np.uint8))
            elif n <= p.min_size:
                # sub-min streams are always exactly one chunk (select_cuts
                # first rule), so the scan is skipped entirely — many tiny
                # files cost one batched digest, not 64 KiB-padded scans
                tiny.append(i)
            elif n > self.scanner.segment_size:
                # long stream: segmented device scan, then resident digest
                chunks = self.scanner.chunk_stream(s)
                obs_profile.dispatch("scan", actual_bytes=n, padded_bytes=n)
                obs_profile.dispatch("select", actual_bytes=n,
                                     padded_bytes=n)
                dev = jnp.asarray(np.frombuffer(bytes(s), dtype=np.uint8))
                out[i] = (chunks, self.digest_chunks(dev, chunks))
            else:
                groups.setdefault(_segment_bucket(n), []).append(i)
        if tiny:
            digs = blake3_many_tpu([streams[i] for i in tiny])
            tiny_bytes = sum(len(streams[i]) for i in tiny)
            obs_profile.dispatch("digest", actual_bytes=tiny_bytes,
                                 padded_bytes=tiny_bytes)
            for i, d in zip(tiny, digs):
                out[i] = ([(0, len(streams[i]))],
                          np.frombuffer(d, dtype=np.uint8).reshape(1, 32))
        return groups

    def _bucketed_batches(self, streams, groups: dict, batch_rows: deque):
        """Generator of (host buf, nv) resident batches for the grouped
        streams; appends each batch's stream indices to ``batch_rows``."""
        for padded, idxs in sorted(groups.items()):
            row = _HALO + padded
            max_rows = max(1, _SCAN_DISPATCH_BYTES // row)
            # pow2 row padding, clamped by the dispatch budget (largest
            # pow2 <= max_rows): a lone 128 MiB stream must not balloon
            # to 8 identical rows, and a full part must not double past
            # the budget — so slice by the pow2 cap itself
            b_cap = 1 << (max_rows.bit_length() - 1)
            for s0 in range(0, len(idxs), b_cap):
                part = idxs[s0:s0 + b_cap]
                B = min(8, b_cap)
                while B < len(part):
                    B *= 2
                buf = np.zeros((B, row), dtype=np.uint8)
                nv = np.zeros(B, dtype=np.int32)
                for r, i in enumerate(part):
                    d = np.frombuffer(bytes(streams[i]), dtype=np.uint8)
                    buf[r, _HALO:_HALO + len(d)] = d
                    nv[r] = len(d)
                batch_rows.append(part)
                yield buf, nv

    def manifest_batch(self, streams) -> List[Tuple[List[tuple], np.ndarray]]:
        """Chunk + fingerprint a batch of independent streams, resident.

        Each stream's bytes are staged into HBM exactly once: streams are
        bucketed by padded length, scanned+selected with one fused dispatch
        per bucket, and chunk buffers are gathered HBM->HBM out of the same
        resident batch before the batched BLAKE3.  Returns a
        ``(chunks, digests)`` pair per stream, bit-identical to the CPU
        oracle pipeline.
        """
        out: List[Optional[Tuple[List[tuple], np.ndarray]]] = [None] * len(streams)
        groups = self._manifest_prepass(streams, out)
        # stage resident batches lazily through the pipelined driver
        # behind the 2-deep H2D staging ring: at most ~3 batches (each
        # bounded by the dispatch budget) live in HBM at once, and batch
        # N+1's upload overlaps batch N's scan->digest
        batch_rows: deque = deque()
        gen = self._bucketed_batches(streams, groups, batch_rows)
        for results in self.manifest_segments_stream(gen):
            part = batch_rows.popleft()
            for r, i in enumerate(part):
                out[i] = results[r]
        return out

    def manifest_batch_classified(self, streams, dedup):
        """:meth:`manifest_batch` through the mesh driver with the
        on-device dedup handoff: returns ``(out, flags)`` where
        ``flags[i]`` is stream i's per-chunk device found-vector or
        ``None`` when the device could not classify it (empty/tiny/long
        streams, shard fallbacks, lost lanes — the host authority
        resolves those via ``MeshDedupIndex.resolve_hints``).
        """
        out: List[Optional[Tuple[List[tuple], np.ndarray]]] = [None] * len(streams)
        flags: List[Optional[np.ndarray]] = [None] * len(streams)
        groups = self._manifest_prepass(streams, out)
        batch_rows: deque = deque()
        gen = self._bucketed_batches(streams, groups, batch_rows)
        for rows, rowflags in self.manifest_segments_mesh(gen, dedup=dedup):
            part = batch_rows.popleft()
            for r, i in enumerate(part):
                out[i] = rows[r]
                flags[i] = rowflags[r]
        return out, flags

    def _chunk_bucket(self, n_bytes: int) -> int:
        """Smallest leaf bucket (power of two, >=16 chunks) holding a chunk;
        bounds padding waste to <2x instead of all-chunks-at-max."""
        need = max(1, -(-n_bytes // CHUNK_LEN))
        b = 16
        while b < need:
            b *= 2
        return min(b, self.l_bucket) if need <= self.l_bucket else need

    def digest_chunks(self, stream: jnp.ndarray, chunks: List[tuple]) -> np.ndarray:
        """Gather + digest chunk spans of a resident stream; (N, 32) u8.

        Chunks group into (B, L) size tiles so device work scales with
        actual bytes, not worst-case chunk size.
        """
        if not chunks:
            return np.zeros((0, 32), dtype=np.uint8)
        # slack so the fixed-span gathers never clamp (dynamic_slice clips
        # out-of-range starts, which would shift data)
        stream = jnp.pad(stream, (0, self.l_bucket * CHUNK_LEN))
        out = np.zeros((len(chunks), 32), dtype=np.uint8)
        groups: dict = {}
        for i, (off, ln) in enumerate(chunks):
            groups.setdefault(self._chunk_bucket(ln), []).append(i)
        for L, idxs in sorted(groups.items()):
            pos = 0
            for bb in _row_tiles(len(idxs), self.b_bucket):
                part = idxs[pos:pos + bb]
                pos += bb
                offs = np.zeros(bb, dtype=np.int32)
                lens = np.zeros(bb, dtype=np.int32)
                for j, i in enumerate(part):
                    offs[j], lens[j] = chunks[i]
                buf = gather_chunks(stream, jnp.asarray(offs), l_bucket=L)
                root = digest_padded(buf.reshape(bb, L * CHUNK_LEN),
                                     jnp.asarray(lens), L=L)
                tile_actual = int(lens.sum())
                tile_padded = bb * L * CHUNK_LEN
                obs_profile.dispatch("gather", actual_bytes=tile_actual,
                                     padded_bytes=tile_padded)
                obs_profile.dispatch("digest", actual_bytes=tile_actual,
                                     padded_bytes=tile_padded)
                got = np.ascontiguousarray(np.asarray(root).astype("<u4"))
                got = got.view(np.uint8).reshape(bb, 32)
                for j, i in enumerate(part):
                    out[i] = got[j]
        return out
