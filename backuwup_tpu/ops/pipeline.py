"""Device-resident dedup pipeline: scan -> cut -> gather chunks -> digest.

Composes the TPU kernels into the full chunk+hash step that ``bench.py``
times and ``__graft_entry__.py`` exposes to the driver:

1. gear-hash scan of a resident byte segment (:mod:`.cdc_tpu`),
2. host cut selection over the sparse candidate words (tiny transfer),
3. on-device gather of the variable-length chunks into a padded
   ``(B, L*1024)`` batch (``vmap`` of ``dynamic_slice`` — bytes move
   HBM->HBM, never through the host),
4. batched BLAKE3 digests (:mod:`.blake3_tpu`).

The reference executes the same logical pipeline one byte / one chunk at a
time on the CPU (``dir_packer.rs:246-311``).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import defaults
from .blake3_tpu import digest_padded
from .cdc_cpu import chunk_stream as chunk_stream_cpu
from .cdc_cpu import cuts_to_chunks, select_cuts
from .blake3_tpu import blake3_many_tpu
from .cdc_tpu import (
    _HALO,
    TpuCdcScanner,
    _decode_words,
    _scan_segment,
    _segment_bucket,
    scan_words_batch,
    unpack_scan_words,
)
from .gear import CDCParams

CHUNK_LEN = 1024

# cap on one vmapped-scan dispatch (rows x row bytes)
_SCAN_DISPATCH_BYTES = 128 * 1024 * 1024


def _pad_to(arr: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


@functools.partial(jax.jit, static_argnames=("B", "L"),
                   donate_argnames=("acc",))
def _gather_digest(flat: jnp.ndarray, meta: jnp.ndarray, start: jnp.ndarray,
                   acc: jnp.ndarray, *, B: int, L: int) -> jnp.ndarray:
    """Fused HBM gather + batched BLAKE3 for one (B, L) chunk bucket.

    ``meta`` is the (3, total) i32 array of [offsets; lengths; starts]
    covering every bucket of the batch — uploaded once; each bucket call
    slices its ``[start, start+B)`` window on device (``start`` is traced,
    so varying bucket layouts never recompile — only (B, L) combinations
    do), gathers the chunk spans out of the resident ``flat`` stream,
    digests, and writes the root chaining values into the donated ``acc``
    at the same window.  One fixed-shape ``acc`` download then returns
    every bucket's digests — no variable-shape concatenation, no
    per-bucket transfers.
    """
    offs = jax.lax.dynamic_slice(meta[0], (start,), (B,))
    lens = jax.lax.dynamic_slice(meta[1], (start,), (B,))
    span = L * CHUNK_LEN

    def one(off):
        return jax.lax.dynamic_slice(flat, (off,), (span,))

    buf = jax.vmap(one)(offs)
    root = digest_padded(buf, lens, L=L)
    return jax.lax.dynamic_update_slice(acc, root, (start, jnp.int32(0)))


@functools.partial(jax.jit, static_argnames=("l_bucket",))
def gather_chunks(stream: jnp.ndarray, offsets: jnp.ndarray,
                  *, l_bucket: int) -> jnp.ndarray:
    """(B,) chunk offsets -> (B, l_bucket*1024) u8 padded chunk buffers.

    Chunks are sliced from the resident stream; callers mask true lengths
    via the ``lens`` argument of :func:`digest_padded`, so over-read bytes
    beyond each chunk are ignored by the masked BLAKE3 scan.
    """
    span = l_bucket * CHUNK_LEN

    def one(off):
        return jax.lax.dynamic_slice(stream, (off,), (span,))

    return jax.vmap(one)(offsets.astype(jnp.int32))


class DevicePipeline:
    """Chunk + fingerprint segments that already live (or land) in HBM."""

    def __init__(self, params: Optional[CDCParams] = None,
                 l_bucket: int = 3072, b_bucket: int = 128):
        self.params = params or CDCParams()
        self.scanner = TpuCdcScanner(self.params)
        if self.params.max_size > l_bucket * CHUNK_LEN:
            raise ValueError("l_bucket smaller than max chunk size")
        self.l_bucket = l_bucket
        self.b_bucket = b_bucket
        self._nv_cache: dict = {}

    def process_segment(self, stream: jnp.ndarray, n_valid: int,
                        prev_tail: bytes = b"") -> Tuple[List[tuple], np.ndarray]:
        """One resident segment -> (chunks [(offset, length)...], digests).

        ``stream`` must be a device u8 array of length >= n_valid + slack
        for the final gather (padding bytes are masked out of digests).
        ``prev_tail`` is ignored for cut semantics here: segments fed to the
        bench are independent streams.
        """
        p = self.params
        ext = jnp.concatenate(
            [jnp.zeros(_HALO, dtype=jnp.uint8), stream])
        k_cap = self.scanner._k_cap(int(stream.shape[0]))
        widx, wl, ws, nz = _scan_segment(
            ext, jnp.int32(n_valid), jnp.uint32(p.mask_s),
            jnp.uint32(p.mask_l), k_cap=k_cap)
        if int(nz) > k_cap:
            raise RuntimeError("candidate overflow in bench pipeline")
        pos_l, is_s = _decode_words(widx, wl, ws, k_cap, 0)
        chunks = cuts_to_chunks(
            select_cuts(pos_l[is_s], pos_l, n_valid, p))
        digests = self.digest_chunks(stream, chunks)
        return chunks, digests

    def manifest_batch(self, streams) -> List[Tuple[List[tuple], np.ndarray]]:
        """Chunk + fingerprint a batch of independent streams, resident.

        Each stream's bytes are staged into HBM exactly once: streams are
        bucketed by padded length, scanned with one vmapped dispatch per
        bucket, cut selection runs on the host over the sparse candidate
        words (tiny transfer), and chunk buffers are gathered HBM->HBM out
        of the same resident batch before the batched BLAKE3.  Returns a
        ``(chunks, digests)`` pair per stream, bit-identical to the CPU
        oracle pipeline.
        """
        p = self.params
        out: List[Optional[Tuple[List[tuple], np.ndarray]]] = [None] * len(streams)
        tiny: List[int] = []
        groups: dict = {}
        for i, s in enumerate(streams):
            n = len(s)
            if n == 0:
                out[i] = ([], np.zeros((0, 32), dtype=np.uint8))
            elif n <= p.min_size:
                # sub-min streams are always exactly one chunk (select_cuts
                # first rule), so the scan is skipped entirely — many tiny
                # files cost one batched digest, not 64 KiB-padded scans
                tiny.append(i)
            elif n > self.scanner.segment_size:
                # long stream: segmented device scan, then resident digest
                chunks = self.scanner.chunk_stream(s)
                dev = jnp.asarray(np.frombuffer(bytes(s), dtype=np.uint8))
                out[i] = (chunks, self.digest_chunks(dev, chunks))
            else:
                groups.setdefault(_segment_bucket(n), []).append(i)
        if tiny:
            digs = blake3_many_tpu([streams[i] for i in tiny])
            for i, d in zip(tiny, digs):
                out[i] = ([(0, len(streams[i]))],
                          np.frombuffer(d, dtype=np.uint8).reshape(1, 32))
        for padded, idxs in sorted(groups.items()):
            row = _HALO + padded
            # bound one scan dispatch (the hash pass peaks at ~9 bytes of
            # HBM per stream byte) and pad the row count to a power of two
            # so arbitrary per-directory batch sizes reuse a handful of
            # compiled shapes
            max_rows = max(1, _SCAN_DISPATCH_BYTES // row)
            # pow2 row padding, clamped by the dispatch budget (largest
            # pow2 <= max_rows): a lone 128 MiB stream must not balloon
            # to 8 identical rows, and a full part must not double past
            # the budget — so slice by the pow2 cap itself
            b_cap = 1 << (max_rows.bit_length() - 1)
            for s0 in range(0, len(idxs), b_cap):
                part = idxs[s0:s0 + b_cap]
                B = min(8, b_cap)
                while B < len(part):
                    B *= 2
                buf = np.zeros((B, row), dtype=np.uint8)
                nv = np.zeros(B, dtype=np.int32)
                for r, i in enumerate(part):
                    d = np.frombuffer(bytes(streams[i]), dtype=np.uint8)
                    buf[r, _HALO:_HALO + len(d)] = d
                    nv[r] = len(d)
                results = self.manifest_resident_batch(jnp.asarray(buf), nv)
                for r, i in enumerate(part):
                    out[i] = results[r]
        return out

    def manifest_resident_batch(self, buf_d: jnp.ndarray, nv: np.ndarray,
                                strict_overflow: bool = False,
                                ) -> List[Tuple[List[tuple], np.ndarray]]:
        """The device core of :meth:`manifest_batch`: one resident
        ``(B, _HALO + P)`` batch -> per-row (chunks, digests).

        ``buf_d`` rows are ``_HALO`` zero bytes then the stream (zero-padded
        to P); ``nv`` holds true lengths.  This is the exact code path the
        engine's backup runs per batch — ``bench.py`` times it directly.
        ``strict_overflow`` raises on sparse-capacity overflow instead of
        falling back to the CPU oracle (benchmarks must not silently time
        the oracle).
        """
        p = self.params
        B, row = int(buf_d.shape[0]), int(buf_d.shape[1])
        padded = row - _HALO
        k_cap = self.scanner._k_cap(padded)
        # round trip 1: one packed download of every row's sparse candidates
        # (repeated nv vectors reuse their device copy — upload once)
        nv = np.asarray(nv, dtype=np.int32)
        nv_key = nv.tobytes()
        nv_d = self._nv_cache.get(nv_key)
        if nv_d is None:
            if len(self._nv_cache) > 64:
                self._nv_cache.clear()
            nv_d = self._nv_cache[nv_key] = jnp.asarray(nv)
        packed = np.asarray(scan_words_batch(
            buf_d, nv_d, mask_s=p.mask_s, mask_l=p.mask_l, k_cap=k_cap))
        per_row: List[List[tuple]] = []
        for r in range(B):
            n = int(nv[r])
            nz, widx, wl, ws = unpack_scan_words(packed[r], k_cap)
            if nz > k_cap:
                if strict_overflow:
                    raise RuntimeError(
                        f"candidate overflow: {nz} words > {k_cap}")
                # sparse capacity overflow (adversarial data): oracle
                # rescan of this one stream keeps output bit-identical
                row_bytes = bytes(np.asarray(buf_d[r, _HALO:_HALO + n]))
                per_row.append(chunk_stream_cpu(row_bytes, p))
            else:
                pos_l, is_s = _decode_words(widx, wl, ws, k_cap, 0)
                per_row.append(cuts_to_chunks(
                    select_cuts(pos_l[is_s], pos_l, n, p)))
        # bucket every chunk of the batch for the fused gather+digest;
        # (offsets; lengths) ride to the device as ONE meta upload and all
        # bucket digests come back as ONE concatenated download
        span_max = self.l_bucket * CHUNK_LEN
        flat = jnp.pad(buf_d.reshape(-1), (0, span_max))
        groups: dict = {}
        for r, chunks in enumerate(per_row):
            base = r * row + _HALO
            for ci, (off, ln) in enumerate(chunks):
                groups.setdefault(self._chunk_bucket(ln), []).append(
                    (base + off, ln, r, ci))
        if not groups:
            return [(per_row[r], np.zeros((0, 32), dtype=np.uint8))
                    for r in range(B)]
        buckets: List[tuple] = []  # (start, Bb, Lb, [(r, ci)...])
        offs_parts: List[np.ndarray] = []
        lens_parts: List[np.ndarray] = []
        start = 0
        for Lb, items in sorted(groups.items()):
            for s0 in range(0, len(items), self.b_bucket):
                part = items[s0:s0 + self.b_bucket]
                Bb = 8
                while Bb < len(part):
                    Bb *= 2
                o = np.zeros(Bb, dtype=np.int32)
                ln_arr = np.zeros(Bb, dtype=np.int32)
                for q, (off, ln, _r, _ci) in enumerate(part):
                    o[q] = off
                    ln_arr[q] = ln
                offs_parts.append(o)
                lens_parts.append(ln_arr)
                buckets.append((start, Bb, Lb,
                                [(r, ci) for _o, _l, r, ci in part]))
                start += Bb
        # round trip 2: one meta upload; per-bucket starts are sliced from
        # it on device so bucket layout never recompiles _gather_digest, and
        # the total is padded to a power of two so neither does meta's shape
        starts = np.array([st for st, _b, _l, _t in buckets], dtype=np.int32)
        total = 256
        while total < max(start, len(starts)):
            total *= 2
        meta = jnp.asarray(np.stack([
            _pad_to(np.concatenate(offs_parts), total),
            _pad_to(np.concatenate(lens_parts), total),
            _pad_to(starts, total)]))
        acc = jnp.zeros((total, 8), dtype=jnp.uint32)
        for i, (_st, Bb, Lb, _tags) in enumerate(buckets):
            acc = _gather_digest(flat, meta, meta[2, i], acc, B=Bb, L=Lb)
        # round trip 3: one fixed-shape digest download
        allcv = np.asarray(acc)
        dig8 = np.ascontiguousarray(allcv.astype("<u4")).view(
            np.uint8).reshape(-1, 32)
        digests_per_row = [np.zeros((len(c), 32), dtype=np.uint8)
                           for c in per_row]
        for st, _Bb, _Lb, tags in buckets:
            for q, (r, ci) in enumerate(tags):
                digests_per_row[r][ci] = dig8[st + q]
        return [(per_row[r], digests_per_row[r]) for r in range(B)]

    def _chunk_bucket(self, n_bytes: int) -> int:
        """Smallest leaf bucket (power of two, >=16 chunks) holding a chunk;
        bounds padding waste to <2x instead of all-chunks-at-max."""
        need = max(1, -(-n_bytes // CHUNK_LEN))
        b = 16
        while b < need:
            b *= 2
        return min(b, self.l_bucket) if need <= self.l_bucket else need

    def digest_chunks(self, stream: jnp.ndarray, chunks: List[tuple]) -> np.ndarray:
        """Gather + digest chunk spans of a resident stream; (N, 32) u8.

        Chunks group into (B, L) size buckets so device work scales with
        actual bytes, not worst-case chunk size.
        """
        if not chunks:
            return np.zeros((0, 32), dtype=np.uint8)
        # slack so the fixed-span gathers never clamp (dynamic_slice clips
        # out-of-range starts, which would shift data)
        stream = jnp.pad(stream, (0, self.l_bucket * CHUNK_LEN))
        out = np.zeros((len(chunks), 32), dtype=np.uint8)
        groups: dict = {}
        for i, (off, ln) in enumerate(chunks):
            groups.setdefault(self._chunk_bucket(ln), []).append(i)
        for L, idxs in sorted(groups.items()):
            for s in range(0, len(idxs), self.b_bucket):
                part = idxs[s:s + self.b_bucket]
                bb = 8
                while bb < len(part):
                    bb *= 2
                bb = min(bb, self.b_bucket)
                offs = np.zeros(bb, dtype=np.int32)
                lens = np.zeros(bb, dtype=np.int32)
                for j, i in enumerate(part):
                    offs[j], lens[j] = chunks[i]
                buf = gather_chunks(stream, jnp.asarray(offs), l_bucket=L)
                root = digest_padded(buf.reshape(bb, L * CHUNK_LEN),
                                     jnp.asarray(lens), L=L)
                got = np.ascontiguousarray(np.asarray(root).astype("<u4"))
                got = got.view(np.uint8).reshape(bb, 32)
                for j, i in enumerate(part):
                    out[i] = got[j]
        return out
