"""TPU execution backend for the windowed Gear CDC scan.

Replaces the reference's sequential FastCDC hot loop
(``client/src/backup/filesystem/dir_packer.rs:246-266``) with a data-parallel
decomposition designed for XLA/TPU:

* The per-position rolling hash ``h[i] = ((h[i-1] << 1) + GEAR[b[i]]) mod 2^32``
  is *exactly* equal to the 32-tap windowed sum
  ``h[i] = sum_{k=0}^{31} GEAR[b[i-k]] << k`` because shifts >= 32 vanish
  mod 2^32.  The window form has no sequential dependence, so the whole
  stream is hashed with 32 shifted vector adds — VPU work XLA fuses into a
  single pass over the bytes.
* The 256-entry gear-table lookup is executed on the **MXU**, not as a
  gather (TPU gathers serialize): bytes become a one-hot bf16 matrix that is
  multiplied against the table split into four 8-bit limbs.  0/1 and 0..255
  are exact in bf16 and the MXU accumulates in f32, so the product is the
  exact integer table value.
* Candidate cut-points (``h & mask == 0``) leave the device as a two-level
  sparse structure: bits are packed 32:1 into u32 words on the VPU, then a
  fixed-capacity ``jnp.nonzero`` compacts the (overwhelmingly zero) words,
  so only a few KiB cross host<->HBM per segment.
* Final cut selection (min/desired/max + two-mask normalization) runs on the
  host over the sparse candidates — the same code path as the CPU oracle
  (:func:`backuwup_tpu.ops.cdc_cpu.select_cuts`), so TPU and CPU chunking
  are bit-identical by construction.
* Long streams are processed in bounded segments with a 31-byte carried halo
  (sequence-parallel blockwise decomposition); across a device mesh the halo
  travels over ICI via ``ppermute`` (:func:`make_sharded_scanner`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..utils.jax_compat import shard_map
from .. import defaults
from .cdc_cpu import cuts_to_chunks, select_cuts
from .cdc_cpu import gear_hashes as gear_hashes_np
from .gear import GEAR_WINDOW, CDCParams

_HALO = GEAR_WINDOW - 1  # 31 bytes of left context carry the full hash state


def _gear_values(b: jnp.ndarray) -> jnp.ndarray:
    """GEAR[b] computed per position: ``fmix32(GEAR_SEED32 + b)``.

    Seven fused elementwise u32 VPU ops — no gather (serializes on TPU)
    and no one-hot matmul (round 3's nibble-bilinear MXU form paid
    ~16 bytes of one-hot HBM traffic per stream byte and was the
    measured scan floor at ~215 ms/256 MiB; this form is pure
    fuseable arithmetic).  Bit-identical to ``GEAR[b]`` by
    construction (gear.make_gear_table evaluates the same formula).
    """
    from .gear import GEAR_SEED32
    h = b.astype(jnp.uint32) + jnp.uint32(GEAR_SEED32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _hash_ext(ext: jnp.ndarray, halo_len: jnp.ndarray) -> jnp.ndarray:
    """Per-position hashes for ``ext[_HALO:]``, warmup-exact.

    ``ext`` is ``(_HALO + L,)`` uint8 — 31 bytes of left context followed by
    the segment.  ``halo_len`` (traced scalar, 0.._HALO) says how many of the
    context bytes really precede the stream position; taps reaching before
    the stream start are masked out, reproducing the oracle's short-window
    warmup at positions < 31.  Unrolled — use only on small/debug inputs
    (XLA materializes the 32 slice temporaries).
    """
    g = _gear_values(ext)
    L = ext.shape[0] - _HALO
    j = jnp.arange(L, dtype=jnp.int32)
    h = jnp.zeros(L, dtype=jnp.uint32)
    for k in range(GEAR_WINDOW):
        seg = g[_HALO - k:_HALO - k + L]
        if k > 0:
            seg = jnp.where(j >= jnp.int32(k) - halo_len.astype(jnp.int32),
                            seg, jnp.uint32(0))
        h = h + (seg << jnp.uint32(k))
    return h


def _hash_ext_fast(ext: jnp.ndarray) -> jnp.ndarray:
    """Per-position hashes for ``ext[_HALO:]``, production path.

    The 32-tap windowed sum is evaluated by **log-doubling** the linear
    recurrence: after pass ``t`` the running array holds
    ``a_t[i] = sum_{k < 2^t} GEAR[b[i-k]] << k``, so five shift-adds
    (``a <- a + (a >> shift 2^t positions) << 2^t``) replace 32 taps —
    ~8x less HBM traffic than a 32-iteration fori_loop.  Positions shifted
    in from beyond the left edge of ``ext`` read zero, which matches the
    zero-filled-halo warmup contract: at a stream start only h[0..30] are
    perturbed, positions that can never be selected as cuts because every
    cut-selection window starts at >= min_size - 1 > 31 (CDC_SPEC.md;
    min_size >= 64).  Candidate *sets* may therefore contain sub-min
    positions the CPU oracle lacks, but selected cuts are bit-identical.
    """
    assert GEAR_WINDOW == 32, "doubling ladder assumes a 32-byte window"
    a = _gear_values(ext)
    for t in range(5):
        s = 1 << t
        shifted = jnp.concatenate([jnp.zeros(s, dtype=a.dtype), a[:-s]])
        a = a + (shifted << jnp.uint32(s))
    return a[_HALO:]


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(L,) bool -> (L/32,) u32, little-endian bit order within each word."""
    w = bits.reshape(-1, 32).astype(jnp.uint32)
    return jnp.sum(w << jnp.arange(32, dtype=jnp.uint32)[None, :], axis=1,
                   dtype=jnp.uint32)


def _candidate_words(h, n_valid, mask_s, mask_l):
    """Packed candidate-bit words for both masks (loose ``l``, strict ``s``)."""
    L = h.shape[0]
    valid = jnp.arange(L, dtype=jnp.int32) < n_valid
    cand_l = ((h & mask_l) == 0) & valid
    cand_s = cand_l & ((h & mask_s) == 0)
    return _pack_bits(cand_l), _pack_bits(cand_s)


@functools.partial(jax.jit, static_argnames=("k_cap",))
def _scan_segment(ext, n_valid, mask_s, mask_l, *, k_cap: int):
    """Hash one padded segment, return sparse candidate words.

    Output: ``(widx, wl, ws, nz_words)`` — up to ``k_cap`` indices of nonzero
    candidate words (-1 padded), the loose/strict packed bits of each, and
    the true nonzero-word count for overflow detection.
    """
    h = _hash_ext_fast(ext)
    words_l, words_s = _candidate_words(h, n_valid, mask_s, mask_l)
    nz = words_l != 0
    (widx,) = jnp.nonzero(nz, size=k_cap, fill_value=-1)
    nz_words = jnp.sum(nz.astype(jnp.int32))
    safe = jnp.clip(widx, 0, words_l.shape[0] - 1)
    return widx, words_l[safe], words_s[safe], nz_words


def _decode_words(widx, wl, ws, count, base_offset: int):
    """Sparse candidate words -> absolute (pos_l, is_s) numpy arrays."""
    widx = np.asarray(widx)[:count]
    wl = np.asarray(wl)[:count]
    ws = np.asarray(ws)[:count]
    keep = widx >= 0
    widx, wl, ws = widx[keep], wl[keep], ws[keep]
    if widx.size == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=bool))
    bits = np.arange(32, dtype=np.uint32)
    has_l = ((wl[:, None] >> bits[None, :]) & 1).astype(bool)
    has_s = ((ws[:, None] >> bits[None, :]) & 1).astype(bool)
    pos = (widx[:, None].astype(np.int64) * 32 + bits[None, :].astype(np.int64)
           + base_offset)
    return pos[has_l], has_s[has_l]


def gear_hashes_tpu(data, prev_tail: bytes = b"") -> np.ndarray:
    """Full per-position hash array on device; mirrors
    :func:`backuwup_tpu.ops.cdc_cpu.gear_hashes` (test/debug API)."""
    tail = bytes(prev_tail)[-_HALO:] if prev_tail else b""
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    ext = np.zeros(_HALO + len(arr), dtype=np.uint8)
    if tail:
        ext[_HALO - len(tail):_HALO] = np.frombuffer(tail, dtype=np.uint8)
    ext[_HALO:] = arr
    out = jax.jit(_hash_ext)(jnp.asarray(ext), jnp.int32(len(tail)))
    return np.asarray(out)


def _round_up(n: int, align: int) -> int:
    return -(-n // align) * align


def _segment_bucket(n: int) -> int:
    """Padded segment length: power-of-two bucket, >= 64 KiB, so a handful of
    compiled shapes cover every input size."""
    b = 64 * 1024
    while b < n:
        b *= 2
    return b


class TpuCdcScanner:
    """Stateless driver: chunk byte streams with the device doing the scan.

    Overflow of the sparse-word capacity (adversarial data only; real data
    yields ~1 candidate per 2^mask_l_bits bytes) falls back to the numpy
    oracle for the affected segment, preserving bit-identical output.
    """

    def __init__(self, params: Optional[CDCParams] = None,
                 segment_size: int = 128 * defaults.MiB,
                 cap_factor: int = 16):
        self.params = params or CDCParams()
        if self.params.min_size < GEAR_WINDOW:
            # _hash_ext_fast's zero-filled stream-start halo perturbs
            # h[0..30]; harmless only when no cut window reaches below 31.
            raise ValueError(
                f"TPU chunker requires min_size >= {GEAR_WINDOW}")
        self.segment_size = segment_size
        self.cap_factor = cap_factor

    def _k_cap(self, padded: int) -> int:
        expected = max(1, padded >> self.params.mask_l_bits)
        return max(512, _round_up(self.cap_factor * expected, 512))

    def candidate_positions(self, data, prev_tail: bytes = b""):
        """Sorted absolute (pos_s, pos_l) candidate arrays for ``data``."""
        params = self.params
        data = bytes(data)
        n = len(data)
        all_pos, all_s = [], []
        offset = 0
        tail = bytes(prev_tail)[-_HALO:] if prev_tail else b""
        while offset < n:
            seg = data[offset:offset + self.segment_size]
            padded = _segment_bucket(len(seg))
            ext = np.zeros(_HALO + padded, dtype=np.uint8)
            if tail:
                ext[_HALO - len(tail):_HALO] = np.frombuffer(tail, np.uint8)
            ext[_HALO:_HALO + len(seg)] = np.frombuffer(seg, np.uint8)
            k_cap = self._k_cap(padded)
            widx, wl, ws, nz_words = _scan_segment(
                jnp.asarray(ext), jnp.int32(len(seg)),
                jnp.uint32(params.mask_s), jnp.uint32(params.mask_l),
                k_cap=k_cap)
            if int(nz_words) > k_cap:  # capacity overflow: oracle rescan
                h = gear_hashes_np(seg, tail)
                cand_l = (h & np.uint32(params.mask_l)) == 0
                p = np.nonzero(cand_l)[0].astype(np.int64)
                s = (h[p] & np.uint32(params.mask_s)) == 0
                all_pos.append(p + offset)
                all_s.append(s)
            else:
                p, s = _decode_words(widx, wl, ws, k_cap, offset)
                all_pos.append(p)
                all_s.append(s)
            tail = seg[-_HALO:] if len(seg) >= _HALO else (tail + seg)[-_HALO:]
            offset += len(seg)
        if all_pos:
            pos_l = np.concatenate(all_pos)
            is_s = np.concatenate(all_s)
        else:
            pos_l = np.empty(0, dtype=np.int64)
            is_s = np.empty(0, dtype=bool)
        return pos_l[is_s], pos_l

    def chunk_stream(self, data):
        """Chunk one stream; list of (offset, length). Bit-identical to
        :func:`backuwup_tpu.ops.cdc_cpu.chunk_stream`."""
        n = len(data)
        pos_s, pos_l = self.candidate_positions(data)
        return cuts_to_chunks(select_cuts(pos_s, pos_l, n, self.params))


# ---------------------------------------------------------------------------
# Batched scan with single-transfer sparse output: the CDC candidate front
# end for whole file batches, one dispatch + ONE device->host download.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l", "k_cap"))
def scan_words_batch(ext_b: jnp.ndarray, nv_b: jnp.ndarray,
                     *, mask_s: int, mask_l: int,
                     k_cap: int) -> jnp.ndarray:
    """``(B, _HALO+P) u8 -> (B, 1+3*k_cap) i32`` packed sparse candidates.

    Per row: ``[nz_words, widx..., words_l..., words_s...]`` — the same
    two-level sparse structure as :func:`_scan_segment`, but all outputs
    packed into ONE array so a whole batch costs a single device->host
    transfer (the relay-attached dev rig pays ~100 ms per transfer; real
    PCIe pays per-transfer latency too, just less).  Host-side cut
    selection then runs the oracle's ``select_cuts`` verbatim.
    """
    ms = jnp.uint32(mask_s)  # static -> folded constants, no upload
    ml = jnp.uint32(mask_l)

    def one(ext, n):
        h = _hash_ext_fast(ext)
        words_l, words_s = _candidate_words(h, n, ms, ml)
        nz = words_l != 0
        (widx,) = jnp.nonzero(nz, size=k_cap, fill_value=-1)
        nz_words = jnp.sum(nz.astype(jnp.int32))
        safe = jnp.clip(widx, 0, words_l.shape[0] - 1)
        return jnp.concatenate([
            nz_words[None], widx.astype(jnp.int32),
            words_l[safe].astype(jnp.int32), words_s[safe].astype(jnp.int32)])

    return jax.vmap(one)(ext_b, nv_b)


def _block_cum(pos, padded: int, bb: int):
    """Exclusive prefix counts of candidates per ``2^bb``-byte block.

    ``cum[b]`` = number of valid candidates (``pos < padded``; the
    compaction pads with sentinel ``padded``) at positions below
    ``b << bb``.  One scatter-add + one short cumsum, both over
    ``padded >> bb`` lanes — negligible next to even a single
    ``searchsorted`` over the candidate array.
    """
    nb = (padded >> bb) + 2
    valid = pos < padded
    cnt = jnp.zeros(nb, dtype=jnp.int32).at[
        jnp.where(valid, (pos >> bb).astype(jnp.int32), nb)
    ].add(1, mode="drop")
    return jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(cnt)[:-1]])


def _make_lookup(pos, cum, cap: int, padded: int, bb: int, probes: int = 6):
    """searchsorted-left on a sorted candidate array in TWO serialized
    gather rounds instead of ``log2(cap)``.

    ``jnp.searchsorted`` lowers to a binary search: ~15-17 *serialized*
    gather rounds over the candidate array, and ``_parallel_select``
    issues ~24 of them — the measured bulk of the 64 KiB-chunk select
    stage (PERF.md).  Here round 1 reads the block prefix table
    (:func:`_block_cum`) for a lower bound, round 2 probes the next
    ``probes+1`` candidates in parallel; sortedness makes the below-query
    prefix-run length the exact correction.  More than ``probes``
    candidates in one block (density far beyond the calibrated gear
    distribution; ``bb`` is sized to keep the expected run < 1/8) sets
    the overflow flag, which joins the row's existing oracle-fallback
    path — output stays bit-identical on every input either way.

    Queries beyond ``padded`` clamp: past-the-end results then differ
    from true searchsorted only in how far PAST the last valid candidate
    they land, which every call site masks (window checks compare the
    gathered position against an in-stream bound; gap-jump targets gather
    the same sentinel either way).
    """
    nb1 = cum.shape[0] - 1

    def lookup(q):
        qc = jnp.clip(q, 0, padded)
        idx0 = cum[jnp.minimum(qc >> bb, nb1)]
        adv = jnp.zeros_like(idx0)
        over = None
        for k in range(probes + 1):
            i = idx0 + k
            below = (i < cap) & (pos[jnp.minimum(i, cap - 1)] < qc)
            if k < probes:
                adv = adv + below.astype(jnp.int32)
            else:
                over = below
        return idx0 + adv, over

    return lookup


def _parallel_select(pos_l, pos_s, n, *, min_size: int, desired_size: int,
                     max_size: int, s_cap: int, l_cap: int, cut_cap: int,
                     padded: int, block_bits: int,
                     probe_iters: int = 6):
    """FastCDC cut selection in O(log) depth instead of a sequential loop.

    The greedy selection (``select_cuts``) is a chain walk: each cut is a
    function of the previous cut only.  The walk is parallelized with the
    classic pointer-jumping construction:

    * ``F(c)`` — the next *candidate* cut after a chunk ending at loose
      candidate ``c``, plus the count of forced (max-size) cuts emitted in
      between — is computed for EVERY candidate at once.  Forced runs are
      resolved in closed form: with no candidate in reach, the next start
      that could possibly cut jumps straight past the whole candidate-free
      gap (``steps = ceil((target-y)/max)``), so even an all-zeros stream
      (zero candidates) resolves in one probe.  ``probe_iters`` bounds the
      alignment retries; unresolved nodes flag the row for the oracle
      fallback (adversarial interval patterns only).
    * Doubling tables ``nxt_k = nxt_{k-1}[nxt_{k-1}]`` give the node and
      emitted-cut count ``2^k`` hops ahead.
    * Each output slot ``m`` independently walks the tables high-to-low
      (take a ``2^k``-hop block iff its emitted count stays <= ``m``),
      then reads its cut: a forced position (arithmetic) or the hop's
      candidate/terminal cut.

    Replaces a ``cut_cap``-iteration ``lax.while_loop`` whose per-step
    latency dominated small-chunk configs (measured 389 ms of 481 ms for
    64 KiB chunks on a 256 MiB segment).  Bit-identical to
    :func:`backuwup_tpu.ops.cdc_cpu.select_cuts` (property-tested; bench
    parity gate end-to-end).
    """
    m = jnp.int32(min_size)
    d = jnp.int32(desired_size)
    M = jnp.int32(max_size)
    TERM = jnp.int32(l_cap)

    # Any-lane probe overflow is ORed into the row's unresolved flag, but
    # ONLY for lanes whose lookup result is actually consumed: sentinel-
    # clamped past-the-end queries and already-resolved/terminal lanes
    # always probe the stream's densest block, and counting them would
    # drop whole rows to the CPU oracle on locally dense (non-adversarial)
    # data even though every consumed lookup succeeded.
    look_ovf = []
    look_s = _make_lookup(pos_s, _block_cum(pos_s, padded, block_bits),
                          s_cap, padded, block_bits)
    look_l = _make_lookup(pos_l, _block_cum(pos_l, padded, block_bits),
                          l_cap, padded, block_bits)

    def ss_s(q, use=None):
        i, ov = look_s(q)
        look_ovf.append(jnp.any(ov if use is None else ov & use))
        return i

    def ss_l(q, use=None):
        i, ov = look_l(q)
        look_ovf.append(jnp.any(ov if use is None else ov & use))
        return i

    def step_from(x, use=None):
        """Candidate-window check for starts ``x``: (hit, cut position)."""
        lo1 = x + (m - 1)
        hi1 = jnp.minimum(x + (d - 2), n - 2)
        i = ss_s(lo1, use)
        e1 = pos_s[jnp.minimum(i, s_cap - 1)]
        ok1 = (i < s_cap) & (e1 <= hi1)
        lo2 = x + (d - 1)
        hi2 = jnp.minimum(x + (M - 2), n - 2)
        j = ss_l(lo2, use)
        e2 = pos_l[jnp.minimum(j, l_cap - 1)]
        ok2 = (j < l_cap) & (e2 <= hi2)
        return ok1 | ok2, jnp.where(ok1, e1, e2)

    def resolve(x0):
        """F for starts ``x0``: (kind TERM/node-pos, forced count,
        final cut pos, unresolved)."""
        y = x0
        jcnt = jnp.zeros_like(x0)
        done = jnp.zeros(x0.shape, dtype=bool)
        is_term = jnp.zeros(x0.shape, dtype=bool)
        final = jnp.full_like(x0, -1)
        for _ in range(probe_iters):
            short = (n - y) <= m  # short tail -> single final chunk
            # short lanes resolve to n-1 regardless of hit/e, so their
            # window lookups are dead; done lanes never consume again
            hit, e = step_from(y, use=~done & ~short)
            at_eof = y >= n - M   # forced cut would land at n-1
            now_term = short | (~hit & at_eof)
            resolved = ~done & (short | hit | at_eof)
            final = jnp.where(resolved,
                              jnp.where(short, n - 1,
                                        jnp.where(hit, e, n - 1)), final)
            is_term = jnp.where(resolved, now_term, is_term)
            # forced-EOF emits its n-1 cut as the hop's final cut, not as
            # one of the arithmetic forced cuts
            done = done | resolved
            # closed-form jump over the candidate-free gap: earliest start
            # that could see the next strict/loose candidate in-window
            # (consumed only by lanes still jumping, i.e. ~done post-update)
            qs = pos_s[jnp.minimum(ss_s(y + (m - 1), ~done), s_cap - 1)]
            ql = pos_l[jnp.minimum(ss_l(y + (d - 1), ~done), l_cap - 1)]
            target = jnp.minimum(jnp.minimum(qs - (d - 2), ql - (M - 2)),
                                 n - M)
            steps = jnp.maximum(
                (target - y + M - 1) // M, 1)
            y = jnp.where(done, y, y + steps * M)
            jcnt = jnp.where(done, jcnt, jcnt + steps)
        return is_term, jcnt, final, ~done

    # F for every candidate node (start = pos_l[c] + 1) and for START
    starts = jnp.concatenate([pos_l + 1, jnp.zeros(1, dtype=pos_l.dtype)])
    is_term, jcnt, final, unres = resolve(starts)
    node_final = final[:l_cap]
    node_term = is_term[:l_cap]
    node_j = jcnt[:l_cap]
    node_un = unres[:l_cap]
    # next node index: the final cut is itself a loose candidate unless
    # terminal (exact match by construction)
    # unresolved nodes carry final=-1 (garbage query) and already flag the
    # row via the unresolved chain, so they don't accumulate overflow here
    nxt0 = jnp.where(
        node_term, TERM,
        ss_l(node_final, ~node_term & ~node_un).astype(jnp.int32))
    emit0 = node_j + 1  # j forced cuts + 1 candidate/terminal cut
    # TERM self-loop emits nothing
    nxt0 = jnp.concatenate([nxt0, TERM[None]])
    emit0 = jnp.concatenate([emit0, jnp.zeros(1, jnp.int32)])
    un0 = jnp.concatenate([node_un, jnp.zeros(1, dtype=bool)])

    # 2^(levels-1) hops must cover the longest possible chain (cut_cap)
    levels = max(1, cut_cap.bit_length() + 1)
    nxts, emits, uns = [nxt0], [emit0], [un0]
    for _ in range(levels - 1):
        nk, ek, uk = nxts[-1], emits[-1], uns[-1]
        nxts.append(nk[nk])
        emits.append(ek + ek[nk])
        uns.append(uk | uk[nk])

    # hop 0: from START (virtual cut at -1, start 0)
    h0_term = is_term[l_cap]
    h0_j = jcnt[l_cap]
    h0_final = final[l_cap]
    h0_un = unres[l_cap]
    b1 = jnp.where(
        h0_term, TERM, ss_l(h0_final, ~h0_term & ~h0_un).astype(jnp.int32))
    h0_emit = h0_j + 1
    total = h0_emit + emits[-1][b1]
    row_unres = h0_un | uns[-1][b1]
    for ov in look_ovf:
        row_unres = row_unres | ov
    n_cuts = jnp.where(n > 0, total, 0)

    # per-slot table walk
    mslot = jnp.arange(cut_cap, dtype=jnp.int32)
    in_h0 = mslot < h0_emit
    # hop-0 cuts: forced k*M-1 for slot k-1, then the resolved final
    cut_h0 = jnp.where(mslot < h0_j, (mslot + 1) * M - 1, h0_final)
    mrel = mslot - h0_emit
    cur = jnp.full(cut_cap, 0, dtype=jnp.int32) + b1
    acc = jnp.zeros(cut_cap, dtype=jnp.int32)
    for k in range(levels - 1, -1, -1):
        cand_acc = acc + emits[k][cur]
        take = cand_acc <= mrel
        cur = jnp.where(take, nxts[k][cur], cur)
        acc = jnp.where(take, cand_acc, acc)
    # the hop from `cur` covers slot mrel: r-th of its fcount forced cuts,
    # or its final candidate/terminal cut
    r = mrel - acc
    cur_safe = jnp.minimum(cur, TERM)
    x_cur = pos_l[jnp.minimum(cur_safe, l_cap - 1)] + 1
    fcount = jnp.maximum(emit0[cur_safe] - 1, 0)
    final_cur = node_final[jnp.minimum(cur_safe, l_cap - 1)]
    cut_m = jnp.where(r < fcount, x_cur + (r + 1) * M - 1, final_cur)
    cuts = jnp.where(in_h0, cut_h0, cut_m)
    cuts = jnp.where(mslot < n_cuts, cuts, -1)
    return n_cuts, cuts, row_unres


@functools.partial(jax.jit, static_argnames=(
    "min_size", "desired_size", "max_size", "mask_s", "mask_l",
    "s_cap", "l_cap", "cut_cap", "fused"))
def scan_select_batch(ext_b: jnp.ndarray, nv_b: jnp.ndarray, *,
                      min_size: int, desired_size: int, max_size: int,
                      mask_s: int, mask_l: int,
                      s_cap: int, l_cap: int, cut_cap: int,
                      fused: bool = False) -> jnp.ndarray:
    """Fused gear scan + FastCDC cut selection, fully on device.

    ``(B, _HALO+P) u8 -> (B, 2+cut_cap) i32`` packed per row as
    ``[overflow, n_cuts, inclusive chunk end positions...]``.  This is the
    whole CDC front end in ONE dispatch: hashes via the doubling ladder,
    candidate compaction via fixed-capacity ``nonzero``, and the
    min/desired/max two-mask selection (bit-identical to
    :func:`backuwup_tpu.ops.cdc_cpu.select_cuts`) over the sparse
    candidates — so the only download a caller needs is the tiny packed
    cut list, instead of candidate words plus a host selection pass plus a
    chunk-meta re-upload.  ``overflow`` flags candidate counts beyond the
    sparse capacity (adversarial data); such rows must be re-chunked by
    the oracle.

    With ``fused=True`` the hash+mask+pack front end runs as the Mosaic
    strip kernel (:func:`backuwup_tpu.ops.scan_fused.fused_candidate_words`,
    ~7x less wall clock than the XLA ladder); callers gate on
    :func:`backuwup_tpu.ops.scan_fused.fused_scan_available`, which
    parity-checks the kernel against the XLA path on the live runtime.
    """
    P = ext_b.shape[1] - _HALO
    ms = jnp.uint32(mask_s)
    ml = jnp.uint32(mask_l)

    # word-level sparse capacity for the two-level compaction below;
    # nearly every candidate lands in its own 32-bit word on real data
    w_cap = max(512, min(l_cap, P // 32 if P >= 32 else 1))

    # block pyramid for the compaction: a direct fixed-capacity nonzero
    # over all P/32 words pays a full-length cumsum (~30+ ms on a 256 MiB
    # segment); reducing 128-word blocks to any-flags first shrinks the
    # expensive cumsums to (P/4096) + (b_cap*128) lanes.
    n_words = (P + 31) // 32
    blk = 128
    while blk > 1 and n_words % blk:
        blk //= 2
    nblk = n_words // blk
    b_cap = min(nblk, max(512, w_cap // 4))

    def compact_words(words_l, words_s):
        """Fixed-capacity (pos_l, is_s-derived pos_s) from packed
        candidate words via THREE-LEVEL compaction.

        A direct ``jnp.nonzero`` over the full position axis costs seconds
        on a 128 MiB segment (measured: the cumsum+scatter over 1.3e8
        lanes dominates the whole pipeline).  Candidate bits arrive packed
        32:1 into u32 words; word blocks reduce to any-flags whose
        ``nonzero`` is tiny, surviving blocks' words are gathered and
        compacted at ``w_cap``, and the final expansion works on
        ``w_cap*32`` lanes.  The strict mask's bits ride along through the
        SAME compaction (its candidates are a subset of the loose ones),
        so no full-axis cumsum or reduction remains.
        """
        wl2 = words_l.reshape(nblk, blk)
        ws2 = words_s.reshape(nblk, blk)
        any_b = jnp.any(wl2 != 0, axis=1)
        (bidx,) = jnp.nonzero(any_b, size=b_cap, fill_value=nblk)
        bsafe = jnp.clip(bidx, 0, nblk - 1)
        in_b = (bidx < nblk)[:, None]
        sub_l = jnp.where(in_b, wl2[bsafe], jnp.uint32(0)).reshape(-1)
        sub_s = jnp.where(in_b, ws2[bsafe], jnp.uint32(0)).reshape(-1)
        # word index (in the full array) of each gathered sub-word
        sub_widx = (bidx[:, None].astype(jnp.int32) * blk
                    + jnp.arange(blk, dtype=jnp.int32)[None, :]).reshape(-1)
        nzw = sub_l != 0
        sub_n = sub_l.shape[0]
        (wsel,) = jnp.nonzero(nzw, size=w_cap, fill_value=sub_n)
        wsafe = jnp.clip(wsel, 0, sub_n - 1)
        in_range = wsel < sub_n
        bits_l = jnp.where(in_range, sub_l[wsafe], jnp.uint32(0))
        bits_s = jnp.where(in_range, sub_s[wsafe], jnp.uint32(0))
        widx = jnp.where(in_range, sub_widx[wsafe], n_words)
        lane = jnp.arange(32, dtype=jnp.int32)[None, :]
        has_l = ((bits_l[:, None] >> lane.astype(jnp.uint32)) & 1) == 1
        has_s = ((bits_s[:, None] >> lane.astype(jnp.uint32)) & 1) == 1
        posmat = widx[:, None] * 32 + lane
        flat_l = has_l.reshape(-1)
        flat_s = has_s.reshape(-1)
        # no masking needed: sel below only gathers flat_l-true lanes, and
        # out-of-range gathers are overwritten with P by sel_ok
        flat_pos = posmat.reshape(-1)
        flat_n = flat_pos.shape[0]
        (sel,) = jnp.nonzero(flat_l, size=l_cap, fill_value=flat_n)
        sel_ok = sel < flat_n
        sel_safe = jnp.clip(sel, 0, flat_n - 1)
        pos_l = jnp.where(sel_ok, flat_pos[sel_safe], P).astype(jnp.int32)
        is_s = sel_ok & flat_s[sel_safe]
        (ssel,) = jnp.nonzero(is_s, size=s_cap, fill_value=l_cap)
        pos_s = jnp.where(ssel < l_cap,
                          pos_l[jnp.clip(ssel, 0, l_cap - 1)],
                          jnp.int32(P))
        overflow = ((jnp.sum(any_b.astype(jnp.int32)) > b_cap)
                    | (jnp.sum(nzw.astype(jnp.int32)) > w_cap)
                    | (jnp.sum(flat_l.astype(jnp.int32)) > l_cap)
                    | (jnp.sum(is_s.astype(jnp.int32)) > s_cap))
        return pos_l, pos_s, overflow

    # lookup-block size: expected loose-candidate count per block stays
    # <= 1/8 (density 2^-mask_l_bits), so the 6-probe correction never
    # overflows on distribution-typical data
    mask_l_bits = bin(mask_l).count("1")
    block_bits = max(5, min(11, mask_l_bits - 3))

    def one(n, words_l, words_s):
        pos_l, pos_s, ovf = compact_words(words_l, words_s)
        n_cuts, cuts, unres = _parallel_select(
            pos_l, pos_s, n, min_size=min_size, desired_size=desired_size,
            max_size=max_size, s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap,
            padded=P, block_bits=block_bits)
        overflow = (ovf | unres).astype(jnp.int32)
        return jnp.concatenate([overflow[None], n_cuts[None], cuts])

    nv_i = nv_b.astype(jnp.int32)
    if fused:
        from .scan_fused import fused_candidate_words
        wl_b, ws_b = fused_candidate_words(ext_b, nv_i,
                                           mask_s=mask_s, mask_l=mask_l)
    else:
        def words_one(ext, n):
            h = _hash_ext_fast(ext)
            return _candidate_words(h, n, ms, ml)

        wl_b, ws_b = jax.vmap(words_one)(ext_b, nv_i)

    return jax.vmap(one)(nv_i, wl_b, ws_b)


def unpack_scan_words(row, k_cap: int):
    """One packed row -> (nz_words, widx, wl(u32), ws(u32)) numpy views."""
    nz = int(row[0])
    widx = row[1:1 + k_cap]
    wl = row[1 + k_cap:1 + 2 * k_cap].astype(np.int64).astype(np.uint32)
    ws = row[1 + 2 * k_cap:1 + 3 * k_cap].astype(np.int64).astype(np.uint32)
    return nz, widx, wl, ws


# ---------------------------------------------------------------------------
# Sharded long-stream scan: blockwise over a device mesh, halo over ICI.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def make_sharded_scanner(mesh: Mesh, axis: str = "data", *,
                         k_cap_per_shard: int = 4096):
    """Build a jitted scanner that shards one long stream across ``mesh``.

    The stream (length divisible by the mesh axis size) is split into
    per-device shards; each device hashes its shard using the 31-byte tail of
    its left neighbour, exchanged over ICI with ``lax.ppermute`` — the CDC
    analog of ring-attention's block decomposition (SURVEY.md section 5.7).

    Returns ``scan(stream_u8, n_valid, mask_s, mask_l) ->
    (widx, wl, ws, nz_words)`` with a leading per-device axis; ``widx`` are
    *absolute* word indices into the stream (-1 pad).
    """
    n_dev = mesh.shape[axis]

    def shard_fn(local, n_valid, mask_s, mask_l):
        idx = jax.lax.axis_index(axis)
        shard_len = local.shape[0]
        # left neighbour's tail rides the ring: shard i sends its last 31
        # bytes to shard i+1.
        tail = jax.lax.ppermute(
            local[-_HALO:], axis,
            perm=[(i, (i + 1) % n_dev) for i in range(n_dev)])
        # shard 0 receives the last shard's tail — garbage, but it only
        # perturbs h[0..30], positions that can never be cuts (min_size > 31)
        ext = jnp.concatenate([tail, local])
        start = idx.astype(jnp.int32) * shard_len
        h = _hash_ext_fast(ext)
        words_l, words_s = _candidate_words(h, n_valid - start, mask_s, mask_l)
        nz = words_l != 0
        (widx,) = jnp.nonzero(nz, size=k_cap_per_shard, fill_value=-1)
        nz_words = jnp.sum(nz.astype(jnp.int32))
        safe = jnp.clip(widx, 0, words_l.shape[0] - 1)
        abs_widx = jnp.where(widx >= 0, widx + start // 32, widx)
        return (abs_widx[None], words_l[safe][None], words_s[safe][None],
                nz_words[None])

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis), P(), P(), P()),
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    return jax.jit(mapped)


def chunk_stream_sharded(data, mesh: Mesh, params: Optional[CDCParams] = None,
                         axis: str = "data", k_cap: Optional[int] = None):
    """Host convenience: chunk one long stream across all devices of ``mesh``.

    Bit-identical to the CPU oracle; used by tests and the multi-chip dryrun.
    ``k_cap`` overrides the per-shard sparse capacity (tests force overflow).
    """
    params = params or CDCParams()
    if params.min_size < GEAR_WINDOW:
        raise ValueError(f"TPU chunker requires min_size >= {GEAR_WINDOW}")
    n = len(data)
    if n >= 2**31:
        # positions are tracked in (x64-disabled) int32 on device; larger
        # streams go through the segmented scanner, which is still exact.
        return TpuCdcScanner(params).chunk_stream(data)
    n_dev = mesh.shape[axis]
    padded = _round_up(max(n, 1), n_dev * 1024)
    buf = np.zeros(padded, dtype=np.uint8)
    buf[:n] = np.frombuffer(bytes(data), dtype=np.uint8)
    # nearly every sparse candidate lands in its own 32-bit word, so size
    # capacity by candidate count, not candidate/32
    if k_cap is None:
        k_cap = max(512, _round_up(
            16 * max(1, (padded // n_dev) >> params.mask_l_bits), 512))
    scan = make_sharded_scanner(mesh, axis, k_cap_per_shard=k_cap)
    stream = jax.device_put(jnp.asarray(buf), NamedSharding(mesh, P(axis)))
    widx, wl, ws, nz_words = scan(stream, jnp.int32(n),
                                  jnp.uint32(params.mask_s),
                                  jnp.uint32(params.mask_l))
    if (np.asarray(nz_words) > k_cap).any():  # overflow: oracle, still exact
        from .cdc_cpu import chunk_stream as cpu_chunk
        return cpu_chunk(data, params)
    pos_parts, s_parts = [], []
    for d in range(n_dev):
        p, s = _decode_words(widx[d], wl[d], ws[d], k_cap, 0)
        pos_parts.append(p)
        s_parts.append(s)
    pos_l = np.concatenate(pos_parts)
    is_s = np.concatenate(s_parts)
    order = np.argsort(pos_l, kind="stable")
    pos_l, is_s = pos_l[order], is_s[order]
    return cuts_to_chunks(select_cuts(pos_l[is_s], pos_l, n, params))
