"""Fused Mosaic/Pallas CDC scan: gear + ladder + candidate masks in VMEM.

The XLA scan (:func:`.cdc_tpu._hash_ext_fast`) pays HBM for every pass:
the fmix32 gear values and each of the five doubling-ladder passes
materialize a u32 array the size of 4x the stream (~45 bytes of HBM
traffic per stream byte, the measured ~200 ms/256 MiB floor).  This
kernel runs the whole scan per VMEM-resident tile and writes only the
packed candidate words (1/4 byte per stream byte), so HBM traffic drops
to ~1.3 bytes per stream byte — within striking distance of the
bandwidth floor.

Layout — the **strip decomposition** (PERF.md round-4 direction 2): the
P-byte stream is split into 128 contiguous strips of S = P/128 bytes;
strip ``l`` occupies lane ``l`` of a ``(S, 128)`` u8 array with stream
position ``l*S + r`` at row ``r``.  A shift by ``s`` positions is then a
pure **sublane** shift (rows), never a lane relayout — the failure mode
that sank round 3's flat-layout ladder kernel (~100-130 ms; PERF.md
"dead ends").  Each strip carries a 32-byte halo of the previous strip's
tail (real bytes, so hashes at strip starts are exact; only global
position 0 sees the spec's zero halo), and each grid step's tile carries
a 32-row halo of the previous tile via a second clamped BlockSpec.

Against the reference: this is the TPU replacement for the byte-at-a-time
FastCDC hot loop in ``client/src/backup/filesystem/dir_packer.rs:246-266``.

Output contract: ``(B, P/32) u32`` candidate words in **position-major
order** (word ``w`` bit ``t`` = candidate at position ``w*32 + t``) —
bit-identical to ``_pack_bits(cand)`` of the XLA path, so the two-level
compaction and the on-device cut selection consume either
interchangeably (tests assert equality; bench parity-gates end to end).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .gear import GEAR_SEED32

_LANES = 128
_HALO_ROWS = 32  # 31 context bytes + 1 alignment row (u8 tile = 32 sublanes)
_DEF_R = 2048  # strip rows per grid step (VMEM working set ~5 MiB)

try:  # CPU-only runs never lower the kernel; import is all they need
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


def _fmix32_u32(x):
    h = x + jnp.uint32(GEAR_SEED32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _make_scan_kernel(mask_s: int, mask_l: int, S: int, R: int):
    def kernel(nv_ref, halo0_ref, main_ref, prev_ref, wl_ref, ws_ref):
        b = pl.program_id(0)
        i = pl.program_id(1)
        # tile halo: previous tile's last 32 strip rows; tile 0 uses the
        # cross-strip halo input (real bytes of each strip's predecessor)
        halo = jnp.where(i > 0, prev_ref[0], halo0_ref[0])
        byts = jnp.concatenate([halo, main_ref[0]], axis=0)  # (R+32, 128) u8
        a = _fmix32_u32(byts.astype(jnp.uint32))
        # 32-tap windowed gear sum by log-doubling; shifts are sublane moves
        for t in range(5):
            s = 1 << t
            shifted = jnp.concatenate(
                [jnp.zeros((s, _LANES), dtype=jnp.uint32), a[:-s]], axis=0)
            a = a + (shifted << jnp.uint32(s))
        h = a[_HALO_ROWS:]  # (R, 128): main rows, taps all real (halo >= 31)
        pos = (jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 1) * S
               + i * R
               + jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 0))
        valid = pos < nv_ref[b]
        cand_l = (((h & jnp.uint32(mask_l)) == jnp.uint32(0)) & valid)
        cand_s = cand_l & ((h & jnp.uint32(mask_s)) == jnp.uint32(0))
        # pack 32 strip rows into one u32 word row (little-endian bit t =
        # row offset t), still lane-per-strip
        cl = cand_l.astype(jnp.uint32).reshape(R // 32, 32, _LANES)
        cs = cand_s.astype(jnp.uint32).reshape(R // 32, 32, _LANES)
        wl = jnp.zeros((R // 32, _LANES), dtype=jnp.uint32)
        ws = jnp.zeros((R // 32, _LANES), dtype=jnp.uint32)
        for t in range(32):
            wl = wl | (cl[:, t, :] << jnp.uint32(t))
            ws = ws | (cs[:, t, :] << jnp.uint32(t))
        wl_ref[0] = wl
        ws_ref[0] = ws

    return kernel


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l"))
def fused_candidate_words(ext_b: jnp.ndarray, nv_b: jnp.ndarray, *,
                          mask_s: int, mask_l: int):
    """``(B, 31+P) u8 -> ((B, P/32) u32, (B, P/32) u32)`` candidate words.

    Drop-in producer of the loose/strict packed candidate-bit arrays in
    position-major order (bit-identical to the XLA path's
    ``_pack_bits(cand)``).  ``P`` must be a multiple of 4096 (every
    production segment bucket is a power of two >= 64 KiB).
    """
    B, n = ext_b.shape
    P = n - 31
    assert P % (128 * 32) == 0, "P must be a multiple of 4096"
    S = P // _LANES
    R = _DEF_R if S % _DEF_R == 0 else S  # small buckets: one grid step
    # strip matrix: strips[b, r, l] = ext32[b, 32 + l*S + r]
    ext32 = jnp.pad(ext_b, ((0, 0), (1, 0)))
    body = ext32[:, 32:].reshape(B, _LANES, S).transpose(0, 2, 1)  # (B,S,128)
    # cross-strip halo: 32 bytes preceding each strip (strip l-1's tail;
    # strip 0 gets the spec zero byte + the row's 31 halo bytes)
    halo0 = jnp.concatenate(
        [ext32[:, :32, None], body[:, S - 32:, :-1]], axis=2)  # (B, 32, 128)
    nv = nv_b.astype(jnp.int32)

    kernel = _make_scan_kernel(mask_s, mask_l, S, R)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S // R),
        in_specs=[
            pl.BlockSpec((1, _HALO_ROWS, _LANES), lambda b, i, *_: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # previous tile's last 32 rows: block index in 32-row units,
            # clamped at 0 (tile 0 substitutes halo0 in-kernel)
            pl.BlockSpec((1, _HALO_ROWS, _LANES),
                         lambda b, i, *_: (b, jnp.maximum(
                             i * (R // _HALO_ROWS) - 1, 0), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, R // 32, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R // 32, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    wl, ws = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32)],
        grid_spec=grid_spec,
    )(nv, halo0, body, body)
    # strip-major -> position-major: word (w, l) covers positions
    # l*S + w*32 ..+31, so transposing to (l, w) and flattening yields
    # flat word index j with base position j*32 — the _pack_bits order.
    wl = wl.transpose(0, 2, 1).reshape(B, P // 32)
    ws = ws.transpose(0, 2, 1).reshape(B, P // 32)
    return wl, ws


@functools.lru_cache(maxsize=1)
def fused_scan_available() -> bool:
    """True when the fused scan kernel lowers and matches the XLA oracle
    on this runtime (checked once, on first use)."""
    import os

    if os.environ.get("BKW_FUSED", "1") == "0":
        return False
    if pl is None:
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover
        return False
    if platform not in ("tpu", "axon"):
        return False
    try:
        import numpy as np

        from .cdc_tpu import _candidate_words, _hash_ext_fast

        rng = np.random.default_rng(7)
        P = 64 * 1024
        ext = rng.integers(0, 256, (2, 31 + P), dtype=np.uint8)
        nv = np.array([P, P - 12345], dtype=np.int32)
        mask_s, mask_l = 0xFFF00000, 0xFFF80000
        wl, ws = fused_candidate_words(jnp.asarray(ext), jnp.asarray(nv),
                                       mask_s=mask_s, mask_l=mask_l)
        for r in range(2):
            h = _hash_ext_fast(jnp.asarray(ext[r]))
            rl, rs = _candidate_words(h, jnp.int32(nv[r]),
                                      jnp.uint32(mask_s), jnp.uint32(mask_l))
            if not (np.array_equal(np.asarray(wl[r]), np.asarray(rl))
                    and np.array_equal(np.asarray(ws[r]), np.asarray(rs))):
                return False
        return True
    except Exception:  # pragma: no cover - lowering failure
        return False
