"""Fused Mosaic/Pallas CDC scan: gear + ladder + candidate masks in VMEM.

The XLA scan (:func:`.cdc_tpu._hash_ext_fast`) pays HBM for every pass:
the fmix32 gear values and each of the five doubling-ladder passes
materialize a u32 array the size of 4x the stream (~45 bytes of HBM
traffic per stream byte, the measured ~200 ms/256 MiB floor).  This
kernel runs the whole scan per VMEM-resident tile and writes only the
packed candidate words (1/4 byte per stream byte), so HBM traffic drops
to ~1.3 bytes per stream byte — within striking distance of the
bandwidth floor.

Layout — the **strip decomposition** (PERF.md round-4 direction 2): the
P-byte stream is split into 128 contiguous strips of S = P/128 bytes;
strip ``l`` occupies lane ``l`` of a ``(S, 128)`` u8 array with stream
position ``l*S + r`` at row ``r``.  A shift by ``s`` positions is then a
pure **sublane** shift (rows), never a lane relayout — the failure mode
that sank round 3's flat-layout ladder kernel (~100-130 ms; PERF.md
"dead ends").  Each strip carries a 32-byte halo of the previous strip's
tail (real bytes, so hashes at strip starts are exact; only global
position 0 sees the spec's zero halo), and each grid step's tile carries
a 32-row halo of the previous tile via a second clamped BlockSpec.

Against the reference: this is the TPU replacement for the byte-at-a-time
FastCDC hot loop in ``client/src/backup/filesystem/dir_packer.rs:246-266``.

Output contract: ``(B, P/32) u32`` candidate words in **position-major
order** (word ``w`` bit ``t`` = candidate at position ``w*32 + t``) —
bit-identical to ``_pack_bits(cand)`` of the XLA path, so the two-level
compaction and the on-device cut selection consume either
interchangeably (tests assert equality; bench parity-gates end to end).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .gear import GEAR_SEED32

_LANES = 128
_HALO_ROWS = 32  # 31 context bytes + 1 alignment row (u8 tile = 32 sublanes)
_DEF_R = 2048  # strip rows per grid step (VMEM working set ~5 MiB)

try:  # CPU-only runs never lower the kernel; import is all they need
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


def _fmix32_u32(x):
    h = x + jnp.uint32(GEAR_SEED32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _make_scan_kernel_u32(mask_s: int, mask_l: int, S: int, R32: int):
    """v2 kernel: the stream stays packed 4 bytes/u32 END TO END.

    v1 transposes the full u8 stream into strip-major layout (the
    dominant XLA-side cost of the fused scan: a 256 MiB u8 relayout) and
    re-expands bytes to u32 inside the kernel.  Here the host-side
    transpose moves S/4 u32 rows (4x fewer elements, register-width
    lanes), and the kernel never materializes per-byte arrays at all:
    positions p = 4r+k live in four interleaved (rows, 128) u32 gear
    planes, a ladder shift by s byte positions is a plane permutation
    ``k -> (k-s) mod 4`` plus a sublane shift of ``(s+k'-k)/4`` rows,
    and the 32:1 bit-pack ORs plane bits at ``4r'+k``.  Bit-identical to
    v1/_pack_bits by construction; the import-time parity gate
    (:func:`fused_scan_available`) proves it on the live runtime before
    production use.
    """
    HR = _HALO_ROWS // 4  # 8 u32 rows = the 32-byte halo

    def kernel(nv_ref, halo0_ref, main_ref, prev_ref, wl_ref, ws_ref):
        b = pl.program_id(0)
        i = pl.program_id(1)
        halo = jnp.where(i > 0, prev_ref[0], halo0_ref[0])  # (HR, 128) u32
        w = jnp.concatenate([halo, main_ref[0]], axis=0)  # (R32+HR, 128)
        rows = R32 + HR
        # per-byte gear values, one plane per byte-in-word slot
        g = [_fmix32_u32((w >> jnp.uint32(8 * k)) & jnp.uint32(0xFF))
             for k in range(4)]
        # 32-tap windowed sum by log-doubling over byte positions
        a = list(g)
        for t in range(5):
            s = 1 << t
            nxt = []
            for k in range(4):
                src = (k - s) % 4
                d = (s + src - k) // 4
                if d:
                    sh = jnp.concatenate(
                        [jnp.zeros((d, _LANES), dtype=jnp.uint32),
                         a[src][:rows - d]], axis=0)
                else:
                    sh = a[src]
                nxt.append(a[k] + (sh << jnp.uint32(s)))
            a = nxt
        # main rows only; plane k holds positions 4r+k
        pos_r = (jax.lax.broadcasted_iota(jnp.int32, (R32, _LANES), 1) * S
                 + (i * R32
                    + jax.lax.broadcasted_iota(jnp.int32, (R32, _LANES), 0))
                 * 4)
        n = nv_ref[b]
        wl = jnp.zeros((R32 // 8, _LANES), dtype=jnp.uint32)
        ws = jnp.zeros((R32 // 8, _LANES), dtype=jnp.uint32)
        for k in range(4):
            h = a[k][HR:]
            valid = (pos_r + k) < n
            cl = (((h & jnp.uint32(mask_l)) == jnp.uint32(0)) & valid)
            cs = cl & ((h & jnp.uint32(mask_s)) == jnp.uint32(0))
            cl3 = cl.astype(jnp.uint32).reshape(R32 // 8, 8, _LANES)
            cs3 = cs.astype(jnp.uint32).reshape(R32 // 8, 8, _LANES)
            for r2 in range(8):
                wl = wl | (cl3[:, r2, :] << jnp.uint32(4 * r2 + k))
                ws = ws | (cs3[:, r2, :] << jnp.uint32(4 * r2 + k))
        wl_ref[0] = wl
        ws_ref[0] = ws

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("mask_s", "mask_l", "interpret"))
def _fused_candidate_words_u32(ext_b: jnp.ndarray, nv_b: jnp.ndarray, *,
                               mask_s: int, mask_l: int,
                               interpret: bool = False):
    """v2 driver: packed-u32 strip layout (see :func:`_make_scan_kernel_u32`).

    Same contract as :func:`fused_candidate_words` v1: position-major
    candidate words, bit-identical to the XLA ``_pack_bits`` path.
    """
    B, n = ext_b.shape
    P = n - 31
    assert P % (128 * 32) == 0, "P must be a multiple of 4096"
    S = P // _LANES
    S32 = S // 4
    R32 = (_DEF_R // 4) if S32 % (_DEF_R // 4) == 0 else S32
    HR = _HALO_ROWS // 4
    ext32 = jnp.pad(ext_b, ((0, 0), (1, 0)))
    # strip-contiguous view, packed 4 bytes/word: FREE reshape+bitcast,
    # then a u32 transpose (4x fewer elements than v1's u8 transpose)
    body_w = jax.lax.bitcast_convert_type(
        ext32[:, 32:].reshape(B, _LANES, S32, 4), jnp.uint32)  # (B,128,S32)
    body = body_w.transpose(0, 2, 1)  # (B, S32, 128)
    head_w = jax.lax.bitcast_convert_type(
        ext32[:, :32].reshape(B, HR, 4), jnp.uint32)  # (B, HR)
    halo0 = jnp.concatenate(
        [head_w[:, :, None], body[:, S32 - HR:, :-1]], axis=2)  # (B,HR,128)
    nv = nv_b.astype(jnp.int32)

    kernel = _make_scan_kernel_u32(mask_s, mask_l, S, R32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S32 // R32),
        in_specs=[
            pl.BlockSpec((1, HR, _LANES), lambda b, i, *_: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R32, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, HR, _LANES),
                         lambda b, i, *_: (b, jnp.maximum(
                             i * (R32 // HR) - 1, 0), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, R32 // 8, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R32 // 8, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    wl, ws = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32)],
        grid_spec=grid_spec,
        interpret=interpret,
    )(nv, halo0, body, body)
    wl = wl.transpose(0, 2, 1).reshape(B, P // 32)
    ws = ws.transpose(0, 2, 1).reshape(B, P // 32)
    return wl, ws


def _make_scan_kernel(mask_s: int, mask_l: int, S: int, R: int):
    def kernel(nv_ref, halo0_ref, main_ref, prev_ref, wl_ref, ws_ref):
        b = pl.program_id(0)
        i = pl.program_id(1)
        # tile halo: previous tile's last 32 strip rows; tile 0 uses the
        # cross-strip halo input (real bytes of each strip's predecessor)
        halo = jnp.where(i > 0, prev_ref[0], halo0_ref[0])
        byts = jnp.concatenate([halo, main_ref[0]], axis=0)  # (R+32, 128) u8
        a = _fmix32_u32(byts.astype(jnp.uint32))
        # 32-tap windowed gear sum by log-doubling; shifts are sublane moves
        for t in range(5):
            s = 1 << t
            shifted = jnp.concatenate(
                [jnp.zeros((s, _LANES), dtype=jnp.uint32), a[:-s]], axis=0)
            a = a + (shifted << jnp.uint32(s))
        h = a[_HALO_ROWS:]  # (R, 128): main rows, taps all real (halo >= 31)
        pos = (jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 1) * S
               + i * R
               + jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 0))
        valid = pos < nv_ref[b]
        cand_l = (((h & jnp.uint32(mask_l)) == jnp.uint32(0)) & valid)
        cand_s = cand_l & ((h & jnp.uint32(mask_s)) == jnp.uint32(0))
        # pack 32 strip rows into one u32 word row (little-endian bit t =
        # row offset t), still lane-per-strip
        cl = cand_l.astype(jnp.uint32).reshape(R // 32, 32, _LANES)
        cs = cand_s.astype(jnp.uint32).reshape(R // 32, 32, _LANES)
        wl = jnp.zeros((R // 32, _LANES), dtype=jnp.uint32)
        ws = jnp.zeros((R // 32, _LANES), dtype=jnp.uint32)
        for t in range(32):
            wl = wl | (cl[:, t, :] << jnp.uint32(t))
            ws = ws | (cs[:, t, :] << jnp.uint32(t))
        wl_ref[0] = wl
        ws_ref[0] = ws

    return kernel


# selected kernel variant; decided ONCE by fused_scan_available()'s
# parity ladder before any production trace (the dispatcher below reads
# it at trace time, so flipping it after a trace would go unnoticed —
# DevicePipeline/callers always probe first)
_V2_SELECTED = False


def fused_candidate_words(ext_b: jnp.ndarray, nv_b: jnp.ndarray, *,
                          mask_s: int, mask_l: int):
    """``(B, 31+P) u8 -> ((B, P/32) u32, (B, P/32) u32)`` candidate words.

    Trace-time dispatcher over the kernel variants: v2 (packed-u32
    strips, no byte-stream relayout) when the parity ladder selected it
    on this runtime, else v1.  Both are bit-identical to the XLA path's
    ``_pack_bits(cand)``; ``P`` must be a multiple of 4096.
    """
    # run the ladder if no caller has yet (lru_cached: once per process)
    # so standalone probes/scripts measure the variant production uses
    fused_scan_available()
    if _V2_SELECTED:
        return _fused_candidate_words_u32(ext_b, nv_b,
                                          mask_s=mask_s, mask_l=mask_l)
    return _fused_candidate_words_v1(ext_b, nv_b,
                                     mask_s=mask_s, mask_l=mask_l)


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l"))
def _fused_candidate_words_v1(ext_b: jnp.ndarray, nv_b: jnp.ndarray, *,
                              mask_s: int, mask_l: int):
    """v1 driver: u8 strip layout (full-stream byte transpose on the
    XLA side; see module docstring)."""
    B, n = ext_b.shape
    P = n - 31
    assert P % (128 * 32) == 0, "P must be a multiple of 4096"
    S = P // _LANES
    R = _DEF_R if S % _DEF_R == 0 else S  # small buckets: one grid step
    # strip matrix: strips[b, r, l] = ext32[b, 32 + l*S + r]
    ext32 = jnp.pad(ext_b, ((0, 0), (1, 0)))
    body = ext32[:, 32:].reshape(B, _LANES, S).transpose(0, 2, 1)  # (B,S,128)
    # cross-strip halo: 32 bytes preceding each strip (strip l-1's tail;
    # strip 0 gets the spec zero byte + the row's 31 halo bytes)
    halo0 = jnp.concatenate(
        [ext32[:, :32, None], body[:, S - 32:, :-1]], axis=2)  # (B, 32, 128)
    nv = nv_b.astype(jnp.int32)

    kernel = _make_scan_kernel(mask_s, mask_l, S, R)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S // R),
        in_specs=[
            pl.BlockSpec((1, _HALO_ROWS, _LANES), lambda b, i, *_: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # previous tile's last 32 rows: block index in 32-row units,
            # clamped at 0 (tile 0 substitutes halo0 in-kernel)
            pl.BlockSpec((1, _HALO_ROWS, _LANES),
                         lambda b, i, *_: (b, jnp.maximum(
                             i * (R // _HALO_ROWS) - 1, 0), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, R // 32, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R // 32, _LANES), lambda b, i, *_: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    wl, ws = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((B, S // 32, _LANES), jnp.uint32)],
        grid_spec=grid_spec,
    )(nv, halo0, body, body)
    # strip-major -> position-major: word (w, l) covers positions
    # l*S + w*32 ..+31, so transposing to (l, w) and flattening yields
    # flat word index j with base position j*32 — the _pack_bits order.
    wl = wl.transpose(0, 2, 1).reshape(B, P // 32)
    ws = ws.transpose(0, 2, 1).reshape(B, P // 32)
    return wl, ws


def _variant_parity_ok(fn) -> bool:
    """Does ``fn`` (a candidate-words producer) match the XLA oracle on
    the live runtime?  Lowering failures count as mismatch."""
    try:
        import numpy as np

        from .cdc_tpu import _candidate_words, _hash_ext_fast

        rng = np.random.default_rng(7)
        # 1 MiB rows = 4 grid steps for both variants (v1 R=2048 of
        # S=8192 rows; v2 R32=512 of S32=2048): the probe must exercise
        # the multi-tile prev-halo path, not just tile 0's halo0 branch
        P = 1 << 20
        ext = rng.integers(0, 256, (2, 31 + P), dtype=np.uint8)
        nv = np.array([P, P - 12345], dtype=np.int32)
        mask_s, mask_l = 0xFFF00000, 0xFFF80000
        wl, ws = fn(jnp.asarray(ext), jnp.asarray(nv),
                    mask_s=mask_s, mask_l=mask_l)
        for r in range(2):
            h = _hash_ext_fast(jnp.asarray(ext[r]))
            rl, rs = _candidate_words(h, jnp.int32(nv[r]),
                                      jnp.uint32(mask_s), jnp.uint32(mask_l))
            if not (np.array_equal(np.asarray(wl[r]), np.asarray(rl))
                    and np.array_equal(np.asarray(ws[r]), np.asarray(rs))):
                return False
        return True
    except Exception:  # pragma: no cover - lowering failure
        return False


@functools.lru_cache(maxsize=1)
def fused_scan_available() -> bool:
    """True when a fused scan kernel lowers and matches the XLA oracle on
    this runtime (checked once, on first use).

    Variant ladder: v2 (packed-u32) is preferred and selected only if it
    proves bit-parity here; otherwise v1 is probed.  A variant that
    mis-lowers on some runtime therefore degrades throughput, never
    correctness.
    """
    import os

    global _V2_SELECTED
    if os.environ.get("BKW_FUSED", "1") == "0":
        return False
    if pl is None:
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover
        return False
    if platform not in ("tpu", "axon"):
        return False
    if (os.environ.get("BKW_FUSED_V2", "1") != "0"
            and _variant_parity_ok(_fused_candidate_words_u32)):
        _V2_SELECTED = True
        return True
    _V2_SELECTED = False
    return _variant_parity_ok(_fused_candidate_words_v1)
