"""ChunkerBackend: one dedup-pipeline contract, CPU and TPU executions.

``BASELINE.json`` pins the seam: a backend turns raw bytes into chunk
manifests (cut points + BLAKE3 fingerprints); everything above it — snapshot
builder, packfiles, peer exchange — is backend-agnostic.  The reference has
only the sequential CPU form (``dir_packer.rs:246-311``); here:

* :class:`CpuBackend` — the numpy oracle pipeline (also the honest baseline
  for the 10x target; see ``bench.py``).
* :class:`TpuBackend` — device gear-scan (:mod:`.cdc_tpu`) + batched
  device BLAKE3 (:mod:`.blake3_tpu`).  Files are processed as batches so
  fingerprinting amortizes into a few bucketed compiles.
* :func:`select_backend` — picks TPU when an accelerator is attached,
  otherwise CPU; both produce bit-identical manifests, so the choice is
  pure policy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..erasure import gf_cpu
from ..obs import profile as obs_profile
from .blake3_cpu import blake3_many
from .blake3_tpu import blake3_many_tpu
from .cdc_cpu import chunk_stream as chunk_stream_cpu
from .cdc_tpu import TpuCdcScanner
from .gear import CDCParams


@dataclass(frozen=True)
class ChunkRef:
    """One chunk of one stream: location + fingerprint."""

    offset: int
    length: int
    hash: bytes


class ChunkerBackend:
    """Contract: ``manifest(data) -> [ChunkRef...]``, batched over streams."""

    name = "abstract"

    def chunk(self, data) -> List[tuple]:
        raise NotImplementedError

    def digest_many(self, datas: Sequence[bytes]) -> List[bytes]:
        raise NotImplementedError

    # --- erasure coding (erasure/; same routing pattern as digest_many:
    # the numpy oracle is the default, TpuBackend overrides with the
    # batched device kernel, and both are bit-identical) ------------------

    def encode_shards(self, stripes, m: int):
        """Reed-Solomon parity: (B, k, L) data shards -> (B, m, L)."""
        stripes = np.asarray(stripes, dtype=np.uint8)
        b, k, ln = stripes.shape
        if m == 0 or b == 0:
            return np.zeros((b, m, ln), dtype=np.uint8)
        parity_rows = gf_cpu.generator_matrix(k, m)[k:]
        return np.stack([gf_cpu.gf_matmul(parity_rows, s) for s in stripes])

    def decode_shards(self, stripes, k: int, m: int, present):
        """Recover data shards from survivors: ``stripes`` is (B, k, L)
        with rows ordered by the sorted ``present`` indices."""
        stripes = np.asarray(stripes, dtype=np.uint8)
        if stripes.shape[0] == 0:
            return stripes
        cols = sorted(set(int(i) for i in present))
        rec = gf_cpu.decode_matrix(k, m, cols)[:, cols]
        return np.stack([gf_cpu.gf_matmul(rec, s) for s in stripes])

    def manifest_many(self, streams: Sequence[bytes]) -> List[List[ChunkRef]]:
        """Chunk + fingerprint a batch of streams in one pipeline pass.

        Dispatch accounting (obs/profile.py, exact on the CPU fallback):
        one scan + one select per stream, one gather per stream that
        produced chunks, one batched digest per call with pieces."""
        all_chunks = []  # (stream_idx, offset, length)
        pieces = []
        for i, data in enumerate(streams):
            n = len(data)
            obs_profile.dispatch("scan", actual_bytes=n, padded_bytes=n)
            obs_profile.dispatch("select", actual_bytes=n, padded_bytes=n)
            gathered = 0
            for off, ln in self.chunk(data):
                all_chunks.append((i, off, ln))
                pieces.append(bytes(data[off:off + ln]))
                gathered += ln
            if gathered:
                obs_profile.dispatch("gather", actual_bytes=gathered,
                                     padded_bytes=gathered)
        if pieces:
            total = sum(len(p) for p in pieces)
            obs_profile.dispatch("digest", actual_bytes=total,
                                 padded_bytes=total)
        digests = self.digest_many(pieces)
        out: List[List[ChunkRef]] = [[] for _ in streams]
        for (i, off, ln), h in zip(all_chunks, digests):
            out[i].append(ChunkRef(offset=off, length=ln, hash=h))
        return out

    def manifest(self, data) -> List[ChunkRef]:
        return self.manifest_many([data])[0]

    def manifest_many_classified(self, streams: Sequence[bytes], dedup):
        """Manifest + dedup-classify one batch in a single call.

        Returns ``(manifests, hints)`` where ``hints`` aligns with the
        flattened refs (row-major over streams) — the packer's dup-hint
        contract.  Base backends run the two passes back to back against
        ``dedup.classify_insert``; :class:`TpuBackend` overrides with the
        mesh pipeline, which hands digests to the sharded table on device
        mid-manifest."""
        out = self.manifest_many(streams)
        hashes = [r.hash for refs in out for r in refs]
        if hashes:
            obs_profile.dispatch("index", actual_bytes=32 * len(hashes),
                                 padded_bytes=32 * len(hashes))
        return out, dedup.classify_insert(hashes)

    def manifest_stream(self, read: Callable[[int], bytes],
                        segment_bytes: int = 256 * 1024 * 1024,
                        emit: Optional[Callable] = None) -> List[ChunkRef]:
        """Chunk + fingerprint a stream without holding it in memory.

        ``read(n)`` returns up to ``n`` bytes ('' at EOF).  Works because a
        CDC cut depends only on bytes up to the cut: chunking a prefix gives
        final chunks except the last (whose end might be EOF-forced), which
        is carried into the next segment.  Bit-identical to chunking the
        whole stream at once.  ``emit(ref, chunk_bytes)`` fires per final
        chunk as soon as it is fingerprinted (lets the caller pack blobs
        incrementally); the returned list is the full manifest.
        """
        out: List[ChunkRef] = []
        carry = b""
        base = 0  # absolute offset of carry[0]
        while True:
            segment = read(segment_bytes)
            eof = not segment
            buf = carry + segment
            chunks = self.chunk(buf)
            obs_profile.dispatch("scan", actual_bytes=len(buf),
                                 padded_bytes=len(buf))
            obs_profile.dispatch("select", actual_bytes=len(buf),
                                 padded_bytes=len(buf))
            if eof:
                final, carry, next_base = chunks, b"", base
            elif len(chunks) > 1:
                final = chunks[:-1]
                last_off = chunks[-1][0]
                carry, next_base = buf[last_off:], base + last_off
            else:
                # single chunk that may still grow: carry everything
                final, carry, next_base = [], buf, base
            pieces = [buf[off:off + ln] for off, ln in final]
            if pieces:
                total = sum(len(p) for p in pieces)
                obs_profile.dispatch("gather", actual_bytes=total,
                                     padded_bytes=total)
                obs_profile.dispatch("digest", actual_bytes=total,
                                     padded_bytes=total)
            for h, (off, ln), data in zip(self.digest_many(pieces), final,
                                          pieces):
                ref = ChunkRef(offset=base + off, length=ln, hash=h)
                out.append(ref)
                if emit is not None:
                    emit(ref, data)
            base = next_base
            if eof:
                break
        return out


class CpuBackend(ChunkerBackend):
    name = "cpu"

    def __init__(self, params: Optional[CDCParams] = None):
        self.params = params or CDCParams()

    def chunk(self, data):
        return chunk_stream_cpu(data, self.params)

    def digest_many(self, datas):
        return blake3_many(datas)


class NativeBackend(ChunkerBackend):
    """Host fast path: the C pipeline (``native/cdc_blake3.c``) via ctypes.

    Same bit-exact manifests as :class:`CpuBackend` (tests pin C vs spec
    oracle) at ~30x the numpy oracle's throughput — the engine's default
    on hosts without an accelerator.  Raises
    :class:`backuwup_tpu.native.NativeUnavailable` at construction when no
    C toolchain/library is present; callers fall back to CpuBackend.
    """

    name = "native"

    def __init__(self, params: Optional[CDCParams] = None):
        from .. import native
        self.params = params or CDCParams()
        native.load()  # raises NativeUnavailable without a toolchain
        self._native = native

    def chunk(self, data):
        return self._native.chunk_native(data, self.params)

    def digest_many(self, datas):
        return [self._native.blake3_native(bytes(d)) for d in datas]

    def manifest_many(self, streams):
        out = []
        for data in streams:
            chunks, digests = self._native.manifest_native(
                bytes(data), self.params)
            # the C pipeline fuses the whole chain into one host call per
            # stream: it counts once under every stage
            n = len(data)
            for stage in ("scan", "select", "gather", "digest"):
                obs_profile.dispatch(stage, actual_bytes=n, padded_bytes=n)
            out.append([ChunkRef(offset=off, length=ln, hash=h)
                        for (off, ln), h in zip(chunks, digests)])
        return out


class TpuBackend(ChunkerBackend):
    """Device-resident execution: ``manifest_many`` stages each batch into
    HBM once and runs scan -> cut -> HBM-to-HBM chunk gather -> batched
    digest (:meth:`DevicePipeline.manifest_batch`) — no per-chunk host
    slicing.  ``chunk``/``digest_many`` remain for the streaming path and
    as the op-level seams the parity tests pin."""

    name = "tpu"

    def __init__(self, params: Optional[CDCParams] = None):
        self.params = params or CDCParams()
        self._scanner = TpuCdcScanner(self.params)
        self._pipeline = None
        self._mesh = None
        self._mesh_axis = "data"

    @property
    def pipeline(self):
        if self._pipeline is None:
            from .pipeline import CHUNK_LEN, DevicePipeline
            l_bucket = max(16, -(-self.params.max_size // CHUNK_LEN))
            self._pipeline = DevicePipeline(self.params, l_bucket=l_bucket,
                                            mesh=self._mesh,
                                            mesh_axis=self._mesh_axis)
        return self._pipeline

    def attach_mesh(self, mesh, axis: str = "data") -> None:
        """Share the dedup mesh with the manifest pipeline so the
        classified path shards its batches over the same axis and can
        hand digest accumulators to the table without leaving the mesh
        (the engine calls this when it builds its MeshDedupIndex)."""
        self._mesh = mesh
        self._mesh_axis = axis
        if self._pipeline is not None and self._pipeline.mesh is None:
            self._pipeline.mesh = mesh
            self._pipeline.mesh_axis = axis

    def chunk(self, data):
        return self._scanner.chunk_stream(data)

    def digest_many(self, datas):
        return blake3_many_tpu(datas)

    def encode_shards(self, stripes, m):
        from ..erasure import rs_tpu
        return rs_tpu.encode_stripes(stripes, m)

    def decode_shards(self, stripes, k, m, present):
        from ..erasure import rs_tpu
        return rs_tpu.decode_stripes(stripes, k, m, present)

    def manifest_many(self, streams):
        results = self.pipeline.manifest_batch(streams)
        out = []
        for chunks, digests in results:
            out.append([
                ChunkRef(offset=off, length=ln, hash=digests[k].tobytes())
                for k, (off, ln) in enumerate(chunks)])
        return out

    def manifest_many_classified(self, streams, dedup):
        """Mesh-sharded manifest with the on-device dedup handoff: the
        digest accumulator feeds ``ShardedDedupIndex.insert_device``
        without a host round trip, and the downloaded found-flags become
        the packer's dup hints via ``resolve_hints``.  Falls back to the
        two-pass base when ``dedup`` has no device handoff or rides a
        different mesh than the pipeline."""
        pipe = self.pipeline
        if getattr(dedup, "classify_dispatch", None) is None:
            return super().manifest_many_classified(streams, dedup)
        if pipe.mesh is None:
            pipe.mesh = dedup.mesh
            pipe.mesh_axis = dedup.axis
        if pipe.mesh is not dedup.mesh or pipe.mesh_axis != dedup.axis:
            return super().manifest_many_classified(streams, dedup)
        results, rowflags = pipe.manifest_batch_classified(streams, dedup)
        out = []
        hashes: List[bytes] = []
        raw: List[Optional[bool]] = []
        for (chunks, digests), fl in zip(results, rowflags):
            refs = [ChunkRef(offset=off, length=ln,
                             hash=digests[k].tobytes())
                    for k, (off, ln) in enumerate(chunks)]
            out.append(refs)
            for k, ref in enumerate(refs):
                hashes.append(ref.hash)
                raw.append(None if fl is None else bool(fl[k]))
        return out, dedup.resolve_hints(hashes, raw)


def _accelerator_attached() -> bool:
    try:
        import jax
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def select_backend(prefer: Optional[str] = None,
                   params: Optional[CDCParams] = None) -> ChunkerBackend:
    """``prefer`` in {"cpu", "native", "tpu", None}; None = auto-detect
    (TPU if an accelerator is attached, else the native C pipeline, else
    the numpy oracle)."""
    if prefer == "cpu":
        return CpuBackend(params)
    if prefer == "native":
        return NativeBackend(params)
    if prefer == "tpu":
        return TpuBackend(params)
    if _accelerator_attached():
        return TpuBackend(params)
    from .. import native
    try:
        return NativeBackend(params)
    except native.NativeUnavailable:
        return CpuBackend(params)
