"""Flat leaf-pool BLAKE3: digest every chunk of a batch in ONE program.

The class-tile digest stage (``manifest_device.scan_digest_batch``) pays
per-class costs ~12 times per batch: a full-length ``nonzero`` compaction,
a padded gather at the class span, an XLA word-prep pass, a separate
Pallas grid, and a scatter — and PERF.md's stage table shows that on
hardware this dispatch + word-prep overhead, not leaf compute, dominates
the digest section (~60-135 ms of a ~100-170 ms segment).  The reference
has no equivalent stage at all — it hashes chunks one at a time on the
CPU (``dir_packer.rs:285-311``); this module is how the same work maps
onto a TPU without the reference's serial structure.

Design: decompose EVERY chunk into its 1 KiB BLAKE3 leaves and run one
flat pool of leaves through a single scan:

1. **Leaf plan, on device.**  A chunk of ``l`` bytes at offset ``o``
   owns ``ceil(l/1024)`` consecutive pool lanes; lane ``k`` covers bytes
   ``[o + 1024k, o + 1024k + min(1024, l - 1024k))`` with BLAKE3 chunk
   counter ``k``.  Ownership is materialized with one scatter of chunk
   ids at each chunk's first lane + a running max — no per-class
   compaction, no searchsorted.
2. **One leaf scan.**  The pool gathers once (1 KiB per lane), word-preps
   once, and runs ONE Pallas grid (or the XLA fallback) over all lanes.
   Padding waste is the final partial leaf of each chunk — near-zero,
   where the class tiles padded every chunk to its class span (~1.2-1.5x
   measured).  The leaf scan is ~94% of single-chunk BLAKE3 compute
   (16 blocks/leaf vs 1 merge per leaf pair), so this stage holds
   essentially all the FLOPs.
3. **Tiny tiered tree.**  Leaf chaining values (32 B/leaf — 32x smaller
   than payload) are gathered per chunk into 2-3 geometric leaf-count
   tiers and pair-merged by :func:`blake3_tpu.tree_reduce_cvs`; tier
   padding costs ~1/16 of leaf work at worst, so coarse tiers are fine
   where payload-level class tiles were not.  Tier capacities cascade
   upward exactly like the class cascade (excess hands to the next tier;
   only terminus overflow aborts to the host-tiled path, bit-exact
   either way).

Digests are bit-identical to :mod:`backuwup_tpu.ops.blake3_cpu` (the
spec oracle) — property-tested in interpret mode and gated at runtime by
``DevicePipeline``'s parity ladder before production use.

Mesh usage (``manifest_device.scan_digest_batch_pool_mesh``): each shard
runs its own pool over its row slice with PER-SHARD ``leaf_cap``/``tiers``
sized for ``B/D`` rows, so the ``(1,)`` overflow flag widens to one flag
per shard and adversarial data re-runs only that shard's rows.  Two
accumulator invariants the dedup handoff leans on: (a) ``acc`` is
zero-initialized and only cascade-placed chunks scatter into it, so
unplaced/invalid lanes stay all-zero — exactly the probe kernel's
padding-query convention; (b) when the leaf pool itself overflows
(``pool_short > 0``) the affected chunks still cascade-place but carry
WRONG digests — the shard's overflow flag forces the host-tiled re-run
for its manifests, and any wrong keys the handoff inserted are inert
junk (2^-128 collision odds against real BLAKE3 prefixes).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .blake3_cpu import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    MAX_LEAVES_PER_CHUNK,
    ROOT,
)
from .blake3_tpu import (
    _IV_NP,
    _bytes_to_words,
    _compress_cols,
    _leaf_scan_pallas,
    tree_reduce_cvs,
)


def _leaf_scan_xla_flat(words_flat: jnp.ndarray, nb: jnp.ndarray,
                        lbl: jnp.ndarray, counter: jnp.ndarray):
    """Flat-lane XLA leaf scan: (lanes, 16, 16) u32 -> (lanes, 8) cv +
    (lanes, 8) penultimate cv (state before the last block's compression,
    for the single-leaf ROOT recompute).  Fallback when the Pallas kernel
    is unavailable; masking mirrors ``digest_padded``'s leaf loop.
    """
    lanes = words_flat.shape[0]
    zeros = jnp.zeros(lanes, dtype=jnp.uint32)
    iv_cols = [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), (lanes,)) + zeros
               for i in range(8)]
    counter = counter.astype(jnp.uint32)

    def body(blk, carry):
        cv, cv_pre = carry
        mslab = jax.lax.dynamic_index_in_dim(words_flat, blk, axis=1,
                                             keepdims=False)  # (lanes, 16)
        m = [mslab[:, w] for w in range(16)]
        active = blk < nb
        is_last = blk == nb - 1
        flags = jnp.where(blk == 0, jnp.uint32(CHUNK_START), jnp.uint32(0))
        flags = jnp.where(is_last, flags | jnp.uint32(CHUNK_END), flags)
        blen = jnp.where(is_last, lbl, jnp.uint32(BLOCK_LEN))
        cv_pre = [jnp.where(is_last, c, p) for c, p in zip(cv, cv_pre)]
        out = _compress_cols(cv, m, counter, zeros, blen, flags)
        cv = [jnp.where(active, o, c) for o, c in zip(out, cv)]
        return cv, cv_pre

    cv, cv_pre = jax.lax.fori_loop(0, MAX_LEAVES_PER_CHUNK, body,
                                   (iv_cols, list(iv_cols)))
    return jnp.stack(cv, axis=1), jnp.stack(cv_pre, axis=1)


@functools.lru_cache(maxsize=32)
def tier_spans(max_leaves: int, n_tiers: int = 3) -> Tuple[int, ...]:
    """Geometric leaf-count tier grid ending at ``max_leaves``.

    Tree padding costs ≤ span/actual of ~1/16 of leaf compute, so a
    2x-geometric grid (vs the payload path's ~12 linear classes) keeps
    total tree overcompute a few percent while cutting the number of
    tree tiles to 2-3.
    """
    spans = [max_leaves]
    while len(spans) < n_tiers and spans[-1] > 8:
        spans.append(max(8, -(-spans[-1] // 2 // 8) * 8))
    return tuple(reversed([s for i, s in enumerate(spans)
                           if i == 0 or s < spans[i - 1]]))


def leaf_capacity(total_padded_bytes: int, max_chunks: int) -> int:
    """Structural upper bound on pool lanes: every payload byte plus at
    most one partial leaf per chunk.  No distribution calibration — the
    pool, unlike the class tiles, cannot overflow on adversarial data."""
    cap = total_padded_bytes // CHUNK_LEN + max_chunks
    return -(-cap // 512) * 512


@functools.partial(jax.jit, static_argnames=(
    "leaf_cap", "tiers", "pallas", "interpret"))
def pool_digest(flat: jnp.ndarray, offs: jnp.ndarray, lens: jnp.ndarray, *,
                leaf_cap: int, tiers: Tuple[Tuple[int, int], ...],
                pallas: bool = False, interpret: bool = False):
    """Digest ``C`` chunks carved from one resident byte pool.

    ``flat``: (N,) u8 with >= CHUNK_LEN slack bytes after the last chunk
    (fixed-span gathers must never clamp); ``offs``/``lens``: (C,) i32
    absolute byte offsets / lengths (len <= 0 marks an unused slot).
    ``tiers``: ((leaf_span, chunk_capacity), ...) ascending by span; the
    last span must be >= the largest possible leaf count.

    Returns ``((C, 8) u32 root chaining values, (1,) i32 overflow)``;
    overflow counts chunks the tier cascade could not place plus any
    pool-lane shortfall (caller falls back, output stays bit-exact).
    """
    C = offs.shape[0]
    offs = offs.astype(jnp.int32)
    lens = lens.astype(jnp.int32)
    valid = lens > 0
    lv = jnp.where(valid, -(-lens // CHUNK_LEN), 0)  # leaves per chunk
    base = jnp.cumsum(lv) - lv  # exclusive prefix
    total = base[-1] + lv[-1]
    pool_short = jnp.maximum(total - leaf_cap, 0)

    # --- ownership fill: one scatter + running max -------------------------
    start_idx = jnp.where(valid, jnp.minimum(base, leaf_cap - 1), leaf_cap)
    marker = jnp.full(leaf_cap, -1, dtype=jnp.int32)
    marker = marker.at[start_idx].max(jnp.arange(C, dtype=jnp.int32),
                                      mode="drop")
    owner = jax.lax.associative_scan(jnp.maximum, marker)  # (leaf_cap,)
    oc = jnp.clip(owner, 0, C - 1)
    lane = jnp.arange(leaf_cap, dtype=jnp.int32)
    k = lane - base[oc]
    active = (owner >= 0) & (k < lv[oc])
    nbytes = jnp.where(active,
                       jnp.clip(lens[oc] - k * CHUNK_LEN, 0, CHUNK_LEN), 0)

    # --- one gather + one word-prep + ONE leaf scan ------------------------
    off = jnp.where(active, offs[oc] + k * CHUNK_LEN, 0)

    def one(o):
        return jax.lax.dynamic_slice(flat, (o,), (CHUNK_LEN,))

    data = jax.vmap(one)(off)  # (leaf_cap, 1024)
    data = jnp.where(
        jnp.arange(CHUNK_LEN, dtype=jnp.int32)[None, :] < nbytes[:, None],
        data, jnp.uint8(0))
    words = _bytes_to_words(
        data.reshape(leaf_cap, MAX_LEAVES_PER_CHUNK, BLOCK_LEN))
    nb = jnp.maximum(1, -(-nbytes // BLOCK_LEN))
    lbl = (nbytes - (nb - 1) * BLOCK_LEN).astype(jnp.uint32)
    kc = jnp.maximum(k, 0)
    if pallas:
        cvp_mat, cvpre_mat = _leaf_scan_pallas(words, nb, lbl, kc,
                                               interpret=interpret)
    else:
        cvp_mat, cvpre_mat = _leaf_scan_xla_flat(words, nb, lbl,
                                                 kc.astype(jnp.uint32))
    # slack rows so fixed-span tier gathers never clamp
    top_span = tiers[-1][0]
    cv_pool = jnp.pad(cvp_mat, ((0, top_span), (0, 0)))

    # --- tiered tree reduction over leaf CVs -------------------------------
    cls = jnp.zeros(C, dtype=jnp.int32)
    for span, _cap in tiers[:-1]:
        cls = cls + (lv > span).astype(jnp.int32)
    acc = jnp.zeros((C, 8), dtype=jnp.uint32)
    carry = jnp.zeros(C, dtype=bool)
    for i, (span, cap) in enumerate(tiers):
        if cap == 0:
            carry = carry | (valid & (cls == i))
            continue
        mine = valid & ((cls == i) | carry)
        rank = jnp.cumsum(mine.astype(jnp.int32)) - 1
        take = mine & (rank < cap)
        carry = mine & ~take
        (idx,) = jnp.nonzero(take, size=cap, fill_value=C)
        safe = jnp.clip(idx, 0, C - 1)
        got = idx < C
        b = jnp.where(got, jnp.minimum(base[safe], leaf_cap - 1), 0)
        cnt = jnp.where(got, lv[safe], 1)

        def tile(bb):
            return jax.lax.dynamic_slice(cv_pool, (bb, 0), (span, 8))

        leaf_mat = jax.vmap(tile)(b)  # (cap, span, 8)
        leaf_cols = [leaf_mat[:, :, ci] for ci in range(8)]
        # single-leaf chunks: recompress leaf 0's final block with ROOT
        nb0 = nb[b]
        m0 = jnp.take_along_axis(
            words[b], (nb0 - 1)[:, None, None], axis=1)[:, 0]  # (cap, 16)
        flags0 = (jnp.where(nb0 == 1, jnp.uint32(CHUNK_START), jnp.uint32(0))
                  | jnp.uint32(CHUNK_END) | jnp.uint32(ROOT))
        zb = jnp.zeros(cap, dtype=jnp.uint32)
        root_single = _compress_cols(
            [cvpre_mat[b, ci] for ci in range(8)],
            [m0[:, w] for w in range(16)], zb, zb, lbl[b], flags0)
        root_seed = [jnp.where(cnt == 1, rs, jnp.uint32(0))
                     for rs in root_single]
        out_tile = tree_reduce_cvs(leaf_cols, cnt, root_seed)  # (cap, 8)
        # fill slots keep idx == C: out of range -> dropped (clipping to
        # C-1 would duplicate-write a real chunk's row, undefined order)
        acc = acc.at[idx].set(out_tile, mode="drop")
    ovf = (jnp.sum(carry.astype(jnp.int32)) + pool_short)[None]
    return acc, ovf


@functools.lru_cache(maxsize=4)
def pool_digest_available(pallas: bool) -> bool:
    """True when the compiled leaf-pool path matches the HOST spec oracle
    on the live runtime.  Same posture as ``pallas_digest_available`` /
    ``fused_scan_available``: a runtime where this program mis-lowers
    loses speed (falls back to the class tiles), never correctness.
    """
    import os

    if os.environ.get("BKW_POOL_DIGEST", "1") == "0":
        return False
    try:
        from .blake3_cpu import blake3_hash
        rng = np.random.default_rng(7)
        flat = rng.integers(0, 256, 256 * 1024, dtype=np.uint8)
        lens = [1, 63, 64, 65, 1023, 1024, 1025, 4096, 70_000, 100_000]
        offs, cur = [], 0
        for l in lens:
            offs.append(cur)
            cur += l
        C = 16
        offs_a = np.zeros(C, np.int32)
        lens_a = np.zeros(C, np.int32)
        offs_a[:len(lens)] = offs
        lens_a[:len(lens)] = lens
        spans = tier_spans(128)
        acc, ovf = pool_digest(
            jnp.asarray(np.concatenate([flat, np.zeros(CHUNK_LEN,
                                                       np.uint8)])),
            jnp.asarray(offs_a), jnp.asarray(lens_a),
            leaf_cap=leaf_capacity(cur, C),
            tiers=tuple((s, 8) for s in spans), pallas=pallas)
        acc = np.asarray(acc)
        if int(np.asarray(ovf)[0]) != 0:
            return False
        for i, l in enumerate(lens):
            want = blake3_hash(flat[offs[i]:offs[i] + l].tobytes())
            if want != np.ascontiguousarray(
                    acc[i].astype("<u4")).tobytes():
                return False
        return True
    except Exception:  # pragma: no cover - lowering failure
        return False


@functools.lru_cache(maxsize=64)
def tier_caps(spans: Tuple[int, ...], fracs_by_leaves, expect_total: float,
              n_extra: int) -> Tuple[Tuple[int, int], ...]:
    """Capacity per tier from a (leaf-count -> fraction) histogram.

    ``fracs_by_leaves``: tuple of (max_leaves_of_bin, fraction) pairs —
    hashable so the plan caches per (params, shape).  Expectation +
     0.75 sigma like the class cascade; the terminus carries the real
    slack plus ``n_extra`` (short per-row tails land in tier 0).
    """
    out = []
    for i, span in enumerate(spans):
        lo = spans[i - 1] if i else 0
        frac = sum(f for ml, f in fracs_by_leaves if lo < ml <= span)
        mu = expect_total * frac
        sigma = (max(mu, 0.0) * max(1.0 - frac, 0.0)) ** 0.5
        want = mu + 0.75 * sigma + 1 + (n_extra if i == 0 else 0)
        if i == len(spans) - 1:
            want += 8 + 0.02 * expect_total
        out.append((span, -(-int(want) // 4) * 4))
    return tuple(out)
