"""BLAKE3 on the host: pure-Python spec reference + numpy batch engine.

The reference fingerprints every chunk and tree blob with BLAKE3
(``client/src/backup/filesystem/dir_packer.rs:286,321,353``) via the SIMD
``blake3`` crate.  Here BLAKE3 is implemented from the public specification
(hash mode only, 32-byte digests):

* :func:`blake3_hash` — scalar pure-Python implementation, the readability
  oracle; used for tiny inputs and tests.
* :class:`Blake3Numpy` — batch engine vectorized over many inputs at once
  with numpy uint32 arrays.  Its masked leaf-scan + pair-merge tree reduction
  is the exact algorithm the TPU kernel (:mod:`.blake3_tpu`) uses, so the two
  are structurally parallel and must agree bit-for-bit.

Tree topology note: BLAKE3 splits the leaves of a subtree so the left side
holds the largest power of two ≤ n leaves.  Bottom-up pair-merging where an
unpaired rightmost node rides up unchanged produces exactly that topology,
which is what both batch engines implement.
"""

from __future__ import annotations

import struct

import numpy as np

M32 = 0xFFFFFFFF
IV = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
      0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)
MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)
BLOCK_LEN = 64
CHUNK_LEN = 1024
MAX_LEAVES_PER_CHUNK = 16  # 64-byte blocks per 1 KiB chunk

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3

# Column/diagonal mixing schedule: (a, b, c, d) state indices for the 8 G
# applications of one round, in order; message words 2i, 2i+1 feed G number i.
G_SCHEDULE = (
    (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
    (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
)


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & M32


def compress(cv, block_words, counter, block_len, flags):
    """One BLAKE3 compression; returns the full 16-word output state."""
    state = list(cv) + [IV[0], IV[1], IV[2], IV[3],
                        counter & M32, (counter >> 32) & M32, block_len, flags]
    m = list(block_words)
    for r in range(7):
        for i, (a, b, c, d) in enumerate(G_SCHEDULE):
            mx, my = m[2 * i], m[2 * i + 1]
            state[a] = (state[a] + state[b] + mx) & M32
            state[d] = _rotr(state[d] ^ state[a], 16)
            state[c] = (state[c] + state[d]) & M32
            state[b] = _rotr(state[b] ^ state[c], 12)
            state[a] = (state[a] + state[b] + my) & M32
            state[d] = _rotr(state[d] ^ state[a], 8)
            state[c] = (state[c] + state[d]) & M32
            state[b] = _rotr(state[b] ^ state[c], 7)
        if r < 6:
            m = [m[p] for p in MSG_PERMUTATION]
    out = [(state[i] ^ state[i + 8]) & M32 for i in range(8)]
    out += [(state[i + 8] ^ cv[i]) & M32 for i in range(8)]
    return out


def _block_words(block: bytes):
    block = block + b"\x00" * (BLOCK_LEN - len(block))
    return struct.unpack("<16I", block)


def _chunk_cv(data: bytes, counter: int, root: bool):
    """Chaining value of one ≤1024-byte chunk (ROOT flagged if requested)."""
    cv = IV
    n_blocks = max(1, (len(data) + BLOCK_LEN - 1) // BLOCK_LEN)
    for i in range(n_blocks):
        block = data[i * BLOCK_LEN:(i + 1) * BLOCK_LEN]
        flags = 0
        if i == 0:
            flags |= CHUNK_START
        if i == n_blocks - 1:
            flags |= CHUNK_END
            if root:
                flags |= ROOT
        out = compress(cv, _block_words(block), counter,
                       len(block) if data else 0, flags)
        cv = out[:8]
    return cv


def _parent_cv(left, right, root: bool):
    out = compress(IV, tuple(left) + tuple(right), 0, BLOCK_LEN,
                   PARENT | (ROOT if root else 0))
    return out[:8]


def blake3_hash(data: bytes) -> bytes:
    """32-byte BLAKE3 digest (hash mode), scalar reference implementation."""
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        return struct.pack("<8I", *_chunk_cv(data, 0, root=True))
    cvs = [_chunk_cv(data[i * CHUNK_LEN:(i + 1) * CHUNK_LEN], i, root=False)
           for i in range(n_chunks)]
    while len(cvs) > 2:
        nxt = [_parent_cv(cvs[i], cvs[i + 1], root=False)
               for i in range(0, len(cvs) - 1, 2)]
        if len(cvs) % 2:
            nxt.append(cvs[-1])
        cvs = nxt
    return struct.pack("<8I", *_parent_cv(cvs[0], cvs[1], root=True))


# --------------------------------------------------------------------------
# numpy batch engine
# --------------------------------------------------------------------------

_IV_NP = np.array(IV, dtype=np.uint32)
_PERM_NP = np.array(MSG_PERMUTATION, dtype=np.int64)


def _rotr_np(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def compress_np(cv, m, counter_lo, counter_hi, block_len, flags):
    """Vectorized compression over a leading batch axis.

    cv: (B, 8) u32; m: (B, 16) u32; counter_lo/hi, block_len, flags: (B,) u32.
    Returns the (B, 8) output chaining value.
    """
    B = cv.shape[0]
    v = np.empty((B, 16), dtype=np.uint32)
    v[:, :8] = cv
    v[:, 8:12] = _IV_NP[:4]
    v[:, 12] = counter_lo
    v[:, 13] = counter_hi
    v[:, 14] = block_len
    v[:, 15] = flags
    m = np.asarray(m, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for r in range(7):
            for i, (a, b, c, d) in enumerate(G_SCHEDULE):
                mx, my = m[:, 2 * i], m[:, 2 * i + 1]
                v[:, a] += v[:, b] + mx
                v[:, d] = _rotr_np(v[:, d] ^ v[:, a], 16)
                v[:, c] += v[:, d]
                v[:, b] = _rotr_np(v[:, b] ^ v[:, c], 12)
                v[:, a] += v[:, b] + my
                v[:, d] = _rotr_np(v[:, d] ^ v[:, a], 8)
                v[:, c] += v[:, d]
                v[:, b] = _rotr_np(v[:, b] ^ v[:, c], 7)
            if r < 6:
                m = m[:, _PERM_NP]
    return v[:, :8] ^ v[:, 8:]


class Blake3Numpy:
    """Batched BLAKE3 over many independent byte strings.

    All inputs of a batch are padded to the same number of 1 KiB chunks; per
    input, invalid chunks/blocks are masked out of the scan/merge so digests
    are exact for every length, including 0.
    """

    def digest_batch(self, datas) -> list:
        if not datas:
            return []
        lens = np.array([len(d) for d in datas], dtype=np.int64)
        B = len(datas)
        n_chunks = np.maximum(1, -(-lens // CHUNK_LEN))  # ceil, min 1
        L = int(n_chunks.max())
        # Byte tensor (B, L*1024), zero padded.
        buf = np.zeros((B, L * CHUNK_LEN), dtype=np.uint8)
        for i, d in enumerate(datas):
            buf[i, :len(d)] = np.frombuffer(bytes(d), dtype=np.uint8)
        return self._digest_padded(buf, lens, L)

    def _digest_padded(self, buf: np.ndarray, lens: np.ndarray, L: int) -> list:
        """buf: (B, L*1024) u8 zero-padded; lens: true byte lengths."""
        B = buf.shape[0]
        words = buf.reshape(B, L, MAX_LEAVES_PER_CHUNK, BLOCK_LEN) \
                   .view(np.uint32).reshape(B, L, MAX_LEAVES_PER_CHUNK, 16)
        # Per-chunk block counts / last-block lengths.
        n_chunks = np.maximum(1, -(-lens // CHUNK_LEN))
        chunk_idx = np.arange(L)
        chunk_valid = chunk_idx[None, :] < n_chunks[:, None]  # (B, L)
        # Bytes in each chunk (0..1024); final chunk may be partial, and a
        # zero-length input still has one (empty) chunk.
        chunk_bytes = np.clip(lens[:, None] - chunk_idx[None, :] * CHUNK_LEN,
                              0, CHUNK_LEN)
        n_blocks = np.maximum(1, -(-chunk_bytes // BLOCK_LEN))  # (B, L)
        last_block_len = (chunk_bytes - (n_blocks - 1) * BLOCK_LEN).astype(np.uint32)

        is_single_chunk = (n_chunks == 1)

        # --- leaf scan: 16 sequential blocks, batched over (B, L) ----------
        cv = np.broadcast_to(_IV_NP, (B * L, 8)).copy()
        cv_root = cv.copy()  # variant with ROOT on the last block (single-chunk roots)
        counter_lo = np.broadcast_to(chunk_idx[None, :].astype(np.uint32),
                                     (B, L)).reshape(-1)
        counter_hi = np.zeros(B * L, dtype=np.uint32)
        nb = n_blocks.reshape(-1)
        lbl = last_block_len.reshape(-1)
        for blk in range(MAX_LEAVES_PER_CHUNK):
            m = words[:, :, blk, :].reshape(B * L, 16)
            active = blk < nb
            is_last = blk == nb - 1
            flags = np.where(blk == 0, CHUNK_START, 0).astype(np.uint32)
            flags = np.where(is_last, flags | CHUNK_END, flags)
            blen = np.where(is_last, lbl, BLOCK_LEN).astype(np.uint32)
            out = compress_np(cv, m, counter_lo, counter_hi, blen, flags)
            cv = np.where(active[:, None], out, cv)
            out_r = compress_np(cv_root, m, counter_lo, counter_hi, blen,
                                np.where(is_last, flags | ROOT, flags).astype(np.uint32))
            cv_root = np.where(active[:, None], out_r, cv_root)
        leaf_cv = cv.reshape(B, L, 8)
        leaf_cv_root = cv_root.reshape(B, L, 8)

        # --- tree reduction: pair-merge, odd node rides up -----------------
        root_cv = np.where(is_single_chunk[:, None], leaf_cv_root[:, 0], 0)
        cvs = leaf_cv
        counts = n_chunks.copy()
        while cvs.shape[1] > 1:
            P = cvs.shape[1] // 2
            left = cvs[:, 0:2 * P:2]  # (B, P, 8)
            right = cvs[:, 1:2 * P:2]
            m = np.concatenate([left, right], axis=-1).reshape(B * P, 16)
            zeros = np.zeros(B * P, dtype=np.uint32)
            merged = compress_np(
                np.broadcast_to(_IV_NP, (B * P, 8)).copy(), m, zeros, zeros,
                np.full(B * P, BLOCK_LEN, dtype=np.uint32),
                np.full(B * P, PARENT, dtype=np.uint32)).reshape(B, P, 8)
            merged_root = compress_np(
                np.broadcast_to(_IV_NP, (B * P, 8)).copy(), m, zeros, zeros,
                np.full(B * P, BLOCK_LEN, dtype=np.uint32),
                np.full(B * P, PARENT | ROOT, dtype=np.uint32)).reshape(B, P, 8)
            # pair j merges iff 2j+1 < count; unpaired node rides up.
            pair_idx = np.arange(P)
            pair_merges = (2 * pair_idx[None, :] + 1) < counts[:, None]  # (B, P)
            nxt_len = (cvs.shape[1] + 1) // 2
            nxt = np.zeros((B, nxt_len, 8), dtype=np.uint32)
            nxt[:, :P] = np.where(pair_merges[:, :, None], merged, left)
            # odd leftover at the old level rides up into the last slot
            if cvs.shape[1] % 2:
                nxt[:, -1] = cvs[:, -1]
            else:
                # even storage width: a ride-up only happens per-item when
                # count is odd and its last valid node sits at index count-1;
                # np.where above already kept `left` for non-merging pairs,
                # which is exactly the ride-up when count-1 is even.
                pass
            # the root is produced by the merge that takes count 2 -> 1
            is_root_merge = (counts == 2)
            root_cv = np.where(is_root_merge[:, None], merged_root[:, 0], root_cv)
            cvs = nxt
            counts = np.where(counts > 1, (counts + 1) // 2, counts)

        out_bytes = root_cv.astype("<u4").tobytes()
        return [out_bytes[i * 32:(i + 1) * 32] for i in range(B)]


_BATCH = Blake3Numpy()


def blake3_many(datas) -> list:
    """Batched digests via the numpy engine (bit-exact vs :func:`blake3_hash`)."""
    return _BATCH.digest_batch(datas)
