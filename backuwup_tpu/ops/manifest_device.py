"""Zero-round-trip manifest: scan -> select -> gather -> digest on device.

Round-3 profiling (scripts/probe_stages_honest.py) showed the pipeline's
wall clock was **host-link latency, not device compute**: the driver
downloaded each segment's cut list before it could stage digest tiles, so
every batch paid two high-latency host round trips while the device sat
idle (~100+ ms each on the relay-attached dev rig; real PCIe pays less
but still serializes).  The reference has the same structure collapsed
onto one CPU (``dir_packer.rs:246-311``): chunk, then hash, then index —
all in one address space.  The TPU answer is to keep the *data plane*
entirely in HBM:

1. :func:`backuwup_tpu.ops.cdc_tpu.scan_select_batch` produces packed
   per-row cut lists on device (Mosaic strip scan + on-device selection).
2. Chunk meta (offset, length, class) is DERIVED on device from the cut
   lists — no host assembly.
3. Chunks are compacted into a small set of power-of-two length classes
   (fixed-capacity ``nonzero``), gathered HBM->HBM at their class's
   padded span, digested with the batched BLAKE3, and the root chaining
   values scattered into one dense ``(B*cut_cap, 8)`` accumulator.
4. The caller downloads ``(cuts, digests, overflow)`` once — for a whole
   run of batches — and assembles manifests host-side.

Class capacities are sized from a one-time oracle calibration of the
chunk-length distribution (:func:`class_plan`); a class overflow (data
far from the calibrated distribution, e.g. adversarial all-max chunks)
sets a flag and the affected batch falls back to the host-tiled path,
preserving bit-exact output.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .cdc_tpu import _HALO, scan_select_batch
from .blake3_tpu import digest_padded
from .gear import CDCParams

CHUNK_LEN = 1024


def _pow2_at_least(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=16)
def _length_histogram(params: CDCParams) -> Tuple[float, Tuple[float, ...]]:
    """(mean_chunk_len, fraction per pow2 leaf class), computed
    analytically from the two-phase geometric cut process.

    On uniform data the gear hash at each position is iid uniform, so a
    chunk survives past length ``x`` with probability
    ``(1-p_s)^a (1-p_l)^b`` where ``a``/``b`` count positions seen by the
    strict/loose windows and ``p = 2^-mask_bits``; the forced cut at
    ``max_size`` truncates the tail.  Exact for random corpora; real
    corpora that deviate far enough to overflow the 1.7x-slack capacities
    fall back to the host-tiled path (still bit-exact), so this estimate
    only steers throughput, never correctness.
    """
    p_s = 2.0 ** -params.mask_s_bits
    p_l = 2.0 ** -params.mask_l_bits
    lens = np.arange(params.min_size, params.max_size + 1, dtype=np.float64)
    # positions examined by each phase for a chunk of length L (cuts land
    # at L-1): strict window spans [min-1, desired-2], loose beyond
    a = np.clip(lens - params.min_size + 1, 0,
                params.desired_size - params.min_size)
    b = np.clip(lens - params.desired_size + 1, 0, None)
    surv = (1 - p_s) ** a * (1 - p_l) ** b
    pmf = np.empty_like(surv)
    pmf[:-1] = surv[:-1] - surv[1:]
    pmf[-1] = surv[-1]  # forced cut at max_size absorbs the tail
    pmf = np.maximum(pmf, 0)
    pmf /= pmf.sum()
    mean = float((lens * pmf).sum())
    classes = class_leaf_sizes(params)
    leaves = -(-lens // CHUNK_LEN)
    fracs = []
    for i, c in enumerate(classes):
        lo = classes[i - 1] if i else 0
        fracs.append(float(pmf[(leaves > lo) & (leaves <= c)].sum()))
    return mean, tuple(fracs)


@functools.lru_cache(maxsize=16)
def class_leaf_sizes(params: CDCParams) -> Tuple[int, ...]:
    """Linear leaf-count class grid covering [1, max chunk leaves].

    ~12 classes bound per-chunk padding waste to one class step (~8% of
    ``max_size``) — pow2 classes measured ~2x padded-digest overcompute
    because most mass lands just above a boundary.
    """
    max_leaves = -(-params.max_size // CHUNK_LEN)
    step = max(8, -(-max_leaves // 12))
    step = -(-step // 8) * 8  # aligned steps keep tile shapes friendly
    out = list(range(step, max_leaves + 1, step))
    if not out or out[-1] != max_leaves:
        out.append(max_leaves)
    return tuple(out)


@functools.lru_cache(maxsize=64)
def class_caps(params: CDCParams, total_bytes: int,
               n_rows: int) -> Tuple[int, ...]:
    """Per-class chunk-slot capacities for one batch shape.

    Expectation + 0.75 sigma (binomial) per class — deliberately tight,
    because digest compute scales with cap x class span and the cascade
    hands per-class excess to the next span class; only total-count
    fluctuation reaches the terminus (which carries the real slack).
    Class 0 additionally holds every row's short tail.  A cascade
    overflow is detected on device and the batch re-runs on the
    host-tiled path (bit-exact either way).
    """
    mean_len, fracs = _length_histogram(params)
    expect_total = total_bytes / max(mean_len, 1.0)
    caps = []
    for i, frac in enumerate(fracs):
        mu = expect_total * frac
        sigma = (expect_total * frac * (1.0 - frac)) ** 0.5
        want = mu + 0.75 * sigma + 1 + (n_rows if i == 0 else 0)
        if i == len(fracs) - 1:
            want += 8 + 0.02 * expect_total  # cascade terminus slack
        elif mu < 1.5 and i > 0:
            # near-empty class: skip its digest tile entirely, the
            # cascade hands its rare chunks one span class up
            want = 0
        caps.append(-(-int(want) // 4) * 4)
    return tuple(caps)


def _chunk_meta(packed: jnp.ndarray, row_len: int):
    """Packed cut rows -> flat per-chunk (abs offset, length, valid).

    Derived entirely on device.  Rows whose scan/select overflowed carry
    garbage cut lists; the host re-runs those rows on the oracle anyway,
    so their chunks are masked out here — otherwise one bad row would
    consume digest capacities and could flag the WHOLE batch overflowed.
    """
    B = packed.shape[0]
    cut_cap = packed.shape[1] - 2
    n_cuts = packed[:, 1]  # (B,)
    ends = packed[:, 2:]   # (B, cut_cap) inclusive ends, -1 padded
    offs = jnp.concatenate(
        [jnp.zeros((B, 1), dtype=ends.dtype), ends[:, :-1] + 1], axis=1)
    lens = ends - offs + 1
    valid = (jnp.arange(cut_cap, dtype=jnp.int32)[None, :]
             < n_cuts[:, None])  # (B, cut_cap)
    row_ok = packed[:, 0] == 0  # (B,)
    valid = valid & row_ok[:, None]
    lens = jnp.where(valid, lens, 0)
    # absolute byte offset of each chunk in the flattened batch buffer
    row_base = (jnp.arange(B, dtype=jnp.int32) * row_len + _HALO)[:, None]
    return ((row_base + offs).reshape(-1), lens.reshape(-1),
            valid.reshape(-1))


@functools.partial(jax.jit, static_argnames=(
    "min_size", "desired_size", "max_size", "mask_s", "mask_l",
    "s_cap", "l_cap", "cut_cap", "fused", "classes", "caps",
    "pallas_digest"))
def scan_digest_batch(buf_d: jnp.ndarray, nv_b: jnp.ndarray, *,
                      min_size: int, desired_size: int, max_size: int,
                      mask_s: int, mask_l: int, s_cap: int, l_cap: int,
                      cut_cap: int, fused: bool,
                      classes: Tuple[int, ...], caps: Tuple[int, ...],
                      pallas_digest: bool = False):
    """One resident ``(B, _HALO+P)`` batch -> (packed cuts, digests, ovf).

    Everything stays on device: ``packed`` is ``scan_select_batch``'s
    ``(B, 2+cut_cap)`` cut rows, ``digests`` is ``(B*cut_cap, 8)`` u32
    root chaining values addressed by ``row*cut_cap + chunk``, ``ovf`` is
    ``(1,)`` i32 — the number of chunks the cascade could not place
    (nonzero means the caller must fall back; see cascade note below).
    """
    B = buf_d.shape[0]
    row_len = buf_d.shape[1]
    packed = scan_select_batch(
        buf_d, nv_b, min_size=min_size, desired_size=desired_size,
        max_size=max_size, mask_s=mask_s, mask_l=mask_l,
        s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=fused)
    abs_offs, flat_lens, flat_valid = _chunk_meta(packed, row_len)
    total = B * cut_cap

    leaves = (flat_lens + (CHUNK_LEN - 1)) // CHUNK_LEN
    # class id = index of smallest class >= leaves (valid chunks only)
    cls = jnp.zeros(total, dtype=jnp.int32)
    for i, c in enumerate(classes[:-1]):
        cls = cls + (leaves > c).astype(jnp.int32)

    flat = buf_d.reshape(-1)
    # slack so fixed-span gathers never clamp (dynamic_slice clips
    # out-of-range starts, which would shift data)
    flat = jnp.pad(flat, (0, classes[-1] * CHUNK_LEN))
    acc = jnp.zeros((total, 8), dtype=jnp.uint32)
    # cascade spill: a class beyond its capacity hands its excess chunks
    # to the next (larger-span) class, so per-class capacities stay at
    # ~expectation and only total-count fluctuation can reach the top
    carry = jnp.zeros(total, dtype=bool)
    for i, (Lc, cap) in enumerate(zip(classes, caps)):
        if cap == 0:  # skipped class: cascade everything upward
            carry = carry | (flat_valid & (cls == i))
            continue
        mine = flat_valid & ((cls == i) | carry)
        rank = jnp.cumsum(mine.astype(jnp.int32)) - 1
        take = mine & (rank < cap)
        carry = mine & ~take
        (idx,) = jnp.nonzero(take, size=cap, fill_value=total)
        safe = jnp.clip(idx, 0, total - 1)
        got = idx < total
        o = jnp.where(got, abs_offs[safe], 0)
        ln = jnp.where(got, flat_lens[safe], 0)
        span = Lc * CHUNK_LEN

        def one(off):
            return jax.lax.dynamic_slice(flat, (off,), (span,))

        tile = jax.vmap(one)(o)
        cv = digest_padded(tile, ln, L=Lc, pallas=pallas_digest)  # (cap, 8)
        acc = acc.at[idx].set(cv, mode="drop")
    ovf = jnp.sum(carry.astype(jnp.int32))[None]  # terminus overflow only
    return packed, acc, ovf


@functools.lru_cache(maxsize=64)
def tier_plan(params: CDCParams, total_bytes: int,
              n_rows: int) -> Tuple[Tuple[int, int], ...]:
    """((leaf_span, chunk_cap), ...) tree tiers for the leaf-pool digest.

    Chunk-count expectations come from the same analytic length
    histogram as :func:`class_caps`, re-binned onto the 2-3 geometric
    tier spans (tree work is ~1/16 of leaf work, so coarse spans cost
    a few percent where the payload-level class tiles could not afford
    them).  Class bins that straddle a tier edge only blur the capacity
    estimate — overflow still cascades and, at the terminus, falls back
    bit-exactly.
    """
    from .digest_pool import tier_caps, tier_spans

    mean_len, fracs = _length_histogram(params)
    classes = class_leaf_sizes(params)
    spans = tier_spans(-(-params.max_size // CHUNK_LEN))
    return tier_caps(spans, tuple(zip(classes, fracs)),
                     total_bytes / max(mean_len, 1.0), n_rows)


@functools.partial(jax.jit, static_argnames=(
    "min_size", "desired_size", "max_size", "mask_s", "mask_l",
    "s_cap", "l_cap", "cut_cap", "fused", "leaf_cap", "tiers",
    "pallas_digest"))
def scan_digest_batch_pool(buf_d: jnp.ndarray, nv_b: jnp.ndarray, *,
                           min_size: int, desired_size: int, max_size: int,
                           mask_s: int, mask_l: int, s_cap: int, l_cap: int,
                           cut_cap: int, fused: bool, leaf_cap: int,
                           tiers: Tuple[Tuple[int, int], ...],
                           pallas_digest: bool = False):
    """Leaf-pool twin of :func:`scan_digest_batch` — same contract, but
    the digest stage is ONE flat leaf scan + 2-3 tiny tree tiles
    (:func:`backuwup_tpu.ops.digest_pool.pool_digest`) instead of ~12
    per-class gather+digest pipelines.  Selected by ``DevicePipeline``'s
    runtime parity ladder; bit-identical output either way.
    """
    from .digest_pool import pool_digest

    row_len = buf_d.shape[1]
    packed = scan_select_batch(
        buf_d, nv_b, min_size=min_size, desired_size=desired_size,
        max_size=max_size, mask_s=mask_s, mask_l=mask_l,
        s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=fused)
    abs_offs, flat_lens, flat_valid = _chunk_meta(packed, row_len)
    flat = jnp.pad(buf_d.reshape(-1), (0, CHUNK_LEN))
    acc, ovf = pool_digest(
        flat, abs_offs, jnp.where(flat_valid, flat_lens, 0),
        leaf_cap=leaf_cap, tiers=tiers, pallas=pallas_digest)
    return packed, acc, ovf


@functools.lru_cache(maxsize=32)
def _mesh_scan_digest_fn(mesh, axis: str, min_size: int, desired_size: int,
                         max_size: int, mask_s: int, mask_l: int, s_cap: int,
                         l_cap: int, cut_cap: int, fused: bool, leaf_cap: int,
                         tiers: Tuple[Tuple[int, int], ...],
                         pallas_digest: bool, emit_queries: bool):
    """Compile the shard-mapped leaf-pool manifest program for one mesh.

    Each shard runs the SAME jitted :func:`scan_digest_batch_pool` body
    over its contiguous slice of the row axis — per-shard leaf pool,
    per-shard tier cascade, per-shard overflow flag.  ``out_specs``
    concatenate shard outputs along that axis, so the global ``packed``
    and ``acc`` keep the single-device addressing (``row*cut_cap+chunk``
    in batch row order) while ``ovf`` widens from ``(1,)`` to ``(D,)``:
    one flag PER SHARD, so adversarial data only re-runs the affected
    shard's rows on the host-tiled path, not the whole batch.

    With ``emit_queries`` each shard also slices its accumulator into a
    ``(1, bs*cut_cap, 4)`` dedup query slab
    (:func:`..dedup_index.queries_from_cvs`), giving a global
    ``(D, bs*cut_cap, 4)`` array already laid out for
    ``ShardedDedupIndex.insert_device`` — fingerprints flow
    manifest -> dedup probe without ever leaving the mesh.
    """
    from jax.sharding import PartitionSpec as P

    from ..utils.jax_compat import shard_map
    from .dedup_index import queries_from_cvs

    def shard_fn(buf_d, nv_b):
        packed, acc, ovf = scan_digest_batch_pool(
            buf_d, nv_b, min_size=min_size, desired_size=desired_size,
            max_size=max_size, mask_s=mask_s, mask_l=mask_l, s_cap=s_cap,
            l_cap=l_cap, cut_cap=cut_cap, fused=fused, leaf_cap=leaf_cap,
            tiers=tiers, pallas_digest=pallas_digest)
        if emit_queries:
            return packed, acc, ovf, queries_from_cvs(acc)[None]
        return packed, acc, ovf

    n_out = 4 if emit_queries else 3
    mapped = shard_map(shard_fn, mesh=mesh, in_specs=(P(axis), P(axis)),
                       out_specs=tuple([P(axis)] * n_out))
    return jax.jit(mapped)


def scan_digest_batch_pool_mesh(buf_d, nv_b, *, mesh, axis: str,
                                min_size: int, desired_size: int,
                                max_size: int, mask_s: int, mask_l: int,
                                s_cap: int, l_cap: int, cut_cap: int,
                                fused: bool, leaf_cap: int,
                                tiers: Tuple[Tuple[int, int], ...],
                                pallas_digest: bool = False,
                                emit_queries: bool = False):
    """Mesh twin of :func:`scan_digest_batch_pool` — same contract,
    data-parallel over the row axis with ``shard_map``.

    ``buf_d``/``nv_b`` must be sharded ``P(axis)`` over a row count
    divisible by the mesh size; ``leaf_cap``/``tiers`` are PER-SHARD
    capacities (sized for ``B/D`` rows).  Returns
    ``(packed, acc, ovf[, queries])`` where ``ovf`` is the ``(D,)``
    per-shard overflow vector.  Bit-identical to the single-device path:
    a shard sees exactly the rows a ``B/D``-row single-device batch would,
    and every kernel is row-independent (parity-ladder posture — a mesh
    that mis-lowers loses speed, never correctness).
    """
    fn = _mesh_scan_digest_fn(mesh, axis, min_size, desired_size, max_size,
                              mask_s, mask_l, s_cap, l_cap, cut_cap, fused,
                              leaf_cap, tiers, pallas_digest, emit_queries)
    return fn(buf_d, nv_b)
