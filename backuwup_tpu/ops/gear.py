"""Deterministic GEAR table + CDC parameter set (see CDC_SPEC.md)."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import defaults

_M64 = (1 << 64) - 1
GEAR_SEED = 0x6261636B75777570  # "backuwup"
GEAR_WINDOW = 32  # bytes of influence of the 32-bit rolling hash


def _splitmix64_stream(seed: int, count: int):
    out = []
    state = seed
    for _ in range(count):
        state = (state + 0x9E3779B97F4A7C15) & _M64
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
        z = z ^ (z >> 31)
        out.append(z)
    return out


def make_gear_table() -> np.ndarray:
    """256 x uint32, high halves of SplitMix64(GEAR_SEED) outputs."""
    return np.array([z >> 32 for z in _splitmix64_stream(GEAR_SEED, 256)],
                    dtype=np.uint32)


GEAR = make_gear_table()


def _top_bits_mask(bits: int) -> int:
    if not 0 < bits < 32:
        raise ValueError("mask bits must be in (0, 32)")
    return (0xFFFFFFFF << (32 - bits)) & 0xFFFFFFFF


@dataclass(frozen=True)
class CDCParams:
    """Chunking parameters; defaults mirror client/src/defaults.rs:62-68."""

    min_size: int = defaults.CDC_MIN_CHUNK
    desired_size: int = defaults.CDC_DESIRED_CHUNK
    max_size: int = defaults.CDC_MAX_CHUNK
    mask_s_bits: int = defaults.CDC_MASK_S_BITS
    mask_l_bits: int = defaults.CDC_MASK_L_BITS

    def __post_init__(self) -> None:
        if not (0 < self.min_size <= self.desired_size <= self.max_size):
            raise ValueError("require 0 < min <= desired <= max")
        if self.mask_l_bits >= self.mask_s_bits:
            raise ValueError("mask_l must be looser (fewer bits) than mask_s")

    @property
    def mask_s(self) -> int:
        return _top_bits_mask(self.mask_s_bits)

    @property
    def mask_l(self) -> int:
        return _top_bits_mask(self.mask_l_bits)

    @classmethod
    def from_desired(cls, desired: int) -> "CDCParams":
        if desired & (desired - 1):
            raise ValueError("desired size must be a power of two")
        bits = desired.bit_length() - 1
        return cls(min_size=max(64, desired // 4), desired_size=desired,
                   max_size=3 * desired, mask_s_bits=bits + 2,
                   mask_l_bits=bits - 2)
