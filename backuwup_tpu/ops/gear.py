"""Deterministic GEAR table + CDC parameter set (see CDC_SPEC.md).

The gear function is **computable, not just tabulated**: ``GEAR[b] =
fmix32(GEAR_SEED32 + b)`` where ``fmix32`` is the murmur3 32-bit
finalizer.  Hosts (CPU oracle, native C baseline) precompute the 256-entry
table once; the TPU scan computes the formula per position on the VPU —
7 fused elementwise u32 ops — because table gathers serialize on TPU and
one-hot MXU lookups pay ~16-64 bytes of HBM traffic per stream byte
(round-3's measured floor, PERF.md).  Spec v2; v1 was SplitMix64-seeded
(changing the table re-chunks streams, so v1 and v2 snapshots do not
dedup against each other — acceptable pre-release, recorded in
CHANGES.md).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import defaults

_M32 = 0xFFFFFFFF
GEAR_SEED32 = 0x6261636B  # "back"
GEAR_WINDOW = 32  # bytes of influence of the 32-bit rolling hash


def fmix32(h: int) -> int:
    """murmur3 finalizer: full-avalanche bijection on u32."""
    h &= _M32
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def make_gear_table() -> np.ndarray:
    """256 x uint32: ``fmix32(GEAR_SEED32 + b)`` for b in 0..255."""
    return np.array([fmix32(GEAR_SEED32 + b) for b in range(256)],
                    dtype=np.uint32)


GEAR = make_gear_table()


def _top_bits_mask(bits: int) -> int:
    if not 0 < bits < 32:
        raise ValueError("mask bits must be in (0, 32)")
    return (0xFFFFFFFF << (32 - bits)) & 0xFFFFFFFF


@dataclass(frozen=True)
class CDCParams:
    """Chunking parameters; defaults mirror client/src/defaults.rs:62-68."""

    min_size: int = defaults.CDC_MIN_CHUNK
    desired_size: int = defaults.CDC_DESIRED_CHUNK
    max_size: int = defaults.CDC_MAX_CHUNK
    mask_s_bits: int = defaults.CDC_MASK_S_BITS
    mask_l_bits: int = defaults.CDC_MASK_L_BITS

    def __post_init__(self) -> None:
        if not (0 < self.min_size <= self.desired_size <= self.max_size):
            raise ValueError("require 0 < min <= desired <= max")
        if self.mask_l_bits >= self.mask_s_bits:
            raise ValueError("mask_l must be looser (fewer bits) than mask_s")

    @property
    def mask_s(self) -> int:
        return _top_bits_mask(self.mask_s_bits)

    @property
    def mask_l(self) -> int:
        return _top_bits_mask(self.mask_l_bits)

    @classmethod
    def from_desired(cls, desired: int) -> "CDCParams":
        if desired & (desired - 1):
            raise ValueError("desired size must be a power of two")
        bits = desired.bit_length() - 1
        return cls(min_size=max(64, desired // 4), desired_size=desired,
                   max_size=3 * desired, mask_s_bits=bits + 2,
                   mask_l_bits=bits - 2)
