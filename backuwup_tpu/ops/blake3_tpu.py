"""Batched BLAKE3 on TPU: the fingerprint stage of the dedup pipeline.

The reference hashes every chunk and tree blob with the SIMD ``blake3`` crate
(``client/src/backup/filesystem/dir_packer.rs:286,321,353``), one chunk at a
time.  Here many independent inputs are digested in one device program:

* Each input is padded to ``L`` 1 KiB leaf chunks; a batch is ``(B, L*1024)``
  u8.  The compression function is vectorized over ``B*L`` lanes as pure u32
  VPU arithmetic (rotates = shift pairs), with the 7 rounds and the message
  permutation schedule unrolled at trace time.
* The leaf scan walks the 16 blocks of every chunk in lock-step; per-lane
  masks (block counts, last-block lengths, CHUNK_START/END/ROOT flags)
  make digests exact for every input length, including 0.
* The binary tree reduction pair-merges chaining values level by level;
  an unpaired rightmost node rides up unchanged, which reproduces BLAKE3's
  largest-power-of-two-left split exactly (see blake3_cpu.py docstring).
* Structure and masking mirror :class:`backuwup_tpu.ops.blake3_cpu.Blake3Numpy`
  line for line, and digests are bit-identical to the scalar spec
  implementation — self-consistent dedup requires nothing less.

Batching policy lives in :func:`bucketed_batches`: variable-size CDC chunks
(256 KiB..3 MiB for default params) are grouped into a handful of (B, L)
compiled shapes (``defaults.BLAKE3_LEAF_BUCKETS``) to bound both padding
waste and XLA recompiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import defaults
from .blake3_cpu import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    G_SCHEDULE,
    IV,
    MAX_LEAVES_PER_CHUNK,
    MSG_PERMUTATION,
    PARENT,
    ROOT,
)

_IV_NP = np.array(IV, dtype=np.uint32)


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_cols(cv, m, counter_lo, counter_hi, block_len, flags):
    """One BLAKE3 compression, vectorized over lanes.

    ``cv``: list of 8 u32 arrays; ``m``: list of 16 u32 arrays; the scalars
    are u32 arrays of the same lane shape.  Columns stay as separate SSA
    values so XLA fuses the whole round structure without scatter ops.
    Returns the 8 output chaining-value columns.
    """
    iv = [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), counter_lo.shape)
          for i in range(4)]
    state = [c + jnp.uint32(0) for c in cv] + iv + [counter_lo, counter_hi,
                                                    block_len, flags]
    m = [w + jnp.uint32(0) for w in m]

    def round_body(_, carry):
        state, m = list(carry[0]), list(carry[1])
        for i, (a, b, c, d) in enumerate(G_SCHEDULE):
            mx, my = m[2 * i], m[2 * i + 1]
            state[a] = state[a] + state[b] + mx
            state[d] = _rotr(state[d] ^ state[a], 16)
            state[c] = state[c] + state[d]
            state[b] = _rotr(state[b] ^ state[c], 12)
            state[a] = state[a] + state[b] + my
            state[d] = _rotr(state[d] ^ state[a], 8)
            state[c] = state[c] + state[d]
            state[b] = _rotr(state[b] ^ state[c], 7)
        # permuting after the final round too is harmless (m is dropped);
        # keeping it unconditional lets the 7 rounds share one loop body
        return tuple(state), tuple(m[p] for p in MSG_PERMUTATION)

    state, _ = jax.lax.fori_loop(0, 7, round_body, (tuple(state), tuple(m)))
    return [state[i] ^ state[i + 8] for i in range(8)]


def _bytes_to_words(buf: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) u8 -> (..., k) u32 little-endian."""
    b = buf.reshape(*buf.shape[:-1], -1, 4).astype(jnp.uint32)
    return (b[..., 0] | (b[..., 1] << jnp.uint32(8))
            | (b[..., 2] << jnp.uint32(16)) | (b[..., 3] << jnp.uint32(24)))


@functools.partial(jax.jit, static_argnames=("L", "pallas",
                                             "pallas_interpret"))
def digest_padded(buf: jnp.ndarray, lens: jnp.ndarray, *, L: int,
                  pallas: bool = False,
                  pallas_interpret: bool = False) -> jnp.ndarray:
    """Digest a zero-padded batch.

    ``buf``: (B, L*1024) u8; ``lens``: (B,) true byte lengths (i32).
    Returns (B, 8) u32 root chaining values (little-endian digest words).

    The 16-block leaf scan runs as a ``fori_loop`` (compile-time: one
    compression in the graph, not 16); the single-chunk ROOT variant is
    produced by stashing the last block's inputs during the scan and
    recompressing once over B lanes afterwards, instead of running a second
    full scan.  Tree levels are unrolled (log2 L of them) with the
    PARENT|ROOT compression computed only for pair 0, the only pair that can
    ever finalize the root.

    ``pallas=True`` swaps the leaf scan for the VMEM-resident Mosaic
    kernel (bit-identical; callers gate on
    :func:`pallas_digest_available`, which parity-checks on the live
    runtime).  The tree reduction stays in XLA — it touches 1/16 of the
    leaf traffic.
    """
    B = buf.shape[0]
    # tolerate junk beyond each row's true length (e.g. buffers gathered
    # from a resident stream): BLAKE3 pads partial blocks with zeros
    lens = lens.astype(jnp.int32)
    buf = jnp.where(
        jnp.arange(buf.shape[1], dtype=jnp.int32)[None, :] < lens[:, None],
        buf, jnp.uint8(0))
    words = _bytes_to_words(buf.reshape(B, L, MAX_LEAVES_PER_CHUNK, BLOCK_LEN))
    lanes = B * L
    words_flat = words.reshape(lanes, MAX_LEAVES_PER_CHUNK, 16)
    n_chunks = jnp.maximum(1, -(-lens // CHUNK_LEN))  # (B,)
    chunk_idx = jnp.arange(L, dtype=jnp.int32)
    chunk_bytes = jnp.clip(lens[:, None] - chunk_idx[None, :] * CHUNK_LEN,
                           0, CHUNK_LEN)  # (B, L)
    n_blocks = jnp.maximum(1, -(-chunk_bytes // BLOCK_LEN))
    last_block_len = (chunk_bytes - (n_blocks - 1) * BLOCK_LEN).astype(jnp.uint32)
    is_single = (n_chunks == 1)

    # --- leaf scan: fori_loop over the 16 blocks, lanes = (B*L,) -----------
    counter_lo = jnp.broadcast_to(chunk_idx[None, :].astype(jnp.uint32),
                                  (B, L)).reshape(-1)
    counter_hi = jnp.zeros(lanes, dtype=jnp.uint32)
    nb = n_blocks.reshape(-1)
    lbl = last_block_len.reshape(-1)
    zeros = jnp.zeros(lanes, dtype=jnp.uint32)

    if pallas:
        cv_mat, cvp_mat = _leaf_scan_pallas(words_flat, nb, lbl, counter_lo,
                                            interpret=pallas_interpret)
        leaf_cv = [cv_mat[:, i].reshape(B, L) for i in range(8)]
        # single-chunk ROOT recompute from the penultimate CV + the last
        # block of chunk 0, rebuilt here (B lanes — negligible)
        nb0 = n_blocks[:, 0]
        m0 = jnp.take_along_axis(
            words[:, 0], (nb0 - 1)[:, None, None], axis=1)[:, 0]  # (B, 16)
        lane0 = jnp.arange(B, dtype=jnp.int32) * L
        blen0 = last_block_len[:, 0]
        flags0 = (jnp.where(nb0 == 1, jnp.uint32(CHUNK_START), jnp.uint32(0))
                  | jnp.uint32(CHUNK_END))
        root_single = _compress_cols(
            [cvp_mat[lane0, i] for i in range(8)],
            [m0[:, w] for w in range(16)],
            jnp.zeros(B, dtype=jnp.uint32), jnp.zeros(B, dtype=jnp.uint32),
            blen0, flags0 | jnp.uint32(ROOT))
    else:
        iv_cols = [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), (lanes,)) + zeros
                   for i in range(8)]

        def leaf_body(blk, carry):
            cv, cv_last_in, m_last, blen_last, flags_last = carry
            mslab = jax.lax.dynamic_index_in_dim(words_flat, blk, axis=1,
                                                 keepdims=False)  # (lanes, 16)
            m = [mslab[:, w] for w in range(16)]
            active = blk < nb
            is_last = blk == nb - 1
            flags = jnp.where(blk == 0, jnp.uint32(CHUNK_START),
                              jnp.uint32(0))
            flags = jnp.where(is_last, flags | jnp.uint32(CHUNK_END), flags)
            blen = jnp.where(is_last, lbl, jnp.uint32(BLOCK_LEN))
            # stash the *inputs* of each chunk's final compression for the
            # single-chunk ROOT recompute after the loop
            cv_last_in = [jnp.where(is_last, c, s)
                          for c, s in zip(cv, cv_last_in)]
            m_last = [jnp.where(is_last, mw, sw)
                      for mw, sw in zip(m, m_last)]
            blen_last = jnp.where(is_last, blen, blen_last)
            flags_last = jnp.where(is_last, flags, flags_last)
            out = _compress_cols(cv, m, counter_lo, counter_hi, blen, flags)
            cv = [jnp.where(active, o, c) for o, c in zip(out, cv)]
            return cv, cv_last_in, m_last, blen_last, flags_last

        init = (iv_cols, list(iv_cols), [zeros] * 16, zeros, zeros)
        cv, cv_last_in, m_last, blen_last, flags_last = jax.lax.fori_loop(
            0, MAX_LEAVES_PER_CHUNK, leaf_body, init)
        leaf_cv = [c.reshape(B, L) for c in cv]

        # single-chunk roots: recompress chunk 0's final block, ROOT set
        def chunk0(col):
            return col.reshape(B, L)[:, 0]

        root_single = _compress_cols(
            [chunk0(c) for c in cv_last_in], [chunk0(mw) for mw in m_last],
            jnp.zeros(B, dtype=jnp.uint32), jnp.zeros(B, dtype=jnp.uint32),
            chunk0(blen_last), chunk0(flags_last) | jnp.uint32(ROOT))

    # --- tree reduction: pair-merge, unpaired node rides up ----------------
    root_cv = [jnp.where(is_single, rs, jnp.uint32(0))
               for rs in root_single]
    return tree_reduce_cvs(leaf_cv, n_chunks, root_cv)


def tree_reduce_cvs(leaf_cv, counts, root_cv):
    """BLAKE3 tree reduction over per-input leaf chaining values.

    ``leaf_cv``: list of 8 (B, L) u32 columns; ``counts``: (B,) true leaf
    counts (>=1); ``root_cv``: list of 8 (B,) columns pre-seeded with the
    single-leaf roots (used where counts == 1).  Pair-merges level by
    level; an unpaired rightmost node rides up unchanged, reproducing
    BLAKE3's largest-power-of-two-left split exactly.  Returns (B, 8).
    """
    B = leaf_cv[0].shape[0]
    cvs = leaf_cv  # list of 8 (B, cur) arrays
    cur = leaf_cv[0].shape[1]
    while cur > 1:
        Pn = cur // 2
        left = [c[:, 0:2 * Pn:2] for c in cvs]   # (B, Pn)
        right = [c[:, 1:2 * Pn:2] for c in cvs]
        m = [l.reshape(-1) for l in left] + [r.reshape(-1) for r in right]
        lanes_p = B * Pn
        zero = jnp.zeros(lanes_p, dtype=jnp.uint32)
        ivc = [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), (lanes_p,))
               for i in range(8)]
        bl = jnp.full(lanes_p, BLOCK_LEN, dtype=jnp.uint32)
        merged = _compress_cols(ivc, m, zero, zero, bl,
                                jnp.full(lanes_p, PARENT, dtype=jnp.uint32))
        merged = [x.reshape(B, Pn) for x in merged]
        # the root merge (count 2 -> 1) always happens at pair 0
        zb = jnp.zeros(B, dtype=jnp.uint32)
        merged_root0 = _compress_cols(
            [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), (B,)) for i in range(8)],
            [l[:, 0] for l in left] + [r[:, 0] for r in right],
            zb, zb, jnp.full(B, BLOCK_LEN, dtype=jnp.uint32),
            jnp.full(B, PARENT | ROOT, dtype=jnp.uint32))
        pair_idx = jnp.arange(Pn, dtype=jnp.int32)
        pair_merges = (2 * pair_idx[None, :] + 1) < counts[:, None]  # (B, Pn)
        nxt = []
        for ci in range(8):
            col = jnp.where(pair_merges, merged[ci], left[ci])
            if cur % 2:
                col = jnp.concatenate([col, cvs[ci][:, -1:]], axis=1)
            nxt.append(col)
        is_root_merge = (counts == 2)
        root_cv = [jnp.where(is_root_merge, mr0, rc)
                   for mr0, rc in zip(merged_root0, root_cv)]
        cvs = nxt
        counts = jnp.where(counts > 1, (counts + 1) // 2, counts)
        cur = (cur + 1) // 2

    return jnp.stack(root_cv, axis=1)  # (B, 8) u32


# ---------------------------------------------------------------------------
# Pallas leaf kernel: the 16-block leaf scan entirely in VMEM.
#
# The XLA leaf scan materializes every intermediate state column in HBM
# (112 G-steps x 6 ops x 4 B per lane per block ~= 26 GB of traffic for a
# 256 MiB batch — measured ~62 ms, HBM-bound at ~8 GiB/s of payload).
# Here each grid step stages 1024 leaves (1 MiB of message words) into
# VMEM, runs all 16 compressions with the state resident, and writes back
# only the output + penultimate chaining values (64 KiB) — payload read
# once, ~10x less traffic.
# ---------------------------------------------------------------------------

_LEAF_LANES = 4096  # leaves per grid step: (32, 128) vector shape
_LROWS = _LEAF_LANES // 128


def _leaf_scan_kernel(nb_ref, lbl_ref, cidx_ref, w_ref, cv_ref, cvp_ref):
    """One grid step: (256, 1024) u32 word-major leaf messages ->
    (64, 128) output CVs + penultimate CVs (single-chunk ROOT recompute).

    State words live as (8, 128) tiles covering the step's 1024 lanes;
    the whole 16-block scan runs without touching HBM.  Mirrors the
    masking of :func:`digest_padded`'s leaf loop exactly.
    """
    nb = nb_ref[0]          # (R, 128) i32: blocks per lane
    lbl = lbl_ref[0]        # (R, 128) u32: last-block length
    counter = cidx_ref[0].astype(jnp.uint32)  # (R, 128): chunk index in row
    zero = jnp.zeros((_LROWS, 128), dtype=jnp.uint32)
    iv_cols = [jnp.broadcast_to(jnp.uint32(_IV_NP[i]), (_LROWS, 128)) + zero
               for i in range(8)]

    def body(blk, carry):
        cv, cv_pre = carry
        # words arrive pre-tiled as (256, R, 128): word bw of the step's
        # lanes IS an (R, 128) tile (a flat row would relayout across
        # lanes on every read); R=32 rows give each vector op 4096 lanes,
        # hiding the G chain's op latency (R=8 measured 2x slower)
        m = [w_ref[0, blk * 16 + w] for w in range(16)]
        active = blk < nb
        is_last = blk == nb - 1
        flags = jnp.where(blk == 0, jnp.uint32(CHUNK_START), jnp.uint32(0))
        flags = jnp.where(is_last, flags | jnp.uint32(CHUNK_END), flags)
        blen = jnp.where(is_last, lbl, jnp.uint32(BLOCK_LEN))
        cv_pre = [jnp.where(is_last, c, p) for c, p in zip(cv, cv_pre)]
        out = _compress_cols(cv, m, counter, zero, blen, flags)
        cv = [jnp.where(active, o, c) for o, c in zip(out, cv)]
        return cv, cv_pre

    cv, cv_pre = jax.lax.fori_loop(
        0, MAX_LEAVES_PER_CHUNK, body, (iv_cols, list(iv_cols)))
    for i in range(8):
        cv_ref[0, i * _LROWS:(i + 1) * _LROWS, :] = cv[i]
        cvp_ref[0, i * _LROWS:(i + 1) * _LROWS, :] = cv_pre[i]


@functools.lru_cache(maxsize=1)
def pallas_digest_available() -> bool:
    """True when the Pallas leaf kernel lowers and matches the XLA path."""
    import os

    if os.environ.get("BKW_PALLAS_DIGEST", "1") == "0":
        return False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:  # pragma: no cover
        return False
    if platform not in ("tpu", "axon"):
        return False
    try:
        rng = np.random.default_rng(3)
        # B*L = 12288 lanes = 3 grid steps (> _LEAF_LANES): the probe must
        # exercise the multi-grid-step index map on the live runtime — a
        # g>1-specific mis-lowering would otherwise pass a g=1 probe and
        # silently corrupt digests in production class tiles.
        B = 1536
        buf = rng.integers(0, 256, (B, 8 * CHUNK_LEN), dtype=np.uint8)
        lens = np.resize(
            np.array([0, 1, 64, 65, 1024, 1025, 4000, 8192], np.int32), B)
        a = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens),
                                     L=8, pallas=False))
        b = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens),
                                     L=8, pallas=True))
        assert B * 8 > _LEAF_LANES  # keep the probe multi-step if consts move
        return bool((a == b).all())
    except Exception:  # pragma: no cover - lowering failure
        return False


def _leaf_scan_pallas(words: jnp.ndarray, n_blocks: jnp.ndarray,
                      last_len: jnp.ndarray, chunk_idx: jnp.ndarray,
                      interpret: bool = False):
    """(lanes, 16, 16) u32 leaf words -> (lanes, 8) cv, (lanes, 8) cv_pre.

    ``interpret=True`` runs the kernel body in the pallas interpreter
    (CPU tests prove the logic; the Mosaic lowering itself is proven by
    :func:`pallas_digest_available`'s runtime parity gate).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lanes = words.shape[0]
    g = -(-lanes // _LEAF_LANES)
    pad = g * _LEAF_LANES - lanes

    def pad_to(x, fill=0):
        if pad:
            x = jnp.concatenate(
                [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)])
        return x

    # word-major per grid step, each word an (R, 128) lane tile:
    # (g, 256, R, 128), dim 1 = block*16 + word
    wt = pad_to(words.reshape(lanes, 256)).reshape(
        g, _LROWS, 128, 256).transpose(0, 3, 1, 2)
    nb = pad_to(n_blocks.astype(jnp.int32)).reshape(g, _LROWS, 128)
    lbl = pad_to(last_len.astype(jnp.uint32)).reshape(g, _LROWS, 128)
    cidx = pad_to(chunk_idx.astype(jnp.int32)).reshape(g, _LROWS, 128)
    cv, cvp = pl.pallas_call(
        _leaf_scan_kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((1, _LROWS, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LROWS, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LROWS, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 256, _LROWS, 128), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 8 * _LROWS, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8 * _LROWS, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((g, 8 * _LROWS, 128), jnp.uint32),
                   jax.ShapeDtypeStruct((g, 8 * _LROWS, 128), jnp.uint32)],
        interpret=interpret,
    )(nb, lbl, cidx, wt)
    # (g, 8 words, R, 128) -> (lanes, 8)
    def unpack(x):
        x = x.reshape(g, 8, _LROWS, 128).transpose(0, 2, 3, 1)
        return x.reshape(g * _LEAF_LANES, 8)[:lanes]

    return unpack(cv), unpack(cvp)


def _root_cv_to_digests(root_cv: np.ndarray) -> list:
    out = np.ascontiguousarray(root_cv.astype("<u4")).tobytes()
    return [out[i * 32:(i + 1) * 32] for i in range(root_cv.shape[0])]


def _leaf_bucket(n_bytes: int) -> int:
    """Smallest configured (B, L) leaf bucket holding ``n_bytes``."""
    n_chunks = max(1, -(-n_bytes // CHUNK_LEN))
    for b in defaults.BLAKE3_LEAF_BUCKETS:
        if n_chunks <= b:
            return b
    return n_chunks  # oversized input: exact-size compile


def _batch_bucket(n: int) -> int:
    """Batch sizes are padded to powers of two (>=8) to bound recompiles."""
    b = 8
    while b < n:
        b *= 2
    return b


def bucketed_batches(datas):
    """Group inputs by leaf bucket; yields (indices, buf, lens, L)."""
    groups = {}
    for i, d in enumerate(datas):
        groups.setdefault(_leaf_bucket(len(d)), []).append(i)
    for L, idxs in sorted(groups.items()):
        B = _batch_bucket(len(idxs))
        buf = np.zeros((B, L * CHUNK_LEN), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for row, i in enumerate(idxs):
            d = datas[i]
            buf[row, :len(d)] = np.frombuffer(bytes(d), dtype=np.uint8)
            lens[row] = len(d)
        yield idxs, buf, lens, L


def blake3_many_tpu(datas) -> list:
    """Batched digests on the device; bit-exact vs
    :func:`backuwup_tpu.ops.blake3_cpu.blake3_hash`."""
    datas = list(datas)
    out = [None] * len(datas)
    for idxs, buf, lens, L in bucketed_batches(datas):
        root = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens),
                                        L=L))
        digests = _root_cv_to_digests(root)
        for row, i in enumerate(idxs):
            out[i] = digests[row]
    return out
