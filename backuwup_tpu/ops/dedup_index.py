"""Sharded dedup-index probe: the global blob-hash table in TPU HBM.

The reference's dedup authority is a host-memory sorted vector with binary
search (``blob_index.rs:143-148``) — one lookup at a time.  Configs #4-#5 of
``BASELINE.json`` lift it to the device: an open-addressed hash table whose
slots live in HBM, **sharded across the mesh by hash**, probed for whole
batches of fingerprints at once with the routing done by XLA collectives
over ICI:

* Each blob hash (BLAKE3, 32 bytes) is reduced to four u32 words; the table
  stores 128-bit keys + a 32-bit value (packfile slot).  Keys being BLAKE3
  output, slot indices and shard routing can use hash words directly — no
  second hash function needed.
* A query batch sharded ``P('data')`` is ``all_gather``-ed along the axis;
  each device linearly probes only the queries whose owner shard is itself
  and contributes masked results combined with ``psum`` — queries ride ICI,
  table rows never move.
* Inserts are functional: ``insert`` returns the next table state (XLA
  donates the buffer, so the update is in place on device).  Linear probing
  is a ``fori_loop`` over MAX_PROBES with vectorized gathers.
* Batch-internal duplicates are pre-deduplicated host-side by the caller
  (the snapshot packer already serializes per-batch inserts); device insert
  handles cross-batch dedup against the resident table.

CPU/TPU equivalence: :class:`backuwup_tpu.snapshot.blob_index.BlobIndex` is
the reference semantics; tests assert identical found/new classification.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..utils.jax_compat import shard_map
from .. import defaults

KEY_WORDS = 4  # 128-bit stored fingerprint of the 256-bit blake3 hash

# `lost` vector codes returned by the device insert kernel:
LOST_RACE = 1  # lost an intra-batch empty-slot race — retryable
LOST_EXHAUSTED = 2  # probe sequence exhausted (shard full) — not retryable


class DedupIndexFull(RuntimeError):
    """A shard's probe sequence was exhausted; the table needs resizing."""


def hashes_to_queries(hashes) -> np.ndarray:
    """List of 32-byte digests -> (N, 4) u32 query words (first 16 bytes)."""
    if len(hashes) == 0:
        return np.zeros((0, KEY_WORDS), dtype=np.uint32)
    buf = np.frombuffer(b"".join(bytes(h)[:16] for h in hashes),
                        dtype="<u4").reshape(-1, KEY_WORDS)
    return np.ascontiguousarray(buf)


def queries_from_cvs(acc):
    """Device-resident analog of :func:`hashes_to_queries`.

    ``acc`` is a digest stage's ``(N, 8)`` u32 root-chaining-value
    accumulator; the 32-byte digest is the little-endian serialization of
    those words, so its first 16 bytes ARE words 0..3 — slicing on device
    is numerically identical to downloading the digests and calling
    :func:`hashes_to_queries`, with zero host round trips.  Unplaced
    accumulator rows stay all-zero (``digest_pool.pool_digest`` scatters
    only placed chunks into a zero-initialized accumulator), and all-zero
    queries are exactly the probe kernel's padding convention, so the
    whole slab feeds :meth:`ShardedDedupIndex.insert_device` unmasked.
    (A real digest whose first 16 bytes happen to be zero — probability
    2^-128 — reads as padding and classifies "new"; the host authority
    still wins, the same stance as the 128-bit key truncation.)
    """
    return acc[:, :KEY_WORDS]


@dataclass
class ShardedDedupIndex:
    """Functional sharded hash table; state lives on the mesh."""

    mesh: Mesh
    axis: str
    capacity: int  # slots per shard
    keys: jax.Array  # (D, capacity, KEY_WORDS) u32, 0-key = empty
    values: jax.Array  # (D, capacity) u32
    max_probes: int

    @classmethod
    def create(cls, mesh: Mesh, axis: str = "data",
               capacity: int = defaults.DEDUP_SHARD_CAPACITY,
               max_probes: int = defaults.DEDUP_MAX_PROBES):
        d = mesh.shape[axis]
        sharding = NamedSharding(mesh, P(axis))
        keys = jax.device_put(
            jnp.zeros((d, capacity, KEY_WORDS), dtype=jnp.uint32), sharding)
        values = jax.device_put(
            jnp.zeros((d, capacity), dtype=jnp.uint32), sharding)
        return cls(mesh=mesh, axis=axis, capacity=capacity, keys=keys,
                   values=values, max_probes=max_probes)

    # --- device kernels ----------------------------------------------------

    def _fn(self, insert: bool):
        return _build_probe_fn(self.mesh, self.axis, self.capacity,
                               self.max_probes, insert)

    def probe(self, queries: np.ndarray) -> np.ndarray:
        """found[i] = value+1 if present else 0 (u32)."""
        q, n = _pad_queries(queries, self.mesh.shape[self.axis])
        found = self._fn(False)(self.keys, self.values, q)
        return np.asarray(found).reshape(-1)[:n]

    def insert(self, queries: np.ndarray, values: np.ndarray) -> np.ndarray:
        """Insert new keys (found keys keep their value); returns the same
        found-vector as probe (pre-insert state).

        Distinct new keys racing for one empty slot within a batch are
        detected on device and retried here, so a returned 0 ("new") always
        ends with the key resident."""
        queries = np.asarray(queries, dtype=np.uint32).reshape(-1, KEY_WORDS)
        values = np.asarray(values, dtype=np.uint32).reshape(-1)
        out = np.zeros(queries.shape[0], dtype=np.uint32)
        pending = np.arange(queries.shape[0])
        first = True
        while pending.size:
            found, lost = self._insert_once(queries[pending], values[pending])
            if np.any(lost == LOST_EXHAUSTED):
                raise DedupIndexFull(
                    f"linear probe exhausted after {self.max_probes} steps; "
                    f"shard too full/clustered — resize capacity "
                    f"(currently {self.capacity}/shard)")
            if first:
                out[pending] = found
                first = False
            pending = pending[np.asarray(lost) == LOST_RACE]
        return out

    def insert_device(self, q_dev, v_dev):
        """Device-resident insert: dispatches and returns
        ``(found_dev, lost_dev)`` WITHOUT any host synchronization — races
        retry on device, so callers batch many inserts back to back and
        validate the (async-downloaded) ``lost`` vectors once at the end
        (`lost != 0` after the in-device retries means the table needs
        resizing; see :meth:`insert`).

        This is the path the backup engine's device-dedup uses: digests
        land in HBM from the digest stage and never round-trip the host
        before probing — the analog of the reference's in-memory
        ``blob_index.rs:143-148`` lookup, at batch granularity.
        """
        self.keys, self.values, found, lost = self._fn(True)(
            self.keys, self.values, q_dev, v_dev)
        return found, lost

    def probe_device(self, q_dev):
        """Device-resident probe: dispatch WITHOUT host synchronization;
        returns the sharded found-vector as a device array (``value+1``
        if present else 0).  The steady-state read path: sustained
        global-dedup queries chain on device back to back, the caller
        downloads results when (and only when) it needs them."""
        return self._fn(False)(self.keys, self.values, q_dev)

    def grown(self, new_capacity: int) -> "ShardedDedupIndex":
        """Capacity-doubled (or more) copy with the resident keys
        re-hashed ON DEVICE — shard routing depends only on the hash
        words, so every key stays on its shard and migration never
        touches the host or ICI (VERDICT r2 weak 8: the old reseed
        re-uploaded every known hash per grow)."""
        if new_capacity <= self.capacity:
            raise ValueError("grown() requires a larger capacity")
        d = self.mesh.shape[self.axis]
        sharding = NamedSharding(self.mesh, P(self.axis))
        nk = jax.device_put(
            jnp.zeros((d, new_capacity, KEY_WORDS), dtype=jnp.uint32),
            sharding)
        nv = jax.device_put(
            jnp.zeros((d, new_capacity), dtype=jnp.uint32), sharding)
        fn = _build_migrate_fn(self.mesh, self.axis, self.capacity,
                               new_capacity, self.max_probes)
        nk, nv, exhausted = fn(self.keys, self.values, nk, nv)
        if int(np.asarray(exhausted).sum()) > 0:
            raise DedupIndexFull("migration exhausted probes; "
                                 "grow further")
        return ShardedDedupIndex(
            mesh=self.mesh, axis=self.axis, capacity=new_capacity,
            keys=nk, values=nv, max_probes=self.max_probes)

    def dump(self):
        """Download every live entry to the host: ``(M, KEY_WORDS)`` u32
        keys plus ``(M,)`` u32 values (empty slots — all-zero keys —
        dropped).  This is the tiered index's demotion path
        (``dedupstore/tiered.py``): the one sanctioned whole-table
        download, rare by construction because it only runs when the
        table hits the HBM budget cap."""
        keys = np.asarray(self.keys).reshape(-1, KEY_WORDS)
        values = np.asarray(self.values).reshape(-1)
        live = keys.any(axis=1)
        return keys[live], values[live]

    def _insert_once(self, queries: np.ndarray, values: np.ndarray):
        d = self.mesh.shape[self.axis]
        q, n = _pad_queries(queries, d)
        v = np.zeros(q.shape[0] * q.shape[1], dtype=np.uint32)
        v[:n] = values
        v = jax.device_put(jnp.asarray(v.reshape(d, -1)),
                           NamedSharding(self.mesh, P(self.axis)))
        self.keys, self.values, found, lost = self._fn(True)(
            self.keys, self.values, q, v)
        return (np.asarray(found).reshape(-1)[:n],
                np.asarray(lost).reshape(-1)[:n])


def _pad_queries(queries: np.ndarray, d: int):
    queries = np.asarray(queries, dtype=np.uint32).reshape(-1, KEY_WORDS)
    n = queries.shape[0]
    padded = max(d, -(-n // d) * d)
    q = np.zeros((padded, KEY_WORDS), dtype=np.uint32)
    q[:n] = queries
    return q.reshape(d, -1, KEY_WORDS), n


@functools.lru_cache(maxsize=64)
def _build_probe_fn(mesh: Mesh, axis: str, capacity: int, max_probes: int,
                    insert: bool):
    """Compile the shard_map probe/insert program for one mesh config."""
    n_dev = mesh.shape[axis]

    def local_probe(keys, values, q):
        """Probe the local shard for queries q (N, 4); returns
        (found (N,), slot (N,), empty_slot_found (N,))."""
        n = q.shape[0]
        start = (q[:, 1] % jnp.uint32(capacity)).astype(jnp.int32)
        is_empty_q = jnp.all(q == 0, axis=1)

        def body(p, carry):
            done, found, slot = carry
            idx = (start + p) % capacity
            k = keys[idx]  # (N, 4) gather
            hit = jnp.all(k == q, axis=1)
            empty = jnp.all(k == 0, axis=1)
            # first terminal event wins: hit -> found; empty -> insert here
            newly = ~done & (hit | empty)
            found = jnp.where(newly & hit, values[idx] + 1, found)
            slot = jnp.where(newly, idx, slot)
            done = done | hit | empty
            return done, found, slot

        done0 = is_empty_q  # padding queries probe nothing
        # derive loop-carry inits from q so they share its vma under shard_map
        found0 = q[:, 0] * jnp.uint32(0)
        slot0 = found0.astype(jnp.int32) - 1
        done, found, slot = jax.lax.fori_loop(0, max_probes, body,
                                              (done0, found0, slot0))
        return found, slot, done

    def shard_fn(keys, values, q, *ins_vals):
        # keys/values: local shard (1, capacity, 4)/(1, capacity)
        # q: local query slice (1, Q/D, 4)
        keys = keys[0]
        values = values[0]
        me = jax.lax.axis_index(axis)
        # queries ride ICI to every shard; table rows never move
        allq = jax.lax.all_gather(q[0], axis).reshape(-1, KEY_WORDS)  # (Q, 4)
        owner = (allq[:, 0] % jnp.uint32(n_dev)).astype(jnp.int32)
        mine = owner == me
        # non-owned queries become empty (probe nothing, contribute 0)
        q_masked = jnp.where(mine[:, None], allq, jnp.uint32(0))
        if insert:
            allv = jax.lax.all_gather(ins_vals[0][0], axis).reshape(-1)
            empty_q = jnp.all(allq == 0, axis=1)

            def attempt(keys, values, active):
                """One probe+scatter round over the ``active`` queries.

                Two *different* new keys landing on the same empty slot:
                last write wins; losers are detected by re-reading the
                slot and retried (they then probe past it).
                """
                qa = jnp.where(active[:, None], allq, jnp.uint32(0))
                found, slot, done = local_probe(keys, values, qa)
                is_new = active & (found == 0) & (slot >= 0) & ~empty_q
                tgt = jnp.where(is_new, slot, capacity)  # capacity=dropped
                upd_keys = keys.at[tgt].set(
                    jnp.where(is_new[:, None], allq, jnp.uint32(0)),
                    mode="drop")
                upd_vals = values.at[tgt].set(
                    jnp.where(is_new, allv, jnp.uint32(0)), mode="drop")
                stored = upd_keys[jnp.clip(slot, 0, capacity - 1)]
                race = is_new & ~jnp.all(stored == allq, axis=1)
                # done==False after max_probes means neither a hit nor an
                # empty slot was seen: the key was NOT inserted.  Reported
                # distinctly so the host resizes instead of dropping keys.
                exhausted = active & ~done
                return upd_keys, upd_vals, found, race, exhausted

            keys, values, found, race, exh = attempt(keys, values, mine)
            found = jnp.where(mine, found, jnp.uint32(0))

            # retry races ON DEVICE (shard-local, collective-free, so
            # divergent trip counts across shards are fine); each round
            # strictly shrinks the race set — one winner per contested
            # slot — large batches at moderate load factors start with
            # thousands of birthday collisions (measured ~1.9k for a
            # 250k-key batch at 12% load), so the cap is generous; any
            # residual goes back to the host loop as before
            def cond(st):
                _k, _v, race, _e, r = st
                return jnp.any(race) & (r < 10)

            def body(st):
                keys, values, race, exh, r = st
                # INVARIANT: `_f` (found) is discarded because a retried
                # query is provably a NEW key — its round-1 probe walked
                # the chain to the contested EMPTY slot without a key
                # match, and the slot it lost was taken by a *different*
                # key (race requires stored != allq).  Re-probing can only
                # pass that now-occupied slot and continue to the next
                # empty one; it can never discover a match for this key.
                # If local_probe's semantics ever change (e.g. deletions
                # leaving tombstones a retry could match), `_f` must be
                # ORed into `found` instead of dropped.
                keys, values, _f, race2, exh2 = attempt(keys, values, race)
                return keys, values, race2, exh | exh2, r + 1

            keys, values, race, exh, _ = jax.lax.while_loop(
                cond, body, (keys, values, race, exh, jnp.int32(0)))
            lost = (race.astype(jnp.uint32) * jnp.uint32(LOST_RACE)
                    + exh.astype(jnp.uint32) * jnp.uint32(LOST_EXHAUSTED))
            found_all = jax.lax.psum(found, axis)
            lost_all = jax.lax.psum(lost, axis)
            myq = found_all.reshape(n_dev, -1)[me]
            mylost = lost_all.reshape(n_dev, -1)[me]
            return keys[None], values[None], myq[None], mylost[None]
        found, slot, done = local_probe(keys, values, q_masked)
        found = jnp.where(mine, found, jnp.uint32(0))
        found_all = jax.lax.psum(found, axis)
        myq = found_all.reshape(n_dev, -1)[me]
        return myq[None]

    in_specs = [P(axis), P(axis), P(axis)] + ([P(axis)] if insert else [])
    out_specs = (P(axis), P(axis), P(axis), P(axis)) if insert else P(axis)
    mapped = shard_map(shard_fn, mesh=mesh, in_specs=tuple(in_specs),
                           out_specs=out_specs)
    if insert:
        return jax.jit(mapped, donate_argnums=(0, 1))
    return jax.jit(mapped)


@functools.lru_cache(maxsize=32)
def _build_migrate_fn(mesh: Mesh, axis: str, old_capacity: int,
                      new_capacity: int, max_probes: int):
    """Shard-local rehash of every resident key into a larger table.

    All keys of one shard are distinct, so the only conflicts are two
    keys racing for the same empty slot in one vectorized round; the
    last-write-wins scatter guarantees one winner per contested slot, so
    the on-device retry loop strictly shrinks and terminates.
    """

    def shard_fn(old_k, old_v, new_k, new_v):
        ok, ov = old_k[0], old_v[0]
        nk, nv = new_k[0], new_v[0]
        live = ~jnp.all(ok == 0, axis=1)  # (old_capacity,)

        def probe(nk, q, pending):
            start = (q[:, 1] % jnp.uint32(new_capacity)).astype(jnp.int32)

            def body(p, carry):
                done, slot = carry
                idx = (start + p) % new_capacity
                k = nk[idx]
                empty = jnp.all(k == 0, axis=1)
                newly = ~done & empty
                slot = jnp.where(newly, idx, slot)
                done = done | empty
                return done, slot

            done0 = ~pending
            # derive from q so the init shares q's vma under shard_map
            slot0 = (q[:, 0] * jnp.uint32(0)).astype(jnp.int32) - 1
            return jax.lax.fori_loop(0, max_probes, body, (done0, slot0))

        def cond(state):
            _nk, _nv, pending, exhausted = state
            return jnp.any(pending) & ~exhausted

        def body(state):
            nk, nv, pending, _ = state
            done, slot = probe(nk, ok, pending)
            can = pending & (slot >= 0)
            exhausted = jnp.any(pending & ~done)
            tgt = jnp.where(can, slot, new_capacity)  # OOB = dropped
            nk2 = nk.at[tgt].set(
                jnp.where(can[:, None], ok, jnp.uint32(0)), mode="drop")
            nv2 = nv.at[tgt].set(
                jnp.where(can, ov, jnp.uint32(0)), mode="drop")
            stored = nk2[jnp.clip(slot, 0, new_capacity - 1)]
            won = can & jnp.all(stored == ok, axis=1)
            return nk2, nv2, pending & ~won, exhausted

        pending0 = live
        # exhausted0 derives from live so its vma matches body's output
        exhausted0 = jnp.any(live) & jnp.logical_not(jnp.any(live))
        nk, nv, _pending, exhausted = jax.lax.while_loop(
            cond, body, (nk, nv, pending0, exhausted0))
        return nk[None], nv[None], exhausted[None]

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)))
    return jax.jit(mapped, donate_argnums=(2, 3))
