"""Crypto & identity: root secret -> every key in the system.

Re-designs the reference key manager (``client/src/key_manager.rs:32-87``)
and mnemonic identity flow (``client/src/ui/cli.rs:26-77``):

* A single 32-byte **root secret** seeds a ChaCha20 deterministic stream;
  the first 32 bytes become the Ed25519 signing seed (the public key doubles
  as the client identity, ``shared/src/types.rs:4-10``), the next 32 the
  symmetric **backup secret**.
* Every content key is derived from the backup secret with HKDF-SHA256 and a
  context string (``key_manager.rs:80-86``): per-blob keys use the blob hash
  as context, the packfile-header key uses ``b"header"``, the index key
  ``b"index"`` (``packfile/pack.rs:58-79``, ``blob_index.rs:16-19``).
* The root secret round-trips through a human-readable **recovery phrase**
  in two equivalent forms, both accepted on restore: a 24-word mnemonic
  from an embedded 2048-word list (the reference prints a BIP39 mnemonic,
  ``cli.rs:55-77``; the wordlist is vendored in-package so restore never
  depends on an external file) and a Crockford-base32 group code with a
  checksum (canonical/compact form).

Host-side only: crypto is I/O-path work, not TPU compute (SURVEY.md §2.4).
"""

from __future__ import annotations

import hmac
import os
from dataclasses import dataclass

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers import Cipher
    from cryptography.hazmat.primitives.ciphers.algorithms import ChaCha20
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ModuleNotFoundError:  # containers without the wheel: libcrypto shim
    from .utils.compat_crypto import (
        Cipher,
        ChaCha20,
        Ed25519PrivateKey,
        Ed25519PublicKey,
        HKDF,
        hashes,
        serialization,
    )

ROOT_SECRET_LEN = 32
KEY_LEN = 32


def _chacha_stream(seed: bytes, length: int) -> bytes:
    """Deterministic expansion of the root secret (CSPRNG analog of the
    reference's seeded rand_chacha, ``key_manager.rs:42-49``)."""
    cipher = Cipher(ChaCha20(seed, b"\x00" * 16), mode=None)
    return cipher.encryptor().update(b"\x00" * length)


def hkdf_derive(secret: bytes, info: bytes, length: int = KEY_LEN) -> bytes:
    """HKDF-SHA256(extract(no salt) || expand(info)) — key_manager.rs:80-86."""
    return HKDF(algorithm=hashes.SHA256(), length=length, salt=None,
                info=info).derive(secret)


@dataclass(frozen=True)
class KeyManager:
    """All client keys, deterministically derived from the root secret."""

    root_secret: bytes
    signing_key: Ed25519PrivateKey
    backup_secret: bytes

    @classmethod
    def generate(cls) -> "KeyManager":
        return cls.from_secret(os.urandom(ROOT_SECRET_LEN))

    @classmethod
    def from_secret(cls, root_secret: bytes) -> "KeyManager":
        if len(root_secret) != ROOT_SECRET_LEN:
            raise ValueError("root secret must be 32 bytes")
        stream = _chacha_stream(root_secret, 64)
        signing_key = Ed25519PrivateKey.from_private_bytes(stream[:32])
        return cls(root_secret=bytes(root_secret), signing_key=signing_key,
                   backup_secret=stream[32:64])

    @property
    def client_id(self) -> bytes:
        """32-byte Ed25519 public key == identity (types.rs:4-10)."""
        return self.signing_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

    def sign(self, message: bytes) -> bytes:
        return self.signing_key.sign(bytes(message))

    def derive_backup_key(self, info: bytes, length: int = KEY_LEN) -> bytes:
        return hkdf_derive(self.backup_secret, bytes(info), length)


def verify_signature(client_id: bytes, message: bytes, signature: bytes) -> bool:
    """Ed25519 verify; mirrors ``verify_strict`` use at every trust boundary
    (``net_p2p/handle_connections.rs:194-204``, server
    ``client_auth_manager.rs:74-78``)."""
    try:
        Ed25519PublicKey.from_public_bytes(bytes(client_id)).verify(
            bytes(signature), bytes(message))
        return True
    except Exception:
        return False


# --------------------------------------------------------------------------
# Recovery phrase: Crockford-base32 groups + checksum (BIP39-mnemonic analog)
# --------------------------------------------------------------------------

_B32 = "0123456789abcdefghjkmnpqrstvwxyz"  # Crockford (no i, l, o, u)
_B32_INV = {c: i for i, c in enumerate(_B32)}
_B32_INV.update({"i": 1, "l": 1, "o": 0})  # transcription forgiveness
_CHECK_LEN = 4
_GROUP = 8


def _check_tag(secret: bytes) -> bytes:
    return hmac.new(b"backuwup-recovery-v1", secret, "sha256").digest()


def _checksum(secret: bytes) -> str:
    v = int.from_bytes(_check_tag(secret)[:4], "big")
    return "".join(_B32[(v >> (5 * i)) & 31] for i in range(_CHECK_LEN))


def secret_to_phrase(secret: bytes) -> str:
    """32-byte secret -> 7 dash-separated groups (52 data + 4 check chars)."""
    if len(secret) != ROOT_SECRET_LEN:
        raise ValueError("root secret must be 32 bytes")
    v = int.from_bytes(secret, "big")
    chars = "".join(_B32[(v >> (5 * i)) & 31] for i in range(52))  # 260 bits
    chars += _checksum(secret)
    return "-".join(chars[i:i + _GROUP] for i in range(0, len(chars), _GROUP))


def phrase_to_secret(phrase: str) -> bytes:
    """Inverse of :func:`secret_to_phrase`; raises ValueError on typos."""
    chars = phrase.strip().lower().replace("-", "").replace(" ", "")
    if len(chars) != 52 + _CHECK_LEN:
        raise ValueError("recovery phrase must have 56 characters")
    try:
        digits = [_B32_INV[c] for c in chars]
    except KeyError as e:
        raise ValueError(f"invalid character in recovery phrase: {e}") from None
    v = 0
    for i, d in enumerate(digits[:52]):
        v |= d << (5 * i)
    if v >= 1 << 256:
        raise ValueError("recovery phrase out of range")
    secret = v.to_bytes(32, "big")
    if "".join(_B32[d] for d in digits[52:]) != _checksum(secret):
        raise ValueError("recovery phrase checksum mismatch")
    return secret


# --------------------------------------------------------------------------
# Recovery phrase, word form: 24 words from the embedded 2048-word list
# (the reference prints a BIP39 mnemonic via the bip39 crate, cli.rs:55-77;
# here the wordlist is vendored in-package, see backuwup_tpu/wordlist.py)
# --------------------------------------------------------------------------

_WORD_BITS = 11
_WORD_COUNT = 24  # 264 bits = 256 secret + 8 checksum, the BIP39 shape


def secret_to_words(secret: bytes) -> str:
    """32-byte secret -> 24 space-separated words (word form of the phrase).

    Layout mirrors the base32 codec: little-endian 11-bit limbs of
    ``secret-int | checksum-byte << 256``.
    """
    if len(secret) != ROOT_SECRET_LEN:
        raise ValueError("root secret must be 32 bytes")
    from .wordlist import WORDS
    v = int.from_bytes(secret, "big") | _check_tag(secret)[4] << 256
    return " ".join(WORDS[(v >> (_WORD_BITS * i)) & 2047]
                    for i in range(_WORD_COUNT))


def _resolve_word(token: str, truncated: bool = False) -> int:
    """Word -> index; exact match, else unique >=4-char prefix (error
    tolerance for truncated transcriptions, BIP39's 4-letter convention).

    In a *truncated* phrase (one where some other token only resolved as
    a prefix) an exact match that is also a proper prefix of longer list
    words — ``bell`` vs ``belly``, ``cat`` vs ``catalog`` — is ambiguous:
    the transcriber may have cut either word down to it.  Full phrases
    keep resolving such words exactly, so round-trips never regress.
    """
    from .wordlist import WORD_INDEX, WORDS
    idx = WORD_INDEX.get(token)
    if idx is not None:
        if truncated and any(w != token and w.startswith(token)
                             for w in WORDS):
            raise ValueError(
                f"ambiguous word {token!r}: in a truncated phrase it may "
                "stand for itself or a longer word; spell it out in full")
        return idx
    if len(token) >= 4:
        hits = [i for i, w in enumerate(WORDS) if w.startswith(token)]
        if len(hits) == 1:
            return hits[0]
        if len(hits) > 1:
            raise ValueError(f"ambiguous word prefix: {token!r}")
    raise ValueError(
        f"unknown recovery word: {token!r} — not in this client's embedded "
        "wordlist; a BIP39 phrase from a different wallet or language "
        "cannot be imported here")


def words_to_secret(phrase: str) -> bytes:
    """Inverse of :func:`secret_to_words`; raises ValueError on typos."""
    from .wordlist import WORD_INDEX
    tokens = phrase.strip().lower().replace("-", " ").replace(",", " ").split()
    if len(tokens) != _WORD_COUNT:
        raise ValueError(f"word phrase must have {_WORD_COUNT} words "
                         f"(got {len(tokens)})")
    # truncation-style entry: at least one token is not a full list word,
    # so exact-but-prefix words elsewhere in the phrase become ambiguous
    truncated = any(tok not in WORD_INDEX for tok in tokens)
    v = 0
    for i, tok in enumerate(tokens):
        v |= _resolve_word(tok, truncated=truncated) << (_WORD_BITS * i)
    secret = (v & ((1 << 256) - 1)).to_bytes(32, "big")
    if v >> 256 != _check_tag(secret)[4]:
        raise ValueError(
            "word phrase checksum mismatch: this is not a phrase this "
            "client generated — a valid BIP39 phrase from another wallet "
            "uses a different checksum layout and cannot be imported")
    return secret


def parse_recovery(phrase: str) -> bytes:
    """Decode a recovery phrase in EITHER form (words or base32 groups).

    Tries the word form first (a base32 string can never resolve as 24
    list words), then the base32 form; surfaces the error of whichever
    form the input most resembles.
    """
    looks_wordy = len(phrase.split()) >= _WORD_COUNT // 2
    try:
        return words_to_secret(phrase)
    except ValueError as word_err:
        try:
            return phrase_to_secret(phrase)
        except ValueError as b32_err:
            raise ValueError(str(word_err if looks_wordy else b32_err)) \
                from None
