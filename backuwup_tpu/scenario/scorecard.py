"""Scenario scorecard: registry deltas + invariant samples -> pass/fail.

A scenario run (scenario/harness.py) captures a registry snapshot before
the first phase and after the last, and samples the durability invariant
gauges throughout.  This module turns those three inputs into the
scorecard the ISSUE/ROADMAP scenario-matrix item calls for:

* **counters** — per-series deltas of the interesting ``bkw_*_total``
  families (backups by outcome, shards rebuilt, audit verdicts, fault
  injections, engine busy rejections, retry firings, ...), so the card
  states what the run *did*, not what the process has ever done;
* **quantiles** — p50/p99 per labeled series of the latency histograms
  (span times, transfer wait/send, pack stages), estimated from the
  delta of the cumulative bucket counts with
  :func:`backuwup_tpu.obs.metrics.quantile_from_buckets`;
* **invariants** — seconds spent with a durability invariant violated
  (the headline), the worst status seen across samples, and the final
  sweep summary;
* **assertions** — the hard gates the harness derived from the scenario
  spec; ``passed`` is their conjunction.

Rendered as JSON (one machine-readable document), JSONL (the raw
invariant samples, one per line), or a human table (:meth:`render`).
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from ..obs import metrics as obs_metrics

#: Counter families whose deltas the card surfaces (a family absent from
#: either snapshot simply contributes nothing).
COUNTER_FAMILIES = (
    "bkw_backup_runs_total",
    "bkw_restore_runs_total",
    "bkw_audit_rounds_total",
    "bkw_audit_total",
    "bkw_repair_rounds_total",
    "bkw_repair_shards_rebuilt_total",
    "bkw_engine_busy_rejections_total",
    "bkw_transfers_total",
    "bkw_transfer_bytes_total",
    "bkw_fault_injections_total",
    "bkw_retry_attempts_total",
    "bkw_erasure_events_total",
    "bkw_durability_sweeps_total",
    "bkw_durability_violation_seconds_total",
    # performance plane (PR 7): pipeline dispatch accounting and the
    # per-peer estimator feed — the telemetry_flowing gate reads these
    "bkw_device_dispatch_total",
    "bkw_pipeline_stage_bytes_total",
    "bkw_peer_transfer_samples_total",
    # resumable WAN transfer plane (PR 8): chunked frames, byte-range
    # resume accounting, stall aborts, and capacity-aware placement
    "bkw_p2p_bytes_sent_total",
    "bkw_p2p_sequence_breaks_total",
    "bkw_transfer_parts_total",
    "bkw_transfer_resumes_total",
    "bkw_transfer_stalls_total",
    "bkw_transfer_bytes_resent_total",
    "bkw_placement_demotions_total",
    # crash-consistency plane (PR 9): startup recovery sweeps, what each
    # sweep reconciled, and the receiver-side partial janitor — the
    # recovery_clean gate's evidence trail
    "bkw_recovery_runs_total",
    "bkw_recovery_items_total",
    "bkw_partials_expired_total",
    # scale-out coordination plane (PR 10): the matchmaking economy's
    # throughput, deadline-heap expiry, per-route request counts, and
    # the write-behind store's commit modes (group vs direct is the
    # swarm bench's off-loop evidence)
    "bkw_matchmakings_total",
    "bkw_matchmaking_expired_total",
    "bkw_server_requests_total",
    "bkw_server_store_commits_total",
    # restore data plane (PR 11): shard-granular pull traffic per source
    # peer and the hedging policy's win/loss record — the restore
    # telemetry gate's evidence
    "bkw_restore_bytes_pulled_total",
    "bkw_restore_hedges_total",
    # snapshot lifecycle plane (PR 13): GC runs, what each swap retired,
    # and both ends of the reclaim protocol — the gc_* gates' evidence
    "bkw_gc_runs_total",
    "bkw_gc_snapshots_pruned_total",
    "bkw_gc_packfiles_dropped_total",
    "bkw_gc_packfiles_compacted_total",
    "bkw_gc_bytes_reclaimed_total",
    "bkw_reclaim_requests_total",
    "bkw_reclaim_bytes_freed_total",
    # live SLO plane (PR 20): recorder sweeps, budget breaches, and the
    # diagnosis reports the slo_* gates read
    "bkw_series_samples_total",
    "bkw_slo_breaches_total",
    "bkw_diagnosis_reports_total",
)

#: Histogram families quantiled in the card.
HISTOGRAM_FAMILIES = (
    "bkw_span_seconds",
    "bkw_transfer_wait_seconds",
    "bkw_transfer_send_seconds",
    "bkw_pack_stage_seconds",
    "bkw_peer_transfer_wait_seconds",
    "bkw_peer_transfer_send_seconds",
    "bkw_recovery_seconds",
    # scale-out coordination plane (PR 10)
    "bkw_server_request_seconds",
    "bkw_loop_stall_seconds",
    "bkw_server_store_batch_ops",
    # restore data plane (PR 11): how many distinct holders each stripe
    # actually drew from
    "bkw_restore_sources_per_stripe",
)


def _series_map(snapshot: dict, family: str) -> Dict[str, dict]:
    """{label-string: series dict} for one family of a snapshot."""
    fam = snapshot.get(family)
    if not fam:
        return {}
    out = {}
    for series in fam.get("series", []):
        labels = series.get("labels", {})
        key = ",".join(f'{k}={labels[k]}' for k in sorted(labels))
        out[key] = series
    return out


def _flat(family: str, key: str) -> str:
    return f"{family}{{{key}}}" if key else family


def counter_deltas(before: dict, after: dict,
                   families=COUNTER_FAMILIES) -> Dict[str, float]:
    """Positive per-series counter deltas, flattened to
    ``name{label=value,...}`` keys."""
    out: Dict[str, float] = {}
    for family in families:
        prior = _series_map(before, family)
        for key, series in _series_map(after, family).items():
            delta = float(series.get("value", 0.0)) - \
                float(prior.get(key, {}).get("value", 0.0))
            if delta > 0:
                out[_flat(family, key)] = round(delta, 6)
    return out


def _bucket_delta(before_b: Dict[str, int],
                  after_b: Dict[str, int]):
    """(bounds, per-bucket counts) from two cumulative exposition views."""
    keys = [k for k in after_b if k != "+Inf"]
    keys.sort(key=float)
    bounds = [float(k) for k in keys]
    cum_prev = 0
    counts: List[int] = []
    for k in keys:
        cum = int(after_b.get(k, 0)) - int(before_b.get(k, 0))
        counts.append(cum - cum_prev)
        cum_prev = cum
    inf = int(after_b.get("+Inf", 0)) - int(before_b.get("+Inf", 0))
    counts.append(inf - cum_prev)
    return bounds, counts


def histogram_quantiles(before: dict, after: dict,
                        families=HISTOGRAM_FAMILIES,
                        qs=(0.5, 0.99)) -> Dict[str, dict]:
    """Per-series p50/p99 (and count/mean) of the run's OWN observations
    — the bucket-count deltas, not the process lifetime."""
    out: Dict[str, dict] = {}
    for family in families:
        prior = _series_map(before, family)
        for key, series in _series_map(after, family).items():
            pb = prior.get(key, {})
            bounds, counts = _bucket_delta(pb.get("buckets", {}),
                                           series.get("buckets", {}))
            total = sum(counts)
            if total <= 0 or not bounds:
                continue
            entry = {"count": total}
            dsum = float(series.get("sum", 0.0)) - float(pb.get("sum", 0.0))
            entry["mean"] = round(dsum / total, 6)
            for q in qs:
                v = obs_metrics.quantile_from_buckets(bounds, counts, q)
                entry[f"p{int(q * 100)}"] = \
                    None if math.isnan(v) else round(v, 6)
            out[_flat(family, key)] = entry
    return out


@dataclass
class Assertion:
    """One hard gate: named, binary, with the evidence inline."""

    name: str
    passed: bool
    detail: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "passed": bool(self.passed),
                "detail": self.detail}


@dataclass
class Scorecard:
    scenario: str
    seed: int
    elapsed_s: float
    phases: List[str]
    counters: Dict[str, float]
    quantiles: Dict[str, dict]
    invariants: dict
    assertions: List[Assertion]
    samples: List[dict] = field(default_factory=list, repr=False)

    @property
    def passed(self) -> bool:
        return all(a.passed for a in self.assertions)

    def to_dict(self, with_samples: bool = False) -> dict:
        doc = {
            "scenario": self.scenario,
            "seed": self.seed,
            "elapsed_s": round(self.elapsed_s, 3),
            "passed": self.passed,
            "phases": list(self.phases),
            "counters": dict(self.counters),
            "quantiles": dict(self.quantiles),
            "invariants": dict(self.invariants),
            "assertions": [a.to_dict() for a in self.assertions],
        }
        if with_samples:
            doc["samples"] = list(self.samples)
        return doc

    def write_json(self, path) -> None:
        Path(path).write_text(
            json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n")

    def write_samples_jsonl(self, path) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for sample in self.samples:
                f.write(json.dumps(sample, sort_keys=True) + "\n")

    def render(self) -> str:
        """Human-readable card for the CLI / bench log."""
        lines = [f"scenario {self.scenario} (seed {self.seed}): "
                 f"{'PASS' if self.passed else 'FAIL'} "
                 f"in {self.elapsed_s:.1f}s over "
                 f"{len(self.phases)} phase(s)"]
        inv = self.invariants
        lines.append(
            f"  invariants: violation_seconds="
            f"{inv.get('violation_seconds', 0)} "
            f"worst_status={inv.get('worst_status', '?')} "
            f"final_status={inv.get('final', {}).get('status', '?')} "
            f"samples={inv.get('samples', 0)}")
        for name, value in sorted(self.counters.items()):
            lines.append(f"  {name} {value:g}")
        for name, entry in sorted(self.quantiles.items()):
            lines.append(
                f"  {name} p50={entry.get('p50')} p99={entry.get('p99')}"
                f" n={entry['count']}")
        for a in self.assertions:
            mark = "ok " if a.passed else "FAIL"
            lines.append(f"  [{mark}] {a.name}"
                         + (f" — {a.detail}" if a.detail else ""))
        return "\n".join(lines)


def build_scorecard(scenario: str, seed: int, elapsed_s: float,
                    phases: List[str], before: dict, after: dict,
                    samples: List[dict],
                    assertions: List[Assertion]) -> Scorecard:
    """Assemble the card from the harness's raw captures."""
    counters = counter_deltas(before, after)
    violation_s = sum(
        v for k, v in counters.items()
        if k.startswith("bkw_durability_violation_seconds_total"))
    worst = 0
    for sample in samples:
        worst = max(worst, int(sample.get("status_level", 0)))
    invariants = {
        "violation_seconds": round(violation_s, 3),
        "worst_status": ["ok", "degraded", "violated"][min(worst, 2)],
        "samples": len(samples),
        "final": samples[-1] if samples else {},
    }
    return Scorecard(scenario=scenario, seed=seed, elapsed_s=elapsed_s,
                     phases=phases, counters=counters,
                     quantiles=histogram_quantiles(before, after),
                     invariants=invariants, assertions=assertions,
                     samples=samples)
