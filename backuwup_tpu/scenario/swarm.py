"""Swarm harness: hundreds of control-plane clients against one server.

The chaos scenarios (``scenario/harness.py``) stress the DATA plane — a
handful of clients moving real bytes.  The coordination plane's scaling
question is the opposite shape: MANY clients, tiny requests, all landing
on one aiohttp process.  This module reuses the scenario machinery (the
:class:`~.harness.Phase` script, the sampler, the
:class:`~.scorecard.Scorecard` gates) but swaps the deployment: no
ClientApps, no packfiles — just N :class:`~..net.client.ServerClient`
identities driving registration, login, matchmaking, snapshot
registration, audit verdicts, and WS churn over loopback.

Phases
======

=============  ==========================================================
``register``   every swarm client registers, logs in, and connects its
               WS push channel; a configured subset is then poisoned
               with failing audit reports from distinct reporters so the
               matchmaker's audit-block path stays exercised under load
``swarm``      the measured window: every client loops over a seeded mix
               of storage requests (the matchmaking economy), snapshot
               registrations, audit verdicts, and — for churners — WS
               drops and reconnects; matchmakings/s is counted over
               exactly this window
``drain``      settle in-flight fulfills, flush the store off-loop, and
               capture the verdict facts: event-loop stall ceiling,
               whether any sqlite commit ran on the loop thread, and the
               p99 of ``bkw_server_request_seconds{route="/backups/request"}``
=============  ==========================================================

An event-loop **stall detector** runs through all phases: an asyncio
task that sleeps a fixed tick and records the overshoot.  A blocking
sqlite commit on the loop shows up as a stall spike (and its thread
ident lands in ``store.commit_threads``); the sharded tier must stay
under ``stall_budget_s`` while the legacy tier is expected to blow
through it — that contrast is bench config ``12_swarm``.

Load generation runs OFF the server's event loop: the swarm clients are
distributed over a small pool of worker threads, each with its own
asyncio loop and HTTP sessions.  Co-locating hundreds of client
coroutines on the server's loop would make the shared loop the
bottleneck and flatten any server-side difference (measured: both tiers
plateau at the same matchmakings/s when co-located); with the drivers
off-loop the main loop carries ONLY the server, so the stall detector
and the bench's tier contrast measure the thing under test.
"""

from __future__ import annotations

import asyncio
import contextlib
import math
import random
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import aiohttp

from .. import defaults
from ..crypto import KeyManager
from ..net import client as net_client
from ..net.matchmaking import _MATCHMAKINGS, ShardedMatchmaker
from ..net.ring import HashRing, partition_key
from ..net.server import _REQUEST_SECONDS, CoordinationServer
from ..net.serverstore import PartitionedServerStore, ReplicatedServerStore
from ..obs import metrics as obs_metrics
from .harness import Phase, ScenarioHarness
from . import scorecard as sc

_LOOP_STALL = obs_metrics.histogram(
    "bkw_loop_stall_seconds",
    "Event-loop scheduling overshoot observed by the swarm stall detector",
    buckets=obs_metrics.log_buckets(0.0005, 2.0, 14))


@dataclass(frozen=True)
class SwarmSpec:
    """One swarm run.  ``legacy=True`` assembles the single-lock
    StorageQueue over the direct-commit store (the bench baseline);
    otherwise the sharded matchmaker over the write-behind store."""

    name: str
    phases: tuple
    seed: int = 4242
    sample_interval_s: float = 0.25
    clients: int = 32
    duration_s: float = 2.5
    legacy: bool = False
    shards: Optional[int] = None
    #: bytes each storage request asks for (small keeps matches plentiful)
    request_bytes: int = 1 << 20
    min_peers: int = 1
    #: queued-request expiry; short enough that the deadline heap reaps
    #: during the run
    expiry_s: float = 20.0
    #: clients poisoned with failing audit reports during register
    audit_failers: int = 2
    #: PASSING audit reports preloaded per client before the run: the
    #: matchmaker's per-candidate ``audit_failing_reporters`` scan then
    #: has realistic weight (a long-lived deployment accretes verdict
    #: history), which the baseline pays inside its global lock on the
    #: event loop and the write-behind tier pays on the writer thread
    audit_history: int = 0
    #: every Nth client drops + reconnects its WS during the swarm (0 = off)
    churn_every: int = 8
    #: max tolerated event-loop stall for the non-legacy tier
    stall_budget_s: float = 0.25
    #: per-client think time ceiling between requests (seconds)
    think_s: float = 0.01
    #: load-generator threads the clients are distributed over (keeps
    #: the drivers off the server's event loop — see module docstring)
    workers: int = 8
    #: coordination nodes; >1 deploys the federation: N servers over a
    #: consistent-hash ring with work stealing + notify relay enabled
    #: (implies the sharded tier — ``legacy`` is ignored).  Each node
    #: gets its OWN :class:`~..net.serverstore.ReplicatedServerStore`
    #: with log shipping to ring successors, so node death is
    #: observable at the storage layer
    nodes: int = 1
    #: store partitions when ``nodes > 1`` (defaults to ``nodes``)
    partitions: Optional[int] = None
    #: opt-in BASELINE leg: front every node with one shared
    #: :class:`~..net.serverstore.PartitionedServerStore` (the pre-PR-17
    #: shortcut — killing a node can never lose rows because the store
    #: is shared, which is exactly what it fails to test)
    shared_store: bool = False
    #: probe cadence override for the replicated deployment (tier-1
    #: permakill must converge in well under a second)
    probe_interval_s: float = 0.25
    #: hard per-route p99 ceiling for the federation gate (only asserted
    #: when ``nodes > 1``; generous — loopback plus failover dial cost)
    p99_budget_s: float = 2.5


class _TokenStore:
    """The minimal Store surface ServerClient touches."""

    def __init__(self):
        self._token: Optional[bytes] = None

    def set_auth_token(self, token: Optional[bytes]) -> None:
        self._token = token

    def get_auth_token(self) -> Optional[bytes]:
        return self._token


class SwarmClient:
    """One simulated identity: deterministic keys, its own HTTP session
    and WS push channel, and a count of matches pushed to it."""

    def __init__(self, index: int, seed: int, addr,
                 ring: Optional[HashRing] = None,
                 node_addrs: Optional[Dict[str, str]] = None):
        self.index = index
        self.worker = None  # set by the harness when homed on a worker
        secret = (seed.to_bytes(8, "big", signed=False)
                  + index.to_bytes(8, "big")).ljust(32, b"\x77")
        self.keys = KeyManager.from_secret(secret)
        if ring is not None:
            # federation: dial the ring owner first, then its steal order
            # — the shape a published node list would hand a real client
            owner = ring.owner(bytes(self.keys.client_id))
            order = [owner] + ring.steal_order(owner)
            addr = [node_addrs[n] for n in order]
        self.client = net_client.ServerClient(
            self.keys, _TokenStore(), addr=addr, tls=False)
        self.matches = 0

        async def on_matched(_msg):
            self.matches += 1

        self.client.on_backup_matched = on_matched

    @property
    def client_id(self) -> bytes:
        return bytes(self.keys.client_id)

    async def connect(self) -> None:
        await self.client.register()
        await self.client.login()
        self.client.start_ws()
        await asyncio.wait_for(self.client.ws_connected.wait(), 15)

    async def rejoin_ws(self) -> None:
        """WS churn: drop the push channel (the server sees the client go
        offline and drops its queued entries at pop) and reconnect."""
        if self.client._ws_task is not None:
            self.client._ws_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self.client._ws_task
            self.client._ws_task = None
        self.client.ws_connected.clear()
        self.client.start_ws()
        await asyncio.wait_for(self.client.ws_connected.wait(), 15)

    async def close(self) -> None:
        await self.client.close()


class _Worker:
    """One load-generator thread: its own asyncio loop hosting a slice
    of the swarm's clients.  The harness submits phase coroutines with
    :meth:`submit` and awaits them via ``asyncio.wrap_future``."""

    def __init__(self, index: int):
        self.index = index
        self.clients: List[SwarmClient] = []
        #: per-worker fact counters, aggregated by the harness after each
        #: phase (threads must not race on the shared facts dict)
        self.counts = {"requests": 0, "errors": 0, "churns": 0}
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._ready = threading.Event()
        self.thread = threading.Thread(
            target=self._main, name=f"swarm-worker-{index}", daemon=True)
        self.thread.start()
        self._ready.wait(timeout=10)

    def _main(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        self._ready.set()
        try:
            self.loop.run_forever()
        finally:
            self.loop.close()

    def submit(self, coro) -> "asyncio.Future":
        """Schedule ``coro`` on this worker's loop; returns an awaitable
        for the CALLER's loop."""
        return asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(coro, self.loop))

    def stop(self) -> None:
        if self.loop is not None and self.loop.is_running():
            self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=10)


class LoopStallDetector:
    """Measures event-loop scheduling overshoot: sleep a fixed tick,
    record how late the wakeup lands.  Any handler blocking the loop —
    e.g. an inline sqlite commit — shows up as a stall at least as long
    as the block."""

    def __init__(self, tick_s: float = 0.02):
        self.tick_s = tick_s
        self.max_stall_s = 0.0
        self.total_stall_s = 0.0
        self.ticks = 0
        self._task: Optional[asyncio.Task] = None

    async def _loop(self) -> None:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.tick_s)
            stall = max(time.monotonic() - t0 - self.tick_s, 0.0)
            self.ticks += 1
            self.total_stall_s += stall
            self.max_stall_s = max(self.max_stall_s, stall)
            _LOOP_STALL.observe(stall)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None


class SwarmHarness(ScenarioHarness):
    """Scenario harness re-pointed at the coordination plane: same phase
    script/sampler/scorecard flow, a completely different deployment."""

    def __init__(self, spec: SwarmSpec, workdir: Path):
        super().__init__(spec, workdir)  # sets rng/samples/t0/facts
        self.spec: SwarmSpec = spec
        self.clients: List[SwarmClient] = []
        self.workers: List[_Worker] = []
        self.stalls = LoopStallDetector()
        self.facts = {"registered": 0, "requests": 0, "errors": 0,
                      "churns": 0, "swarm_matchmakings": 0,
                      "swarm_elapsed_s": 0.0, "matchmakings_per_s": 0.0,
                      "client_matches": 0, "max_stall_s": None,
                      "commits_on_loop": None, "p99_request_s": None,
                      "node_kills": 0, "failovers": 0,
                      "post_revive_matchmakings": None,
                      "total_matchmakings": 0, "negotiated_rows": None,
                      "permakills": 0, "promotions": 0,
                      "repl_promote_s": None,
                      "post_promote_matchmakings": None}
        self.servers: List[CoordinationServer] = []
        self.ring: Optional[HashRing] = None
        self.node_ids: List[str] = []
        self.peer_urls: Dict[str, str] = {}
        self.store = None
        #: node id -> per-node store (replicated deployment)
        self.stores: Dict[str, ReplicatedServerStore] = {}
        self._permakilled: set = set()

    # --- lifecycle ---------------------------------------------------------

    async def setup(self) -> None:
        spec = self.spec
        self._saved = {"BACKUP_REQUEST_EXPIRY_S":
                       defaults.BACKUP_REQUEST_EXPIRY_S}
        defaults.BACKUP_REQUEST_EXPIRY_S = spec.expiry_s
        if spec.nodes > 1:
            self.node_ids = [f"node{i}" for i in range(spec.nodes)]
            self.ring = HashRing(self.node_ids)
            if spec.shared_store:
                # opt-in BASELINE: every node fronts the SAME partitioned
                # store, so killing a node loses connections and
                # in-flight handlers but by construction never rows
                self.store = await asyncio.to_thread(
                    PartitionedServerStore, str(self.workdir / "store"),
                    spec.partitions or spec.nodes)
                for _nid in self.node_ids:
                    srv = CoordinationServer(store=self.store,
                                             shards=spec.shards)
                    await srv.start()
                    self.servers.append(srv)
            else:
                # the real deployment shape: per-node replicated stores
                # with ring-successor log shipping (docs/server.md
                # §Replication) — node death is observable at the
                # storage layer and survived by promote-on-death
                self._saved["REPL_PROBE_INTERVAL_S"] = \
                    defaults.REPL_PROBE_INTERVAL_S
                defaults.REPL_PROBE_INTERVAL_S = spec.probe_interval_s
                for nid in self.node_ids:
                    store = await asyncio.to_thread(
                        ReplicatedServerStore,
                        str(self.workdir / "store" / nid), nid,
                        spec.partitions or spec.nodes)
                    self.stores[nid] = store
                    srv = CoordinationServer(store=store,
                                             shards=spec.shards)
                    await srv.start()
                    self.servers.append(srv)
                self.store = self.stores[self.node_ids[0]]
            self.peer_urls = {
                nid: f"http://127.0.0.1:{srv.port}"
                for nid, srv in zip(self.node_ids, self.servers)}
            for nid, srv in zip(self.node_ids, self.servers):
                srv.enable_federation(nid, self.ring, self.peer_urls)
            self.server = self.servers[0]
            self.server_port = self.server.port
        else:
            self.server = CoordinationServer(
                db_path=str(self.workdir / "server.db"),
                legacy=spec.legacy, shards=spec.shards)
            self.server_port = await self.server.start()
            self.servers = [self.server]
            self.store = self.server.db
        addr = f"127.0.0.1:{self.server_port}"
        node_addrs = {nid: url.removeprefix("http://")
                      for nid, url in self.peer_urls.items()}
        self.workers = [_Worker(i)
                        for i in range(max(1, min(spec.workers,
                                                  spec.clients)))]

        async def make(worker: _Worker, indices: List[int]) -> None:
            # created ON the worker loop so every asyncio primitive the
            # client owns (events, sessions, ws tasks) binds there
            for i in indices:
                c = SwarmClient(i, spec.seed, addr,
                                ring=self.ring, node_addrs=node_addrs)
                c.worker = worker
                worker.clients.append(c)

        await asyncio.gather(*(
            w.submit(make(w, list(range(wi, spec.clients,
                                        len(self.workers)))))
            for wi, w in enumerate(self.workers)))
        self.clients = sorted(
            (c for w in self.workers for c in w.clients),
            key=lambda c: c.index)
        if spec.audit_history:
            await asyncio.to_thread(self._preload_audit_history)
        self._mm0 = _MATCHMAKINGS.value()
        self.stalls.start()

    def _preload_audit_history(self) -> None:
        """Bulk-insert passing verdicts (setup-time, pre-measurement) so
        every client enters matchmaking with a populated audit window.
        Rows route by REPORTER partition when the store is partitioned —
        the same invariant the write path keeps, so the fan-out read
        sees every reporter's latest verdicts."""
        now = time.time()
        groups: Dict[int, Tuple] = {}
        for c in self.clients:
            reporter = self.clients[(c.index + 1) % len(self.clients)]
            rows_for = [
                (reporter.client_id, c.client_id, 1, "preload",
                 now - i * 1e-3)
                for i in range(self.spec.audit_history)]
            if self.stores:
                # replicated deployment: preload EVERY node's copy of
                # the reporter's partition (preloads bypass the op log,
                # so a later promotion must still see them)
                targets = [s.partition_for(reporter.client_id)
                           for s in self.stores.values()]
            elif isinstance(self.store, PartitionedServerStore):
                targets = [self.store.partition_for(reporter.client_id)]
            else:
                targets = [self.store]
            for store in targets:
                _, rows = groups.setdefault(id(store), (store, []))
                rows.extend(rows_for)
        for store, rows in groups.values():
            with getattr(store, "_direct_lock"):
                store._db.executemany(
                    "INSERT INTO audit_reports (reporter, peer, passed,"
                    " detail, timestamp) VALUES (?, ?, ?, ?, ?)", rows)
                store._db.commit()

    async def teardown(self) -> None:
        await self.stalls.stop()

        async def close_all(worker: _Worker) -> None:
            await asyncio.gather(*(c.close() for c in worker.clients),
                                 return_exceptions=True)

        for w in self.workers:
            with contextlib.suppress(Exception):
                await asyncio.wait_for(w.submit(close_all(w)), 30)
            w.stop()
        for srv in (self.servers or
                    ([self.server] if self.server is not None else [])):
            await srv.stop()
        if self.spec.nodes > 1:
            # injected stores: the servers don't own them, close here
            # (idempotent — a permakilled node's store is already closed)
            for store in (self.stores.values() if self.stores
                          else [self.store]):
                await asyncio.to_thread(store.close)
        for k, v in self._saved.items():
            setattr(defaults, k, v)

    # --- sampling (server-side gauges, not durability invariants) ----------

    def _sample_once(self) -> None:
        if self.server is None:
            return
        self.samples.append({
            "t": round(time.time() - self.t0, 3),
            "queue_depth": self.server.queue.pending(),
            "connected": self.server.connections.count(),
            "matchmakings": _MATCHMAKINGS.value(),
            "max_stall_s": round(self.stalls.max_stall_s, 4),
        })

    # --- phases ------------------------------------------------------------

    async def _phase_register(self, ph: Phase) -> None:
        """Register/login/WS-connect the whole swarm, bounded per-worker
        concurrency (the aiohttp server accepts, but hundreds of
        simultaneous handshakes still deserve a ceiling)."""

        async def register_all(worker: _Worker) -> None:
            gate = asyncio.Semaphore(6)

            async def one(c: SwarmClient) -> None:
                async with gate:
                    await c.connect()
                    worker.counts["registered"] = \
                        worker.counts.get("registered", 0) + 1

            await asyncio.gather(*(one(c) for c in worker.clients))

        try:
            await asyncio.gather(*(w.submit(register_all(w))
                                   for w in self.workers))
        finally:
            self.facts["registered"] = sum(
                w.counts.get("registered", 0) for w in self.workers)
        # poison the tail clients with failing audit verdicts from enough
        # DISTINCT reporters to trip the matchmaker's audit-block gate
        failers = self.clients[-self.spec.audit_failers:] \
            if self.spec.audit_failers else []
        for failer in failers:
            reporters = [c for c in self.clients if c is not failer][
                :defaults.AUDIT_SERVER_BLOCK_FAILURES]
            for rep in reporters:
                await rep.worker.submit(rep.client.audit_report(
                    failer.client_id, passed=False, detail="swarm poison"))

    async def _drive(self, c: SwarmClient, deadline: float,
                     counts: Dict) -> None:
        """One client's request loop (runs on its worker's loop): a
        seeded mix of matchmaking, snapshot registration, audit verdicts,
        and (for churners) WS drops.  Server-side rejections count as
        errors; the gate allows a small budget (a churned peer can race
        a fulfill)."""
        spec = self.spec
        rng = random.Random(spec.seed * 1000003 + c.index)
        churner = spec.churn_every and c.index % spec.churn_every == 3
        while time.monotonic() < deadline:
            roll = rng.random()
            try:
                if roll < 0.72:
                    await c.client.backup_storage_request(
                        spec.request_bytes, min_peers=spec.min_peers)
                    counts["requests"] += 1
                elif roll < 0.82:
                    await c.client.backup_done(rng.randbytes(32))
                elif roll < 0.92:
                    peer = self.clients[rng.randrange(len(self.clients))]
                    if peer is not c:
                        await c.client.audit_report(
                            peer.client_id, passed=True)
                elif churner:
                    await c.rejoin_ws()
                    counts["churns"] += 1
            except (net_client.ServerError, aiohttp.ClientError,
                    asyncio.TimeoutError, OSError):
                # server rejections, plus the connection errors a node
                # kill inflicts on requests already in flight (dial
                # failures against live fallbacks are absorbed by the
                # client's failover and never surface here)
                counts["errors"] += 1
            # always yield: a zero-think no-op roll must not spin the
            # worker loop and starve its sibling clients
            await asyncio.sleep(rng.uniform(0.0, spec.think_s)
                                if spec.think_s > 0 else 0)

    async def _drive_window(self, duration: float) -> None:
        """Run every client's request loop across all workers for
        ``duration`` seconds, folding the per-worker counters into the
        facts afterwards."""
        deadline = time.monotonic() + duration

        async def drive_all(worker: _Worker) -> None:
            await asyncio.gather(*(self._drive(c, deadline, worker.counts)
                                   for c in worker.clients))

        try:
            await asyncio.gather(*(w.submit(drive_all(w))
                                   for w in self.workers))
        finally:
            for key in ("requests", "errors", "churns"):
                self.facts[key] = sum(w.counts[key] for w in self.workers)

    async def _phase_swarm(self, ph: Phase) -> None:
        duration = ph.duration_s or self.spec.duration_s
        t0 = time.monotonic()
        mm0 = _MATCHMAKINGS.value()
        await self._drive_window(duration)
        elapsed = time.monotonic() - t0
        made = _MATCHMAKINGS.value() - mm0
        self.facts["swarm_elapsed_s"] = round(elapsed, 3)
        self.facts["swarm_matchmakings"] = int(made)
        self.facts["matchmakings_per_s"] = round(made / elapsed, 2)

    async def _phase_nodekill(self, ph: Phase) -> None:
        """Federation churn: stop a non-primary node mid-run (its homed
        clients fail over along their ring order), keep driving, revive
        a fresh server over the SAME shared store on the SAME port,
        re-enable federation, and drive again.  The gates downstream
        assert no matchmaking's durable rows were lost across the kill
        and that matches flow again after the revive."""
        spec = self.spec
        if len(self.servers) < 2:
            raise RuntimeError("nodekill phase requires nodes > 1")
        window = (ph.duration_s or 1.6) / 2
        victim_i = 1
        nid = self.node_ids[victim_i]
        port = self.servers[victim_i].port
        await self.servers[victim_i].stop()
        self.facts["node_kills"] += 1
        await self._drive_window(window)
        store = self.stores.get(nid, self.store)
        revived = CoordinationServer(store=store, shards=spec.shards)
        await revived.start(port=port)
        revived.enable_federation(nid, self.ring, self.peer_urls)
        if self.stores:
            # rejoin with the CURRENT topology, not the static ring view
            # — survivors may have promoted past us during the outage
            # (the operator hands a rejoining node the live owner map)
            for i, owner in self.servers[0].db.owners.items():
                revived.db.set_owner(i, owner)
        self.servers[victim_i] = revived
        mm0 = _MATCHMAKINGS.value()
        await self._drive_window(window)
        self.facts["post_revive_matchmakings"] = int(
            _MATCHMAKINGS.value() - mm0)

    async def _phase_permakill(self, ph: Phase) -> None:
        """The replication gate: permanently kill a partition-owning
        node mid-run — server stopped, store closed, never revived —
        then wait for a ring successor to detect the death and promote
        (replaying its shipped log tail), and drive load against the
        survivors.  Downstream gates assert zero durable matchmaking
        rows were lost even though the only server that ever APPLIED
        those partitions' writes is gone."""
        spec = self.spec
        if not self.stores:
            raise RuntimeError(
                "permakill phase requires per-node replicated stores"
                " (nodes > 1, shared_store=False)")
        # victim: a non-entry node that owns at least one partition (so
        # the kill actually strands state a successor must recover)
        n_parts = len(self.store.parts)
        victim_i = next(
            i for i in range(1, len(self.node_ids))
            if any(self.ring.owner(partition_key(p)) == self.node_ids[i]
                   for p in range(n_parts)))
        nid = self.node_ids[victim_i]
        owned = [p for p in range(n_parts)
                 if self.servers[0].db.owners.get(p) == nid]
        # clock starts at the kill, not after: graceful stop() overlaps
        # the survivors' probe detection, so promotion is often already
        # visible by the time stop() returns
        t0 = time.monotonic()
        await self.servers[victim_i].stop()
        await asyncio.to_thread(self.stores[nid].close)
        self._permakilled.add(nid)
        self.facts["permakills"] += 1
        self.facts["node_kills"] += 1
        # wait for promote-on-death: every partition the victim owned
        # must land on a live node (probe deadline + replay, with slack)
        survivors = [s for i, s in enumerate(self.servers)
                     if i != victim_i]
        deadline = time.monotonic() + max(
            10 * spec.probe_interval_s * defaults.REPL_PROBE_FAILURES,
            5.0)
        while time.monotonic() < deadline:
            owners = {p: next(
                (s.db.owners.get(p) for s in survivors
                 if s.db.owners.get(p) != nid), None) for p in owned}
            if all(o is not None for o in owners.values()):
                break
            await asyncio.sleep(spec.probe_interval_s / 4)
        else:
            raise RuntimeError(
                f"no successor promoted {nid}'s partitions {owned}")
        self.facts["repl_promote_s"] = round(time.monotonic() - t0, 3)
        self.facts["promotions"] += len(owned)
        # propagate the new ownership to every survivor's table so no
        # forward chases the corpse (announce is best-effort; the drive
        # below must not burn its error budget on stale maps)
        final = {p: next(s.db.owners[p] for s in survivors
                         if s.db.owners.get(p) != nid) for p in owned}
        for s in survivors:
            for p, owner in final.items():
                s.db.set_owner(p, owner)
        mm0 = _MATCHMAKINGS.value()
        await self._drive_window(ph.duration_s or 1.2)
        self.facts["post_promote_matchmakings"] = int(
            _MATCHMAKINGS.value() - mm0)

    async def _phase_drain(self, ph: Phase) -> None:
        """Let in-flight fulfills settle, force the write-behind queue
        through a commit (off-loop), and capture the verdict facts."""
        await asyncio.sleep(ph.duration_s or 0.2)
        live_stores = ([s for n, s in self.stores.items()
                        if n not in self._permakilled]
                       if self.stores else [self.store])
        for store in live_stores:
            await asyncio.to_thread(store.flush)
        self.facts["client_matches"] = sum(c.matches for c in self.clients)
        self.facts["max_stall_s"] = round(self.stalls.max_stall_s, 4)
        self.facts["commits_on_loop"] = any(
            threading.get_ident() in s.commit_threads
            for s in live_stores)
        p99 = _REQUEST_SECONDS.quantile(0.99, route="/backups/request")
        self.facts["p99_request_s"] = (
            None if math.isnan(p99) else round(p99, 5))
        self.facts["total_matchmakings"] = int(
            _MATCHMAKINGS.value() - self._mm0)
        self.facts["failovers"] = sum(
            c.client.failovers for c in self.clients)
        if self.spec.nodes > 1:
            self.facts["negotiated_rows"] = await asyncio.to_thread(
                self._count_negotiated_rows)

    def _count_negotiated_rows(self) -> int:
        """Durable matchmaking evidence across every partition: each
        completed matchmaking writes one row per negotiation endpoint,
        so ``rows >= 2 * matchmakings`` iff no completed matchmaking
        lost its records (kill-window orphans can only ADD rows).

        Replicated deployment: each partition is counted ONCE, from its
        CURRENT owner's store — after a permakill that is the promoted
        successor, so the count fails exactly when promotion lost rows
        the dead primary had acked."""
        if self.stores:
            ref = next(s for i, s in enumerate(self.servers)
                       if self.node_ids[i] not in self._permakilled)
            total = 0
            for p_idx in range(len(self.store.parts)):
                owner = ref.db.owners.get(p_idx)
                store = self.stores.get(owner)
                if store is None or owner in self._permakilled:
                    continue  # unrecovered partition counts nothing
                part = store.parts[p_idx]
                with part._direct_lock:
                    total += part._db.execute(
                        "SELECT COUNT(*) FROM peer_backups"
                    ).fetchone()[0]
            return total
        total = 0
        parts = getattr(self.store, "parts", [self.store])
        for p in parts:
            with getattr(p, "_direct_lock"):
                total += p._db.execute(
                    "SELECT COUNT(*) FROM peer_backups").fetchone()[0]
        return total

    # --- gates -------------------------------------------------------------

    def _assertions(self, error, counters) -> List[sc.Assertion]:
        spec, facts = self.spec, self.facts
        A = sc.Assertion
        out = [A("phases_completed", error is None,
                 "" if error is None else f"{error[0]}: {error[1]}")]
        out.append(A("swarm_registered",
                     facts["registered"] == spec.clients,
                     f"{facts['registered']}/{spec.clients} clients"))
        made = counters.get("bkw_matchmakings_total", 0)
        out.append(A("matchmaking_flowing",
                     made > 0 and facts["client_matches"] > 0,
                     f"matchmakings={made:g}"
                     f" pushed={facts['client_matches']}"))
        budget = max(0.05 * max(facts["requests"], 1), 3)
        out.append(A("error_budget", facts["errors"] <= budget,
                     f"{facts['errors']} errors /"
                     f" {facts['requests']} requests"))
        out.append(A("request_p99_measured",
                     facts["p99_request_s"] is not None,
                     f"p99={facts['p99_request_s']}"))
        if not spec.legacy:
            # the tentpole's two hard gates: the loop never blocks past
            # budget, and no sqlite commit ever ran on the loop thread
            out.append(A("loop_stall_under_budget",
                         facts["max_stall_s"] is not None
                         and facts["max_stall_s"] <= spec.stall_budget_s,
                         f"max_stall={facts['max_stall_s']}s"
                         f" budget={spec.stall_budget_s}s"))
            out.append(A("commits_off_event_loop",
                         facts["commits_on_loop"] is False,
                         "no commit on the event-loop thread"))
            reaps = self.server.queue.reap_ops()
            out.append(A("deadline_heap_live", reaps >= 0,
                         f"reap_ops={reaps}"))
        if spec.nodes > 1:
            # federation gates: clients actually exercised failover,
            # every completed matchmaking kept both durable rows across
            # the kill/revive, matches flowed again after the revive,
            # and the per-route p99 stayed bounded through the churn
            out.append(A("federation_failover_exercised",
                         facts["node_kills"] == 0
                         or facts["failovers"] >= 1,
                         f"failovers={facts['failovers']}"))
            rows, mm = facts["negotiated_rows"], facts["total_matchmakings"]
            out.append(A("federation_no_lost_matchmakings",
                         rows is not None and rows >= 2 * mm,
                         f"negotiated_rows={rows}"
                         f" matchmakings={mm} (need >= {2 * mm})"))
            out.append(A("federation_post_revive_flow",
                         facts["node_kills"] <= facts["permakills"]
                         or (facts["post_revive_matchmakings"] or 0) > 0,
                         "post_revive_matchmakings="
                         f"{facts['post_revive_matchmakings']}"))
            out.append(A("federation_p99_bounded",
                         facts["p99_request_s"] is not None
                         and facts["p99_request_s"] <= spec.p99_budget_s,
                         f"p99={facts['p99_request_s']}s"
                         f" budget={spec.p99_budget_s}s"))
        if facts["permakills"]:
            # replication gates: a successor actually promoted the dead
            # node's partitions (within the probe deadline — the phase
            # raises on timeout, this records how fast), and matches
            # flowed against the survivors afterwards.  Row durability
            # across the permakill is federation_no_lost_matchmakings
            # above, now counted against per-node stores.
            out.append(A("replication_promoted",
                         facts["promotions"] >= 1
                         and facts["repl_promote_s"] is not None,
                         f"promotions={facts['promotions']}"
                         f" in {facts['repl_promote_s']}s"))
            out.append(A("replication_post_promote_flow",
                         (facts["post_promote_matchmakings"] or 0) > 0,
                         "post_promote_matchmakings="
                         f"{facts['post_promote_matchmakings']}"))
            # the permakill must not register as a durability event on
            # any honest client — the promoted successor's replayed
            # state is indistinguishable from the dead primary's
            violation_s = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_durability_violation_seconds_total"))
            out.append(A("replication_durability_invariant",
                         violation_s == 0,
                         f"violation_seconds={violation_s:g}"))
        return out


async def run_swarm(spec: SwarmSpec, workdir) -> Tuple[sc.Scorecard, Dict]:
    """setup -> run -> teardown, returning the scorecard plus the flat
    summary bench config 12 embeds (matchmakings/s, p99, stall, commit
    mode counts)."""
    harness = SwarmHarness(spec, Path(workdir))
    await harness.setup()
    try:
        card = await harness.run()
    finally:
        await harness.teardown()
    return card, summarize(spec, card, harness.facts)


def summarize(spec: SwarmSpec, card: sc.Scorecard, facts: Dict) -> Dict:
    commits = {
        mode: card.counters.get(
            f"bkw_server_store_commits_total{{mode={mode}}}", 0)
        for mode in ("group", "direct")}
    p99 = facts.get("p99_request_s")
    fed = {} if spec.nodes <= 1 else {
        "nodes": spec.nodes,
        "shared_store": spec.shared_store,
        "node_kills": facts.get("node_kills"),
        "failovers": facts.get("failovers"),
        "post_revive_matchmakings": facts.get("post_revive_matchmakings"),
        "total_matchmakings": facts.get("total_matchmakings"),
        "negotiated_rows": facts.get("negotiated_rows"),
        "permakills": facts.get("permakills"),
        "promotions": facts.get("promotions"),
        "repl_promote_s": facts.get("repl_promote_s"),
        "post_promote_matchmakings": facts.get("post_promote_matchmakings"),
    }
    return {
        "tier": "legacy" if spec.legacy else "sharded",
        "clients": spec.clients,
        **fed,
        "duration_s": facts.get("swarm_elapsed_s"),
        "matchmakings": facts.get("swarm_matchmakings"),
        "matchmakings_per_s": facts.get("matchmakings_per_s"),
        "server_p99_ms": None if p99 is None else round(p99 * 1e3, 3),
        "max_stall_ms": None if facts.get("max_stall_s") is None
        else round(facts["max_stall_s"] * 1e3, 2),
        "commits_on_loop": facts.get("commits_on_loop"),
        "requests": facts.get("requests"),
        "errors": facts.get("errors"),
        "commits": commits,
        "passed": card.passed,
    }


# --- direct matchmaking-layer load (bench config 12's speedup legs) --------
#
# The HTTP swarm above proves the end-to-end properties (p99, stall
# budget, commits off the loop), but on a single-core box the identical
# per-request HTTP/auth/python cost dominates both tiers and flattens
# the matchmaking-layer difference.  The speedup legs therefore drive
# the matchmaker + store pair DIRECTLY — same real file-backed sqlite,
# same fsync discipline, same audit-history weight per candidate scan —
# with time-boxed client coroutines that yield at each request boundary
# exactly like the aiohttp handlers do.  Time-boxing (not fixed rounds)
# keeps the pairing supply saturated in both legs, so matchmakings/s
# measures matchmaker capacity rather than driver shape.


@dataclass(frozen=True)
class MatchLoadSpec:
    """One time-boxed matchmaking-layer load leg."""

    clients: int = 128
    duration_s: float = 2.5
    legacy: bool = False
    shards: Optional[int] = None
    request_bytes: int = 1 << 20
    #: passing audit reports preloaded (total) so every candidate scan
    #: reads a realistically deep verdict window
    audit_history: int = 800
    expiry_s: float = 60.0


class _AlwaysOnline:
    """Connection-registry stub for the direct legs: every client is
    online and every notify lands after one loop yield (the shape of a
    loopback WS push without the socket)."""

    def is_online(self, client_id) -> bool:
        return True

    async def notify(self, client_id, msg) -> bool:
        await asyncio.sleep(0)
        return True


def _bulk_audit_history(store, pubkeys: List[bytes], rows: int) -> None:
    """Setup-time bulk insert of passing verdicts, ring-wise reporters,
    directly on the store's connection (pre-measurement)."""
    now = time.time()
    payload = []
    for i in range(rows):
        peer = pubkeys[i % len(pubkeys)]
        reporter = pubkeys[(i + 1) % len(pubkeys)]
        payload.append((reporter, peer, 1, "preload", now - i * 1e-3))
    with store._direct_lock:
        store._db.executemany(
            "INSERT INTO audit_reports (reporter, peer, passed, detail,"
            " timestamp) VALUES (?, ?, ?, ?, ?)", payload)
        store._db.commit()


async def _match_load(spec: MatchLoadSpec, db_path: str) -> Dict:
    from ..net.server import StorageQueue
    from ..net.serverstore import ServerDB, SqliteServerStore
    pubkeys = [i.to_bytes(8, "big") + bytes(24)
               for i in range(1, spec.clients + 1)]
    if spec.legacy:
        store = ServerDB(db_path)
        queue = StorageQueue(store, _AlwaysOnline(), expiry_s=spec.expiry_s)
    else:
        store = SqliteServerStore(db_path)
        queue = ShardedMatchmaker(store, _AlwaysOnline(),
                                  expiry_s=spec.expiry_s,
                                  shards=spec.shards)
    try:
        if spec.audit_history:
            _bulk_audit_history(store, pubkeys, spec.audit_history)
        fulfills = [0]

        async def drive(pk: bytes, deadline: float) -> None:
            while time.monotonic() < deadline:
                await queue.fulfill(pk, spec.request_bytes)
                fulfills[0] += 1
                # request boundary: yield exactly once, like a handler
                # returning to the loop between requests
                await asyncio.sleep(0)

        mm0 = _MATCHMAKINGS.value()
        t0 = time.monotonic()
        deadline = t0 + spec.duration_s
        await asyncio.gather(*(drive(pk, deadline) for pk in pubkeys))
        elapsed = time.monotonic() - t0
        made = _MATCHMAKINGS.value() - mm0
    finally:
        store.close()
    return {
        "tier": "legacy" if spec.legacy else "sharded",
        "clients": spec.clients,
        "duration_s": round(elapsed, 3),
        "fulfills": fulfills[0],
        "matchmakings": int(made),
        "matchmakings_per_s": round(made / elapsed, 2),
        "fulfills_per_s": round(fulfills[0] / elapsed, 2),
    }


def run_match_load(spec: MatchLoadSpec, workdir) -> Dict:
    """Run one leg in a fresh event loop against a file-backed store
    under ``workdir``; returns the flat leg record."""
    db_path = str(Path(workdir) / f"match_{spec.legacy and 'legacy' or 'sharded'}.db")
    return asyncio.run(_match_load(spec, db_path))


def builtin_swarms() -> Dict[str, SwarmSpec]:
    """``swarm`` is the tier-1 acceptance run (≈32 clients, a few
    seconds on loopback); ``swarm_full`` is the slow-tier load shape
    bench config 12 also uses."""
    P = Phase
    return {
        "swarm": SwarmSpec(
            name="swarm", seed=101, clients=32,
            phases=(P("register"), P("swarm", duration_s=2.0),
                    P("drain"))),
        "swarm_full": SwarmSpec(
            name="swarm_full", seed=111, clients=192, think_s=0.02,
            phases=(P("register"), P("swarm", duration_s=6.0),
                    P("drain"))),
        # federation acceptance: 3 nodes over one SHARED partitioned
        # store (the explicit opt-in baseline leg — row survival across
        # a kill is by construction), node kill + same-port revive
        # mid-run; tier-1 sized.  WS churn is off — the nodekill phase
        # IS the churn under test
        "federation": SwarmSpec(
            name="federation", seed=202, clients=12, workers=4, nodes=3,
            churn_every=0, think_s=0.005, shared_store=True,
            phases=(P("register"), P("swarm", duration_s=1.2),
                    P("nodekill", duration_s=1.6), P("drain"))),
        # slow-tier soak: more nodes, more clients, a second full swarm
        # window after the revive so steady-state federation throughput
        # is measured post-churn
        "federation_soak": SwarmSpec(
            name="federation_soak", seed=212, clients=48, nodes=4,
            churn_every=0, think_s=0.02, shared_store=True,
            phases=(P("register"), P("swarm", duration_s=4.0),
                    P("nodekill", duration_s=4.0),
                    P("swarm", duration_s=3.0), P("drain"))),
        # replication acceptance (docs/server.md §Replication): 3 nodes
        # with PER-NODE replicated stores and a mid-run PERMAKILL — one
        # node dies forever, a ring successor must promote within the
        # probe deadline and serve its partitions with zero lost
        # matchmaking rows; tier-1 sized
        # load is deliberately gentler than the federation baseline:
        # every foreign-partition write is a real forward hop and every
        # owned write a real ship hop, all sharing one CPU in CI — the
        # gates probe correctness across the permakill, not throughput
        "replication": SwarmSpec(
            name="replication", seed=303, clients=8, workers=4, nodes=3,
            churn_every=0, think_s=0.05, p99_budget_s=8.0,
            phases=(P("register"), P("swarm", duration_s=1.2),
                    P("permakill", duration_s=1.5), P("drain"))),
        # slow-tier soak: longer chains (4 nodes, REPL_SUCCESSORS=2
        # leaves a spare successor after the kill), heavier load, and a
        # second swarm window in the promoted steady state
        # the soak stresses DURATION (a promoted successor keeps serving
        # through two more load windows), not raw client concurrency —
        # 16 clients over 4 nodes is already past what one core serves
        # without queueing, and queueing is not what this gate measures.
        # The p99 budget is a LIVENESS bound, not a latency SLO: with
        # ~200 requests the 99th percentile lands on the one or two
        # requests whose forwards straddled the permakill and paid
        # REPL_FORWARD_TIMEOUT_S (possibly twice — fulfill issues
        # several store ops) before the promoted owner took over
        "replication_soak": SwarmSpec(
            name="replication_soak", seed=313, clients=12, nodes=4,
            churn_every=0, think_s=0.08, p99_budget_s=45.0,
            phases=(P("register"), P("swarm", duration_s=3.0),
                    P("permakill", duration_s=3.0),
                    P("swarm", duration_s=2.0), P("drain"))),
    }
