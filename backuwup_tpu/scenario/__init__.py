"""Composed chaos scenarios and their scorecard gate.

``harness`` runs a scripted multi-client deployment through timed fault
phases while sampling the durability invariants; ``scorecard`` turns
the run's registry deltas and samples into a machine-readable pass/fail
card.  ``swarm`` re-points the same machinery at the coordination plane:
N lightweight control-plane clients hammering one server (the PR-10
scale-out proof).  ``scripts/scenario.py`` is the CLI; the ``scenario``-
and ``swarm``-marked tests gate the composed runs in tier 1.
"""

from .harness import (Phase, ScenarioHarness, ScenarioSpec,
                      builtin_scenarios, run_scenario)
from .scorecard import Assertion, Scorecard, build_scorecard
from .swarm import (MatchLoadSpec, SwarmHarness, SwarmSpec, builtin_swarms,
                    run_match_load, run_swarm, summarize)

__all__ = ["Phase", "ScenarioHarness", "ScenarioSpec",
           "builtin_scenarios", "run_scenario",
           "Assertion", "Scorecard", "build_scorecard",
           "MatchLoadSpec", "SwarmHarness", "SwarmSpec", "builtin_swarms",
           "run_match_load", "run_swarm", "summarize"]
