"""Composed chaos scenarios and their scorecard gate.

``harness`` runs a scripted multi-client deployment through timed fault
phases while sampling the durability invariants; ``scorecard`` turns
the run's registry deltas and samples into a machine-readable pass/fail
card.  ``scripts/scenario.py`` is the CLI; the ``scenario``-marked
tests gate the composed scenario in tier 1.
"""

from .harness import (Phase, ScenarioHarness, ScenarioSpec,
                      builtin_scenarios, run_scenario)
from .scorecard import Assertion, Scorecard, build_scorecard

__all__ = ["Phase", "ScenarioHarness", "ScenarioSpec",
           "builtin_scenarios", "run_scenario",
           "Assertion", "Scorecard", "build_scorecard"]
