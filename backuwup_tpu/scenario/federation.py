"""Federation bench plane: N coordination nodes as real OS processes.

The swarm harness (``scenario/swarm.py``) proves the federation's
end-to-end properties — failover, zero lost matchmakings across a node
kill, bounded p99 — but its nodes share one event loop, so it cannot
show THROUGHPUT scaling.  Bench config ``16_federation``'s scaling legs
need nodes that genuinely run in parallel: this module spawns each node
as its own OS process with its own ServerStore partition file, its own
consistent-hash ring copy, and real ``/fed/steal`` HTTP between them.

Deployment per node process
===========================

* a :class:`~..net.serverstore.SqliteServerStore` at
  ``<workdir>/node<i>.db`` — per-node partition files keep sqlite WAL
  writers process-local (cross-process WAL sharing would serialize the
  very commits the scaling legs measure),
* a :class:`~..net.server.CoordinationServer` with its connection
  registry stubbed always-online (the drivers call ``queue.fulfill``
  directly, exactly like ``_match_load`` — the thing under test is the
  matchmaker + federation RPC, not the HTTP/auth envelope),
* ``enable_federation`` over the full ring, so a node whose local
  shards drain steals work from its ring successors over real sockets.

Synchronization is file-based and two-stage: every child polls every
peer's ``/healthz`` (proves all sockets are up), drops a ``ready_<i>``
marker, then waits for the parent's ``go.json`` carrying a shared
``t0``/``deadline`` — all nodes measure the same wall-clock window, so
the parent may sum matchmakings and divide by the longest elapsed.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional

import aiohttp


@dataclass(frozen=True)
class FederationLoadSpec:
    """One multi-process federation throughput leg."""

    nodes: int = 2
    #: pubkey universe shared by all nodes; each node drives the subset
    #: the ring homes on it
    clients: int = 64
    duration_s: float = 2.0
    request_bytes: int = 1 << 20
    shards: Optional[int] = None
    #: ceiling for child startup (interpreter + imports + bind + the
    #: healthz barrier) — generous because the children import the full
    #: package cold
    startup_timeout_s: float = 90.0


def _free_ports(n: int) -> List[int]:
    """Reserve n distinct loopback ports (bind-then-close; the tiny
    rebind race is acceptable for a bench on loopback)."""
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class _FedOnline:
    """Always-online connection registry for the bench nodes: every
    notify lands after one loop yield.  ``enable_federation`` installs
    its relay hook here, but local notifies never fail so the relay is
    exercised only via remote-steal pushes."""

    def __init__(self):
        self.relay = None

    def count(self) -> int:
        return 0

    def is_online(self, client_id) -> bool:
        return True

    async def notify_local(self, client_id, msg) -> bool:
        await asyncio.sleep(0)
        return True

    async def notify(self, client_id, msg) -> bool:
        await asyncio.sleep(0)
        return True


def _universe(clients: int) -> List[bytes]:
    return [i.to_bytes(8, "big") + bytes(24)
            for i in range(1, clients + 1)]


async def _node_main(cfg: Dict) -> Dict:
    from ..net.matchmaking import _MATCHMAKINGS
    from ..net.ring import HashRing
    from ..net.server import (_FED_STEAL_SERVED, _FED_STEALS,
                              CoordinationServer)
    from ..net.serverstore import SqliteServerStore

    idx = cfg["node_index"]
    node_ids = [f"node{i}" for i in range(cfg["nodes"])]
    nid = node_ids[idx]
    ring = HashRing(node_ids)
    workdir = Path(cfg["workdir"])
    store = await asyncio.to_thread(
        SqliteServerStore, str(workdir / f"{nid}.db"))
    server = CoordinationServer(store=store, shards=cfg["shards"])
    online = _FedOnline()
    # stub BEFORE enable_federation so the relay hook lands on the stub
    server.connections = online
    server.queue.connections = online
    await server.start(port=cfg["ports"][idx])
    peers = {n: f"http://127.0.0.1:{p}"
             for n, p in zip(node_ids, cfg["ports"])}
    server.enable_federation(nid, ring, peers)

    # barrier 1: every peer's socket answers /healthz
    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=1)) as sess:
        for url in peers.values():
            while True:
                try:
                    async with sess.get(url + "/healthz") as resp:
                        if resp.status == 200:
                            break
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError):
                    pass
                await asyncio.sleep(0.05)
    await asyncio.to_thread(
        (workdir / f"ready_{idx}").write_text, "ok")

    # barrier 2: the parent's go file carries the shared window
    go_path = workdir / "go.json"
    while not go_path.exists():
        await asyncio.sleep(0.02)
    go = json.loads(await asyncio.to_thread(go_path.read_text))

    mine = [pk for pk in _universe(cfg["clients"])
            if ring.owner(pk) == nid]
    fulfills = [0]
    deadline = go["deadline"]

    async def drive(pk: bytes) -> None:
        while time.time() < deadline:
            await server.queue.fulfill(pk, cfg["request_bytes"])
            fulfills[0] += 1
            await asyncio.sleep(0)

    await asyncio.sleep(max(0.0, go["t0"] - time.time()))
    mm0 = _MATCHMAKINGS.value()
    t0 = time.time()
    await asyncio.gather(*(drive(pk) for pk in mine))
    elapsed = time.time() - t0
    made = _MATCHMAKINGS.value() - mm0
    steals = {o: _FED_STEALS.value(outcome=o)
              for o in ("hit", "miss", "error")}
    served = {o: _FED_STEAL_SERVED.value(outcome=o)
              for o in ("hit", "empty")}
    await server.stop()
    await asyncio.to_thread(store.close)
    return {
        "node": nid,
        "owned_clients": len(mine),
        "elapsed_s": round(elapsed, 3),
        "fulfills": fulfills[0],
        "matchmakings": int(made),
        "steals": steals,
        "steals_served": served,
    }


def _child_main(argv: List[str]) -> int:
    cfg = json.loads(Path(argv[1]).read_text())
    out = asyncio.run(_node_main(cfg))
    (Path(cfg["workdir"]) / f"result_{cfg['node_index']}.json").write_text(
        json.dumps(out))
    return 0


def _tail(path: Path, n: int = 12) -> str:
    try:
        return "\n".join(path.read_text(errors="replace").splitlines()[-n:])
    except OSError:
        return "<no log>"


def run_federation_load(spec: FederationLoadSpec, workdir) -> Dict:
    """Spawn the node processes, coordinate the shared measurement
    window, and aggregate.  Raises if any node dies or misses the
    startup ceiling (with its log tail — a bench leg must fail loudly,
    not report a partial fleet as a throughput number)."""
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    ports = _free_ports(spec.nodes)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs: List[subprocess.Popen] = []
    logs: List[Path] = []
    try:
        for i in range(spec.nodes):
            cfg_path = workdir / f"node_{i}.json"
            cfg_path.write_text(json.dumps({
                "node_index": i, "nodes": spec.nodes,
                "ports": ports, "workdir": str(workdir),
                "clients": spec.clients,
                "request_bytes": spec.request_bytes,
                "shards": spec.shards,
            }))
            log_path = workdir / f"node_{i}.log"
            logs.append(log_path)
            with log_path.open("wb") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "backuwup_tpu.scenario.federation", str(cfg_path)],
                    stdout=lf, stderr=subprocess.STDOUT, env=env))
        t_stop = time.monotonic() + spec.startup_timeout_s
        while not all((workdir / f"ready_{i}").exists()
                      for i in range(spec.nodes)):
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"federation node {i} died during startup "
                        f"(rc={p.returncode}):\n{_tail(logs[i])}")
            if time.monotonic() > t_stop:
                raise RuntimeError(
                    "federation nodes missed the startup ceiling "
                    f"({spec.startup_timeout_s}s):\n{_tail(logs[0])}")
            time.sleep(0.05)
        t0 = time.time() + 0.5
        (workdir / "go.json").write_text(json.dumps(
            {"t0": t0, "deadline": t0 + spec.duration_s}))
        results = []
        for i, p in enumerate(procs):
            rc = p.wait(timeout=spec.duration_s + spec.startup_timeout_s)
            if rc != 0:
                raise RuntimeError(
                    f"federation node {i} failed (rc={rc}):\n"
                    f"{_tail(logs[i])}")
            results.append(json.loads(
                (workdir / f"result_{i}.json").read_text()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    made = sum(r["matchmakings"] for r in results)
    elapsed = max(r["elapsed_s"] for r in results)
    steals = {o: sum(r["steals"][o] for r in results)
              for o in ("hit", "miss", "error")}
    return {
        "nodes": spec.nodes,
        "clients": spec.clients,
        "duration_s": round(elapsed, 3),
        "matchmakings": made,
        "matchmakings_per_s": round(made / elapsed, 2) if elapsed else 0.0,
        "fulfills": sum(r["fulfills"] for r in results),
        "steals": steals,
        "per_node": results,
    }


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv))
