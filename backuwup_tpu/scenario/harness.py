"""Composed chaos scenario harness (docs/scenarios.md).

The chaos e2e tests each exercise one subsystem; the ROADMAP
scenario-matrix item asks for their composition.  This harness runs a
full loopback deployment — one CoordinationServer, one source client,
N storage holders, and spare peers, all in-process — and drives it
through a scripted sequence of timed phases:

=============  ============================================================
``backup``     full backup of the (optionally grown) corpus; every
               packfile placed as an RS(k+m) stripe on distinct holders
``steady``     idle wall time: the invariant sampler keeps sweeping and
               steady state must stay clean
``churn``      a backup racing sustained peer churn: holders are killed
               and revived through the fault plane every ``interval_s``
               while the transfer plane retries around them
``byzantine``  holders' stored shard bytes are flipped; one audit round
               catches the bad proofs and demotes them
``kill``       unrepaired peer loss: a holder goes permanently dark and
               is audit-demoted via consecutive misses — durability
               must flip to degraded within one monitor sweep
``repair``     one ``engine.repair_round()``: sourceless shard rebuild
               onto spare peers
``race``       backup + restore + repair all fired concurrently on the
               one client; losers of the exclusivity lock spin on
               EngineError until everything completes
``restore``    restore to a fresh directory and verify byte-for-byte
               against the source tree digest
``restore_hedged``  a restore with one measured-fast holder stalled:
               every frame toward it sleeps past the hedge deadline, so
               the download lanes must race redundant shards from the
               spare holders and win
               (``bkw_restore_hedges_total{outcome=won}``)
``wan``        WAN-grade transfer conditions: chunked sends with armed
               mid-transfer cuts that force byte-range resumes, peer
               stats seeded so capacity-aware placement avoids the
               placement-demoted slow holder, and probation recovery
``crash``      the crash matrix: for each armed commit seam the source
               client's backup dies at that exact instruction
               (:func:`~backuwup_tpu.utils.faults.crashpoint`), the
               client is restarted in-process (every in-memory structure
               discarded, directories re-opened) so the startup recovery
               sweep reconciles, then a re-run backup must complete and
               a second ``recover()`` must reconcile zero items
``gc``         snapshot lifecycle: mutate the corpus so a retention
               prune (keep-last:1) creates dead blobs, back up, then
               collect.  With ``sites``, per armed GC seam the
               ``run_gc`` dies mid-commit, the client restarts, and the
               re-run + recovery must converge (same crash-facts shape
               as ``crash``, so the ``recovery_clean`` gate applies);
               without sites, GC races a concurrent backup + restore on
               the exclusivity lock while still reclaiming bytes
=============  ============================================================

Everything is seeded (fault plane, corpus bytes, victim choice), so a
scenario is deterministic enough for a tier-1 test; a background sampler
sweeps :class:`~backuwup_tpu.obs.invariants.InvariantMonitor`
continuously and the run ends in a :class:`~.scorecard.Scorecard` built
from registry deltas with hard pass/fail assertions.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

from .. import defaults
from ..app import ClientApp
from ..engine import EngineError
from ..net.server import CoordinationServer
from ..obs import diagnose as obs_diagnose
from ..obs import invariants as obs_invariants
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs.series import SeriesRecorder
from ..ops.backend import ChunkerBackend, CpuBackend
from ..net.peer_stats import PeerEstimate
from ..ops.gear import CDCParams
from ..store import PeerStatsRow
from ..utils import faults
from . import scorecard as sc


class ScenarioError(Exception):
    pass


@dataclass(frozen=True)
class Phase:
    """One scripted step; ``kind`` selects the behavior table above."""

    kind: str
    duration_s: float = 0.0  # steady/churn wall time
    count: int = 1           # victims for byzantine/kill
    interval_s: float = 0.3  # churn kill/revive cadence
    grow: bool = False       # write fresh corpus files first
    sites: tuple = ()        # crash: commit seams (() = _CRASH_MATRIX)
    name: str = ""

    @property
    def label(self) -> str:
        return self.name or self.kind


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    phases: tuple
    seed: int = 1234
    holders: int = 6
    spares: int = 1
    corpus_files: int = 6
    corpus_file_bytes: int = 24 * 1024
    packfile_target: int = 64 * 1024
    chunk_desired: int = 4096
    #: 0 keeps defaults.TRANSFER_CHUNK_BYTES (1 MiB — every loopback
    #: payload rides the legacy single-frame path); the wan scenario
    #: shrinks it so shards span several FILE_PART frames
    chunk_bytes: int = 0
    sample_interval_s: float = 0.1
    expect_violation: bool = False
    expect_final_status: str = "ok"
    min_shards_rebuilt: int = 0
    #: opt into the live SLO plane: a journal at the workdir, series
    #: sampling + burn-rate evaluation riding the invariant sampler, a
    #: diagnosis report on breach, and the slo_* gates
    slo: bool = False
    #: catalog subset to evaluate — loopback runs keep the objectives
    #: whose healthy baseline is provably quiet (overlap efficiency on a
    #: tiny synthetic corpus is not)
    slo_objectives: tuple = ("durability", "transfer_stalls",
                             "backup_p99", "restore_p99")
    #: multi-window pairs shrunk onto loopback seconds
    slo_windows: tuple = ((1.0, 3.0), (6.0, 18.0))


#: The sender-side commit seams a scenario backup crosses, i.e. the
#: default crash matrix (`docs/crash_consistency.md`).  The receiver-side
#: seam (``partial.sink.*``) and the repair re-home seam
#: (``repair.rehome.*``) fire in code paths a plain backup never enters;
#: tests/test_crash.py covers those with targeted unit recoveries.
_CRASH_MATRIX = (
    "pack.seal.pre", "pack.seal.post",
    "challenge.save.pre", "challenge.save.post",
    "index.save.pre", "index.save.post",
    "placement.insert.pre", "placement.insert.post",
    "stripe.finish.pre", "stripe.finish.post",
)


def _crash_count(ph: Phase) -> int:
    return len(ph.sites or _CRASH_MATRIX)


#: defaults shrunk for loopback scenarios; saved/restored around a run.
_PATCH = {
    "ACK_TIMEOUT_S": 1.5,
    "RESTORE_REQUEST_THROTTLE_S": 0.0,
    "AUDIT_SERVE_MIN_INTERVAL_S": 0.0,
    "PEER_WAIT_BASE_S": 0.05,
    "PEER_WAIT_CAP_S": 0.25,
    "DIAL_RETRY_ATTEMPTS": 1,
    "DIAL_RETRY_BASE_S": 0.05,
    "DIAL_RETRY_CAP_S": 0.2,
    "DURABILITY_SWEEP_INTERVAL_S": 0.5,
    "RECLAIM_MIN_INTERVAL_S": 0.0,
}


def _tree_digest(root: Path) -> Dict[str, str]:
    out = {}
    for p in sorted(root.rglob("*")):
        if p.is_file():
            out[str(p.relative_to(root))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    return out


class ScenarioHarness:
    """Owns the deployment, the fault plane, and the invariant sampler
    for one scenario run.  Use :func:`run_scenario` unless a test needs
    to poke mid-run state (the healthz-flip test does)."""

    def __init__(self, spec: ScenarioSpec, workdir: Path,
                 backend: Optional[ChunkerBackend] = None):
        self.spec = spec
        self.workdir = Path(workdir)
        self.backend = backend
        self.rng = random.Random(spec.seed)
        self.src = self.workdir / "src"
        self.samples: List[dict] = []
        self.facts: Dict = {"backups": 0, "restores": 0, "repairs": 0,
                            "demoted": [], "restore_verified": None,
                            "source_digest": None}
        self.server: Optional[CoordinationServer] = None
        self.a: Optional[ClientApp] = None
        self.holders: List[ClientApp] = []
        self.spares: List[ClientApp] = []
        self.plane: Optional[faults.FaultPlane] = None
        self.monitor = None
        self.server_port: Optional[int] = None
        self.t0 = 0.0
        self._saved: Dict = {}
        self._grown = 0
        self._restores = 0
        self.series: Optional[SeriesRecorder] = None
        self.slo: Optional[obs_slo.SLOMonitor] = None
        self.diagnoses: List[dict] = []
        self._saved_journal = None

    # --- lifecycle ---------------------------------------------------------

    async def setup(self) -> None:
        spec = self.spec
        self._saved = {k: getattr(defaults, k) for k in _PATCH}
        self._saved["PACKFILE_TARGET_SIZE"] = defaults.PACKFILE_TARGET_SIZE
        self._saved["TRANSFER_CHUNK_BYTES"] = defaults.TRANSFER_CHUNK_BYTES
        for k, v in _PATCH.items():
            setattr(defaults, k, v)
        defaults.PACKFILE_TARGET_SIZE = spec.packfile_target
        if spec.chunk_bytes > 0:
            defaults.TRANSFER_CHUNK_BYTES = spec.chunk_bytes
        self.plane = faults.install(faults.FaultPlane(seed=spec.seed))
        if self.backend is None:
            self.backend = CpuBackend(
                CDCParams.from_desired(spec.chunk_desired))
        self._write_corpus("seed")

        self.server = CoordinationServer(
            db_path=str(self.workdir / "server.db"))
        self.server_port = await self.server.start()

        self.a = self._make_app("a")
        self.holders = [self._make_app(f"h{i}")
                        for i in range(spec.holders)]
        self.spares = [self._make_app(f"s{i}")
                       for i in range(spec.spares)]
        for app in self._apps():
            await app.start()
            # the harness drives audits and sweeps; background schedulers
            # would inject nondeterminism
            app._audit_task.cancel()
            app._monitor_task.cancel()
            app._slo_task.cancel()
        self.a.engine.auto_repair = False
        self.monitor = self.a.monitor
        if spec.slo:
            self._saved_journal = obs_journal.get()
            obs_journal.install(obs_journal.Journal(
                self.workdir / "journal.jsonl"))
            catalog = [o for o in obs_slo.parse_catalog()
                       if o.id in spec.slo_objectives]
            families = sorted({o.family for o in catalog}
                              | {o.total_family for o in catalog
                                 if o.total_family})
            self.series = SeriesRecorder(families)
            self.slo = obs_slo.SLOMonitor(
                self.series, catalog=catalog,
                windows=spec.slo_windows,
                on_breach=self._on_breach,
                client=self.a.client_id.hex()[:8])

        # manual negotiation (matchmaking has its own tests); holders get
        # the larger allowance so free-space ordering stripes onto them
        # and spares stay fresh for sourceless repair to re-home onto
        grants = [(h, 32 << 20) for h in self.holders] + \
                 [(s, 8 << 20) for s in self.spares]
        for peer, amount in grants:
            self.a.store.add_peer_negotiated(peer.client_id, amount)
            peer.store.add_peer_negotiated(self.a.client_id, amount)
            self.server.db.save_storage_negotiated(
                bytes(self.a.client_id), bytes(peer.client_id), amount)

    def _make_app(self, name: str) -> ClientApp:
        app = ClientApp(config_dir=self.workdir / name / "cfg",
                        data_dir=self.workdir / name / "data",
                        server_addr=f"127.0.0.1:{self.server_port}",
                        backend=self.backend,
                        tls=False)  # plaintext loopback deployment
        app.store.set_backup_path(str(self.src))
        return app

    async def teardown(self) -> None:
        for app in self._apps():
            try:
                await app.stop()
            except Exception:
                pass
        if self.server is not None:
            await self.server.stop()
        faults.uninstall()
        if self.spec.slo:
            obs_journal.uninstall()
            if self._saved_journal is not None:
                obs_journal.install(self._saved_journal)
        for k, v in self._saved.items():
            setattr(defaults, k, v)

    def _apps(self) -> List[ClientApp]:
        return [self.a] + self.holders + self.spares if self.a else []

    # --- the run -----------------------------------------------------------

    async def run(self) -> sc.Scorecard:
        before = obs_metrics.registry().snapshot()
        self.t0 = time.time()
        sampler = asyncio.create_task(self._sampler())
        error: Optional[tuple] = None
        executed: List[str] = []
        try:
            for phase in self.spec.phases:
                executed.append(phase.label)
                try:
                    await self._run_phase(phase)
                except Exception as e:
                    error = (phase.label, repr(e)[:300])
                    break
        finally:
            sampler.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await sampler
        self._sample_once()  # authoritative final sweep
        after = obs_metrics.registry().snapshot()
        assertions = self._assertions(
            error, sc.counter_deltas(before, after))
        return sc.build_scorecard(self.spec.name, self.spec.seed,
                                  time.time() - self.t0, executed,
                                  before, after, self.samples, assertions)

    async def _run_phase(self, ph: Phase) -> None:
        fn = getattr(self, f"_phase_{ph.kind}", None)
        if fn is None:
            raise ScenarioError(f"unknown phase kind {ph.kind!r}")
        await fn(ph)

    # --- invariant sampling ------------------------------------------------

    def _on_breach(self, breach) -> None:
        """SLO breach hook: diagnose against the run's journal + series
        history, keep the report for the gates."""
        self.facts.setdefault("slo_breaches", []).append({
            "objective": breach.objective, "status": breach.status,
            "t": round(time.time() - self.t0, 3)})
        report = obs_diagnose.explain(breach, recorder=self.series)
        self.diagnoses.append(report)

    def _sample_once(self) -> None:
        if self.monitor is None:  # crash-phase restart window: no live client
            return
        rep = self.monitor.sweep()
        self.samples.append({
            "t": round(time.time() - self.t0, 3),
            "status": rep.status,
            "status_level": obs_invariants._STATUS_LEVEL[rep.status],
            "stripes_total": rep.stripes_total,
            "stripes_degraded": rep.stripes_degraded,
            "stripes_lost": rep.stripes_lost,
            "unrestorable": rep.packfiles_unrestorable,
            "repair_debt_bytes": rep.repair_debt_bytes,
            "orphaned_placements": rep.orphaned_placements,
        })
        if self.slo is not None:
            # the SLO plane rides the invariant sampler's cadence: every
            # evaluation judges the sweep that just published
            self.series.sample()
            self.slo.evaluate()

    async def _sampler(self) -> None:
        while True:
            self._sample_once()
            await asyncio.sleep(self.spec.sample_interval_s)

    # --- corpus ------------------------------------------------------------

    def _write_corpus(self, tag: str) -> None:
        self.src.mkdir(parents=True, exist_ok=True)
        for i in range(self.spec.corpus_files):
            sub = self.src / f"d{i % 2}"
            sub.mkdir(exist_ok=True)
            size = self.spec.corpus_file_bytes + self.rng.randrange(4096)
            (sub / f"{tag}_{i}.bin").write_bytes(self.rng.randbytes(size))

    def _grow(self) -> None:
        self._grown += 1
        self._write_corpus(f"grow{self._grown}")

    def _mutate_corpus(self) -> None:
        """Rewrite every other corpus file in place.  The old contents
        then live only in pre-mutation snapshots, so a retention prune
        turns them into dead blobs — GC's raw material."""
        files = sorted(p for p in self.src.rglob("*.bin") if p.is_file())
        for p in files[::2]:
            p.write_bytes(self.rng.randbytes(p.stat().st_size))

    async def _retry_busy(self, op, pause: float = 0.05):
        """Spin on the engine exclusivity lock — the race phase's whole
        point is that concurrent ops are rejected, counted
        (bkw_engine_busy_rejections_total), and succeed on retry."""
        while True:
            try:
                return await op()
            except EngineError as e:
                if "already running" not in str(e):
                    raise
                await asyncio.sleep(pause)

    def _alive_holders(self) -> List[ClientApp]:
        return [h for h in self.holders
                if not self.plane.is_dead(h.client_id)
                and not self.a.store.get_audit_state(h.client_id).demoted]

    # --- phases ------------------------------------------------------------

    async def _phase_backup(self, ph: Phase) -> None:
        if ph.grow:
            self._grow()
        snapshot = await asyncio.wait_for(self.a.backup(), 180)
        if not snapshot:
            raise ScenarioError("backup returned no snapshot")
        self.facts["backups"] += 1
        self.facts["source_digest"] = _tree_digest(self.src)

    async def _phase_steady(self, ph: Phase) -> None:
        await asyncio.sleep(ph.duration_s)

    async def _phase_churn(self, ph: Phase) -> None:
        """A backup forced to make progress through sustained peer churn:
        one holder is down at any moment, the victim rotating every
        ``interval_s``; the transfer plane must retry around the hole."""
        if ph.grow:
            self._grow()
        backup = asyncio.create_task(self.a.backup())
        deadline = time.time() + ph.duration_s
        try:
            while time.time() < deadline and not backup.done():
                victim = self.holders[self.rng.randrange(len(self.holders))]
                self.plane.kill(victim.client_id)
                await asyncio.sleep(ph.interval_s)
                self.plane.revive(victim.client_id)
                await asyncio.sleep(ph.interval_s / 3)
        finally:
            for h in self.holders:  # nobody stays dead past the phase
                self.plane.revive(h.client_id)
        snapshot = await asyncio.wait_for(backup, 180)
        if not snapshot:
            raise ScenarioError("churn backup returned no snapshot")
        self.facts["backups"] += 1
        self.facts["source_digest"] = _tree_digest(self.src)

    async def _phase_byzantine(self, ph: Phase) -> None:
        """Byzantine holders: every stored shard byte-flipped, so their
        next audit proof is provably wrong and one failed round demotes
        (AUDIT_DEMOTE_FAILURES)."""
        victims = self._alive_holders()[:ph.count]
        if len(victims) < ph.count:
            raise ScenarioError("not enough alive holders to corrupt")
        for victim in victims:
            stored = victim.store.received_dir(self.a.client_id)
            flipped = 0
            for f in sorted(stored.rglob("*")):
                if f.is_file():
                    blob = bytearray(f.read_bytes())
                    if blob:
                        blob[len(blob) // 2] ^= 0xFF
                        f.write_bytes(bytes(blob))
                        flipped += 1
            if not flipped:
                raise ScenarioError(
                    f"byzantine victim {victim.client_id.hex()[:8]}"
                    " holds nothing to corrupt")
            result = await asyncio.wait_for(
                self._retry_busy(
                    lambda v=victim: self.a.engine.audit_peer(v.client_id)),
                60)
            if result is None or result.passed:
                raise ScenarioError("corrupt shards passed their audit")
            if not self.a.store.get_audit_state(victim.client_id).demoted:
                raise ScenarioError("failed audit did not demote")
            self.facts["demoted"].append(victim.client_id.hex()[:8])

    async def _phase_kill(self, ph: Phase) -> None:
        """Unrepaired peer loss: permanently dark, demoted via
        consecutive audit misses.  No repair here — the point is that
        the monitor flips durability to degraded and holds it there."""
        victims = self._alive_holders()[:ph.count]
        if len(victims) < ph.count:
            raise ScenarioError("not enough alive holders to kill")
        t0 = time.time()
        self.facts.setdefault("fault_t", round(t0 - self.t0, 3))
        for victim in victims:
            self.plane.kill(victim.client_id)
            for i in range(defaults.AUDIT_DEMOTE_MISSES):
                await asyncio.wait_for(
                    self._retry_busy(
                        lambda v=victim, i=i: self.a.engine.audit_peer(
                            v.client_id, now=t0 + i)),
                    60)
            if not self.a.store.get_audit_state(victim.client_id).demoted:
                raise ScenarioError("missed audits did not demote")
            self.facts["demoted"].append(victim.client_id.hex()[:8])

    async def _phase_repair(self, ph: Phase) -> None:
        report = await asyncio.wait_for(
            self._retry_busy(lambda: self.a.engine.repair_round()), 180)
        self.facts["repairs"] += 1
        self.facts.setdefault("repair_reports", []).append(
            {k: report[k] for k in ("packfiles", "bytes_replaced",
                                    "shards_rebuilt")})

    async def _phase_race(self, ph: Phase) -> None:
        """backup + restore + repair all at once on one client.  The
        engine's exclusivity lock serializes them; every loser is
        rejected (counted) and retries until it runs."""
        if ph.grow:
            self._grow()
        self._restores += 1
        dest = self.workdir / f"race_restore_{self._restores}"
        await asyncio.wait_for(asyncio.gather(
            self._retry_busy(lambda: self.a.backup()),
            self._retry_busy(lambda: self.a.engine.run_restore(dest)),
            self._retry_busy(lambda: self.a.engine.repair_round()),
        ), 240)
        self.facts["backups"] += 1
        self.facts["restores"] += 1
        self.facts["repairs"] += 1
        self.facts["source_digest"] = _tree_digest(self.src)

    async def _phase_restore(self, ph: Phase) -> None:
        self._restores += 1
        dest = self.workdir / f"restore_{self._restores}"
        await asyncio.wait_for(
            self._retry_busy(lambda: self.a.restore(dest)), 180)
        self.facts["restores"] += 1
        ok = _tree_digest(dest) == self.facts["source_digest"]
        if self.facts["restore_verified"] is None:
            self.facts["restore_verified"] = ok
        else:
            self.facts["restore_verified"] &= ok

    async def _phase_restore_hedged(self, ph: Phase) -> None:
        """Restore with one holder stalled mid-stripe.  The victim is
        seeded as the fastest measured holder, so the restore planner
        must pick it as a primary source for every stripe it touches;
        an armed fault-plane latency then makes every frame the client
        sends toward it (the FETCH_REQUEST, the acks) sleep past the
        hedge deadline.  The download lanes must notice the stall, race
        a redundant shard from a spare holder, and win — the
        ``bkw_restore_hedges_total{outcome=won}`` gate's evidence —
        while the restore still verifies byte-for-byte."""
        placed = sorted({peer for _, peer, _size, idx, _ in
                         self.a.store.all_placements() if idx >= 0})
        if not placed:
            raise ScenarioError("no striped placements to stall")
        now = time.time()
        victim = placed[0]
        ps = self.a.engine.peer_stats
        for peer in placed:
            bps = 80e6 if peer == victim else 20e6
            # the live estimator bank only reads store rows at startup,
            # so seed both: the row (persistence) and the bank (ranking)
            self.a.store.put_peer_stats(PeerStatsRow(
                bytes(peer), bps, 0.01, 1.0, 10, now))
            with ps._lock:
                ps._est[bytes(peer)] = PeerEstimate(
                    peer=bytes(peer), throughput_bps=bps, latency_s=0.01,
                    success=1.0, samples=10, updated=now)
        site = f"send.latency:{bytes(victim).hex()}"
        saved = (self.plane.latency, self.plane.latency_s)
        # rate epsilon keeps every other latency site quiet while the
        # armed indices fire unconditionally on the victim's stream
        self.plane.latency = 1e-12
        self.plane.latency_s = 2.0
        self.plane.arm(site, *range(4096))
        try:
            await self._phase_restore(ph)
        finally:
            self.plane.latency, self.plane.latency_s = saved
            self.plane._armed.pop(site, None)

    async def _phase_wan(self, ph: Phase) -> None:
        """WAN conditions over the chunked transfer plane.  Peer stats
        are seeded so one holder measures slow/flaky and starts
        placement-demoted: capacity-aware placement must stripe onto the
        fast set only.  Every fast holder gets two armed exact-offset
        cuts, so the backup's shard sends are severed mid-transfer and
        must resume from the receiver's verified partial rather than
        restart — the scorecard gates on bkw_transfer_resumes_total and
        on bkw_transfer_bytes_resent_total staying under budget.
        Afterwards the slow holder's probation is expired to show the
        demotion is recoverable, unlike an audit demotion."""
        if ph.grow:
            self._grow()
        now = time.time()
        fast, slow = self.holders[:-1], self.holders[-1]
        for h in fast:
            self.a.store.put_peer_stats(PeerStatsRow(
                bytes(h.client_id), 50e6, 0.01, 1.0, 10, now))
        self.a.store.put_peer_stats(PeerStatsRow(
            bytes(slow.client_id), 2e3, 0.5, 0.1, 10, now))
        self.a.store.set_placement_demoted(slow.client_id, True, now=now)
        for h in fast:
            # one-shot cuts inside the first and second resume attempt's
            # uncovered ranges (chunk_bytes=4096: parts 2 and 3)
            self.plane.arm_cut(h.client_id, 6000, 10000)
        snapshot = await asyncio.wait_for(self.a.backup(), 180)
        if not snapshot:
            raise ScenarioError("wan backup returned no snapshot")
        self.facts["backups"] += 1
        self.facts["source_digest"] = _tree_digest(self.src)
        placed = {peer for _, peer, _, _, _ in self.a.store.all_placements()}
        demoted = self.a.store.placement_demoted_peers()
        self.facts["wan_placement_ok"] = (
            bytes(slow.client_id) in demoted
            and bytes(slow.client_id) not in placed
            and placed <= {bytes(h.client_id) for h in fast}
            | {bytes(s.client_id) for s in self.spares})
        # recoverability: re-demote with a timestamp past the probation
        # window; the lazy expiry in placement_demoted_peers() must clear
        # it, putting the peer back in the placement pool
        self.a.store.set_placement_demoted(
            slow.client_id, True,
            now=time.time() - defaults.PLACEMENT_PROBATION_S - 1)
        self.facts["wan_placement_recovered"] = (
            bytes(slow.client_id)
            not in self.a.store.placement_demoted_peers())

    async def _restart_client(self) -> dict:
        """Simulate process death + reboot of the source client: throw
        away every in-memory structure (engine, blob index, store
        connection) and re-open the same directories — exactly the state
        a real crash loses — then let ``ClientApp.start``'s recovery
        sweep reconcile.  Returns that sweep's report."""
        # null the monitor before the first await: the sampler task shares
        # this loop and must not sweep the closed store mid-restart
        self.monitor = None
        await self.a.stop()
        app = self._make_app("a")
        # recover() runs inside start(); it must not spawn a background
        # repair task — the harness drives every round deterministically
        app.engine.auto_repair = False
        await app.start()
        app._audit_task.cancel()
        app._monitor_task.cancel()
        app._slo_task.cancel()
        self.a = app
        self.monitor = app.monitor
        return app.engine.last_recovery

    async def _phase_crash(self, ph: Phase) -> None:
        """The crash matrix.  Per seam: grow the corpus, arm the crash
        point, drive a backup into the injected crash, restart the
        client, and prove recovery — the re-run backup completes, a
        second ``recover()`` reconciles zero items (idempotency), and
        the invariant sweep shows zero violations."""
        crashes = self.facts.setdefault("crash_sites", [])
        for site in ph.sites or _CRASH_MATRIX:
            self._grow()
            self.plane.arm_crash(site)
            try:
                await asyncio.wait_for(self.a.backup(), 180)
                raise ScenarioError(f"armed crash at {site} never fired")
            except faults.CrashInjected as e:
                if e.site != site:
                    raise ScenarioError(
                        f"crash fired at {e.site}, armed {site}")
            report = await self._restart_client()
            # the drain: the next backup's send loop picks up every
            # leftover unsent packfile alongside the re-packed blobs
            snapshot = await asyncio.wait_for(
                self._retry_busy(lambda: self.a.backup()), 180)
            if not snapshot:
                raise ScenarioError(
                    f"post-crash backup after {site} returned no snapshot")
            self.facts["backups"] += 1
            again = await self.a.engine.recover()
            sweep = self.monitor.sweep()
            crashes.append({
                "site": site,
                "reconciled": report["reconciled"],
                "backlog": report["packfiles_pending"]
                + report["stripes_underplaced"],
                "idempotent": again["reconciled"] == 0,
                "violations_after": len(sweep.violations),
            })
        self.facts["source_digest"] = _tree_digest(self.src)

    async def _phase_gc(self, ph: Phase) -> None:
        """Snapshot lifecycle under pressure (docs/lifecycle.md).

        Both modes start by mutating the corpus and backing it up, so a
        ``keep-last:1`` prune has a victim snapshot whose exclusive
        blobs are provably dead — the bytes-reclaimed gates cannot pass
        vacuously.  ``sites`` mode then walks the GC crash matrix like
        :meth:`_phase_crash` walks the backup's; plain mode races GC
        against a concurrent backup + restore on the exclusivity lock.
        """
        self.a.store.set_retention_policy("keep-last:1")
        gcs = self.facts.setdefault("gc_reports", [])
        if ph.sites:
            crashes = self.facts.setdefault("crash_sites", [])
            for site in ph.sites:
                self._mutate_corpus()
                snapshot = await asyncio.wait_for(
                    self._retry_busy(lambda: self.a.backup()), 180)
                if not snapshot:
                    raise ScenarioError(
                        f"gc setup backup before {site} returned"
                        " no snapshot")
                self.facts["backups"] += 1
                self.plane.arm_crash(site)
                try:
                    await asyncio.wait_for(self.a.engine.run_gc(), 180)
                    raise ScenarioError(
                        f"armed crash at {site} never fired")
                except faults.CrashInjected as e:
                    if e.site != site:
                        raise ScenarioError(
                            f"crash fired at {e.site}, armed {site}")
                report = await self._restart_client()
                # the re-run must converge from whatever the recovery
                # sweep rolled forward or back
                gcs.append(await asyncio.wait_for(
                    self._retry_busy(lambda: self.a.engine.run_gc()), 180))
                again = await self.a.engine.recover()
                sweep = self.monitor.sweep()
                crashes.append({
                    "site": site,
                    "reconciled": report["reconciled"],
                    "backlog": report["packfiles_pending"]
                    + report["stripes_underplaced"],
                    "idempotent": again["reconciled"] == 0,
                    "violations_after": len(sweep.violations),
                })
        else:
            self._mutate_corpus()
            snapshot = await asyncio.wait_for(
                self._retry_busy(lambda: self.a.backup()), 180)
            if not snapshot:
                raise ScenarioError("gc setup backup returned no snapshot")
            self.facts["backups"] += 1
            self._restores += 1
            dest = self.workdir / f"gc_restore_{self._restores}"
            _, _, gc_report = await asyncio.wait_for(asyncio.gather(
                self._retry_busy(lambda: self.a.backup()),
                self._retry_busy(lambda: self.a.engine.run_restore(dest)),
                self._retry_busy(lambda: self.a.engine.run_gc()),
            ), 240)
            gcs.append(gc_report)
            self.facts["backups"] += 1
            self.facts["restores"] += 1
        self.facts["source_digest"] = _tree_digest(self.src)

    # --- gates -------------------------------------------------------------

    def _assertions(self, error, counters) -> List[sc.Assertion]:
        spec, facts = self.spec, self.facts
        A = sc.Assertion
        out = [A("phases_completed", error is None,
                 "" if error is None else f"{error[0]}: {error[1]}")]
        want_backups = sum(
            _crash_count(p) if p.kind == "crash"
            # gc: one setup backup per armed seam, or setup + racer
            else (len(p.sites) if p.sites else 2) if p.kind == "gc"
            else 1
            for p in spec.phases
            if p.kind in ("backup", "churn", "race", "wan", "crash", "gc"))
        out.append(A("backups_completed",
                     facts["backups"] >= want_backups,
                     f"{facts['backups']}/{want_backups}"))
        restore_kinds = ("restore", "restore_hedged")
        if any(p.kind in restore_kinds for p in spec.phases):
            out.append(A("restore_verified",
                         facts["restore_verified"] is True,
                         "byte-for-byte vs source digest"))
        if any(p.kind in restore_kinds + ("race",) for p in spec.phases):
            # the restore data plane must actually pull: a zero delta
            # means every stripe silently fell back to the legacy
            # RESTORE_ALL stream (PR 11)
            pulled = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_restore_bytes_pulled_total"))
            out.append(A("restore_telemetry_flowing", pulled > 0,
                         f"bytes_pulled={pulled:g}"))
        if any(p.kind == "restore_hedged" for p in spec.phases):
            won = counters.get(
                "bkw_restore_hedges_total{outcome=won}", 0)
            out.append(A("hedge_recovered_stall", won >= 1,
                         f"hedges_won={won:g}"))
        violation_s = sum(
            v for k, v in counters.items()
            if k.startswith("bkw_durability_violation_seconds_total"))
        saw_violation = violation_s > 0 or any(
            s.get("status_level", 0) >= 2 for s in self.samples)
        if spec.expect_violation:
            out.append(A("violation_observed", saw_violation,
                         f"violation_seconds={violation_s:.3f}"))
        else:
            out.append(A("zero_violation_seconds", not saw_violation,
                         f"violation_seconds={violation_s:.3f}"))
        final = self.monitor.last_report
        out.append(A("final_status",
                     final is not None
                     and final.status == spec.expect_final_status,
                     f"want {spec.expect_final_status}, got "
                     f"{final.status if final else 'no sweep'}"))
        if spec.min_shards_rebuilt:
            rebuilt = counters.get("bkw_repair_shards_rebuilt_total", 0)
            out.append(A("shards_rebuilt",
                         rebuilt >= spec.min_shards_rebuilt,
                         f"{rebuilt:g} >= {spec.min_shards_rebuilt}"))
        if want_backups:
            # performance telemetry must keep flowing: every backup's
            # chunk pipeline feeds bkw_device_dispatch_total and every
            # finalized transfer feeds a per-peer estimator sample — a
            # zero delta here means the profiler or PeerStats wiring
            # silently died (PR 7)
            dispatches = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_device_dispatch_total"))
            samples = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_peer_transfer_samples_total"))
            out.append(A("telemetry_flowing",
                         dispatches > 0 and samples > 0,
                         f"dispatches={dispatches:g}"
                         f" peer_samples={samples:g}"))
        if any(p.kind == "wan" for p in spec.phases):
            resumes = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_transfer_resumes_total"))
            out.append(A("resume_exercised", resumes >= 1,
                         f"resumes={resumes:g}"))
            resent = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_transfer_bytes_resent_total"))
            sent = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_transfer_bytes_total"))
            # resume must pay back: re-sent bytes a small fraction of
            # the payload bytes moved, not a restart-from-zero doubling
            out.append(A("resent_under_budget",
                         resent <= 0.25 * max(sent, 1.0),
                         f"resent={resent:g} of {sent:g} sent"))
            out.append(A("placement_capacity_aware",
                         facts.get("wan_placement_ok") is True,
                         "shards landed on measured-fast holders only"))
            out.append(A("placement_demotion_recovered",
                         facts.get("wan_placement_recovered") is True,
                         "probation expiry re-admitted the slow holder"))
        crash_like = [p for p in spec.phases if p.kind == "crash"
                      or (p.kind == "gc" and p.sites)]
        if crash_like:
            want = sum(_crash_count(p) if p.kind == "crash"
                       else len(p.sites) for p in crash_like)
            crashes = facts.get("crash_sites", [])
            injections = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_fault_injections_total")
                and "crash." in k)
            out.append(A("crashes_injected",
                         len(crashes) >= want and injections >= want,
                         f"{len(crashes)}/{want} seams crashed"
                         f" ({injections:g} injections counted)"))
            recoveries = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_recovery_runs_total"))
            out.append(A("recoveries_swept", recoveries >= 2 * len(crashes),
                         f"recovery_runs={recoveries:g} for"
                         f" {len(crashes)} crash(es)"))
            # the PR-9 hard gate: every crashed seam recovered to a
            # violation-free world and a provably idempotent recover()
            bad = [c["site"] for c in crashes
                   if not c["idempotent"] or c["violations_after"]]
            out.append(A("recovery_clean", bool(crashes) and not bad,
                         "all seams idempotent + violation-free"
                         if not bad else "dirty: " + ", ".join(bad)))
        if any(p.kind == "gc" for p in spec.phases):
            ok_runs = counters.get("bkw_gc_runs_total{outcome=ok}", 0)
            out.append(A("gc_completed", ok_runs >= 1,
                         f"ok_runs={ok_runs:g}"))
            reclaimed = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_gc_bytes_reclaimed_total"))
            out.append(A("gc_reclaimed_bytes", reclaimed > 0,
                         f"bytes_reclaimed={reclaimed:g}"))
            # make-before-break's other end: the holders really deleted
            # (every peer is in-process, so their serve-side counter
            # lands in the same registry)
            freed = sum(
                v for k, v in counters.items()
                if k.startswith("bkw_reclaim_bytes_freed_total"))
            out.append(A("gc_holders_freed_bytes", freed > 0,
                         f"reclaim_freed={freed:g}"))
        if spec.slo:
            breaches = facts.get("slo_breaches", [])
            fault_t = facts.get("fault_t")
            # detection: the first breach must land within 2 sweep
            # intervals of the first violated invariant sample
            first_bad = next((s["t"] for s in self.samples
                              if s.get("status_level", 0) >= 2), None)
            first_breach = breaches[0]["t"] if breaches else None
            budget_s = 2 * defaults.DURABILITY_SWEEP_INTERVAL_S
            detect_s = (None if first_breach is None or first_bad is None
                        else round(first_breach - first_bad, 3))
            out.append(A("slo_breach_detected",
                         detect_s is not None and detect_s <= budget_s,
                         f"detection={detect_s}s budget={budget_s}s"))
            # precision: every breach must postdate the armed fault
            false_pos = [b for b in breaches
                         if fault_t is None or b["t"] < fault_t]
            out.append(A("slo_no_false_positives", not false_pos,
                         f"{len(false_pos)} breach(es) before the fault"))
            # attribution: the armed fault site (a killed victim's id in
            # a fault:* cause) must rank in the explainer's top-3
            top3 = [c["id"] for d in self.diagnoses
                    for c in d["causes"][:3]]
            victims = facts.get("demoted", [])
            named = any(c.startswith("fault:")
                        and any(v in c for v in victims)
                        for c in top3)
            out.append(A("diagnosis_names_fault", named,
                         f"top causes: {sorted(set(top3))[:6]}"))
            facts["slo"] = {
                "detection_s": detect_s,
                "precision": (round(1.0 - len(false_pos)
                                    / len(breaches), 4)
                              if breaches else None),
                "breaches": len(breaches),
                "top_causes": top3[:3],
            }
        return out


async def run_scenario(spec: ScenarioSpec, workdir,
                       backend: Optional[ChunkerBackend] = None
                       ) -> sc.Scorecard:
    """setup -> run -> teardown; the one-call entry point used by the
    CLI (scripts/scenario.py), bench config 9, and the tests."""
    harness = ScenarioHarness(spec, Path(workdir), backend=backend)
    await harness.setup()
    try:
        return await harness.run()
    finally:
        await harness.teardown()


def builtin_scenarios() -> Dict[str, ScenarioSpec]:
    """The scenario matrix.  ``composed`` is the tier-1 acceptance run
    (churn + byzantine + race, < 60 s on loopback); ``full`` is the slow
    matrix adding unrepaired loss, a second repair wave, and a bigger
    corpus."""
    P = Phase
    return {
        "steady": ScenarioSpec(
            name="steady", seed=11,
            phases=(P("backup"), P("steady", duration_s=0.6),
                    P("restore"))),
        "churn": ScenarioSpec(
            name="churn", seed=21,
            phases=(P("backup"),
                    P("churn", duration_s=2.0, interval_s=0.3, grow=True),
                    # a churn backup may finish with a stripe short a
                    # shard (kept locally unsent); repair drains the debt
                    P("repair"),
                    P("restore"))),
        "byzantine": ScenarioSpec(
            name="byzantine", seed=31, min_shards_rebuilt=1,
            phases=(P("backup"), P("byzantine"), P("repair"),
                    P("restore"))),
        "loss": ScenarioSpec(
            name="loss", seed=41, expect_final_status="degraded",
            phases=(P("backup"), P("kill"), P("steady", duration_s=0.4))),
        # the live-SLO acceptance run: a quiet pre-fault baseline, then
        # three of six holders permanently dark — below RS k, so
        # durability flips to violated, violation-seconds accrue, the
        # fast burn windows fire, and the explainer must pin the armed
        # kills (docs/observability.md §Diagnosis)
        "diagnosis": ScenarioSpec(
            name="diagnosis", seed=121, slo=True,
            expect_violation=True, expect_final_status="violated",
            phases=(P("backup"),
                    P("steady", duration_s=1.0),
                    P("kill", count=3),
                    P("steady", duration_s=1.5))),
        "composed": ScenarioSpec(
            name="composed", seed=51, spares=2, min_shards_rebuilt=1,
            phases=(P("backup"),
                    P("steady", duration_s=0.4),
                    P("churn", duration_s=1.5, interval_s=0.3, grow=True),
                    P("byzantine"),
                    P("repair"),
                    P("race", grow=True),
                    P("restore_hedged"))),
        "wan": ScenarioSpec(
            name="wan", seed=71, corpus_files=4, chunk_bytes=4096,
            phases=(P("wan"), P("restore"))),
        # crash: a representative seam per commit layer (tier-1);
        # crash_full walks every sender-side seam (slow matrix)
        "crash": ScenarioSpec(
            name="crash", seed=81, corpus_files=4,
            phases=(P("backup"),
                    P("crash", sites=("pack.seal.pre", "index.save.pre",
                                      "placement.insert.post")),
                    P("restore"))),
        "crash_full": ScenarioSpec(
            name="crash_full", seed=91, corpus_files=4,
            phases=(P("backup"), P("crash"), P("restore"))),
        # gc: lifecycle race (tier-1); gc_full arms every GC commit seam
        "gc": ScenarioSpec(
            name="gc", seed=101, corpus_files=4,
            phases=(P("backup"), P("gc"), P("restore"))),
        "gc_full": ScenarioSpec(
            name="gc_full", seed=111, corpus_files=4,
            phases=(P("backup"),
                    P("gc", sites=(
                        "gc.prune.pre", "gc.prune.post",
                        "gc.sweep.pre", "gc.sweep.post",
                        "gc.compact.seal.pre", "gc.compact.seal.post",
                        "gc.swap.pre", "gc.swap.post",
                        "gc.reclaim.pre", "gc.reclaim.post")),
                    P("restore"))),
        "full": ScenarioSpec(
            name="full", seed=61, spares=2, corpus_files=10,
            corpus_file_bytes=48 * 1024, min_shards_rebuilt=1,
            phases=(P("backup"),
                    P("steady", duration_s=1.0),
                    P("churn", duration_s=4.0, interval_s=0.4, grow=True),
                    P("byzantine"),
                    P("repair"),
                    P("race", grow=True),
                    P("kill"),
                    P("steady", duration_s=0.6),
                    P("repair"),
                    P("restore"))),
    }
