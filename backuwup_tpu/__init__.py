"""backuwup_tpu — a TPU-native peer-to-peer encrypted backup framework.

A brand-new framework with the capabilities of the Rust reference
``profi248/backuwup`` (a P2P encrypted backup system: clients trade disk space
with matched peers, a coordination server does identity / matchmaking /
rendezvous only, and all backup data flows client<->client, end-to-end
encrypted), re-designed TPU-first:

* the content-defined chunker (windowed Gear rolling hash, FastCDC-2020-style
  normalized chunking) and the BLAKE3 chunk-fingerprint stage run as batched
  ``jit(vmap(...))`` JAX/Pallas kernels scanning many streams in parallel
  (reference hot loop: ``client/src/backup/filesystem/dir_packer.rs:246-311``);
* the global dedup index is a sharded open-addressed hash-table probe over TPU
  HBM under ``shard_map`` (reference: in-memory sorted vec + binary search,
  ``client/src/backup/filesystem/packfile/blob_index.rs:143-148``);
* long streams are split block-wise across devices with a 31-byte Gear-hash
  halo exchanged over ICI — the sequence-parallel decomposition of this domain.

Layer map (mirrors SURVEY.md section 1):

=====  =============================  ==================================
layer  reference                       backuwup_tpu
=====  =============================  ==================================
L0     ``shared/src``                  :mod:`backuwup_tpu.wire`, :mod:`backuwup_tpu.defaults`,
                                       :mod:`backuwup_tpu.utils` (retry / faults / tracing)
L1     ``client/src/key_manager.rs``   :mod:`backuwup_tpu.crypto`
L2     ``client/src/config``           :mod:`backuwup_tpu.store`
L3     ``client/src/backup``           :mod:`backuwup_tpu.ops`, :mod:`backuwup_tpu.snapshot`,
                                       :mod:`backuwup_tpu.engine`, :mod:`backuwup_tpu.audit`
L4     ``client/src/net_*``            :mod:`backuwup_tpu.net`
L5     ``client/src/ui``               :mod:`backuwup_tpu.ui`
L6     ``server/src``                  :mod:`backuwup_tpu.net.server`
=====  =============================  ==================================
"""

__version__ = "0.1.0"
