"""Erasure-coded shard placement (docs/erasure.md).

Layers:

* :mod:`.gf_cpu` — pure-numpy GF(2^8) Reed-Solomon oracle (ground truth).
* :mod:`.rs_tpu` — batched device kernel (table-lookup multiply +
  XOR-accumulate under ``jit(vmap)``), bit-exact against the oracle.
* :mod:`.stripe` — self-describing shard containers, split/assemble/
  rebuild, and the restore-side stripe assembly tree walk.

Routing between oracle and device lives on ``ops.backend.ChunkerBackend``
(``encode_shards`` / ``decode_shards``), mirroring ``digest_many``.
"""

from .stripe import (  # noqa: F401
    SHARD_ID_LEN,
    Shard,
    StripeError,
    assemble_packfile,
    assemble_tree,
    parse_shard,
    parse_shard_id,
    rebuild_shards,
    shard_id,
    split_packfile,
)
