"""Batched device Reed-Solomon encode/decode.

The GF(2^8) generator-matrix product is expressed as a table-lookup
multiply plus XOR-accumulate: gather ``MUL_TABLE[mat[i, j], shard[j, l]]``
and reduce over ``j`` with ``lax.bitwise_xor``.  Following the
``blake3_tpu`` idiom, the kernel is plain jnp/lax under
``jit(vmap(...))`` over shard stripes — no per-byte host work — and must
be bit-exact against the :mod:`.gf_cpu` oracle (tests pin the parity).

The k x k recovery-matrix inversion stays on the host (:func:`gf_cpu.
decode_matrix`): it is an O(k^3) operation on a <= 32-wide matrix, far
below device-dispatch cost.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import gf_cpu


@functools.lru_cache(maxsize=None)
def _matmul_batched():
    """jit(vmap) GF(2^8) matmul: (mat (r, j), stripes (B, j, L)) -> (B, r, L).

    The multiplication table is closed over as a device constant; jit
    caches per (r, j, B, L) shape bucket.
    """
    table = jnp.asarray(gf_cpu.MUL_TABLE)

    def one(mat, stripe):
        prods = table[mat.astype(jnp.int32)[:, :, None],
                      stripe.astype(jnp.int32)[None, :, :]]
        return jax.lax.reduce(prods, np.uint8(0), jax.lax.bitwise_xor, (1,))

    return jax.jit(jax.vmap(one, in_axes=(None, 0)))


def gf_matmul_stripes(mat: np.ndarray, stripes: np.ndarray) -> np.ndarray:
    """Device GF(2^8) matmul over a batch of stripes; returns host uint8."""
    mat = np.asarray(mat, dtype=np.uint8)
    stripes = np.asarray(stripes, dtype=np.uint8)
    out = _matmul_batched()(jnp.asarray(mat), jnp.asarray(stripes))
    return np.asarray(jax.device_get(out), dtype=np.uint8)


def encode_stripes(stripes: np.ndarray, m: int) -> np.ndarray:
    """(B, k, L) data shards -> (B, m, L) parity shards on device."""
    stripes = np.asarray(stripes, dtype=np.uint8)
    b, k, ln = stripes.shape
    if m == 0 or b == 0:
        return np.zeros((b, m, ln), dtype=np.uint8)
    parity_rows = gf_cpu.generator_matrix(k, m)[k:]
    return gf_matmul_stripes(parity_rows, stripes)


def decode_stripes(stripes: np.ndarray, k: int, m: int,
                   present: Sequence[int]) -> np.ndarray:
    """(B, k, L) surviving shards (rows in sorted ``present`` order) ->
    (B, k, L) reconstructed data shards."""
    stripes = np.asarray(stripes, dtype=np.uint8)
    if stripes.shape[0] == 0:
        return stripes
    cols = sorted(set(int(i) for i in present))
    rec = gf_cpu.decode_matrix(k, m, cols)[:, cols]
    return gf_matmul_stripes(rec, stripes)
