"""Shard containers and stripe assembly.

A sealed packfile is split into ``k`` data + ``m`` parity shards; each
shard ships as a small self-describing container so the restore/repair
side needs no out-of-band metadata:

    magic ``BKWS`` (4) | version u8 | shard index u8 | k u8 | m u8 |
    orig_len u64 LE | BLAKE3(payload) (32) | payload

The per-shard digest is what makes corrupted-shard *detection* (vs mere
reconstruction failure) possible: a container whose payload hash
mismatches is dropped before it can poison the GF solve, and any k
clean survivors still reconstruct.

Shard ids on the wire and in the audit plane are the 12-byte packfile id
plus one index byte (13 bytes, :func:`shard_id`).  Encode is
deterministic — re-splitting a packfile or rebuilding a lost shard from
survivors reproduces byte-identical containers — which keeps re-sends
idempotent and pre-computed per-shard audit challenge tables valid after
repair.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from . import gf_cpu

MAGIC = b"BKWS"
VERSION = 1
HEADER_LEN = 4 + 1 + 1 + 1 + 1 + 8 + 32  # 48
DIGEST_LEN = 32
SHARD_ID_LEN = 13  # 12-byte packfile id + 1 index byte


class StripeError(Exception):
    pass


def shard_id(packfile_id: bytes, index: int) -> bytes:
    return bytes(packfile_id) + bytes([index])


def parse_shard_id(sid: bytes) -> Tuple[bytes, int]:
    sid = bytes(sid)
    if len(sid) != SHARD_ID_LEN:
        raise StripeError(f"bad shard id length {len(sid)}")
    return sid[:-1], sid[-1]


@dataclass(frozen=True)
class Shard:
    """One parsed container (digest NOT yet verified — see
    :func:`collect_shards`)."""

    index: int
    k: int
    m: int
    orig_len: int
    digest: bytes
    payload: bytes


def pack_shard(index: int, k: int, m: int, orig_len: int, digest: bytes,
               payload: bytes) -> bytes:
    if len(digest) != DIGEST_LEN:
        raise StripeError("bad shard digest length")
    return (MAGIC + bytes([VERSION, index, k, m])
            + struct.pack("<Q", orig_len) + digest + payload)


def parse_shard(blob: bytes) -> Shard:
    blob = bytes(blob)
    if len(blob) < HEADER_LEN or blob[:4] != MAGIC:
        raise StripeError("not a shard container")
    if blob[4] != VERSION:
        raise StripeError(f"unsupported shard version {blob[4]}")
    index, k, m = blob[5], blob[6], blob[7]
    (orig_len,) = struct.unpack("<Q", blob[8:16])
    digest, payload = blob[16:48], blob[48:]
    if not (1 <= k and k + m <= 256 and index < k + m):
        raise StripeError(f"bad shard geometry idx={index} k={k} m={m}")
    if len(payload) != gf_cpu.shard_len(orig_len, k):
        raise StripeError("shard payload length mismatch")
    return Shard(index=index, k=k, m=m, orig_len=orig_len, digest=digest,
                 payload=payload)


def split_packfile(data: bytes, k: int, m: int, backend) -> List[bytes]:
    """Encode ``data`` into k + m shard containers (deterministic)."""
    data = bytes(data)
    data_shards = gf_cpu.split_data(data, k)
    parity = backend.encode_shards(data_shards[None], m)[0]
    rows = np.concatenate([data_shards, parity], axis=0)
    payloads = [rows[i].tobytes() for i in range(k + m)]
    digests = backend.digest_many(payloads)
    return [pack_shard(i, k, m, len(data), digests[i], payloads[i])
            for i in range(k + m)]


def collect_shards(containers: Iterable[bytes], backend,
                   ) -> Tuple[Dict[int, Shard], Optional[Tuple[int, int, int]],
                              List[str]]:
    """Parse + digest-verify containers; drop (and report) bad ones.

    Returns ``(shards_by_index, (k, m, orig_len) or None, drop_reasons)``.
    """
    parsed: List[Shard] = []
    drops: List[str] = []
    for blob in containers:
        try:
            parsed.append(parse_shard(blob))
        except StripeError as e:
            drops.append(str(e))
    good = parsed and backend.digest_many([s.payload for s in parsed])
    shards: Dict[int, Shard] = {}
    geom: Optional[Tuple[int, int, int]] = None
    for s, digest in zip(parsed, good or []):
        if digest != s.digest:
            drops.append(f"shard {s.index}: payload digest mismatch")
            continue
        if geom is None:
            geom = (s.k, s.m, s.orig_len)
        elif (s.k, s.m, s.orig_len) != geom:
            drops.append(f"shard {s.index}: inconsistent stripe geometry")
            continue
        shards[s.index] = s
    return shards, geom, drops


def _decode_data(shards: Dict[int, Shard], k: int, m: int,
                 backend) -> np.ndarray:
    present = sorted(shards)[:k]
    stacked = np.stack([np.frombuffer(shards[i].payload, dtype=np.uint8)
                        for i in present], axis=0)
    return backend.decode_shards(stacked[None], k, m, present)[0]


def assemble_packfile(containers: Iterable[bytes], backend) -> bytes:
    """Reconstruct the original packfile bytes from any k valid shards."""
    shards, geom, drops = collect_shards(containers, backend)
    if geom is None:
        raise StripeError("no valid shard containers: " + "; ".join(drops))
    k, m, orig_len = geom
    if len(shards) < k:
        raise StripeError(
            f"only {len(shards)} valid shards, need {k}"
            + (": " + "; ".join(drops) if drops else ""))
    return gf_cpu.join_data(_decode_data(shards, k, m, backend), orig_len)


def rebuild_shards(containers: Iterable[bytes], missing: Sequence[int],
                   backend) -> Dict[int, bytes]:
    """Rebuild the ``missing`` shard containers from any k survivors.

    Byte-identical to the originals (sourceless repair leans on this)."""
    shards, geom, drops = collect_shards(containers, backend)
    if geom is None:
        raise StripeError("no valid shard containers: " + "; ".join(drops))
    k, m, orig_len = geom
    if len(shards) < k:
        raise StripeError(f"only {len(shards)} valid shards, need {k}")
    data = _decode_data(shards, k, m, backend)
    parity = None
    if any(int(i) >= k for i in missing):
        parity = backend.encode_shards(data[None], m)[0]
    out: Dict[int, bytes] = {}
    for idx in missing:
        idx = int(idx)
        if not 0 <= idx < k + m:
            raise StripeError(f"shard index {idx} out of range")
        row = data[idx] if idx < k else parity[idx - k]
        payload = np.asarray(row, dtype=np.uint8).tobytes()
        digest = backend.digest_many([payload])[0]
        out[idx] = pack_shard(idx, k, m, orig_len, digest, payload)
    return out


def iter_shard_dirs(shard_root: Path):
    """Yield ``(packfile_id, [container bytes...])`` under a shard tree.

    Layout (written by ``RestoreFilesWriter``): ``shard_root/<pid hex>/
    <index>``.  Unparseable directory names are skipped.
    """
    if not shard_root.is_dir():
        return
    for pid_dir in sorted(shard_root.iterdir()):
        try:
            pid = bytes.fromhex(pid_dir.name)
        except ValueError:
            continue
        if not pid_dir.is_dir() or len(pid) != 12:
            continue
        blobs = [p.read_bytes() for p in sorted(pid_dir.iterdir())
                 if p.is_file()]
        yield pid, blobs


def assemble_tree(shard_root: Path, pack_root: Path, backend,
                  ) -> Tuple[List[bytes], List[Tuple[bytes, str]]]:
    """Reconstruct every stripe under ``shard_root`` into ``pack_root``.

    The restore path calls this after the pull phase: reconstructed
    packfiles land exactly where whole-packfile streams would have, so
    everything downstream (coverage check, unpack) is stripe-blind.
    Returns ``(assembled_pids, [(pid, reason) failures])``.
    """
    from ..snapshot.packfile import packfile_path

    done: List[bytes] = []
    failed: List[Tuple[bytes, str]] = []
    for pid, blobs in iter_shard_dirs(shard_root):
        out = packfile_path(pack_root, pid)
        if out.exists():
            done.append(pid)
            continue
        try:
            data = assemble_packfile(blobs, backend)
        except StripeError as e:
            failed.append((pid, str(e)))
            continue
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_bytes(data)
        done.append(pid)
    return done, failed
