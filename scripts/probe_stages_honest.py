"""Honest per-stage device timings (chained-execution sync; see
backuwup_tpu/obs/profile.py)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from backuwup_tpu.obs.profile import dev_time


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import functools

    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops.cdc_tpu import _HALO, scan_select_batch
    from backuwup_tpu.ops.blake3_tpu import digest_padded
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.pipeline import DevicePipeline
    from backuwup_tpu.ops.scan_fused import fused_candidate_words

    P = 256 << 20
    params = CDCParams()
    pipe = DevicePipeline(params)
    print("fused available:", pipe.fused)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (P,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]
                               ).reshape(1, _HALO + P)

    buf = synth(key)
    nv = jnp.asarray(np.full(1, P, dtype=np.int32))
    print(f"synth: {dev_time(synth, key)*1000:.2f} ms")

    # scan front end alone (fused kernel incl. transposes)
    fw = jax.jit(functools.partial(fused_candidate_words,
                                   mask_s=params.mask_s, mask_l=params.mask_l))
    print(f"fused_candidate_words: {dev_time(fw, buf, nv)*1000:.2f} ms")

    # full scan+select, fused and xla
    s_cap, l_cap, cut_cap = pipe._caps(P)
    for fused in (True, False):
        fn = jax.jit(functools.partial(
            scan_select_batch, min_size=params.min_size,
            desired_size=params.desired_size, max_size=params.max_size,
            mask_s=params.mask_s, mask_l=params.mask_l,
            s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=fused))
        print(f"scan_select_batch fused={fused}: "
              f"{dev_time(fn, buf, nv)*1000:.2f} ms")

    # digest: gather+digest of 256 chunks x 1 MiB from the resident stream
    n_chunks = 256
    offs = jnp.asarray((np.arange(n_chunks) * (1 << 20)).astype(np.int32))
    lens = jnp.asarray(np.full(n_chunks, 1 << 20, dtype=np.int32))
    flat = jnp.pad(buf.reshape(-1), (0, 3072 * 1024))

    @functools.partial(jax.jit, static_argnames=("L",))
    def gd(flat, offs, lens, L):
        def one(off):
            return jax.lax.dynamic_slice(flat, (off,), (L * 1024,))
        b = jax.vmap(one)(offs)
        return digest_padded(b, lens, L=L)

    for L, B in ((1024, 256), (2048, 128), (3072, 128)):
        o = offs[:B]
        ln = lens[:B]
        dt = dev_time(gd, flat, o, ln, L)
        mib = B * L / 1024
        print(f"gather+digest B={B} L={L}: {dt*1000:.2f} ms "
              f"({mib/max(dt,1e-9)/1024:.2f} GiB/s of padded bytes)")


if __name__ == "__main__":
    main()
