"""Wall-clock of the zero-round-trip device driver on the live rig."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops.cdc_tpu import _HALO
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.pipeline import DevicePipeline

    n_seg = int(os.environ.get("N_SEG", "12"))
    seg_mib = 256
    P = seg_mib << 20
    params = CDCParams()
    pipe = DevicePipeline(params)
    print("fused:", pipe.fused)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (P,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]
                               ).reshape(1, _HALO + P)

    nv = np.full(1, P, dtype=np.int32)

    # warm both drivers on two segments
    key, k1, k2 = jax.random.split(key, 3)
    warm = [(synth(k1), nv), (synth(k2), nv)]
    list(pipe.manifest_segments_device(iter(warm), strict_overflow=True))
    list(pipe.manifest_segments(iter(warm), strict_overflow=True))

    corpus = []
    for _ in range(n_seg):
        key, sub = jax.random.split(key)
        corpus.append((synth(sub), nv))
    jax.block_until_ready([b for b, _ in corpus])
    # force real settle: download one byte of the last segment
    np.asarray(corpus[-1][0][0, -1])

    for name, driver in (("device(0-rt)", pipe.manifest_segments_device),
                         ("host-tiled", pipe.manifest_segments)):
        t0 = time.time()
        res = list(driver(iter(corpus), strict_overflow=True))
        dt = time.time() - t0
        chunks = sum(len(c) for batch in res for c, _ in batch)
        print(f"{name}: {n_seg}x{seg_mib} MiB in {dt:.2f}s = "
              f"{n_seg*seg_mib/dt:.0f} MiB/s ({chunks} chunks)")


if __name__ == "__main__":
    main()
