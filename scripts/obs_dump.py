#!/usr/bin/env python3
"""Operator CLI for the observability plane (docs/observability.md).

Three read-only views, no accelerator and no repo imports beyond stdlib:

* ``--url http://HOST:PORT`` — fetch ``/metrics`` from a coordination
  server or a client status listener (BKW_STATUS_PORT) and print the
  non-zero samples, one per line, followed by estimated p50/p99 lines
  for each histogram series.  ``--watch N`` re-polls every N seconds
  and prints only the samples that changed, with their deltas.
  Repeatable: several ``--url`` flags (a federation's nodes) print one
  per-node section each plus a merged fleet view with samples summed;
  ``--watch`` then tracks deltas of the merged view.
* ``--journal PATH [-n N]`` — tail the last N parsed lines of a JSONL
  journal written under ``BKW_JOURNAL``; ``--trace TID`` filters to one
  correlated trace.  Repeatable: several clients' journals concatenate.
* ``--panic PATH`` — pretty-print a ``<journal>.panic.json`` flight-
  recorder dump (metrics snapshot + journal tail at panic time).

Two SLO-plane views (PR 20):

* ``--url ... --watch N --series`` — keep a rolling last-N history of
  every changing sample across polls and render one unicode sparkline
  per key (``~ series key ▁▃▇ last=...``) each interval: the terminal
  version of the in-process ``obs/series.py`` ring buffers.
* ``--journal PATH --explain`` — render the **latest** ranked
  ``diagnosis_report`` journal line (obs/diagnose.py): the breach
  header plus one ``score kind id xcount evidence`` line per cause.
  Exits 1 when the journal holds no report.

Plus one export: ``--journal PATH [--journal PATH2 ...] --timeline
out.json`` merges the journals into one Chrome trace-event document
loadable in Perfetto (ui.perfetto.dev), one process row per journal,
cross-process spans correlated by the trace ids on the wire envelopes;
``--trace TID`` cuts it to one backup.  (Timeline export is the one
mode that imports the repo — ``backuwup_tpu.obs.timeline`` — since the
span-to-event mapping must not fork from the library.)

The ``--url`` view surfaces the per-peer transfer estimators
(``bkw_peer_*`` gauges, net/peer_stats.py) as ``~ peer`` summary lines
next to the generic per-series histogram p50/p99 lines.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import time
import urllib.request

_BUCKET_RE = re.compile(r'^(?P<name>[A-Za-z_:][\w:]*)_bucket'
                        r'\{(?P<labels>[^}]*)\} ')
_LE_RE = re.compile(r'(^|,)le="(?P<le>[^"]+)"')


def _fetch(url: str) -> str:
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode("utf-8", "replace")


def _parse(text: str) -> "dict[str, float]":
    """Exposition text -> {sample key: value}, skipping comments."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        try:
            key, value = line.rsplit(" ", 1)
            out[key] = float(value)
        except (IndexError, ValueError):
            continue
    return out


def _quantile(bounds, counts, q):
    """Log-bucket quantile estimate — same geometric interpolation as
    backuwup_tpu.obs.metrics.quantile_from_buckets, restated here so the
    script stays stdlib-only."""
    total = sum(counts)
    if total <= 0:
        return math.nan
    rank = q * total
    cum = 0
    for i, c in enumerate(counts[:-1]):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = float(bounds[i])
            frac = (rank - prev) / c
            if lo > 0.0:
                return lo * (hi / lo) ** frac
            return hi * frac
    return float(bounds[-1])


def _histogram_quantiles(samples: dict, prev=None) -> "list[str]":
    """One ``p50/p99`` line per histogram series; with ``prev``, over
    the delta of the cumulative bucket counts (this interval only)."""
    series = {}
    for key, value in samples.items():
        m = _BUCKET_RE.match(key + " ")
        if not m:
            continue
        le = _LE_RE.search(m.group("labels"))
        if not le:
            continue
        base = _LE_RE.sub("", m.group("labels")).strip(",")
        if prev is not None:
            value -= prev.get(key, 0.0)
        series.setdefault((m.group("name"), base),
                          {})[le.group("le")] = value
    lines = []
    for (name, base), buckets in sorted(series.items()):
        keys = sorted((k for k in buckets if k != "+Inf"), key=float)
        bounds = [float(k) for k in keys]
        counts, cum_prev = [], 0.0
        for k in keys:
            counts.append(buckets[k] - cum_prev)
            cum_prev = buckets[k]
        counts.append(buckets.get("+Inf", cum_prev) - cum_prev)
        total = int(sum(counts))
        if total <= 0 or not bounds:
            continue
        p50 = _quantile(bounds, counts, 0.5)
        p99 = _quantile(bounds, counts, 0.99)
        tag = f"{name}{{{base}}}" if base else name
        lines.append(f"~ {tag} p50={p50:.6g} p99={p99:.6g} n={total}")
    return lines


_PEER_GAUGE_RE = re.compile(
    r'^(?P<name>bkw_peer_(?:throughput_bytes_per_second|latency_seconds'
    r'|success_ratio|transfer_samples_total))\{peer="(?P<peer>[^"]*)"\} $')

_PEER_FIELDS = {
    "bkw_peer_throughput_bytes_per_second": ("tput_MiBs", 1 / (1 << 20)),
    "bkw_peer_latency_seconds": ("lat_s", 1.0),
    "bkw_peer_success_ratio": ("success", 1.0),
    "bkw_peer_transfer_samples_total": ("n", 1.0),
}


def _peer_lines(samples: dict) -> "list[str]":
    """One summary line per peer from the estimator gauges
    (net/peer_stats.py): throughput, latency, success ratio, samples."""
    peers: dict = {}
    for key, value in samples.items():
        m = _PEER_GAUGE_RE.match(key + " ")
        if not m:
            continue
        field, scale = _PEER_FIELDS[m.group("name")]
        peers.setdefault(m.group("peer"), {})[field] = value * scale
    lines = []
    for peer, fields in sorted(peers.items()):
        parts = " ".join(f"{k}={fields[k]:.6g}"
                         for k in ("tput_MiBs", "lat_s", "success", "n")
                         if k in fields)
        lines.append(f"~ peer {peer} {parts}")
    return lines


_RESTORE_PULL_RE = re.compile(
    r'^bkw_restore_bytes_pulled_total\{peer="(?P<peer>[^"]*)"\} $')
_RESTORE_HEDGE_RE = re.compile(
    r'^bkw_restore_hedges_total\{outcome="(?P<outcome>[^"]*)"\} $')


def _restore_lines(samples: dict) -> "list[str]":
    """One summary line for the restore data plane (net/transfer.py
    download lanes): bytes pulled per source peer and the hedging
    policy's win/loss record."""
    pulled: dict = {}
    hedges: dict = {}
    for key, value in samples.items():
        m = _RESTORE_PULL_RE.match(key + " ")
        if m:
            pulled[m.group("peer")] = value
            continue
        m = _RESTORE_HEDGE_RE.match(key + " ")
        if m:
            hedges[m.group("outcome")] = value
    lines = []
    if pulled:
        total = sum(pulled.values())
        top = max(pulled, key=pulled.get)
        lines.append(
            f"~ restore pulled_MiB={total / (1 << 20):.6g} "
            f"sources={len(pulled)} top={top} "
            f"top_MiB={pulled[top] / (1 << 20):.6g}")
    if hedges:
        parts = " ".join(f"{k}={hedges[k]:g}"
                         for k in ("won", "lost", "wasted") if k in hedges)
        lines.append(f"~ restore hedges {parts}")
    return lines


_SPARK_BARS = "▁▂▃▄▅▆▇█"


def _sparkline(values) -> str:
    """Min/max-normalized unicode sparkline; flat series render mid-bar
    so one glance separates 'constant' from 'missing'."""
    values = list(values)
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK_BARS[3] * len(values)
    idx = [int((v - lo) / (hi - lo) * (len(_SPARK_BARS) - 1))
           for v in values]
    return "".join(_SPARK_BARS[i] for i in idx)


def _series_lines(history: dict, changed=None) -> "list[str]":
    """One sparkline line per tracked key (``--series``); with
    ``changed``, only keys whose last poll moved."""
    lines = []
    for key, values in sorted(history.items()):
        if changed is not None and key not in changed:
            continue
        if len(values) < 2:
            continue
        lines.append(f"~ series {key} {_sparkline(values)} "
                     f"last={values[-1]:g}")
    return lines


def _print_view(samples: dict, prev=None) -> None:
    """Non-zero samples (first poll) or changed-with-delta (re-polls),
    then the histogram quantile and per-peer estimator summary lines."""
    for key, value in samples.items():
        if prev is None:
            # keep the catalog readable: hide never-touched zero samples
            # (bucket cumulative zeros, un-fired counters)
            if value != 0.0:
                print(f"{key} {value:g}")
        else:
            delta = value - prev.get(key, 0.0)
            if delta != 0.0:
                print(f"{key} {value:g} ({delta:+g})")
    for line in _histogram_quantiles(samples, prev=prev):
        print(line)
    for line in _peer_lines(samples):
        print(line)
    for line in _restore_lines(samples):
        print(line)


def _merge(sample_maps) -> "dict[str, float]":
    """Sum the same sample key across nodes.  Sound for counters and
    histogram buckets (cumulative, monotone); gauges come out as a
    fleet total, which the merged header says out loud."""
    out: dict = {}
    for samples in sample_maps:
        for key, value in samples.items():
            out[key] = out.get(key, 0.0) + value
    return out


def dump_metrics(urls, raw: bool, watch: float, series: bool = False,
                 lastn: int = 50) -> int:
    """One URL: the classic view.  Several (repeated ``--url``, e.g. a
    federation's nodes): a per-node section each, then a merged view
    with counters summed — the fleet-wide picture one grep away."""
    if raw and not watch:
        for url in urls:
            sys.stdout.write(_fetch(url))
        return 0

    def poll():
        per = [_parse(_fetch(u)) for u in urls]
        return per, (_merge(per) if len(per) > 1 else per[0])

    history: dict = {}

    def track(samples):
        if not series:
            return
        for key, value in samples.items():
            history.setdefault(key, []).append(value)
            del history[key][:-max(2, lastn)]

    per, merged = poll()
    track(merged)
    if len(urls) > 1:
        for url, samples in zip(urls, per):
            print(f"== {url}")
            _print_view(samples)
        print(f"== merged ({len(urls)} nodes, samples summed)")
    _print_view(merged)
    while watch:
        time.sleep(watch)
        _, fresh = poll()
        track(fresh)
        print(f"--- {time.strftime('%H:%M:%S')} (+{watch:g}s)")
        _print_view(fresh, prev=merged)
        changed = {k for k, v in fresh.items()
                   if v != merged.get(k, 0.0)}
        for line in _series_lines(history, changed=changed):
            print(line)
        merged = fresh
    return 0


def dump_journal(paths, lines: int, trace: str) -> int:
    kept = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue  # torn tail line from a crash mid-write
                if trace and doc.get("trace_id") != trace:
                    continue
                kept.append(doc)
    for doc in kept[-lines:]:
        print(json.dumps(doc, sort_keys=True))
    return 0


def dump_explain(paths) -> int:
    """Render the newest ``diagnosis_report`` line across the journals:
    breach header, then the evidence-ranked causes (obs/diagnose.py)."""
    report = None
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue
                if doc.get("kind") != "diagnosis_report":
                    continue
                if report is None or doc.get("ts", 0) >= report.get("ts", 0):
                    report = doc
    if report is None:
        print("no diagnosis_report in journal(s)")
        return 1
    print(f"objective={report.get('objective', '?')} "
          f"status={report.get('status', '?')} "
          f"t={report.get('t', 0):g} "
          f"window_s={report.get('window_s', 0):g} "
          f"evidence_events={report.get('evidence_events', 0)}")
    for cause in report.get("causes", ()):
        evidence = cause.get("evidence", "")
        print(f"  {cause.get('score', 0):6.3f} "
              f"{cause.get('kind', '?'):<10} {cause.get('id', '?')} "
              f"x{cause.get('count', 1)}"
              + (f"  {evidence}" if evidence else ""))
    return 0


def dump_timeline(paths, out: str, trace: str) -> int:
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    from backuwup_tpu.obs import timeline

    doc = timeline.export_timeline(paths, out, trace_id=trace or None)
    print(f"{len(doc['traceEvents'])} trace events -> {out} "
          f"(load in ui.perfetto.dev)")
    return 0


def dump_panic(path: str) -> int:
    with open(path, "r", encoding="utf-8") as f:
        print(json.dumps(json.load(f), indent=2, sort_keys=True))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", action="append",
                     help="base URL of a /metrics endpoint (repeatable:"
                          " per-node views plus a merged fleet view)")
    src.add_argument("--journal", action="append",
                    help="path to a BKW_JOURNAL JSONL file (repeatable:"
                         " merge several clients' journals)")
    src.add_argument("--panic", help="path to a <journal>.panic.json dump")
    ap.add_argument("-n", "--lines", type=int, default=50,
                    help="journal lines to show (default 50)")
    ap.add_argument("--trace", default="",
                    help="only journal lines with this trace_id")
    ap.add_argument("--timeline", default="", metavar="OUT",
                    help="with --journal: write a Perfetto-loadable Chrome"
                         " trace-event JSON merging the journals")
    ap.add_argument("--raw", action="store_true",
                    help="with --url: full exposition incl. zero samples")
    ap.add_argument("--watch", type=float, default=0.0, metavar="N",
                    help="with --url: re-poll every N seconds and print "
                         "changed samples with deltas (ctrl-c to stop)")
    ap.add_argument("--series", action="store_true",
                    help="with --url --watch: keep a rolling last-N"
                         " (-n) history per sample and print sparklines"
                         " for the keys that moved each interval")
    ap.add_argument("--explain", action="store_true",
                    help="with --journal: render the latest ranked"
                         " diagnosis_report (exit 1 when none)")
    args = ap.parse_args(argv)
    if args.url:
        try:
            return dump_metrics(args.url, args.raw, args.watch,
                                series=args.series, lastn=args.lines)
        except KeyboardInterrupt:
            return 0
    if args.journal:
        if args.timeline:
            return dump_timeline(args.journal, args.timeline, args.trace)
        if args.explain:
            return dump_explain(args.journal)
        return dump_journal(args.journal, args.lines, args.trace)
    return dump_panic(args.panic)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # piped into head etc.
        os.close(sys.stdout.fileno())
        sys.exit(0)
