#!/usr/bin/env python3
"""Operator CLI for the observability plane (docs/observability.md).

Three read-only views, no accelerator and no repo imports beyond stdlib:

* ``--url http://HOST:PORT`` — fetch ``/metrics`` from a coordination
  server or a client status listener (BKW_STATUS_PORT) and print the
  non-zero samples, one per line.
* ``--journal PATH [-n N]`` — tail the last N parsed lines of a JSONL
  journal written under ``BKW_JOURNAL``; ``--trace TID`` filters to one
  correlated trace.
* ``--panic PATH`` — pretty-print a ``<journal>.panic.json`` flight-
  recorder dump (metrics snapshot + journal tail at panic time).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request


def dump_metrics(url: str, raw: bool) -> int:
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=10) as resp:
        text = resp.read().decode("utf-8", "replace")
    if raw:
        sys.stdout.write(text)
        return 0
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        # keep the catalog readable: hide never-touched zero samples
        # (bucket cumulative zeros, un-fired counters)
        try:
            value = float(line.rsplit(" ", 1)[1])
        except (IndexError, ValueError):
            value = 1.0
        if value != 0.0:
            print(line)
    return 0


def dump_journal(path: str, lines: int, trace: str) -> int:
    kept = []
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                doc = json.loads(raw)
            except ValueError:
                continue  # torn tail line from a crash mid-write
            if trace and doc.get("trace_id") != trace:
                continue
            kept.append(doc)
    for doc in kept[-lines:]:
        print(json.dumps(doc, sort_keys=True))
    return 0


def dump_panic(path: str) -> int:
    with open(path, "r", encoding="utf-8") as f:
        print(json.dumps(json.load(f), indent=2, sort_keys=True))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="base URL of a /metrics endpoint")
    src.add_argument("--journal", help="path to a BKW_JOURNAL JSONL file")
    src.add_argument("--panic", help="path to a <journal>.panic.json dump")
    ap.add_argument("-n", "--lines", type=int, default=50,
                    help="journal lines to show (default 50)")
    ap.add_argument("--trace", default="",
                    help="only journal lines with this trace_id")
    ap.add_argument("--raw", action="store_true",
                    help="with --url: full exposition incl. zero samples")
    args = ap.parse_args(argv)
    if args.url:
        return dump_metrics(args.url, args.raw)
    if args.journal:
        return dump_journal(args.journal, args.lines, args.trace)
    return dump_panic(args.panic)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # piped into head etc.
        os.close(sys.stdout.fileno())
        sys.exit(0)
