"""Break down fused-scan cost: transpose / halo build / kernel / out-transpose."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, *args, reps=5):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.time() - t0) / reps


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import functools

    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops import scan_fused as sf

    P = 256 << 20
    S = P // 128
    rng = np.random.default_rng(7)
    ext = rng.integers(0, 256, (1, 31 + P), dtype=np.uint8)
    dev = jnp.asarray(ext)
    jax.block_until_ready(dev)
    mask_s = (0xFFFFFFFF << (32 - 22)) & 0xFFFFFFFF
    mask_l = (0xFFFFFFFF << (32 - 18)) & 0xFFFFFFFF
    nv = jnp.asarray(np.array([P], dtype=np.int32))

    @jax.jit
    def build(ext_b):
        ext32 = jnp.pad(ext_b, ((0, 0), (1, 0)))
        body = ext32[:, 32:].reshape(1, 128, S).transpose(0, 2, 1)
        halo0 = jnp.concatenate(
            [ext32[:, :32, None], body[:, S - 32:, :-1]], axis=2)
        return body, halo0

    body, halo0 = build(dev)
    jax.block_until_ready((body, halo0))
    print(f"build(transpose+halo): {timeit(build, dev)*1000:.1f} ms")

    @functools.partial(jax.jit, static_argnames=("R",))
    def kern_only(body, halo0, nv, R):
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        kernel = sf._make_scan_kernel(mask_s, mask_l, S, R)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1, S // R),
            in_specs=[
                pl.BlockSpec((1, 32, 128), lambda b, i, *_: (b, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, R, 128), lambda b, i, *_: (b, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 32, 128),
                             lambda b, i, *_: (b, jnp.maximum(
                                 i * (R // 32) - 1, 0), 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, R // 32, 128), lambda b, i, *_: (b, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, R // 32, 128), lambda b, i, *_: (b, i, 0),
                             memory_space=pltpu.VMEM),
            ],
        )
        return pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct((1, S // 32, 128), jnp.uint32),
                       jax.ShapeDtypeStruct((1, S // 32, 128), jnp.uint32)],
            grid_spec=grid_spec,
        )(nv, halo0, body, body)

    for R in (1024, 2048, 4096, 8192):
        if S % R:
            continue
        try:
            dt = timeit(kern_only, body, halo0, nv, R)
            print(f"kernel only R={R}: {dt*1000:.1f} ms = {256/dt:.0f} MiB/s")
        except Exception as e:
            print(f"R={R}: FAIL {str(e)[:200]}")

    @jax.jit
    def out_t(wl):
        return wl.transpose(0, 2, 1).reshape(1, P // 32)

    wl, ws = kern_only(body, halo0, nv, 2048)
    jax.block_until_ready((wl, ws))
    print(f"out transpose (one array): {timeit(out_t, wl)*1000:.1f} ms")

    dt = timeit(jax.jit(functools.partial(
        sf.fused_candidate_words, mask_s=mask_s, mask_l=mask_l)), dev, nv)
    print(f"full fused_candidate_words: {dt*1000:.1f} ms = {256/dt:.0f} MiB/s")


if __name__ == "__main__":
    main()
