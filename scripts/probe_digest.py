"""Isolate the digest-section cost of scan_digest_batch."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from backuwup_tpu.obs.profile import dev_time


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import functools

    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops.blake3_tpu import digest_padded
    from backuwup_tpu.ops.cdc_tpu import _HALO, scan_select_batch
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.manifest_device import (class_caps,
                                                  class_leaf_sizes,
                                                  scan_digest_batch)
    from backuwup_tpu.ops.pipeline import DevicePipeline

    # standalone digest_padded: 256 chunks x 1 MiB resident tile
    key = jax.random.PRNGKey(1)
    for B, L in ((256, 1024), (248, 1280), (128, 2048)):
        tile = jax.random.randint(key, (B, L * 1024), 0, 256, dtype=jnp.uint8)
        lens = jnp.full(B, L * 1024 - 7, dtype=jnp.int32)
        jax.block_until_ready(tile)
        for pallas in (False, True):
            fn = jax.jit(functools.partial(digest_padded, L=L, pallas=pallas))
            dt = dev_time(fn, tile, lens, n=10)
            mib = B * L / 1024
            print(f"digest_padded B={B} L={L} pallas={pallas}: "
                  f"{dt*1e3:.1f} ms ({mib/max(dt,1e-9)/1024:.2f} GiB/s)",
                  flush=True)

    # full manifest with XLA vs pallas digest
    P = 256 << 20
    params = CDCParams()
    pipe = DevicePipeline(params)
    buf = jnp.concatenate(
        [jnp.zeros(_HALO, dtype=jnp.uint8),
         jax.random.randint(key, (P,), 0, 256, dtype=jnp.uint8)]
    ).reshape(1, _HALO + P)
    nv = jnp.asarray(np.full(1, P, dtype=np.int32))
    s_cap, l_cap, cut_cap = pipe._caps(P)
    classes = class_leaf_sizes(params)
    caps = class_caps(params, P, 1)
    base = dict(min_size=params.min_size, desired_size=params.desired_size,
                max_size=params.max_size, mask_s=params.mask_s,
                mask_l=params.mask_l, s_cap=s_cap, l_cap=l_cap,
                cut_cap=cut_cap, fused=True)
    for pallas in (False, True):
        fn = jax.jit(functools.partial(scan_digest_batch, classes=classes,
                                       caps=caps, pallas_digest=pallas,
                                       **base))
        dt = dev_time(fn, buf, nv, n=10)
        print(f"scan_digest_batch pallas={pallas}: {dt*1e3:.1f} ms "
              f"= {256/dt:.0f} MiB/s", flush=True)


if __name__ == "__main__":
    main()
