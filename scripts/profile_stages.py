"""Stage-isolated scan timings.

Methodology for the axon relay (see memory/PERF.md): the relay caches
identical dispatches and block_until_ready does not reliably fence, so
(a) every rep gets a *fresh* input via a cheap jitted update of one
resident buffer, and (b) each stage is reported as [update+stage] -
[update+nop] so the copy and dispatch overheads cancel.

Round-4 finding this measures: u8 elementwise throughput is ~6 GB/s on
this chip (1D T(1024) layout, one byte per 32-bit lane), so the scan
must run on u32 *words* (4 bytes/lane).  u8->u32 bitcast must go through
(..., 128, 4) shapes — a (M, 4) trailing axis pads 32x in HBM.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops.cdc_tpu import _HALO, _gear_values, _pack_bits
from backuwup_tpu.ops.gear import CDCParams, GEAR_SEED32

SEG_MIB = int(os.environ.get("PROF_SEGMENT_MIB", "128"))
REPS = int(os.environ.get("PROF_REPS", "5"))
N = SEG_MIB << 20
NW = N // 4
params = CDCParams()
ms, ml = jnp.uint32(params.mask_s), jnp.uint32(params.mask_l)


@jax.jit
def fresh_u8(buf, i):
    return buf.at[i].add(jnp.uint8(1))


@jax.jit
def fresh_u32(buf, i):
    return buf.at[i].add(jnp.uint32(1))


def run(fn, base, fresh):
    b = fresh(base, jnp.int32(0))
    out = fn(b)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    t0 = time.time()
    for r in range(REPS):
        b = fresh(base, jnp.int32(r + 1))
        out = fn(b)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    jax.block_until_ready(out)
    return (time.time() - t0) / REPS


def report(label, fn, base, fresh, nop_dt):
    dt = run(fn, base, fresh) - nop_dt
    mibs = SEG_MIB / dt if dt > 1e-9 else float("inf")
    print(f"{label:52s} {dt*1e3:9.1f} ms ({mibs:8.1f} MiB/s)", flush=True)


@jax.jit
def nop_u8(ext):
    return jnp.sum(ext[:1024].astype(jnp.uint32))


@jax.jit
def nop_u32(w):
    return jnp.sum(w[:1024])


@jax.jit
def u8_sum(ext):
    return jnp.sum(ext.astype(jnp.uint32))


@jax.jit
def u32_sum(w):
    return jnp.sum(w)


@jax.jit
def u8_to_words_sum(ext):
    w = jax.lax.bitcast_convert_type(
        ext.reshape(-1, 128, 4), jnp.uint32).reshape(-1)
    return jnp.sum(w)


def _gear_fmix(b32):
    h = b32 + jnp.uint32(GEAR_SEED32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _planes(w):
    """u32 words -> four u32 gear-value planes (plane j: positions 4m+j)."""
    return [_gear_fmix((w >> jnp.uint32(8 * j)) & jnp.uint32(0xFF))
            for j in range(4)]


def _wshift(a, q):
    if q == 0:
        return a
    return jnp.concatenate([jnp.zeros(q, dtype=a.dtype), a[:-q]])


def _ladder_planes(planes):
    for t in range(5):
        s = 1 << t
        new = []
        for p in range(4):
            src_p = (p - s) % 4
            q = (s - p + src_p) // 4
            new.append(planes[p] + (_wshift(planes[src_p], q)
                                    << jnp.uint32(s)))
        planes = new
    return planes


@jax.jit
def plane_gear_sum(w):
    return sum(jnp.sum(p) for p in _planes(w))


@jax.jit
def plane_ladder_sum(w):
    return sum(jnp.sum(p) for p in _ladder_planes(_planes(w)))


@jax.jit
def plane_words_sum(w):
    pl = _ladder_planes(_planes(w))
    acc_l = None
    acc_s = None
    for p in range(4):
        cl = ((pl[p] & ml) == 0)
        cs = cl & ((pl[p] & ms) == 0)
        shifts = (jnp.arange(8, dtype=jnp.uint32) * 4 + p)[None, :]
        wl = jnp.sum(cl.reshape(-1, 8).astype(jnp.uint32) << shifts, axis=1,
                     dtype=jnp.uint32)
        ws = jnp.sum(cs.reshape(-1, 8).astype(jnp.uint32) << shifts, axis=1,
                     dtype=jnp.uint32)
        acc_l = wl if acc_l is None else acc_l | wl
        acc_s = ws if acc_s is None else acc_s | ws
    return jnp.sum(acc_l), jnp.sum(acc_s)


@jax.jit
def plane_words_nonzero(w):
    """Full front end on words: gear, ladder, candidates, pack, two-level
    compaction (the production scan's output structure)."""
    pl = _ladder_planes(_planes(w))
    acc_l = None
    acc_s = None
    for p in range(4):
        cl = ((pl[p] & ml) == 0)
        cs = cl & ((pl[p] & ms) == 0)
        shifts = (jnp.arange(8, dtype=jnp.uint32) * 4 + p)[None, :]
        wl = jnp.sum(cl.reshape(-1, 8).astype(jnp.uint32) << shifts, axis=1,
                     dtype=jnp.uint32)
        ws = jnp.sum(cs.reshape(-1, 8).astype(jnp.uint32) << shifts, axis=1,
                     dtype=jnp.uint32)
        acc_l = wl if acc_l is None else acc_l | wl
        acc_s = ws if acc_s is None else acc_s | ws
    nz = acc_l != 0
    (widx,) = jnp.nonzero(nz, size=8192, fill_value=-1)
    safe = jnp.clip(widx, 0, acc_l.shape[0] - 1)
    return widx, acc_l[safe], acc_s[safe], jnp.sum(nz.astype(jnp.int32))


print(f"devices: {jax.devices()}  segment={SEG_MIB} MiB  reps={REPS}",
      flush=True)
key = jax.random.PRNGKey(7)
base_u8 = jax.random.randint(key, (N,), 0, 256, dtype=jnp.uint8)
base_u32 = jax.lax.bitcast_convert_type(
    base_u8.reshape(-1, 128, 4), jnp.uint32).reshape(-1)
jax.block_until_ready((base_u8, base_u32))

nop8 = run(nop_u8, base_u8, fresh_u8)
print(f"{'u8 update+nop (calibration)':52s} {nop8*1e3:9.1f} ms", flush=True)
nop32 = run(nop_u32, base_u32, fresh_u32)
print(f"{'u32 update+nop (calibration)':52s} {nop32*1e3:9.1f} ms", flush=True)
report("u8 sum", u8_sum, base_u8, fresh_u8, nop8)
# NOTE: u8->u32 device bitcast at 128 MiB is uncompilable: XLA lowers it
# as convert+combine with a (..., 4)-shaped u32 temp padded 32x -> OOM.
# Words must be uploaded/synthesized as u32 from the start.
report("u32 word sum", u32_sum, base_u32, fresh_u32, nop32)
report("WORDS gear x4 planes + sum", plane_gear_sum, base_u32, fresh_u32,
       nop32)
report("WORDS gear + ladder + sum", plane_ladder_sum, base_u32, fresh_u32,
       nop32)
report("WORDS gear + ladder + packed words + sum", plane_words_sum,
       base_u32, fresh_u32, nop32)
report("WORDS full front end (with nonzero)", plane_words_nonzero,
       base_u32, fresh_u32, nop32)


# --- packing + compaction variants ---------------------------------------

def _pack_planes_reshape(cls):
    """Variant A: (M, 8) reshape + weighted sum per plane (current)."""
    acc = None
    for p, cl in enumerate(cls):
        shifts = (jnp.arange(8, dtype=jnp.uint32) * 4 + p)[None, :]
        w = jnp.sum(cl.reshape(-1, 8) << shifts, axis=1, dtype=jnp.uint32)
        acc = w if acc is None else acc | w
    return acc


def _pack_planes_doubling(cls):
    """Variant B: log-doubling pairwise combine via strided slices.
    Bit mapping: plane p -> bits [8p..8p+7], position j within group in
    bit-reversal-ish order fixed by the doubling; any fixed per-word
    permutation is decodable."""
    acc = None
    for p, cl in enumerate(cls):
        a = cl
        sh = 1
        for _ in range(3):  # 8 -> 1 entries
            a = a[0::2] | (a[1::2] << jnp.uint32(sh))
            sh *= 2
        acc = (a << jnp.uint32(8 * p)) if acc is None else \
            acc | (a << jnp.uint32(8 * p))
    return acc


@jax.jit
def pack_reshape_sum(w):
    pl = _ladder_planes(_planes(w))
    cls = [((p & ml) == 0).astype(jnp.uint32) for p in pl]
    return jnp.sum(_pack_planes_reshape(cls))


@jax.jit
def pack_doubling_sum(w):
    pl = _ladder_planes(_planes(w))
    cls = [((p & ml) == 0).astype(jnp.uint32) for p in pl]
    return jnp.sum(_pack_planes_doubling(cls))


@jax.jit
def nonzero_only(w):
    """Word-level nonzero cost on N/32 words (no gather)."""
    words = (w[: NW // 8] * jnp.uint32(2654435761)) > jnp.uint32(0xFFFFF000)
    (widx,) = jnp.nonzero(words, size=8192, fill_value=-1)
    return widx


@jax.jit
def nonzero_gather(w):
    words = w[: NW // 8] * jnp.uint32(2654435761)
    nz = words > jnp.uint32(0xFFFFF000)
    (widx,) = jnp.nonzero(nz, size=8192, fill_value=-1)
    safe = jnp.clip(widx, 0, words.shape[0] - 1)
    return widx, words[safe]


@jax.jit
def full_doubling_front(w):
    """Doubling pack + 3-level compaction (OR-superwords before nonzero)."""
    pl = _ladder_planes(_planes(w))
    cls = [((p & ml) == 0).astype(jnp.uint32) for p in pl]
    css = [(c & (((p & ms) == 0).astype(jnp.uint32))) for c, p in
           zip(cls, pl)]
    wl = _pack_planes_doubling(cls)
    ws = _pack_planes_doubling(css)
    sup = wl[0::4] | wl[1::4] | wl[2::4] | wl[3::4]
    nz = sup != 0
    (sidx,) = jnp.nonzero(nz, size=2048, fill_value=-1)
    safe = jnp.clip(sidx, 0, sup.shape[0] - 1)
    # expand each nonzero superword back to its 4 words
    g = (safe[:, None] * 4 + jnp.arange(4, dtype=sidx.dtype)[None, :]
         ).reshape(-1)
    return sidx, wl[g], ws[g], jnp.sum(nz.astype(jnp.int32))


report("pack variant A: (M,8) reshape", pack_reshape_sum, base_u32,
       fresh_u32, nop32)
report("pack variant B: strided doubling", pack_doubling_sum, base_u32,
       fresh_u32, nop32)
report("nonzero only (N/32 words)", nonzero_only, base_u32, fresh_u32,
       nop32)
report("nonzero + 8k gather", nonzero_gather, base_u32, fresh_u32, nop32)
report("FULL: doubling pack + 3-level compact", full_doubling_front,
       base_u32, fresh_u32, nop32)


def _cand_u32(h, bits):
    """Indicator((h & top-bits-mask) == 0) as pure u32 arithmetic — no
    bool arrays (i1 lives in u8 lanes, the slow path)."""
    return jnp.minimum(h >> jnp.uint32(32 - bits), jnp.uint32(1)) \
        ^ jnp.uint32(1)


@jax.jit
def full_u32_front(w):
    """Word-native front end with pure-u32 indicators end to end."""
    pl = _ladder_planes(_planes(w))
    acc_l = None
    acc_s = None
    for p in range(4):
        cl = _cand_u32(pl[p], params.mask_l_bits)
        cs = cl & _cand_u32(pl[p], params.mask_s_bits)
        shifts = (jnp.arange(8, dtype=jnp.uint32) * 4 + p)[None, :]
        wl = jnp.sum(cl.reshape(-1, 8) << shifts, axis=1, dtype=jnp.uint32)
        ws = jnp.sum(cs.reshape(-1, 8) << shifts, axis=1, dtype=jnp.uint32)
        acc_l = wl if acc_l is None else acc_l | wl
        acc_s = ws if acc_s is None else acc_s | ws
    nz = acc_l != 0
    (widx,) = jnp.nonzero(nz, size=8192, fill_value=-1)
    safe = jnp.clip(widx, 0, acc_l.shape[0] - 1)
    return widx, acc_l[safe], acc_s[safe], jnp.sum(nz.astype(jnp.int32))


report("FULL u32-indicator front end", full_u32_front, base_u32,
       fresh_u32, nop32)
report("FULL u32-indicator front end (rep2)", full_u32_front, base_u32,
       fresh_u32, nop32)
