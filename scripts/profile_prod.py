"""Calibrated timings of the CURRENT production pipeline stages."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from backuwup_tpu.utils.jaxcache import enable_compilation_cache
enable_compilation_cache()
import jax, jax.numpy as jnp, numpy as np
from backuwup_tpu.ops.cdc_tpu import _HALO
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline

SEG_MIB = int(os.environ.get("PROF_SEGMENT_MIB", "128"))
REPS = 5
N = SEG_MIB << 20
row = _HALO + N
params = CDCParams()
pipe = DevicePipeline(params)
nv = np.full(1, N, dtype=np.int32)

@jax.jit
def fresh(buf, i):
    return buf.at[0, i].add(jnp.uint8(1))

key = jax.random.PRNGKey(3)
base = jax.random.randint(key, (1, row), 0, 256, dtype=jnp.uint8)
jax.block_until_ready(base)

def timeit(label, fn):
    out = fn(fresh(base, jnp.int32(0)))  # warm
    t0 = time.time()
    for r in range(REPS):
        out = fn(fresh(base, jnp.int32(r + 1)))
    leaves = jax.tree_util.tree_leaves(out)
    if leaves and hasattr(leaves[0], 'block_until_ready'):
        np.asarray(leaves[0]).ravel()[:1]
        jax.block_until_ready(out)
    dt = (time.time() - t0) / REPS
    print(f"{label:46s} {dt*1e3:9.1f} ms ({SEG_MIB/dt:8.1f} MiB/s)", flush=True)
    return dt

nop_dt = timeit("update+nop (calibration)",
                lambda b: jnp.sum(b[0, :128].astype(jnp.uint32)))
timeit("production scan_select dispatch+download",
       lambda b: np.asarray(pipe.scan_select_dispatch(b, nv)))
def full(b):
    return pipe.manifest_resident_batch(b, nv, strict_overflow=True)
out = full(fresh(base, jnp.int32(99)))
t0 = time.time()
for r in range(REPS):
    out = full(fresh(base, jnp.int32(100 + r)))
dt = (time.time() - t0) / REPS
print(f"{'production manifest_resident_batch (e2e)':46s} {dt*1e3:9.1f} ms "
      f"({SEG_MIB/dt:8.1f} MiB/s)", flush=True)
print(f"(calibration to subtract: {nop_dt*1e3:.1f} ms)", flush=True)
