"""Stage split after hierarchical compaction (honest chained timing)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from backuwup_tpu.obs.profile import dev_time


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import functools

    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops.cdc_tpu import _HALO, scan_select_batch
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.manifest_device import (class_caps,
                                                  class_leaf_sizes,
                                                  scan_digest_batch)
    from backuwup_tpu.ops.blake3_tpu import pallas_digest_available
    from backuwup_tpu.ops.pipeline import DevicePipeline
    from backuwup_tpu.ops.scan_fused import fused_candidate_words

    pdig = pallas_digest_available()
    print("pallas digest:", pdig)

    P = 256 << 20
    key = jax.random.PRNGKey(0)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (P,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]
                               ).reshape(1, _HALO + P)

    buf = synth(key)
    nv = jnp.asarray(np.full(1, P, dtype=np.int32))

    for tag, params in (("1MiB", CDCParams()),
                        ("64KiB", CDCParams.from_desired(64 << 10))):
        pipe = DevicePipeline(params)
        s_cap, l_cap, cut_cap = pipe._caps(P)
        fw = jax.jit(functools.partial(
            fused_candidate_words, mask_s=params.mask_s,
            mask_l=params.mask_l))
        t_scan = dev_time(fw, buf, nv)
        print(f"[{tag}] scan={t_scan*1e3:.1f}ms", flush=True)
        fn = jax.jit(functools.partial(
            scan_select_batch, min_size=params.min_size,
            desired_size=params.desired_size, max_size=params.max_size,
            mask_s=params.mask_s, mask_l=params.mask_l,
            s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=True))
        t_ss = dev_time(fn, buf, nv)
        print(f"[{tag}] scan+select={t_ss*1e3:.1f}ms "
              f"(compact+select={1e3*(t_ss-t_scan):.1f})", flush=True)
        classes = class_leaf_sizes(params)
        caps = class_caps(params, P, 1)
        full = jax.jit(functools.partial(
            scan_digest_batch, min_size=params.min_size,
            desired_size=params.desired_size, max_size=params.max_size,
            mask_s=params.mask_s, mask_l=params.mask_l,
            s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=True,
            classes=classes, caps=caps, pallas_digest=pdig))
        t_full = dev_time(full, buf, nv, n=10)
        print(f"[{tag}] scan={t_scan*1e3:.1f}ms  "
              f"scan+select={t_ss*1e3:.1f}ms "
              f"(compact+select={1e3*(t_ss-t_scan):.1f})  "
              f"full manifest={t_full*1e3:.1f}ms "
              f"(digest~={1e3*(t_full-t_ss):.1f})  "
              f"=> {256/t_full:.0f} MiB/s device-side")


if __name__ == "__main__":
    main()
