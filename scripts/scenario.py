#!/usr/bin/env python3
"""Run a composed chaos scenario and print its scorecard.

Examples::

    python scripts/scenario.py --list
    python scripts/scenario.py --scenario composed
    python scripts/scenario.py --scenario full --seed 7 \\
        --out card.json --samples samples.jsonl

Exit status is 0 when every scorecard assertion passed, 1 otherwise —
usable directly as a CI gate.  See docs/scenarios.md.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from backuwup_tpu.obs import journal as obs_journal  # noqa: E402
from backuwup_tpu.obs import timeline as obs_timeline  # noqa: E402
from backuwup_tpu.scenario import (builtin_scenarios, builtin_swarms,  # noqa: E402
                                   run_scenario, run_swarm)
from backuwup_tpu.sim import builtin_sims, card_json, run_sim  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="composed",
                    help="scenario or swarm name (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list built-in scenarios and exit")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario's seed")
    ap.add_argument("--clients", type=int, default=None,
                    help="population override for sim scenarios")
    ap.add_argument("--out", default=None,
                    help="write the scorecard JSON here")
    ap.add_argument("--samples", default=None,
                    help="write the raw invariant samples (JSONL) here")
    ap.add_argument("--workdir", default=None,
                    help="run here instead of a throwaway temp dir")
    ap.add_argument("--profile", default=None, metavar="OUT",
                    help="journal the run and write a Perfetto-loadable"
                         " timeline JSON of the composed run here")
    args = ap.parse_args()

    scenarios = builtin_scenarios()
    swarms = builtin_swarms()
    sims = builtin_sims()
    if args.list:
        for name, spec in {**scenarios, **swarms}.items():
            kind = "swarm" if name in swarms else "chaos"
            print(f"{name:12s} {kind:5s} seed={spec.seed:<4d} "
                  f"phases={'/'.join(p.label for p in spec.phases)}")
        for name, desc in sims.items():
            print(f"{name:12s} sim   {desc}")
        return 0
    if args.scenario in sims:
        # virtual-clock plane: no workdir, no journal — one process, one
        # event loop, wall-free scorecard (docs/simulation.md)
        card, stats = run_sim(args.scenario, clients=args.clients,
                              seed=args.seed)
        for gate in card["gates"]:
            mark = "PASS" if gate["passed"] else "FAIL"
            print(f"[{mark}] {gate['name']}: {gate['detail']}")
        print(f"simulated {card['sim_seconds'] / 86_400:.1f}d of"
              f" {card['clients']} clients in {stats['wall_s']}s wall"
              f" ({stats['events_per_s']} ev/s,"
              f" {stats['time_compression']}x compression)")
        if args.out:
            Path(args.out).write_text(card_json(card) + "\n")
            print(f"scorecard written to {args.out}")
        return 0 if card["passed"] else 1
    spec = scenarios.get(args.scenario) or swarms.get(args.scenario)
    if spec is None:
        print(f"unknown scenario {args.scenario!r}; try --list",
              file=sys.stderr)
        return 2
    if args.seed is not None:
        spec = dataclasses.replace(spec, seed=args.seed)
    is_swarm = args.scenario in swarms

    async def run_spec(workdir: Path):
        if is_swarm:
            card, summary = await run_swarm(spec, workdir)
            print(" ".join(f"{k}={v}" for k, v in summary.items()))
            return card
        return await run_scenario(spec, workdir)

    def run_in(workdir: Path):
        if not args.profile:
            return asyncio.run(run_spec(workdir))
        # every client in the harness shares this process, so one
        # installed journal captures all sides' spans; the timeline
        # export then shows pack/seal/send/store overlap across peers,
        # correlated by the trace ids on the wire envelopes
        jr = obs_journal.install(
            obs_journal.Journal(workdir / "scenario_journal.jsonl"))
        try:
            return asyncio.run(run_spec(workdir))
        finally:
            obs_journal.uninstall()
            doc = obs_timeline.export_timeline(
                jr.files(), args.profile, labels=[spec.name])
            print(f"{len(doc['traceEvents'])} trace events -> "
                  f"{args.profile} (load in ui.perfetto.dev)")

    if args.workdir:
        workdir = Path(args.workdir)
        workdir.mkdir(parents=True, exist_ok=True)
        card = run_in(workdir)
    else:
        with tempfile.TemporaryDirectory(prefix="bkw_scenario_") as td:
            card = run_in(Path(td))

    print(card.render())
    if args.out:
        card.write_json(args.out)
        print(f"scorecard written to {args.out}")
    if args.samples:
        card.write_samples_jsonl(args.samples)
        print(f"samples written to {args.samples}")
    return 0 if card.passed else 1


if __name__ == "__main__":
    sys.exit(main())
