#!/bin/sh
# Poll the axon TPU until a trivial op completes; log recovery time, then
# immediately recapture a benchmark run so the recovery window is measured
# (BENCH_attempt_<stamp>.json next to bench.py unless BENCH_OUT_DIR is set).
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
BENCH_OUT_DIR="${BENCH_OUT_DIR:-$REPO_DIR}"
while true; do
    if timeout 25 python -c "
import jax, numpy as np, jax.numpy as jnp
print('tpu ok', np.asarray(jnp.ones(8).sum()))" >/tmp/tpu_watch_probe.log 2>&1; then
        echo "TPU RECOVERED at $(date)" >> /tmp/tpu_watch.log
        # pre-capture static gate: a tree that fails bkwlint produces
        # captures nobody should trust (blocked loops skew every
        # latency number) — log the findings and refuse to capture
        if ! python "$REPO_DIR/scripts/bkwlint.py" \
                >> /tmp/tpu_watch.log 2>&1; then
            echo "bkwlint FAILED — captures skipped at $(date)" \
                >> /tmp/tpu_watch.log
            exit 1
        fi
        echo "bkwlint clean at $(date)" >> /tmp/tpu_watch.log
        stamp="$(date -u +%Y%m%dT%H%M%SZ)"
        out="$BENCH_OUT_DIR/BENCH_attempt_${stamp}.json"
        if timeout "${BENCH_TIMEOUT_S:-1800}" \
                python "$REPO_DIR/bench.py" > "$out" 2>>/tmp/tpu_watch.log; then
            echo "bench recaptured to $out at $(date)" >> /tmp/tpu_watch.log
        else
            echo "bench recapture FAILED (see $out) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated erasure recapture: config #7 alone on a short window,
        # so the RS encode/decode number exists even when the full suite
        # above timed out partway
        ers="$BENCH_OUT_DIR/BENCH_erasure_${stamp}.json"
        if timeout "${BENCH_ERASURE_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=7_erasure BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$ers" 2>>/tmp/tpu_watch.log; then
            echo "erasure bench recaptured to $ers at $(date)" >> /tmp/tpu_watch.log
        else
            echo "erasure bench recapture FAILED (see $ers) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated transfer recapture: config #8 alone (host-only
        # loopback p2p, serial-vs-concurrent ratio) — the overlap number
        # survives even when the device suite above timed out partway
        trf="$BENCH_OUT_DIR/BENCH_transfer_${stamp}.json"
        if timeout "${BENCH_TRANSFER_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=8_transfer BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$trf" 2>>/tmp/tpu_watch.log; then
            echo "transfer bench recaptured to $trf at $(date)" >> /tmp/tpu_watch.log
        else
            echo "transfer bench recapture FAILED (see $trf) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated pipeline-profile recapture: headline device run with
        # configs off, 1 GiB — the embedded pipeline_report (per-stage
        # dispatch counts, padding efficiency; obs/profile.py) is the
        # before/after for the round-5 digest-dispatch merge (PERF.md)
        # even when the full suite above timed out partway
        prf="$BENCH_OUT_DIR/BENCH_pipeline_${stamp}.json"
        if timeout "${BENCH_PIPELINE_TIMEOUT_S:-600}" \
                env BENCH_CONFIGS=0 BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$prf" 2>>/tmp/tpu_watch.log; then
            echo "pipeline bench recaptured to $prf at $(date)" >> /tmp/tpu_watch.log
        else
            echo "pipeline bench recapture FAILED (see $prf) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated scenario recapture: config #9 alone (host-only
        # composed chaos scenario + scorecard) — the durability gate
        # verdict survives even when the device suite timed out partway
        scn="$BENCH_OUT_DIR/BENCH_scenario_${stamp}.json"
        if timeout "${BENCH_SCENARIO_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=9_scenario BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$scn" 2>>/tmp/tpu_watch.log; then
            echo "scenario bench recaptured to $scn at $(date)" >> /tmp/tpu_watch.log
        else
            echo "scenario bench recapture FAILED (see $scn) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated wan-resume recapture: config #10 alone (host-only
        # loopback p2p, resume-vs-restart bytes-on-wire ratio across two
        # injected mid-transfer cuts) — the resume payoff number
        # survives even when the device suite timed out partway
        wan="$BENCH_OUT_DIR/BENCH_wan_${stamp}.json"
        if timeout "${BENCH_WAN_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=10_wan BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$wan" 2>>/tmp/tpu_watch.log; then
            echo "wan bench recaptured to $wan at $(date)" >> /tmp/tpu_watch.log
        else
            echo "wan bench recapture FAILED (see $wan) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated crash-matrix recapture: config #11 alone (host-only
        # crash scenario: armed commit-seam crashes, restart + recovery
        # sweep per seam) — the recovery-cost numbers and the
        # recovery_clean gate verdict survive even when the device suite
        # timed out partway
        crs="$BENCH_OUT_DIR/BENCH_crash_${stamp}.json"
        if timeout "${BENCH_CRASH_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=11_crash BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$crs" 2>>/tmp/tpu_watch.log; then
            echo "crash bench recaptured to $crs at $(date)" >> /tmp/tpu_watch.log
        else
            echo "crash bench recapture FAILED (see $crs) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated swarm recapture: config #12 alone (host-only
        # coordination plane: sharded-vs-single-lock matchmaking speedup
        # legs + the HTTP swarm's p99/stall/off-loop-commit evidence) —
        # the scale-out gate verdict survives even when the device suite
        # timed out partway
        swm="$BENCH_OUT_DIR/BENCH_swarm_${stamp}.json"
        if timeout "${BENCH_SWARM_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=12_swarm BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$swm" 2>>/tmp/tpu_watch.log; then
            echo "swarm bench recaptured to $swm at $(date)" >> /tmp/tpu_watch.log
        else
            echo "swarm bench recapture FAILED (see $swm) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated restore recapture: config #13 alone (host-only
        # loopback p2p, serial RESTORE_ALL vs multi-source k-of-n pulls
        # under one slow and one dark holder) — the restore_speedup and
        # restore_bytes_ratio numbers survive even when the device suite
        # timed out partway
        rst="$BENCH_OUT_DIR/BENCH_restore_${stamp}.json"
        if timeout "${BENCH_RESTORE_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=13_restore BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$rst" 2>>/tmp/tpu_watch.log; then
            echo "restore bench recaptured to $rst at $(date)" >> /tmp/tpu_watch.log
        else
            echo "restore bench recapture FAILED (see $rst) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated multichip recapture: config #14 alone (mesh manifest
        # plane: matched-work 1-dev vs N-dev shard_map manifest with the
        # manifest->dedup device handoff; parity/even-split/handoff gates
        # always on, wall-clock speedup gate armed on real chips) — the
        # multichip_speedup number survives even when the device suite
        # timed out partway
        mcp="$BENCH_OUT_DIR/BENCH_multichip_${stamp}.json"
        if timeout "${BENCH_MULTICHIP_TIMEOUT_S:-900}" \
                env BENCH_ONLY_CONFIG=14_multichip BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$mcp" 2>>/tmp/tpu_watch.log; then
            echo "multichip bench recaptured to $mcp at $(date)" >> /tmp/tpu_watch.log
        else
            echo "multichip bench recapture FAILED (see $mcp) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated gc recapture: config #15 alone (host-only snapshot
        # lifecycle scenario: retention prune + mark-and-sweep GC with
        # one armed commit-seam crash + resume, then a byte-identical
        # restore) — the gc_reclaim_ratio number and the zero-violation
        # verdict survive even when the device suite timed out partway
        gcb="$BENCH_OUT_DIR/BENCH_gc_${stamp}.json"
        if timeout "${BENCH_GC_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=15_gc BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$gcb" 2>>/tmp/tpu_watch.log; then
            echo "gc bench recaptured to $gcb at $(date)" >> /tmp/tpu_watch.log
        else
            echo "gc bench recapture FAILED (see $gcb) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated federation recapture: config #16 alone (host-only
        # multi-process coordination plane: 1/2/4-node scaling legs over
        # real /fed/steal HTTP plus the kill/revive churn swarm) — the
        # federation_speedup_* numbers and the zero-lost verdict survive
        # even when the device suite timed out partway
        fed="$BENCH_OUT_DIR/BENCH_federation_${stamp}.json"
        if timeout "${BENCH_FEDERATION_TIMEOUT_S:-900}" \
                env BENCH_ONLY_CONFIG=16_federation BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$fed" 2>>/tmp/tpu_watch.log; then
            echo "federation bench recaptured to $fed at $(date)" >> /tmp/tpu_watch.log
        else
            echo "federation bench recapture FAILED (see $fed) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated tiered-dedup recapture: config #17 alone (the
        # HBM-capped hot table over the host LSM cold tier: oracle
        # parity + budget + >95% device hit rate always on; the
        # skewed-vs-uniform wall gate arms on real chips where HBM
        # locality is measurable) — the tiered_hit_rate number
        # survives even when the device suite timed out partway
        trd="$BENCH_OUT_DIR/BENCH_tiered_${stamp}.json"
        if timeout "${BENCH_TIERED_TIMEOUT_S:-900}" \
                env BENCH_ONLY_CONFIG=17_tiered BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$trd" 2>>/tmp/tpu_watch.log; then
            echo "tiered bench recaptured to $trd at $(date)" >> /tmp/tpu_watch.log
        else
            echo "tiered bench recapture FAILED (see $trd) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated replication recapture: config #18 alone (host-only
        # coordination plane: the 3-node permakill swarm over per-node
        # replicated stores plus the shared-store baseline) — the
        # replication_lost_rows=0 verdict and repl_promote_s survive
        # even when the device suite timed out partway
        rpl="$BENCH_OUT_DIR/BENCH_replication_${stamp}.json"
        if timeout "${BENCH_REPLICATION_TIMEOUT_S:-900}" \
                env BENCH_ONLY_CONFIG=18_replication BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$rpl" 2>>/tmp/tpu_watch.log; then
            echo "replication bench recaptured to $rpl at $(date)" >> /tmp/tpu_watch.log
        else
            echo "replication bench recapture FAILED (see $rpl) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated sim recapture: config #19 alone (host-only virtual
        # clock: the 1e5-client simulated-week builtin plus the
        # determinism double-run) — events/s and the time-compression
        # ratio survive even when the device suite timed out partway
        simj="$BENCH_OUT_DIR/BENCH_sim_${stamp}.json"
        if timeout "${BENCH_SIM_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=19_sim BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$simj" 2>>/tmp/tpu_watch.log; then
            echo "sim bench recaptured to $simj at $(date)" >> /tmp/tpu_watch.log
        else
            echo "sim bench recapture FAILED (see $simj) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated dataflow recapture: config #20 alone (host-only
        # loopback p2p: phased-vs-stream legs over one corpus) — the
        # dataflow_speedup / overlap_efficiency verdict survives even
        # when the device suite timed out partway
        dfl="$BENCH_OUT_DIR/BENCH_dataflow_${stamp}.json"
        if timeout "${BENCH_DATAFLOW_TIMEOUT_S:-900}" \
                env BENCH_ONLY_CONFIG=20_dataflow BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$dfl" 2>>/tmp/tpu_watch.log; then
            echo "dataflow bench recaptured to $dfl at $(date)" >> /tmp/tpu_watch.log
        else
            echo "dataflow bench recapture FAILED (see $dfl) at $(date)" >> /tmp/tpu_watch.log
        fi
        # dedicated slo recapture: config #21 alone (host-only live SLO
        # plane: the diagnosis scenario's breach-detection latency and
        # explainer precision, plus the sim burn-rate determinism
        # double-run) — the detection/precision numbers survive even
        # when the device suite timed out partway
        slo="$BENCH_OUT_DIR/BENCH_slo_${stamp}.json"
        if timeout "${BENCH_SLO_TIMEOUT_S:-600}" \
                env BENCH_ONLY_CONFIG=21_slo BENCH_GIB=1 \
                python "$REPO_DIR/bench.py" > "$slo" 2>>/tmp/tpu_watch.log; then
            echo "slo bench recaptured to $slo at $(date)" >> /tmp/tpu_watch.log
        else
            echo "slo bench recapture FAILED (see $slo) at $(date)" >> /tmp/tpu_watch.log
        fi
        # trend check over the whole capture history (the one just
        # written included): per-config deltas vs the previous capture,
        # REGRESSION lines + nonzero exit when a gated metric slid —
        # the watch log learns about a slide the moment it lands
        if python "$REPO_DIR/scripts/bench_trend.py" \
                --dir "$BENCH_OUT_DIR" >> /tmp/tpu_watch.log 2>&1; then
            echo "bench trend clean at $(date)" >> /tmp/tpu_watch.log
        else
            echo "bench trend REGRESSION (see above) at $(date)" >> /tmp/tpu_watch.log
        fi
        exit 0
    fi
    echo "still down $(date)" >> /tmp/tpu_watch.log
    sleep 45
done
