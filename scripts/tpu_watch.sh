#!/bin/sh
# Poll the axon TPU until a trivial op completes; log recovery time.
while true; do
    if timeout 25 python -c "
import jax, numpy as np, jax.numpy as jnp
print('tpu ok', np.asarray(jnp.ones(8).sum()))" >/tmp/tpu_watch_probe.log 2>&1; then
        echo "TPU RECOVERED at $(date)" >> /tmp/tpu_watch.log
        exit 0
    fi
    echo "still down $(date)" >> /tmp/tpu_watch.log
    sleep 45
done
