"""Stage-by-stage profiler for the redesigned device-resident pipeline.

Times, with hard device syncs between stages, on BENCH-shaped segments:
  1. scan_select_batch (fused hash + candidate compaction + cut while_loop)
  2. packed-cuts download + host chunk assembly
  3. digest_dispatch (flat pad + meta upload + gather/digest tiles)
  4. digest download
plus sub-kernels in isolation (hash ladder alone, nonzero alone) so the
optimization attacks measured cost, not guessed cost.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import functools

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops.cdc_tpu import _HALO, _hash_ext_fast, scan_select_batch
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline

SEG_MIB = int(os.environ.get("PROF_SEGMENT_MIB", "128"))
REPS = int(os.environ.get("PROF_REPS", "3"))


def timed(label, fn, *args, reps=3, **kw):
    fn(*args, **kw)  # warm/compile
    jax.block_until_ready(jnp.zeros(1))
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args, **kw)
        jax.block_until_ready(out)
    dt = (time.time() - t0) / reps
    print(f"  {label:42s} {dt*1e3:8.1f} ms  ({SEG_MIB/dt:7.1f} MiB/s)",
          flush=True)
    return out


def main():
    params = CDCParams()
    pipe = DevicePipeline(params)
    seg_bytes = SEG_MIB << 20
    row = _HALO + seg_bytes
    print(f"devices: {jax.devices()}  segment={SEG_MIB} MiB", flush=True)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (seg_bytes,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]).reshape(1, row)

    key = jax.random.PRNGKey(7)
    nv = np.full(1, seg_bytes, dtype=np.int32)
    buf = synth(key)
    jax.block_until_ready(buf)

    # --- sub-kernels in isolation -----------------------------------------
    hash_j = jax.jit(lambda e: _hash_ext_fast(e[0]))
    timed("hash ladder only", hash_j, buf)

    p = params

    @jax.jit
    def hash_cand_nonzero(ext_b, n):
        h = _hash_ext_fast(ext_b[0])
        valid = jnp.arange(h.shape[0], dtype=jnp.int32) < n
        cand_l = ((h & jnp.uint32(p.mask_l)) == 0) & valid
        (pos_l,) = jnp.nonzero(cand_l, size=8192, fill_value=h.shape[0])
        return pos_l

    timed("hash + candidates + nonzero", hash_cand_nonzero, buf,
          jnp.int32(seg_bytes))

    s_cap, l_cap, cut_cap = pipe._caps(seg_bytes)
    print(f"  caps: s={s_cap} l={l_cap} cut={cut_cap}", flush=True)
    scan_fn = functools.partial(
        scan_select_batch, min_size=p.min_size, desired_size=p.desired_size,
        max_size=p.max_size, mask_s=p.mask_s, mask_l=p.mask_l,
        s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap)
    nv_d = jnp.asarray(nv)
    packed_d = timed("scan_select_batch (fused)", scan_fn, buf, nv_d)

    # --- full pipeline stages ---------------------------------------------
    for rep in range(REPS):
        key, sub = jax.random.split(key)
        buf = synth(sub)
        jax.block_until_ready(buf)

        t0 = time.time()
        packed_d = pipe.scan_select_dispatch(buf, nv)
        jax.block_until_ready(packed_d)
        t_scan = time.time() - t0

        t0 = time.time()
        per_row = pipe.scan_select_collect(packed_d, buf, nv, True)
        t_collect = time.time() - t0

        t0 = time.time()
        pending = pipe.digest_dispatch(buf, per_row)
        jax.block_until_ready(pending[0])
        t_dig = time.time() - t0

        t0 = time.time()
        results = pipe.digest_collect(pending, per_row)
        t_dl = time.time() - t0

        tot = t_scan + t_collect + t_dig + t_dl
        n_tiles = len(pending[1])
        print(f"rep{rep}: scan+select={t_scan*1e3:7.1f}  "
              f"collect={t_collect*1e3:6.1f}  "
              f"digest={t_dig*1e3:7.1f} ({n_tiles} tiles, "
              f"{len(per_row[0])} chunks)  dl={t_dl*1e3:6.1f}  "
              f"TOTAL={tot*1e3:7.1f} ms -> {SEG_MIB/tot:6.1f} MiB/s",
              flush=True)

    # --- pipelined driver end to end --------------------------------------
    segs = []
    for _ in range(4):
        key, sub = jax.random.split(key)
        segs.append((synth(sub), nv))
    jax.block_until_ready([b for b, _ in segs])
    list(pipe.manifest_segments(segs, strict_overflow=True))  # warm
    t0 = time.time()
    list(pipe.manifest_segments(segs, strict_overflow=True))
    dt = time.time() - t0
    print(f"pipelined 4x{SEG_MIB} MiB: {dt:.2f}s -> {4*SEG_MIB/dt:.1f} MiB/s",
          flush=True)


if __name__ == "__main__":
    main()
