"""Stage-by-stage profiler for the device-resident dedup pipeline.

Times, with hard device syncs between stages, on a BENCH-shaped segment:
  0. trivial-dispatch latency (the relay-tunnel floor)
  1. scan_words_batch dispatch + download
  2. host cut selection over the sparse words
  3. flat pad + per-bucket _gather_digest dispatches
  4. final digest download
Prints a per-stage table so the optimization attacks measured cost, not
guessed cost.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops.cdc_cpu import cuts_to_chunks, select_cuts
from backuwup_tpu.ops.cdc_tpu import _HALO, scan_words_batch, unpack_scan_words
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import CHUNK_LEN, DevicePipeline, _gather_digest, _pad_to

SEG_MIB = int(os.environ.get("PROF_SEGMENT_MIB", "128"))
REPS = int(os.environ.get("PROF_REPS", "3"))


def sync():
    jax.block_until_ready(jnp.zeros(1))


def main():
    params = CDCParams()
    pipe = DevicePipeline(params)
    seg_bytes = SEG_MIB << 20
    row = _HALO + seg_bytes
    print(f"devices: {jax.devices()}", flush=True)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (seg_bytes,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]).reshape(1, row)

    key = jax.random.PRNGKey(7)
    nv = np.full(1, seg_bytes, dtype=np.int32)
    nv_d = jnp.asarray(nv)

    # measure trivial dispatch latency
    tiny = jax.jit(lambda x: x + 1)
    tiny(jnp.zeros(8)).block_until_ready()
    t0 = time.time()
    for _ in range(10):
        tiny(jnp.zeros(8)).block_until_ready()
    disp = (time.time() - t0) / 10
    print(f"trivial dispatch+sync: {disp*1e3:.1f} ms", flush=True)

    # tiny download latency
    x = jnp.zeros(8)
    jax.block_until_ready(x)
    t0 = time.time()
    for _ in range(10):
        np.asarray(tiny(x))
    dl = (time.time() - t0) / 10
    print(f"tiny roundtrip (dispatch+download): {dl*1e3:.1f} ms", flush=True)

    # warm everything once via the production path
    key, sub = jax.random.split(key)
    buf = synth(sub)
    jax.block_until_ready(buf)
    pipe.manifest_resident_batch(buf, nv, strict_overflow=True)

    k_cap = pipe.scanner._k_cap(seg_bytes)
    print(f"k_cap={k_cap}", flush=True)

    for rep in range(REPS):
        key, sub = jax.random.split(key)
        t0 = time.time()
        buf = synth(sub)
        jax.block_until_ready(buf)
        t_synth = time.time() - t0

        # stage 1: scan dispatch (device only)
        t0 = time.time()
        packed_d = scan_words_batch(buf, nv_d, mask_s=params.mask_s,
                                    mask_l=params.mask_l, k_cap=k_cap)
        jax.block_until_ready(packed_d)
        t_scan = time.time() - t0

        # stage 1b: download of packed words
        t0 = time.time()
        packed = np.asarray(packed_d)
        t_dl1 = time.time() - t0

        # stage 2: host cut selection
        t0 = time.time()
        from backuwup_tpu.ops.cdc_tpu import _decode_words
        nz, widx, wl, ws = unpack_scan_words(packed[0], k_cap)
        assert nz <= k_cap
        pos_l, is_s = _decode_words(widx, wl, ws, k_cap, 0)
        chunks = cuts_to_chunks(select_cuts(pos_l[is_s], pos_l, seg_bytes, params))
        t_cut = time.time() - t0

        # stage 3: flat pad
        t0 = time.time()
        span_max = pipe.l_bucket * CHUNK_LEN
        flat = jnp.pad(buf.reshape(-1), (0, span_max))
        jax.block_until_ready(flat)
        t_pad = time.time() - t0

        # stage 3b: bucket + gather_digest dispatches
        t0 = time.time()
        groups = {}
        for ci, (off, ln) in enumerate(chunks):
            groups.setdefault(pipe._chunk_bucket(ln), []).append((_HALO + off, ln, 0, ci))
        buckets = []
        offs_parts, lens_parts = [], []
        start = 0
        for Lb, items in sorted(groups.items()):
            for s0 in range(0, len(items), pipe.b_bucket):
                part = items[s0:s0 + pipe.b_bucket]
                Bb = 8
                while Bb < len(part):
                    Bb *= 2
                o = np.zeros(Bb, dtype=np.int32)
                ln_arr = np.zeros(Bb, dtype=np.int32)
                for q, (off, ln, _r, _ci) in enumerate(part):
                    o[q] = off
                    ln_arr[q] = ln
                offs_parts.append(o)
                lens_parts.append(ln_arr)
                buckets.append((start, Bb, Lb, None))
                start += Bb
        starts = np.array([st for st, _b, _l, _t in buckets], dtype=np.int32)
        total = 256
        while total < max(start, len(starts)):
            total *= 2
        meta = jnp.asarray(np.stack([
            _pad_to(np.concatenate(offs_parts), total),
            _pad_to(np.concatenate(lens_parts), total),
            _pad_to(starts, total)]))
        acc = jnp.zeros((total, 8), dtype=jnp.uint32)
        jax.block_until_ready(meta)
        t_meta = time.time() - t0

        t0 = time.time()
        for i, (_st, Bb, Lb, _tags) in enumerate(buckets):
            acc = _gather_digest(flat, meta, meta[2, i], acc, B=Bb, L=Lb)
        jax.block_until_ready(acc)
        t_dig = time.time() - t0

        t0 = time.time()
        allcv = np.asarray(acc)
        t_dl2 = time.time() - t0

        tot = t_scan + t_dl1 + t_cut + t_pad + t_meta + t_dig + t_dl2
        print(f"rep{rep}: synth={t_synth*1e3:7.1f}  scan={t_scan*1e3:7.1f}  "
              f"dl1={t_dl1*1e3:6.1f}  cut={t_cut*1e3:6.1f}  pad={t_pad*1e3:6.1f}  "
              f"meta={t_meta*1e3:6.1f}  digest={t_dig*1e3:7.1f} ({len(buckets)} buckets, "
              f"{len(chunks)} chunks)  dl2={t_dl2*1e3:6.1f}  "
              f"TOTAL={tot*1e3:7.1f} ms -> {SEG_MIB/tot:6.1f} MiB/s", flush=True)

    print("\nper-(B,L) single-dispatch timings:", flush=True)
    for (st, Bb, Lb, _t) in buckets[:6]:
        t0 = time.time()
        acc = _gather_digest(flat, meta, meta[2, 0], acc, B=Bb, L=Lb)
        jax.block_until_ready(acc)
        t1 = time.time() - t0
        print(f"  B={Bb:4d} L={Lb:5d} ({Bb*Lb/1024:7.1f} MiB padded): {t1*1e3:7.1f} ms "
              f"-> {Bb*Lb/1024/t1:7.1f} MiB/s", flush=True)


if __name__ == "__main__":
    main()
