#!/usr/bin/env python3
"""bkwlint — AST invariant linter for backuwup_tpu.

Thin launcher over ``backuwup_tpu.analysis.cli`` so the tool runs from
a checkout without installing the package:

    python scripts/bkwlint.py                 # lint the repo tree
    python scripts/bkwlint.py --format json   # machine-readable
    python scripts/bkwlint.py --no-baseline   # show baselined findings

Exit codes: 0 clean / 1 findings / 2 usage error / 3 stale baseline.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from backuwup_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
