import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    from backuwup_tpu.ops.cdc_tpu import _candidate_words, _hash_ext_fast
    from backuwup_tpu.ops.scan_fused import fused_candidate_words

    print("devices:", jax.devices())
    rng = np.random.default_rng(7)

    # parity across sizes
    for P in (64 * 1024, 1 << 20, 16 << 20):
        ext = rng.integers(0, 256, (2, 31 + P), dtype=np.uint8)
        nv = np.array([P, P - 12345], dtype=np.int32)
        mask_s = (0xFFFFFFFF << (32 - 22)) & 0xFFFFFFFF
        mask_l = (0xFFFFFFFF << (32 - 18)) & 0xFFFFFFFF
        wl, ws = fused_candidate_words(jnp.asarray(ext), jnp.asarray(nv),
                                       mask_s=mask_s, mask_l=mask_l)
        ok = True
        for r in range(2):
            h = _hash_ext_fast(jnp.asarray(ext[r]))
            rl, rs = _candidate_words(h, jnp.int32(nv[r]),
                                      jnp.uint32(mask_s), jnp.uint32(mask_l))
            el = np.array_equal(np.asarray(wl[r]), np.asarray(rl))
            es = np.array_equal(np.asarray(ws[r]), np.asarray(rs))
            ok = ok and el and es
            if not (el and es):
                a, b = np.asarray(wl[r]), np.asarray(rl)
                bad = np.nonzero(a != b)[0]
                print(f"  P={P} row {r}: loose diff at words {bad[:5]} "
                      f"(of {bad.size})", a[bad[:3]], b[bad[:3]])
        print(f"P={P}: parity {'OK' if ok else 'FAIL'}")
        if not ok:
            return

    # timing: 256 MiB single row
    P = 256 << 20
    ext = rng.integers(0, 256, (1, 31 + P), dtype=np.uint8)
    nv = np.array([P], dtype=np.int32)
    dev = jnp.asarray(ext)
    jax.block_until_ready(dev)

    def t_fused():
        return fused_candidate_words(dev, jnp.asarray(nv),
                                     mask_s=mask_s, mask_l=mask_l)

    def t_xla():
        h = _hash_ext_fast(dev[0])
        return _candidate_words(h, jnp.int32(P), jnp.uint32(mask_s),
                                jnp.uint32(mask_l))

    for name, fn in (("fused", t_fused), ("xla", t_xla)):
        out = fn()
        jax.block_until_ready(out)
        t0 = time.time()
        for _ in range(3):
            out = fn()
            jax.block_until_ready(out)
        dt = (time.time() - t0) / 3
        print(f"{name}: {dt*1000:.1f} ms / 256 MiB = {256/dt:.0f} MiB/s")


if __name__ == "__main__":
    main()
