"""Isolate the scan+select bottleneck with FRESH inputs per rep (the relay
caches identical dispatches, so same-input timings lie)."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import functools

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops.cdc_tpu import _HALO, _hash_ext_fast, scan_select_batch
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline

SEG_MIB = int(os.environ.get("PROF_SEGMENT_MIB", "128"))
seg_bytes = SEG_MIB << 20
row = _HALO + seg_bytes
params = CDCParams()
pipe = DevicePipeline(params)
s_cap, l_cap, cut_cap = pipe._caps(seg_bytes)
P = seg_bytes


@jax.jit
def synth(key):
    seg = jax.random.randint(key, (seg_bytes,), 0, 256, dtype=jnp.uint8)
    return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]).reshape(1, row)


def bench(label, fn, keys):
    out = fn(synth(keys[0]))  # warm/compile
    jax.block_until_ready(out)
    t0 = time.time()
    for k in keys[1:]:
        buf = synth(k)
        jax.block_until_ready(buf)
        t1 = time.time()
        out = fn(buf)
        jax.block_until_ready(out)
    dt = time.time() - t1  # last rep only (excludes synth)
    print(f"{label:46s} {dt*1e3:9.1f} ms ({SEG_MIB/dt:8.1f} MiB/s)",
          flush=True)


def keysplit(key, n):
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        out.append(sub)
    return key, out


p = params
ms, ml = jnp.uint32(p.mask_s), jnp.uint32(p.mask_l)


@jax.jit
def hash_only(buf):
    return _hash_ext_fast(buf[0])


@jax.jit
def hash_cand(buf):
    h = _hash_ext_fast(buf[0])
    valid = jnp.arange(P, dtype=jnp.int32) < P
    cand_l = ((h & ml) == 0) & valid
    cand_s = cand_l & ((h & ms) == 0)
    return jnp.sum(cand_l.astype(jnp.int32)), jnp.sum(cand_s.astype(jnp.int32))


@jax.jit
def hash_cand_nonzero(buf):
    h = _hash_ext_fast(buf[0])
    valid = jnp.arange(P, dtype=jnp.int32) < P
    cand_l = ((h & ml) == 0) & valid
    cand_s = cand_l & ((h & ms) == 0)
    (pos_l,) = jnp.nonzero(cand_l, size=l_cap, fill_value=P)
    (pos_s,) = jnp.nonzero(cand_s, size=s_cap, fill_value=P)
    return pos_l, pos_s


def _select_loop(pos_s, pos_l, n, lower_bound):
    def cond(st):
        s, k, _ = st
        return s < n

    def body(st):
        s, k, cuts = st
        lo = s + jnp.int32(p.min_size - 1)
        hi = jnp.minimum(s + jnp.int32(p.desired_size - 2), n - 2)
        i = lower_bound(pos_s, lo)
        e1 = pos_s[jnp.minimum(i, s_cap - 1)]
        ok1 = (i < s_cap) & (e1 <= hi)
        lo2 = s + jnp.int32(p.desired_size - 1)
        hi2 = jnp.minimum(s + jnp.int32(p.max_size - 2), n - 2)
        j = lower_bound(pos_l, lo2)
        e2 = pos_l[jnp.minimum(j, l_cap - 1)]
        ok2 = (j < l_cap) & (e2 <= hi2)
        e = jnp.where(ok1, e1, jnp.where(
            ok2, e2, jnp.minimum(s + jnp.int32(p.max_size - 1), n - 1)))
        e = jnp.where(n - s <= jnp.int32(p.min_size), n - 1, e)
        cuts = cuts.at[k].set(e)
        return e + 1, k + 1, cuts

    return jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int32(0), jnp.full(cut_cap, -1, jnp.int32)))


@jax.jit
def full_searchsorted(buf):
    pos_l, pos_s = _nonzero(buf)
    return _select_loop(pos_s, pos_l, jnp.int32(P),
                        lambda a, v: jnp.searchsorted(a, v, side="left"))


def _nonzero(buf):
    h = _hash_ext_fast(buf[0])
    valid = jnp.arange(P, dtype=jnp.int32) < P
    cand_l = ((h & ml) == 0) & valid
    cand_s = cand_l & ((h & ms) == 0)
    (pos_l,) = jnp.nonzero(cand_l, size=l_cap, fill_value=P)
    (pos_s,) = jnp.nonzero(cand_s, size=s_cap, fill_value=P)
    return pos_l.astype(jnp.int32), pos_s.astype(jnp.int32)


@jax.jit
def full_sumlb(buf):
    pos_l, pos_s = _nonzero(buf)
    return _select_loop(pos_s, pos_l, jnp.int32(P),
                        lambda a, v: jnp.sum((a < v).astype(jnp.int32)))


scan_fn = functools.partial(
    scan_select_batch, min_size=p.min_size, desired_size=p.desired_size,
    max_size=p.max_size, mask_s=p.mask_s, mask_l=p.mask_l,
    s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap)
nv_d = jnp.asarray(np.full(1, seg_bytes, dtype=np.int32))


def main():
    print(f"devices: {jax.devices()}  segment={SEG_MIB} MiB  "
          f"caps s={s_cap} l={l_cap} cut={cut_cap}", flush=True)
    key = jax.random.PRNGKey(3)
    for label, fn in [
        ("hash ladder only", hash_only),
        ("hash + cand counts", hash_cand),
        ("production scan_select_batch", lambda b: scan_fn(b, nv_d)),
    ]:
        key, keys = keysplit(key, 3)
        bench(label, fn, keys)

    # digest steady state with fresh data
    key, keys = keysplit(key, 3)
    nv = np.full(1, seg_bytes, dtype=np.int32)
    for k in keys:
        buf = synth(k)
        jax.block_until_ready(buf)
        packed = pipe.scan_select_dispatch(buf, nv)
        per_row = pipe.scan_select_collect(packed, buf, nv, True)
        t0 = time.time()
        pending = pipe.digest_dispatch(buf, per_row)
        jax.block_until_ready(pending[0])
        print(f"digest ({len(per_row[0])} chunks, {len(pending[1])} tiles): "
              f"{(time.time()-t0)*1e3:8.1f} ms", flush=True)


if __name__ == "__main__":
    main()
