"""Honest device timing on the axon relay.

``jax.block_until_ready`` does not wait for device completion on this
rig (measured: a 256 MiB scan "completes" in 0.08 ms, below the HBM
read floor), so wall-clock timing needs a forced host download to sync.
``dev_time`` times N back-to-back executions followed by ONE tiny
download and subtracts the download-only baseline — the relay latency is
paid once, device executions queue and run back to back.
"""
import time

import numpy as np


def _sync(out):
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    return np.asarray(leaf.ravel()[0])


def dev_time(fn, *args, n=20):
    """Seconds of device time per execution of ``fn(*args)``."""
    out = fn(*args)  # warm / compile
    _sync(out)
    t0 = time.time()
    _sync(out)
    base = time.time() - t0  # download-only round trip on a ready value
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    _sync(out)
    total = time.time() - t0
    return max(total - base, 1e-9) / n
