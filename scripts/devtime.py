"""Thin wrapper: the honest chained-execution device timer now lives in
``backuwup_tpu.obs.profile`` (promoted to a library API with the metrics
registry as its sink — see docs/observability.md).  This shim keeps
every ``from scripts.devtime import dev_time`` in the probe scripts and
the recovery runbook working unchanged."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from backuwup_tpu.obs.profile import (  # noqa: E402,F401
    _sync,
    dev_time,
    dev_time_stage,
)
