import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from backuwup_tpu.ops.dedup_index import ShardedDedupIndex

mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
t0 = time.time()
big = ShardedDedupIndex.create(mesh, capacity=1 << 18)
print("create", time.time() - t0, flush=True)
batch = 250_000
vals = jnp.ones((8, batch // 8), dtype=jnp.uint32)
key = jax.random.PRNGKey(99)
for i in range(2):
    key, sub = jax.random.split(key)
    t0 = time.time()
    q = jax.device_put(
        jax.random.bits(sub, (batch, 4), dtype=jnp.uint32
                        ).reshape(8, batch // 8, 4),
        NamedSharding(mesh, P("data")))
    jax.block_until_ready(q)
    print("synth", time.time() - t0, flush=True)
    t0 = time.time()
    f, lo = big.insert_device(q, vals)
    jax.block_until_ready((f, lo))
    print("insert", i, time.time() - t0, "lost",
          int(np.asarray(lo).sum()), flush=True)
