#!/usr/bin/env python3
"""Per-config trend deltas across the BENCH_*.json capture history.

Every capture (driver rounds ``BENCH_r*.json``, ``tpu_watch.sh``
recaptures ``BENCH_<kind>_<stamp>.json``) carries the same shape: a
top-level headline (``metric``/``value``/``vs_baseline``) plus a
``configs`` map of per-config numeric evidence.  This script lines the
captures up in time order and prints, for every config metric, the
latest value against its previous appearance — then **exits nonzero
when a gated metric regressed** beyond the tolerance, so the watch
loop (and a human about to trust a number) learns about a slide the
moment it is captured, not at the next paper-draft read-through.

Direction is inferred from the metric name (throughput/speedup/
ratio/efficiency-style names must not drop; seconds/latency/debt-style
names must not rise); names that match neither way are printed as
informational but never gate.  Stdlib-only, like every script here.

    python scripts/bench_trend.py                  # repo-root history
    python scripts/bench_trend.py --dir out/ --tolerance 0.05
    BENCH_TREND_TOLERANCE=0.2 python scripts/bench_trend.py file1 file2
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: substrings that mark a metric higher-is-better (checked first: a
#: throughput named mib_s must not fall into the seconds bucket below)
_HIGHER = ("mib_s", "speedup", "throughput", "ratio", "efficiency",
           "hit_rate", "events_per", "compression", "precision",
           "vs_baseline", "files")
#: substrings / suffixes that mark a metric lower-is-better
_LOWER_SUB = ("latency", "lag", "debt", "lost", "violation", "stall",
              "detection", "wait")
_LOWER_SUFFIX = ("_s", "_seconds", "_bytes", "_p99", "_p50")


def direction(metric: str) -> int:
    """+1 must-not-drop, -1 must-not-rise, 0 informational only."""
    m = metric.lower()
    if any(s in m for s in _HIGHER):
        return 1
    if any(s in m for s in _LOWER_SUB) or m.endswith(_LOWER_SUFFIX):
        return -1
    return 0


def load_record(path: str):
    """The capture's parsed BENCH record, or None when the file is not
    a usable capture (torn write, device-down run carrying ``error``)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]  # driver wrapper {cmd, rc, parsed, ...}
    if not isinstance(doc, dict) or doc.get("error"):
        return None
    return doc


def flatten(record: dict) -> "dict[tuple, float]":
    """(config, metric) -> value; the headline rides as config ''."""
    out = {}
    headline = str(record.get("metric", "value"))
    for key in ("value", "vs_baseline"):
        if isinstance(record.get(key), (int, float)):
            tag = headline if key == "value" \
                else f"{headline} vs_baseline"
            out[("", tag)] = float(record[key])
    configs = record.get("configs")
    if isinstance(configs, dict):
        for cfg, metrics in configs.items():
            if not isinstance(metrics, dict):
                continue
            for metric, value in metrics.items():
                if isinstance(value, (int, float)) \
                        and not isinstance(value, bool):
                    out[(str(cfg), str(metric))] = float(value)
    return out


def compare(history, tolerance: float):
    """[(config, metric, prev, last, rel_delta, regressed)] between each
    key's last two appearances across the time-ordered history."""
    series: dict = {}
    for _path, flat in history:
        for key, value in flat.items():
            series.setdefault(key, []).append(value)
    rows = []
    for (cfg, metric), values in sorted(series.items()):
        if len(values) < 2:
            continue
        prev, last = values[-2], values[-1]
        base = max(abs(prev), 1e-12)
        rel = (last - prev) / base
        sense = direction(metric)
        regressed = (sense > 0 and rel < -tolerance) or \
                    (sense < 0 and rel > tolerance)
        rows.append((cfg, metric, prev, last, rel, regressed))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="explicit captures in time order (default:"
                         " BENCH_*.json under --dir, mtime order)")
    ap.add_argument("--dir", default=os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."),
        help="directory to glob BENCH_*.json from (default: repo root)")
    ap.add_argument("--tolerance", type=float, default=float(
        os.environ.get("BENCH_TREND_TOLERANCE", 0.10)),
        help="relative slide a gated metric may take before the exit"
             " code turns nonzero (default 0.10, env"
             " BENCH_TREND_TOLERANCE)")
    args = ap.parse_args(argv)

    paths = args.files or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_*.json")),
        key=lambda p: (os.path.getmtime(p), p))
    history = []
    for path in paths:
        record = load_record(path)
        if record is not None:
            history.append((path, flatten(record)))
    if len(history) < 2:
        print(f"bench-trend: {len(history)} usable capture(s) — need 2"
              f" for a delta; nothing to compare")
        return 0

    rows = compare(history, args.tolerance)
    regressions = 0
    for cfg, metric, prev, last, rel, regressed in rows:
        tag = f"{cfg}/{metric}" if cfg else metric
        flag = ""
        if regressed:
            flag = "  REGRESSION"
            regressions += 1
        elif direction(metric) == 0:
            flag = "  (info)"
        print(f"{tag}: {prev:g} -> {last:g} ({rel:+.1%}){flag}")
    print(f"bench-trend: {len(history)} captures, {len(rows)} tracked"
          f" metrics, {regressions} regression(s)"
          f" (tolerance {args.tolerance:.0%})")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
