"""Distribution equivalence: windowed Gear CDC vs FastCDC-2020 semantics.

The reference chunks with the Rust ``fastcdc`` crate's v2020 algorithm
(restart the gear hash at each chunk start, skip the first ``min`` bytes,
two-mask normalized chunking).  CDC_SPEC.md deliberately replaces the
restart with a pure 32-byte sliding window so candidates are
position-independent (the property that makes the TPU decomposition
possible), and documents the deviation.  This test closes the
"FastCDC-class" claim empirically: a faithful restart-variant
implementation (same selection rules, same mask popcounts — the
quantities that determine chunking statistics) must produce

* the same chunk-length distribution (mean within 3%, CDF sup-distance
  small), and
* the same dedup behavior under localized edits (re-chunk a mutated
  copy; duplicate-chunk ratios within a few points),

as the production windowed chunker on identical corpora.
"""

import numpy as np
import pytest

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.gear import GEAR, CDCParams

PARAMS = CDCParams.from_desired(8192)  # 2 KiB / 8 KiB / 24 KiB


def fastcdc2020_chunks(data: bytes, params: CDCParams):
    """Restart-variant FastCDC v2020 semantics (reference behavior model).

    Per chunk: gear hash restarts at the chunk start, the first
    ``min_size`` bytes are skipped entirely, the strict mask applies up
    to ``desired`` and the loose mask to ``max``, cut forced at ``max``.
    Mask popcounts match the production spec, so the per-position cut
    probability — the driver of the length distribution — is identical.
    Vectorized via the windowed identity: ``h_restart[i] == h_window[i]``
    once ``i`` is >= 31 positions past the restart point; only the first
    31 scanned positions of each chunk need the partial-sum correction.
    """
    n = len(data)
    buf = np.frombuffer(data, dtype=np.uint8)
    hw = cdc_cpu.gear_hashes(data)  # windowed hashes, all positions
    g = GEAR[buf]
    mask_s = np.uint32(params.mask_s)
    mask_l = np.uint32(params.mask_l)
    chunks = []
    s = 0
    while s < n:
        if n - s <= params.min_size:
            chunks.append((s, n - s))
            break
        start_scan = s + params.min_size - 1
        # restart-correct hashes for the first 31 scanned positions
        prefix_end = min(start_scan + 31, n)
        h_prefix = np.zeros(prefix_end - start_scan, dtype=np.uint32)
        for j in range(start_scan, prefix_end):
            # h over bytes s..j only (window truncated at restart)
            lo = max(s, j - 31)
            acc = 0
            for k in range(lo, j + 1):
                acc = ((acc << 1) + int(g[k])) & 0xFFFFFFFF
            h_prefix[j - start_scan] = np.uint32(acc)
        e = None
        hi1 = min(s + params.desired_size - 2, n - 2)
        hi2 = min(s + params.max_size - 2, n - 2)
        for j in range(start_scan, hi2 + 1):
            h = (h_prefix[j - start_scan]
                 if j < prefix_end else hw[j])
            if j <= hi1:
                if (h & mask_s) == 0:
                    e = j
                    break
            else:
                if (h & mask_l) == 0:
                    e = j
                    break
        if e is None:
            e = min(s + params.max_size - 1, n - 1)
        chunks.append((s, e - s + 1))
        s = e + 1
    return chunks


@pytest.fixture(scope="module")
def corpus():
    return np.random.default_rng(42).integers(
        0, 256, 8 << 20, dtype=np.uint8).tobytes()


def test_length_distribution_matches(corpus):
    ours = cdc_cpu.chunk_stream(corpus, PARAMS)
    theirs = fastcdc2020_chunks(corpus, PARAMS)
    a = np.sort([ln for _, ln in ours[:-1]])   # drop EOF tails
    b = np.sort([ln for _, ln in theirs[:-1]])
    assert abs(a.mean() - b.mean()) / b.mean() < 0.03
    # CDF sup-distance on the pooled grid (two-sample KS statistic)
    grid = np.unique(np.concatenate([a, b]))
    cdf_a = np.searchsorted(a, grid, side="right") / len(a)
    cdf_b = np.searchsorted(b, grid, side="right") / len(b)
    ks = np.abs(cdf_a - cdf_b).max()
    # KS must be small in absolute terms AND not significant at ~1%
    # (c(0.01) = 1.63 for the two-sample statistic)
    thresh = 1.63 * np.sqrt((len(a) + len(b)) / (len(a) * len(b)))
    assert ks < max(0.08, thresh), (ks, thresh)


def test_dedup_under_edits_matches(corpus):
    rng = np.random.default_rng(7)
    edited = bytearray(corpus)
    for _ in range(24):
        off = int(rng.integers(0, len(edited) - 4096))
        edited[off:off + 4096] = rng.bytes(4096)
    edited = bytes(edited)

    def dedup_ratio(chunker):
        base = chunker(corpus, PARAMS)
        seen = {corpus[o:o + l] for o, l in base}
        after = chunker(edited, PARAMS)
        dup = sum(1 for o, l in after if edited[o:o + l] in seen)
        return dup / len(after)

    r_ours = dedup_ratio(cdc_cpu.chunk_stream)
    r_theirs = dedup_ratio(fastcdc2020_chunks)
    # both must recover nearly all unedited content; windowed
    # resynchronization should be at least as good as restart
    assert r_ours > 0.9 and r_theirs > 0.9
    assert r_ours >= r_theirs - 0.02, (r_ours, r_theirs)
