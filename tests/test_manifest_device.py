"""Zero-round-trip device manifest must be bit-identical to the oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
from backuwup_tpu.ops.cdc_tpu import _HALO
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.manifest_device import (
    class_caps,
    class_leaf_sizes,
    scan_digest_batch,
)
from backuwup_tpu.ops.pipeline import DevicePipeline

SMALL = CDCParams.from_desired(4096)


def _oracle(data, params):
    chunks = cdc_cpu.chunk_stream(data, params)
    digests = Blake3Numpy().digest_batch([data[o:o + l] for o, l in chunks])
    return chunks, digests


def _stage(rows, P):
    buf = np.zeros((len(rows), _HALO + P), dtype=np.uint8)
    nv = np.zeros(len(rows), dtype=np.int32)
    for r, d in enumerate(rows):
        buf[r, _HALO:_HALO + len(d)] = np.frombuffer(d, dtype=np.uint8)
        nv[r] = len(d)
    return jnp.asarray(buf), nv


def test_class_plan_sizes():
    classes = class_leaf_sizes(SMALL)
    assert classes[-1] == SMALL.max_size // 1024
    caps = class_caps(SMALL, 1 << 20, 4)
    assert len(caps) == len(classes)
    assert all(c % 4 == 0 for c in caps)
    assert caps[-1] > 0  # cascade terminus always has slots


@pytest.mark.parametrize("sizes", [
    [65536], [65536, 30_000, 0, 65536], [1, 64, 1024]])
def test_scan_digest_batch_matches_oracle(sizes):
    P = 65536
    rows = [np.random.default_rng(3 + i).integers(
        0, 256, n, dtype=np.uint8).tobytes() for i, n in enumerate(sizes)]
    buf, nv = _stage(rows, P)
    pipe = DevicePipeline(SMALL)
    s_cap, l_cap, cut_cap = pipe._caps(P)
    classes = class_leaf_sizes(SMALL)
    caps = class_caps(SMALL, len(rows) * P, len(rows))
    packed, acc, ovf = scan_digest_batch(
        buf, jnp.asarray(nv), min_size=SMALL.min_size,
        desired_size=SMALL.desired_size, max_size=SMALL.max_size,
        mask_s=SMALL.mask_s, mask_l=SMALL.mask_l,
        s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=False,
        classes=classes, caps=caps)
    packed = np.asarray(packed)
    acc = np.asarray(acc)
    assert not np.asarray(ovf).any()
    dig8 = np.ascontiguousarray(acc.astype("<u4")).view(np.uint8).reshape(
        len(rows), cut_cap, 32)
    for r, data in enumerate(rows):
        ref_chunks, ref_digests = _oracle(data, SMALL)
        assert packed[r, 0] == 0
        n_cuts = int(packed[r, 1])
        ends = packed[r, 2:2 + n_cuts].astype(np.int64)
        offs = np.concatenate([[0], ends[:-1] + 1])
        got = list(zip(offs.tolist(), (ends - offs + 1).tolist()))
        assert got == ref_chunks
        assert [bytes(d) for d in dig8[r, :n_cuts]] == ref_digests


def test_manifest_segments_device_driver():
    P = 65536
    rng = np.random.default_rng(11)
    batches = []
    rows_all = []
    for b in range(3):
        rows = [rng.integers(0, 256, rng.integers(1000, P + 1),
                             dtype=np.uint8).tobytes() for _ in range(2)]
        rows_all.append(rows)
        batches.append(_stage(rows, P))
    pipe = DevicePipeline(SMALL)
    results = list(pipe.manifest_segments_device(iter(batches)))
    assert len(results) == 3
    for rows, res in zip(rows_all, results):
        for data, (chunks, digests) in zip(rows, res):
            ref_chunks, ref_digests = _oracle(data, SMALL)
            assert chunks == ref_chunks
            assert [bytes(d) for d in digests] == ref_digests


def test_class_overflow_falls_back():
    # all-zero data chunks entirely at max size: the top class overflows
    # its calibrated capacity once the batch is large enough, and the
    # driver falls back to the host-tiled path with identical output
    P = 1 << 20
    data = b"\0" * P
    buf, nv = _stage([data], P)
    pipe = DevicePipeline(SMALL)
    (res,), = pipe.manifest_segments_device(iter([(buf, nv)]))
    chunks, digests = res
    ref_chunks, ref_digests = _oracle(data, SMALL)
    assert chunks == ref_chunks
    assert [bytes(d) for d in digests] == ref_digests
