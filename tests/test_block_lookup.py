"""Block-skip lookup (= searchsorted-left) unit equivalence.

End-to-end bit-parity of the selection lives in
``tests/test_parallel_select.py``; here the lookup primitive itself is
pinned against ``np.searchsorted`` — including the overflow contract:
whenever the probe window underestimates (more than ``probes``
candidates between a query's block start and the query), the overflow
flag MUST be set, because an unflagged wrong index would silently
corrupt cut selection instead of routing the row to the oracle.
"""

import numpy as np

import jax.numpy as jnp

from backuwup_tpu.ops.cdc_tpu import _block_cum, _make_lookup

BB = 7  # 128-byte blocks keep the dense cases interesting


def _build(pos_np, cap, padded):
    pos = jnp.asarray(pos_np.astype(np.int32))
    cum = _block_cum(pos, padded, BB)
    return _make_lookup(pos, cum, cap, padded, BB)


def _check(pos_np, queries, cap, padded):
    look = _build(pos_np, cap, padded)
    idx, over = look(jnp.asarray(queries.astype(np.int32)))
    idx = np.asarray(idx)
    over = np.asarray(over)
    want = np.searchsorted(pos_np, np.clip(queries, 0, padded), side="left")
    bad = (idx != want) & ~over
    assert not bad.any(), (
        f"unflagged divergence at {np.nonzero(bad)[0][:5]}: "
        f"got {idx[bad][:5]}, want {want[bad][:5]}")
    return over


def test_sparse_exact_no_overflow(nprng):
    padded = 1 << 16
    cap = 256
    vals = np.sort(nprng.choice(padded - 1, size=120, replace=False))
    pos = np.full(cap, padded, dtype=np.int64)
    pos[:120] = vals
    queries = np.concatenate([
        nprng.integers(-5, padded + 40, size=500),
        vals, vals + 1, vals - 1,  # boundary hits on every side
        np.array([0, padded, padded - 1]),
    ])
    over = _check(pos, queries, cap, padded)
    # density ~0.23/block: the 6-probe window must never overflow here
    assert not over.any()


def test_dense_block_flags_overflow(nprng):
    padded = 1 << 14
    cap = 64
    # 10 candidates crammed into one 128-byte block: any query beyond
    # them in the same block exceeds the probe window and must flag
    base = 4 * 128
    pos = np.full(cap, padded, dtype=np.int64)
    pos[:10] = base + np.arange(10)
    queries = np.array([base + 9, base + 10, base + 127,  # inside the block
                        base, base + 3, base + 200])
    over = _check(pos, queries, cap, padded)
    assert over[:3].all(), "dense-run queries must flag overflow"
    assert not over[3:5].any(), "short-run queries stay exact"


def test_full_array_no_sentinels(nprng):
    padded = 1 << 14
    cap = 32
    pos = np.sort(nprng.choice(np.arange(0, padded, 130), size=cap,
                               replace=False)).astype(np.int64)
    queries = np.concatenate([pos, pos + 1, [0, padded],
                              nprng.integers(0, padded, size=200)])
    _check(pos, queries, cap, padded)


def test_empty_table():
    padded = 1 << 13
    cap = 16
    pos = np.full(cap, padded, dtype=np.int64)
    over = _check(pos, np.array([0, 1, 5000, padded]), cap, padded)
    assert not over.any()
