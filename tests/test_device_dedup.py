"""MeshDedupIndex: device-batched dedup decisions with BlobIndex parity."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.device_dedup import MeshDedupIndex


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


@pytest.fixture
def host_index(tmp_path):
    keys = KeyManager.from_secret(b"\x07" * 32)
    return BlobIndex(keys, tmp_path / "index")


def _hashes(n, seed=0):
    return [blake3_hash(f"{seed}:{i}".encode()) for i in range(n)]


def test_classify_matches_host(mesh, host_index):
    dev = MeshDedupIndex(mesh, host_index, capacity=256)
    hs = _hashes(100)
    flags = dev.classify_insert(hs)
    for h, f in zip(hs, flags):
        assert f == host_index.is_duplicate(h)  # all new
        host_index.mark_queued(h)
    # second round: everything is now a duplicate on both sides
    flags2 = dev.classify_insert(hs)
    assert all(flags2)
    assert all(host_index.is_duplicate(h) for h in hs)


def test_intra_batch_repeats(mesh, host_index):
    dev = MeshDedupIndex(mesh, host_index, capacity=256)
    hs = _hashes(5, seed=1)
    batch = [hs[0], hs[1], hs[0], hs[2], hs[1], hs[0]]
    flags = dev.classify_insert(batch)
    assert flags == [False, False, True, False, True, True]


def test_seeded_from_host(mesh, host_index):
    pre = _hashes(20, seed=2)
    for h in pre[:10]:
        host_index.mark_queued(h)
    host_index.finalize_packfile(b"\x01" * 12, pre[10:15])
    dev = MeshDedupIndex(mesh, host_index, capacity=256)
    flags = dev.classify_insert(pre)
    assert flags == [True] * 15 + [False] * 5


def test_streamed_chunks_synced_before_next_classify(mesh, tmp_path):
    """A chunk first seen via the streaming path (host-classified only)
    must reach the device table before the next batch classify, or its
    re-occurrence reads device-new/host-dup and trips the divergence
    guard."""
    import random

    from backuwup_tpu.ops.backend import CpuBackend
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.snapshot.packer import DirPacker
    from backuwup_tpu.snapshot.packfile import PackfileWriter

    keys = KeyManager.from_secret(b"\x08" * 32)
    params = CDCParams.from_desired(4096)
    rng = random.Random(21)
    big = rng.randbytes(200_000)
    src = tmp_path / "src"
    src.mkdir()
    # a_big streams (size > batch_bytes); b_pre shares its leading chunks
    (src / "a_big.bin").write_bytes(big)
    (src / "b_pre.bin").write_bytes(big[:50_000])

    index = BlobIndex(keys, tmp_path / "index")
    dev = MeshDedupIndex(mesh, index, capacity=1024)
    writer = PackfileWriter(keys, tmp_path / "pack",
                            on_packfile=lambda pid, path, hashes, size:
                            index.finalize_packfile(pid, hashes))
    packer = DirPacker(CpuBackend(params), writer, index,
                       batch_bytes=100_000,
                       dedup_batch=dev.classify_insert)
    packer.pack(src)
    # wrong sync order shows up as device/host divergences (host wins,
    # logged + counted)
    assert packer.stats.dedup_divergences == 0
    assert packer.stats.chunks_deduped > 0


def test_grows_under_pressure(mesh, host_index):
    dev = MeshDedupIndex(mesh, host_index, capacity=8)
    hs = _hashes(600, seed=3)
    # host must know the hashes a grow() reseeds from
    flags = []
    for s in range(0, len(hs), 64):
        batch = hs[s:s + 64]
        flags.extend(dev.classify_insert(batch))
        for h in batch:
            host_index.mark_queued(h)
    assert not any(flags)  # all distinct -> all new
    assert dev.capacity > 8  # grew at least once
    assert all(dev.classify_insert(hs))  # now all resident


def test_engine_auto_attaches_mesh_on_accelerator(tmp_path, monkeypatch):
    """A plain Engine on the device backend classifies via MeshDedupIndex
    without a caller-supplied mesh (VERDICT r2 item 5)."""
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.ops.backend import TpuBackend
    from backuwup_tpu.ops.gear import CDCParams

    app = ClientApp(config_dir=tmp_path / "cfg", data_dir=tmp_path / "data",
                    server_addr="127.0.0.1:1",
                    backend=TpuBackend(CDCParams.from_desired(4096)))
    assert app.engine.device_dedup is not None

    monkeypatch.setenv("BKW_DEVICE_DEDUP", "0")
    app2 = ClientApp(config_dir=tmp_path / "cfg2",
                     data_dir=tmp_path / "data2",
                     server_addr="127.0.0.1:1",
                     backend=TpuBackend(CDCParams.from_desired(4096)))
    assert app2.engine.device_dedup is None
