"""Device-path BLAKE3 must be bit-exact vs the scalar spec implementation."""

import random

import numpy as np
import jax.numpy as jnp

from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.blake3_tpu import (
    blake3_many_tpu,
    bucketed_batches,
    digest_padded,
)

EMPTY_DIGEST = "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262"
# Official test vector: input = single byte 0x00 (the 0..250 repeating
# pattern truncated to length 1), from BLAKE3's test_vectors.json.
ONE_BYTE_DIGEST = (
    "2d3adedff11b61f14c886e35afa036736dcd87a74d27b5c1510225d0f592e213")


def _corpus():
    rng = random.Random(7)
    lengths = [0, 1, 63, 64, 65, 1023, 1024, 1025, 2048, 2049, 3072, 4096,
               5000, 1024 * 7, 1024 * 8 + 1, 1024 * 16, 1024 * 31 + 17,
               1024 * 64, 1024 * 100 + 3]
    return [rng.randbytes(n) for n in lengths]


def test_empty_vector():
    assert blake3_many_tpu([b""])[0].hex() == EMPTY_DIGEST


def test_one_byte_official_vector():
    assert blake3_hash(b"\x00").hex() == ONE_BYTE_DIGEST
    assert blake3_many_tpu([b"\x00"])[0].hex() == ONE_BYTE_DIGEST


def test_matches_scalar_spec():
    corpus = _corpus()
    for data, got in zip(corpus, blake3_many_tpu(corpus)):
        assert got == blake3_hash(data), f"len={len(data)}"


def test_digest_padded_direct():
    # One bucket shape, mixed lengths inside it, including all-padding rows.
    datas = [b"", b"a", b"b" * 1500, b"c" * (16 * 1024)]
    buf = np.zeros((4, 16 * 1024), dtype=np.uint8)
    lens = np.zeros(4, dtype=np.int32)
    for i, d in enumerate(datas):
        buf[i, :len(d)] = np.frombuffer(d, dtype=np.uint8)
        lens[i] = len(d)
    root = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens), L=16))
    for i, d in enumerate(datas):
        assert root[i].astype("<u4").tobytes() == blake3_hash(d)


def test_bucketing_covers_all_inputs_once():
    corpus = _corpus()
    seen = []
    for idxs, buf, lens, L in bucketed_batches(corpus):
        seen.extend(idxs)
        assert buf.shape[1] == L * 1024
        for row, i in enumerate(idxs):
            assert lens[row] == len(corpus[i])
    assert sorted(seen) == list(range(len(corpus)))
