"""Property-based tests (hypothesis) for the invariants everything else
rests on — the reference ships almost no tests (SURVEY §4), so the spec
properties are pinned here instead:

* CDC: the vectorized oracle == the definitional scalar loop; chunks
  tile the stream exactly; every non-final chunk respects [min, max].
* BLAKE3: the batched engine == the scalar spec implementation.
* Packfile: write -> read round-trips bit-exactly under random blob mixes.
* Wire: tree/blob codecs round-trip.
"""

import pytest

pytest.importorskip("hypothesis", reason="hypothesis not installed")

from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy, blake3_hash
from backuwup_tpu.ops.gear import CDCParams

SMALL_PARAMS = [
    CDCParams.from_desired(256),
    CDCParams.from_desired(1024),
    CDCParams.from_desired(4096),
]

prop = settings(max_examples=40, deadline=None,
                suppress_health_check=[HealthCheck.too_slow])


@prop
@given(data=st.binary(min_size=0, max_size=16384),
       pi=st.integers(0, len(SMALL_PARAMS) - 1))
def test_cdc_vectorized_matches_scalar(data, pi):
    params = SMALL_PARAMS[pi]
    assert cdc_cpu.chunk_stream(data, params) == \
        cdc_cpu.chunk_stream_scalar(data, params)


@prop
@given(data=st.binary(min_size=0, max_size=65536),
       pi=st.integers(0, len(SMALL_PARAMS) - 1))
def test_cdc_chunks_tile_stream_and_respect_bounds(data, pi):
    params = SMALL_PARAMS[pi]
    chunks = cdc_cpu.chunk_stream(data, params)
    pos = 0
    for i, (off, ln) in enumerate(chunks):
        assert off == pos and ln > 0
        pos += ln
        if i < len(chunks) - 1:
            assert params.min_size <= ln <= params.max_size
        else:
            assert ln <= params.max_size
    assert pos == len(data)
    # chunking is deterministic
    assert chunks == cdc_cpu.chunk_stream(data, params)


@prop
@given(datas=st.lists(st.binary(min_size=0, max_size=5000),
                      min_size=1, max_size=8))
def test_blake3_batch_matches_scalar(datas):
    engine = Blake3Numpy()
    batch = engine.digest_batch(datas)
    for data, got in zip(datas, batch):
        assert got == blake3_hash(data)


@settings(max_examples=15, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(blobs=st.lists(st.binary(min_size=1, max_size=30000),
                      min_size=1, max_size=10),
       seed=st.integers(0, 2**32 - 1))
def test_packfile_roundtrip(tmp_path_factory, blobs, seed):
    from backuwup_tpu.crypto import KeyManager
    from backuwup_tpu.snapshot.packfile import PackfileReader, PackfileWriter
    from backuwup_tpu.wire import Blob, BlobKind

    tmp = tmp_path_factory.mktemp("pf")
    keys = KeyManager.from_secret(seed.to_bytes(4, "little") * 8)
    written = []
    writer = PackfileWriter(
        keys, tmp, on_packfile=lambda pid, path, hashes, size:
        written.append((pid, hashes)))
    expect = {}
    for data in blobs:
        h = blake3_hash(data)
        expect[h] = data
        writer.add_blob(Blob(hash=h, kind=BlobKind.FILE_CHUNK, data=data))
    writer.flush()
    reader = PackfileReader(keys, tmp)
    seen = set()
    for pid, hashes in written:
        for h in hashes:
            blob = reader.get_blob(pid, h)
            assert blob.data == expect[h]
            seen.add(h)
    assert seen == set(expect)


@prop
@given(name=st.text(max_size=40),
       children=st.lists(st.binary(min_size=32, max_size=32), max_size=6),
       size=st.integers(0, 2**60),
       has_sibling=st.booleans())
def test_tree_codec_roundtrip(name, children, size, has_sibling):
    from backuwup_tpu.wire import Tree, TreeKind, TreeMetadata

    tree = Tree(kind=TreeKind.FILE, name=name,
                metadata=TreeMetadata(size=size, mtime_ns=123, ctime_ns=456),
                children=list(children),
                next_sibling=(b"\x09" * 32 if has_sibling else None))
    encoded = tree.encode_bytes()
    back = Tree.decode_bytes(encoded)
    assert back.name == tree.name
    assert back.children == tree.children
    assert back.metadata.size == size
    assert back.next_sibling == tree.next_sibling
