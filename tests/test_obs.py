"""Observability plane: metrics registry, correlated traces, journal,
and the /metrics + /healthz exposition (docs/observability.md)."""

import asyncio
import json
import os
import random
import subprocess
import sys
import threading

import pytest

from backuwup_tpu import wire
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs import trace as obs_trace
from backuwup_tpu.obs.journal import Journal
from backuwup_tpu.obs.metrics import MetricError, Registry, log_buckets
from backuwup_tpu.ui.messenger import Messenger


@pytest.fixture(autouse=True)
def _isolate():
    """Zero the process registry and drop any installed journal so tests
    never see each other's series."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# --- registry ---------------------------------------------------------------

def test_counter_concurrent_exactness():
    reg = Registry()
    c = reg.counter("t_total", "x", ("worker",))

    def work(w):
        for _ in range(2000):
            c.inc(worker=w)
            c.inc(worker="shared")

    threads = [threading.Thread(target=work, args=(f"w{i}",))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(8):
        assert c.value(worker=f"w{i}") == 2000
    assert c.value(worker="shared") == 16000


def test_histogram_bucket_edges():
    reg = Registry()
    h = reg.histogram("t_seconds", "x", buckets=(0.001, 0.002, 0.004))
    # le semantics: a value exactly on a bound lands IN that bucket
    h.observe(0.001)
    h.observe(0.0015)
    h.observe(0.004)
    h.observe(5.0)  # past the last bound: +Inf only
    b = h.bucket_counts()
    assert b["0.001"] == 1
    assert b["0.002"] == 2
    assert b["0.004"] == 3
    assert b["+Inf"] == 4
    assert h.count_value() == 4
    assert h.sum_value() == pytest.approx(5.0065)


def test_log_buckets_geometry():
    assert log_buckets(0.001, 2.0, 4) == (0.001, 0.002, 0.004, 0.008)
    with pytest.raises(MetricError):
        Registry().histogram("t", buckets=())


def test_prometheus_render_golden():
    reg = Registry()
    reg.counter("app_requests_total", "Requests served",
                ("path",)).inc(3, path="/x")
    reg.gauge("app_depth", "Queue depth").set(2)
    h = reg.histogram("app_lat_seconds", "Latency", buckets=(0.5, 1.0))
    h.observe(0.25)
    h.observe(0.75)
    assert reg.render_prometheus() == (
        "# HELP app_depth Queue depth\n"
        "# TYPE app_depth gauge\n"
        "app_depth 2\n"
        "# HELP app_lat_seconds Latency\n"
        "# TYPE app_lat_seconds histogram\n"
        'app_lat_seconds_bucket{le="0.5"} 1\n'
        'app_lat_seconds_bucket{le="1"} 2\n'
        'app_lat_seconds_bucket{le="+Inf"} 2\n'
        "app_lat_seconds_sum 1\n"
        "app_lat_seconds_count 2\n"
        "# HELP app_requests_total Requests served\n"
        "# TYPE app_requests_total counter\n"
        'app_requests_total{path="/x"} 3\n')


def test_prometheus_render_escaping_golden():
    # exposition-format escaping pin: backslash FIRST, then newline and
    # quote — a value like '\n' must render '\\n', never '\\\\n' or a
    # literal line break that tears the sample line
    reg = Registry()
    reg.counter("app_weird_total", 'help with \\ and\nnewline',
                ("path",)).inc(1, path='a\\b"c\nd')
    assert reg.render_prometheus() == (
        "# HELP app_weird_total help with \\\\ and\\nnewline\n"
        "# TYPE app_weird_total counter\n"
        'app_weird_total{path="a\\\\b\\"c\\nd"} 1\n')
    # the escaped exposition must round-trip through a line-oriented
    # parser: exactly 3 lines, the sample line intact
    lines = reg.render_prometheus().splitlines()
    assert len(lines) == 3 and lines[2].endswith("} 1")


def test_family_conflicts():
    reg = Registry()
    c = reg.counter("t_total", "x", ("a",))
    assert reg.counter("t_total", "different help", ("a",)) is c
    with pytest.raises(MetricError):
        reg.histogram("t_total")  # type mismatch
    with pytest.raises(MetricError):
        reg.counter("t_total", "x", ("b",))  # labelnames mismatch


# --- journal ----------------------------------------------------------------

def test_journal_rotation_and_tail(tmp_path):
    j = Journal(tmp_path / "j.jsonl", max_bytes=600, keep=2)
    for i in range(60):
        j.emit("tick", n=i)
    j.close()
    assert j.rotations > 0
    assert (tmp_path / "j.jsonl.1").exists()
    # no generation beyond keep survives
    assert not (tmp_path / "j.jsonl.3").exists()
    tail = j.tail(20)
    assert len(tail) == 20
    # ordered across the rotation boundary, newest last
    assert [r["n"] for r in tail] == list(range(40, 60))
    assert all(r["kind"] == "tick" for r in tail)


def test_journal_rotation_under_concurrent_writers(tmp_path):
    # two writer threads race emit() across dozens of rotation
    # boundaries: every line must parse (no torn writes) and every
    # event must survive (no line lost to a mid-rotation rename)
    j = Journal(tmp_path / "j.jsonl", max_bytes=2000, keep=20)
    per_writer = 150
    barrier = threading.Barrier(2)

    def writer(tag):
        barrier.wait()
        for i in range(per_writer):
            j.emit("tick", w=tag, n=i)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    j.close()
    assert j.rotations > 2
    seen = {"a": [], "b": []}
    for path in j.files():
        for line in path.read_text(encoding="utf-8").splitlines():
            rec = json.loads(line)  # a torn line would raise here
            seen[rec["w"]].append(rec["n"])
    assert sorted(seen["a"]) == list(range(per_writer))
    assert sorted(seen["b"]) == list(range(per_writer))
    assert j.lines_written == 2 * per_writer


def test_journal_panic_dump(tmp_path):
    obs_journal.install(Journal(tmp_path / "j.jsonl"))
    obs_metrics.counter("t_panic_total", "x").inc(7)
    obs_journal.emit("status", event="before")
    path = obs_journal.panic("it broke")
    doc = json.loads(path.read_text())
    assert doc["message"] == "it broke"
    assert doc["metrics"]["t_panic_total"]["series"][0]["value"] == 7
    kinds = [r["kind"] for r in doc["journal_tail"]]
    assert "status" in kinds and "panic" in kinds


def test_journal_emit_without_install_is_noop():
    obs_journal.uninstall()
    obs_journal.emit("status", event="dropped")  # must not raise
    assert obs_journal.panic("nobody home") is None


# --- traces -----------------------------------------------------------------

def test_span_nesting_journals_one_trace(tmp_path):
    obs_journal.install(Journal(tmp_path / "j.jsonl"))
    with obs_trace.span("outer"):
        tid = obs_trace.current_trace_id()
        outer_sid = obs_trace.current_span_id()
        with obs_trace.span("inner"):
            assert obs_trace.current_trace_id() == tid
    recs = {r["name"]: r for r in obs_journal.get().tail(10)
            if r["kind"] == "span"}
    assert recs["inner"]["trace_id"] == recs["outer"]["trace_id"] == tid
    assert recs["inner"]["parent_id"] == outer_sid
    assert recs["outer"]["parent_id"] is None


def test_span_seconds_histogram_always_observes():
    obs_trace.enable(False)
    with obs_trace.span("obs_test.work"):
        pass
    h = obs_metrics.registry().get("bkw_span_seconds")
    assert h.count_value(name="obs_test.work") == 1
    # the flat BKW_TRACE table stays gated off (utils/tracing compat)
    assert "obs_test.work" not in obs_trace.report()


def test_clean_trace_id():
    assert obs_trace.clean_trace_id("deadbeef") == "deadbeef"
    assert obs_trace.clean_trace_id("A" * 8) is None
    assert obs_trace.clean_trace_id("g" * 8) is None
    assert obs_trace.clean_trace_id("0" * 33) is None
    assert obs_trace.clean_trace_id("") is None
    assert obs_trace.clean_trace_id(None) is None


def test_wire_trace_id_roundtrip():
    env = wire.EncapsulatedMsg(body=b"b" * 10, signature=b"s" * 64,
                               trace_id="deadbeefcafe0123")
    out = wire.EncapsulatedMsg.decode_bytes(env.encode_bytes())
    assert out.trace_id == "deadbeefcafe0123"
    # absent field (an old peer's frame) decodes as None
    bare = wire.EncapsulatedMsg(body=b"b" * 10, signature=b"s" * 64)
    assert wire.EncapsulatedMsg.decode_bytes(bare.encode_bytes()).trace_id \
        is None


# --- messenger --------------------------------------------------------------

def test_messenger_flushes_final_progress_on_finish():
    m = Messenger(debounce_s=3600.0)
    events = []
    m.subscribe(events.append)
    m.backup_started()
    m.progress(file="a.txt")  # first one passes the debounce gate
    m.progress(file="b.txt")  # debounced away
    m.backup_finished(b"\x01" * 32)
    kinds = [e.kind for e in events]
    assert kinds == ["backup_started", "progress", "progress",
                     "backup_finished"]
    final = events[-2].payload
    assert final["files_done"] == 2  # the debounced update was not lost
    assert final["running"] is False


def test_messenger_counts_and_logs_subscriber_errors(caplog):
    m = Messenger()
    good = []

    def bad(event):
        raise RuntimeError("boom")

    m.subscribe(bad)
    m.subscribe(good.append)
    with caplog.at_level("ERROR", logger="backuwup_tpu.ui.messenger"):
        for i in range(3):
            m.log(f"msg {i}")
    assert len(good) == 3  # a broken subscriber never starves the rest
    errs = obs_metrics.registry().get(
        "bkw_messenger_subscriber_errors_total")
    label = bad.__qualname__
    assert errs.value(subscriber=label) == 3
    logged = [r for r in caplog.records if label in r.getMessage()]
    assert len(logged) == 1  # first failure only


# --- exposition -------------------------------------------------------------

def test_server_metrics_and_healthz(tmp_path, loop):
    import aiohttp

    from backuwup_tpu.net.server import CoordinationServer

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "s.db"))
        port = await server.start()
        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                text = await resp.text()
            async with http.get(
                    f"http://127.0.0.1:{port}/healthz") as resp:
                assert resp.status == 200
                health = await resp.json()
        await server.stop()
        # the core catalog is advertised even on a fresh server
        for name in ("bkw_transfer_send_seconds", "bkw_audit_total",
                     "bkw_repair_rounds_total",
                     "bkw_matchmaking_queue_depth",
                     "bkw_server_requests_total"):
            assert f"# TYPE {name}" in text, name
        assert health["status"] == "ok"
        assert health["queue_depth"] == 0
        assert health["uptime_s"] >= 0

    loop.run_until_complete(asyncio.wait_for(run(), 30))


def test_client_server_trace_propagation(tmp_path, loop):
    from backuwup_tpu.crypto import KeyManager
    from backuwup_tpu.net.client import ServerClient
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.store import Store

    obs_journal.install(Journal(tmp_path / "j.jsonl"))

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "s.db"))
        port = await server.start()
        keys = KeyManager.from_secret(b"\x05" * 32)
        store = Store(tmp_path / "c")
        c = ServerClient(keys, store, addr=f"127.0.0.1:{port}")
        await c.register()
        await c.login()
        with obs_trace.span("test.op"):
            tid = obs_trace.current_trace_id()
            await c.backup_done(b"\x01" * 32)
        await c.close()
        store.close()
        await server.stop()
        return tid

    tid = loop.run_until_complete(asyncio.wait_for(run(), 30))
    spans = [r for r in obs_journal.get().tail(200) if r["kind"] == "span"]
    server_side = [r for r in spans
                   if r["name"] == "server/backups/done"
                   and r["trace_id"] == tid]
    assert server_side, "server handler span must join the client's trace"


def test_obs_runs_without_accelerator(tmp_path):
    """Tier-1 guard: the whole plane imports and instruments on a bare
    CPU process with no accelerator runtime."""
    prog = (
        "from backuwup_tpu.obs import journal, metrics, trace\n"
        "from backuwup_tpu.obs.journal import Journal\n"
        "journal.install(Journal(r'%s'))\n"
        "metrics.counter('g_total', 'x').inc()\n"
        "with trace.span('g.span'):\n"
        "    pass\n"
        "assert 'g_total 1' in metrics.registry().render_prometheus()\n"
        "assert journal.get().tail(5)[-1]['kind'] == 'span'\n"
        "print('GUARD_OK')\n" % (tmp_path / "g.jsonl"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "GUARD_OK" in out.stdout


# --- end-to-end trace join ---------------------------------------------------

def test_two_client_backup_trace_joins_peer_store(tmp_path, loop):
    """One backup's trace_id must join the sender's pack span to the
    receiving peer's store span across the p2p wire (the Dapper claim)."""
    import aiohttp

    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.ops.backend import CpuBackend
    from backuwup_tpu.ops.gear import CDCParams

    obs_journal.install(Journal(tmp_path / "j.jsonl"))
    rng = random.Random(7)
    for name in ("a_src", "b_src"):
        root = tmp_path / name
        (root / "sub").mkdir(parents=True)
        (root / "f.bin").write_bytes(rng.randbytes(200_000))
        (root / "sub" / "g.bin").write_bytes(rng.randbytes(80_000))

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"
        backend = CpuBackend(CDCParams.from_desired(4096))

        def make_app(name, **kw):
            return ClientApp(config_dir=tmp_path / name / "cfg",
                             data_dir=tmp_path / name / "data",
                             server_addr=addr, backend=backend, **kw)

        a = make_app("a", status_port=0)
        b = make_app("b")
        await a.start()
        await b.start()
        assert a.status_port  # ephemeral port resolved
        a.store.set_backup_path(str(tmp_path / "a_src"))
        b.store.set_backup_path(str(tmp_path / "b_src"))
        await asyncio.wait_for(asyncio.gather(a.backup(), b.backup()), 120)

        # the opt-in client status listener serves the same registry
        async with aiohttp.ClientSession() as http:
            url = f"http://127.0.0.1:{a.status_port}"
            async with http.get(url + "/metrics") as resp:
                text = await resp.text()
            async with http.get(url + "/healthz") as resp:
                health = await resp.json()
        assert 'bkw_backup_runs_total{outcome="ok"} 2' in text
        assert health["client_id"] == a.client_id.hex()

        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 180))

    spans = [r for r in obs_journal.get().tail(100_000)
             if r["kind"] == "span"]
    pack_traces = {r["trace_id"] for r in spans
                   if r["name"] == "engine.pack" and r["trace_id"]}
    store_traces = {r["trace_id"] for r in spans
                    if r["name"] == "receiver.store" and r["trace_id"]}
    assert pack_traces, "pack spans must journal"
    assert store_traces, "peer store spans must journal"
    joined = pack_traces & store_traces
    assert joined, (
        "a backup's trace_id must survive the p2p wire: "
        f"pack={pack_traces} store={store_traces}")
