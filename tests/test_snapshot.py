"""End-to-end snapshot slice: pack a tree -> restore byte-identical.

This is SURVEY.md §7's minimum slice (steps 1-5) without networking: the
chunk+hash pipeline, dedup, packfiles, tree building, and restore."""

import os
import random
from pathlib import Path

from backuwup_tpu import defaults
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.packer import DirPacker
from backuwup_tpu.snapshot.packfile import PackfileReader, PackfileWriter
from backuwup_tpu.snapshot.unpacker import DirUnpacker, fetch_full_tree

KEYS = KeyManager.from_secret(bytes(range(32)))
SMALL = CDCParams.from_desired(4096)


def _build_corpus(root: Path, rng: random.Random):
    (root / "docs").mkdir(parents=True)
    (root / "docs" / "deep" / "deeper").mkdir(parents=True)
    (root / "empty_dir").mkdir()
    files = {
        "readme.txt": b"hello backuwup\n",
        "empty.bin": b"",
        "docs/big.bin": rng.randbytes(300_000),
        "docs/deep/deeper/nested.dat": rng.randbytes(50_000),
        "docs/dup_a.bin": b"\xabsame content" * 4000,
        "docs/dup_b.bin": b"\xabsame content" * 4000,  # dedups against a
    }
    for rel, data in files.items():
        p = root / rel
        p.write_bytes(data)
        os.utime(p, ns=(1_600_000_000_000_000_000, 1_600_000_000_000_000_000))
    return files


def _make_engine(tmp_path, on_packfile_extra=None):
    index = BlobIndex(KEYS, tmp_path / "index")

    def on_packfile(pid, path, hashes, size):
        index.finalize_packfile(pid, hashes)
        if on_packfile_extra:
            on_packfile_extra(pid, path, hashes, size)

    writer = PackfileWriter(KEYS, tmp_path / "pack", on_packfile=on_packfile)
    packer = DirPacker(CpuBackend(SMALL), writer, index)
    reader = PackfileReader(KEYS, tmp_path / "pack")

    def resolve(h):
        pid = index.lookup(h)
        if pid is None:
            raise KeyError(bytes(h).hex())
        return reader.get_blob(pid, h)

    return packer, index, resolve


def test_pack_restore_round_trip(tmp_path, rng):
    src = tmp_path / "src"
    src.mkdir()
    files = _build_corpus(src, rng)
    packer, index, resolve = _make_engine(tmp_path)
    snapshot = packer.pack(src)
    assert len(snapshot) == 32
    assert packer.stats.files == len(files)

    dest = tmp_path / "restored"
    DirUnpacker(resolve).unpack(snapshot, dest)
    for rel, data in files.items():
        p = dest / rel
        assert p.read_bytes() == data, rel
        assert p.stat().st_mtime_ns == 1_600_000_000_000_000_000
    assert (dest / "empty_dir").is_dir()


def test_identical_content_dedups(tmp_path, rng):
    src = tmp_path / "src"
    src.mkdir()
    _build_corpus(src, rng)
    packer, _, _ = _make_engine(tmp_path)
    packer.pack(src)
    assert packer.stats.chunks_deduped >= 1  # dup_b dedups against dup_a


def test_incremental_repack_is_cheap(tmp_path, rng):
    """Re-running a backup against the persisted index re-packs ~nothing
    (checkpoint/resume semantics, SURVEY.md §5.4)."""
    src = tmp_path / "src"
    src.mkdir()
    _build_corpus(src, rng)
    packer, index, _ = _make_engine(tmp_path)
    snap1 = packer.pack(src)
    index.flush()
    bytes_before = packer.writer.bytes_written

    # second engine over the same on-disk state
    index2 = BlobIndex(KEYS, tmp_path / "index")
    index2.load()
    writer2 = PackfileWriter(
        KEYS, tmp_path / "pack",
        on_packfile=lambda pid, path, hashes, size:
        index2.finalize_packfile(pid, hashes))
    packer2 = DirPacker(CpuBackend(SMALL), writer2, index2)
    snap2 = packer2.pack(src)
    assert snap2 == snap1  # deterministic snapshot id
    assert writer2.bytes_written == 0  # everything deduped


def test_change_one_file_changes_root(tmp_path, rng):
    src = tmp_path / "src"
    src.mkdir()
    _build_corpus(src, rng)
    packer, index, _ = _make_engine(tmp_path)
    snap1 = packer.pack(src)
    (src / "readme.txt").write_bytes(b"changed!")
    snap2 = packer.pack(src)
    assert snap1 != snap2


def test_tree_split_chain(tmp_path, rng, monkeypatch):
    monkeypatch.setattr(defaults, "TREE_MAX_CHILDREN", 10)
    src = tmp_path / "src"
    src.mkdir()
    for i in range(37):
        (src / f"f{i:03d}.txt").write_bytes(f"file {i}".encode())
    packer, index, resolve = _make_engine(tmp_path)
    snapshot = packer.pack(src)
    root = fetch_full_tree(resolve, snapshot)
    assert len(root.children) == 37
    dest = tmp_path / "restored"
    DirUnpacker(resolve).unpack(snapshot, dest)
    assert len(list(dest.iterdir())) == 37
    assert (dest / "f036.txt").read_bytes() == b"file 36"


def test_streaming_manifest_matches_whole_file(rng):
    from backuwup_tpu.ops.backend import CpuBackend
    import io
    backend = CpuBackend(SMALL)
    data = rng.randbytes(150_000)
    whole = backend.manifest(data)
    f = io.BytesIO(data)
    emitted = []
    streamed = backend.manifest_stream(
        f.read, segment_bytes=32768,
        emit=lambda ref, chunk: emitted.append((ref.offset, chunk)))
    assert streamed == whole
    for off, chunk in emitted:
        assert data[off:off + len(chunk)] == chunk


def test_large_file_takes_streaming_path(tmp_path, rng):
    src = tmp_path / "src"
    src.mkdir()
    big = rng.randbytes(200_000)
    (src / "big.bin").write_bytes(big)
    packer, index, resolve = _make_engine(tmp_path)
    packer.batch_bytes = 50_000  # force streaming for the 200 KB file
    snapshot = packer.pack(src)
    dest = tmp_path / "restored"
    DirUnpacker(resolve).unpack(snapshot, dest)
    assert (dest / "big.bin").read_bytes() == big

    # snapshot id identical to the non-streaming engine's
    packer2, _, _ = _make_engine(tmp_path / "other")
    (tmp_path / "other").mkdir(exist_ok=True)
    snap2 = packer2.pack(src)
    assert snap2 == snapshot
