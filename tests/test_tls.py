"""TLS control plane (wss/https) + the typed error taxonomy end-to-end.

The reference is TLS-by-default with a ``USE_TLS`` off-switch
(client/src/defaults.rs:6-7, net_server/requests.rs:246-258); here a
self-signed certificate is generated on the fly, the coordination server
serves HTTPS/WSS, and a client with ``TLS_CA_FILE`` pinned to the cert
registers, logs in, opens the push channel, and receives typed errors.
"""

import asyncio
import datetime

import pytest

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.client import (
    BadRequest,
    ClientNotFound,
    DestinationUnreachable,
    NoBackups,
    ServerClient,
    Unauthorized,
)
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.store import Store


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def tls_files(tmp_path):
    """Self-signed localhost certificate via the cryptography package."""
    pytest.importorskip(
        "cryptography", reason="x509 needs the real cryptography package")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress").ip_address(
                    "127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_file = tmp_path / "cert.pem"
    key_file = tmp_path / "key.pem"
    cert_file.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_file.write_bytes(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return cert_file, key_file


def test_tls_control_plane_roundtrip(tmp_path, tls_files, loop, monkeypatch):
    cert_file, key_file = tls_files
    monkeypatch.setenv("TLS_CA_FILE", str(cert_file))

    async def run():
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_file, key_file)
        server = CoordinationServer()
        port = await server.start(ssl_context=ctx)

        keys = KeyManager.from_secret(b"\x31" * 32)
        store = Store(tmp_path / "cfg")
        client = ServerClient(keys, store, addr=f"127.0.0.1:{port}",
                              tls=True)
        await client.register()
        token = await client.login()
        assert len(token) == 16
        # wss push channel comes up over the same TLS session
        client.start_ws()
        await asyncio.wait_for(client.ws_connected.wait(), 10)
        assert server.connections.is_online(keys.client_id)
        # typed error over TLS
        with pytest.raises(NoBackups):
            await client.backup_restore()
        await client.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_error_taxonomy_distinguished(tmp_path, loop):
    """The client raises a distinct exception per wire.ErrorKind
    (server_message.rs:43-54 parity)."""

    async def run():
        server = CoordinationServer()
        port = await server.start()

        def client(name):
            keys = KeyManager.from_secret(bytes([len(name)]) * 32)
            return ServerClient(keys, Store(tmp_path / name),
                                addr=f"127.0.0.1:{port}", tls=False)

        a = client("aa")
        # ClientNotFound: login before registering
        with pytest.raises(ClientNotFound):
            await a.login()
        await a.register()
        await a.login()
        # NoBackups: restore with no snapshot recorded
        with pytest.raises(NoBackups):
            await a.backup_restore()
        # BadRequest: oversized storage request
        with pytest.raises(BadRequest):
            await a.backup_storage_request(17 << 30)
        # DestinationUnreachable: p2p toward an offline client
        with pytest.raises(DestinationUnreachable):
            await a.p2p_connection_begin(b"\x77" * 32, b"\x01" * 16)
        # Unauthorized: raw call with a bogus token (bypass re-login)
        from backuwup_tpu import wire
        with pytest.raises(Unauthorized):
            await a._post("/backups/done", wire.BackupDone(
                session_token=b"\x00" * 16, snapshot_hash=b"\x01" * 32))
        await a.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_reregistration_after_phrase_recovery(tmp_path, loop):
    """A recovered identity registering again gets ClientExists (409) and
    register() treats it as success (identity.rs:46-69 recovery path)."""

    async def run():
        server = CoordinationServer()
        port = await server.start()
        keys = KeyManager.from_secret(b"\x55" * 32)
        a = ServerClient(keys, Store(tmp_path / "a"),
                         addr=f"127.0.0.1:{port}", tls=False)
        await a.register()
        # same identity, fresh store (the disaster-recovery scenario)
        b = ServerClient(KeyManager.from_secret(b"\x55" * 32),
                         Store(tmp_path / "b"),
                         addr=f"127.0.0.1:{port}", tls=False)
        await b.register()  # ClientExists swallowed
        token = await b.login()
        assert len(token) == 16
        await a.close()
        await b.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_full_backup_cycle_over_tls(tmp_path, tls_files, loop, monkeypatch):
    """The complete two-client backup->match->transfer flow with the
    control plane on https/wss end to end (data plane stays peer WS, as
    in the reference)."""
    import random

    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.ops.backend import CpuBackend
    from backuwup_tpu.ops.gear import CDCParams

    cert_file, key_file = tls_files
    monkeypatch.setenv("TLS_CA_FILE", str(cert_file))
    monkeypatch.setenv("USE_TLS", "1")
    rng = random.Random(31)
    for name in ("a", "b"):
        d = tmp_path / f"{name}_src"
        d.mkdir()
        (d / "f.bin").write_bytes(rng.randbytes(120_000))

    async def run():
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_file, key_file)
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start(ssl_context=ctx)

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=CpuBackend(CDCParams.from_desired(4096)))
            app.store.set_backup_path(str(tmp_path / f"{name}_src"))
            return app

        a, b = make_app("a"), make_app("b")
        await a.start()
        await b.start()
        snap_a, snap_b = await asyncio.wait_for(
            asyncio.gather(a.backup(), b.backup()), 120)
        assert len(snap_a) == 32 and len(snap_b) == 32
        assert server.db.get_latest_client_snapshot(a.client_id) == snap_a
        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 180))
