"""Concurrent transfer plane (net/transfer.py + engine fan-out).

Deterministic concurrency coverage driven by the PR-2 fault plane's
latency hook: injected per-peer latency makes overlap *measurable*
(a stripe completes in ~max(shard times), not the sum) and
``kill_after`` makes mid-flight peer death exact (only that shard's
transfer fails; the siblings ack to their own peers).  Plus unit
coverage of the scheduler invariants (per-peer ordering, in-flight byte
cap, failure isolation) and the pipelined packfile seal path.
"""

import asyncio
import os
import time

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine, Orchestrator
from backuwup_tpu.net.p2p import P2PError
from backuwup_tpu.net.transfer import TransferScheduler
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.snapshot.packfile import (
    DirtyPackfileError,
    PackfileError,
    PackfileReader,
    PackfileWriter,
)
from backuwup_tpu.store import Store
from backuwup_tpu.utils import faults

pytestmark = pytest.mark.concurrency


@pytest.fixture
def plane():
    p = faults.install(faults.FaultPlane(seed=77))
    yield p
    faults.uninstall()


@pytest.fixture
def engine(tmp_path):
    keys = KeyManager.generate()
    store = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    eng = Engine(keys, store, server=None, node=None,
                 backend=CpuBackend(CDCParams.from_desired(4096)))
    yield eng
    store.close()


class FaultedTransport:
    """Fake transport that consults the fault plane exactly where the
    real Transport.send_data does — latency sleeps and peer death flow
    through the identical PR-2 hook."""

    def __init__(self, peer_id: bytes):
        self.peer_id = bytes(peer_id)
        self.sent = []

    async def send_data(self, data, kind, file_id):
        if faults.PLANE is not None:
            action = await faults.PLANE.on_send(self.peer_id)
            if action == faults.ACT_DROP:
                raise P2PError("injected: connection dropped")
        self.sent.append((kind, bytes(file_id), len(data)))

    async def send_file(self, data, kind, file_id, *, resume=True,
                        throughput_bps=0.0, progress=None):
        # sub-chunk payloads ride the legacy frame, like the real
        # Transport.send_file
        await self.send_data(data, kind, file_id)

    async def close(self):
        pass


def _mk_packfile(engine, pid: bytes, payload: bytes):
    d = engine._pack_dir() / pid.hex()[:2]
    d.mkdir(parents=True, exist_ok=True)
    path = d / pid.hex()
    path.write_bytes(payload)
    return path


def _run(coro, timeout=30):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


# --- stripe fan-out under injected latency ---------------------------------

def test_stripe_wall_clock_bounded_by_slowest_shard(engine, plane):
    """6 shards to 6 peers, each with 0.5 s injected latency: the serial
    loop would take >= 3.0 s; the concurrent plane is bounded by the
    slowest single shard (one latency window plus bounded overhead)."""
    plane.latency = 1.0  # every send draws the latency sleep
    plane.latency_s = 0.5
    pid = b"\x42" * 12
    path = _mk_packfile(engine, pid, b"x" * 4096)
    peers = [bytes([i + 1]) * 32 for i in range(6)]
    conns = [(FaultedTransport(p), p, 1 << 30) for p in peers]

    async def fake_conns(orch, need, exclude, min_free):
        return conns[:need]

    engine._get_stripe_connections = fake_conns
    sched = TransferScheduler()

    async def go():
        t0 = time.monotonic()
        leftover, placed = await engine._send_stripes(
            Orchestrator(), sched, [(pid, path, 4096)])
        return time.monotonic() - t0, leftover, placed

    wall, leftover, placed = _run(go())
    assert leftover == [] and placed == 4096
    assert not path.exists()  # deleted only after all k+m acks
    assert [len(t.sent) for t, _, _ in conns] == [1] * 6
    assert len(engine.store.shards_for_packfile(pid)) == 6
    # max-not-sum: one 0.5 s window (+ encode/challenge-table overhead),
    # never the 6 x 0.5 s a serial send would pay
    assert wall < 3 * plane.latency_s, f"shards did not overlap: {wall:.2f}s"


def test_midflight_peer_death_fails_only_that_shard(engine, plane):
    pid = b"\x43" * 12
    payload = b"y" * 4096
    path = _mk_packfile(engine, pid, payload)
    peers = [bytes([i + 0x10]) * 32 for i in range(6)]
    dead = peers[3]
    plane.kill_after(dead, 0)  # the very next send finds the peer dead
    conns = [(FaultedTransport(p), p, 1 << 30) for p in peers]

    async def fake_conns(orch, need, exclude, min_free):
        # mirror P2PNode.connect: dead peers accept no dial
        return [c for c in conns
                if c[1] not in exclude and not faults.PLANE.is_dead(c[1])
                ][:need]

    engine._get_stripe_connections = fake_conns
    sched = TransferScheduler()

    leftover, placed = _run(engine._send_stripes(
        Orchestrator(), sched, [(pid, path, 4096)]))
    # only the dead peer's shard failed; the stripe is partial and retried
    assert leftover == [(pid, path, 4096)] and placed == 0
    assert path.exists()
    placements = engine.store.shards_for_packfile(pid)
    assert len(placements) == 5
    assert all(bytes(p) != dead for p, _ in placements)
    live = [t for t, p, _ in conns if p != dead]
    assert [len(t.sent) for t in live] == [1] * 5

    # next tick: a replacement peer takes the one missing shard and the
    # stripe completes — the 5 placed shards are not re-sent
    spare = b"\x77" * 32
    conns.append((FaultedTransport(spare), spare, 1 << 30))
    leftover2, placed2 = _run(engine._send_stripes(
        Orchestrator(), sched, leftover))
    assert leftover2 == [] and placed2 == 4096
    assert not path.exists()
    assert len(engine.store.shards_for_packfile(pid)) == 6
    assert [len(t.sent) for t in live] == [1] * 5  # unchanged
    assert len(conns[-1][0].sent) == 1


def test_stripe_read_failure_requeues_for_retry(engine, plane):
    """Satellite regression: a packfile whose file vanished mid-tick must
    land back in leftover (and be logged), not silently skip the run."""
    logged = []

    class Msgr:
        def log(self, msg):
            logged.append(msg)

    engine.messenger = Msgr()
    pid = b"\x44" * 12
    path = engine._pack_dir() / pid.hex()[:2] / pid.hex()  # never created
    peers = [bytes([i + 0x30]) * 32 for i in range(6)]
    conns = [(FaultedTransport(p), p, 1 << 30) for p in peers]

    async def fake_conns(orch, need, exclude, min_free):
        return conns[:need]

    engine._get_stripe_connections = fake_conns
    leftover, placed = _run(engine._send_stripes(
        Orchestrator(), TransferScheduler(), [(pid, path, 4096)]))
    assert leftover == [(pid, path, 4096)] and placed == 0
    assert any("read failed" in m for m in logged)


# --- whole-file multi-peer fan-out -----------------------------------------

def test_whole_files_fan_out_across_peers(engine, monkeypatch):
    monkeypatch.setattr(defaults, "RS_M", 0)  # striping off: legacy path
    pids = [bytes([0x50 + i]) * 12 for i in range(3)]
    paths = [_mk_packfile(engine, pid, b"z" * 1000) for pid in pids]
    peer_a, peer_b = b"\x05" * 32, b"\x06" * 32
    ta, tb = FaultedTransport(peer_a), FaultedTransport(peer_b)

    async def fake_get_peer(orch, estimate, fulfilled, last_request,
                            min_free=1):
        return ta, peer_a, 10_000

    async def fake_conns(orch, need, exclude, min_free):
        assert peer_a in exclude  # the first peer is never doubled up
        return [(tb, peer_b, 10_000)]

    engine._get_peer_connection = fake_get_peer
    engine._get_stripe_connections = fake_conns
    orch = Orchestrator()
    orch.packing_completed = True
    orch.buffer_bytes = 3000
    _run(engine._send_loop(orch, 0))
    assert len(ta.sent) + len(tb.sent) == 3
    assert len(ta.sent) >= 1 and len(tb.sent) >= 1  # genuinely fanned out
    assert not any(p.exists() for p in paths)
    assert orch.bytes_sent == 3000
    for pid in pids:
        assert engine.store.shards_for_packfile(pid) != []


# --- scheduler invariants ---------------------------------------------------

def test_scheduler_per_peer_order_cap_and_isolation():
    async def go():
        sched = TransferScheduler(max_inflight_bytes=100, max_transfers=2)
        order = []
        peak = {"count": 0, "bytes": 0}

        def job(name, fail=False):
            async def send():
                peak["count"] = max(peak["count"], sched.inflight_count)
                peak["bytes"] = max(peak["bytes"], sched.inflight_bytes)
                await asyncio.sleep(0)
                order.append(name)
                if fail:
                    raise P2PError("boom")
            return send

        pa, pb = b"a" * 32, b"b" * 32
        tasks = [
            sched.submit(pa, 40, job("a1")),
            sched.submit(pa, 40, job("a2", fail=True)),
            sched.submit(pa, 40, job("a3")),
            sched.submit(pb, 60, job("b1")),
        ]
        results = await sched.gather(tasks)
        return sched, order, results, peak

    sched, order, results, peak = _run(go())
    # per-peer FIFO: a1 < a2 < a3 even though a2 failed mid-flight
    assert [o for o in order if o.startswith("a")] == ["a1", "a2", "a3"]
    assert [r.ok for r in results] == [True, False, True, True]
    assert isinstance(results[1].error, P2PError)  # isolated, not raised
    assert peak["count"] <= 2 and peak["bytes"] <= 100
    assert sched.completed == 3 and sched.failed == 1
    assert sched.inflight_count == 0 and sched.inflight_bytes == 0


def test_scheduler_admits_oversize_transfer_when_empty():
    async def go():
        sched = TransferScheduler(max_inflight_bytes=10, max_transfers=4)
        ran = []

        async def send():
            ran.append(True)

        r = await sched.submit(b"p" * 32, 1000, send)
        return r, ran

    r, ran = _run(go())
    assert r.ok and ran == [True]  # bigger than the cap, still admitted


def test_scheduler_emits_transfer_telemetry():
    events = []

    class Msgr:
        def transfer(self, peer, outcome, **kw):
            events.append((peer, outcome, kw))

    async def go():
        sched = TransferScheduler(messenger=Msgr())

        async def send():
            pass

        await sched.submit(b"\xaa" * 32, 123, send, label="pack:test")
        return sched

    sched = _run(go())
    assert len(events) == 1
    peer, outcome, kw = events[0]
    assert outcome == "sent" and kw["size"] == 123
    assert kw["label"] == "pack:test"
    assert sched.bytes_sent == 123


# --- pipelined packfile seal -------------------------------------------------

def _blob(data: bytes) -> wire.Blob:
    return wire.Blob(hash=blake3_hash(data), kind=wire.BlobKind.FILE_CHUNK,
                     data=data)


def test_pipelined_writer_parity_with_synchronous(tmp_path, monkeypatch):
    """seal_workers>0 must produce readable packfiles holding exactly the
    same blobs, splitting on the target size like the synchronous path."""
    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 64 * 1024)
    keys = KeyManager.generate()
    written = []
    writer = PackfileWriter(
        keys, tmp_path / "pack", seal_workers=2,
        on_packfile=lambda pid, path, hashes, size:
            written.append((bytes(pid), list(hashes))))
    blobs = [os.urandom(20_000) for _ in range(20)]
    for data in blobs:
        writer.add_blob(_blob(data))
    writer.flush()
    writer.close()
    assert len(written) >= 2  # target-size splits happened in the pipeline
    reader = PackfileReader(keys, tmp_path / "pack")
    got = {}
    for pid, hashes in written:
        for h in hashes:
            got[bytes(h)] = reader.get_blob(pid, h).data
    assert len(got) == len(blobs)
    for data in blobs:
        assert got[blake3_hash(data)] == data


def test_pipelined_writer_enforces_hard_cap(tmp_path, monkeypatch):
    """The cap check moves to the writer thread (post-seal, actual
    ciphertext sizes) but still fires before anything hits disk."""
    monkeypatch.setattr(defaults, "PACKFILE_MAX_SIZE", 4 * 1024)
    keys = KeyManager.generate()
    writer = PackfileWriter(keys, tmp_path / "pack", seal_workers=1)
    try:
        writer.add_blob(_blob(os.urandom(64 * 1024)))  # incompressible
        with pytest.raises(PackfileError):
            writer.flush()
        assert not list((tmp_path / "pack").rglob("*")) or not [
            p for p in (tmp_path / "pack").rglob("*") if p.is_file()]
    finally:
        writer.shutdown()


def test_pipelined_writer_dirty_close_raises(tmp_path):
    keys = KeyManager.generate()
    writer = PackfileWriter(keys, tmp_path / "pack", seal_workers=1)
    writer.add_blob(_blob(b"q" * 100))
    with pytest.raises(DirtyPackfileError):
        writer.close()
    writer.flush()
    writer.close()  # clean after flush
