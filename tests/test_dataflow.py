"""Streaming dataflow backup engine tests (docs/dataflow.md).

The backup path is one backpressured streaming dataflow: the packer's
chunk stream feeds seal workers through bounded queues and sealed
packfiles enter transfer admission the moment they commit.  These tests
pin the load-bearing properties:

* backpressure — a deliberately slow wire (fault-plane latency) must
  bound the local packfile buffer at its cap and stall the packer
  WITHOUT deadlocking; the run still completes and drains;
* event-driven wakeup — the seal callback's event wakes the send loop;
  the retired ``send_idle`` poll never fires during a streaming backup;
* crash drain — an injected crash mid-pack tears the send loop down
  cleanly, ``recover()`` reconciles the debris, and a re-backup works;
* phased/stream parity — ``BKW_BACKUP_PHASED=1`` (the sum(stage)
  baseline) and the streaming default produce the SAME snapshot id:
  lag-bounded partial emission is byte-invisible in the snapshot.
"""

import asyncio
import contextlib
import random
from pathlib import Path

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.app import ClientApp
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.utils import faults
from backuwup_tpu.utils import retry

pytestmark = pytest.mark.dataflow

SMALL = CDCParams.from_desired(4096)


def _corpus(root: Path, seed: int = 31, files: int = 24,
            lo: int = 8 << 10, hi: int = 32 << 10) -> int:
    rng = random.Random(seed)
    (root / "sub").mkdir(parents=True, exist_ok=True)
    written = 0
    for i in range(files):
        n = rng.randint(lo, hi)
        (root / ("sub" if i % 3 else ".") / f"f{i}").write_bytes(
            rng.randbytes(n))
        written += n
    return written


@contextlib.asynccontextmanager
async def _universe(base: Path, src: Path, tag: str, peers: int = 2):
    """Coordination server + source client ``a`` + ``peers`` holders with
    pre-negotiated storage (no matchmaking dance — these tests exercise
    the dataflow, not the economy)."""
    server = CoordinationServer(db_path=str(base / f"server_{tag}.db"))
    port = await server.start()

    def mk(name):
        app = ClientApp(config_dir=base / tag / name / "cfg",
                        data_dir=base / tag / name / "data",
                        server_addr=f"127.0.0.1:{port}",
                        backend=CpuBackend(SMALL))
        app.store.set_backup_path(str(src))
        return app

    a = mk("a")
    holders = [mk(f"h{i}") for i in range(peers)]
    apps = [a] + holders
    try:
        for app in apps:
            await app.start()
            app._audit_task.cancel()
        a.engine.auto_repair = False
        amt = 64 << 20
        for h in holders:
            a.store.add_peer_negotiated(h.client_id, amt)
            h.store.add_peer_negotiated(a.client_id, amt)
            server.db.save_storage_negotiated(
                bytes(a.client_id), bytes(h.client_id), amt)
        yield a
    finally:
        for app in apps:
            with contextlib.suppress(Exception):
                await app.stop()
        await server.stop()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_backpressure_bounds_buffer_and_drains(tmp_path, loop, monkeypatch):
    """Slow wire + tiny local buffer cap: the send loop must pause the
    packer when the sealed-but-unsent buffer crosses the cap, the buffer
    must stay bounded (cap + bounded emission slack), and the run must
    complete and drain — stalled upstream, no deadlock."""
    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 32 << 10)
    monkeypatch.setattr(defaults, "PACKFILE_LOCAL_BUFFER_LIMIT", 64 << 10)
    monkeypatch.setattr(defaults, "PACKFILE_RESUME_THRESHOLD", 16 << 10)
    src = tmp_path / "src"
    src.mkdir()
    _corpus(src, files=32)

    async def run():
        # ONE holder and a genuinely slow wire: the single send lane
        # must fall far behind the packer or the cap is never tested
        faults.install(faults.FaultPlane(seed=31, latency=1.0,
                                         latency_s=0.08))
        try:
            async with _universe(tmp_path, src, "bp", peers=1) as a:
                samples = []
                paused_seen = []

                async def sample():
                    while True:
                        orch = a.engine.orchestrator
                        samples.append(orch.buffer_bytes)
                        paused_seen.append(orch.paused)
                        await asyncio.sleep(0.005)

                sampler = asyncio.create_task(sample())
                try:
                    snap = await asyncio.wait_for(a.backup(), 120)
                finally:
                    sampler.cancel()
                    with contextlib.suppress(asyncio.CancelledError):
                        await sampler
                assert len(snap) == 32
                # drained: nothing sealed is left local
                assert a.engine._unsent_packfiles() == []
                # the cap held: cap + seal-pipeline slack (the queued
                # seal workers may each commit one more packfile after
                # the pause flag flips — that emission lag is bounded
                # by the seal queue, docs/dataflow.md)
                slack = (defaults.PACK_SEAL_QUEUE_PACKFILES
                         + defaults.PACK_SEAL_WORKERS + 1) \
                    * defaults.PACKFILE_TARGET_SIZE
                assert max(samples) <= \
                    defaults.PACKFILE_LOCAL_BUFFER_LIMIT + slack
                # backpressure actually engaged on this corpus
                assert any(paused_seen)
        finally:
            faults.uninstall()

    loop.run_until_complete(asyncio.wait_for(run(), 150))


def test_streaming_send_loop_is_event_driven_not_polled(tmp_path, loop):
    """The seal callback's event wakes the send loop; the old
    fixed-interval ``send_idle`` poll must fire zero times during a
    streaming backup."""
    src = tmp_path / "src"
    src.mkdir()
    _corpus(src, files=12)

    async def run():
        async with _universe(tmp_path, src, "ev") as a:
            before = retry._ATTEMPTS.value(policy="send_idle")
            snap = await asyncio.wait_for(a.backup(), 120)
            assert len(snap) == 32
            assert retry._ATTEMPTS.value(policy="send_idle") == before
        return None

    loop.run_until_complete(asyncio.wait_for(run(), 150))


def test_crash_mid_pack_drains_cleanly_then_recovers(tmp_path, loop,
                                                     monkeypatch):
    """An armed ``pack.seal.pre`` crash mid-stream must propagate out of
    ``backup()`` promptly (the send loop is torn down, not left spinning
    against a dead backup); ``recover()`` reconciles the debris and a
    re-backup over the same tree succeeds and drains."""
    # small packfiles so the corpus seals several times — the armed
    # index below must actually be reached mid-stream
    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 32 << 10)
    src = tmp_path / "src"
    src.mkdir()
    _corpus(src, files=16)

    async def run():
        plane = faults.install(faults.FaultPlane(seed=31))
        # not the first seal: let the dataflow actually stream a bit so
        # the teardown path runs with transfers in flight
        plane.arm_crash("pack.seal.pre", 2)
        try:
            async with _universe(tmp_path, src, "crash") as a:
                with pytest.raises(faults.CrashInjected):
                    await asyncio.wait_for(a.backup(), 120)
                assert a.engine.orchestrator.failed
                rep = await a.engine.recover()
                assert rep is a.engine.last_recovery
                snap = await asyncio.wait_for(a.backup(), 120)
                assert len(snap) == 32
                assert a.engine._unsent_packfiles() == []
        finally:
            faults.uninstall()

    loop.run_until_complete(asyncio.wait_for(run(), 200))


def test_phased_and_stream_snapshots_identical(tmp_path, loop, monkeypatch):
    """BKW_BACKUP_PHASED=1 (send starts only after the full pack) and
    the streaming default must produce the same content-addressed
    snapshot: partial-packfile emission changes packfile boundaries on
    the wire, never snapshot bytes."""
    # small packfiles so both legs seal multiple times and the legs'
    # packfile boundaries can actually differ
    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 32 << 10)
    src = tmp_path / "src"
    src.mkdir()
    _corpus(src, files=16)

    async def one(tag: str, phased: bool):
        if phased:
            monkeypatch.setenv("BKW_BACKUP_PHASED", "1")
        else:
            monkeypatch.delenv("BKW_BACKUP_PHASED", raising=False)
        async with _universe(tmp_path, src, tag) as a:
            snap = await asyncio.wait_for(a.backup(), 120)
            mode = a.engine.last_overlap["mode"]
            assert mode == ("phased" if phased else "stream")
            return bytes(snap)

    async def run():
        return await one("phased", True), await one("stream", False)

    snap_p, snap_s = loop.run_until_complete(asyncio.wait_for(run(), 300))
    assert snap_p == snap_s
