"""bkwlint toolkit tests: per-rule fixtures, baseline semantics, CLI
contract, and the repo-wide tier-1 gate.

Each rule gets a positive fixture (a tiny package written into
``tmp_path`` that MUST fire) and a negative twin (the same shape with
the invariant honored, which MUST stay silent) — so the gate cannot rot
into a linter that flags nothing.
"""

import io
import json
from pathlib import Path

import pytest

import backuwup_tpu
from backuwup_tpu.analysis import (BaselineError, LintConfig, RULE_IDS,
                                   apply_baseline, collect_findings,
                                   load_baseline, load_graph,
                                   load_package, run_lint,
                                   static_crash_sites, build_graph)
from backuwup_tpu.analysis.cli import main as cli_main

REPO = Path(backuwup_tpu.__file__).resolve().parent.parent


def _mk_pkg(tmp_path, files):
    """Write ``files`` (rel -> source) as package ``pkg`` under tmp."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        init = p.parent / "__init__.py"
        if not init.exists():
            init.write_text("")
    if not (root / "__init__.py").exists():
        (root / "__init__.py").write_text("")
    return root


def _lint(root, rules, doc_path=None, baseline_path=None):
    cfg = LintConfig(package_root=root, doc_path=doc_path,
                     baseline_path=baseline_path, rules=set(rules))
    return run_lint(cfg)


# --- BKW001: blocking I/O reachable from async ------------------------------


def test_bkw001_flags_blocking_reachable_through_sync_helper(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import time\n"
        "def helper():\n"
        "    time.sleep(1)\n"
        "async def serve():\n"
        "    helper()\n")})
    report = _lint(root, {"BKW001"})
    assert len(report.findings) == 1
    f = report.findings[0]
    assert f.rule == "BKW001" and "time.sleep" in f.message
    assert "serve" in f.message and "helper" in f.message


def test_bkw001_executor_seam_and_nested_defs_are_off_the_loop(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import asyncio, time\n"
        "class Engine:\n"
        "    @staticmethod\n"
        "    async def _blocking(fn, *args):\n"
        "        loop = asyncio.get_running_loop()\n"
        "        return await loop.run_in_executor(None, fn, *args)\n"
        "    def commit(self):\n"
        "        time.sleep(1)\n"
        "    async def serve(self):\n"
        "        await self._blocking(self.commit)\n"
        "        def pack_thread():\n"
        "            time.sleep(2)\n"
        "        loop = asyncio.get_running_loop()\n"
        "        await loop.run_in_executor(None, pack_thread)\n")})
    assert _lint(root, {"BKW001"}).findings == []


def test_bkw001_sqlite_and_alias_normalization(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import sqlite3 as sq\n"
        "async def serve():\n"
        "    sq.connect(':memory:')\n")})
    report = _lint(root, {"BKW001"})
    assert len(report.findings) == 1
    assert "sqlite3" in report.findings[0].message


def test_bkw001_loop_scheduled_callback_is_a_root(tmp_path):
    # a sync callable handed to call_soon_threadsafe runs ON the loop
    # thread — blocking work inside it must fire even though no async
    # body ever calls it
    root = _mk_pkg(tmp_path, {"a.py": (
        "import asyncio, time\n"
        "def wake():\n"
        "    time.sleep(1)\n"
        "def writer_thread(loop):\n"
        "    loop.call_soon_threadsafe(wake)\n")})
    report = _lint(root, {"BKW001"})
    assert len(report.findings) == 1
    f = report.findings[0]
    assert "time.sleep" in f.message and "wake" in f.message
    assert "call_soon_threadsafe" in f.message


def test_bkw001_event_setting_callback_and_done_callback(tmp_path):
    # the dataflow wakeup shape: a callback that only sets an event is
    # clean, and add_done_callback targets are scanned the same way
    root = _mk_pkg(tmp_path, {"a.py": (
        "import asyncio, time\n"
        "class Orch:\n"
        "    def __init__(self):\n"
        "        self.ev = asyncio.Event()\n"
        "    def notify(self):\n"
        "        self.ev.set()\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self.orch = Orch()\n"
        "    def writer_thread(self, loop):\n"
        "        loop.call_soon_threadsafe(self.orch.notify)\n"
        "def log_done(fut):\n"
        "    time.sleep(1)\n"
        "async def serve():\n"
        "    fut = asyncio.get_running_loop().create_future()\n"
        "    fut.add_done_callback(log_done)\n")})
    report = _lint(root, {"BKW001"})
    assert len(report.findings) == 1
    f = report.findings[0]
    assert "log_done" in f.message and "add_done_callback" in f.message


# --- BKW002: lock held across await -----------------------------------------


def test_bkw002_flags_await_under_threading_lock(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import threading, asyncio\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    async def go(self):\n"
        "        with self._lock:\n"
        "            await asyncio.sleep(0)\n")})
    report = _lint(root, {"BKW002"})
    assert len(report.findings) == 1
    f = report.findings[0]
    assert f.severity == "error" and "threading.Lock" in f.message


def test_bkw002_silent_without_await_or_with_asyncio_lock(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import threading, asyncio\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._alock = asyncio.Lock()\n"
        "    async def sync_crit(self):\n"
        "        with self._lock:\n"
        "            x = 1\n"
        "        await asyncio.sleep(0)\n"
        "    async def async_crit(self):\n"
        "        async with self._alock:\n"
        "            await asyncio.sleep(0)\n")})
    assert _lint(root, {"BKW002"}).findings == []


def test_bkw002_lock_like_unresolved_name_is_warning(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": (
        "import asyncio\n"
        "async def go(lock):\n"
        "    with lock:\n"
        "        await asyncio.sleep(0)\n")})
    report = _lint(root, {"BKW002"})
    assert len(report.findings) == 1
    assert report.findings[0].severity == "warning"


# --- BKW003: crash-seam coverage --------------------------------------------

_FAULTS_STUB = (
    "CRASH_SITES = set()\n"
    "def register_crash_site(site):\n"
    "    CRASH_SITES.add(site)\n"
    "    return site\n"
    "def crashpoint(site):\n"
    "    pass\n")


def test_bkw003_uncovered_commit_and_dead_site(tmp_path):
    root = _mk_pkg(tmp_path, {
        "utils/faults.py": _FAULTS_STUB,
        "utils/durable.py": "def commit_replace(p, b):\n    pass\n",
        "a.py": (
            "from .utils import durable, faults\n"
            "_CP = faults.register_crash_site('a.never_called')\n"
            "def commit(p, b):\n"
            "    durable.commit_replace(p, b)\n")})
    report = _lint(root, {"BKW003"})
    anchors = {f.anchor for f in report.findings}
    assert "seam:commit:durable.commit_replace" in anchors
    assert "dead-site:a.never_called" in anchors


def test_bkw003_lexical_callee_and_caller_coverage(tmp_path):
    root = _mk_pkg(tmp_path, {
        "utils/faults.py": _FAULTS_STUB,
        "utils/durable.py": "def commit_replace(p, b):\n    pass\n",
        "a.py": (
            "from .utils import durable, faults\n"
            "_CP = faults.register_crash_site('a.commit')\n"
            "_CP2 = faults.register_crash_site('a.append')\n"
            "class Index:\n"
            "    def save(self):\n"
            "        faults.crashpoint(_CP)\n"
            "        durable.commit_replace('p', b'')\n"
            "    def flush(self):\n"
            "        self.save()\n"
            "class Store:\n"
            "    def append(self, b):\n"
            "        durable.commit_replace('q', b)\n"
            "class App:\n"
            "    def __init__(self):\n"
            "        self.index = Index()\n"
            "        self.partials = Store()\n"
            "    def run(self):\n"
            "        faults.crashpoint(_CP2)\n"
            "        self.partials.append(b'x')\n"
            "        self.index.flush()\n")})
    report = _lint(root, {"BKW003"})
    assert report.findings == [], \
        "\n".join(f.render() for f in report.findings)


_REPL_PKG_BODY = (
    "from .utils import durable, faults\n"
    "{consts}"
    "class OpLog:\n"
    "    def append(self, recs):\n"
    "        durable.fsync_file('p')\n"
    "    def set_epoch(self, e):\n"
    "        pass\n"
    "    def truncate_after(self, lsn):\n"
    "        pass\n"
    "class Part:\n"
    "    def __init__(self):\n"
    "        self.log = OpLog()\n"
    "    def _ship_tail(self, recs):\n"
    "        pass\n"
    "    def batch(self, recs):\n"
    "{batch_cp}"
    "        self.log.append(recs)\n"
    "        self._ship_tail(recs)\n"
    "    def promote(self):\n"
    "{promote_cp}"
    "        self.log.set_epoch(1)\n"
    "    def adopt(self):\n"
    "{adopt_cp}"
    "        self.log.truncate_after(0)\n")


def test_bkw003_replication_seams_require_crashpoints(tmp_path):
    """The op-log commit points (append / set_epoch / truncate_after),
    the ship-ack barrier, and the fsync-append helper are commit seams:
    bare, each one is a finding."""
    root = _mk_pkg(tmp_path, {
        "utils/faults.py": _FAULTS_STUB,
        "utils/durable.py": "def fsync_file(p):\n    pass\n",
        "a.py": _REPL_PKG_BODY.format(
            consts="", batch_cp="", promote_cp="", adopt_cp="")})
    report = _lint(root, {"BKW003"})
    seams = {f.message.split("(")[1].split(")")[0]
             for f in report.findings if "commit seam" in f.message}
    assert seams == {"durable.fsync_file", "oplog.append", "repl.ship",
                     "oplog.set_epoch", "oplog.truncate_after"}


def test_bkw003_replication_seams_covered_by_adjacent_crashpoints(tmp_path):
    """Crashpoints lexically beside each replication commit point clear
    every seam — including durable.fsync_file inside OpLog.append,
    covered through its direct caller (the same rule that clears the
    stage-on-executor idiom)."""
    root = _mk_pkg(tmp_path, {
        "utils/faults.py": _FAULTS_STUB,
        "utils/durable.py": "def fsync_file(p):\n    pass\n",
        "a.py": _REPL_PKG_BODY.format(
            consts=("_CP_A = faults.register_crash_site('r.append')\n"
                    "_CP_P = faults.register_crash_site('r.promote')\n"
                    "_CP_T = faults.register_crash_site('r.adopt')\n"),
            batch_cp="        faults.crashpoint(_CP_A)\n",
            promote_cp="        faults.crashpoint(_CP_P)\n",
            adopt_cp="        faults.crashpoint(_CP_T)\n")})
    report = _lint(root, {"BKW003"})
    assert report.findings == [], \
        "\n".join(f.render() for f in report.findings)


def test_bkw003_unregistered_site_literal(tmp_path):
    root = _mk_pkg(tmp_path, {
        "utils/faults.py": _FAULTS_STUB,
        "a.py": (
            "from .utils import faults\n"
            "def go():\n"
            "    faults.crashpoint('a.rogue')\n")})
    report = _lint(root, {"BKW003"})
    assert {f.anchor for f in report.findings} == {
        "unregistered-site:a.rogue"}


# --- BKW004: metrics-catalog sync -------------------------------------------

_METRICS_STUB = (
    "def counter(name, help, labelnames=()):\n    pass\n"
    "def gauge(name, help, labelnames=()):\n    pass\n"
    "def histogram(name, help, labelnames=(), buckets=None):\n    pass\n")


def _doc(tmp_path, rows):
    doc = tmp_path / "observability.md"
    body = ["| Metric | Type | Labels | Instrumented in |",
            "|---|---|---|---|"] + rows
    doc.write_text("\n".join(body) + "\n")
    return doc


def test_bkw004_undocumented_and_unconstructed(tmp_path):
    root = _mk_pkg(tmp_path, {
        "obs/metrics.py": _METRICS_STUB,
        "a.py": ("from .obs import metrics\n"
                 "C = metrics.counter('bkw_live_total', 'h')\n")})
    doc = _doc(tmp_path, ["| `bkw_ghost_total` | counter | — | x |"])
    report = _lint(root, {"BKW004"}, doc_path=doc)
    anchors = {f.anchor for f in report.findings}
    assert anchors == {"undocumented:bkw_live_total",
                       "unconstructed:bkw_ghost_total"}


def test_bkw004_label_drift_and_constant_resolution(tmp_path):
    root = _mk_pkg(tmp_path, {
        "obs/metrics.py": _METRICS_STUB,
        "a.py": ("from .obs import metrics\n"
                 "_LABELS = ('client',)\n"
                 "G = metrics.gauge('bkw_depth', 'h', _LABELS)\n")})
    good = _doc(tmp_path, ["| `bkw_depth` | gauge | `client` | a.py |"])
    assert _lint(root, {"BKW004"}, doc_path=good).findings == []
    bad = _doc(tmp_path, ["| `bkw_depth` | gauge | `peer` | a.py |"])
    report = _lint(root, {"BKW004"}, doc_path=bad)
    assert {f.anchor for f in report.findings} == {"label-drift:bkw_depth"}


def test_bkw004_conflicting_label_sets_across_sites(tmp_path):
    root = _mk_pkg(tmp_path, {
        "obs/metrics.py": _METRICS_STUB,
        "a.py": ("from .obs import metrics\n"
                 "A = metrics.counter('bkw_x_total', 'h', ('op',))\n"),
        "b.py": ("from .obs import metrics\n"
                 "B = metrics.counter('bkw_x_total', 'h', ('kind',))\n")})
    doc = _doc(tmp_path, ["| `bkw_x_total` | counter | `op` | a.py |"])
    report = _lint(root, {"BKW004"}, doc_path=doc)
    assert "conflict:bkw_x_total" in {f.anchor for f in report.findings}


# --- BKW005: wire-handler exhaustiveness ------------------------------------

_WIRE = ("import enum\n"
         "class RequestType(enum.IntEnum):\n"
         "    TRANSPORT = 0\n"
         "    AUDIT = 1\n")


def test_bkw005_unhandled_member(tmp_path):
    root = _mk_pkg(tmp_path, {
        "wire.py": _WIRE,
        "net/p2p.py": ("from .. import wire\n"
                       "def serve(t):\n"
                       "    if t == wire.RequestType.TRANSPORT:\n"
                       "        pass\n")})
    report = _lint(root, {"BKW005"})
    assert {f.anchor for f in report.findings} == {
        "unhandled:RequestType.AUDIT"}


def test_bkw005_dead_member_reference(tmp_path):
    root = _mk_pkg(tmp_path, {
        "wire.py": _WIRE,
        "net/p2p.py": ("from .. import wire\n"
                       "def serve(t):\n"
                       "    if t == wire.RequestType.TRANSPORT:\n"
                       "        pass\n"
                       "    elif t == wire.RequestType.AUDIT:\n"
                       "        pass\n"
                       "    elif t == wire.RequestType.GONE:\n"
                       "        pass\n")})
    report = _lint(root, {"BKW005"})
    assert {f.anchor for f in report.findings} == {
        "dead-member:RequestType.GONE"}


def test_bkw005_exhaustive_dispatch_is_silent(tmp_path):
    root = _mk_pkg(tmp_path, {
        "wire.py": _WIRE,
        "net/p2p.py": ("from .. import wire\n"
                       "HANDLERS = {wire.RequestType.TRANSPORT: 1,\n"
                       "            wire.RequestType.AUDIT: 2}\n")})
    assert _lint(root, {"BKW005"}).findings == []


# --- BKW006: clock-seam purity in sim-covered modules -----------------------


def test_bkw006_flags_wall_clock_in_covered_module(tmp_path):
    root = _mk_pkg(tmp_path, {
        "utils/retry.py": ("import time, asyncio\n"
                           "def due():\n"
                           "    return time.time()\n"
                           "async def pause():\n"
                           "    await asyncio.sleep(1)\n")})
    report = _lint(root, {"BKW006"})
    assert {f.anchor for f in report.findings} == {
        "due->time.time", "pause->asyncio.sleep"}
    assert all(f.severity == "error" for f in report.findings)
    assert "utils/clock.py seam" in report.findings[0].message


def test_bkw006_sim_tree_is_covered_and_others_are_not(tmp_path):
    root = _mk_pkg(tmp_path, {
        "sim/driver.py": ("import time\n"
                          "def tick():\n"
                          "    return time.monotonic()\n"),
        "engine.py": ("import time\n"
                      "def stamp():\n"
                      "    return time.time()\n")})
    report = _lint(root, {"BKW006"})
    assert {f.path for f in report.findings} == {"sim/driver.py"}


def test_bkw006_seam_calls_are_silent(tmp_path):
    root = _mk_pkg(tmp_path, {
        "net/peer_stats.py": (
            "from ..utils import clock as clockmod\n"
            "class PeerStats:\n"
            "    def __init__(self, clock=None):\n"
            "        self.clock = clockmod.resolve(clock)\n"
            "    def observe(self):\n"
            "        return self.clock.now()\n"),
        "utils/clock.py": ("def resolve(c):\n"
                           "    return c\n")})
    assert _lint(root, {"BKW006"}).findings == []


# --- BKW007: SLO-catalog sync -----------------------------------------------


def _slo_pkg(tmp_path, catalog,
             construct="C = metrics.counter('bkw_v_total', 'h',"
                       " ('client',))\n"):
    return _mk_pkg(tmp_path, {
        "obs/metrics.py": _METRICS_STUB,
        "a.py": "from .obs import metrics\n" + construct,
        "defaults.py": f"SLO_CATALOG = {catalog!r}\n"})


def _slo_doc(tmp_path, rows):
    doc = tmp_path / "observability.md"
    body = ["| Objective | Kind | Signal family | Budget |",
            "|---|---|---|---|"] + rows
    doc.write_text("\n".join(body) + "\n")
    return doc


_GOOD_ENTRY = {"id": "durability", "kind": "counter_rate",
               "family": "bkw_v_total", "budget": 0.001}


def test_bkw007_clean_catalog_and_doc(tmp_path):
    root = _slo_pkg(tmp_path, (_GOOD_ENTRY,))
    doc = _slo_doc(tmp_path, [
        "| `durability` | counter_rate | `bkw_v_total` | 0.001 |"])
    assert _lint(root, {"BKW007"}, doc_path=doc).findings == []


def test_bkw007_unknown_family_and_label_drift(tmp_path):
    ghost = dict(_GOOD_ENTRY, id="ghost", family="bkw_ghost_total")
    drift = dict(_GOOD_ENTRY, id="drift", labels={"peer": "x"})
    root = _slo_pkg(tmp_path, (_GOOD_ENTRY, ghost, drift))
    doc = _slo_doc(tmp_path, [
        "| `durability` | counter_rate | `bkw_v_total` | 0.001 |",
        "| `ghost` | counter_rate | `bkw_ghost_total` | 0.001 |",
        "| `drift` | counter_rate | `bkw_v_total` | 0.001 |"])
    report = _lint(root, {"BKW007"}, doc_path=doc)
    assert {f.anchor for f in report.findings} == {
        "slo-unknown-family:ghost:family", "slo-label-drift:drift"}


def test_bkw007_doc_sync_both_directions(tmp_path):
    root = _slo_pkg(tmp_path, (_GOOD_ENTRY,))
    # missing row -> undocumented; stale row -> uncatalogued; a row
    # naming the wrong family -> doc-family-drift
    doc = _slo_doc(tmp_path, [
        "| `durability` | counter_rate | `bkw_other_total` | 0.001 |",
        "| `retired` | counter_rate | `bkw_v_total` | 0.01 |"])
    report = _lint(root, {"BKW007"}, doc_path=doc)
    assert {f.anchor for f in report.findings} == {
        "slo-doc-family-drift:durability", "slo-uncatalogued:retired"}
    report = _lint(root, {"BKW007"}, doc_path=_slo_doc(tmp_path, []))
    assert {f.anchor for f in report.findings} == {
        "slo-undocumented:durability"}


def test_bkw007_malformed_entries_and_unparsable_catalog(tmp_path):
    bad_kind = dict(_GOOD_ENTRY, id="weird", kind="percentile")
    no_total = {"id": "stalls", "kind": "ratio",
                "family": "bkw_v_total", "budget": 0.02}
    root = _slo_pkg(tmp_path, (bad_kind, no_total))
    doc = _slo_doc(tmp_path, [])
    report = _lint(root, {"BKW007"}, doc_path=doc)
    assert {f.anchor for f in report.findings} == {
        "slo-bad-entry:weird", "slo-bad-entry:stalls"}
    root = _mk_pkg(tmp_path / "dyn", {
        "obs/metrics.py": _METRICS_STUB,
        "defaults.py": "SLO_CATALOG = tuple(build())\n"})
    report = _lint(root, {"BKW007"}, doc_path=doc)
    assert {f.anchor for f in report.findings} == {
        "slo-unparsable-catalog"}


# --- baseline semantics -----------------------------------------------------


def _one_finding_pkg(tmp_path):
    return _mk_pkg(tmp_path, {"a.py": (
        "import time\n"
        "async def serve():\n"
        "    time.sleep(1)\n")})


def test_baseline_suppresses_and_expires(tmp_path):
    root = _one_finding_pkg(tmp_path)
    cfg = LintConfig(package_root=root, rules={"BKW001"})
    key = collect_findings(cfg)[0].key
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"key": key, "justification": "deliberate for the fixture"}]}))
    report = _lint(root, {"BKW001"}, baseline_path=bl)
    assert report.findings == [] and len(report.baselined) == 1
    assert report.clean
    # fix the code: the entry goes stale and the report is NOT clean
    (root / "a.py").write_text("async def serve():\n    pass\n")
    report = _lint(root, {"BKW001"}, baseline_path=bl)
    assert report.findings == [] and not report.clean
    assert [e["key"] for e in report.stale_baseline] == [key]


def test_baseline_requires_justification_and_unique_keys(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"key": "BKW001:a.py:x", "justification": "  "}]}))
    with pytest.raises(BaselineError):
        load_baseline(bl)
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"key": "k", "justification": "a"},
        {"key": "k", "justification": "b"}]}))
    with pytest.raises(BaselineError):
        load_baseline(bl)
    bl.write_text("{not json")
    with pytest.raises(BaselineError):
        load_baseline(bl)


def test_finding_keys_are_line_independent(tmp_path):
    root = _one_finding_pkg(tmp_path)
    cfg = LintConfig(package_root=root, rules={"BKW001"})
    key = collect_findings(cfg)[0].key
    src = (root / "a.py").read_text()
    (root / "a.py").write_text("# a comment\n\n" + src)
    assert collect_findings(cfg)[0].key == key


# --- CLI contract -----------------------------------------------------------


def test_cli_json_schema_and_exit_codes(tmp_path):
    root = _one_finding_pkg(tmp_path)
    out = io.StringIO()
    rc = cli_main([str(root), "--rule", "BKW001", "--format", "json"],
                  out=out)
    assert rc == 1
    doc = json.loads(out.getvalue())
    assert doc["version"] == 1 and doc["clean"] is False
    (f,) = doc["findings"]
    assert set(f) == {"rule", "severity", "path", "line", "message",
                      "key"}
    assert f["rule"] == "BKW001" and f["path"] == "a.py"
    # unknown rule -> usage error
    assert cli_main([str(root), "--rule", "BKW999"]) == 2
    # missing package root -> usage error
    assert cli_main([str(tmp_path / "nope")]) == 2


def test_cli_stale_baseline_exit_code(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": "async def ok():\n    pass\n"})
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 1, "entries": [
        {"key": "BKW001:a.py:gone->time.sleep",
         "justification": "was deliberate once"}]}))
    out = io.StringIO()
    rc = cli_main([str(root), "--rule", "BKW001", "--baseline", str(bl)],
                  out=out)
    assert rc == 3
    assert "stale" in out.getvalue()


def test_cli_write_baseline_round_trips(tmp_path):
    root = _one_finding_pkg(tmp_path)
    bl = tmp_path / "bl.json"
    out = io.StringIO()
    assert cli_main([str(root), "--rule", "BKW001",
                     "--write-baseline", str(bl)], out=out) == 0
    doc = json.loads(bl.read_text())
    assert doc["version"] == 1 and len(doc["entries"]) == 1
    # the placeholder stamp gates: a suppression nobody justified is
    # exit 3 until the entry is edited
    out = io.StringIO()
    rc = cli_main([str(root), "--rule", "BKW001", "--baseline", str(bl)],
                  out=out)
    assert rc == 3
    assert "TODO placeholder" in out.getvalue()
    doc["entries"][0]["justification"] = "deliberate: startup-only path"
    bl.write_text(json.dumps(doc))
    rc = cli_main([str(root), "--rule", "BKW001", "--baseline", str(bl)],
                  out=io.StringIO())
    assert rc == 0


def test_cli_write_baseline_with_justification(tmp_path):
    """``--justification`` stamps every written entry with a real
    reason, so the round trip is immediately clean."""
    root = _one_finding_pkg(tmp_path)
    bl = tmp_path / "bl.json"
    assert cli_main([str(root), "--rule", "BKW001",
                     "--write-baseline", str(bl),
                     "--justification",
                     "batch exception: legacy sync seam"],
                    out=io.StringIO()) == 0
    doc = json.loads(bl.read_text())
    assert all(e["justification"] == "batch exception: legacy sync seam"
               for e in doc["entries"])
    assert cli_main([str(root), "--rule", "BKW001", "--baseline", str(bl)],
                    out=io.StringIO()) == 0


def test_unjustified_baseline_entries_reported(tmp_path):
    """apply_baseline routes TODO-prefixed matched entries into
    ``report.unjustified`` (json view included), and ``clean`` is
    False until they are edited."""
    root = _one_finding_pkg(tmp_path)
    cfg = LintConfig(package_root=root, doc_path=None,
                     baseline_path=None, rules={"BKW001"})
    findings = collect_findings(cfg)
    assert findings
    baseline = {findings[0].key: "TODO: justify this exception"}
    report = apply_baseline(findings, baseline)
    assert not report.findings and not report.stale_baseline
    assert [e["key"] for e in report.unjustified] == [findings[0].key]
    assert not report.clean
    assert report.to_dict()["unjustified"]


# --- the repo-wide tier-1 gate ----------------------------------------------


def test_repo_is_lint_clean():
    """The gate: zero unbaselined findings and zero stale baseline
    entries across all six rules on the real tree."""
    report = run_lint(LintConfig.for_repo(REPO))
    assert report.findings == [], \
        "\n".join(f.render() for f in report.findings)
    assert report.stale_baseline == [], report.stale_baseline
    assert report.clean


def test_repo_baseline_entries_all_match(tmp_path):
    """Every baseline entry matches a real finding (apply_baseline in
    reverse: nothing stale), and carries a real justification."""
    bl = load_baseline(REPO / ".bkwlint-baseline.json")
    cfg = LintConfig.for_repo(REPO)
    findings = collect_findings(cfg)
    keys = {f.key for f in findings}
    for key, why in bl.items():
        assert key in keys, f"stale baseline entry: {key}"
        assert len(why.strip()) > 10


def test_repo_rule_ids_cover_all_emitted_findings():
    cfg = LintConfig.for_repo(REPO)
    for f in collect_findings(cfg):
        assert f.rule in RULE_IDS


def test_static_crash_sites_match_runtime_registry():
    """The registry fills at import time, so import exactly the modules
    the static pass says register sites, then demand equality."""
    import importlib

    from backuwup_tpu.analysis.rules_crash import collect_registry
    from backuwup_tpu.utils import faults
    graph = load_graph(REPO / "backuwup_tpu")
    registered, _ = collect_registry(graph)
    for rel, _line in registered.values():
        mod = "backuwup_tpu." + rel[:-3].replace("/", ".")
        importlib.import_module(mod)
    assert static_crash_sites(graph) == set(faults.crash_sites())


def test_loader_survives_syntax_error(tmp_path):
    root = _mk_pkg(tmp_path, {"a.py": "def broken(:\n"})
    with pytest.raises(SyntaxError) as ei:
        load_package(root)
    assert "a.py" in str(ei.value)


def test_callgraph_resolves_mixin_subclass_attrs(tmp_path):
    """The idiom BKW003's caller-coverage depends on: a mixin method
    calling through an attr only the concrete subclass assigns."""
    root = _mk_pkg(tmp_path, {"a.py": (
        "class Store:\n"
        "    def append(self, b):\n"
        "        pass\n"
        "class Mixin:\n"
        "    def sink(self, b):\n"
        "        self.partials.append(b)\n"
        "class Writer(Mixin):\n"
        "    def __init__(self):\n"
        "        self.partials = Store()\n")})
    graph = build_graph(load_package(root))
    sink = graph.functions["a.py::Mixin.sink"]
    (cs,) = [c for c in sink.calls if c.repr.endswith("append")]
    assert cs.target == "a.py::Store.append"
