"""Process-level smoke test: REAL `python -m backuwup_tpu` processes.

The reference's manual two-client local test (docs/src/client.md:41-45,
mirrored in this repo's docs/client.md walkthrough) driven end-to-end
against actual OS processes and loopback sockets: one coordination
server + two clients, matched backup, then a restore after data loss —
everything through the same entry points a user runs, not in-process
wiring (which tests/test_integration.py already covers).

Accelerator-free: the subprocesses run with JAX_PLATFORMS=cpu and the
clients use the host backend for the tiny corpora here.
"""

import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, cwd=REPO):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual mesh: plain 1-core client procs
    return subprocess.Popen(
        [sys.executable, "-m", "backuwup_tpu", *args], cwd=cwd, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        bufsize=1)


def _reader(proc):
    """Daemon thread pumping a process's stdout into a queue, so waiting
    on output can honor a real deadline: a bare ``readline()`` blocks
    arbitrarily long when the process wedges without exiting, making the
    ``timeout`` parameter a dead letter.  One reader per process, cached
    on the Popen object (two readers would steal lines from each other)."""
    if getattr(proc, "_line_queue", None) is None:
        q = queue.Queue()

        def pump():
            for line in proc.stdout:
                q.put(line)
            q.put(None)  # EOF sentinel

        threading.Thread(target=pump, daemon=True).start()
        proc._line_queue = q
    return proc._line_queue


def _wait_line(proc, needle: str, timeout: float = 120) -> str:
    deadline = time.monotonic() + timeout
    q = _reader(proc)
    lines = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            line = q.get(timeout=remaining)
        except queue.Empty:
            break
        if line is None:
            raise AssertionError(
                f"process exited before {needle!r}:\n{''.join(lines)}")
        lines.append(line)
        if needle in line:
            return line
    raise AssertionError(f"timeout waiting for {needle!r}:\n{''.join(lines)}")


def _stop(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(15)


def _ws_url(dash_line: str) -> str:
    # "... dashboard at http://127.0.0.1:PORT"
    return dash_line.rsplit("at ", 1)[1].strip().rstrip("/") + "/ws"


async def _drive(ws_url_a: str, ws_url_b: str, src_a: Path):
    """Start backups on both clients over their dashboards' WS command
    channel, await completion, then wipe A's data and restore it."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_url_a) as wa, \
                session.ws_connect(ws_url_b) as wb:
            await wa.send_str(json.dumps({"command": "start_backup"}))
            await wb.send_str(json.dumps({"command": "start_backup"}))

            async def finish(ws):
                while True:
                    ev = json.loads(await ws.receive_str())
                    assert ev["kind"] != "error", ev
                    if ev["kind"] == "backup_finished":
                        return ev["payload"]["snapshot"]

            snap_a = await finish(wa)
            snap_b = await finish(wb)
            assert len(bytes.fromhex(snap_a)) == 32
            assert len(bytes.fromhex(snap_b)) == 32

            # disaster on A: lose the data, restore from peer B
            for p in sorted(src_a.rglob("*"), reverse=True):
                p.unlink() if p.is_file() else p.rmdir()
            await wa.send_str(json.dumps({"command": "start_restore"}))
            while True:
                ev = json.loads(await wa.receive_str())
                assert ev["kind"] != "error", ev
                if ev["kind"] == "restore_finished":
                    return


def test_two_process_backup_restore(tmp_path):
    import asyncio
    import random

    rng = random.Random(7)
    src_a = tmp_path / "a_src"
    src_b = tmp_path / "b_src"
    files_a = {}
    for d, tag in ((src_a, "a"), (src_b, "b")):
        (d / "sub").mkdir(parents=True)
        data = {"f.bin": rng.randbytes(300_000),
                "sub/nested.txt": f"hello {tag}\n".encode()}
        for rel, blob in data.items():
            (d / rel).write_bytes(blob)
        if tag == "a":
            files_a = data

    port = _free_port()
    server = _spawn(["server", "--bind", f"127.0.0.1:{port}",
                     "--db", str(tmp_path / "srv.db")])
    clients = []
    try:
        _wait_line(server, f"listening on 127.0.0.1:{port}")
        ws_urls = []
        for name, src in (("a", src_a), ("b", src_b)):
            c = _spawn(["client", "--non-interactive",
                        "--server-addr", f"127.0.0.1:{port}",
                        "--config-dir", str(tmp_path / name / "cfg"),
                        "--data-dir", str(tmp_path / name / "data"),
                        "--backup-path", str(src),
                        "--ui-bind", "127.0.0.1:0"])
            clients.append(c)
            ws_urls.append(_ws_url(_wait_line(c, "dashboard at")))

        # the dashboard itself must be served by the real process
        with urllib.request.urlopen(
                ws_urls[0][:-3], timeout=10) as resp:
            assert b"backuwup" in resp.read()

        asyncio.run(asyncio.wait_for(
            _drive(ws_urls[0], ws_urls[1], src_a), 180))

        # byte-identical restore
        for rel, blob in files_a.items():
            assert (src_a / rel).read_bytes() == blob, rel
    finally:
        for c in clients:
            _stop(c)
        _stop(server)
