"""Leaf-pool digest must be bit-identical to the BLAKE3 spec oracle.

Covers the round-5 digest-stage redesign (`ops/digest_pool.py`): one flat
leaf scan + tiered tree reduction replacing the ~12 per-class digest
pipelines of `scan_digest_batch`.  The reference hashes chunks serially
on the CPU (`dir_packer.rs:285-311`); bit-exact parity with the spec
implementation is the correctness bar for both designs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy, blake3_hash
from backuwup_tpu.ops.cdc_tpu import _HALO
from backuwup_tpu.ops.digest_pool import (
    leaf_capacity,
    pool_digest,
    pool_digest_available,
    tier_caps,
    tier_spans,
)
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.manifest_device import (
    scan_digest_batch_pool,
    tier_plan,
)
from backuwup_tpu.ops.pipeline import DevicePipeline

SMALL = CDCParams.from_desired(4096)


def _digests_of(acc: np.ndarray):
    return [np.ascontiguousarray(row.astype("<u4")).tobytes() for row in acc]


def _run_pool(flat, offs, lens, C, tiers=None, leaf_cap=None, **kw):
    offs_a = np.zeros(C, np.int32)
    lens_a = np.zeros(C, np.int32)
    offs_a[:len(offs)] = offs
    lens_a[:len(lens)] = lens
    if tiers is None:
        tiers = tuple((s, C) for s in tier_spans(128))
    if leaf_cap is None:
        leaf_cap = leaf_capacity(len(flat), C)
    flat_p = np.concatenate([flat, np.zeros(1024, np.uint8)])
    acc, ovf = pool_digest(jnp.asarray(flat_p), jnp.asarray(offs_a),
                           jnp.asarray(lens_a), leaf_cap=leaf_cap,
                           tiers=tiers, **kw)
    return np.asarray(acc), int(np.asarray(ovf)[0])


@pytest.mark.parametrize("pallas_kw", [
    {"pallas": False},
    {"pallas": True, "interpret": True},
], ids=["xla", "pallas-interpret"])
def test_pool_digest_matches_oracle(pallas_kw):
    rng = np.random.default_rng(5)
    flat = rng.integers(0, 256, 512 * 1024, dtype=np.uint8)
    # every structural edge: sub-block, block boundary, leaf boundary,
    # multi-leaf, power-of-two and odd leaf counts, unused slots
    lens = [1, 2, 63, 64, 65, 1023, 1024, 1025, 2048, 2049, 5 * 1024,
            17 * 1024 + 7, 64 * 1024, 100_000]
    offs, cur = [], 0
    for l in lens:
        offs.append(cur)
        cur += l
    acc, ovf = _run_pool(flat, offs, lens, C=20, **pallas_kw)
    assert ovf == 0
    got = _digests_of(acc)
    for i, l in enumerate(lens):
        assert got[i] == blake3_hash(flat[offs[i]:offs[i] + l].tobytes()), \
            f"len {l}"


def test_pool_digest_overlapping_and_shuffled_spans():
    """Chunks may share bytes (dedup re-reads) and arrive in any order."""
    rng = np.random.default_rng(6)
    flat = rng.integers(0, 256, 256 * 1024, dtype=np.uint8)
    spans = [(0, 10_000), (5_000, 10_000), (5_000, 3_000), (200_000, 50_000),
             (1, 1), (0, 256 * 1024)]
    rng.shuffle(spans)
    offs = [o for o, _ in spans]
    lens = [l for _, l in spans]
    acc, ovf = _run_pool(flat, offs, lens, C=8,
                         tiers=tuple((s, 8) for s in tier_spans(256)))
    assert ovf == 0
    got = _digests_of(acc)
    for i, (o, l) in enumerate(spans):
        assert got[i] == blake3_hash(flat[o:o + l].tobytes())


def test_pool_digest_tier_cascade_and_overflow():
    rng = np.random.default_rng(8)
    flat = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
    lens = [4096] * 8  # 4 leaves each
    offs = [i * 4096 for i in range(8)]
    # tier 0 holds only 4 of the 8; the rest must cascade up and still
    # digest correctly in the wider tier
    tiers = ((4, 4), (8, 8))
    acc, ovf = _run_pool(flat, offs, lens, C=8, tiers=tiers)
    assert ovf == 0
    got = _digests_of(acc)
    for i in range(8):
        assert got[i] == blake3_hash(flat[offs[i]:offs[i] + 4096].tobytes())
    # terminus overflow: capacity 4+2 < 8 chunks -> flagged, not silent
    acc, ovf = _run_pool(flat, offs, lens, C=8, tiers=((4, 4), (8, 2)))
    assert ovf > 0


def test_pool_digest_leaf_cap_shortfall_flagged():
    flat = np.zeros(32 * 1024, np.uint8)
    acc, ovf = _run_pool(flat, [0, 8192], [8192, 8192], C=4,
                         tiers=((8, 4), (16, 4)), leaf_cap=8)
    assert ovf > 0  # 16 leaves needed, 8 lanes available


def test_tier_plan_shapes():
    spans = tier_spans(3072)
    assert spans[-1] == 3072 and len(spans) <= 3
    assert all(a < b for a, b in zip(spans, spans[1:]))
    plan = tier_plan(SMALL, 4 << 20, 4)
    assert plan[-1][0] == SMALL.max_size // 1024
    assert all(c % 4 == 0 for _, c in plan)
    assert plan[-1][1] > 0
    assert leaf_capacity(1 << 20, 64) >= (1 << 20) // 1024 + 64


def test_pool_gate_runs():
    # on the test runtime (CPU mesh) the XLA pool path must pass its gate
    assert pool_digest_available(False) is True


def test_scan_digest_batch_pool_matches_oracle():
    P = 65536
    rng = np.random.default_rng(13)
    sizes = [P, 30_000, 0, 1, 5000]
    rows = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in sizes]
    buf = np.zeros((len(rows), _HALO + P), dtype=np.uint8)
    nv = np.zeros(len(rows), dtype=np.int32)
    for r, d in enumerate(rows):
        buf[r, _HALO:_HALO + len(d)] = np.frombuffer(d, dtype=np.uint8)
        nv[r] = len(d)
    pipe = DevicePipeline(SMALL)
    s_cap, l_cap, cut_cap = pipe._caps(P)
    packed, acc, ovf = scan_digest_batch_pool(
        jnp.asarray(buf), jnp.asarray(nv), min_size=SMALL.min_size,
        desired_size=SMALL.desired_size, max_size=SMALL.max_size,
        mask_s=SMALL.mask_s, mask_l=SMALL.mask_l,
        s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap, fused=False,
        leaf_cap=leaf_capacity(len(rows) * P, len(rows) * cut_cap),
        tiers=tier_plan(SMALL, len(rows) * P, len(rows)))
    packed = np.asarray(packed)
    acc = np.asarray(acc)
    assert not np.asarray(ovf).any()
    dig8 = np.ascontiguousarray(acc.astype("<u4")).view(np.uint8).reshape(
        len(rows), cut_cap, 32)
    for r, data in enumerate(rows):
        ref_chunks = cdc_cpu.chunk_stream(data, SMALL)
        ref_digests = Blake3Numpy().digest_batch(
            [data[o:o + l] for o, l in ref_chunks])
        assert packed[r, 0] == 0
        n_cuts = int(packed[r, 1])
        ends = packed[r, 2:2 + n_cuts].astype(np.int64)
        offs = np.concatenate([[0], ends[:-1] + 1])
        assert list(zip(offs.tolist(),
                        (ends - offs + 1).tolist())) == ref_chunks
        assert [bytes(d) for d in dig8[r, :n_cuts]] == ref_digests
