"""Mosaic kernel parity (TPU rig only — the CPU CI mesh skips).

The Pallas kernels are experimental alternates for the scan hot ops
(PERF.md documents why they are not yet the production path); bit-parity
against the spec implementations is asserted whenever the lowering is
available so they can never rot silently.
"""

import numpy as np
import pytest

from backuwup_tpu.ops import pallas_kernels as pk
from backuwup_tpu.ops.gear import GEAR, CDCParams

pytestmark = pytest.mark.skipif(
    not pk.pallas_available(), reason="no Pallas TPU lowering here")


def test_gear_values_parity():
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    for n in (1, 255, pk._TILE_BYTES, pk._TILE_BYTES * 3 + 17, 1 << 20):
        b = rng.integers(0, 256, n, dtype=np.uint8)
        g = np.asarray(pk.gear_values_pallas(jnp.asarray(b)))
        assert np.array_equal(g, GEAR[b]), n


def test_ladder_candidates_parity():
    import jax.numpy as jnp

    from backuwup_tpu.ops.cdc_cpu import gear_hashes

    p = CDCParams()
    block = pk._LADDER_ROWS * pk._LANES
    rng = np.random.default_rng(8)
    n = 2 * block
    data = rng.integers(0, 256, n - 31, dtype=np.uint8)
    ext = np.zeros(n, dtype=np.uint8)
    ext[31:] = data
    g = GEAR[ext].astype(np.uint32)
    cl, cs = pk.ladder_candidates_pallas(
        jnp.asarray(g), n, mask_s=p.mask_s, mask_l=p.mask_l)
    cl = np.asarray(cl)[31:].astype(bool)
    cs = np.asarray(cs)[31:].astype(bool)
    # the kernel sees 31 zero BYTES of left context; give the oracle the
    # identical context so even the warmup positions compare bit-exactly
    h = gear_hashes(data, prev_tail=bytes(31))
    cl_ref = (h & np.uint32(p.mask_l)) == 0
    cs_ref = cl_ref & ((h & np.uint32(p.mask_s)) == 0)
    assert np.array_equal(cl, cl_ref)
    assert np.array_equal(cs, cs_ref)
