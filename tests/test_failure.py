"""Fault-injection plane + peer-loss repair + unified retry policy.

Unit level: the retry policy shapes (delay progression, caps, jitter
bounds, attempt budgets, Backoff/RetryTimer state machines), fault-plane
determinism under a fixed seed (and inertness when disabled), the
receiver's idempotent re-send acceptance, blob-index forget/last-wins
semantics, placement retirement, and the server's schema-version gate.

System level: the chaos acceptance scenario — three real clients through
the coordination server; one peer is killed mid-backup and one frame to
the surviving peer is corrupted plus one ack withheld (the crash-between-
write-and-ack window), yet the backup completes; audit demotion of the
dead peer triggers one ``repair_round()`` that re-replicates every
orphaned packfile onto the survivor, retires the dead placements, and
reports the reclaimed allocation; a subsequent restore with the lost peer
permanently dark reproduces the source tree byte-for-byte.
"""

import asyncio
import hashlib
import random
import shutil
from dataclasses import replace

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine
from backuwup_tpu.net.p2p import P2PError, ReceivedFilesWriter, obfuscate
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.store import Store
from backuwup_tpu.utils import faults, retry
from backuwup_tpu.utils.faults import ACT_CORRUPT, ACT_DROP, FaultPlane

BACKEND = CpuBackend(CDCParams.from_desired(4096))


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def plane():
    """Install a fault plane; ALWAYS uninstall so other tests stay clean."""
    installed = faults.install(FaultPlane(seed=1234))
    yield installed
    faults.uninstall()


# --------------------------------------------------------------------------
# retry policy
# --------------------------------------------------------------------------


def test_retry_delay_progression_and_cap():
    p = retry.RetryPolicy(base_s=1.0, cap_s=8.0, jitter=0.0)
    assert [p.delay_s(a) for a in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0]


def test_retry_jitter_stays_in_band():
    p = retry.RetryPolicy(base_s=10.0, cap_s=100.0, jitter=0.1)
    rng = random.Random(3)
    draws = [p.delay_s(1, rand=rng.random) for _ in range(200)]
    assert all(9.0 <= d <= 11.0 for d in draws)
    assert max(draws) - min(draws) > 0.5  # actually jittered
    # injectable rand pins the draw exactly
    assert p.delay_s(1, rand=lambda: 0.0) == pytest.approx(9.0)
    assert p.delay_s(1, rand=lambda: 0.5) == pytest.approx(10.0)


def test_backoff_budget_and_reset():
    p = retry.RetryPolicy(base_s=1.0, cap_s=4.0, jitter=0.0, max_attempts=2)
    b = retry.Backoff(p)
    assert b.next_delay() == 1.0
    assert b.next_delay() == 2.0
    assert b.next_delay() is None  # budget spent
    b.reset()
    assert b.next_delay() == 1.0  # success resets to the base delay


def test_retry_timer_due_fire_reset():
    p = retry.RetryPolicy(base_s=10.0, cap_s=40.0, jitter=0.0)
    t = retry.RetryTimer(p)
    assert t.due(0.0)  # fresh timer fires immediately
    t.fire(100.0)
    assert not t.due(105.0) and t.due(110.0)
    t.fire(110.0)  # second consecutive dry spell: window doubles
    assert not t.due(125.0) and t.due(130.0)
    t.reset()
    assert t.due(130.0) and t.attempt == 0


def test_retry_async_succeeds_then_exhausts_on_virtual_clock(loop):
    """retry_async rides the clock seam: real-scale backoff delays
    elapse in virtual time (the test sleeps zero wall seconds), so the
    progression can be asserted EXACTLY instead of dwarfing base_s down
    to milliseconds and hoping the wall clock keeps up."""
    from backuwup_tpu.sim import SimClock, SimDriver
    p = retry.RetryPolicy(base_s=2.0, cap_s=8.0, jitter=0.0,
                          max_attempts=3)
    clock = SimClock()
    driver = SimDriver(clock)
    calls = {"n": 0}

    async def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return clock.now()

    async def scenario():
        task = driver.spawn(retry.retry_async(
            flaky, p, retry_on=(OSError,), clock=clock))
        await driver.run(until=60.0)
        return await task

    done_at = loop.run_until_complete(scenario())
    assert calls["n"] == 3
    assert done_at == 2.0 + 4.0  # base, then doubled: virtual seconds

    async def always_down():
        raise OSError("hard down")

    async def exhaust():
        driver.spawn(retry.retry_async(
            always_down, p, retry_on=(OSError,), clock=clock))
        await driver.run(until=180.0)

    with pytest.raises(OSError, match="hard down"):
        loop.run_until_complete(exhaust())


def test_audit_policy_matches_ledger_backoff():
    # the ledger persists absolute next_due times tests assert exactly —
    # the shared AUDIT policy must stay jitter-free and base*2^(n-1)
    assert retry.AUDIT.jitter == 0.0
    assert retry.AUDIT.delay_s(1) == defaults.AUDIT_RETRY_BASE_S
    assert retry.AUDIT.delay_s(2) == 2 * defaults.AUDIT_RETRY_BASE_S
    assert retry.AUDIT.delay_s(1000) == defaults.AUDIT_BACKOFF_CAP_S


# --------------------------------------------------------------------------
# fault plane: determinism, inertness, env parsing
# --------------------------------------------------------------------------


def test_plane_disabled_by_default():
    assert faults.PLANE is None  # one is-None check is the whole overhead


def test_plane_decide_deterministic_under_seed():
    a, b = FaultPlane(seed=7, drop_send=0.3), FaultPlane(seed=7,
                                                         drop_send=0.3)
    sa = [a.decide("send.drop:ff", 0.3) for _ in range(200)]
    sb = [b.decide("send.drop:ff", 0.3) for _ in range(200)]
    assert sa == sb and any(sa) and not all(sa)
    # a different site is an independent stream, same seed
    sc = [a.decide("send.drop:ee", 0.3) for _ in range(200)]
    assert sc != sa
    # a different seed changes the stream
    sd = [FaultPlane(seed=8).decide("send.drop:ff", 0.3)
          for _ in range(200)]
    assert sd != sa


def test_plane_arming_never_shifts_later_draws():
    plain, armed = FaultPlane(seed=5), FaultPlane(seed=5)
    armed.arm("site", 5)
    a = [plain.decide("site", 0.2) for _ in range(100)]
    b = [armed.decide("site", 0.2) for _ in range(100)]
    assert b[5] is True
    assert [x for i, x in enumerate(a) if i != 5] == \
        [x for i, x in enumerate(b) if i != 5]
    assert armed.fired["site"] >= 1


def test_plane_kill_after_counts_sends(loop):
    plane = FaultPlane(seed=0)
    peer = b"\x11" * 32

    async def run():
        plane.kill_after(peer, 2)
        assert await plane.on_send(peer) is None
        assert await plane.on_send(peer) is None
        assert await plane.on_send(peer) == ACT_DROP  # the fatal one
        assert plane.is_dead(peer)
        assert await plane.on_send(peer) == ACT_DROP  # stays dead
        plane.revive(peer)
        assert await plane.on_send(peer) is None

    loop.run_until_complete(run())


def test_plane_corrupt_flips_exactly_one_byte():
    plane = FaultPlane(seed=3)
    raw = bytes(range(256)) * 4
    out = plane.corrupt(raw, b"\x22" * 32)
    assert len(out) == len(raw)
    assert sum(x != y for x, y in zip(raw, out)) == 1


def test_from_env_parses_spec_and_rejects_unknown_keys():
    assert faults.from_env("") is None
    plane = faults.from_env(
        "seed=7,drop_send=0.05,latency=0.2,latency_s=0.1,kill="
        + "ab" * 32 + "+" + "cd" * 32)
    assert plane.seed == 7 and plane.drop_send == 0.05
    assert plane.latency == 0.2 and plane.latency_s == 0.1
    assert plane.is_dead(b"\xab" * 32) and plane.is_dead(b"\xcd" * 32)
    with pytest.raises(ValueError, match="unknown BKW_FAULTS key"):
        faults.from_env("explode=1")


def test_injected_corrupt_detected_by_signature_check(plane):
    # a corrupted signed frame must never verify — the receiver drops it
    # and the sender's ack timeout drives the retry path
    from backuwup_tpu.net.p2p import _sign_body, _verify_msg
    keys = KeyManager.from_secret(b"\x31" * 32)
    body = wire.P2PBody(
        kind=wire.P2PBodyKind.FILE,
        header=wire.P2PHeader(sequence_number=1,
                              session_nonce=b"\x01" * wire.TRANSPORT_NONCE_LEN),
        file_info=wire.FileInfoKind.PACKFILE, file_id=b"\x05" * 12,
        data=b"payload" * 100)
    raw = _sign_body(keys, body)
    assert _verify_msg(raw, keys.client_id).data == body.data
    with pytest.raises((P2PError, ValueError)):
        _verify_msg(plane.corrupt(raw, b"\x00" * 32), keys.client_id)


# --------------------------------------------------------------------------
# idempotent re-send acceptance (receiver side)
# --------------------------------------------------------------------------


@pytest.fixture
def store(tmp_path):
    s = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    s.set_obfuscation_key(b"\xaa\x01\x7f\x33")
    yield s
    s.close()


def test_sink_acks_identical_resend_without_double_quota(store, loop):
    peer = b"\x41" * 32
    store.add_peer_negotiated(peer, 1 << 20)
    writer = ReceivedFilesWriter(store, peer)
    data = random.Random(9).randbytes(5000)
    fid = b"\x0a" * 12

    async def run():
        await writer.sink(wire.FileInfoKind.PACKFILE, fid, data)
        received = store.get_peer(peer).bytes_received
        # lost-ack retry: same id + same bytes is acked, quota NOT re-counted
        await writer.sink(wire.FileInfoKind.PACKFILE, fid, data)
        assert store.get_peer(peer).bytes_received == received
        # same id + different bytes is still the collision refusal
        with pytest.raises(P2PError, match="refusing to overwrite"):
            await writer.sink(wire.FileInfoKind.PACKFILE, fid, data[::-1])

    loop.run_until_complete(run())


def test_sink_resend_accepted_even_when_quota_exhausted(store, loop):
    # the duplicate check must run BEFORE the quota check: the first write
    # already consumed the allowance, and a retry of the very file that
    # filled it must still be acked
    peer = b"\x42" * 32
    store.add_peer_negotiated(peer, 100)
    writer = ReceivedFilesWriter(store, peer)
    data = b"z" * (100 + defaults.PEER_OVERUSE_GRACE)  # fills quota+grace

    async def run():
        await writer.sink(wire.FileInfoKind.PACKFILE, b"\x0b" * 12, data)
        await writer.sink(wire.FileInfoKind.PACKFILE, b"\x0b" * 12, data)

    loop.run_until_complete(run())


# --------------------------------------------------------------------------
# blob index: forget + last-wins reload (re-homing after repair)
# --------------------------------------------------------------------------


def test_forget_packfiles_reopens_dedup_for_lost_blobs(tmp_path):
    keys = KeyManager.from_secret(b"\x51" * 32)
    index = BlobIndex(keys, tmp_path / "idx")
    pid_a, pid_b = b"\x01" * 12, b"\x02" * 12
    h1, h2, h3 = (bytes([i]) * 32 for i in (1, 2, 3))
    index.finalize_packfile(pid_a, [h1, h2])
    index.finalize_packfile(pid_b, [h3])
    assert index.hashes_for_packfiles([pid_a]) == {h1, h2}
    lost = index.forget_packfiles([pid_a])
    assert lost == {h1, h2}
    assert not index.is_duplicate(h1) and not index.is_duplicate(h2)
    assert index.is_duplicate(h3)  # untouched packfile keeps its entries
    assert index.forget_packfiles([pid_a]) == set()  # idempotent


def test_index_reload_last_wins_after_rehoming(tmp_path):
    keys = KeyManager.from_secret(b"\x52" * 32)
    h = b"\x07" * 32
    old_pid, new_pid = b"\x0c" * 12, b"\x0d" * 12
    index = BlobIndex(keys, tmp_path / "idx")
    index.finalize_packfile(old_pid, [h])
    index.flush()  # file 000000 names the soon-to-die packfile
    index.forget_packfiles([old_pid])
    index.finalize_packfile(new_pid, [h])  # repair re-homes the blob
    index.flush()  # file 000001 names the replacement
    reloaded = BlobIndex(keys, tmp_path / "idx")
    reloaded.load()
    assert reloaded.lookup(h) == new_pid  # later file must win


# --------------------------------------------------------------------------
# store: placement retirement + avoid-set exclusion
# --------------------------------------------------------------------------


def test_store_peers_for_packfile_and_retirement(store):
    pid, p1, p2 = b"\x0e" * 12, b"\x61" * 32, b"\x62" * 32
    store.record_placement(pid, p1, 1000)
    store.record_placement(pid, p2, 1000)
    assert {bytes(p) for p in store.peers_for_packfile(pid)} == {p1, p2}
    assert store.retire_placements(p1) == 1
    assert store.placements_for_peer(p1) == []
    assert {bytes(p) for p in store.peers_for_packfile(pid)} == {p2}
    assert store.retire_placements(p1) == 0  # idempotent


def test_find_peers_with_storage_honors_exclude(store):
    p1, p2 = b"\x63" * 32, b"\x64" * 32
    store.add_peer_negotiated(p1, 1 << 20)
    store.add_peer_negotiated(p2, 1 << 10)
    assert [bytes(p.pubkey) for p in
            store.find_peers_with_storage()] == [p1, p2]
    assert [bytes(p.pubkey) for p in
            store.find_peers_with_storage(exclude={p1})] == [p2]


# --------------------------------------------------------------------------
# server: schema version gate + repair bookkeeping
# --------------------------------------------------------------------------


def test_server_schema_version_stamped_and_newer_refused(tmp_path):
    from backuwup_tpu.net.server import SCHEMA_VERSION, ServerDB

    path = str(tmp_path / "server.db")
    db = ServerDB(path)
    assert db.schema_version() == SCHEMA_VERSION
    db._db.execute("UPDATE metadata SET value = ? WHERE key = ?",
                   (str(SCHEMA_VERSION + 1), "schema_version"))
    db._db.commit()
    with pytest.raises(RuntimeError, match="newer than this server"):
        ServerDB(path)


def test_server_reclaim_negotiation_drops_both_directions(tmp_path):
    from backuwup_tpu.net.server import ServerDB

    db = ServerDB(":memory:")
    a, b, c = b"\x71" * 32, b"\x72" * 32, b"\x73" * 32
    db.save_storage_negotiated(a, b, 1000)
    db.save_storage_negotiated(b, a, 1000)
    db.save_storage_negotiated(a, c, 1000)
    assert db.reclaim_negotiation(a, b) == 2
    assert db.get_client_negotiated_peers(a) == [c]
    assert db.get_clients_storing_on(a) == []


# --------------------------------------------------------------------------
# engine: demotion hook spawns a repair round
# --------------------------------------------------------------------------


def test_demotion_hook_spawns_one_repair_round(tmp_path, loop):
    keys = KeyManager.generate()
    st = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    eng = Engine(keys, st, server=None, node=None, backend=BACKEND)
    rounds = []

    async def fake_repair(now=None):
        rounds.append(now)

    eng.repair_round = fake_repair
    peer = b"\x65" * 32
    demoted = replace(st.get_audit_state(peer), demoted=True)
    healthy = st.get_audit_state(peer)

    async def run():
        eng._audit_event(peer, "fail", "digest mismatch", demoted)
        await asyncio.sleep(0)
        assert len(rounds) == 1
        eng._audit_event(peer, "pass", "", healthy)  # no spawn on healthy
        eng.auto_repair = False
        eng._audit_event(peer, "fail", "x", demoted)  # tests drive manually
        await asyncio.sleep(0)
        assert len(rounds) == 1
        await eng.aclose()

    loop.run_until_complete(run())
    st.close()


def test_repair_round_noop_without_lost_peers(tmp_path, loop):
    keys = KeyManager.generate()
    st = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    eng = Engine(keys, st, server=None, node=None, backend=BACKEND)
    st.record_placement(b"\x0f" * 12, b"\x66" * 32, 1000)  # healthy holder
    report = loop.run_until_complete(eng.repair_round(now=1.0))
    assert report["packfiles"] == 0 and report["bytes_replaced"] == 0
    st.close()


# --------------------------------------------------------------------------
# chaos end-to-end: the acceptance scenario
# --------------------------------------------------------------------------


def _corpus(root, rng):
    root.mkdir(parents=True, exist_ok=True)
    (root / "docs").mkdir()
    (root / "big.bin").write_bytes(rng.randbytes(280_000))
    (root / "docs" / "notes.txt").write_bytes(rng.randbytes(90_000))
    (root / "small.cfg").write_bytes(b"alpha=1\nbeta=2\n")


def _tree_digest(root):
    out = {}
    for p in sorted(root.rglob("*")):
        if p.is_file():
            out[str(p.relative_to(root))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    return out


def test_chaos_peer_death_repair_and_dark_restore(tmp_path, loop,
                                                  monkeypatch, plane):
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer

    # small packfiles so the corpus spans several of them; fast ack
    # timeouts so injected corruption/withholding resolves quickly
    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 64 * 1024)
    monkeypatch.setattr(defaults, "ACK_TIMEOUT_S", 1.5)
    monkeypatch.setattr(defaults, "RESTORE_REQUEST_THROTTLE_S", 0.0)
    monkeypatch.setattr(defaults, "AUDIT_SERVE_MIN_INTERVAL_S", 0.0)
    rng = random.Random(20)
    _corpus(tmp_path / "a_src", rng)
    source_digest = _tree_digest(tmp_path / "a_src")

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=CpuBackend(CDCParams.from_desired(4096)))
            app.store.set_backup_path(str(tmp_path / "a_src"))
            return app

        a, b, c = make_app("a"), make_app("b"), make_app("c")
        for app in (a, b, c):
            await app.start()
            # deterministic chaos: no background audit scheduling
            app._audit_task.cancel()
        a.engine.auto_repair = False  # this test drives repair explicitly
        a_hex = bytes(a.client_id).hex()
        c_hex = bytes(c.client_id).hex()

        # manual negotiation (matchmaking has its own tests): B gets the
        # larger allowance so the send loop prefers it, then loses it
        for peer, amt in ((b, 8 << 20), (c, 4 << 20)):
            a.store.add_peer_negotiated(peer.client_id, amt)
            peer.store.add_peer_negotiated(a.client_id, amt)
            server.db.save_storage_negotiated(
                bytes(a.client_id), bytes(peer.client_id), amt)

        # chaos plan: B vanishes after 2 stored packfiles; C's first frame
        # is corrupted in flight (signature check + ack-timeout retry);
        # the first file C persists gets its ack withheld (crash window —
        # exercises the idempotent re-send acceptance).  The withhold
        # stream is keyed by the SENDER id, so B's two acked files consume
        # query indices 0-1 and C's first persisted file is index 2.
        plane.kill_after(b.client_id, 2)
        plane.arm(f"send.corrupt:{c_hex}", 0)
        plane.arm(f"recv.withhold_ack:{a_hex}", 2)

        # --- backup completes despite peer death mid-stream --------------
        snapshot = await asyncio.wait_for(a.backup(), 180)
        assert snapshot
        b_rows = a.store.placements_for_peer(b.client_id)
        c_rows = a.store.placements_for_peer(c.client_id)
        assert len(b_rows) == 2, "B should hold exactly its pre-death sends"
        assert c_rows, "backup did not fail over to the surviving peer"
        assert plane.fired.get(f"send.dead:{bytes(b.client_id).hex()}")
        assert plane.fired.get(f"send.corrupt:{c_hex}") == 1
        assert plane.fired.get(f"recv.withhold_ack:{a_hex}") == 1

        # --- audit-demote the dead peer (3 consecutive misses) -----------
        import time as _time
        t0 = _time.time()
        for i in range(defaults.AUDIT_DEMOTE_MISSES):
            res = await a.engine.audit_peer(b.client_id, now=t0 + i)
            assert res is not None and not res.passed
        st = a.store.get_audit_state(b.client_id)
        assert st.demoted
        orphaned_pids = [bytes(pid) for pid, _ in b_rows]
        lost_hashes = a.engine.index.hashes_for_packfiles(orphaned_pids)
        assert lost_hashes, "B's packfiles must map to committed blobs"

        # --- one repair round restores full placement coverage ------------
        report = await asyncio.wait_for(
            a.engine.repair_round(now=t0 + 10), 180)
        assert report["packfiles"] == len(orphaned_pids)
        assert report["blobs"] == len(lost_hashes)
        assert report["bytes_replaced"] > 0
        assert a.store.placements_for_peer(b.client_id) == []
        for h in lost_hashes:  # every lost blob re-homed off the dead peer
            pid = a.engine.index.lookup(h)
            assert pid is not None and pid not in orphaned_pids
            holders = {bytes(p) for p in a.store.peers_for_packfile(pid)}
            assert holders and bytes(b.client_id) not in holders
        # reclaimed allocation reported: the dead edge is gone server-side
        assert server.db.get_client_negotiated_peers(
            bytes(a.client_id)) == [bytes(c.client_id)]
        n_reports = server.db._db.execute(
            "SELECT COUNT(*) FROM repair_reports WHERE peer = ?",
            (bytes(b.client_id),)).fetchone()[0]
        assert n_reports == 1

        # --- restore succeeds with B permanently dark ---------------------
        await b.stop()  # dark for good (the plane also still marks it dead)
        shutil.rmtree(tmp_path / "a_src")
        dest = tmp_path / "restored"
        await asyncio.wait_for(a.restore(dest), 180)
        assert _tree_digest(dest) == source_digest  # byte-for-byte

        await a.stop()
        await c.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 500))
