"""L0 codec and message round-trip tests (golden-file style per SURVEY.md §4)."""

import pytest

from backuwup_tpu import wire
from backuwup_tpu.utils.serialization import CodecError, Reader, Writer


def test_writer_reader_roundtrip():
    w = Writer()
    w.u8(7)
    w.u32(0xDEADBEEF)
    w.u64(1 << 45)
    w.blob(b"hello")
    w.str("päth/ü")
    w.opt_fixed(None, 32)
    w.opt_fixed(b"\x01" * 32, 32)
    buf = w.take()

    r = Reader(buf)
    assert r.u8() == 7
    assert r.u32() == 0xDEADBEEF
    assert r.u64() == 1 << 45
    assert r.blob() == b"hello"
    assert r.str() == "päth/ü"
    assert r.opt_fixed(32) is None
    assert r.opt_fixed(32) == b"\x01" * 32
    r.expect_end()


def test_reader_truncation_raises():
    r = Reader(b"\x01\x02")
    with pytest.raises(CodecError):
        r.u64()


def test_tree_roundtrip_deterministic():
    t = wire.Tree(
        kind=wire.TreeKind.DIR,
        name="subdir",
        metadata=wire.TreeMetadata(size=123, mtime_ns=10**18, ctime_ns=42),
        children=[bytes([i] * 32) for i in range(3)],
        next_sibling=b"\xaa" * 32,
    )
    enc1 = t.encode_bytes()
    enc2 = wire.Tree.decode_bytes(enc1).encode_bytes()
    assert enc1 == enc2
    back = wire.Tree.decode_bytes(enc1)
    assert back.kind == wire.TreeKind.DIR
    assert back.name == "subdir"
    assert back.children == t.children
    assert back.next_sibling == t.next_sibling


def test_json_messages_roundtrip():
    msgs = [
        wire.ClientRegistrationRequest(pubkey=b"\x01" * 32),
        wire.BackupRequest(session_token=b"\x02" * 16, storage_required=10**9),
        wire.ServerChallenge(nonce=b"\x03" * 32),
        wire.BackupMatched(destination_id=b"\x04" * 32, storage_available=5),
        wire.Error(kind="NoData", detail="nothing yet"),
        wire.BackupRestoreInfo(snapshot_hash=None, peers=["ab" * 32]),
    ]
    for m in msgs:
        s = m.to_json()
        back = wire.JsonMessage.from_json(s)
        assert back == m, s


def test_json_unknown_tag_rejected():
    with pytest.raises(ValueError):
        wire.JsonMessage.from_json('{"t":"Nope"}')


def test_json_missing_required_field_rejected():
    with pytest.raises(ValueError, match="missing required field"):
        wire.JsonMessage.from_json('{"t":"BackupRequest"}')
    with pytest.raises(ValueError, match="missing required field"):
        wire.JsonMessage.from_json('{"t":"ClientRegistrationRequest","pubkey":null}')
    # optional fields may be absent
    m = wire.JsonMessage.from_json('{"t":"BackupRestoreInfo"}')
    assert m == wire.BackupRestoreInfo(snapshot_hash=None, peers=[])


def test_json_non_string_bytes_field_rejected():
    with pytest.raises(ValueError, match="hex string"):
        wire.JsonMessage.from_json('{"t":"ClientRegistrationRequest","pubkey":123}')


def test_packfile_header_blob_bad_hash_rejected():
    from backuwup_tpu.utils.serialization import Writer
    bad = wire.PackfileHeaderBlob(hash=b"short", kind=wire.BlobKind.FILE_CHUNK,
                                  compression=wire.CompressionKind.NONE,
                                  length=1, offset=0)
    with pytest.raises(ValueError):
        bad.encode(Writer())


def test_p2p_body_roundtrip():
    hdr = wire.P2PHeader(sequence_number=7, session_nonce=b"\x09" * 16)
    bodies = [
        wire.P2PBody(kind=wire.P2PBodyKind.REQUEST, header=hdr,
                     request_type=wire.RequestType.RESTORE_ALL),
        wire.P2PBody(kind=wire.P2PBodyKind.FILE, header=hdr,
                     file_info=wire.FileInfoKind.PACKFILE,
                     file_id=b"\x01" * 12, data=b"x" * 1000),
        wire.P2PBody(kind=wire.P2PBodyKind.ACK, header=hdr, acked_sequence=6),
    ]
    for b in bodies:
        enc = b.encode_bytes()
        assert wire.P2PBody.decode_bytes(enc) == b

    env = wire.EncapsulatedMsg(body=bodies[1].encode_bytes(), signature=b"s" * 64)
    assert wire.EncapsulatedMsg.decode_bytes(env.encode_bytes()) == env
