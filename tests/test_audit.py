"""Storage attestation: challenge–response audits of peer-held packfiles.

Unit level: challenge-table construction/persistence (single-use, write-
once), prover window digests over the obfuscated store (honest MISSING /
SHORT admissions), verifier judgment, cursor burning, and the ledger's
pass/fail/miss demotion policy.

System level: the acceptance scenario — two real clients through the
coordination server, a passing audit round of >= 8 random-window
challenges over the batched digest path, then deliberate corruption and
deletion detected within one round, failure recorded, peer demoted out of
the free-space matchmaking ordering.  Plus stale-proof rejection via the
sequence/nonce header and offline-peer miss tolerance.
"""

import asyncio
import random
from dataclasses import replace

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.audit import (
    build_challenge_table,
    check_proofs,
    compute_proofs,
    detection_probability,
    record_fail,
    record_miss,
    record_pass,
    select_challenges,
)
from backuwup_tpu.audit.challenge import sample_windows, to_wire
from backuwup_tpu.audit.prover import deobfuscate_window
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.p2p import obfuscate
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.snapshot.blob_index import ChallengeTable
from backuwup_tpu.store import Store
from backuwup_tpu.wire import ProofStatus

BACKEND = CpuBackend(CDCParams.from_desired(4096))
KEYS = KeyManager.from_secret(b"\x21" * 32)
VERIFIER = b"\x07" * 32  # verifier client id
PID = b"\x42" * 12


def _rng_bytes(n, seed=5):
    return random.Random(seed).randbytes(n)


@pytest.fixture
def store(tmp_path):
    s = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    s.set_obfuscation_key(b"\xaa\x01\x7f\x33")
    yield s
    s.close()


def _install_packfile(store, verifier, pid, raw):
    """Store ``raw`` the way ReceivedFilesWriter would: obfuscated."""
    d = store.received_dir(verifier) / "pack"
    d.mkdir(parents=True, exist_ok=True)
    path = d / bytes(pid).hex()
    path.write_bytes(obfuscate(raw, store.get_obfuscation_key()))
    return path


# --------------------------------------------------------------------------
# challenge construction + table persistence
# --------------------------------------------------------------------------


def test_sample_windows_stay_in_bounds():
    rng = random.Random(3)
    for size in (1, 100, 65536, 300_000):
        for off, ln in sample_windows(size, 50, rand=rng.randbytes):
            assert 0 <= off and off + ln <= size
            assert ln == min(defaults.AUDIT_WINDOW_BYTES, size)
    with pytest.raises(ValueError):
        sample_windows(0, 4)


def test_challenge_table_roundtrip_and_write_once(tmp_path):
    data = _rng_bytes(200_000)
    entries = build_challenge_table(BACKEND, data, count=6)
    assert len(entries) == 6
    nonces = {e.nonce for e in entries}
    assert len(nonces) == 6  # fresh nonce per entry
    tables = ChallengeTable(KEYS, tmp_path)
    tables.save(PID, entries)
    assert tables.has(PID)
    assert tables.load(PID) == entries
    # single-use nonces must never be regenerated over the same id
    with pytest.raises(FileExistsError):
        tables.save(PID, entries)


def test_detection_probability_math():
    assert detection_probability(0.0, 16) == 0.0
    assert detection_probability(1.0, 1) == 1.0
    # the docs/audit.md headline number: 1% corruption, 16 probes
    assert detection_probability(0.01, 16) == pytest.approx(0.1485, abs=1e-3)
    assert detection_probability(0.1, 8) > 0.56


# --------------------------------------------------------------------------
# prover
# --------------------------------------------------------------------------


def test_deobfuscate_window_at_unaligned_offsets():
    key = b"\x13\x9e\x00\xf7"
    raw = _rng_bytes(1000)
    stream = obfuscate(raw, key)
    for off, ln in ((0, 100), (1, 37), (2, 500), (3, 997), (777, 223)):
        assert deobfuscate_window(stream[off:off + ln], key, off) == \
            raw[off:off + ln]


def test_prover_honest_proofs_match_table(store):
    raw = _rng_bytes(150_000)
    entries = build_challenge_table(BACKEND, raw, count=5)
    _install_packfile(store, VERIFIER, PID, raw)
    proofs = compute_proofs(store, BACKEND, VERIFIER, to_wire(PID, entries))
    assert [p.status for p in proofs] == [ProofStatus.OK] * 5
    assert [bytes(p.digest) for p in proofs] == [e.digest for e in entries]
    result = check_proofs(to_wire(PID, entries),
                          [e.digest for e in entries], proofs)
    assert result.passed and result.checked == 5


def test_prover_admits_missing_and_truncated(store):
    raw = _rng_bytes(150_000)
    entries = build_challenge_table(BACKEND, raw, count=4)
    path = _install_packfile(store, VERIFIER, PID, raw)
    challenges = to_wire(PID, entries)
    expected = [e.digest for e in entries]

    # truncated: windows past the cut come back SHORT
    path.write_bytes(path.read_bytes()[:1000])
    proofs = compute_proofs(store, BACKEND, VERIFIER, challenges)
    assert all(p.status in (ProofStatus.SHORT, ProofStatus.OK)
               for p in proofs)
    assert any(p.status == ProofStatus.SHORT for p in proofs)
    verdict = check_proofs(challenges, expected, proofs)
    assert not verdict.passed and "short" in verdict.detail

    # deleted: every proof is an honest MISSING
    path.unlink()
    proofs = compute_proofs(store, BACKEND, VERIFIER, challenges)
    assert [p.status for p in proofs] == [ProofStatus.MISSING] * 4
    verdict = check_proofs(challenges, expected, proofs)
    assert not verdict.passed and "missing" in verdict.detail


def test_check_proofs_rejects_count_mismatch_and_bad_digest():
    data = _rng_bytes(80_000)
    entries = build_challenge_table(BACKEND, data, count=3)
    challenges = to_wire(PID, entries)
    expected = [e.digest for e in entries]
    ok = [wire.StorageProof(packfile_id=PID, status=ProofStatus.OK,
                            digest=e.digest) for e in entries]
    assert check_proofs(challenges, expected, ok).passed
    assert not check_proofs(challenges, expected, ok[:2]).passed
    forged = ok[:2] + [replace(ok[2], digest=b"\x00" * 32)]
    verdict = check_proofs(challenges, expected, forged)
    assert not verdict.passed and "digest mismatch" in verdict.detail


# --------------------------------------------------------------------------
# verifier selection: single-use cursor
# --------------------------------------------------------------------------


def test_select_challenges_burns_cursor_and_exhausts(store, tmp_path):
    raw = _rng_bytes(100_000)
    tables = ChallengeTable(KEYS, tmp_path / "tables")
    tables.save(PID, build_challenge_table(BACKEND, raw, count=5))
    peer = b"\x50" * 32
    store.record_placement(PID, peer, len(raw))

    first, exp1 = select_challenges(store, tables, peer, samples=3)
    second, exp2 = select_challenges(store, tables, peer, samples=3)
    assert len(first) == 3 and len(second) == 2  # table holds only 5
    # burned: no (offset, nonce) is ever issued twice
    seen = {(c.offset, c.nonce) for c in first}
    assert not seen & {(c.offset, c.nonce) for c in second}
    assert select_challenges(store, tables, peer) == ([], [])


# --------------------------------------------------------------------------
# ledger policy
# --------------------------------------------------------------------------


def test_ledger_miss_demotion_threshold_and_backoff(store):
    peer = b"\x61" * 32
    t0 = 1_000_000.0
    st = record_miss(store, peer, now=t0)
    assert not st.demoted and st.misses == 1
    assert st.next_due == t0 + defaults.AUDIT_RETRY_BASE_S
    st = record_miss(store, peer, now=t0)
    assert not st.demoted
    assert st.next_due == t0 + 2 * defaults.AUDIT_RETRY_BASE_S  # backoff
    st = record_miss(store, peer, now=t0)  # 3rd consecutive: demoted
    assert st.demoted and st.consecutive_misses == \
        defaults.AUDIT_DEMOTE_MISSES
    # a later pass re-promotes and resets the streaks
    st = record_pass(store, peer, now=t0)
    assert not st.demoted and st.consecutive_misses == 0
    assert st.next_due == t0 + defaults.AUDIT_INTERVAL_S


def test_ledger_single_failure_demotes_and_excludes_peer(store):
    peer = b"\x62" * 32
    store.add_peer_negotiated(peer, 1 << 20)
    assert any(bytes(p.pubkey) == peer
               for p in store.find_peers_with_storage())
    st = record_fail(store, peer, "digest mismatch", now=2.0)
    assert st.demoted and st.failures == 1
    assert "digest mismatch" in st.last_result
    assert peer in {bytes(p) for p in store.demoted_peers()}
    # demoted peers drop out of the send-path ordering
    assert all(bytes(p.pubkey) != peer
               for p in store.find_peers_with_storage())


def test_audit_due_scheduling(store):
    peer = b"\x63" * 32
    store.record_placement(PID, peer, 1000, now=1.0)
    assert peer in [bytes(p) for p in store.audit_due_peers(now=2.0)]
    record_pass(store, peer, now=2.0)
    assert store.audit_due_peers(now=3.0) == []
    store.mark_audit_due(peer, now=3.0)  # server AuditDue push
    assert peer in [bytes(p) for p in store.audit_due_peers(now=3.0)]


# --------------------------------------------------------------------------
# coordination server: reports adjust matchmaking
# --------------------------------------------------------------------------


def test_server_blocks_peer_failing_for_multiple_reporters(tmp_path):
    from backuwup_tpu.net.server import ServerDB

    db = ServerDB(":memory:")
    peer, r1, r2 = b"\x70" * 32, b"\x71" * 32, b"\x72" * 32
    window = defaults.AUDIT_REPORT_WINDOW_S
    db.save_audit_report(r1, peer, False, "digest mismatch")
    assert db.audit_failing_reporters(peer, window) == 1
    db.save_audit_report(r2, peer, False, "missing")
    assert db.audit_failing_reporters(peer, window) == 2
    # a LATER pass from one reporter clears that reporter's vote
    db.save_audit_report(r1, peer, True, "")
    assert db.audit_failing_reporters(peer, window) == 1


def test_storage_queue_skips_audit_blocked_candidate(tmp_path):
    from backuwup_tpu.net.server import (
        Connections,
        ServerDB,
        StorageQueue,
    )

    class Online(Connections):
        def __init__(self):
            super().__init__()
            self.pushed = []

        def is_online(self, client_id):
            return True

        async def notify(self, client_id, msg):
            self.pushed.append((bytes(client_id), msg))
            return True

    async def run():
        db = ServerDB(":memory:")
        conns = Online()
        queue = StorageQueue(db, conns)
        bad, requester = b"\x80" * 32, b"\x81" * 32
        for reporter in (b"\x90" * 32, b"\x91" * 32):
            db.save_audit_report(reporter, bad, False, "missing")
        await queue.fulfill(bad, 1000)  # bad peer queues a request
        await queue.fulfill(requester, 1000)
        # the blocked candidate was skipped, not matched
        assert all(dst != bad for dst, _ in
                   [(d, m) for d, m in conns.pushed
                    if isinstance(m, wire.BackupMatched)])
        assert db.get_client_negotiated_peers(requester) == []

    asyncio.new_event_loop().run_until_complete(run())


# --------------------------------------------------------------------------
# end-to-end: the acceptance scenario
# --------------------------------------------------------------------------


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def _corpus(root, rng):
    root.mkdir(parents=True, exist_ok=True)
    (root / "data.bin").write_bytes(rng.randbytes(300_000))


def test_audit_e2e_detects_corruption_and_demotes(tmp_path, loop,
                                                  monkeypatch):
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer

    # the e2e round audits the same peer repeatedly; disable the prover's
    # per-peer serve throttle so back-to-back rounds are answered, and
    # grow the per-packfile table so four rounds never exhaust it
    monkeypatch.setattr(defaults, "AUDIT_SERVE_MIN_INTERVAL_S", 0.0)
    monkeypatch.setattr(defaults, "AUDIT_CHALLENGES_PER_PACKFILE", 64)
    rng = random.Random(11)
    _corpus(tmp_path / "a_src", rng)
    _corpus(tmp_path / "b_src", rng)

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=CpuBackend(CDCParams.from_desired(4096)))
            app.store.set_backup_path(str(tmp_path / f"{name}_src"))
            return app

        a, b = make_app("a"), make_app("b")
        audit_events = []
        a.messenger.subscribe(lambda ev: audit_events.append(ev)
                              if ev.kind == "audit" else None)
        await a.start()
        await b.start()
        # this test drives audit verdicts against data that stays put on
        # b; each failing leg demotes b, and the background repair that
        # demotion fires would orphan b's packfiles and retire their
        # challenge tables (dead data must not stay auditable), starving
        # the later legs — drive audits only, per the engine's test
        # contract
        a.engine.auto_repair = False
        await asyncio.wait_for(asyncio.gather(a.backup(), b.backup()), 120)
        assert a.store.peers_with_placements(), "no placements recorded"

        # --- round 1: intact data, >= 8 challenges, passes ---------------
        results = await asyncio.wait_for(a.engine.run_audit_round(), 60)
        verdict = results[bytes(b.client_id)]
        assert verdict.passed and verdict.checked >= 8, verdict
        st = a.store.get_audit_state(b.client_id)
        assert st.passes == 1 and not st.demoted
        assert [e.payload["outcome"] for e in audit_events] == ["pass"]

        # --- stale proof replay: wrong sequence number is rejected -------
        async def stale_prover(source, transport):
            body = await transport.recv_body(10)
            proofs = compute_proofs(b.store, b.engine.backend, source,
                                    body.challenges)
            await transport.send_body(wire.P2PBody(
                kind=wire.P2PBodyKind.PROOF,
                header=wire.P2PHeader(
                    sequence_number=body.header.sequence_number + 7,
                    session_nonce=transport.session_nonce),
                proofs=tuple(proofs)))

        b.node.on_audit_request = stale_prover
        a.store.mark_audit_due(b.client_id)
        results = await asyncio.wait_for(a.engine.run_audit_round(), 60)
        verdict = results[bytes(b.client_id)]
        assert not verdict.passed and "replayed" in verdict.detail
        b.node.on_audit_request = b._serve_audit
        record_pass(a.store, b.client_id)  # reset ledger for the next leg

        # --- round 2: corrupt one stored packfile, detect in one round ---
        pack_dir = b.store.received_dir(a.client_id) / "pack"
        victim = sorted(pack_dir.iterdir())[0]
        blob = bytearray(victim.read_bytes())
        # flip a byte every quarter-window so EVERY possible sampled
        # window covers corruption — the verdict must not depend on
        # which os.urandom table entries this round happens to burn
        for off in range(0, len(blob),
                         max(1, defaults.AUDIT_WINDOW_BYTES // 4)):
            blob[off] ^= 0xFF
        victim.write_bytes(bytes(blob))
        a.store.mark_audit_due(b.client_id)
        results = await asyncio.wait_for(a.engine.run_audit_round(), 60)
        verdict = results[bytes(b.client_id)]
        assert not verdict.passed, "corruption escaped a full audit round"
        st = a.store.get_audit_state(b.client_id)
        assert st.failures >= 1 and st.demoted
        assert bytes(b.client_id) in {bytes(p)
                                      for p in a.store.demoted_peers()}
        assert audit_events[-1].payload["outcome"] == "fail"
        assert audit_events[-1].payload["demoted"] is True
        # ... and the server heard about it
        assert server.db.audit_failing_reporters(
            bytes(b.client_id), defaults.AUDIT_REPORT_WINDOW_S) == 1

        # --- round 3: deleted packfile is an honest MISSING failure ------
        record_pass(a.store, b.client_id)
        victim.unlink()
        a.store.mark_audit_due(b.client_id)
        results = await asyncio.wait_for(a.engine.run_audit_round(), 60)
        verdict = results[bytes(b.client_id)]
        assert not verdict.passed and "missing" in verdict.detail

        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 240))


def test_audit_offline_peer_records_miss(tmp_path, loop):
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer

    rng = random.Random(12)
    _corpus(tmp_path / "a_src", rng)
    _corpus(tmp_path / "b_src", rng)

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=CpuBackend(CDCParams.from_desired(4096)))
            app.store.set_backup_path(str(tmp_path / f"{name}_src"))
            return app

        a, b = make_app("a"), make_app("b")
        await a.start()
        await b.start()
        await asyncio.wait_for(asyncio.gather(a.backup(), b.backup()), 120)

        # peer goes offline: the audit is a MISS, tolerated, backed off
        await b.stop()
        a.store.mark_audit_due(b.client_id)
        results = await asyncio.wait_for(a.engine.run_audit_round(), 60)
        verdict = results[bytes(b.client_id)]
        assert not verdict.passed and verdict.checked == 0
        st = a.store.get_audit_state(b.client_id)
        assert st.misses == 1 and not st.demoted  # offline is not data loss
        assert st.next_due > st.last_audit  # exponential backoff scheduled
        # challenges burned for the miss stay burned (single-use), but the
        # peer is NOT excluded from matchmaking
        assert bytes(b.client_id) not in {bytes(p)
                                          for p in a.store.demoted_peers()}

        await a.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 240))
