"""TPU (device-path) CDC scan must be bit-identical to the CPU oracle."""

import numpy as np
import pytest

import jax

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.cdc_tpu import (
    TpuCdcScanner,
    chunk_stream_sharded,
    gear_hashes_tpu,
)
from backuwup_tpu.ops.gear import CDCParams

SMALL = CDCParams.from_desired(4096)  # min 1024 / desired 4096 / max 12288


def _data(n, seed=7):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8).tobytes()


@pytest.mark.parametrize("n", [0, 1, 31, 32, 33, 1000, 4096, 65536, 200_000])
def test_hashes_match_oracle(n):
    data = _data(n)
    np.testing.assert_array_equal(gear_hashes_tpu(data),
                                  cdc_cpu.gear_hashes(data))


def test_hashes_with_halo():
    data = _data(10_000)
    tail, rest = data[:5000], data[5000:]
    got = gear_hashes_tpu(rest, prev_tail=tail)
    np.testing.assert_array_equal(got, cdc_cpu.gear_hashes(data)[5000:])


@pytest.mark.parametrize("n", [0, 1, 1023, 1024, 5000, 200_000, 1_000_000])
def test_chunks_match_oracle(n):
    data = _data(n, seed=n or 1)
    scanner = TpuCdcScanner(SMALL)
    assert scanner.chunk_stream(data) == cdc_cpu.chunk_stream(data, SMALL)


def test_chunks_multi_segment():
    # Segment smaller than the stream forces the carried-halo path.
    data = _data(300_000, seed=3)
    scanner = TpuCdcScanner(SMALL, segment_size=65536)
    assert scanner.chunk_stream(data) == cdc_cpu.chunk_stream(data, SMALL)


def test_chunk_invariants():
    data = _data(500_000, seed=9)
    chunks = TpuCdcScanner(SMALL).chunk_stream(data)
    assert sum(c[1] for c in chunks) == len(data)
    offsets = [c[0] for c in chunks]
    assert offsets == sorted(offsets)
    for off, ln in chunks[:-1]:
        assert SMALL.min_size <= ln <= SMALL.max_size
    assert chunks[-1][1] <= SMALL.max_size


def test_sharded_scan_matches_oracle():
    devs = jax.devices()
    assert len(devs) == 8, "conftest must provide 8 virtual devices"
    mesh = jax.sharding.Mesh(np.array(devs), ("data",))
    for n in (0, 1, 100_000, 777_777):
        data = _data(n, seed=n or 2)
        assert (chunk_stream_sharded(data, mesh, SMALL)
                == cdc_cpu.chunk_stream(data, SMALL))


def test_segment_overflow_falls_back_to_oracle(monkeypatch):
    # Force the sparse-word capacity below the real candidate count so the
    # oracle-rescan branch runs; output must stay bit-identical.
    data = _data(200_000, seed=11)
    scanner = TpuCdcScanner(SMALL, segment_size=65536)
    monkeypatch.setattr(scanner, "_k_cap", lambda padded: 512)
    n_cand = len(cdc_cpu.candidate_positions(data[:65536], SMALL)[1])
    assert n_cand > 0  # sanity: there are candidates to overflow with
    assert scanner.chunk_stream(data) == cdc_cpu.chunk_stream(data, SMALL)


def test_sharded_overflow_falls_back_to_oracle():
    devs = jax.devices()
    mesh = jax.sharding.Mesh(np.array(devs), ("data",))
    dense = CDCParams(min_size=64, desired_size=256, max_size=1024,
                      mask_s_bits=6, mask_l_bits=4)
    data = _data(300_000, seed=13)
    got = chunk_stream_sharded(data, mesh, dense, k_cap=512)
    assert got == cdc_cpu.chunk_stream(data, dense)


def test_scan_select_forced_cut_fallback_and_parallel_paths(rng):
    """The pointer-doubling selection and its sequential fallback must both
    be bit-identical to the oracle: zero runs force non-candidate cuts
    (fallback), random data stays on the parallel path, and mixtures cross
    between them mid-stream."""
    import functools

    import jax.numpy as jnp
    import numpy as np

    from backuwup_tpu.ops import cdc_cpu
    from backuwup_tpu.ops.cdc_tpu import _HALO, scan_select_batch
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.pipeline import DevicePipeline

    params = CDCParams.from_desired(1024)
    pipe = DevicePipeline(params, l_bucket=4)
    cases = [
        rng.randbytes(50_000),                      # parallel path
        b"\x00" * 40_000,                           # all forced (fallback)
        rng.randbytes(20_000) + b"\x00" * 20_000 + rng.randbytes(20_000),
        b"\x00" * 20_000 + rng.randbytes(30_000),   # forced then candidates
        rng.randbytes(1),                           # single byte
        rng.randbytes(params.min_size),             # exactly min
    ]
    P = 65536
    for data in cases:
        n = len(data)
        s_cap, l_cap, cut_cap = pipe._caps(P)
        buf = np.zeros((1, _HALO + P), dtype=np.uint8)
        buf[0, _HALO:_HALO + n] = np.frombuffer(data, dtype=np.uint8)
        fn = functools.partial(
            scan_select_batch, min_size=params.min_size,
            desired_size=params.desired_size, max_size=params.max_size,
            mask_s=params.mask_s, mask_l=params.mask_l,
            s_cap=s_cap, l_cap=l_cap, cut_cap=cut_cap)
        packed = np.asarray(fn(jnp.asarray(buf),
                               jnp.asarray(np.full(1, n, dtype=np.int32))))
        assert packed[0, 0] == 0, "unexpected overflow"
        n_cuts = int(packed[0, 1])
        ends = packed[0, 2:2 + n_cuts].tolist()
        ref = cdc_cpu.select_cuts(*_oracle_candidates(data, params),
                                  n, params).tolist()
        assert ends == ref, (n, len(ref))


def _oracle_candidates(data, params):
    from backuwup_tpu.ops import cdc_cpu
    return cdc_cpu.candidate_positions(data, params)
