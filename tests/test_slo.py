"""Live SLO plane: series window math on virtual time, multi-window
burn-rate gating, the evidence-ranked breach explainer, and the
``diagnosis`` scenario gate (docs/observability.md §Time series /
§SLOs & burn rates / §Diagnosis)."""

import asyncio
import time
from pathlib import Path

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.obs import diagnose as obs_diagnose
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs import slo as obs_slo
from backuwup_tpu.obs.series import SeriesRecorder, robust_zscore
from backuwup_tpu.sim.clock import SimClock

pytestmark = pytest.mark.slo


@pytest.fixture(autouse=True)
def _isolate():
    """Zero the process registry and drop any installed journal so tests
    never see each other's series."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# --- series recorder --------------------------------------------------------


def test_series_delta_is_counter_reset_safe():
    rec = SeriesRecorder(())
    for t, v in ((0, 10.0), (1, 14.0), (2, 3.0), (3, 5.0)):
        rec.record("bkw_c_total", v, t=t, kind="counter")
    # 10->14 (+4), 14->3 (reset: accrue the post-reset floor 3),
    # 3->5 (+2) — never a negative burn
    assert rec.delta("bkw_c_total", 10.0) == 9.0
    assert rec.rate("bkw_c_total", 10.0) == 3.0
    assert rec.span("bkw_c_total", 10.0) == 3.0


def test_series_retention_caps_per_family():
    rec = SeriesRecorder({"k": 8})
    for i in range(30):
        rec.record("k", float(i), t=float(i))
    pts = rec.points("k")
    assert len(pts) == 8 and pts[-1] == (29.0, 29.0)
    # unknown families fall back to the recorder-wide default
    rec2 = SeriesRecorder((), retention=4)
    for i in range(9):
        rec2.record("other", float(i), t=float(i))
    assert len(rec2.points("other")) == 4


def test_series_window_is_anchored_on_last_point():
    rec = SeriesRecorder(())
    for t in (0.0, 5.0, 9.0, 10.0):
        rec.record("g", t, t=t)
    assert [p[0] for p in rec.points("g", 5.0)] == [5.0, 9.0, 10.0]


def test_series_anomaly_flags_level_shift_not_flat():
    rec = SeriesRecorder(())
    for t in range(12):
        rec.record("flat", 7.0, t=float(t))
        rec.record("jump", 100.0 if t == 11 else 1.0, t=float(t))
    flagged = rec.anomalies(window_s=60.0)
    assert [a["key"] for a in flagged] == ["jump"]
    assert flagged[0]["z"] >= defaults.SERIES_ANOMALY_Z
    # MAD == 0 with a genuine outlier hits the cap, not a ZeroDivision
    assert robust_zscore([1.0] * 8 + [50.0]) == 99.0


def test_series_samples_registry_families_on_the_sim_clock():
    clock = SimClock()
    reg = obs_metrics.registry()
    ctr = reg.counter("bkw_sl_events_total", "h", ("op",))
    hist = reg.histogram("bkw_sl_lat_seconds", "h")
    rec = SeriesRecorder(("bkw_sl_events_total", "bkw_sl_lat_seconds"),
                         clock=clock)
    for step in range(6):
        ctr.inc(2, op="put")
        hist.observe(0.002 if step < 5 else 30.0)
        rec.sample()
        clock.advance_to(float(step + 1) * 10.0)
    keys = rec.family_keys("bkw_sl_events_total", {"op": "put"})
    assert keys == ['bkw_sl_events_total{op=put}']
    assert rec.delta(keys[0], 100.0) == 10.0  # 5 sampled steps of +2
    hkey = rec.family_keys("bkw_sl_lat_seconds", {})[0]
    frac = rec.fraction_over(hkey, 100.0, 1.0)
    assert frac == pytest.approx(1 / 5)
    assert rec.samples_taken == 6
    fam = reg.get("bkw_series_samples_total")
    assert sum(s["value"] for s in fam._snapshot_series()) == 6


# --- burn-rate gating -------------------------------------------------------

_WINDOWS = ((4.0, 12.0), (30.0, 60.0))


def _monitor(rec, hook=None):
    catalog = [obs_slo.Objective(
        id="viol", kind="counter_rate", family="bkw_viol_total",
        budget=0.05)]
    return obs_slo.SLOMonitor(rec, catalog=catalog, windows=_WINDOWS,
                              on_breach=hook)


def test_slo_spike_does_not_page_sustained_burn_does():
    rec = SeriesRecorder(())
    breaches = []
    mon = _monitor(rec, breaches.append)
    cum = 0.0
    for t in range(101):  # a quiet window of history
        rec.record("bkw_viol_total", cum, t=float(t), kind="counter")
    # 4 s spike: the fast-short window burns (frac 1.0 / 0.05 = 20x)
    # but fast-long holds 4/12 => 6.7x < 14.4 — no page
    for t in range(101, 105):
        cum += 1.0
        rec.record("bkw_viol_total", cum, t=float(t), kind="counter")
    assert mon.evaluate(now=104.0) == {"viol": "ok"}
    assert breaches == []
    # sustained: fast-long reaches frac 1.0 as well — both fire
    for t in range(105, 117):
        cum += 1.0
        rec.record("bkw_viol_total", cum, t=float(t), kind="counter")
    assert mon.evaluate(now=116.0) == {"viol": "violated"}
    assert len(breaches) == 1 and len(mon.breaches) == 1
    b = breaches[0]
    assert b.objective == "viol" and b.t == 116.0
    assert b.prev_status == "ok" and b.status == "violated"
    assert b.burns["4s"] >= mon.fast_burn <= b.burns["12s"]
    # recovery: flat counter -> burn 0 -> ok again, no second breach
    for t in range(117, 140):
        rec.record("bkw_viol_total", cum, t=float(t), kind="counter")
    assert mon.evaluate(now=139.0) == {"viol": "ok"}
    assert len(mon.breaches) == 1
    assert mon.summary()["status"] == "ok"


def test_slo_no_signal_scores_burn_zero():
    mon = _monitor(SeriesRecorder(()))
    assert mon.evaluate(now=10.0) == {"viol": "ok"}
    assert mon.last_burns["viol"] == {
        "4s": 0.0, "12s": 0.0, "30s": 0.0, "60s": 0.0}


def test_slo_status_exports_and_registry_summary():
    rec = SeriesRecorder(())
    mon = _monitor(rec)
    cum = 0.0
    for t in range(80):
        cum += 1.0
        rec.record("bkw_viol_total", cum, t=float(t), kind="counter")
    assert mon.evaluate(now=79.0)["viol"] == "violated"
    s = obs_slo.summary_from_registry()
    assert s["status"] == "violated"
    assert s["objectives"] == {"viol": "violated"} and s["breaches"] == 1
    assert obs_slo.join_status("ok", "degraded", "ok") == "degraded"
    assert obs_slo.join_status() == "ok"


def test_slo_catalog_parses_and_rejects_malformed():
    objectives = obs_slo.parse_catalog()
    assert [o.id for o in objectives] == \
        [e["id"] for e in defaults.SLO_CATALOG]
    with pytest.raises(obs_slo.SLOError):
        obs_slo.parse_catalog([{"id": "x", "kind": "nope",
                                "family": "f", "budget": 0.1}])
    with pytest.raises(obs_slo.SLOError):
        obs_slo.parse_catalog([{"id": "x", "kind": "ratio",
                                "family": "f", "budget": 0.1}])
    with pytest.raises(obs_slo.SLOError):
        obs_slo.parse_catalog(
            [{"id": "x", "kind": "counter_rate", "family": "f",
              "budget": 0.1}] * 2)


# --- diagnosis --------------------------------------------------------------


def _breach(t=1000.0):
    return obs_slo.Breach(objective="viol", t=t, status="violated",
                          prev_status="ok", burns={"4s": 20.0},
                          window_s=12.0)


def test_diagnose_ranks_fault_first_and_is_deterministic():
    rec = SeriesRecorder(())
    for t in range(988, 1000):
        rec.record("noise", 100.0 if t == 999 else 1.0, t=float(t))
    events = [
        {"ts": 998.0, "kind": "fault", "site": "dial.dead:abcd1234"},
        {"ts": 998.5, "kind": "fault", "site": "dial.dead:abcd1234"},
        {"ts": 999.0, "kind": "durability", "status": "violated"},
        {"ts": 997.0, "kind": "placement_demotion", "peer": "abcd1234"},
        {"ts": 100.0, "kind": "fault", "site": "ancient.crash"},  # stale
    ]
    r1 = obs_diagnose.explain(_breach(), recorder=rec, events=events)
    r2 = obs_diagnose.explain(_breach(), recorder=rec, events=events)
    assert r1 == r2
    ids = [c["id"] for c in r1["causes"]]
    assert ids[0] == "fault:dial.dead:abcd1234"
    assert r1["causes"][0]["count"] == 2
    assert r1["causes"][0]["score"] > 4.0  # repeat bonus on top
    assert "durability:violated" in ids
    assert "event:placement_demotion" in ids
    assert "series:noise" in ids  # anomaly evidence, weakest layer
    assert ids.index("durability:violated") < ids.index("series:noise")
    assert "fault:ancient.crash" not in ids  # outside the window
    assert r1["objective"] == "viol" and r1["evidence_events"] == 4


def test_diagnose_reads_installed_journal_and_counts_reports(tmp_path):
    obs_journal.install(obs_journal.Journal(tmp_path / "j.jsonl"))
    obs_journal.emit("fault", site="send.dead:feedbeef")
    # breaches stamp clock.now() — the journal's epoch axis — so the
    # explainer's window lines up with the emitted event's ts
    breach = obs_slo.Breach(objective="viol", t=time.time(),
                            status="violated", prev_status="ok",
                            burns={}, window_s=0.0)
    report = obs_diagnose.explain(breach)
    assert [c["id"] for c in report["causes"]] == [
        "fault:send.dead:feedbeef"]
    # the report itself lands in the journal (skipped as evidence)
    kinds = [r["kind"] for r in obs_journal.get().tail(10)]
    assert "diagnosis_report" in kinds
    fam = obs_metrics.registry().get("bkw_diagnosis_reports_total")
    assert sum(s["value"] for s in fam._snapshot_series()) == 1


def test_diagnose_truncates_to_top_and_caps_series_score():
    rec = SeriesRecorder(())
    events = [{"ts": 999.0, "kind": f"thing_{i}", "reason": "x"}
              for i in range(12)]
    report = obs_diagnose.explain(_breach(), recorder=rec,
                                  events=events, top=3)
    assert len(report["causes"]) == 3
    assert all(c["score"] <= 4.0 for c in report["causes"])


# --- the composed acceptance gate -------------------------------------------


@pytest.mark.scenario
def test_diagnosis_scenario_gate(tmp_path, loop):
    """The PR-20 acceptance run: quiet baseline, three of six holders
    permanently dark, durability flips violated — the breach must land
    within two sweep intervals, with zero pre-fault breaches and the
    armed fault site in the explainer's top-3 causes."""
    from backuwup_tpu.scenario import builtin_scenarios
    from backuwup_tpu.scenario.harness import ScenarioHarness

    spec = builtin_scenarios()["diagnosis"]
    harness = ScenarioHarness(spec, Path(tmp_path))

    async def go():
        await harness.setup()
        try:
            return await harness.run()
        finally:
            await harness.teardown()

    card = loop.run_until_complete(go())
    assert card.passed, card.render()
    by_name = {a.name: a for a in card.assertions}
    for gate in ("slo_breach_detected", "slo_no_false_positives",
                 "diagnosis_names_fault"):
        assert by_name[gate].passed, by_name[gate].detail
    slo = harness.facts["slo"]
    assert slo["precision"] == 1.0 and slo["breaches"] >= 1
    assert slo["detection_s"] is not None
    # two of the harness's patched 0.5 s sweep intervals
    assert slo["detection_s"] <= 1.0
