"""Full-system loopback integration: the reference's manual two-client test
(docs/src/client.md:41-45), automated.

Two clients + coordination server in one process.  A and B both request
storage, get matched, back up to each other; A then loses its local data
and restores everything from B byte-identically.
"""

import asyncio
import random
import shutil
from pathlib import Path

import pytest

from backuwup_tpu.app import ClientApp
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams

SMALL = CDCParams.from_desired(4096)


def _corpus(root: Path, rng: random.Random, tag: str):
    (root / "sub").mkdir(parents=True)
    files = {
        "hello.txt": f"hello from {tag}\n".encode(),
        "data.bin": rng.randbytes(400_000),
        "sub/nested.bin": rng.randbytes(120_000),
        "sub/dup.bin": rng.randbytes(60_000) * 2,
    }
    for rel, data in files.items():
        (root / rel).write_bytes(data)
    return files


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_two_client_backup_restore_cycle(tmp_path, loop):
    rng = random.Random(42)
    src_a = tmp_path / "a_src"
    src_b = tmp_path / "b_src"
    src_a.mkdir()
    src_b.mkdir()
    files_a = _corpus(src_a, rng, "a")
    _corpus(src_b, rng, "b")

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"

        def make_app(name):
            return ClientApp(config_dir=tmp_path / name / "cfg",
                             data_dir=tmp_path / name / "data",
                             server_addr=addr, backend=CpuBackend(SMALL))

        a = make_app("a")
        b = make_app("b")
        await a.start()
        await b.start()
        a.store.set_backup_path(str(src_a))
        b.store.set_backup_path(str(src_b))

        # both clients back up concurrently — their storage requests match
        # each other (the economy needs a counterparty)
        snap_a, snap_b = await asyncio.wait_for(
            asyncio.gather(a.backup(), b.backup()), 120)
        assert len(snap_a) == 32 and len(snap_b) == 32

        # A's packfiles left the machine (deleted after ack)
        assert a.engine._unsent_packfiles() == []
        # B holds obfuscated data for A
        stored_for_a = list(
            (b.store.received_dir(a.client_id) / "pack").iterdir())
        assert stored_for_a, "B must hold A's packfiles"

        # server knows both snapshots
        assert server.db.get_latest_client_snapshot(a.client_id) == snap_a
        assert server.db.get_latest_client_snapshot(b.client_id) == snap_b

        # --- disaster: A loses everything local ----------------------------
        shutil.rmtree(src_a)
        dest = tmp_path / "a_restored"
        restored = await asyncio.wait_for(a.restore(dest), 60)
        for rel, data in files_a.items():
            assert (restored / rel).read_bytes() == data, rel

        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 180))


def test_two_client_cycle_device_backend_and_mesh_dedup(tmp_path, loop):
    """The same backup->disaster->restore cycle with the production device
    pipeline (TpuBackend resident batches) and dedup decisions routed
    through the sharded HBM index on the 8-device mesh, host BlobIndex
    parity asserted throughout (VERDICT round-1 item 2)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from backuwup_tpu.ops.backend import TpuBackend

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    rng = random.Random(1234)
    src_a = tmp_path / "a_src"
    src_b = tmp_path / "b_src"
    src_a.mkdir()
    src_b.mkdir()
    files_a = _corpus(src_a, rng, "a")
    _corpus(src_b, rng, "b")

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"

        def make_app(name):
            return ClientApp(config_dir=tmp_path / name / "cfg",
                             data_dir=tmp_path / name / "data",
                             server_addr=addr, backend=TpuBackend(SMALL),
                             dedup_mesh=mesh)

        a = make_app("a")
        b = make_app("b")
        await a.start()
        await b.start()
        a.store.set_backup_path(str(src_a))
        b.store.set_backup_path(str(src_b))

        snap_a, snap_b = await asyncio.wait_for(
            asyncio.gather(a.backup(), b.backup()), 300)
        assert len(snap_a) == 32 and len(snap_b) == 32
        assert a.engine.device_dedup is not None
        # the dup.bin corpus file repeats a 60k block: dedup must have fired
        # on the very first backup (device-routed classification)
        assert a.engine.last_pack_stats.chunks_deduped > 0

        shutil.rmtree(src_a)
        dest = tmp_path / "a_restored"
        restored = await asyncio.wait_for(a.restore(dest), 120)
        for rel, data in files_a.items():
            assert (restored / rel).read_bytes() == data, rel

        # incremental re-backup: identical content, so the device-routed
        # dedup must classify every chunk duplicate (the snapshot id itself
        # changes — tree metadata carries fresh ctimes)
        for rel, data in files_a.items():
            (src_a / rel).parent.mkdir(parents=True, exist_ok=True)
            (src_a / rel).write_bytes(data)
        await asyncio.wait_for(a.backup(), 300)
        stats = a.engine.last_pack_stats
        assert stats.chunks > 0
        assert stats.chunks_deduped >= stats.chunks

        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 600))


def test_backup_resumes_after_interrupted_send(tmp_path, loop):
    """Packfiles that never got acked stay local and are re-sent by the next
    backup run (send.rs:82-92 semantics)."""
    rng = random.Random(7)
    src = tmp_path / "src"
    src.mkdir()
    _corpus(src, rng, "solo")

    async def run():
        server = CoordinationServer()
        port = await server.start()
        addr = f"127.0.0.1:{port}"
        solo = ClientApp(config_dir=tmp_path / "solo" / "cfg",
                         data_dir=tmp_path / "solo" / "data",
                         server_addr=addr, backend=CpuBackend(SMALL))
        await solo.start()
        solo.store.set_backup_path(str(src))
        # no counterparty online: the backup's send loop can't finish; pack
        # completes, packfiles stay local
        task = asyncio.create_task(solo.backup())
        for _ in range(200):
            await asyncio.sleep(0.05)
            if solo.engine.orchestrator.packing_completed:
                break
        assert solo.engine.orchestrator.packing_completed
        assert solo.engine._unsent_packfiles(), "data must wait locally"
        for _ in range(100):  # the send loop issues the request on its next tick
            if server.queue.pending() >= 1:
                break
            await asyncio.sleep(0.05)
        assert server.queue.pending() >= 1  # storage request queued
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass
        await solo.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_three_client_disjoint_restore(tmp_path, loop, monkeypatch):
    """Restore parity (VERDICT r2 item 3): A's backup history is split
    across two peers (first snapshot lands on B, the incremental second on
    C); restore fans out to both concurrently and completes only when BOTH
    streams land; the staging buffer is removed after success."""
    from backuwup_tpu import defaults

    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 40_000)
    monkeypatch.setattr(defaults, "STORAGE_REQUEST_STEP", 150_000)
    monkeypatch.setattr(defaults, "STORAGE_REQUEST_RETRY_S", 0.2)
    monkeypatch.setattr(defaults, "PEER_OVERUSE_GRACE", 10_000)
    monkeypatch.setattr(defaults, "RESTORE_REQUEST_THROTTLE_S", 0.0)

    rng = random.Random(77)
    src = {}
    for name, size in (("a", 120_000), ("b", 100_000), ("c", 5_000)):
        d = tmp_path / f"{name}_src"
        d.mkdir()
        (d / "data.bin").write_bytes(rng.randbytes(size))
        src[name] = d

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=addr, backend=CpuBackend(SMALL))
            app.store.set_backup_path(str(src[name]))
            return app

        a, b, c = make_app("a"), make_app("b"), make_app("c")
        await a.start()
        await b.start()

        # phase 1: only B is online; A's first snapshot lands wholly on B
        snap1, _ = await asyncio.wait_for(
            asyncio.gather(a.backup(), b.backup()), 120)

        # phase 2: new data; C comes online and the incremental snapshot's
        # fresh packfiles land on C (B's allowance is nearly exhausted)
        new_data = rng.randbytes(120_000)
        (src["a"] / "more.bin").write_bytes(new_data)
        await c.start()
        a2_task = asyncio.create_task(a.backup())
        await asyncio.wait_for(c.backup(), 60)
        snap2 = await asyncio.wait_for(a2_task, 120)
        assert snap2 != snap1

        # disjoint split: both B and C hold some of A's packfiles
        held_b = list((b.store.received_dir(a.client_id) / "pack").rglob("*"))
        held_c = list((c.store.received_dir(a.client_id) / "pack").rglob("*"))
        assert any(p.is_file() for p in held_b), "B holds none of A's data"
        assert any(p.is_file() for p in held_c), "C holds none of A's data"

        # --- disaster ------------------------------------------------------
        files_a = {rel: (src["a"] / rel).read_bytes()
                   for rel in ("data.bin", "more.bin")}
        shutil.rmtree(src["a"])

        # with C offline, the restore must fail loudly (both streams are
        # required), and the staging buffer must survive for retry
        await c.stop()
        from backuwup_tpu.engine import EngineError
        with pytest.raises(EngineError, match="restore incomplete"):
            await asyncio.wait_for(a.restore(tmp_path / "a_restored"), 60)

        # C back online: restore fans out to both peers and completes
        c2 = ClientApp(config_dir=tmp_path / "c" / "cfg",
                       data_dir=tmp_path / "c" / "data",
                       server_addr=addr, backend=CpuBackend(SMALL))
        await c2.start()
        dest = tmp_path / "a_restored2"
        restored = await asyncio.wait_for(a.restore(dest), 120)
        for rel, data in files_a.items():
            assert (restored / rel).read_bytes() == data, rel
        # staging buffer cleaned up after success (backup/mod.rs:180)
        assert not a.store.restore_dir().exists() or \
            not any(a.store.restore_dir().iterdir())

        await a.stop()
        await b.stop()
        await c2.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 300))

def test_restore_tolerates_phantom_negotiated_peer(tmp_path, loop,
                                                   monkeypatch):
    """A phantom negotiation (server crashed between record and notify —
    see the matcher's crash-window note in net/server.py) lists a peer
    that stores nothing for us and refuses our dial.  Restore must still
    succeed when the remaining peers' data covers the snapshot."""
    from backuwup_tpu import defaults

    monkeypatch.setattr(defaults, "STORAGE_REQUEST_RETRY_S", 0.2)
    monkeypatch.setattr(defaults, "RESTORE_REQUEST_THROTTLE_S", 0.0)

    rng = random.Random(99)
    src = tmp_path / "a_src"
    src.mkdir()
    files = _corpus(src, rng, "phantom")

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"

        def make_app(name, path):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=addr, backend=CpuBackend(SMALL))
            app.store.set_backup_path(str(path))
            return app

        b_src = tmp_path / "b_src"
        b_src.mkdir()
        (b_src / "x.bin").write_bytes(rng.randbytes(500_000))
        a, b = make_app("a", src), make_app("b", b_src)
        await a.start()
        await b.start()
        await asyncio.wait_for(asyncio.gather(a.backup(), b.backup()), 120)

        # c registers but never exchanges data with a — then the server
        # "crashes" mid-match, leaving only the phantom DB record
        c = make_app("c", b_src)
        await c.start()
        server.db.save_storage_negotiated(a.client_id, c.client_id, 50_000)
        server.db.save_storage_negotiated(c.client_id, a.client_id, 50_000)

        shutil.rmtree(src)
        dest = tmp_path / "a_restored"
        restored = await asyncio.wait_for(a.restore(dest), 120)
        for rel, data in files.items():
            assert (restored / rel).read_bytes() == data, rel

        await a.stop()
        await b.stop()
        await c.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 120))
