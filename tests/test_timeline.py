"""Timeline export (obs/timeline.py): spans -> Chrome trace events.

A golden test pins the exact event list for a small synthetic journal
(span tree with close-time/duration math, instants, process metadata,
track assignment), and a cross-process test drives the REAL span
machinery — two Journal files, a trace id carried from the sender's
span into the receiver's ``trace.bind`` the way wire envelopes carry it
— and requires the merged document to correlate both processes under
the one trace id.
"""

import json

from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import timeline
from backuwup_tpu.obs import trace

# Synthetic journal records: span lines record CLOSE time + dur_s, the
# way obs/trace.py writes them.
SENDER = [
    {"ts": 12.0, "kind": "span", "name": "engine.backup",
     "trace_id": "t1", "span_id": "s1", "parent_id": None, "dur_s": 2.0},
    {"ts": 10.5, "kind": "span", "name": "packer.manifest_many",
     "trace_id": "t1", "span_id": "s2", "parent_id": "s1", "dur_s": 0.5},
    {"ts": 11.0, "kind": "backup_started", "trace_id": "t1",
     "snapshot": "abcd"},
    {"ts": 11.5, "kind": "span", "name": "unrelated.trace",
     "trace_id": "t2", "span_id": "s9", "parent_id": None, "dur_s": 0.1},
    {"ts": 11.6, "kind": "checkpoint"},  # no trace id: track 0
]
RECEIVER = [
    {"ts": 11.2, "kind": "span", "name": "receiver.store",
     "trace_id": "t1", "span_id": "r1", "parent_id": None, "dur_s": 0.2},
]


def test_golden_trace_events():
    events = timeline.to_trace_events(
        [("sender", SENDER), ("receiver", RECEIVER)])
    assert events == [
        # metadata rows sort first
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "sender"}},
        {"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
         "args": {"name": "receiver"}},
        # start = close - dur; "t1" is the sender's first track
        {"name": "engine.backup", "cat": "span", "ph": "X",
         "ts": 10_000_000, "dur": 2_000_000, "pid": 1, "tid": 1,
         "args": {"trace_id": "t1", "span_id": "s1", "parent_id": None}},
        {"name": "packer.manifest_many", "cat": "span", "ph": "X",
         "ts": 10_000_000, "dur": 500_000, "pid": 1, "tid": 1,
         "args": {"trace_id": "t1", "span_id": "s2", "parent_id": "s1"}},
        {"name": "backup_started", "cat": "journal", "ph": "i", "s": "t",
         "ts": 11_000_000, "pid": 1, "tid": 1,
         "args": {"trace_id": "t1", "snapshot": "abcd"}},
        {"name": "receiver.store", "cat": "span", "ph": "X",
         "ts": 11_000_000, "dur": 200_000, "pid": 2, "tid": 1,
         "args": {"trace_id": "t1", "span_id": "r1", "parent_id": None}},
        # second distinct trace in the sender journal: second track
        {"name": "unrelated.trace", "cat": "span", "ph": "X",
         "ts": 11_400_000, "dur": 100_000, "pid": 1, "tid": 2,
         "args": {"trace_id": "t2", "span_id": "s9", "parent_id": None}},
        # traceless instant lands on track 0
        {"name": "checkpoint", "cat": "journal", "ph": "i", "s": "t",
         "ts": 11_600_000, "pid": 1, "tid": 0, "args": {}},
    ]


def test_trace_id_filter_cuts_to_one_backup():
    events = timeline.to_trace_events(
        [("sender", SENDER), ("receiver", RECEIVER)], trace_id="t1")
    names = [e["name"] for e in events if e["ph"] != "M"]
    # t2 span and the traceless instant are gone; t1 survives everywhere
    assert "unrelated.trace" not in names
    assert "checkpoint" not in names
    assert set(names) == {"engine.backup", "packer.manifest_many",
                          "backup_started", "receiver.store"}
    assert all(e["args"]["trace_id"] == "t1"
               for e in events if e["ph"] == "X")


def test_zero_duration_span_still_renders():
    events = timeline.to_trace_events(
        [("j", [{"ts": 5.0, "kind": "span", "name": "tiny",
                 "trace_id": "t", "span_id": "s", "parent_id": None,
                 "dur_s": 0.0}])])
    (span,) = [e for e in events if e["ph"] == "X"]
    assert span["dur"] == 1  # Perfetto drops dur=0 slices


def test_journal_records_skips_torn_lines(tmp_path):
    p = tmp_path / "j.jsonl"
    p.write_text('{"ts": 1.0, "kind": "ok"}\n'
                 '{"ts": 2.0, "kind": "torn', encoding="utf-8")
    recs = timeline.journal_records(p)
    assert [r["kind"] for r in recs] == ["ok"]
    assert timeline.journal_records(tmp_path / "missing.jsonl") == []


def test_cross_process_merge_by_trace_id(tmp_path):
    """Two real journals, the trace id carried sender -> receiver via
    trace.bind exactly as the wire envelope does: the merged timeline
    must show both processes' spans on the one trace."""
    sender_path = tmp_path / "sender.jsonl"
    receiver_path = tmp_path / "receiver.jsonl"

    obs_journal.install(obs_journal.Journal(sender_path))
    try:
        with trace.span("engine.backup") as ctx:
            tid = ctx.trace_id
            with trace.span("transfer.send"):
                pass
    finally:
        obs_journal.uninstall()

    obs_journal.install(obs_journal.Journal(receiver_path))
    try:
        with trace.bind(tid):  # what _verify_body does with the envelope
            with trace.span("receiver.store"):
                pass
    finally:
        obs_journal.uninstall()

    out = tmp_path / "timeline.json"
    doc = timeline.export_timeline(
        [sender_path, receiver_path], out, trace_id=tid,
        labels=["sender", "receiver"])
    events = doc["traceEvents"]
    assert doc["otherData"]["generator"] == "backuwup-tpu obs.timeline"

    spans = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(spans) == {"engine.backup", "transfer.send",
                          "receiver.store"}
    assert all(e["args"]["trace_id"] == tid for e in spans.values())
    # the two journals really are two Perfetto processes
    assert spans["engine.backup"]["pid"] == 1
    assert spans["transfer.send"]["pid"] == 1
    assert spans["receiver.store"]["pid"] == 2
    # child nests inside its parent on the sender timeline (±5 us for
    # the independent close-timestamp/duration roundings)
    parent, child = spans["engine.backup"], spans["transfer.send"]
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    assert parent["ts"] <= child["ts"] + 5
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 5
    # labelled process metadata made it through
    meta = {e["pid"]: e["args"]["name"]
            for e in events if e["ph"] == "M"}
    assert meta == {1: "sender", 2: "receiver"}
    # and the on-disk document reloads identically
    assert json.loads(out.read_text(encoding="utf-8")) == doc
