"""Virtual-clock simulation plane (backuwup_tpu/sim, docs/simulation.md).

Units first: SimClock event ordering and sleep parking, SimDriver
quiescence (including the failure-propagation and stuck-task contracts
that keep determinism honest).  Then the point of the plane: REAL
production code — RetryTimer, InvariantMonitor, ShardedMatchmaker over
a real SqliteServerStore — running on virtual time with exact-value
assertions no wall clock could support.  Integration: same seed ⇒
byte-identical scorecard, and the tier-1 acceptance run — a simulated
week of 10⁵-client churn through regionfail with its gates.  The 10⁶
soak rides the same path, marked slow.
"""

import asyncio
import json

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.net.matchmaking import ShardedMatchmaker
from backuwup_tpu.net.serverstore import SqliteServerStore
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs.invariants import InvariantMonitor
from backuwup_tpu.sim import (BUILTINS, SimClock, SimDriver, card_json,
                              run_sim)
from backuwup_tpu.store import Store
from backuwup_tpu.utils import retry

pytestmark = pytest.mark.sim

WEEK_S = 7 * 86_400.0


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def pk(i: int) -> bytes:
    return i.to_bytes(8, "big") + bytes(24)


def _ctr(name: str, **labels) -> float:
    fam = obs_metrics.registry().get(name)
    return fam.value(**labels) if fam is not None else 0.0


# --- SimClock ---------------------------------------------------------------


def test_clock_fires_in_deadline_order_with_submission_tiebreak(loop):
    clock = SimClock()
    driver = SimDriver(clock)
    fired = []
    clock.call_at(5.0, fired.append, "b")
    clock.call_at(2.0, fired.append, "a")
    clock.call_at(5.0, fired.append, "c")  # same deadline: after "b"
    clock.call_later(1.0, fired.append, "first")
    loop.run_until_complete(driver.run(until=10.0))
    assert fired == ["first", "a", "b", "c"]
    assert clock.now() == clock.monotonic() == 10.0
    assert driver.events == 4


def test_clock_clamps_past_deadlines_to_now(loop):
    clock = SimClock(start=100.0)
    driver = SimDriver(clock)
    fired = []
    clock.call_at(3.0, lambda: fired.append(clock.now()))
    loop.run_until_complete(driver.run(until=100.0))
    assert fired == [100.0]  # the past is not addressable


def test_clock_sleep_parks_until_virtual_deadline(loop):
    clock = SimClock()
    driver = SimDriver(clock)
    woke = []

    async def sleeper():
        await clock.sleep(30.0)
        woke.append(clock.now())
        await clock.sleep(12.5)
        woke.append(clock.now())

    async def scenario():
        task = driver.spawn(sleeper())
        await driver.run(until=100.0)
        assert task.done() and clock.blocked == 0

    loop.run_until_complete(scenario())
    assert woke == [30.0, 42.5]


# --- SimDriver contracts ----------------------------------------------------


def test_driver_awaits_async_handlers_inline(loop):
    clock = SimClock()
    driver = SimDriver(clock)
    order = []

    async def handler(tag):
        order.append(("start", tag, clock.now()))
        order.append(("end", tag))

    clock.call_at(1.0, handler, "x")
    clock.call_at(2.0, handler, "y")
    loop.run_until_complete(driver.run(until=5.0))
    # x ran to completion before y fired — no interleaving
    assert order == [("start", "x", 1.0), ("end", "x"),
                     ("start", "y", 2.0), ("end", "y")]


def test_driver_propagates_spawned_task_failures(loop):
    clock = SimClock()
    driver = SimDriver(clock)

    async def doomed():
        await clock.sleep(5.0)
        raise ValueError("sim model bug")

    async def scenario():
        driver.spawn(doomed())
        await driver.run(until=10.0)

    with pytest.raises(ValueError, match="sim model bug"):
        loop.run_until_complete(scenario())


def test_driver_refuses_tasks_parked_off_the_clock(loop):
    """A spawned task blocked on anything but SimClock.sleep would make
    time advance past work that is still pending: the driver raises
    instead of silently racing."""
    clock = SimClock()
    driver = SimDriver(clock)

    async def stuck():
        await asyncio.get_running_loop().create_future()  # never set

    async def scenario():
        driver.spawn(stuck())
        await driver.run(until=1.0)

    with pytest.raises(RuntimeError, match="did not quiesce"):
        loop.run_until_complete(scenario())
    loop.run_until_complete(driver.shutdown())


# --- real production code on virtual time -----------------------------------


def test_retry_timer_reads_the_injected_clock():
    clock = SimClock(start=1000.0)
    p = retry.RetryPolicy(base_s=10.0, cap_s=40.0, jitter=0.0)
    t = retry.RetryTimer(p, clock=clock)
    assert t.due()  # fresh timer fires immediately
    t.fire()
    clock.advance_to(1005.0)
    assert not t.due()
    clock.advance_to(1010.0)
    assert t.due()


def test_invariant_monitor_cadence_on_virtual_clock(tmp_path, loop):
    """InvariantMonitor.run — the production background task, not a
    copy — sweeps on the virtual cadence: five sweeps across 21 virtual
    seconds at interval 5, zero wall waiting."""
    obs_metrics.registry().reset()
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    clock = SimClock()
    driver = SimDriver(clock)
    mon = InvariantMonitor(store, client="simcadence", clock=clock)

    async def scenario():
        driver.spawn(mon.run(interval_s=5.0))
        await driver.run(until=21.0)
        await driver.shutdown()

    try:
        loop.run_until_complete(scenario())
        # sweeps at t = 0, 5, 10, 15, 20
        assert _ctr("bkw_durability_sweeps_total",
                    client="simcadence") == 5.0
    finally:
        store.close()
        obs_metrics.registry().reset()


def test_matchmaker_expiry_on_virtual_clock(loop):
    """A queued request expires on the deadline heap when VIRTUAL time
    passes expiry_s — the real ShardedMatchmaker + SqliteServerStore,
    no wall clock anywhere."""
    store = SqliteServerStore(":memory:", write_behind=False)
    clock = SimClock()
    expired0 = _ctr("bkw_matchmaking_expired_total")

    class AlwaysOnline:
        def is_online(self, client_id):
            return True

        async def notify(self, client_id, msg):
            return True

    m = ShardedMatchmaker(store, AlwaysOnline(), expiry_s=300.0,
                          shards=2, clock=clock)
    try:
        store.register_client(pk(1))
        store.register_client(pk(2))
        loop.run_until_complete(m.fulfill(pk(1), 4096, min_peers=1))
        assert m.pending() == 1  # queued, waiting for a counterparty
        clock.advance_to(301.0)
        assert m.pending() == 0  # reaped: the deadline passed virtually
        assert _ctr("bkw_matchmaking_expired_total") - expired0 == 1.0
        # a fresh request after the expiry finds no stale candidate
        loop.run_until_complete(m.fulfill(pk(2), 4096, min_peers=1))
        assert m.pending() == 1
    finally:
        store.close()


# --- scenarios: determinism and the scorecard -------------------------------


def test_same_seed_same_scorecard_byte_identical():
    c1, _ = run_sim("flashcrowd", clients=1500)
    c2, _ = run_sim("flashcrowd", clients=1500)
    assert card_json(c1) == card_json(c2)
    assert c1["passed"], json.dumps(c1["gates"], indent=1)


def test_scorecard_is_wall_clock_free_and_metrics_flush():
    events0 = _ctr("bkw_sim_events_total", scenario="drought")
    card, stats = run_sim("drought")
    assert card["passed"], json.dumps(card["gates"], indent=1)
    # wall-derived numbers live in stats, never in the (replayable) card
    assert not any("wall" in k for k in card)
    assert set(stats) == {"wall_s", "events_per_s", "time_compression"}
    assert _ctr("bkw_sim_events_total",
                scenario="drought") - events0 == card["events"]


def test_builtin_registry_names_and_specs():
    assert set(BUILTINS) == {"flashcrowd", "regionfail", "auditstorm",
                             "drought", "repaircascade"}
    desc, spec = BUILTINS["regionfail"]
    assert spec["clients"] == 100_000 and spec["sim_seconds"] == WEEK_S


# --- the tier-1 acceptance run ----------------------------------------------


def test_simulated_week_of_1e5_client_churn_in_tier1_minutes():
    """The headline: 10⁵ clients, a simulated week, a quarter of the
    regions lost on day 2 — real matchmaking and serverstore paths on
    the virtual clock, gates on match-rate, repair-debt drain, and
    violation client-seconds.  Runs in well under a tier-1 minute's
    budget; the compression-ratio gate itself lives in bench #19."""
    card, stats = run_sim("regionfail")
    assert card["clients"] == 100_000
    assert card["sim_seconds"] == WEEK_S
    assert {g["name"] for g in card["gates"]} == {
        "match_rate>=0.90", "repair_debt_drained<=3d",
        "violation_seconds_bounded"}
    assert card["passed"], json.dumps(card["gates"], indent=1)
    # a simulated week must not cost a wall week: 3 orders of magnitude
    # is the floor even on a loaded CI box (bench gates the real 10⁴×)
    assert stats["time_compression"] > 1_000.0


@pytest.mark.slow
def test_simulated_week_of_1e6_client_soak():
    card, _stats = run_sim("regionfail", clients=1_000_000)
    assert card["passed"], json.dumps(card["gates"], indent=1)
    assert card["deaths"] >= 200_000  # a quarter of the regions died
