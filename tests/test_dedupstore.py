"""Tiered dedup index (dedupstore/): cold LSM units, crash seams,
promotion clock, and the BlobIndex bit-identity parity gates
(docs/dedup_tiering.md)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.dedupstore import ColdFingerprintStore, TieredDedupIndex
from backuwup_tpu.dedupstore.cold import pack_keys, unpack_keys
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.ops.dedup_index import hashes_to_queries
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.utils import faults

pytestmark = pytest.mark.tiered

TIER_SITES = {
    "tier.run.commit.pre", "tier.run.commit.post",
    "tier.compact.commit.pre", "tier.compact.commit.post",
}


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    faults.uninstall()


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


@pytest.fixture
def host_index(tmp_path):
    keys = KeyManager.from_secret(b"\x07" * 32)
    return BlobIndex(keys, tmp_path / "index")


def _queries(n, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.integers(1, 2 ** 32, (n, 4), dtype=np.uint32)
    return q


def _hashes(n, seed=0):
    rng = np.random.default_rng(seed)
    return [t.tobytes()
            for t in rng.integers(0, 256, (n, 32), dtype=np.uint8)]


def _metric(name, **labels):
    m = obs_metrics.registry().get(name)
    return 0 if m is None else m.value(**labels)


# --- key packing ------------------------------------------------------------


def test_pack_unpack_roundtrip_and_order():
    q = _queries(4096, seed=3)
    # include words with trailing-zero bytes (numpy S16 strips trailing
    # NULs; packing must stay injective and order-preserving anyway)
    q[:17, 3] = 0
    q[5, :] = [1, 0, 0, 0]
    packed = pack_keys(q)
    assert packed.dtype == np.dtype("S16")
    back = unpack_keys(packed)
    assert np.array_equal(back, q)
    # byte order == numeric (w0, w1, w2, w3) order
    srt = np.sort(packed)
    lex = np.lexsort((q[:, 3], q[:, 2], q[:, 1], q[:, 0]))
    assert np.array_equal(unpack_keys(srt), q[lex])


# --- cold store units -------------------------------------------------------


def test_cold_memtable_classify_and_padding(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold")
    q = _queries(64, seed=1)
    store.insert(q, np.arange(64, dtype=np.uint32))
    got = store.classify(q)
    assert np.array_equal(got, np.arange(64, dtype=np.uint32) + 1)
    # all-zero padding rows stay 0 through insert AND classify
    padded = np.vstack([np.zeros((2, 4), dtype=np.uint32), q[:3]])
    assert np.array_equal(store.classify(padded)[:2], [0, 0])
    store.insert(np.zeros((5, 4), dtype=np.uint32))
    assert store.classify(np.zeros((1, 4), dtype=np.uint32))[0] == 0
    # absent keys classify 0
    assert (store.classify(_queries(16, seed=2)) == 0).all()


def test_cold_flush_reopen_durable(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold")
    q = _queries(300, seed=4)
    store.insert(q)
    store.flush()
    assert store.run_count == 1
    again = ColdFingerprintStore(tmp_path / "cold")
    assert (again.classify(q) != 0).all()
    assert len(again) == 300


def test_cold_newest_value_wins(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold", compact_fanin=64)
    q = _queries(10, seed=5)
    store.insert(q, np.full(10, 7, dtype=np.uint32))
    store.flush()
    store.insert(q[:4], np.full(4, 9, dtype=np.uint32))
    # memtable layer overrides the run
    assert (store.classify(q[:4]) == 10).all()
    store.flush()
    # newer run overrides the older one after flush too
    assert (store.classify(q[:4]) == 10).all()
    assert (store.classify(q[4:]) == 8).all()


def test_cold_compaction_folds_same_size_runs(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold", compact_fanin=3)
    qs = [_queries(50, seed=10 + i) for i in range(6)]
    for q in qs:
        store.insert(q)
        store.flush()
    # 6 same-tier flushes with fanin 3 fold down (3 -> 1, twice, then
    # the two merged runs sit one tier up)
    assert store.run_count < 6
    for q in qs:
        assert (store.classify(q) != 0).all()
    again = ColdFingerprintStore(tmp_path / "cold")
    for q in qs:
        assert (again.classify(q) != 0).all()


def test_cold_reset_drops_everything(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold")
    q = _queries(40, seed=6)
    store.insert(q)
    store.flush()
    store.insert(_queries(8, seed=7))
    store.reset()
    assert store.run_count == 0 and len(store) == 0
    assert (store.classify(q) == 0).all()
    assert not list((tmp_path / "cold").glob("r*.run"))


def test_cold_recovery_drops_tmp_leftovers(tmp_path):
    store = ColdFingerprintStore(tmp_path / "cold")
    store.insert(_queries(20, seed=8))
    store.flush()
    junk = tmp_path / "cold" / "r999999999999.tmp"
    junk.write_bytes(b"partial run image")
    again = ColdFingerprintStore(tmp_path / "cold")
    assert not junk.exists()
    assert again.run_count == 1


# --- crash seams ------------------------------------------------------------


def test_tier_crash_sites_registered():
    assert TIER_SITES <= set(faults.crash_sites())


@pytest.mark.crash
@pytest.mark.parametrize("site", ["tier.run.commit.pre",
                                  "tier.run.commit.post"])
def test_crash_around_run_commit_recovers(tmp_path, site):
    store = ColdFingerprintStore(tmp_path / "cold")
    q = _queries(100, seed=20)
    store.insert(q)
    plane = faults.install(faults.FaultPlane(seed=1))
    plane.arm_crash(site)
    with pytest.raises(faults.CrashInjected):
        store.flush()
    faults.uninstall()
    again = ColdFingerprintStore(tmp_path / "cold")
    assert not list((tmp_path / "cold").glob("*.tmp"))
    if site.endswith(".pre"):
        # crash before the rename: the run never became visible; the
        # memtable was volatile by contract (the tiered front only drops
        # hot keys after a successful flush)
        assert again.run_count == 0
        assert (again.classify(q) == 0).all()
    else:
        # crash after the rename: the run is durable and answers
        assert again.run_count == 1
        assert (again.classify(q) != 0).all()


@pytest.mark.crash
@pytest.mark.parametrize("site", ["tier.compact.commit.pre",
                                  "tier.compact.commit.post"])
def test_crash_around_compaction_recovers(tmp_path, site):
    store = ColdFingerprintStore(tmp_path / "cold", compact_fanin=3)
    qs = [_queries(50, seed=30 + i) for i in range(2)]
    for q in qs:
        store.insert(q)
        store.flush()
    assert store.run_count == 2
    plane = faults.install(faults.FaultPlane(seed=1))
    plane.arm_crash(site)
    q3 = _queries(50, seed=32)
    store.insert(q3)
    with pytest.raises(faults.CrashInjected):
        store.flush()  # third same-tier run triggers the merge
    faults.uninstall()
    again = ColdFingerprintStore(tmp_path / "cold")
    assert not list((tmp_path / "cold").glob("*.tmp"))
    if site.endswith(".pre"):
        # merged run never committed: the three inputs survive
        assert again.run_count == 3
    else:
        # merged run committed before the crash, inputs not yet
        # unlinked: recovery rolls the make-before-break forward
        assert again.run_count == 1
    for q in qs + [q3]:
        assert (again.classify(q) != 0).all()


# --- tiered front -----------------------------------------------------------


def test_budget_is_hard_cap_with_demotion(mesh, host_index, tmp_path):
    budget = 8 * 64 * 20  # 512 hot slots across the mesh
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget, memtable_limit=256)
    hs = _hashes(5000, seed=40)  # ~10x the hot slot count
    for s in range(0, len(hs), 500):
        batch = hs[s:s + 500]
        flags = ti.classify_insert(batch)
        for h, f in zip(batch, flags):
            assert f == host_index.is_duplicate(h)
            host_index.mark_queued(h)
        assert ti.hbm_table_bytes <= budget
    assert _metric("bkw_tier_demotions_total") > 0
    assert _metric("bkw_tier_hbm_highwater_bytes") <= budget
    # every key — hot or demoted — still classifies duplicate
    rng = np.random.default_rng(41)
    sample = [hs[i] for i in rng.integers(0, len(hs), 1000)]
    assert all(ti.classify_insert(sample))
    # fresh keys still classify new (device-miss + cold-miss => new)
    assert not any(ti.classify_insert(_hashes(200, seed=42)))


def test_promotion_clock_repins_hot_cold_keys(mesh, host_index, tmp_path):
    budget = 8 * 64 * 20
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget, memtable_limit=256,
                          clock_windows=1, promote_min_hits=1)
    hs = _hashes(4000, seed=50)
    for s in range(0, len(hs), 500):
        ti.classify_insert(hs[s:s + 500])
        for h in hs[s:s + 500]:
            host_index.mark_queued(h)  # the packer's per-batch queue
    # find keys that were demoted out of HBM (cold answers, hot does not)
    demoted = [h for h in hs
               if ti.sharded.probe(hashes_to_queries([h]))[0] == 0
               and ti.cold.classify(hashes_to_queries([h]))[0] != 0][:32]
    assert demoted, "expected demoted keys at ~8x budget"
    # the dispatch path reports them as device misses (raw False); the
    # cold tier answers, the hits queue promotions, and the one-window
    # clock re-pins them into HBM
    before = _metric("bkw_tier_promotions_total")
    assert all(ti.resolve_hints(demoted, [False] * len(demoted)))
    assert _metric("bkw_tier_promotions_total") > before
    q = hashes_to_queries(demoted)
    assert (ti.sharded.probe(q) != 0).all()  # resident again
    assert ti.hbm_table_bytes <= budget
    # once promoted, the working set answers from the device path: the
    # real flags a dispatch would now produce are all-found
    d0, h0 = (_metric("bkw_tier_probes_total", path="device"),
              _metric("bkw_tier_hits_total", path="device"))
    flags = [bool(f) for f in ti.sharded.probe(q) != 0]
    assert all(ti.resolve_hints(demoted, flags))
    d1, h1 = (_metric("bkw_tier_probes_total", path="device"),
              _metric("bkw_tier_hits_total", path="device"))
    assert d1 - d0 >= len(demoted)
    assert (h1 - h0) / (d1 - d0) > 0.95


def test_resolve_hints_cold_fallthrough(mesh, host_index, tmp_path):
    budget = 8 * 64 * 20
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget, memtable_limit=256)
    hs = _hashes(4000, seed=60)
    for s in range(0, len(hs), 500):
        for h, f in zip(hs[s:s + 500],
                        ti.classify_insert(hs[s:s + 500])):
            host_index.mark_queued(h)
    # raw all-False mimics the pipeline's device-miss flags for keys
    # that were demoted out of HBM: the cold tier must answer True
    demoted = [h for h in hs[:512]
               if ti.cold.classify(hashes_to_queries([h]))[0] != 0][:16]
    assert demoted, "expected some demoted keys at 10x budget"
    flags = ti.resolve_hints(demoted, [False] * len(demoted))
    assert all(flags)
    # a genuinely new hash with a concrete False flag stays new
    fresh = _hashes(4, seed=61)
    assert ti.resolve_hints(fresh, [False] * 4) == [False] * 4
    # None still routes to the host authority
    q = _hashes(2, seed=62)
    host_index.mark_queued(q[0])
    assert ti.resolve_hints(q, [None, None]) == [True, False]


def test_restart_seeds_from_cold_runs(mesh, host_index, tmp_path):
    budget = 8 * 64 * 20
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget, memtable_limit=256)
    hs = _hashes(3000, seed=70)
    for s in range(0, len(hs), 500):
        ti.classify_insert(hs[s:s + 500])
        for h in hs[s:s + 500]:
            host_index.mark_queued(h)
    ti.cold.flush()
    runs = ti.cold.run_count
    assert runs > 0
    # restart: persisted runs survive the reconcile and keep answering
    ti2 = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                           hbm_budget_bytes=budget, memtable_limit=256)
    assert ti2.cold.run_count >= runs
    assert all(ti2.classify_insert(hs))
    assert ti2.hbm_table_bytes <= budget


def test_reconcile_wipes_stale_cold_keys(mesh, tmp_path):
    keys = KeyManager.from_secret(b"\x07" * 32)
    host = BlobIndex(keys, tmp_path / "index")
    budget = 8 * 64 * 20
    hs = _hashes(3000, seed=80)
    for h in hs:
        host.mark_queued(h)
    ti = TieredDedupIndex(mesh, host, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget, memtable_limit=256)
    ti.cold.flush()
    assert len(ti.cold) > 0
    # the authority pruned half its blobs (GC / peer-loss repair): a
    # fresh front must not let stale cold runs classify them duplicate
    pruned, kept = hs[:1500], hs[1500:]
    host2 = BlobIndex(keys, tmp_path / "index2")
    for h in kept:
        host2.mark_queued(h)
    ti2 = TieredDedupIndex(mesh, host2, cold_dir=tmp_path / "cold",
                           hbm_budget_bytes=budget, memtable_limit=256)
    flags = ti2.classify_insert(pruned[:300])
    assert not any(flags)
    assert all(ti2.classify_insert(kept[:300]))


@pytest.mark.timeout(600)
def test_parity_oracle_1e6_under_budget(mesh, host_index, tmp_path):
    """The acceptance gate: bit-identical classification against the
    BlobIndex oracle at 1e6 fingerprints while the population is ~15x
    the hot slot budget and HBM bytes never exceed the cap."""
    n = 1_000_000
    budget = 8 * 8192 * 20  # 65536 hot slots: population ~15x
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget)
    rng = np.random.default_rng(90)
    hashes = [t.tobytes()
              for t in rng.integers(0, 256, (n, 32), dtype=np.uint8)]
    mismatches = 0
    for s in range(0, n, 8192):
        batch = hashes[s:s + 8192]
        flags = ti.classify_insert(batch)
        for h, f in zip(batch, flags):
            if f != host_index.is_duplicate(h):
                mismatches += 1
            host_index.mark_queued(h)
        assert ti.hbm_table_bytes <= budget
    assert mismatches == 0
    assert _metric("bkw_tier_hbm_highwater_bytes") <= budget
    assert _metric("bkw_tier_demotions_total") > 0
    # second pass over a sample: everything is a duplicate on both sides
    sample = [hashes[i] for i in rng.integers(0, n, 20000)]
    assert all(ti.classify_insert(sample))
    # fresh keys stay new
    fresh = [t.tobytes()
             for t in rng.integers(0, 256, (2000, 32), dtype=np.uint8)]
    assert not any(ti.classify_insert(fresh))


@pytest.mark.slow
@pytest.mark.timeout(7200)
def test_soak_1e8_cold_population(mesh, host_index, tmp_path):
    """1e8-fingerprint soak: the cold tier absorbs a population four
    orders past the hot budget; classification stays bit-identical on
    sampled slices and HBM never exceeds the cap."""
    n_cold = 100_000_000
    block = 1_000_000
    budget = 8 * 8192 * 20
    ti = TieredDedupIndex(mesh, host_index, cold_dir=tmp_path / "cold",
                          hbm_budget_bytes=budget,
                          memtable_limit=1 << 20)
    rng = np.random.default_rng(99)
    # bulk population goes straight into the cold store (vectorized
    # blocks; seeds are reproducible so sampling can regenerate them)
    for b in range(n_cold // block):
        q = np.random.default_rng(1000 + b).integers(
            1, 2 ** 32, (block, 4), dtype=np.uint32)
        ti.cold.insert(q)
    ti.cold.flush()
    assert ti.hbm_table_bytes <= budget
    # sampled membership via the tiered front's own cold path
    for b in rng.integers(0, n_cold // block, 5):
        q = np.random.default_rng(1000 + int(b)).integers(
            1, 2 ** 32, (block, 4), dtype=np.uint32)
        sel = rng.integers(0, block, 4096)
        assert (ti.cold.classify(q[sel]) != 0).all()
    # absent keys (word 0 == 0 never appears above)
    probe = rng.integers(1, 2 ** 32, (4096, 4), dtype=np.uint32)
    probe[:, 0] = 0
    probe[0] = 0  # padding row
    assert (ti.cold.classify(probe) == 0).all()
    # the live classify interface on top stays exact
    hs = _hashes(50000, seed=101)
    flags = ti.classify_insert(hs)
    assert not any(flags)
    for h in hs:
        host_index.mark_queued(h)
    assert all(ti.classify_insert(hs))
    assert _metric("bkw_tier_hbm_highwater_bytes") <= budget
