"""Swarm harness acceptance (scenario/swarm.py — PR 10).

Tier 1 runs the ~32-client ``swarm`` spec end-to-end over loopback HTTP
and requires the scorecard to pass: every client registered, the
matchmaking economy flowed, the request p99 was measured from
``bkw_server_request_seconds``, the event loop never stalled past
budget, and no sqlite commit ran on the loop thread.  A second tier-1
run pins the LEGACY tier's expected contrast: its direct-commit store
commits on the event loop (that is the baseline the bench beats).  The
192-client load shape and the measured speedup legs are slow.
"""

import asyncio
import dataclasses

import pytest

from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.scenario import (MatchLoadSpec, builtin_swarms,
                                   run_match_load, run_swarm)

pytestmark = pytest.mark.swarm


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.mark.timeout(240)
def test_swarm_acceptance(tmp_path, loop):
    spec = builtin_swarms()["swarm"]
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    gates = {a.name: a.passed for a in card.assertions}
    assert gates.get("loop_stall_under_budget") is True
    assert gates.get("commits_off_event_loop") is True
    assert summary["commits_on_loop"] is False
    assert summary["matchmakings"] > 0
    assert summary["server_p99_ms"] is not None
    # the per-route histogram fed the card's quantile section
    assert any(k.startswith("bkw_server_request_seconds")
               for k in card.quantiles), card.quantiles
    # the write-behind store really group-committed during the run
    assert summary["commits"]["group"] > 0
    assert summary["commits"]["direct"] == 0


@pytest.mark.timeout(240)
def test_swarm_legacy_commits_on_loop(tmp_path, loop):
    """The baseline contrast the bench measures: the legacy tier's
    direct-commit store fsyncs on the event-loop thread (visible in
    ``commit_threads``), which is exactly what the sharded tier's
    ``commits_off_event_loop`` gate forbids."""
    spec = dataclasses.replace(builtin_swarms()["swarm"], name="swarm_legacy",
                               seed=102, legacy=True)
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    assert summary["commits_on_loop"] is True
    assert summary["commits"]["direct"] > 0
    assert summary["matchmakings"] > 0


def test_match_load_smoke(tmp_path):
    """Both speedup legs produce matches on a short window (the >= 2x
    gate itself is bench config 12 and the slow test below)."""
    spec = MatchLoadSpec(clients=16, duration_s=0.3, audit_history=64)
    legacy = run_match_load(dataclasses.replace(spec, legacy=True), tmp_path)
    sharded = run_match_load(spec, tmp_path)
    for leg in (legacy, sharded):
        assert leg["matchmakings"] > 0
        assert leg["matchmakings_per_s"] > 0
    assert legacy["tier"] == "legacy" and sharded["tier"] == "sharded"


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_swarm_full_load_shape(tmp_path, loop):
    card, summary = loop.run_until_complete(
        run_swarm(builtin_swarms()["swarm_full"], tmp_path))
    assert card.passed, card.render()
    assert summary["commits_on_loop"] is False


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_match_load_speedup(tmp_path):
    """The bench gate's shape at full weight; the test bound is kept
    conservative (>= 1.3x) so scheduler noise cannot flake it while a
    real regression — sharded no faster than the single lock — still
    fails loudly."""
    spec = MatchLoadSpec()
    legacy = run_match_load(dataclasses.replace(spec, legacy=True), tmp_path)
    sharded = run_match_load(spec, tmp_path)
    speedup = sharded["matchmakings_per_s"] / legacy["matchmakings_per_s"]
    assert speedup >= 1.3, (legacy, sharded)
