"""Sharded HBM dedup index vs the host BlobIndex semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.dedup_index import (
    KEY_WORDS,
    DedupIndexFull,
    ShardedDedupIndex,
    hashes_to_queries,
    queries_from_cvs,
)


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    return jax.sharding.Mesh(np.array(devs), ("data",))


def _hashes(n, seed=0):
    return [blake3_hash(f"{seed}:{i}".encode()) for i in range(n)]


def test_probe_empty_table(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    found = idx.probe(hashes_to_queries(_hashes(10)))
    assert (found == 0).all()


def test_insert_then_probe(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    hs = _hashes(100)
    q = hashes_to_queries(hs)
    vals = np.arange(100, dtype=np.uint32)
    found = idx.insert(q, vals)
    assert (found == 0).all()  # all new
    got = idx.probe(q)
    assert (got == vals + 1).all()  # value+1 encoding
    # unseen hashes still miss
    assert (idx.probe(hashes_to_queries(_hashes(50, seed=9))) == 0).all()


def test_reinsert_keeps_original_value(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    hs = _hashes(20)
    q = hashes_to_queries(hs)
    idx.insert(q, np.full(20, 5, dtype=np.uint32))
    found = idx.insert(q, np.full(20, 9, dtype=np.uint32))
    assert (found == 6).all()  # found with original value 5 (+1)
    assert (idx.probe(q) == 6).all()


def test_matches_host_index_classification(mesh):
    """Device probe and the host map agree on found/new for a mixed stream."""
    idx = ShardedDedupIndex.create(mesh, capacity=4096)
    host = {}
    rng = np.random.default_rng(3)
    for batch in range(5):
        n = 200
        hs = []
        for i in range(n):
            if host and rng.random() < 0.4:  # resample a known hash
                hs.append(list(host)[int(rng.integers(len(host)))])
            else:
                hs.append(blake3_hash(f"b{batch}i{i}".encode()))
        # host-side de-dup within batch (the packer does this)
        seen_in_batch = set()
        uniq = [h for h in hs if not (h in seen_in_batch or seen_in_batch.add(h))]
        q = hashes_to_queries(uniq)
        vals = np.arange(len(uniq), dtype=np.uint32)
        found = idx.insert(q, vals)
        for h, f in zip(uniq, found):
            assert (f > 0) == (h in host), h.hex()
            if h not in host:
                host[h] = True


def test_probe_exhaustion_raises_not_silently_drops(mesh):
    """Overfilling a shard must raise DedupIndexFull, never silently drop
    keys (which would misclassify later duplicates as new)."""
    idx = ShardedDedupIndex.create(mesh, capacity=8, max_probes=8)
    hs = _hashes(512, seed=11)  # 512 keys into 8*8=64 slots: must overflow
    q = hashes_to_queries(hs)
    with pytest.raises(DedupIndexFull):
        idx.insert(q, np.arange(512, dtype=np.uint32))


def test_capacity_pressure_linear_probing(mesh):
    # capacity 64 per shard * 8 shards = 512 slots; insert 256 keys so some
    # shards see heavy probing but stay under capacity
    idx = ShardedDedupIndex.create(mesh, capacity=64, max_probes=64)
    hs = _hashes(256, seed=4)
    q = hashes_to_queries(hs)
    found = idx.insert(q, np.arange(256, dtype=np.uint32))
    assert (found == 0).all()
    assert (idx.probe(q) > 0).all()


# --- query-construction edge rows ------------------------------------------


def test_hashes_to_queries_edge_rows():
    # empty input: a well-formed (0, 4) slab, not an exception
    empty = hashes_to_queries([])
    assert empty.shape == (0, KEY_WORDS) and empty.dtype == np.uint32
    # exact little-endian word split of the first 16 bytes; bytes 16..31
    # never reach the query (the 128-bit truncation)
    h = bytes(range(32))
    q = hashes_to_queries([h, h[:16] + b"\xff" * 16])
    expect = np.frombuffer(h[:16], dtype="<u4")
    assert np.array_equal(q[0], expect)
    assert np.array_equal(q[0], q[1])
    # memoryview/bytearray inputs coerce like bytes
    q2 = hashes_to_queries([bytearray(h), memoryview(h)])
    assert np.array_equal(q2[0], expect)


def test_zero_query_rows_are_padding_for_probe_and_insert(mesh):
    """All-zero rows are the kernels' padding convention: probe answers
    0 and insert must not burn a slot or report found for them."""
    idx = ShardedDedupIndex.create(mesh, capacity=64)
    hs = _hashes(6, seed=21)
    q = hashes_to_queries(hs)
    padded = np.vstack([q[:3],
                        np.zeros((2, KEY_WORDS), dtype=np.uint32),
                        q[3:]])
    found = idx.insert(padded, np.arange(8, dtype=np.uint32))
    assert (found == 0).all()
    # the real keys landed, the padding rows did not
    assert (idx.probe(q) > 0).all()
    assert (idx.probe(np.zeros((4, KEY_WORDS), dtype=np.uint32)) == 0).all()
    # a second padded probe still reports 0 on the zero rows
    again = idx.probe(padded)
    assert (again[3:5] == 0).all() and (again[:3] > 0).all()


def test_intra_batch_duplicate_fingerprints_single_resident(mesh):
    """Occurrences of one fingerprint inside one insert batch all report
    the pre-batch state ("new"), and exactly one occurrence's value ends
    up resident (which one is a write race — the kernel's contract asks
    for distinct keys per batch, and MeshDedupIndex.classify_insert's
    host-side first-occurrence walk builds on exactly these semantics)."""
    idx = ShardedDedupIndex.create(mesh, capacity=64)
    h = _hashes(1, seed=22)[0]
    q = hashes_to_queries([h, h, h])
    found = idx.insert(q, np.array([4, 9, 13], dtype=np.uint32))
    assert (found == 0).all()  # all report the pre-batch state
    got = idx.probe(hashes_to_queries([h]))
    assert int(got[0]) in (5, 10, 14)  # one occurrence's value (+1)
    # and a later batch sees it as a plain duplicate with that value
    again = idx.insert(q[:1], np.array([77], dtype=np.uint32))
    assert int(again[0]) == int(got[0])


def test_queries_from_cvs_matches_host_path():
    """Slicing the accumulator on device == downloading digests and
    calling hashes_to_queries; all-zero accumulator rows stay padding."""
    rng = np.random.default_rng(23)
    acc = rng.integers(0, 2 ** 32, (16, 8), dtype=np.uint32)
    acc[4] = 0  # unplaced row (digest_pool scatters into zeros)
    acc[11] = 0
    q_dev = np.asarray(queries_from_cvs(jnp.asarray(acc)))
    digests = [np.ascontiguousarray(row.astype("<u4")).tobytes()
               for row in acc]
    q_host = hashes_to_queries(digests)
    assert np.array_equal(q_dev, q_host)
    assert (q_dev[4] == 0).all() and (q_dev[11] == 0).all()
