"""Sharded HBM dedup index vs the host BlobIndex semantics."""

import numpy as np
import pytest

import jax

from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.dedup_index import (
    DedupIndexFull,
    ShardedDedupIndex,
    hashes_to_queries,
)


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    return jax.sharding.Mesh(np.array(devs), ("data",))


def _hashes(n, seed=0):
    return [blake3_hash(f"{seed}:{i}".encode()) for i in range(n)]


def test_probe_empty_table(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    found = idx.probe(hashes_to_queries(_hashes(10)))
    assert (found == 0).all()


def test_insert_then_probe(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    hs = _hashes(100)
    q = hashes_to_queries(hs)
    vals = np.arange(100, dtype=np.uint32)
    found = idx.insert(q, vals)
    assert (found == 0).all()  # all new
    got = idx.probe(q)
    assert (got == vals + 1).all()  # value+1 encoding
    # unseen hashes still miss
    assert (idx.probe(hashes_to_queries(_hashes(50, seed=9))) == 0).all()


def test_reinsert_keeps_original_value(mesh):
    idx = ShardedDedupIndex.create(mesh, capacity=1024)
    hs = _hashes(20)
    q = hashes_to_queries(hs)
    idx.insert(q, np.full(20, 5, dtype=np.uint32))
    found = idx.insert(q, np.full(20, 9, dtype=np.uint32))
    assert (found == 6).all()  # found with original value 5 (+1)
    assert (idx.probe(q) == 6).all()


def test_matches_host_index_classification(mesh):
    """Device probe and the host map agree on found/new for a mixed stream."""
    idx = ShardedDedupIndex.create(mesh, capacity=4096)
    host = {}
    rng = np.random.default_rng(3)
    for batch in range(5):
        n = 200
        hs = []
        for i in range(n):
            if host and rng.random() < 0.4:  # resample a known hash
                hs.append(list(host)[int(rng.integers(len(host)))])
            else:
                hs.append(blake3_hash(f"b{batch}i{i}".encode()))
        # host-side de-dup within batch (the packer does this)
        seen_in_batch = set()
        uniq = [h for h in hs if not (h in seen_in_batch or seen_in_batch.add(h))]
        q = hashes_to_queries(uniq)
        vals = np.arange(len(uniq), dtype=np.uint32)
        found = idx.insert(q, vals)
        for h, f in zip(uniq, found):
            assert (f > 0) == (h in host), h.hex()
            if h not in host:
                host[h] = True


def test_probe_exhaustion_raises_not_silently_drops(mesh):
    """Overfilling a shard must raise DedupIndexFull, never silently drop
    keys (which would misclassify later duplicates as new)."""
    idx = ShardedDedupIndex.create(mesh, capacity=8, max_probes=8)
    hs = _hashes(512, seed=11)  # 512 keys into 8*8=64 slots: must overflow
    q = hashes_to_queries(hs)
    with pytest.raises(DedupIndexFull):
        idx.insert(q, np.arange(512, dtype=np.uint32))


def test_capacity_pressure_linear_probing(mesh):
    # capacity 64 per shard * 8 shards = 512 slots; insert 256 keys so some
    # shards see heavy probing but stay under capacity
    idx = ShardedDedupIndex.create(mesh, capacity=64, max_probes=64)
    hs = _hashes(256, seed=4)
    q = hashes_to_queries(hs)
    found = idx.insert(q, np.arange(256, dtype=np.uint32))
    assert (found == 0).all()
    assert (idx.probe(q) > 0).all()
