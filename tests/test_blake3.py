"""BLAKE3 correctness: known vector + pure-python vs numpy batch parity."""

import hashlib
import random

from backuwup_tpu.ops.blake3_cpu import blake3_hash, blake3_many

# Official test vector for the empty input (BLAKE3 spec appendix).
EMPTY_DIGEST = "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262"


def test_empty_vector():
    assert blake3_hash(b"").hex() == EMPTY_DIGEST
    assert blake3_many([b""])[0].hex() == EMPTY_DIGEST


def _corpus():
    rng = random.Random(7)
    lengths = [0, 1, 63, 64, 65, 1023, 1024, 1025, 2048, 2049, 3072, 4096,
               5000, 1024 * 7, 1024 * 8 + 1, 1024 * 16, 1024 * 31 + 17]
    return [rng.randbytes(n) for n in lengths]


def test_pure_vs_numpy_parity():
    corpus = _corpus()
    batched = blake3_many(corpus)
    for data, got in zip(corpus, batched):
        assert got == blake3_hash(data), f"len={len(data)}"


def test_batch_order_and_dedup_stability():
    corpus = _corpus()
    shuffled = list(reversed(corpus))
    a = dict(zip([len(c) for c in corpus], blake3_many(corpus)))
    b = dict(zip([len(c) for c in shuffled], blake3_many(shuffled)))
    assert a == b


def test_distinct_inputs_distinct_digests():
    # sanity: flags/counters separate structurally similar inputs
    pairs = [
        (b"", b"\x00"),
        (b"\x00" * 1024, b"\x00" * 1025),
        (b"a" * 2048, b"a" * 2049),
    ]
    for x, y in pairs:
        assert blake3_hash(x) != blake3_hash(y)
    # and blake3 != sha256 trivially (guard against accidental hashlib use)
    assert blake3_hash(b"x") != hashlib.sha256(b"x").digest()
