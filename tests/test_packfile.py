"""Packfile + blob index: round trips, format invariants, persistence."""

import os

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.packfile import (
    BlobNotFoundError,
    DirtyPackfileError,
    PackfileReader,
    PackfileWriter,
    packfile_path,
)
from backuwup_tpu.wire import Blob, BlobKind

KEYS = KeyManager.from_secret(bytes(range(32)))


def _blob(data: bytes, kind=BlobKind.FILE_CHUNK) -> Blob:
    return Blob(hash=blake3_hash(data), kind=kind, data=data)


@pytest.fixture
def writer_env(tmp_path):
    written = []
    w = PackfileWriter(KEYS, tmp_path / "pack",
                       on_packfile=lambda pid, path, hashes, size:
                       written.append((pid, path, hashes, size)))
    return w, written, tmp_path


def test_round_trip_single_packfile(writer_env, nprng):
    w, written, tmp = writer_env
    blobs = [_blob(nprng.integers(0, 256, n, dtype="u1").tobytes())
             for n in (10, 1000, 65536)]
    blobs.append(_blob(b"tree bytes", BlobKind.TREE))
    for b in blobs:
        w.add_blob(b)
    w.flush()
    w.close()
    assert len(written) == 1
    pid, path, hashes, size = written[0]
    assert path == packfile_path(tmp / "pack", pid)
    assert hashes == [b.hash for b in blobs]
    reader = PackfileReader(KEYS, tmp / "pack")
    for b in blobs:
        got = reader.get_blob(pid, b.hash)
        assert got.data == b.data and got.kind == b.kind
    with pytest.raises(BlobNotFoundError):
        reader.get_blob(pid, b"\x00" * 32)


def test_write_triggers_at_target_size(writer_env, nprng):
    w, written, _ = writer_env
    # incompressible data: each 1 MiB blob stays ~1 MiB compressed
    for _ in range(7):
        w.add_blob(_blob(nprng.integers(0, 256, 1 << 20, dtype="u1").tobytes()))
    assert len(written) >= 2  # 3 MiB target -> multiple files
    w.flush()
    for _, path, _, size in written:
        assert size <= defaults.PACKFILE_MAX_SIZE


def test_dirty_close_raises(writer_env):
    w, _, _ = writer_env
    w.add_blob(_blob(b"data"))
    with pytest.raises(DirtyPackfileError):
        w.close()
    w.flush()
    w.close()


def test_encrypted_at_rest(writer_env):
    w, written, tmp = writer_env
    secret = b"super secret plaintext payload" * 10
    w.add_blob(_blob(secret))
    w.flush()
    raw = written[0][1].read_bytes()
    assert secret not in raw
    # wrong key cannot read
    other = PackfileReader(KeyManager.from_secret(b"\x09" * 32), tmp / "pack")
    with pytest.raises(Exception):
        other.get_blob(written[0][0], written[0][2][0])


def test_blob_index_dedup_and_persistence(tmp_path):
    idx = BlobIndex(KEYS, tmp_path / "index")
    h1, h2 = blake3_hash(b"one"), blake3_hash(b"two")
    pid = os.urandom(12)
    assert not idx.is_duplicate(h1)
    idx.mark_queued(h1)
    assert idx.is_duplicate(h1)  # queued counts as duplicate
    idx.finalize_packfile(pid, [h1, h2])
    assert idx.lookup(h2) == pid
    files = idx.flush()
    assert len(files) == 1
    # reload from disk
    idx2 = BlobIndex(KEYS, tmp_path / "index")
    assert idx2.load() == 2
    assert idx2.lookup(h1) == pid
    assert idx2.is_duplicate(h2)
    # wrong key fails to decrypt
    bad = BlobIndex(KeyManager.from_secret(b"\x08" * 32), tmp_path / "index")
    with pytest.raises(Exception):
        bad.load()


def test_blob_index_split_files(tmp_path, monkeypatch):
    monkeypatch.setattr(defaults, "INDEX_FILE_MAX_ENTRIES", 3)
    idx = BlobIndex(KEYS, tmp_path / "index")
    hashes = [blake3_hash(bytes([i])) for i in range(8)]
    idx.finalize_packfile(os.urandom(12), hashes)
    files = idx.flush()
    assert [f.name for f in files] == ["000000", "000001", "000002"]
    idx2 = BlobIndex(KEYS, tmp_path / "index")
    assert idx2.load() == 8


def test_rebuild_from_packfiles(tmp_path, nprng):
    w = PackfileWriter(KEYS, tmp_path / "pack")
    blobs = [_blob(nprng.integers(0, 256, 500, dtype="u1").tobytes())
             for _ in range(5)]
    for b in blobs:
        w.add_blob(b)
    w.flush()
    reader = PackfileReader(KEYS, tmp_path / "pack")
    idx = BlobIndex(KEYS, tmp_path / "index")
    assert idx.rebuild_from_packfiles(reader, tmp_path / "pack") == 5
    for b in blobs:
        assert idx.is_duplicate(b.hash)
        assert reader.get_blob(idx.lookup(b.hash), b.hash).data == b.data


def test_index_never_reuses_file_counters(tmp_path):
    """Counter doubles as the AES-GCM nonce: recovery paths that skip load()
    must still advance past existing files."""
    idx = BlobIndex(KEYS, tmp_path / "index")
    idx.finalize_packfile(os.urandom(12), [blake3_hash(b"x")])
    first = idx.flush()[0]
    original = first.read_bytes()
    # fresh instance, no load() (e.g. rebuild_from_packfiles recovery path)
    idx2 = BlobIndex(KEYS, tmp_path / "index")
    idx2.finalize_packfile(os.urandom(12), [blake3_hash(b"y")])
    files = idx2.flush()
    assert files[0].name == "000001"  # not 000000 again
    assert first.read_bytes() == original


def test_hard_cap_enforced_before_write(writer_env, nprng):
    """A near-max blob after buffered data must flush first, never produce
    an oversized file."""
    w, written, _ = writer_env
    cap = min(defaults.PACKFILE_MAX_SIZE, defaults.PACKFILE_WIRE_MAX)
    w.add_blob(_blob(nprng.integers(0, 256, 2 << 20, dtype="u1").tobytes()))
    big = nprng.integers(0, 256, 7 << 20, dtype="u1").tobytes()
    w.add_blob(_blob(big))
    w.flush()
    assert len(written) >= 2
    for _, path, _, size in written:
        assert size <= cap
    # a single blob that cannot fit any sendable packfile is refused
    with pytest.raises(Exception):
        w.add_blob(_blob(nprng.integers(0, 256, 9 << 20, dtype="u1").tobytes()))


def test_tampered_packfile_is_rejected(writer_env, nprng):
    """Flipping ciphertext bits anywhere in a packfile must surface as a
    loud decryption failure, never as silently wrong plaintext (AES-GCM
    authenticates both the header and every blob record)."""
    w, written, tmp = writer_env
    data = nprng.integers(0, 256, 50_000, dtype="u1").tobytes()
    blob = _blob(data)
    w.add_blob(blob)
    w.flush()
    (pid, path, hashes, size) = written[0]
    raw = bytearray(path.read_bytes())
    reader = PackfileReader(KEYS, tmp / "pack")
    assert reader.get_blob(pid, blob.hash).data == data

    # offsets cover the unauthenticated length prefix (0, 5), the header
    # ciphertext (12), blob ciphertext (mid), and the final GCM tag
    for flip_at in (0, 5, 12, len(raw) // 2, len(raw) - 3):
        tampered = bytearray(raw)
        tampered[flip_at] ^= 0x01
        path.write_bytes(bytes(tampered))
        with pytest.raises(Exception):
            PackfileReader(KEYS, tmp / "pack").get_blob(pid, blob.hash)
    path.write_bytes(bytes(raw))  # restore: intact file reads again
    assert PackfileReader(KEYS, tmp / "pack").get_blob(
        pid, blob.hash).data == data
